"""Pallas TPU kernel: batched ed25519 verification, whole ladder in VMEM.

Round-2 redesign of the north-star kernel, driven by two on-chip findings:
  1. the round-1 XLA kernel (ops/ed25519_batch.py fallback path) is
     HBM-bound — schoolbook scatter-updates materialise a (B, 32) array
     per limb row, ~1.3 ms per field-mul at B=65536 against ~0.06 ms of
     VPU compute;
  2. XLA's elementwise-fusion pass goes superlinear in region size
     (4 chained muls compile in 3.7 s, 8 in 211 s), so the fusion-barrier
     workaround tops out ~70k sigs/s with ~3500 kernel launches/batch.

Pallas sidesteps both: one kernel per batch block, all intermediates live
in VMEM/vregs, Mosaic compiles loop-structured code in linear time.

Layout: limbs on sublanes, batch on lanes — a field element is a
(16, BLK) uint32 array (radix 2^16, strict limbs < 2^16), so every field
op is a dense full-width VPU op. The verification program per block:

  * decompress A and R (lane-concatenated, one 2^252-3 chain);
  * build the 16-entry joint Straus table i*B + j*(-A) (B, 2B, 3B are
    compile-time affine constants);
  * 128 ladder iterations (2 doubles + table-select + add) consuming
    2 bits of s and h per step from a precomputed digit scratch;
  * verdict mask [s]B + [h](-A) == R (cofactorless, matching the
    i2p/ref10 semantics the reference inherits via `Crypto.isValid`,
    reference `core/.../crypto/Crypto.kt:535-541`).

Host-side parsing/hashing and the portable XLA fallback live in
ops/ed25519_batch.py; this module is TPU-only.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.crypto import ed25519_math
from .field25519 import P_INT, D_INT, SQRT_M1_INT

def _validated_blk(env_name: str, default: int) -> int:
    """Block-size env knobs must be powers of two (so bucketed batch pads
    are always BLK-divisible) and lane-dim multiples of 128 (Mosaic tile
    constraint). An arbitrary int like 384 would floor the kernel grid
    and silently skip tail lanes — reject at import instead."""
    value = int(os.environ.get(env_name, str(default)))
    if value < 128 or value & (value - 1) != 0:
        raise ValueError(
            f"{env_name}={value}: must be a power of two >= 128"
        )
    return value


# signatures per grid step (lane-dim multiple of 128); the env knob lets
# tools/tune_kernel.py sweep block sizes on real hardware without edits
BLK = _validated_blk("CORDA_TPU_ED25519_BLK", 512)

_MASK = np.uint32(0xFFFF)

#: 2-bit Shamir digits per scalar: both ladder scalars are < L < 2^253,
#: so 127 digits (bits 0..253) cover them and the top digit is skipped.
NDIGITS = 127


def _limbs(x: int):
    """Python-int limb list (shared radix with ops/field25519.int_to_limbs)."""
    return [(x >> (16 * k)) & 0xFFFF for k in range(16)]


_P_LIMBS = _limbs(P_INT)
_TWOP_LIMBS = _limbs(2 * P_INT)
_D2_INT = 2 * D_INT % P_INT


def _const_col(limbs, width):
    """Integer limbs -> (16, width) uint32 constant, built from primitives
    (pallas kernels may not capture trace-time jnp arrays)."""
    return jnp.concatenate(
        [jnp.full((1, width), np.uint32(v), jnp.uint32) for v in limbs],
        axis=0,
    )


def _zeros(rows, width):
    return jnp.zeros((rows, width), jnp.uint32)


def _cat(parts):
    """Row-concatenate, dropping zero-row pieces (Mosaic requires positive
    vector sizes)."""
    live = [p for p in parts if p.shape[0] > 0]
    return live[0] if len(live) == 1 else jnp.concatenate(live, axis=0)


# --- field ops on (16, W) uint32 values (strict limbs < 2^16) ---------------

def _reduce(d):
    """(16, W) coefficients < 2^27 -> strict limbs congruent mod p.

    Two sequential carry chains with *38 folds at 2^256 (bound argument as
    in ops/fe25519.py `_reduce`)."""
    def chain(rows_in):
        rows = []
        carry = None
        for k in range(16):
            v = rows_in[k] if carry is None else rows_in[k] + carry
            rows.append(v & _MASK)
            carry = v >> 16
        return rows, carry

    rows, cout = chain([d[k : k + 1] for k in range(16)])
    rows[0] = rows[0] + cout * np.uint32(38)
    rows, c2 = chain(rows)
    v0 = rows[0] + c2 * np.uint32(38)
    rows[0] = v0 & _MASK
    rows[1] = rows[1] + (v0 >> 16)
    return jnp.concatenate(rows, axis=0)


# Mosaic-only accumulation trim (docs/perf-roofline.md item 3): the dense
# shifted accumulation below adds each 16-row product block into a 32-row
# accumulator, so half of every add's rows are zeros.  The fast variants
# add into the 16 live rows only (static-slice .at[].add), trimming
# ~25-30% of the multiply's element-ops — but the slice+concat HLO this
# lowers to blows XLA *CPU* compile time up ~3x (measured round 2), so it
# is only switched on while tracing the Pallas TPU kernel.  The switch is
# THREAD-LOCAL: a concurrent CPU-side trace on another thread must not
# observe the TPU trace's flag (and vice versa).  Env knob
# CORDA_TPU_FAST_MUL=0 disables for A/B runs.
import threading as _threading

_FAST_MUL_TLS = _threading.local()
#: Default OFF since round 3: the jax.export TPU cross-lowering gate
#: proved Mosaic has no scatter-add lowering, so the .at[].add variants
#: cannot compile on current JAX (the runtime ladder would catch it, but
#: a doomed first attempt wastes tunnel-time compiles). The knob stays
#: for future JAX versions that implement it.
_FAST_MUL_ENABLED = os.environ.get("CORDA_TPU_FAST_MUL", "0") != "0"


def _fast_mul_active() -> bool:
    return getattr(_FAST_MUL_TLS, "active", False)


from contextlib import contextmanager as _contextmanager


@_contextmanager
def _fast_mul_trace(enabled: bool = True):
    """Enable the fast-mul variants for the duration of a kernel trace
    on THIS thread (the single place the save/set/restore lives)."""
    prev = _fast_mul_active()
    _FAST_MUL_TLS.active = enabled
    try:
        yield
    finally:
        _FAST_MUL_TLS.active = prev


def _mul_fast(a, b):
    """_mul with live-row accumulation (differential-tested vs _mul in
    tests/test_ops_ed25519.py; identical bounds argument)."""
    w = a.shape[1]
    acc = _zeros(32, w)
    for i in range(16):
        p = a[i : i + 1] * b
        lo = p & _MASK
        hi = p >> 16
        acc = acc.at[i : i + 16].add(lo)
        acc = acc.at[i + 1 : i + 17].add(hi)
    d = acc[:16] + np.uint32(38) * acc[16:]
    return _reduce(d)


def _square_fast(a):
    """_square with live-row accumulation (same symmetry exploitation)."""
    w = a.shape[1]
    acc = _zeros(32, w)
    for i in range(16):
        diag = a[i : i + 1] * a[i : i + 1]
        acc = acc.at[2 * i : 2 * i + 1].add(diag & _MASK)
        acc = acc.at[2 * i + 1 : 2 * i + 2].add(diag >> 16)
        if i + 1 < 16:
            p = a[i : i + 1] * a[i + 1 :]
            rows = p.shape[0]
            acc = acc.at[2 * i + 1 : 2 * i + 1 + rows].add((p & _MASK) * 2)
            acc = acc.at[2 * i + 2 : 2 * i + 2 + rows].add((p >> 16) * 2)
    d = acc[:16] + np.uint32(38) * acc[16:]
    return _reduce(d)


# --- radix-2^13 field variant (20 limbs on sublanes) ------------------------
#
# The 16-bit-limb multiply must split every row product into lo/hi
# halfwords immediately (products are full 32-bit), which costs 2 mask/
# shift ops and doubles the accumulation adds. With 13-bit limbs the
# products are 26-bit and a whole schoolbook column (<= 20 terms) sums
# below 2^31 — no splitting at all, ONE carry normalization at the end:
# ~26% fewer element-ops per multiply with live-row accumulation, ~33%
# fewer in the dense form (docs/perf-roofline.md, round-3 addendum).
#
# Representation notes (all differential-tested against python ints):
#   * field element = (20, W) uint32, limbs < 2^13 (tiny transient slack
#     from _reduce13's bounded final carry is tolerated by the product
#     bound, same trick as the 16-bit _reduce);
#   * 20*13 = 260 bits, so values are NOT clamped near p by capacity
#     (2^260 ~ 32p). The algebra is mod-p correct throughout; only
#     parity/zero tests need a true canonical value, via a binary
#     descent of conditional subtractions of 16p..p (_canonical13);
#   * 2^260 ≡ 608 (mod p) replaces the 16-bit scheme's 2^256 ≡ 38.
# The switch is trace-time + thread-local like fast-mul: the Pallas
# kernel enables it per compile (static jit arg), off-TPU tests via
# _radix13_trace. The portable XLA kernel and host prep stay 16-bit;
# the kernel converts its (16, W) inputs on entry (_rows16_to_13).

ROWS13 = 20
_MASK13 = np.uint32(0x1FFF)
_F13 = np.uint32(608)  # 2^260 mod p

_RADIX_ENV = os.environ.get("CORDA_TPU_ED25519_RADIX", "13")
if _RADIX_ENV not in ("13", "16"):
    raise ValueError(
        f"CORDA_TPU_ED25519_RADIX={_RADIX_ENV}: must be 13 or 16"
    )
#: default radix for the Pallas kernel (A/B knob for tools/hw_capture.py;
#: the off-TPU XLA kernel and host prep are always radix-16). Radix 13
#: became the DEFAULT in round 3: its dense kernel passes the TPU
#: cross-lowering gate and its multiply costs ~25-30% fewer vector ops
#: than radix-16 dense (docs/perf-roofline.md round-3 addendum).
_RADIX13_ENABLED = _RADIX_ENV == "13"


def _limbs13(x: int):
    return [(x >> (13 * k)) & 0x1FFF for k in range(ROWS13)]


_P13 = _limbs13(P_INT)
# descending multiples of p for canonicalization: values carry limb
# slack (< 2^13 + 2^11.5, see _reduce13), so magnitudes reach ~1.4*2^260
# ~ 45p — two 16p steps cover it
_CANON13_STEPS = [_limbs13(m * P_INT) for m in (16, 16, 8, 4, 2, 1, 1)]

_R13_TLS = _threading.local()


def _r13_active() -> bool:
    return getattr(_R13_TLS, "active", False)


@_contextmanager
def _radix13_trace(enabled: bool = True):
    prev = _r13_active()
    _R13_TLS.active = enabled
    try:
        yield
    finally:
        _R13_TLS.active = prev


def _fe_rows() -> int:
    """Rows of a field element under the active radix."""
    return ROWS13 if _r13_active() else 16


def _cur_limbs(x: int):
    return _limbs13(x) if _r13_active() else _limbs(x)


def _rows16_to_13(a16):
    """(16, W) 16-bit rows -> (20, W) 13-bit rows, value-preserving
    (static bit plumbing; each 13-bit window spans <= two 16-bit limbs)."""
    rows = []
    for k in range(ROWS13):
        bit = 13 * k
        w, off = bit // 16, bit % 16
        v = a16[w : w + 1] >> np.uint32(off)
        if off > 3 and w + 1 < 16:  # window crosses into the next limb
            v = v | (a16[w + 1 : w + 2] << np.uint32(16 - off))
        rows.append(v & _MASK13)
    return jnp.concatenate(rows, axis=0)


def _carry_round13(v):
    """One full-width carry-propagation round: every row keeps its low
    13 bits and receives the carry of the row below. The carry out of
    the TOP row is returned separately (callers fold it via *608).

    This replaces a sequential per-row chain (N ops of (1, W) each, 1/8
    sublane utilization on the VPU) with ~4 dense (N, W) ops — the
    single biggest vector-op cost in the radix-13 multiply."""
    w = v.shape[1]
    c = v >> 13
    kept = v & _MASK13
    return kept + _cat([_zeros(1, w), c[:-1]]), c[-1:]


def _reduce13(d):
    """(N, W) coefficients (each < 2^32) -> (20, W) value congruent
    mod p with SLACK limbs: the steady-state bound is the fixpoint of
    L -> 2^13 + carry-chain(20*L^2), bounded by the worst single-op
    output (11840, from _sub13's 608*6 fold); the
    uint32 product-column requirement is 20*L^2 < 2^32 i.e. L < 14654,
    comfortably above L* (empirically max limb ~8.3k over chained-op
    stress, tests/test_ops_ed25519.py::TestRadix13Field). N is 39 from
    a product, 20 from an add.

    Three vectorized carry rounds with *608 folds (2^260 ≡ 608 mod p) —
    replacing sequential per-row chains (~120 ops of (1, W) each at 1/8
    sublane utilization) with ~12 dense (N, W) ops."""
    n = d.shape[0]
    w = d.shape[1]
    assert n in (ROWS13, 2 * ROWS13 - 1), n  # the *608 fold weights assume it
    va, ca = _carry_round13(d)  # (n, W) normalized rows; ca at 2^(13n)
    if n > ROWS13:
        lo = va[:ROWS13]
        hi = _cat([va[ROWS13:], ca])  # exactly 20 rows at 2^260..
        lo = lo + _F13 * hi
    else:
        # n == ROWS13: the only out-of-range digit is ca, at 2^260
        lo = va + _F13 * _cat([ca, _zeros(ROWS13 - 1, w)])
    vb, cb = _carry_round13(lo)
    vb = vb + _F13 * _cat([cb, _zeros(ROWS13 - 1, w)])
    vc, cc = _carry_round13(vb)
    return vc + _F13 * _cat([cc, _zeros(ROWS13 - 1, w)])


def _mul13(a, b):
    """Radix-13 schoolbook: no lo/hi splitting. Inputs carry slack
    limbs (worst case 11840 = _sub13's output bound, the single proven
    bound all radix-13 comments share): products are ~27.5-bit and
    column sums reach 20 * 11840^2 = 2.80e9 — within uint32, NOT within
    int32; _reduce13's fixpoint argument keeps this stable."""
    w = a.shape[1]
    if _fast_mul_active():
        acc = _zeros(2 * ROWS13 - 1, w)
        for i in range(ROWS13):
            acc = acc.at[i : i + ROWS13].add(a[i : i + 1] * b)
    else:
        acc = _zeros(2 * ROWS13 - 1, w)
        for i in range(ROWS13):
            p = a[i : i + 1] * b
            acc = acc + _cat(
                [_zeros(i, w), p, _zeros(ROWS13 - 1 - i, w)]
            )
    return _reduce13(acc)


def _square13(a):
    """a^2 via symmetry: cross terms doubled. Slack-limb inputs give
    column sums <= 21 * 11840^2 = 2.94e9 (worst-case limb 11840, see
    _mul13) — uint32-safe per _reduce13's fixpoint bound."""
    w = a.shape[1]
    acc = _zeros(2 * ROWS13 - 1, w)
    if _fast_mul_active():
        for i in range(ROWS13):
            diag = a[i : i + 1] * a[i : i + 1]
            acc = acc.at[2 * i : 2 * i + 1].add(diag)
            if i + 1 < ROWS13:
                p = a[i : i + 1] * a[i + 1 :]
                rows = p.shape[0]
                acc = acc.at[2 * i + 1 : 2 * i + 1 + rows].add(p + p)
    else:
        for i in range(ROWS13):
            diag = a[i : i + 1] * a[i : i + 1]
            acc = acc + _cat(
                [_zeros(2 * i, w), diag, _zeros(2 * ROWS13 - 2 - 2 * i, w)]
            )
            if i + 1 < ROWS13:
                p = a[i : i + 1] * a[i + 1 :]
                rows = p.shape[0]
                acc = acc + _cat(
                    [
                        _zeros(2 * i + 1, w),
                        p + p,
                        _zeros(2 * ROWS13 - 2 - 2 * i - rows, w),
                    ]
                )
    return _reduce13(acc)


def _mul_const13(a, limbs):
    """a times compile-time 13-bit limbs (zero rows skipped)."""
    w = a.shape[1]
    acc = _zeros(2 * ROWS13 - 1, w)
    for i in range(ROWS13):
        if limbs[i] == 0:
            continue
        p = np.uint32(limbs[i]) * a
        acc = acc + _cat([_zeros(i, w), p, _zeros(ROWS13 - 1 - i, w)])
    return _reduce13(acc)


def _sub13_bias_rows():
    """Per-row constants for the vectorized subtraction: the digits of
    4C (C = 2^260 - 608 ≡ 0 mod p) with +2^14 added to EVERY row and -2
    compensated into the next position (net value unchanged; the top
    compensation comes out of 4C's implicit 2^262-bits digit, 3 -> 1).
    With them, a - b + bias is NON-NEGATIVE per row for any slack-limbed
    a, b (rows < 2^13.6): min row value = 0 - 12289 + 22144 > 0 — so a
    single UNSIGNED carry round normalizes; no borrow can ripple."""
    base = 4 * (2**260 - 608)
    d = [(base >> (13 * k)) & 0x1FFF for k in range(ROWS13)]
    rows = [d[0] + 2**14] + [d[k] + 2**14 - 2 for k in range(1, ROWS13)]
    top = (base >> 260) - 2  # = 1
    return rows, top


_SUB13_ROWS, _SUB13_TOP = _sub13_bias_rows()


def _sub13(a, b):
    """a - b mod p for slack-limbed values: one dense a - b + bias (all
    rows provably non-negative, see _sub13_bias_rows), ONE vectorized
    carry round, and a *608 fold of the 2^260-digit (= top carry + 1).
    Output limbs < 2^13 + 608*6 < 11.9k, inside every consumer's slack
    budget (see _reduce13's fixpoint bound)."""
    w = a.shape[1]
    bias = jnp.concatenate(
        [jnp.full((1, w), np.uint32(v), jnp.uint32) for v in _SUB13_ROWS],
        axis=0,
    )
    # rows stay non-negative, so plain uint32 wrap-free arithmetic works
    v = a + bias - b
    vr, c_top = _carry_round13(v)
    digit_260 = c_top + np.uint32(_SUB13_TOP)
    row0 = vr[0:1] + digit_260 * _F13
    return _cat([row0, vr[1:]])


def _cond_sub13(a, limbs):
    rows = []
    carry = None
    for k in range(ROWS13):
        v = a[k : k + 1].astype(jnp.int32) - np.int32(limbs[k])
        if carry is not None:
            v = v + carry
        rows.append((v & 0x1FFF).astype(jnp.uint32))
        carry = v >> 13
    geq = carry == 0
    return jnp.where(geq, jnp.concatenate(rows, axis=0), a), geq


def _canonical13(a):
    """True canonical (< p) from any SLACK-limbed value (limbs < L* ~
    11.2k, magnitudes up to ~1.4 * 2^260 ~ 45p): binary descent over
    conditional subtractions of 16p, 16p, 8p, 4p, 2p, p, p (48p
    coverage)."""
    r = a
    for limbs in _CANON13_STEPS:
        r, _ = _cond_sub13(r, limbs)
    return r


def _mul(a, b):
    """Schoolbook product via shifted accumulation; all ops dense (W lanes).

    Row products a_i * b fit uint32 exactly (16x16-bit limbs); coefficient
    sums <= 32 halfword terms < 2^21; the *38 fold keeps < 2^27."""
    if _r13_active():
        return _mul13(a, b)
    if _fast_mul_active():
        return _mul_fast(a, b)
    w = a.shape[1]
    c = _zeros(32, w)
    for i in range(16):
        p = a[i : i + 1] * b
        lo = p & _MASK
        hi = p >> 16
        c = c + _cat([_zeros(i, w), lo, _zeros(16 - i, w)])
        c = c + _cat([_zeros(i + 1, w), hi, _zeros(15 - i, w)])
    d = c[:16] + np.uint32(38) * c[16:]
    return _reduce(d)


def _square(a):
    """a^2 exploiting symmetry: off-diagonal halfwords doubled (< 2^17;
    coefficient sums stay < 2^21), ~0.6x the products of _mul."""
    if _r13_active():
        return _square13(a)
    if _fast_mul_active():
        return _square_fast(a)
    w = a.shape[1]
    c = _zeros(32, w)
    for i in range(16):
        diag = a[i : i + 1] * a[i : i + 1]
        lo = diag & _MASK
        hi = diag >> 16
        c = c + _cat([_zeros(2 * i, w), lo, hi, _zeros(30 - 2 * i, w)])
        if i + 1 < 16:
            p = a[i : i + 1] * a[i + 1 :]
            rows = p.shape[0]
            lo = (p & _MASK) * 2
            hi = (p >> 16) * 2
            c = c + _cat(
                [_zeros(2 * i + 1, w), lo, _zeros(31 - 2 * i - rows, w)]
            )
            c = c + _cat(
                [_zeros(2 * i + 2, w), hi, _zeros(30 - 2 * i - rows, w)]
            )
    d = c[:16] + np.uint32(38) * c[16:]
    return _reduce(d)


def _mul_const(a, limbs):
    """a times compile-time limbs: same structure as _mul, constant rows.
    `limbs` must be in the ACTIVE radix (call sites use _cur_limbs)."""
    if _r13_active():
        return _mul_const13(a, limbs)
    w = a.shape[1]
    c = _zeros(32, w)
    for i in range(16):
        if limbs[i] == 0:
            continue
        p = np.uint32(limbs[i]) * a
        lo = p & _MASK
        hi = p >> 16
        c = c + _cat([_zeros(i, w), lo, _zeros(16 - i, w)])
        c = c + _cat([_zeros(i + 1, w), hi, _zeros(15 - i, w)])
    d = c[:16] + np.uint32(38) * c[16:]
    return _reduce(d)


def _add(a, b):
    if _r13_active():
        return _reduce13(a + b)
    return _reduce(a + b)


def _sub(a, b):
    """a - b via a + 2p - b with a signed borrow chain (bounds as in
    ops/fe25519.py `sub`)."""
    if _r13_active():
        return _sub13(a, b)
    twop = np.asarray(_TWOP_LIMBS, np.int32)
    rows = []
    carry = None
    for k in range(16):
        v = (
            a[k : k + 1].astype(jnp.int32)
            - b[k : k + 1].astype(jnp.int32)
            + np.int32(int(twop[k]))
        )
        if carry is not None:
            v = v + carry
        rows.append((v & 0xFFFF).astype(jnp.uint32))
        carry = v >> 16
    negative = carry < 0
    pos_rows = list(rows)
    pos_rows[0] = rows[0] + jnp.maximum(carry, 0).astype(jnp.uint32) * np.uint32(38)
    pos = _reduce(jnp.concatenate(pos_rows, axis=0))
    neg0 = rows[0] - np.uint32(38)
    neg = jnp.concatenate([neg0] + rows[1:], axis=0)
    return jnp.where(negative, neg, pos)


def _neg(a):
    return _sub(jnp.zeros_like(a), a)


def _cond_sub_p(a):
    if _r13_active():
        return _cond_sub13(a, _P13)
    rows = []
    carry = None
    for k in range(16):
        v = a[k : k + 1].astype(jnp.int32) - np.int32(_P_LIMBS[k])
        if carry is not None:
            v = v + carry
        rows.append((v & 0xFFFF).astype(jnp.uint32))
        carry = v >> 16
    geq = carry == 0
    return jnp.where(geq, jnp.concatenate(rows, axis=0), a), geq


def _canonical(a):
    if _r13_active():
        return _canonical13(a)
    r, _ = _cond_sub_p(a)
    r, _ = _cond_sub_p(r)
    return r


def _lt_p(a):
    _, geq = _cond_sub_p(a)
    return ~geq


def _is_zero(a):
    c = _canonical(a)
    acc = c[0:1]
    for k in range(1, _fe_rows()):
        acc = acc | c[k : k + 1]
    return acc == 0


def _eq(a, b):
    return _is_zero(_sub(a, b))


def _select_fe(mask, a, b):
    return jnp.where(mask, a, b)


def _nsquare(x, n):
    if n <= 2:
        for _ in range(n):
            x = _square(x)
        return x
    return lax.fori_loop(0, n, lambda _, v: _square(v), x)


def _pow22523(x):
    """x^(2^252 - 3): classic chain, 250 squarings + 11 multiplies."""
    z2 = _square(x)
    z8 = _nsquare(z2, 2)
    z9 = _mul(x, z8)
    z11 = _mul(z2, z9)
    z22 = _square(z11)
    z_5_0 = _mul(z9, z22)
    z_10_5 = _nsquare(z_5_0, 5)
    z_10_0 = _mul(z_10_5, z_5_0)
    z_20_10 = _nsquare(z_10_0, 10)
    z_20_0 = _mul(z_20_10, z_10_0)
    z_40_20 = _nsquare(z_20_0, 20)
    z_40_0 = _mul(z_40_20, z_20_0)
    z_50_40 = _nsquare(z_40_0, 10)
    z_50_0 = _mul(z_50_40, z_10_0)
    z_100_50 = _nsquare(z_50_0, 50)
    z_100_0 = _mul(z_100_50, z_50_0)
    z_200_100 = _nsquare(z_100_0, 100)
    z_200_0 = _mul(z_200_100, z_100_0)
    z_250_200 = _nsquare(z_200_0, 50)
    z_250_0 = _mul(z_250_200, z_50_0)
    z_252_2 = _nsquare(z_250_0, 2)
    return _mul(z_252_2, x)


# --- point ops: extended coordinates, each coord (16, W) --------------------

def _pt_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = _mul(_sub(Y1, X1), _sub(Y2, X2))
    b = _mul(_add(Y1, X1), _add(Y2, X2))
    c = _mul_const(_mul(T1, T2), _cur_limbs(_D2_INT))
    zz = _mul(Z1, Z2)
    d = _add(zz, zz)
    e, f, g, h = _sub(b, a), _sub(d, c), _add(d, c), _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _to_cached(p):
    """Extended point -> cached form (Y+X, Y-X, 2Z, 2d*T) for the ladder
    add: saves one constant mul and three add/subs per iteration."""
    X, Y, Z, T = p
    return (
        _add(Y, X), _sub(Y, X), _add(Z, Z),
        _mul_const(T, _cur_limbs(_D2_INT)),
    )


def _pt_add_cached(p, q_cached):
    X1, Y1, Z1, T1 = p
    ypx, ymx, z2x2, t2d = q_cached
    a = _mul(_sub(Y1, X1), ymx)
    b = _mul(_add(Y1, X1), ypx)
    c = _mul(T1, t2d)
    d = _mul(Z1, z2x2)
    e, f, g, h = _sub(b, a), _sub(d, c), _add(d, c), _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _pt_double(p, with_t=True):
    X1, Y1, Z1, _ = p
    a = _square(X1)
    b = _square(Y1)
    zz = _square(Z1)
    c = _add(zz, zz)
    h = _add(a, b)
    e = _sub(h, _square(_add(X1, Y1)))
    g = _sub(a, b)
    f = _add(c, g)
    t = _mul(e, h) if with_t else p[3]
    return (_mul(e, f), _mul(g, h), _mul(f, g), t)


def _pt_neg(p):
    X, Y, Z, T = p
    return (_neg(X), Y, Z, _neg(T))


def _decompress(y, sign):
    """y limbs (active radix) + (1, W) sign -> ((x, y, 1, xy), ok (1, W))."""
    w = y.shape[1]
    one = _const_col(_cur_limbs(1), w)
    ok_y = _lt_p(y)
    y2 = _square(y)
    u = _sub(y2, one)
    v = _add(_mul_const(y2, _cur_limbs(D_INT)), one)
    v3 = _mul(_square(v), v)
    v7 = _mul(_square(v3), v)
    t = _pow22523(_mul(u, v7))
    x = _mul(_mul(u, v3), t)
    vx2 = _mul(v, _square(x))
    root1 = _eq(vx2, u)
    root2 = _eq(vx2, _neg(u))
    x = _select_fe(root1, x, _mul_const(x, _cur_limbs(SQRT_M1_INT)))
    ok = ok_y & (root1 | root2)
    x_is_zero = _is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    flip = (_canonical(x)[0:1] & 1) != sign
    x = _select_fe(flip, _neg(x), x)
    return (x, y, one, _mul(x, y)), ok


def _affine_const_pt(k: int, width):
    pt = ed25519_math.scalar_mult(k, ed25519_math.BASE)
    x, y = ed25519_math.to_affine(pt)
    return (
        _const_col(_cur_limbs(x), width),
        _const_col(_cur_limbs(y), width),
        _const_col(_cur_limbs(1), width),
        _const_col(_cur_limbs(x * y % P_INT), width),
    )


def _identity_pt(width):
    return (
        _zeros(_fe_rows(), width),
        _const_col(_cur_limbs(1), width),
        _const_col(_cur_limbs(1), width),
        _zeros(_fe_rows(), width),
    )


# --- the kernel --------------------------------------------------------------

def _verify_core(width, y_a, sign_a, y_r, sign_r, s_words, h_words, ok_in,
                 write_table, read_table, write_idx, read_idx,
                 unroll_ladder=False):
    """The verification program, abstracted over table/digit storage.

    The Pallas kernel backs `write_table`/`read_table`/`write_idx`/
    `read_idx` with VMEM scratch refs; the off-TPU unit test backs them
    with dict-buffered arrays + dynamic_slice reads
    (tests/test_ops_ed25519.py), so every field/point/ladder step — under
    the same lax.fori_loop control flow — is exercised without TPU
    hardware. unroll_ladder=True remains for debugging with accessors that
    need concrete indices."""
    R = _fe_rows()
    if _r13_active():
        # host prep + the portable XLA kernel stay radix-16; convert the
        # compressed-y inputs on entry (static bit plumbing, ~80 ops per
        # field element vs ~4.6M for the ladder)
        y_a = _rows16_to_13(y_a)
        y_r = _rows16_to_13(y_r)
    # Decompress A and R lane-concatenated: one pow chain for both.
    pts, oks = _decompress(
        jnp.concatenate([y_a, y_r], axis=1),
        jnp.concatenate([sign_a, sign_r], axis=1),
    )
    a_pt = tuple(c[:, :width] for c in pts)
    r_pt = tuple(c[:, width:] for c in pts)
    ok_a, ok_r = oks[:, :width], oks[:, width:]

    neg_a = _pt_neg(a_pt)
    a2 = _pt_double(neg_a)
    a3 = _pt_add(a2, neg_a)
    a_mults = [neg_a, a2, a3]
    b_mults = [_affine_const_pt(k, width) for k in (1, 2, 3)]

    # Joint Straus table: entry e = i + 4*j holds i*B + j*(-A).
    entries = [None] * 16
    entries[0] = _identity_pt(width)
    for i in (1, 2, 3):
        entries[i] = b_mults[i - 1]
    for j in (1, 2, 3):
        entries[4 * j] = a_mults[j - 1]
    for i in (1, 2, 3):
        for j in (1, 2, 3):
            entries[i + 4 * j] = _pt_add(b_mults[i - 1], a_mults[j - 1])
    for e, p in enumerate(entries):
        write_table(e, jnp.concatenate(_to_cached(p), axis=0))

    # 2-bit digit rows for both scalars: idx row t = s-digit + 4*h-digit.
    # Only 127 digits: both scalars are < L < 2^253 (s by the host's
    # s_ok canonicality check — rows with s >= L are already failed by
    # the mask, so their garbage ladder result is irrelevant — and
    # h = SHA-512 mod L by construction), so digit t=127 (bits 254-255)
    # is always zero and its 2 doubles + 1 add are skipped.
    for t in range(NDIGITS):
        w, r = (2 * t) // 32, (2 * t) % 32
        write_idx(
            t,
            ((s_words[w : w + 1] >> r) & 3)
            + 4 * ((h_words[w : w + 1] >> r) & 3),
        )

    def body(i, q):
        t = NDIGITS - 1 - i
        row = read_idx(t)  # (1, width)
        q = _pt_double(q, with_t=False)
        q = _pt_double(q)
        sel = _zeros(4 * R, width)
        for e in range(16):
            m = (row == e).astype(jnp.uint32)
            sel = sel + m * read_table(e)
        sel_c = tuple(sel[c * R : (c + 1) * R] for c in range(4))
        return _pt_add_cached(q, sel_c)

    if unroll_ladder:
        # Off-TPU test path: python loop so array-backed accessors can use
        # concrete indices (lax.fori_loop traces its body).
        q = _identity_pt(width)
        for i in range(NDIGITS):
            q = body(i, q)
    else:
        q = lax.fori_loop(0, NDIGITS, body, _identity_pt(width))

    eq_x = _eq(q[0], _mul(r_pt[0], q[2]))
    eq_y = _eq(q[1], _mul(r_pt[1], q[2]))
    return ((ok_in != 0) & ok_a & ok_r & eq_x & eq_y).astype(jnp.uint32)


def _make_kernel(fast_mul: bool, radix13: bool = False):
    """Kernel body closure over the fast-mul and radix choices. Both must
    be compile-time parameters (part of the jit cache key below): if they
    were read from module globals at trace time, flipping a global after
    a cached compile could never reach a retry with the same shapes."""
    stride = 4 * (ROWS13 if radix13 else 16)

    def _kernel(y_a_ref, sign_a_ref, y_r_ref, sign_r_ref, s_ref, h_ref,
                ok_ref, out_ref, tab_ref, idx_ref):
        def write_table(e, rows):
            tab_ref[e * stride : (e + 1) * stride, :] = rows

        def read_table(e):
            return tab_ref[e * stride : (e + 1) * stride, :]

        def write_idx(t, row):
            idx_ref[t : t + 1, :] = row

        def read_idx(t):
            return idx_ref[pl.ds(t, 1), :]

        # trace-time switch: the fast-mul variants lower well under Mosaic
        # but blow up XLA CPU compiles, so they are enabled only while this
        # TPU kernel body is being traced, on this thread only (module
        # comment at _FAST_MUL_TLS)
        with _fast_mul_trace(fast_mul), _radix13_trace(radix13):
            out_ref[:] = _verify_core(
                BLK,
                y_a_ref[:],
                sign_a_ref[:],
                y_r_ref[:],
                sign_r_ref[:],
                s_ref[:],
                h_ref[:],
                ok_ref[:],
                write_table,
                read_table,
                write_idx,
                read_idx,
            )

    return _kernel


def verify_kernel_pallas(y_a_t, sign_a, y_r_t, sign_r, s_t, h_t, s_ok,
                         fast_mul=None, radix13=None):
    """Transposed inputs: y_*_t (16, B), sign_* (1, B), s_t/h_t (8, B),
    s_ok (1, B) uint32. B must be a multiple of BLK. Returns (1, B) uint32
    pass/fail. `fast_mul`/`radix13` default to the module flags, resolved
    HERE (outside jit) so a post-failure flip reaches the next call as a
    new static argument instead of hitting the stale cached executable."""
    if fast_mul is None:
        fast_mul = _FAST_MUL_ENABLED
    if radix13 is None:
        radix13 = _RADIX13_ENABLED
    return _verify_kernel_pallas_jit(
        y_a_t, sign_a, y_r_t, sign_r, s_t, h_t, s_ok,
        fast_mul=bool(fast_mul), radix13=bool(radix13),
    )


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("fast_mul", "radix13"))
def _verify_kernel_pallas_jit(y_a_t, sign_a, y_r_t, sign_r, s_t, h_t, s_ok,
                              *, fast_mul, radix13=False):
    n = y_a_t.shape[1]
    if n % BLK != 0:
        # flooring the grid would silently skip tail lanes — refuse
        raise ValueError(
            f"batch lane count {n} is not a multiple of BLK={BLK}"
        )
    grid = n // BLK

    def spec(rows):
        return pl.BlockSpec((rows, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)

    fe_rows = ROWS13 if radix13 else 16
    return pl.pallas_call(
        _make_kernel(fast_mul, radix13),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.uint32),
        grid=(grid,),
        in_specs=[
            spec(16),
            spec(1),
            spec(16),
            spec(1),
            spec(8),
            spec(8),
            spec(1),
        ],
        out_specs=spec(1),
        scratch_shapes=[
            pltpu.VMEM((16 * 4 * fe_rows, BLK), jnp.uint32),  # Straus table
            pltpu.VMEM((NDIGITS + 1, BLK), jnp.uint32),  # digit rows
        ],
    )(y_a_t, sign_a, y_r_t, sign_r, s_t, h_t, s_ok)

"""Batched BLS12-381 G1/G2 arithmetic and optimal-ate pairing kernels.

The device half of the BLS aggregate-signature scheme (host reference:
core.crypto.bls_math; field tower: ops.field_bls12). Everything is
batch-first and batch-uniform:

  * The Miller loop runs under ONE lax.fori_loop over the 63 post-MSB
    bits of |x| (weight 6): every iteration computes the doubling step
    AND the addition step and selects by the bit — the pow_const
    pattern, no data-dependent control flow.
  * G2 loop points are homogeneous projective (X, Y, Z) on the twist,
    with INVERSION-FREE line evaluations: the affine line
    ell = xi*yP - lam*xP*w^5 + (lam*x - y)*w^3 (M-twist untwist, scaled
    by xi) is cleared of denominators by scaling with 2YZ^2 (doubling)
    / the chord denominator (addition) — per-line Fp2 constants, killed
    by the final exponentiation.
  * The final exponentiation mirrors bls_math exactly: easy part, then
    the Hayashida-Hayasaka-Teruya hard part (pairing CUBED — asserted
    identity, see bls_math's module doc), so device and host compute
    IDENTICAL GT values and differential tests compare exactly.
  * Independent Fp2 multiplies inside each step are gathered into
    stacked calls (field_bls12's stacked-coefficient representation):
    compile cost on XLA CPU scales with scan/dot NODES, not with batch
    rows, so a step is a handful of stacked ops rather than ~40 field
    muls.

Verification entry: `verify_pairs_batch` checks
e(P1, Q1) * e(P2, Q2) == 1 per row — the shape of both a single BLS
verify (e(-g1, sig) * e(pk, H(m))) and a committee aggregate verify
(e(-g1, agg_sig) * e(agg_pk, H(m))): ONE such row per committee block
regardless of committee size. Rows pad to CORDA_TPU_BLS12_BLK so
tools/tune_kernel.py can sweep the pairing batch size.
"""
from __future__ import annotations

import os
from typing import List, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.crypto import bls_math
from . import field_bls12 as FB

# pairing batch granularity: rows pad up to a multiple (one compiled
# shape per multiple; swept by tools/tune_kernel.py --bls-blks)
BLK = int(os.environ.get("CORDA_TPU_BLS12_BLK", "8"))

_X_ABS_BITS = [int(b) for b in bin(-bls_math.X)[3:]]  # MSB consumed by T=Q


def _fp2_stack_mul(pairs):
    """One stacked F.mul over independent fp2 products: pairs is a list
    of (a, b) fp2 arrays with identical shapes; returns the list of
    products. THE compile-cost lever — k muls cost one graph."""
    a = jnp.stack([p[0] for p in pairs], axis=-3)
    b = jnp.stack([p[1] for p in pairs], axis=-3)
    out = FB.fp2_mul(a, b)
    return [out[..., i, :, :] for i in range(len(pairs))]


def _line_fp12(g0, h1, h2):
    """Sparse line g0 + h1*w^3 + h2*w^5 as a dense fp12 array (the
    zero slots cost adds inside the following fp12_mul; a sparse
    multiply is a future op-budget optimization, pinned separately)."""
    z = jnp.zeros_like(g0)
    g = jnp.stack([g0, z, z], axis=-3)  # fp6: (g0, 0, 0)
    h = jnp.stack([z, h1, h2], axis=-3)  # fp6: (0, h1, h2) -> w^3, w^5
    return jnp.stack([g, h], axis=-4)


def _dbl_step(tx, ty, tz, neg_xp, yp):
    """Projective doubling + line through T evaluated at P.

    T = 3X^2, U = 2YZ, V = T^2 Z - 2XU^2:
      X3 = UV, Y3 = T(XU^2 - V) - YU^3, Z3 = U^3 Z
    line scaled by U*Z: g0 = xi*(UZ)*yP, h1 = T*X - U*Y, h2 = -T*Z*xP.
    """
    sq, yz = _fp2_stack_mul([(tx, tx), (ty, tz)])
    t3 = FB.fp2_scale_small(sq, 3)
    u = FB.F.add(yz, yz)
    u2, t3x, uty, t3z, uz, t3sq = _fp2_stack_mul(
        [(u, u), (t3, tx), (u, ty), (t3, tz), (u, tz), (t3, t3)]
    )
    u3, xu2, t2z = _fp2_stack_mul([(u2, u), (tx, u2), (t3sq, tz)])
    v = FB.fp2_sub(t2z, FB.F.add(xu2, xu2))
    x3, acoef, yu3, z3 = _fp2_stack_mul(
        [(u, v), (t3, FB.fp2_sub(xu2, v)), (ty, u3), (u3, tz)]
    )
    y3 = FB.fp2_sub(acoef, yu3)
    g0 = FB.fp2_mul_fp(FB.fp2_mul_xi(uz), yp)
    h1 = FB.fp2_sub(t3x, uty)
    h2 = FB.fp2_mul_fp(t3z, neg_xp)
    return (x3, y3, z3), _line_fp12(g0, h1, h2)


def _add_step(tx, ty, tz, qx, qy, neg_xp, yp):
    """Mixed addition T + Q (Q affine) + chord line through T, Q at P.

    N = Y - yQ Z, D = X - xQ Z, W = N^2 Z - D^2 (X + xQ Z):
      X3 = WD, Y3 = N(xQ D^2 Z - W) - yQ D^3 Z, Z3 = D^3 Z
    line scaled by D: g0 = xi*D*yP, h1 = N xQ - D yQ, h2 = -N xP.
    """
    qxz, qyz = _fp2_stack_mul([(qx, tz), (qy, tz)])
    n = FB.fp2_sub(ty, qyz)
    d = FB.fp2_sub(tx, qxz)
    n2, d2 = _fp2_stack_mul([(n, n), (d, d)])
    n2z, d2z, d2s = _fp2_stack_mul(
        [(n2, tz), (d2, tz), (d2, FB.F.add(tx, qxz))]
    )
    w = FB.fp2_sub(n2z, d2s)
    x3, d3z, qxd2z, nqx, dqy = _fp2_stack_mul(
        [(w, d), (d, d2z), (qx, d2z), (n, qx), (d, qy)]
    )
    t1, t2 = _fp2_stack_mul([(n, FB.fp2_sub(qxd2z, w)), (qy, d3z)])
    y3 = FB.fp2_sub(t1, t2)
    g0 = FB.fp2_mul_fp(FB.fp2_mul_xi(d), yp)
    h1 = FB.fp2_sub(nqx, dqy)
    h2 = FB.fp2_mul_fp(n, neg_xp)
    return (x3, y3, d3z), _line_fp12(g0, h1, h2)


def miller_loop(xp, yp, qx, qy):
    """Batched optimal-ate Miller function f_{|x|,Q}(P), conjugated for
    the negative x — one (P, Q) pair per batch row.

    xp/yp: (B, 24) Montgomery Fp; qx/qy: (B, 2, 24) Montgomery Fp2
    affine twist coordinates. Returns (B, 2, 3, 2, 24) fp12.
    """
    batch = xp.shape[:-1]
    bits = jnp.asarray(_X_ABS_BITS, jnp.uint32)
    neg_xp = FB.F.neg(xp)
    one2 = jnp.stack(
        [FB.F.const(FB.ONE_M, batch), FB.F.const(FB.ZERO_M, batch)],
        axis=-2,
    )
    state = (qx, qy, one2, FB.fp12_one(batch))

    def body(i, st):
        tx, ty, tz, f = st
        f = FB.fp12_sq(f)
        (tx, ty, tz), line = _dbl_step(tx, ty, tz, neg_xp, yp)
        f = FB.fp12_mul(f, line)
        (ax, ay, az), aline = _add_step(tx, ty, tz, qx, qy, neg_xp, yp)
        fa = FB.fp12_mul(f, aline)
        take = bits[i] == 1
        f = FB.fp12_select(take, fa, f)
        tx = FB.fp2_select(take, ax, tx)
        ty = FB.fp2_select(take, ay, ty)
        tz = FB.fp2_select(take, az, tz)
        return (tx, ty, tz, f)

    _, _, _, f = lax.fori_loop(0, len(_X_ABS_BITS), body, state)
    return FB.fp12_conj(f)


def _pow_x_abs(a):
    """a^|x| under a fori_loop over the 63 post-MSB bits."""
    bits = jnp.asarray(_X_ABS_BITS, jnp.uint32)

    def body(i, acc):
        acc = FB.fp12_sq(acc)
        return FB.fp12_select(bits[i] == 1, FB.fp12_mul(acc, a), acc)

    return lax.fori_loop(0, len(_X_ABS_BITS), body, a)


def final_exponentiation(f):
    """f^(3*(p^12-1)/r), mirroring bls_math.final_exponentiation."""
    f = FB.fp12_mul(FB.fp12_conj(f), FB.fp12_inv(f))  # ^(p^6 - 1)
    f = FB.fp12_mul(FB.fp12_frob(FB.fp12_frob(f)), f)  # ^(p^2 + 1)

    def pow_x(a):  # cyclotomic: inverse = conjugate, x < 0
        return FB.fp12_conj(_pow_x_abs(a))

    a = FB.fp12_mul(pow_x(f), FB.fp12_conj(f))
    a = FB.fp12_mul(pow_x(a), FB.fp12_conj(a))
    b = FB.fp12_mul(pow_x(a), FB.fp12_frob(a))
    c = FB.fp12_mul(
        FB.fp12_mul(pow_x(pow_x(b)), FB.fp12_frob(FB.fp12_frob(b))),
        FB.fp12_conj(b),
    )
    return FB.fp12_mul(c, FB.fp12_mul(FB.fp12_sq(f), f))


@jax.jit
def pairing_kernel(xp, yp, qx, qy):
    """Full batched pairing e(P, Q)^3: Miller loop + final exp."""
    return final_exponentiation(miller_loop(xp, yp, qx, qy))


@jax.jit
def verify_pairs_kernel(xp, yp, qx, qy):
    """Rows hold TWO (P, Q) pairs each (leading pair axis folded into
    the batch as (B, 2)): returns the (B,) mask of
    e(P1,Q1)*e(P2,Q2) == 1 — one Miller product, ONE final exp per row.
    """
    f = miller_loop(xp, yp, qx, qy)  # (B, 2, ...fp12)
    prod = FB.fp12_mul(f[:, 0], f[:, 1])
    return FB.fp12_eq_one(final_exponentiation(prod))


# --- host packing ------------------------------------------------------------

def _pad(n: int) -> int:
    return ((max(n, 1) + BLK - 1) // BLK) * BLK


def pack_g1(points) -> Tuple[np.ndarray, np.ndarray]:
    """Affine int G1 points -> (B, 24) Montgomery xp, yp."""
    xp = np.stack([FB.F.to_mont_int(p[0]) for p in points])
    yp = np.stack([FB.F.to_mont_int(p[1]) for p in points])
    return xp, yp


def pack_g2(points) -> Tuple[np.ndarray, np.ndarray]:
    qx = np.stack([FB.fp2_to_mont(p[0]) for p in points])
    qy = np.stack([FB.fp2_to_mont(p[1]) for p in points])
    return qx, qy


def pairing_batch(ps, qs) -> List[bls_math.Fp12]:
    """Batched pairings of affine G1/G2 int points (no infinities —
    callers handle those; bls_math is the scalar oracle). Returns
    bls_math-format Fp12 values, bit-identical to bls_math.pairing."""
    n = len(ps)
    if n == 0:
        return []
    pad = _pad(n)
    ps = list(ps) + [ps[-1]] * (pad - n)
    qs = list(qs) + [qs[-1]] * (pad - n)
    xp, yp = pack_g1(ps)
    qx, qy = pack_g2(qs)
    out = np.asarray(pairing_kernel(xp, yp, qx, qy))
    return [FB.fp12_from_mont(out[i]) for i in range(n)]


def verify_pairs_batch(pairs1, pairs2) -> List[bool]:
    """Batched product-of-two-pairings identity checks.

    pairs1/pairs2: per row, the ((P, Q)) tuples of affine int points.
    Row i verifies e(P1_i, Q1_i) * e(P2_i, Q2_i) == 1 — the BLS verify
    and committee-aggregate-verify shape."""
    n = len(pairs1)
    if n == 0:
        return []
    pad = _pad(n)
    p1 = list(pairs1) + [pairs1[-1]] * (pad - n)
    p2 = list(pairs2) + [pairs2[-1]] * (pad - n)
    flat_p = []
    flat_q = []
    for (a1, b1), (a2, b2) in zip(p1, p2):
        flat_p.extend([a1, a2])
        flat_q.extend([b1, b2])
    xp, yp = pack_g1(flat_p)
    qx, qy = pack_g2(flat_q)
    mask = np.asarray(verify_pairs_kernel(
        xp.reshape(pad, 2, -1), yp.reshape(pad, 2, -1),
        qx.reshape(pad, 2, 2, -1), qy.reshape(pad, 2, 2, -1),
    ))
    return [bool(mask[i]) for i in range(n)]


def aggregate_verify_device(pubkeys: Sequence[bytes], message: bytes,
                            agg_signature: bytes) -> bool:
    """The committee check through the device kernel: decompress/
    aggregate on the host (bls_math), ONE 2-pairing row on the device.
    The per-row work is constant in committee size — the aggregation
    lever the bench stage measures. Same boolean contract as the host
    aggregate_verify: malformed/off-curve/non-subgroup bytes return
    False, never raise."""
    try:
        agg_pk = bls_math.aggregate_pubkeys(pubkeys)
        sig_pt = bls_math.g2_decompress(agg_signature)
    except ValueError:
        return False
    if agg_pk is None or sig_pt is None:
        return False
    h = bls_math.hash_to_curve_g2(message)
    return verify_pairs_batch(
        [(bls_math.g1_neg(bls_math.G1_GEN), sig_pt)],
        [(agg_pk, h)],
    )[0]


def _microbench(blk: int, reps: int = 3) -> dict:
    """One-shot pairing-kernel microbench (tools/tune_kernel.py sweeps
    BLK through this): compile + best-of wall per verify row."""
    import time

    rng = np.random.default_rng(11)
    sks = [int(rng.integers(1, 2**62)) for _ in range(blk)]
    rows1, rows2 = [], []
    h = bls_math.hash_to_curve_g2(b"tune")
    for sk in sks:
        pk = bls_math.g1_mul(bls_math.G1_GEN, sk)
        sig = bls_math.g2_mul(h, sk)
        rows1.append((bls_math.g1_neg(bls_math.G1_GEN), sig))
        rows2.append((pk, h))
    t0 = time.perf_counter()
    out = verify_pairs_batch(rows1, rows2)
    compile_s = time.perf_counter() - t0
    assert all(out), "tuning batch failed to verify"
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        verify_pairs_batch(rows1, rows2)
        best = min(best, time.perf_counter() - t0)
    return {
        "metric": "bls12-aggregate-verify-rows/s",
        "blk": blk,
        "value": round(blk / best, 2),
        "compile_s": round(compile_s, 2),
        "row_ms": round(best / blk * 1000, 3),
    }


if __name__ == "__main__":
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="bls12_batch")
    ap.add_argument("--bench", action="store_true")
    ap.add_argument("--blk", type=int, default=BLK)
    args = ap.parse_args()
    if args.bench:
        BLK = args.blk
        print(json.dumps(_microbench(args.blk)))

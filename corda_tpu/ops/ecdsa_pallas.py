"""Pallas TPU kernel: batched ECDSA (secp256k1/r1) verification.

The XLA kernel (ops/ecdsa_batch.py) shares the round-1 ed25519 kernel's
weakness on TPU: scatter-style limb updates materialise HBM traffic per
field op. This module applies the ed25519 Pallas redesign
(ops/ed25519_pallas.py) to the secp curves:

  * limbs on sublanes, batch on lanes — a field element is (16, W) uint32,
    radix 2^16, Montgomery domain (CIOS with delayed carries; bounds as
    in ops/field_secp.MontField.mul's docstring);
  * Jacobian double/add (dbl-2007-bl / add-2007-bl) with every degenerate
    case (infinity, doubling, inverse) resolved by masks — batch-uniform
    control flow;
  * one joint 2-bit Shamir ladder computing u1*G + u2*Q: a 16-entry
    scratch table (i*G + j*Q), 128 iterations of 2 doubles + table-select
    + one general add (entry 0 is the point at infinity, so "no digit"
    needs no special case);
  * verdict: R finite and x(R) mod n == r.

Host-side DER/X962 parsing and the mod-n scalar work stay in
ops/ecdsa_batch.prepare_batch; this module is TPU-only, with the math
core (`_verify_core`) exercised off-TPU by tests/test_ops_ecdsa.py via
array-backed accessors, exactly like the ed25519 kernel's core.

Reference parity: replaces the per-signature BouncyCastle verify
(`Crypto.kt:91-118` -> JCA `Signature.verify`).
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .field_secp import MontField
# shared row-layout helpers (incl. _cat's Mosaic drop-zero-rows rule) and
# the layout-agnostic curve table live with their original kernels
from .ed25519_pallas import _cat, _const_col, _limbs, _validated_blk, _zeros
from .ecdsa_batch import _CURVES, _double

BLK = _validated_blk("CORDA_TPU_ECDSA_BLK", 256)

_MASK = np.uint32(0xFFFF)


class _RowField:
    """Montgomery field on (16, W) rows (port of field_secp.MontField to
    the sublane-limb layout; identical bound arguments)."""

    def __init__(self, host_field: MontField):
        self.h = host_field
        self.p_limbs = [int(v) for v in host_field.p_limbs]
        self.n0p = np.uint32(host_field.n0p)

    # -- helpers -------------------------------------------------------------

    def const_int(self, x: int, width: int):
        return _const_col(_limbs(x), width)

    def mont_const(self, x: int, width: int):
        return self.const_int((x * self.h.r_int) % self.h.p_int, width)

    def _carry16(self, rows):
        """Propagate carries over 16 (1, W) rows; returns rows + final carry."""
        out = []
        carry = None
        for k in range(16):
            v = rows[k] if carry is None else rows[k] + carry
            out.append(v & _MASK)
            carry = v >> 16
        return out, carry

    def _cond_sub_p(self, a, force=None):
        rows = []
        carry = None
        for k in range(16):
            v = a[k : k + 1].astype(jnp.int32) - np.int32(self.p_limbs[k])
            if carry is not None:
                v = v + carry
            rows.append((v & 0xFFFF).astype(jnp.uint32))
            carry = v >> 16
        geq = carry == 0
        take = geq if force is None else (geq | force)
        return jnp.where(take, _cat(rows), a)

    def add(self, a, b):
        rows, carry = self._carry16([a[k : k + 1] + b[k : k + 1] for k in range(16)])
        return self._cond_sub_p(_cat(rows), force=carry > 0)

    def sub(self, a, b):
        rows = []
        carry = None
        for k in range(16):
            v = a[k : k + 1].astype(jnp.int32) - b[k : k + 1].astype(jnp.int32)
            if carry is not None:
                v = v + carry
            rows.append((v & 0xFFFF).astype(jnp.uint32))
            carry = v >> 16
        borrowed = carry < 0
        rows2 = []
        carry2 = None
        for k in range(16):
            v = rows[k] + np.uint32(self.p_limbs[k])
            if carry2 is not None:
                v = v + carry2
            rows2.append(v & _MASK)
            carry2 = v >> 16
        return jnp.where(borrowed, _cat(rows2), _cat(rows))

    def mul(self, a, b):
        """CIOS Montgomery product on rows (bounds: field_secp.mul).

        Under the Pallas-trace fast-mul switch the shifted accumulations
        add into the LIVE rows only (static-slice .at[].add) instead of
        full 32-row adds half of whose rows are zeros — the same
        Mosaic-only trim as ed25519's _mul_fast (docs/perf-roofline.md
        item 3); differential-tested in tests/test_field_secp_rows.py."""
        from .ed25519_pallas import _fast_mul_active

        fast = _fast_mul_active()
        w = a.shape[1]
        acc = _zeros(32, w)
        for i in range(16):
            prod = a[i : i + 1] * b          # (16, W)
            lo = prod & _MASK
            hi = prod >> 16
            if fast:
                acc = acc.at[i : i + 16].add(lo)
                acc = acc.at[i + 1 : i + 17].add(hi)
            else:
                acc = acc + _cat([_zeros(i, w), lo, _zeros(16 - i, w)])
                acc = acc + _cat([_zeros(i + 1, w), hi, _zeros(15 - i, w)])
        c = jnp.zeros((1, w), jnp.uint32)
        for i in range(16):
            ti = acc[i : i + 1] + c
            m = (ti * self.n0p) & _MASK       # (1, W)
            lo_rows = []
            hi_rows = []
            for k in range(16):
                mp = m * np.uint32(self.p_limbs[k])
                lo_rows.append(mp & _MASK)
                hi_rows.append(mp >> 16)
            c = hi_rows[0] + ((ti + lo_rows[0]) >> 16)
            add_lo = _cat(lo_rows[1:])        # positions i+1 .. i+15
            add_hi = _cat(hi_rows[1:])        # positions i+2 .. i+16
            if fast:
                acc = acc.at[i + 1 : i + 16].add(add_lo)
                acc = acc.at[i + 2 : i + 17].add(add_hi)
            else:
                acc = acc + _cat([_zeros(i + 1, w), add_lo, _zeros(16 - i, w)])
                acc = acc + _cat([_zeros(i + 2, w), add_hi, _zeros(15 - i, w)])
        r_rows = [acc[16 + k : 17 + k] for k in range(16)]
        r_rows[0] = r_rows[0] + c
        rows, carry = self._carry16(r_rows)
        return self._cond_sub_p(_cat(rows), force=carry > 0)

    def square(self, a):
        return self.mul(a, a)

    def pow_const(self, x, exponent: int):
        """Static-exponent exponentiation, fully trace-time scheduled.

        The previous form slice-indexed a bits column with the loop
        counter — `lax.dynamic_slice` on a VALUE, which the Pallas TPU
        lowering does not implement (caught by the jax.export TPU
        cross-lowering gate, tests/test_ops_ecdsa.py). The exponent is a
        compile-time int, so no dynamic anything is needed: 4-bit fixed
        windows — a 16-entry power table (14 muls), then per window 4
        squares + one statically-indexed multiply, zero windows skipped.
        ~256 squares + ~80 muls for a 256-bit exponent."""
        width = x.shape[1]
        if exponent == 0:
            return self.mont_const(1, width)
        table = [self.mont_const(1, width), x]
        for _ in range(14):
            table.append(self.mul(table[-1], x))
        n_windows = (exponent.bit_length() + 3) // 4
        acc = None
        for k in range(n_windows - 1, -1, -1):
            w = (exponent >> (4 * k)) & 0xF
            if acc is None:
                acc = table[w]  # top window of a positive exponent: w > 0
                continue
            for _ in range(4):
                acc = self.square(acc)
            if w:
                acc = self.mul(acc, table[w])
        return acc

    def inv(self, x):
        return self.pow_const(x, self.h.p_int - 2)

    def is_zero(self, a):
        acc = a[0:1]
        for k in range(1, 16):
            acc = acc | a[k : k + 1]
        return acc == 0

    def eq(self, a, b):
        acc = a[0:1] ^ b[0:1]
        for k in range(1, 16):
            acc = acc | (a[k : k + 1] ^ b[k : k + 1])
        return acc == 0


# --- Jacobian point ops (coords (16, W) Montgomery; Z == 0 <=> infinity).
# _double is reused from ecdsa_batch (pure field ops, layout-agnostic);
# _add_general is re-expressed here because its degenerate-case masks are
# (1, W) rows in this layout, not trailing-limb-dim broadcasts.

def _add_general(F: _RowField, a_mont, X1, Y1, Z1, X2, Y2, Z2):
    """add-2007-bl with degenerate cases by mask (port of
    ecdsa_batch._add_general to rows)."""
    Z1Z1 = F.square(Z1)
    Z2Z2 = F.square(Z2)
    U1 = F.mul(X1, Z2Z2)
    U2 = F.mul(X2, Z1Z1)
    S1 = F.mul(F.mul(Y1, Z2), Z2Z2)
    S2 = F.mul(F.mul(Y2, Z1), Z1Z1)
    H = F.sub(U2, U1)
    rr = F.sub(S2, S1)
    rr2 = F.add(rr, rr)
    HH = F.add(H, H)
    I = F.square(HH)
    J = F.mul(H, I)
    V = F.mul(U1, I)
    X3 = F.sub(F.sub(F.square(rr2), J), F.add(V, V))
    Y3 = F.sub(F.mul(rr2, F.sub(V, X3)), F.mul(F.add(S1, S1), J))
    Z3 = F.mul(F.sub(F.square(F.add(Z1, Z2)), F.add(Z1Z1, Z2Z2)), H)

    dX, dY, dZ = _double(F, a_mont, X1, Y1, Z1)

    p1_inf = F.is_zero(Z1)
    p2_inf = F.is_zero(Z2)
    h_zero = F.is_zero(H)
    r_zero = F.is_zero(rr)
    both = ~p1_inf & ~p2_inf
    same_point = both & h_zero & r_zero
    opposite = both & h_zero & ~r_zero

    def sel(w1, w2, w3):
        return jnp.where(p1_inf, w2, jnp.where(p2_inf, w1, w3))

    zero = jnp.zeros_like(X1)
    X = sel(X1, X2, jnp.where(same_point, dX, X3))
    Y = sel(Y1, Y2, jnp.where(same_point, dY, Y3))
    Z = sel(Z1, Z2, jnp.where(same_point, dZ, jnp.where(opposite, zero, Z3)))
    return X, Y, Z


# --- the verification program ------------------------------------------------

def shamir_digit_row(u1_words, u2_words, t: int):
    """Table index row for ladder step t (consumed MSB-digit-first as
    t = 127 - i): (u1 2-bit digit) + 4*(u2 2-bit digit). u*_words are
    (8, W) uint32 little-endian scalar words. Shared with
    tests/test_field_secp_rows.py so the digit extraction has fast
    default-on coverage."""
    w, r = (2 * t) // 32, (2 * t) % 32
    return (
        (u1_words[w : w + 1] >> r) & 3
    ) + 4 * ((u2_words[w : w + 1] >> r) & 3)


def _verify_core(curve_name, width, qx, qy, u1_words, u2_words, r_cmp, ok_in,
                 write_table, read_table, write_idx, read_idx):
    """u1*G + u2*Q via a joint 2-bit Shamir ladder; returns (1, W) mask.

    Accessors back the 16-entry (48 rows each: X,Y,Z) point table and the
    128 digit rows with VMEM scratch (kernel) or plain arrays (off-TPU
    test) — the exact pattern of ed25519_pallas._verify_core."""
    host_field, a_int, curve = _CURVES[curve_name]
    F = _RowField(host_field)
    a_mont = F.mont_const(a_int % host_field.p_int, width)
    one_m = F.mont_const(1, width)
    zero = _zeros(16, width)

    # Q multiples (runtime) and G multiples (compile-time constants).
    q1 = (qx, qy, one_m)
    q2 = _double(F, a_mont, *q1)
    q3 = _add_general(F, a_mont, *q2, *q1)
    q_mults = [q1, q2, q3]

    def g_const(k: int):
        px, py = curve.mul(k, curve.g)
        return (F.mont_const(px, width), F.mont_const(py, width), one_m)

    g_mults = [g_const(1), g_const(2), g_const(3)]

    entries = [None] * 16
    entries[0] = (zero, one_m, zero)  # infinity (Z=0)
    for i in (1, 2, 3):
        entries[i] = g_mults[i - 1]
    for j in (1, 2, 3):
        entries[4 * j] = q_mults[j - 1]
    # All nine g_i + q_j combos in ONE general add: lanes are the batch
    # dimension and every row op is width-agnostic, so concatenating the
    # operand pairs along lanes computes them together — one traced point
    # op instead of nine (kernel trace time, not runtime, is the cost).
    g_cat = tuple(
        jnp.concatenate([g_mults[i][c] for i in (0, 1, 2) for _ in (0, 1, 2)],
                        axis=1)
        for c in range(3)
    )
    q_cat = tuple(
        jnp.concatenate([q_mults[j][c] for _ in (0, 1, 2) for j in (0, 1, 2)],
                        axis=1)
        for c in range(3)
    )
    a9 = jnp.concatenate([a_mont] * 9, axis=1)
    combo = _add_general(F, a9, *g_cat, *q_cat)
    for k, (i, j) in enumerate(
        (i, j) for i in (1, 2, 3) for j in (1, 2, 3)
    ):
        entries[i + 4 * j] = tuple(
            c[:, (k) * width : (k + 1) * width] for c in combo
        )
    for e, (X, Y, Z) in enumerate(entries):
        write_table(e, jnp.concatenate([X, Y, Z], axis=0))

    for t in range(128):
        write_idx(t, shamir_digit_row(u1_words, u2_words, t))

    def body(i, acc):
        t = 127 - i
        row = read_idx(t)
        X, Y, Z = acc
        X, Y, Z = _double(F, a_mont, X, Y, Z)
        X, Y, Z = _double(F, a_mont, X, Y, Z)
        sel = _zeros(48, width)
        for e in range(16):
            m = (row == e).astype(jnp.uint32)
            sel = sel + m * read_table(e)
        return _add_general(
            F, a_mont, X, Y, Z, sel[0:16], sel[16:32], sel[32:48]
        )

    X, Y, Z = lax.fori_loop(0, 128, body, (zero, one_m, zero))

    finite = ~F.is_zero(Z)
    zinv = F.inv(Z)
    x_mont = F.mul(X, F.square(zinv))
    # Montgomery -> standard domain (one CIOS by literal 1).
    x_std = F.mul(x_mont, F.const_int(1, width))
    # x mod n: p < 2n for both curves -> at most one subtraction.
    n_limbs = _limbs(curve.n)
    rows = []
    carry = None
    for k in range(16):
        v = x_std[k : k + 1].astype(jnp.int32) - np.int32(n_limbs[k])
        if carry is not None:
            v = v + carry
        rows.append((v & 0xFFFF).astype(jnp.uint32))
        carry = v >> 16
    x_mod_n = jnp.where(carry == 0, _cat(rows), x_std)
    match = F.eq(x_mod_n, r_cmp)
    return ((ok_in != 0) & finite & match).astype(jnp.uint32)


# --- the kernel --------------------------------------------------------------

def _make_kernel(curve_name: str):
    def kernel(qx_ref, qy_ref, u1_ref, u2_ref, r_ref, ok_ref, out_ref,
               tab_ref, idx_ref):
        def write_table(e, rows):
            tab_ref[e * 48 : e * 48 + 48, :] = rows

        def read_table(e):
            return tab_ref[e * 48 : e * 48 + 48, :]

        def write_idx(t, row):
            idx_ref[t : t + 1, :] = row

        def read_idx(t):
            return idx_ref[pl.ds(t, 1), :]

        # trace-time fast-mul switch, thread-local (see ed25519_pallas:
        # the live-row CIOS lowers well under Mosaic but blows up XLA
        # CPU compiles, so only the TPU kernel trace enables it)
        from .ed25519_pallas import _FAST_MUL_ENABLED, _fast_mul_trace

        with _fast_mul_trace(_FAST_MUL_ENABLED):
            out_ref[:] = _verify_core(
                curve_name,
                BLK,
                qx_ref[:], qy_ref[:], u1_ref[:], u2_ref[:], r_ref[:],
                ok_ref[:],
                write_table, read_table, write_idx, read_idx,
            )

    return kernel


def verify_kernel_pallas(curve_name: str, qx_t, qy_t, u1_t, u2_t, r_t, ok):
    """Transposed inputs: qx_t/qy_t/r_t (16, B) uint32 (Montgomery for the
    point, standard for r), u1_t/u2_t (8, B), ok (1, B). B must be a
    multiple of BLK. Returns (1, B) uint32 pass/fail."""
    n = qx_t.shape[1]
    if n % BLK != 0:
        # flooring the grid would silently skip tail lanes (real sigs
        # would come back unverified as zeros) — refuse instead
        raise ValueError(
            f"batch lane count {n} is not a multiple of BLK={BLK}"
        )
    grid = n // BLK

    def spec(rows):
        return pl.BlockSpec((rows, BLK), lambda i: (0, i), memory_space=pltpu.VMEM)

    return pl.pallas_call(
        _make_kernel(curve_name),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.uint32),
        grid=(grid,),
        in_specs=[spec(16), spec(16), spec(8), spec(8), spec(16), spec(1)],
        out_specs=spec(1),
        scratch_shapes=[
            pltpu.VMEM((16 * 48, BLK), jnp.uint32),  # Shamir table
            pltpu.VMEM((128, BLK), jnp.uint32),      # digit rows
        ],
    )(qx_t, qy_t, u1_t, u2_t, r_t, ok)

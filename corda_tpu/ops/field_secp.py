"""Batched prime-field arithmetic for the secp256k1/r1 ECDSA kernels.

Unlike GF(2^255-19) (field25519.py) whose 2^256 overflow folds via the tiny
constant 38, the secp primes need a generic reduction — so this module is a
**Montgomery-domain** field over 16 little-endian radix-2^16 uint32 limbs,
parameterized by the prime.  One implementation serves both curves
(reference binds each to BouncyCastle, `Crypto.kt:91-118`; here both share
one batched CIOS multiplier).

Design notes (same TPU-first rules as field25519):
  * CIOS Montgomery multiply, word size 2^16: every inner step is
    t[j] + a_i*b[j] + carry with all three terms bounded so the sum is
    <= 2^32 - 1 — exact uint32, no int64 emulation.
  * Batch dims leading, limb dim last; loops are Python-unrolled (traced
    once inside the caller's lax.fori_loop over scalar bits).
  * Values are kept canonical (< p) in Montgomery form between ops.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMB = 16
MASK16 = jnp.uint32(0xFFFF)


def int_to_limbs(x: int) -> np.ndarray:
    if not 0 <= x < 2**256:
        raise ValueError("out of range")
    return np.array([(x >> (16 * k)) & 0xFFFF for k in range(NLIMB)], np.uint32)


def limbs_to_int(limbs: np.ndarray) -> int:
    limbs = np.asarray(limbs, dtype=np.uint64)
    return sum(int(limbs[..., k]) << (16 * k) for k in range(NLIMB))


class MontField:
    """Montgomery field mod a 256-bit prime, radix-2^16 CIOS."""

    def __init__(self, p: int):
        self.p_int = p
        self.p_limbs = int_to_limbs(p)
        self._p_i32 = self.p_limbs.astype(np.int32)
        # -p^-1 mod 2^16 (the CIOS m-multiplier)
        self.n0p = (-pow(p, -1, 1 << 16)) & 0xFFFF
        self.r_int = (1 << 256) % p
        self.r2_int = (self.r_int * self.r_int) % p
        self.r2_limbs = int_to_limbs(self.r2_int)
        self.one_mont = int_to_limbs(self.r_int)  # 1 in Montgomery form
        self.zero = int_to_limbs(0)

    # -- host-side helpers ---------------------------------------------------

    def to_mont_int(self, x: int) -> np.ndarray:
        """Host conversion: x -> limbs of x*R mod p (for batch prep)."""
        return int_to_limbs((x * self.r_int) % self.p_int)

    def from_mont_limbs(self, limbs: np.ndarray) -> int:
        return (limbs_to_int(limbs) * pow(self.r_int, -1, self.p_int)) % self.p_int

    def const(self, limbs: np.ndarray, batch_shape=()) -> jnp.ndarray:
        return jnp.broadcast_to(
            jnp.asarray(limbs, jnp.uint32), (*batch_shape, NLIMB)
        )

    # -- device ops ----------------------------------------------------------

    def _cond_sub_p(self, a, force=None):
        """a - p where (a >= p or force); batch-uniform."""
        ai = a.astype(jnp.int32)
        outs = []
        carry = jnp.zeros_like(ai[..., 0])
        for k in range(NLIMB):
            v = ai[..., k] - jnp.int32(int(self._p_i32[k])) + carry
            outs.append((v & 0xFFFF).astype(jnp.uint32))
            carry = v >> 16
        t = jnp.stack(outs, axis=-1)
        geq = carry == 0
        take = geq if force is None else (geq | force)
        return jnp.where(take[..., None], t, a)

    def add(self, a, b):
        """(a + b) mod p for canonical inputs (sum < 2p: one cond-subtract,
        with the 2^256 carry bit forcing it)."""
        s = a + b  # limb sums < 2^17
        outs = []
        carry = jnp.zeros_like(s[..., 0])
        for k in range(NLIMB):
            v = s[..., k] + carry
            outs.append(v & MASK16)
            carry = v >> 16
        r = jnp.stack(outs, axis=-1)
        return self._cond_sub_p(r, force=carry > 0)

    def sub(self, a, b):
        """(a - b) mod p for canonical inputs: a - b + (p if borrow)."""
        ai = a.astype(jnp.int32)
        bi = b.astype(jnp.int32)
        outs = []
        carry = jnp.zeros_like(ai[..., 0])
        for k in range(NLIMB):
            v = ai[..., k] - bi[..., k] + carry
            outs.append((v & 0xFFFF).astype(jnp.uint32))
            carry = v >> 16
        t = jnp.stack(outs, axis=-1)
        borrowed = carry < 0
        # add p back where we borrowed
        outs2 = []
        carry2 = jnp.zeros_like(t[..., 0])
        for k in range(NLIMB):
            v = t[..., k] + jnp.uint32(int(self.p_limbs[k])) + carry2
            outs2.append(v & MASK16)
            carry2 = v >> 16
        t2 = jnp.stack(outs2, axis=-1)
        return jnp.where(borrowed[..., None], t2, t)

    def mul(self, a, b):
        """Montgomery product a*b*R^-1 mod p (SOS with delayed carries).

        Shallow structure for fast XLA compiles: a 32-limb schoolbook
        product with lo/hi halfword split (accumulated sums < 2^21, depth
        16), then 16 reduction steps each adding m_i*p as one 16-wide
        vector MAC — only a single scalar carry is chained between steps
        (depth ~4 per step), not a full 16-limb chain.

        Bounds: acc limbs < 2^21 (product) + 2^21 (reduction adds) < 2^22;
        the chained carry c < 2^17 (inductively: ti < 2^22 + 2^17 < 2^23,
        ti + lo0 < 2^24, so c <= (2^16-1) + 2^8 < 2^17).  Final value
        < 2p, so one (possibly forced) subtraction of p canonicalizes.
        """
        batch = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
        acc = jnp.zeros((*batch, 2 * NLIMB), jnp.uint32)
        for i in range(NLIMB):
            prod = a[..., i : i + 1] * b
            acc = acc.at[..., i : i + NLIMB].add(prod & MASK16)
            acc = acc.at[..., i + 1 : i + NLIMB + 1].add(prod >> 16)
        n0p = jnp.uint32(self.n0p)
        p_vec = jnp.asarray(self.p_limbs, jnp.uint32)
        c = jnp.zeros(batch, jnp.uint32)
        for i in range(NLIMB):
            ti = acc[..., i] + c
            m = (ti * n0p) & MASK16
            mp = m[..., None] * p_vec
            lo = mp & MASK16
            hi = mp >> 16
            # position i is consumed: (ti + lo0) ≡ 0 mod 2^16 by choice of m
            c = hi[..., 0] + ((ti + lo[..., 0]) >> 16)
            acc = acc.at[..., i + 1 : i + NLIMB].add(lo[..., 1:])
            acc = acc.at[..., i + 2 : i + NLIMB + 1].add(hi[..., 1:])
        r = acc[..., NLIMB:]
        r = r.at[..., 0].add(c)
        outs = []
        carry = jnp.zeros_like(r[..., 0])
        for k in range(NLIMB):
            v = r[..., k] + carry
            outs.append(v & MASK16)
            carry = v >> 16
        r = jnp.stack(outs, axis=-1)
        return self._cond_sub_p(r, force=carry > 0)

    def square(self, a):
        return self.mul(a, a)

    def pow_const(self, x, exponent: int):
        """x^exponent (Montgomery domain) for a compile-time exponent."""
        nbits = exponent.bit_length()
        bits = jnp.asarray(
            [(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)],
            jnp.uint32,
        )
        acc0 = self.const(self.one_mont, x.shape[:-1])

        def body(i, acc):
            acc = self.square(acc)
            return jnp.where(bits[i] == 1, self.mul(acc, x), acc)

        return lax.fori_loop(0, nbits, body, acc0)

    def inv(self, x):
        """x^-1 via Fermat (x^(p-2)); 0 -> 0."""
        return self.pow_const(x, self.p_int - 2)

    def is_zero(self, a):
        return jnp.all(a == 0, axis=-1)

    def eq(self, a, b):
        return jnp.all(a == b, axis=-1)


# The two curve fields (SEC2 primes).
P_K1 = 2**256 - 2**32 - 977
P_R1 = 2**256 - 2**224 + 2**192 + 2**96 - 1

FIELD_K1 = MontField(P_K1)
FIELD_R1 = MontField(P_R1)

"""Batched GF(2^255-19) field arithmetic for the TPU ed25519 kernel.

TPU-first design notes (rather than a port of the reference's JVM crypto,
`core/src/main/kotlin/net/corda/core/crypto/Crypto.kt:119-132` which binds
ed25519 to the i2p JCA provider):

  * Representation: 16 little-endian radix-2^16 limbs held in uint32, batch
    dims leading, limb dim last -> every op is a (B,)-wide vector op on the
    TPU VPU; vmap/shard_map over the batch gives lane parallelism.
  * Why radix 2^16: a 16x16-bit limb product fits *exactly* in uint32, and its
    hi halfword shifts cleanly by exactly one limb position, so schoolbook
    multiplication needs one uint32 multiply per limb pair and no int64
    emulation (XLA lowers int64 on TPU to slow s32 pairs).
  * Why 16 limbs: 16*16 = 256 bits aligns the reduction boundary at 2^256,
    where 2^256 = 38 mod p -- the fold multiplier is tiny (fits any limb
    bound comfortably).
  * All control flow is batch-uniform: invalid inputs flow through as data
    and are reported in a validity bitmask, never via branches.

Overflow analysis (the invariants each helper maintains) is documented
inline; "strict" means every limb < 2^16.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

P_INT = 2**255 - 19
L_INT = 2**252 + 27742317777372353535851937790883648493
D_INT = (-121665 * pow(121666, P_INT - 2, P_INT)) % P_INT
SQRT_M1_INT = pow(2, (P_INT - 1) // 4, P_INT)

NLIMB = 16
MASK16 = jnp.uint32(0xFFFF)


def int_to_limbs(x: int) -> np.ndarray:
    """Python int -> (16,) uint32 strict limbs (host-side, for constants)."""
    if not 0 <= x < 2**256:
        raise ValueError("out of range")
    return np.array([(x >> (16 * k)) & 0xFFFF for k in range(NLIMB)], np.uint32)


def limbs_to_int(limbs: np.ndarray) -> int:
    limbs = np.asarray(limbs, dtype=np.uint64)
    return sum(int(limbs[..., k]) << (16 * k) for k in range(NLIMB))


def bytes_to_limbs(le_bytes: np.ndarray) -> np.ndarray:
    """(..., 32) uint8 little-endian byte strings -> (..., 16) uint32 limbs."""
    v = np.asarray(le_bytes, dtype=np.uint32)
    return v[..., 0::2] | (v[..., 1::2] << 8)


P_LIMBS = int_to_limbs(P_INT)
_P_I32 = P_LIMBS.astype(np.int32)
_TWOP_I32 = int_to_limbs(2 * P_INT).astype(np.int32)
D_LIMBS = int_to_limbs(D_INT)
D2_LIMBS = int_to_limbs(2 * D_INT % P_INT)
SQRT_M1_LIMBS = int_to_limbs(SQRT_M1_INT)
ONE_LIMBS = int_to_limbs(1)
ZERO_LIMBS = int_to_limbs(0)


def const(limbs: np.ndarray, batch_shape=()) -> jnp.ndarray:
    """Broadcast a (16,) constant to (batch..., 16)."""
    return jnp.broadcast_to(jnp.asarray(limbs, jnp.uint32), (*batch_shape, NLIMB))


# --- carries / reduction -----------------------------------------------------

def _carry_u(c):
    """Full sequential carry chain. Input limbs < 2^27 (so limb + carry < 2^28
    fits uint32); returns (strict limbs, carry_out < 2^12)."""
    outs = []
    carry = jnp.zeros_like(c[..., 0])
    for k in range(NLIMB):
        v = c[..., k] + carry
        outs.append(v & MASK16)
        carry = v >> 16
    return jnp.stack(outs, axis=-1), carry


def _fold_tail(r, cout):
    """Fold a carry-out at 2^256 back via *38, renormalize to strict limbs.

    Preconditions: r strict, cout < 2^12, and value(r) + 2^256*cout came from a
    quantity < 2^268 -- which makes the second chain's carry-out c2 in {0,1}
    and, when c2 == 1, leaves limb1 <= 3 so the final mini-carry cannot
    overflow limb1 past 2^16.
    """
    r = r.at[..., 0].add(cout * jnp.uint32(38))
    r, c2 = _carry_u(r)
    r = r.at[..., 0].add(c2 * jnp.uint32(38))
    v0 = r[..., 0]
    r = r.at[..., 0].set(v0 & MASK16)
    r = r.at[..., 1].add(v0 >> 16)
    return r


def add(a, b):
    """a + b mod-ish (strict limbs, value < 2^256, congruent mod p)."""
    return _fold_tail(*_carry_u(a + b))  # limb sums < 2^17


def sub(a, b):
    """a - b mod p via a + 2p - b with a signed borrow chain.

    Strict inputs only bound a, b < 2^256, so a + 2p - b lies in (-38, 2^257):
    the final carry is -1, 0 or 1. The -1 (negative) case means the masked
    limbs hold a + 2p - b + 2^256, a value in (2^256-38, 2^256) which is
    congruent to (a - b) + 38 mod p — and whose limb0 >= 0xFFDB, so
    subtracting the 38 back off cannot borrow.
    """
    ai = a.astype(jnp.int32)
    bi = b.astype(jnp.int32)
    outs = []
    carry = jnp.zeros_like(ai[..., 0])
    for k in range(NLIMB):
        v = ai[..., k] + jnp.int32(int(_TWOP_I32[k])) - bi[..., k] + carry
        outs.append((v & 0xFFFF).astype(jnp.uint32))
        carry = v >> 16  # arithmetic shift keeps borrow semantics
    r = jnp.stack(outs, axis=-1)
    negative = carry < 0
    pos = _fold_tail(r, jnp.maximum(carry, 0).astype(jnp.uint32))
    neg = r.at[..., 0].add(jnp.uint32(0) - jnp.uint32(38))  # limb0 >= 0xFFDB
    return jnp.where(negative[..., None], neg, pos)


def neg(a):
    return sub(const(ZERO_LIMBS, a.shape[:-1]), a)


def mul(a, b):
    """Schoolbook product with lo/hi halfword split.

    Each pairwise product fits uint32 exactly; its hi halfword lands exactly
    one limb up (radix 2^16). Coefficient sums <= 32 terms * 2^16 < 2^21; the
    2^256 fold multiplies the high half by 38 -> < 2^27, within _carry_u's
    input bound.
    """
    acc = jnp.zeros((*jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), 2 * NLIMB), jnp.uint32)
    for i in range(NLIMB):
        p = a[..., i : i + 1] * b
        acc = acc.at[..., i : i + NLIMB].add(p & MASK16)
        acc = acc.at[..., i + 1 : i + NLIMB + 1].add(p >> 16)
    folded = acc[..., :NLIMB] + jnp.uint32(38) * acc[..., NLIMB:]
    return _fold_tail(*_carry_u(folded))


def square(a):
    return mul(a, a)


def pow_const(x, exponent: int):
    """x ** exponent for a compile-time-constant exponent.

    Left-to-right square-and-multiply under lax.fori_loop with the exponent's
    bits as a constant array: small traced graph (2 field muls per step), no
    data-dependent control flow.
    """
    nbits = exponent.bit_length()
    bits = jnp.asarray(
        [(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)], jnp.uint32
    )
    one = const(ONE_LIMBS, x.shape[:-1])

    def body(i, acc):
        acc = square(acc)
        with_mul = mul(acc, x)
        return jnp.where(bits[i] == 1, with_mul, acc)

    return lax.fori_loop(0, nbits, body, one)


# --- canonicalization / comparisons -----------------------------------------

def _cond_sub_p(a):
    """(a - p if a >= p else a, a >= p mask)."""
    ai = a.astype(jnp.int32)
    outs = []
    carry = jnp.zeros_like(ai[..., 0])
    for k in range(NLIMB):
        v = ai[..., k] - jnp.int32(int(_P_I32[k])) + carry
        outs.append((v & 0xFFFF).astype(jnp.uint32))
        carry = v >> 16
    t = jnp.stack(outs, axis=-1)
    geq = carry == 0
    return jnp.where(geq[..., None], t, a), geq


def canonical(a):
    """Fully reduced representative in [0, p). Strict input < 2^256 needs at
    most two conditional subtractions (2^256 - 2p = 38)."""
    r, _ = _cond_sub_p(a)
    r, _ = _cond_sub_p(r)
    return r


def lt_p(a):
    """a < p elementwise over the batch (for canonical-encoding checks)."""
    _, geq = _cond_sub_p(a)
    return ~geq


def is_zero(a):
    return jnp.all(canonical(a) == 0, axis=-1)


def eq(a, b):
    return is_zero(sub(a, b))

"""Batched ed25519 verification — the north-star TPU kernel.

Replaces the reference's per-signature JCA loop
(`core/src/main/kotlin/net/corda/core/transactions/TransactionWithSignatures.kt:58-62`
-> `Crypto.kt:535-541` -> i2p-EdDSA) with a single batch-uniform device
program: every signature in the batch flows through identical control flow;
invalid encodings/points are carried as data and surface in the returned
pass/fail bitmask (reference semantics: `Crypto.isValid`, boolean, no throw).

Work split (TPU-first):
  * host (numpy + hashlib): byte parsing, SHA-512(R||A||M) -> h mod L (C-speed
    hashing; variable-length messages don't belong on the accelerator),
    s < L canonicality.
  * device (JAX, vmappable, jit-cached per padded batch shape): point
    decompression (fixed square-and-multiply chains), Straus interleaved
    double-scalar multiplication computing [s]B + [h](-A), equality with R.

The cofactorless check [s]B == R + [h]A matches the i2p/ref10 semantics the
reference inherits.

This module holds the portable XLA kernel (used on CPU meshes, the
multichip dryrun, and as the non-TPU fallback) plus the vectorised host
prepare; on a real TPU backend `verify_batch` dispatches to the Pallas
kernel in ops/ed25519_pallas.py, which keeps the whole ladder in VMEM and
is ~10x faster (see its docstring for the measured roofline story).
"""
from __future__ import annotations

import hashlib
import os
import time as _time
from functools import partial
from typing import Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.crypto import ed25519_math
from . import field25519 as F

# Base point in extended coordinates, as limb constants.
_BX, _BY = ed25519_math.to_affine(ed25519_math.BASE)
_B_LIMBS = tuple(
    F.int_to_limbs(v) for v in (_BX, _BY, 1, _BX * _BY % F.P_INT)
)

Point = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]  # X, Y, Z, T


def _identity(batch_shape) -> Point:
    return (
        F.const(F.ZERO_LIMBS, batch_shape),
        F.const(F.ONE_LIMBS, batch_shape),
        F.const(F.ONE_LIMBS, batch_shape),
        F.const(F.ZERO_LIMBS, batch_shape),
    )


def point_add(p: Point, q: Point) -> Point:
    """Unified extended-coordinates addition (complete on the curve; handles
    identity and doubling inputs). Mirrors the host oracle
    corda_tpu.core.crypto.ed25519_math.point_add."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = F.mul(F.sub(Y1, X1), F.sub(Y2, X2))
    b = F.mul(F.add(Y1, X1), F.add(Y2, X2))
    c = F.mul(T1, F.mul(T2, F.const(F.D2_LIMBS, T1.shape[:-1])))
    zz = F.mul(Z1, Z2)
    d = F.add(zz, zz)
    e, f, g, h = F.sub(b, a), F.sub(d, c), F.add(d, c), F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_double(p: Point) -> Point:
    X1, Y1, Z1, _ = p
    a = F.square(X1)
    b = F.square(Y1)
    zz = F.square(Z1)
    c = F.add(zz, zz)
    h = F.add(a, b)
    e = F.sub(h, F.square(F.add(X1, Y1)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_neg(p: Point) -> Point:
    X, Y, Z, T = p
    return (F.neg(X), Y, Z, F.neg(T))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray) -> Tuple[Point, jnp.ndarray]:
    """Batched RFC 8032 point decompression.

    Returns (point, ok_mask). Invalid encodings (y >= p, non-residue x^2,
    x == 0 with sign set) are flagged, with garbage-but-well-typed point data
    flowing on (masked out by the caller).
    """
    batch = y_limbs.shape[:-1]
    one = F.const(F.ONE_LIMBS, batch)
    ok_y = F.lt_p(y_limbs)
    y2 = F.square(y_limbs)
    u = F.sub(y2, one)
    v = F.add(F.mul(F.const(F.D_LIMBS, batch), y2), one)
    v3 = F.mul(F.square(v), v)
    v7 = F.mul(F.square(v3), v)
    w = F.pow_const(F.mul(u, v7), (F.P_INT - 5) // 8)
    x = F.mul(F.mul(u, v3), w)
    vx2 = F.mul(v, F.square(x))
    root1 = F.eq(vx2, u)
    root2 = F.eq(vx2, F.neg(u))
    x = jnp.where(
        root1[..., None], x, F.mul(x, F.const(F.SQRT_M1_LIMBS, batch))
    )
    ok = ok_y & (root1 | root2)
    xc = F.canonical(x)
    x_is_zero = jnp.all(xc == 0, axis=-1)
    ok &= ~(x_is_zero & (sign == 1))
    flip = (xc[..., 0] & 1) != sign
    x = jnp.where(flip[..., None], F.neg(x), x)
    return (x, y_limbs, one, F.mul(x, y_limbs)), ok


def _select4(table_coords: Sequence[jnp.ndarray], idx: jnp.ndarray) -> Point:
    """table_coords: 4 arrays of shape (..., 4, 16); idx: (...,) in 0..3."""
    onehot = (idx[..., None] == jnp.arange(4, dtype=idx.dtype)).astype(jnp.uint32)
    return tuple(
        jnp.sum(c * onehot[..., None], axis=-2) for c in table_coords
    )


def _bit_at(words: jnp.ndarray, i: jnp.ndarray) -> jnp.ndarray:
    """words: (..., 8) uint32 little-endian scalar words; i: traced bit index."""
    w = lax.dynamic_slice_in_dim(words, i >> 5, 1, axis=-1)[..., 0]
    return (w >> (i & 31)) & 1


def _verify_kernel_impl(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_words: jnp.ndarray,
    h_words: jnp.ndarray,
    s_ok: jnp.ndarray,
) -> jnp.ndarray:
    """Pass/fail bitmask for a batch: [s]B + [h](-A) == R, cofactorless.

    Shapes: y_* (B, 16) uint32 limbs; sign_* (B,) uint32; *_words (B, 8)
    uint32; s_ok (B,) bool (host-checked s < L and length checks).
    """
    batch = y_a.shape[:-1]
    # Decompress A and R in one double-width batch (one traced pow chain).
    pts, oks = decompress(
        jnp.concatenate([y_a, y_r], axis=0),
        jnp.concatenate([sign_a, sign_r], axis=0),
    )
    n = y_a.shape[0]
    a_pt = tuple(c[:n] for c in pts)
    r_pt = tuple(c[n:] for c in pts)
    ok_a, ok_r = oks[:n], oks[n:]

    neg_a = point_neg(a_pt)
    b_pt = tuple(F.const(l, batch) for l in _B_LIMBS)
    b_plus_na = point_add(b_pt, neg_a)
    ident = _identity(batch)
    # Straus table indexed by (h_bit, s_bit): 0 -> O, 1 -> B, 2 -> -A, 3 -> B-A
    table = [
        jnp.stack([ident[c], b_pt[c], neg_a[c], b_plus_na[c]], axis=-2)
        for c in range(4)
    ]

    def body(i, q):
        j = 255 - i
        q = point_double(q)
        idx = _bit_at(s_words, j) + 2 * _bit_at(h_words, j)
        return point_add(q, _select4(table, idx))

    q = lax.fori_loop(0, 256, body, ident)

    eq_x = F.eq(q[0], F.mul(r_pt[0], q[2]))
    eq_y = F.eq(q[1], F.mul(r_pt[1], q[2]))
    return s_ok & ok_a & ok_r & eq_x & eq_y


verify_kernel = jax.jit(_verify_kernel_impl)

#: Donated variant for the staged verification pipeline's dispatch stage
#: (verifier/pipeline.py): s_ok (bool[B]) matches the returned mask's
#: shape/dtype so XLA can alias its buffer into the output. Safe because
#: prepare_batch builds fresh arrays per batch and the staged dispatch
#: never rereads its kernel inputs after launch. Separate jit cache from
#: verify_kernel — the pipelined and synchronous paths each compile
#: their own executable once per shape.
verify_kernel_donated = jax.jit(_verify_kernel_impl, donate_argnums=(6,))


# --- host-side batch preparation --------------------------------------------

from ..utils.profiling import ED25519_SHAPE_BUCKETS as _BUCKETS


def _bucket(n: int) -> int:
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + _BUCKETS[-1] - 1) // _BUCKETS[-1]) * _BUCKETS[-1]


_L_WORDS = np.frombuffer(F.L_INT.to_bytes(32, "little"), np.uint32)

#: padded batch shapes already seen (each new one = one XLA compile)
_SEEN_SHAPES: set = set()


def prepare_batch(
    public_keys: Sequence[bytes],
    signatures: Sequence[bytes],
    messages: Sequence[bytes],
    pad_to: int | None = None,
):
    """Parse + hash a batch on the host, pad to a bucketed shape.

    Returns (kernel kwargs dict, n_real). Malformed lengths are mapped to an
    all-zero row with s_ok=False (batch-uniform: bad input is data). All
    parsing is vectorised numpy; the SHA-512 prehash of well-formed rows
    goes through the native batch hasher (corda_tpu.native) in one call.
    """
    n = len(public_keys)
    size = pad_to if pad_to is not None else _bucket(max(n, 1))
    # each distinct padded shape costs one XLA compile downstream; the
    # ops endpoint exports the count as Jax.CompileCount, with a
    # per-bucket label so a recompile storm names its shape (the event
    # itself is recorded after the kwargs exist, so it can carry the
    # lowering duration + cost analysis of the new shape)
    new_shape = size not in _SEEN_SHAPES
    if new_shape:
        _SEEN_SHAPES.add(size)
    y_a = np.zeros((size, F.NLIMB), np.uint32)
    y_r = np.zeros((size, F.NLIMB), np.uint32)
    sign_a = np.zeros(size, np.uint32)
    sign_r = np.zeros(size, np.uint32)
    s_words = np.zeros((size, 8), np.uint32)
    h_words = np.zeros((size, 8), np.uint32)
    s_ok = np.zeros(size, bool)

    good = [
        i
        for i in range(n)
        if len(public_keys[i]) == 32 and len(signatures[i]) == 64
    ]
    if good:
        gi = np.asarray(good)
        pub_mat = np.frombuffer(
            b"".join(public_keys[i] for i in good), np.uint8
        ).reshape(-1, 32)
        sig_mat = np.frombuffer(
            b"".join(signatures[i] for i in good), np.uint8
        ).reshape(-1, 64)
        a_limbs = F.bytes_to_limbs(pub_mat)
        r_limbs = F.bytes_to_limbs(sig_mat[:, :32])
        sign_a[gi] = a_limbs[:, 15] >> 15
        sign_r[gi] = r_limbs[:, 15] >> 15
        a_limbs[:, 15] &= 0x7FFF
        r_limbs[:, 15] &= 0x7FFF
        y_a[gi] = a_limbs
        y_r[gi] = r_limbs
        sw = np.ascontiguousarray(sig_mat[:, 32:]).view(np.uint32)
        s_words[gi] = sw
        # s < L: vectorised lexicographic compare from the top word down.
        lt = np.zeros(len(good), bool)
        decided = np.zeros(len(good), bool)
        for k in range(7, -1, -1):
            w = sw[:, k]
            lt |= ~decided & (w < _L_WORDS[k])
            decided |= w != _L_WORDS[k]
        s_ok[gi] = lt

        from .. import native

        msg_lens = {len(messages[i]) for i in good}
        if len(msg_lens) == 1:
            # uniform messages (the loadtest/firehose case): assemble the
            # R||A||M preimages as ONE contiguous matrix — no per-row
            # bytes objects, no marshal copy
            mlen = msg_lens.pop()
            buf = np.empty((len(good), 64 + mlen), np.uint8)
            buf[:, :32] = sig_mat[:, :32]
            buf[:, 32:64] = pub_mat
            if mlen:
                buf[:, 64:] = np.frombuffer(
                    b"".join(messages[i] for i in good), np.uint8
                ).reshape(-1, mlen)
            h_words[gi] = native.sha512_mod_l_rows(buf)
        else:
            preimages = [
                signatures[i][:32] + public_keys[i] + messages[i]
                for i in good
            ]
            h_words[gi] = native.sha512_mod_l_many(preimages)

    kwargs = dict(
        y_a=jnp.asarray(y_a),
        sign_a=jnp.asarray(sign_a),
        y_r=jnp.asarray(y_r),
        sign_r=jnp.asarray(sign_r),
        s_words=jnp.asarray(s_words),
        h_words=jnp.asarray(h_words),
        s_ok=jnp.asarray(s_ok),
    )
    if new_shape:
        from ..utils import profiling

        bucket = str(size) if size in _BUCKETS else "other"
        lower_s = None
        if profiling.cost_analysis_enabled():
            # ONE .lower() per new padded shape, HERE where jax is
            # already live: the flops/bytes land in the jax-free cost
            # cache so a /kernels scrape never triggers tracing. The
            # lowering wall doubles as the compile event's duration
            # (the closest honest stand-in for the compile this shape
            # is about to pay).
            t0 = _time.perf_counter()
            try:
                analysis = verify_kernel.lower(**kwargs).cost_analysis()
                lower_s = _time.perf_counter() - t0
                profiling.record_cost_analysis(
                    "ed25519.verify_batch", bucket, size, analysis,
                    backend=jax.default_backend(),
                )
            # lint: allow(swallow) — cost capture must never fail a verify
            except Exception:
                pass
        profiling.record_compile(
            "ed25519.batch_shape", bucket=bucket, seconds=lower_s
        )
    return kwargs, n


def verify_batch(
    public_keys: Sequence[bytes],
    signatures: Sequence[bytes],
    messages: Sequence[bytes],
) -> np.ndarray:
    """End-to-end batched verify: (B,) bool numpy mask.

    Per-element semantics match the host oracle `ed25519_math.verify` /
    `Crypto.isValid` (reference `Crypto.kt:535-541`).
    """
    if len(public_keys) == 0:
        return np.zeros(0, bool)
    if jax.default_backend() == "tpu":
        return _verify_batch_pallas(public_keys, signatures, messages)
    kwargs, n = prepare_batch(public_keys, signatures, messages)
    mask = verify_kernel(**kwargs)
    return np.asarray(mask)[:n]


_PIPE_CHUNK = int(os.environ.get("CORDA_TPU_PIPE_CHUNK", "65536"))


def _dispatch_pallas(kwargs):
    from . import ed25519_pallas as _pl

    return _pl.verify_kernel_pallas(
        kwargs["y_a"].T,
        kwargs["sign_a"][None, :],
        kwargs["y_r"].T,
        kwargs["sign_r"][None, :],
        kwargs["s_words"].T,
        kwargs["h_words"].T,
        kwargs["s_ok"][None, :].astype(jnp.uint32),
    )


#: set after the Pallas kernel failed with fast-mul already off — every
#: later batch goes straight to the portable XLA kernel (same latch as
#: ecdsa_batch._pallas_failed_once)
_pallas_failed_once = False

#: (fast_mul, radix13) configs whose kernel passed the known-answer
#: self-check on this backend
_selfchecked: set = set()


def _self_check_vectors():
    """16 deterministic known-answer rows: 8 valid signatures, 8 broken
    in distinct ways (flipped sig bit, wrong message, junk key, bad s)."""
    pubs, sigs, msgs = [], [], []
    for i in range(16):
        seed = hashlib.sha512(b"selfcheck-%d" % i).digest()[:32]
        msg = b"self-check message %d" % i
        pub = ed25519_math.public_from_seed(seed)
        sig = ed25519_math.sign(seed, msg)
        if i >= 8:
            kind = i % 4
            if kind == 0:
                sig = bytes([sig[0] ^ 1]) + sig[1:]
            elif kind == 1:
                msg = msg + b"!"
            elif kind == 2:
                pub = hashlib.sha256(pub).digest()  # near-certain non-point
            else:
                sig = sig[:32] + b"\xff" * 32  # s >= L
        pubs.append(pub)
        sigs.append(sig)
        msgs.append(msg)
    # the host oracle is the ground truth (the junk-key row especially)
    expect = [
        ed25519_math.verify(p, m, s) for p, m, s in zip(pubs, msgs, sigs)
    ]
    assert expect[:8] == [True] * 8 and not any(expect[8:])
    return pubs, sigs, msgs, expect


def _self_check(_pl) -> None:
    """Known-answer test of the CURRENT kernel config, once per process.

    A Mosaic lowering bug can manifest as silently wrong lanes rather
    than a compile error; for an unattended bench/verifier run that must
    degrade the retry ladder, not poison verdicts (consensus!) or crash
    the run. Costs one extra small-shape compile per config."""
    config = (_pl._FAST_MUL_ENABLED, _pl._RADIX13_ENABLED)
    if config in _selfchecked:
        return
    pubs, sigs, msgs, expect = _self_check_vectors()
    kwargs, real = prepare_batch(pubs, sigs, msgs, pad_to=_pl.BLK)
    mask = np.asarray(_dispatch_pallas(kwargs))[0, :real]
    got = [bool(b) for b in mask]
    if got != expect:
        raise RuntimeError(
            f"Pallas kernel self-check FAILED for config fast_mul="
            f"{config[0]} radix13={config[1]}: {got} != {expect}"
        )
    _selfchecked.add(config)


def _verify_batch_pallas(public_keys, signatures, messages) -> np.ndarray:
    """TPU path: chunked software pipeline — the host parses/hashes chunk
    i+1 while the device runs chunk i (JAX dispatch is async; results are
    only synchronised at the end), so end-to-end throughput approaches
    max(host-prep rate, kernel rate) instead of their sum.

    Degrades instead of sinking the caller (the bench gate and the
    verifier hot path both live here): if the kernel fails to compile or
    run with the fast-mul variants on — the one lowering question only
    real hardware answers (docs/perf-roofline.md) — it retries with the
    dense multiply (measured working on-chip round 2); if THAT fails,
    it latches over to the portable XLA kernel."""
    import logging

    from . import ed25519_pallas as _pl

    global _pallas_failed_once
    n = len(public_keys)
    while not _pallas_failed_once:
        try:
            _self_check(_pl)
            pending = []
            for lo in range(0, n, _PIPE_CHUNK):
                hi = min(lo + _PIPE_CHUNK, n)
                pad = max(_bucket(hi - lo), _pl.BLK)
                kwargs, real = prepare_batch(
                    public_keys[lo:hi], signatures[lo:hi], messages[lo:hi],
                    pad_to=pad,
                )
                pending.append((_dispatch_pallas(kwargs), real))
            return np.concatenate(
                [np.asarray(m)[0, :real].astype(bool) for m, real in pending]
            )
        except Exception:
            log = logging.getLogger(__name__)
            # Drop fast-mul BEFORE the radix: the live-row accumulation
            # is the documented open Mosaic question, and radix-13 dense
            # is projected above-target while radix-16 dense is not
            # (docs/perf-roofline.md) — so the ladder must be able to
            # settle on r13+dense.
            if _pl._FAST_MUL_ENABLED:
                log.exception(
                    "Pallas ed25519 kernel failed with fast-mul on; "
                    "retrying with the dense multiply"
                )
                _pl._FAST_MUL_ENABLED = False
                continue
            if _pl._RADIX13_ENABLED:
                log.exception(
                    "Pallas ed25519 kernel failed with radix-13 limbs "
                    "(dense); retrying with the radix-16 field"
                )
                _pl._RADIX13_ENABLED = False
                # the dense failure may have been r13-specific: give the
                # round-2-validated r16+fast config its chance before
                # settling on r16+dense
                _pl._FAST_MUL_ENABLED = True
                continue
            _pallas_failed_once = True
            log.exception(
                "Pallas ed25519 kernel failed; falling back to the "
                "portable XLA kernel for the rest of this process"
            )
    kwargs, real = prepare_batch(public_keys, signatures, messages)
    mask = verify_kernel(**kwargs)
    return np.asarray(mask)[:real]

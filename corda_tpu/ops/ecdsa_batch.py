"""Batched ECDSA (secp256k1 / secp256r1) verification on TPU.

Replaces the reference's per-signature BouncyCastle path
(`Crypto.kt:91-118`, `doVerify` -> JCA `Signature.verify`) with a batch
kernel mirroring the ed25519 design (ops/ed25519_batch.py):

  * host prepare: X962 point decode + DER parse + SHA-256 digest + the
    cheap mod-n scalar work (w = s^-1, u1 = e*w, u2 = r*w) — malformed
    inputs become all-zero rows with ok=False (bad input is data);
  * device kernel: the FLOP-heavy double-scalar multiplication
    R = u1*G + u2*Q in Jacobian coordinates over the Montgomery field
    (field_secp), one interleaved Shamir ladder inside lax.fori_loop —
    batch-uniform control flow, all degenerate point cases handled by
    masks (never branches);
  * verdict: x(R) mod n == r as a validity bitmask.

Curve-generic: the same ladder serves both curves; only the field, a, b,
and generator constants differ.
"""
from __future__ import annotations

import functools
import hashlib
import time as _time
from typing import List, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.crypto import secp_math
from . import field_secp
from .field_secp import FIELD_K1, FIELD_R1, MontField, NLIMB, int_to_limbs

# (field, curve a, host curve object) per scheme
_CURVES = {
    "secp256k1": (FIELD_K1, 0, secp_math.SECP256K1),
    "secp256r1": (FIELD_R1, secp_math.SECP256R1.a, secp_math.SECP256R1),
}


# ---------------------------------------------------------------------------
# Jacobian point ops (coords in Montgomery form). A point is (X, Y, Z);
# Z == 0 encodes infinity.
# ---------------------------------------------------------------------------

def _double(F: MontField, a_mont, X, Y, Z):
    """dbl-2007-bl (general a). Z=0 flows through (Z'=0)."""
    XX = F.square(X)
    YY = F.square(Y)
    YYYY = F.square(YY)
    ZZ = F.square(Z)
    S = F.sub(F.square(F.add(X, YY)), F.add(XX, YYYY))
    S = F.add(S, S)
    M = F.add(F.add(XX, XX), XX)
    M = F.add(M, F.mul(a_mont, F.square(ZZ)))
    X3 = F.sub(F.square(M), F.add(S, S))
    Y8 = F.add(YYYY, YYYY)
    Y8 = F.add(Y8, Y8)
    Y8 = F.add(Y8, Y8)
    Y3 = F.sub(F.mul(M, F.sub(S, X3)), Y8)
    Z3 = F.sub(F.square(F.add(Y, Z)), F.add(YY, ZZ))
    return X3, Y3, Z3


def _add_general(F: MontField, a_mont, X1, Y1, Z1, X2, Y2, Z2):
    """add-2007-bl with full degenerate-case handling via masks:
    P+inf, inf+P, P+P (doubling), P+(-P) (infinity)."""
    Z1Z1 = F.square(Z1)
    Z2Z2 = F.square(Z2)
    U1 = F.mul(X1, Z2Z2)
    U2 = F.mul(X2, Z1Z1)
    S1 = F.mul(F.mul(Y1, Z2), Z2Z2)
    S2 = F.mul(F.mul(Y2, Z1), Z1Z1)
    H = F.sub(U2, U1)
    rr = F.sub(S2, S1)
    rr = F.add(rr, rr)
    HH = F.add(H, H)
    I = F.square(HH)
    J = F.mul(H, I)
    V = F.mul(U1, I)
    X3 = F.sub(F.sub(F.square(rr), J), F.add(V, V))
    S1J = F.mul(S1, J)
    Y3 = F.sub(F.mul(rr, F.sub(V, X3)), F.add(S1J, S1J))
    Z3 = F.mul(F.sub(F.square(F.add(Z1, Z2)), F.add(Z1Z1, Z2Z2)), H)

    p1_inf = F.is_zero(Z1)
    p2_inf = F.is_zero(Z2)
    h_zero = F.is_zero(H)
    r_zero = F.is_zero(rr)
    same_point = h_zero & r_zero & ~p1_inf & ~p2_inf
    opposite = h_zero & ~r_zero & ~p1_inf & ~p2_inf

    dX, dY, dZ = _double(F, a_mont, X1, Y1, Z1)

    def sel(mask, a, b):
        return jnp.where(mask[..., None], a, b)

    zero = jnp.zeros_like(Z3)
    X = sel(p1_inf, X2, sel(p2_inf, X1, sel(same_point, dX, X3)))
    Y = sel(p1_inf, Y2, sel(p2_inf, Y1, sel(same_point, dY, Y3)))
    Z = sel(p1_inf, Z2, sel(p2_inf, Z1, sel(same_point, dZ,
            sel(opposite, zero, Z3))))
    return X, Y, Z


def _bit_at(words: jnp.ndarray, i) -> jnp.ndarray:
    """Bit i (LE) of (..., 8) uint32 scalar words."""
    return (words[..., i // 32] >> jnp.uint32(i % 32)) & jnp.uint32(1)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0,))
def _verify_kernel(curve_name: str, qx, qy, u1_words, u2_words, r_cmp, ok):
    """R = u1*G + u2*Q; valid iff R finite and x(R) mod n == r.

    qx/qy: (B,16) Montgomery affine pubkey coords; u*_words: (B,8) uint32 LE
    scalars; r_cmp: (B,16) standard-domain r limbs; ok: (B,) host validity.
    """
    F, a_int, curve = _CURVES[curve_name]
    batch = qx.shape[:-1]
    a_mont = F.const(F.to_mont_int(a_int % F.p_int), batch)
    gx, gy = curve.g
    GX = F.const(F.to_mont_int(gx), batch)
    GY = F.const(F.to_mont_int(gy), batch)
    one_m = F.const(F.one_mont, batch)
    zero = F.const(F.zero, batch)

    # Table: G, Q, and G+Q (computed once per batch, general add).
    TX, TY, TZ = _add_general(F, a_mont, GX, GY, one_m, qx, qy, one_m)

    def body(k, acc):
        X, Y, Z = acc
        i = 255 - k
        X, Y, Z = _double(F, a_mont, X, Y, Z)
        b1 = _bit_at(u1_words, i)
        b2 = _bit_at(u2_words, i)
        idx = b1 + 2 * b2  # 0=skip, 1=G, 2=Q, 3=G+Q

        def sel(w1, w2, w3):
            m1 = (idx == 1)[..., None]
            m2 = (idx == 2)[..., None]
            return jnp.where(m1, w1, jnp.where(m2, w2, w3))

        AX = sel(GX, qx, TX)
        AY = sel(GY, qy, TY)
        AZ = sel(one_m, one_m, TZ)
        nX, nY, nZ = _add_general(F, a_mont, X, Y, Z, AX, AY, AZ)
        skip = (idx == 0)[..., None]
        return (
            jnp.where(skip, X, nX),
            jnp.where(skip, Y, nY),
            jnp.where(skip, Z, nZ),
        )

    X, Y, Z = lax.fori_loop(0, 256, body, (zero, one_m, zero))

    finite = ~F.is_zero(Z)
    zinv = F.inv(Z)
    x_mont = F.mul(X, F.square(zinv))
    # Montgomery -> standard domain: one more CIOS by literal 1.
    x_std = F.mul(x_mont, F.const(int_to_limbs(1), batch))
    # x mod n: p < 2n for both curves -> at most one subtraction of n.
    n_limbs = int_to_limbs(curve.n)
    xi = x_std.astype(jnp.int32)
    outs = []
    carry = jnp.zeros_like(xi[..., 0])
    for k in range(NLIMB):
        v = xi[..., k] - jnp.int32(int(n_limbs[k])) + carry
        outs.append((v & 0xFFFF).astype(jnp.uint32))
        carry = v >> 16
    reduced = jnp.stack(outs, axis=-1)
    x_mod_n = jnp.where((carry == 0)[..., None], reduced, x_std)
    match = jnp.all(x_mod_n == r_cmp, axis=-1)
    return ok & finite & match


# ---------------------------------------------------------------------------
# Host-side batch prep + public API
# ---------------------------------------------------------------------------

def _scalar_to_words(x: int) -> np.ndarray:
    return np.array([(x >> (32 * k)) & 0xFFFFFFFF for k in range(8)], np.uint32)


#: padded batch shapes already seen (each new one = one XLA compile),
#: keyed per curve — feeds the same Jax.CompileCount telemetry as the
#: ed25519 buckets (utils/profiling.py)
_SEEN_SHAPES: set = set()


def prepare_batch(
    curve_name: str,
    public_keys: Sequence[bytes],  # X962 (compressed or uncompressed)
    signatures: Sequence[bytes],   # DER
    messages: Sequence[bytes],
    pad_to: int | None = None,
):
    """Parse/digest on the host; malformed rows become ok=False zeros."""
    F, _a, curve = _CURVES[curve_name]
    n = len(public_keys)
    size = pad_to if pad_to is not None else max(
        8, 1 << (max(n, 1) - 1).bit_length()
    )
    new_shape = (curve_name, size) not in _SEEN_SHAPES
    if new_shape:
        _SEEN_SHAPES.add((curve_name, size))
    qx = np.zeros((size, NLIMB), np.uint32)
    qy = np.zeros((size, NLIMB), np.uint32)
    u1 = np.zeros((size, 8), np.uint32)
    u2 = np.zeros((size, 8), np.uint32)
    r_cmp = np.zeros((size, NLIMB), np.uint32)
    ok = np.zeros(size, bool)

    from .. import native

    digests = native.sha256_many(list(messages))
    for i in range(n):
        try:
            pt = curve.decode_point(public_keys[i])
            if pt is None:
                continue
            r, s = secp_math.der_decode_sig(signatures[i])
            if not (1 <= r < curve.n and 1 <= s < curve.n):
                continue
            e = secp_math._bits2int(digests[i], curve.n)
            w = pow(s, -1, curve.n)
            qx[i] = F.to_mont_int(pt[0])
            qy[i] = F.to_mont_int(pt[1])
            u1[i] = _scalar_to_words((e * w) % curve.n)
            u2[i] = _scalar_to_words((r * w) % curve.n)
            r_cmp[i] = int_to_limbs(r)
            ok[i] = True
        except Exception:
            continue
    kwargs = {
        "qx": jnp.asarray(qx), "qy": jnp.asarray(qy),
        "u1_words": jnp.asarray(u1), "u2_words": jnp.asarray(u2),
        "r_cmp": jnp.asarray(r_cmp), "ok": jnp.asarray(ok),
    }
    if new_shape:
        from ..utils import profiling

        lower_s = None
        if profiling.cost_analysis_enabled():
            # one .lower() per new (curve, padded shape) while jax is
            # live; flops/bytes go to the jax-free cost cache so a
            # /kernels scrape never traces (ed25519_batch has the twin)
            t0 = _time.perf_counter()
            try:
                analysis = _verify_kernel.lower(
                    curve_name, **kwargs
                ).cost_analysis()
                lower_s = _time.perf_counter() - t0
                profiling.record_cost_analysis(
                    f"ecdsa.{curve_name}.verify_batch", str(size), size,
                    analysis, backend=jax.default_backend(),
                )
            # lint: allow(swallow) — cost capture must never fail a verify
            except Exception:
                pass
        profiling.record_compile(
            f"ecdsa.{curve_name}.batch_shape", bucket=str(size),
            seconds=lower_s,
        )
    return kwargs, n


_pallas_failed_once = False

#: (curve_name, fast_mul) configs whose Pallas kernel passed the
#: known-answer self-check on this backend (same defense as
#: ed25519_batch._self_check: silent Mosaic miscompiles must degrade the
#: retry ladder, never poison verdicts)
_selfchecked: set = set()


def _self_check_vectors(curve_name: str):
    """8 deterministic known-answer rows per curve: 4 valid RFC6979
    signatures, 4 broken in distinct ways."""
    _F, _a, curve = _CURVES[curve_name]
    pubs, sigs, msgs = [], [], []
    for i in range(8):
        priv = (
            int.from_bytes(
                hashlib.sha256(b"ecdsa-selfcheck-%d" % i).digest(), "big"
            ) % (curve.n - 1) + 1
        )
        pub = curve.encode_point(curve.mul(priv, curve.g))
        msg = b"ecdsa self-check %d" % i
        r, s = secp_math.ecdsa_sign(curve, priv, msg)
        sig = secp_math.der_encode_sig(r, s)
        if i >= 4:
            kind = i % 4
            if kind == 0:
                msg = msg + b"!"  # signature over different content
            elif kind == 1:
                # signature from a different key
                r2, s2 = secp_math.ecdsa_sign(curve, priv + 1, msg)
                sig = secp_math.der_encode_sig(r2, s2)
            elif kind == 2:
                sig = secp_math.der_encode_sig(s, r)  # swapped components
            else:
                sig = b"\x30\x00"  # malformed DER
        pubs.append(pub)
        sigs.append(sig)
        msgs.append(msg)
    return pubs, sigs, msgs, [True] * 4 + [False] * 4


def _self_check_pallas(curve_name: str, _pl) -> None:
    from .ed25519_pallas import _FAST_MUL_ENABLED

    config = (curve_name, _FAST_MUL_ENABLED)
    if config in _selfchecked:
        return
    pubs, sigs, msgs, expect = _self_check_vectors(curve_name)
    kwargs, real = prepare_batch(curve_name, pubs, sigs, msgs, pad_to=_pl.BLK)
    mask = _pl.verify_kernel_pallas(
        curve_name,
        kwargs["qx"].T,
        kwargs["qy"].T,
        kwargs["u1_words"].T,
        kwargs["u2_words"].T,
        kwargs["r_cmp"].T,
        kwargs["ok"][None, :].astype(jnp.uint32),
    )
    got = [bool(b) for b in np.asarray(mask)[0, :real]]
    if got != expect:
        raise RuntimeError(
            f"Pallas ECDSA kernel self-check FAILED for {config}: "
            f"{got} != {expect}"
        )
    _selfchecked.add(config)


def verify_batch(
    curve_name: str,
    public_keys: Sequence[bytes],
    signatures: Sequence[bytes],
    messages: Sequence[bytes],
) -> List[bool]:
    global _pallas_failed_once
    n = len(public_keys)
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        from . import ecdsa_pallas as _pl

        # power-of-2 bucket >= BLK: kernel shapes stay in a small fixed
        # set (this kernel's Mosaic compile is expensive; recompiling per
        # batch size would dominate — same invariant as ed25519's buckets)
        pad = max(_pl.BLK, 1 << (max(n, 1) - 1).bit_length())
    else:
        pad = None
    kwargs, real = prepare_batch(
        curve_name, public_keys, signatures, messages, pad_to=pad
    )
    while on_tpu and not _pallas_failed_once:
        try:
            _self_check_pallas(curve_name, _pl)
            mask = _pl.verify_kernel_pallas(
                curve_name,
                kwargs["qx"].T,
                kwargs["qy"].T,
                kwargs["u1_words"].T,
                kwargs["u2_words"].T,
                kwargs["r_cmp"].T,
                kwargs["ok"][None, :].astype(jnp.uint32),
            )
            return [bool(b) for b in np.asarray(mask)[0, :real]]
        except Exception:
            # the Pallas path must never sink verification: first drop
            # the fast-mul variants (the one Mosaic-lowering unknown,
            # docs/perf-roofline.md) and retry; then log once and serve
            # everything from the portable XLA kernel from here on
            import logging

            from . import ed25519_pallas as _ed

            log = logging.getLogger(__name__)
            if _ed._FAST_MUL_ENABLED:
                log.exception(
                    "Pallas ECDSA kernel failed with fast-mul on; "
                    "retrying with the dense multiply"
                )
                _ed._FAST_MUL_ENABLED = False
                continue
            _pallas_failed_once = True
            log.exception(
                "Pallas ECDSA kernel failed; falling back to the XLA "
                "kernel for the rest of this process"
            )
    mask = np.asarray(_verify_kernel(curve_name, **kwargs))
    return [bool(b) for b in mask[:real]]

"""Kernel op-budget attestation: trace-and-count the verify kernels.

docs/perf-roofline.md derives the ed25519 ladder's cost budget by hand
(≈3,300 field muls per signature for the Pallas kernel) and the round-3
levers were all justified by op counts — but nothing MEASURED the counts,
so a regression that quietly doubles the ladder's multiply work (a lost
`_square` special case, a broadcast that re-runs a chain per limb, an
accidental extra canonicalization) would ship invisibly and only surface
months later as a halved hardware rate. This module closes that hole
off-hardware, the same move as the Mosaic lowering gate:

  * `count_kernel(name)` traces a registered verify kernel to its jaxpr
    (abstract inputs — no compile, no device, works on the CPU-only CI
    box) and walks it, multiplying through `scan` trip counts
    (lax.fori_loop with static bounds lowers to scan), counting integer
    `mul` element-ops and total integer element-ops, normalized per
    signature.
  * Each kernel family is self-calibrated: its own field multiply is
    traced the same way, so `field_mul_equiv_per_sig` =
    kernel-mul-elems / field-mul-elems stays meaningful across radix or
    formulation changes to the field core itself.
  * `opbudget_manifest.json` pins the counts (`python -m
    corda_tpu.ops.opbudget --pin` regenerates it after a DELIBERATE
    kernel change); `check_budget`/`check_all` fail when a kernel's
    multiply count grows more than `tolerance` (default 5%) over its
    pin — the tier-1 gate (tests/test_opbudget.py) and `bench.py --gate
    → tools/bench_gate.py --opbudget` both call it.
  * Counts are cached per process and exported as
    `Kernel.OpBudget.*{kernel=…}` gauges on /metrics (−1 until counted:
    a metrics scrape must never pay a multi-second trace, so the gauges
    go live after the first gate run or `GET /opbudget?compute=1`).

The module deliberately imports jax only inside functions: the node
registers the gauges (and the ops endpoint serves the cached view)
without touching the backend.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.profiling import OPBUDGET_KERNELS
from ..utils import lockorder

MANIFEST_PATH = os.path.join(os.path.dirname(__file__), "opbudget_manifest.json")

#: tolerated relative growth of a pinned count before the gate fails
DEFAULT_TOLERANCE = 0.05

#: the counts a pin records and the gate compares (growth-gated ones
#: first; the rest ride along as context)
GATED_METRICS = ("u32_mul_elems_per_sig",)
PINNED_METRICS = (
    "u32_mul_elems_per_sig",
    "field_mul_equiv_per_sig",
    "int_elems_per_sig",
    "mul_eqns",
)

#: TEST HOOK — extra dummy field multiplies folded into the traced
#: kernel, per trace (tests/test_opbudget.py uses it to prove the gate
#: fails on synthetic ladder growth; production never sets it)
_TEST_EXTRA_MULS = 0

#: TEST HOOK — extra dynamic-update-slice ops folded into the traced
#: kernel (the kernel-jaxpr lint proves its gate trips on them)
_TEST_EXTRA_DUS = 0

_cache: Dict[str, Dict] = {}
_cache_lock = lockorder.make_lock("opbudget._cache_lock")


# -- jaxpr walking -----------------------------------------------------------

def _walk(jaxpr, mult: int, stats: Dict[str, int]) -> Dict[str, int]:
    """Accumulate integer element-op counts over a jaxpr, recursing into
    nested jaxprs and multiplying through static loop trip counts."""
    import numpy as np
    import jax.numpy as jnp

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub = None
        m = mult
        if name == "scan":
            sub = eqn.params["jaxpr"]
            m = mult * int(eqn.params["length"])
        elif name == "while":
            # dynamic trip count: body counted ONCE and flagged — a
            # gated kernel growing a while loop must fail review, not
            # silently under-count
            sub = eqn.params["body_jaxpr"]
            stats["dynamic_loops"] += 1
        elif name == "cond":
            for branch in eqn.params["branches"]:
                _walk(branch.jaxpr, mult, stats)
            continue
        elif "jaxpr" in eqn.params:  # pjit / closed_call / pallas grid
            sub = eqn.params["jaxpr"]
        elif "call_jaxpr" in eqn.params:  # custom_jvp/vjp, core.call
            sub = eqn.params["call_jaxpr"]
        if sub is not None:
            _walk(sub.jaxpr if hasattr(sub, "jaxpr") else sub, m, stats)
            continue
        if name == "dynamic_update_slice":
            # the kernel-jaxpr lint (corda_tpu/analysis/kernel_lint.py)
            # pins this at 0: d-u-s chains are the exact shape that
            # compiled pathologically on XLA CPU (fp12_mul 306s → 5.5s)
            stats["dus_eqns"] += m
        out = eqn.outvars[0].aval
        dtype = getattr(out, "dtype", None)
        if dtype is None or not jnp.issubdtype(dtype, jnp.integer):
            continue
        elems = m * int(np.prod(out.shape)) if out.shape else m
        stats["int_elems"] += elems
        if name == "mul":
            stats["mul_eqns"] += m
            stats["mul_elems"] += elems
    return stats


def _count_fn(fn: Callable, args: Tuple, kwargs: Dict) -> Dict[str, int]:
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return _walk(jaxpr.jaxpr, 1, {
        "mul_eqns": 0, "mul_elems": 0, "int_elems": 0, "dynamic_loops": 0,
        "dus_eqns": 0,
    })


def _inflate(mask, arr, field_mul: Callable):
    """Fold `_TEST_EXTRA_MULS` dummy field multiplies (and
    `_TEST_EXTRA_DUS` dynamic-update-slices) into the traced graph,
    keeping them live in the output so tracing cannot drop them."""
    if not (_TEST_EXTRA_MULS or _TEST_EXTRA_DUS):
        return mask
    x = arr
    for _ in range(_TEST_EXTRA_MULS):
        x = field_mul(x, x)
    if _TEST_EXTRA_DUS:
        from jax import lax

        for _ in range(_TEST_EXTRA_DUS):
            update = x[(slice(0, 1),) * x.ndim]
            x = lax.dynamic_update_slice(x, update, (0,) * x.ndim)
    return mask & (x[..., 0] >= 0)


# -- kernel registry ---------------------------------------------------------
# Each spec returns (traced_fn, args, kwargs, batch, calibrate) where
# `calibrate` is (field_mul_fn, cal_args) traced separately to get the
# family's per-field-mul element cost.

def _spec_ed25519_xla():
    import jax
    import jax.numpy as jnp

    from . import ed25519_batch
    from . import field25519 as F

    B = 16
    s = jax.ShapeDtypeStruct
    kwargs = dict(
        y_a=s((B, 16), jnp.uint32), sign_a=s((B,), jnp.uint32),
        y_r=s((B, 16), jnp.uint32), sign_r=s((B,), jnp.uint32),
        s_words=s((B, 8), jnp.uint32), h_words=s((B, 8), jnp.uint32),
        s_ok=s((B,), jnp.bool_),
    )

    def fn(**kw):
        mask = ed25519_batch.verify_kernel(**kw)
        return _inflate(mask, kw["y_a"], F.mul)

    cal = (F.mul, (s((1, 16), jnp.uint32), s((1, 16), jnp.uint32)), 1)
    return fn, (), kwargs, B, cal


def _spec_ed25519_pallas():
    import jax
    import jax.numpy as jnp

    from . import ed25519_pallas as _pl
    from . import field25519 as F

    B = _pl.BLK
    s = jax.ShapeDtypeStruct
    args = (
        s((16, B), jnp.uint32), s((1, B), jnp.uint32),
        s((16, B), jnp.uint32), s((1, B), jnp.uint32),
        s((8, B), jnp.uint32), s((8, B), jnp.uint32),
        s((1, B), jnp.uint32),
    )

    def fn(y_a, sign_a, y_r, sign_r, s_words, h_words, s_ok):
        mask = _pl.verify_kernel_pallas(
            y_a, sign_a, y_r, sign_r, s_words, h_words, s_ok
        )
        # rows-first layout: inflate over the batch width like the
        # kernel does; F.mul's limb axis lands on the batch dim, which
        # is irrelevant for COUNTING the synthetic growth
        return _inflate(mask, y_a.T, F.mul)

    # rows-first field core: the batch is the WIDTH (last axis), so the
    # calibration normalizes per lane (cal batch = 8)
    if _pl._RADIX13_ENABLED:
        def cal_mul(a, b):
            with _pl._radix13_trace(True):
                return _pl._mul13(a, b)

        cal = (cal_mul, (s((_pl.ROWS13, 8), jnp.uint32),
                         s((_pl.ROWS13, 8), jnp.uint32)), 8)
    else:
        cal = (_pl._mul, (s((16, 8), jnp.uint32), s((16, 8), jnp.uint32)), 8)
    return fn, args, {}, B, cal


def _spec_ecdsa_secp256r1_xla():
    import jax
    import jax.numpy as jnp

    from . import ecdsa_batch
    from .field_secp import FIELD_R1

    B = 8
    s = jax.ShapeDtypeStruct
    kwargs = dict(
        qx=s((B, 16), jnp.uint32), qy=s((B, 16), jnp.uint32),
        u1_words=s((B, 8), jnp.uint32), u2_words=s((B, 8), jnp.uint32),
        r_cmp=s((B, 16), jnp.uint32), ok=s((B,), jnp.bool_),
    )

    def fn(**kw):
        mask = ecdsa_batch._verify_kernel("secp256r1", **kw)
        return _inflate(mask, kw["qx"], FIELD_R1.mul)

    cal = (FIELD_R1.mul, (s((1, 16), jnp.uint32), s((1, 16), jnp.uint32)), 1)
    return fn, (), kwargs, B, cal


def _spec_bls12_miller_loop():
    import jax
    import jax.numpy as jnp

    from . import bls12_batch
    from . import field_bls12 as FB

    B = 2
    s = jax.ShapeDtypeStruct
    args = (
        s((B, 24), jnp.uint32), s((B, 24), jnp.uint32),
        s((B, 2, 24), jnp.uint32), s((B, 2, 24), jnp.uint32),
    )

    def fn(xp, yp, qx, qy):
        f = bls12_batch.miller_loop(xp, yp, qx, qy)
        mask = _inflate(jnp.all(f >= 0, axis=(-1, -2, -3, -4)), xp, FB.F.mul)
        return f, mask

    cal = (FB.F.mul, (s((1, 24), jnp.uint32), s((1, 24), jnp.uint32)), 1)
    return fn, (), dict(zip(("xp", "yp", "qx", "qy"), args)), B, cal


def _spec_bls12_final_exp():
    import jax
    import jax.numpy as jnp

    from . import bls12_batch
    from . import field_bls12 as FB

    B = 2
    s = jax.ShapeDtypeStruct
    f_in = s((B, 2, 3, 2, 24), jnp.uint32)

    def fn(f):
        out = bls12_batch.final_exponentiation(f)
        mask = _inflate(
            jnp.all(out >= 0, axis=(-1, -2, -3, -4)), f[..., 0, 0, 0, :],
            FB.F.mul,
        )
        return out, mask

    cal = (FB.F.mul, (s((1, 24), jnp.uint32), s((1, 24), jnp.uint32)), 1)
    return fn, (), {"f": f_in}, B, cal


_SPECS: Dict[str, Callable] = {
    "ed25519_xla": _spec_ed25519_xla,
    "ed25519_pallas": _spec_ed25519_pallas,
    "ecdsa_secp256r1_xla": _spec_ecdsa_secp256r1_xla,
    "bls12_miller_loop": _spec_bls12_miller_loop,
    "bls12_final_exp": _spec_bls12_final_exp,
}
KERNEL_NAMES: Tuple[str, ...] = tuple(_SPECS)
assert KERNEL_NAMES == OPBUDGET_KERNELS, (
    "utils/profiling.OPBUDGET_KERNELS (the jax-free gauge name source) "
    "must list exactly the registered kernels"
)


# -- counting ----------------------------------------------------------------

def count_kernel(name: str, use_cache: bool = True) -> Dict:
    """Trace + count one kernel. Cached per process (the counts are
    static for a given kernel config); `use_cache=False` re-traces —
    the test-inflation path needs a fresh trace."""
    if name not in _SPECS:
        raise KeyError(f"unknown kernel {name!r}; have {KERNEL_NAMES}")
    with _cache_lock:
        if use_cache and name in _cache:
            return dict(_cache[name])
    import jax

    fn, args, kwargs, batch, (cal_fn, cal_args, cal_batch) = _SPECS[name]()
    stats = _count_fn(fn, args, kwargs)
    cal_stats = _count_fn(cal_fn, cal_args, {})
    cal_elems = max(cal_stats["mul_elems"] / cal_batch, 1)
    counts = {
        "kernel": name,
        "batch": batch,
        "mul_eqns": stats["mul_eqns"],
        "u32_mul_elems_per_sig": round(stats["mul_elems"] / batch, 1),
        "int_elems_per_sig": round(stats["int_elems"] / batch, 1),
        "field_mul_equiv_per_sig": round(
            stats["mul_elems"] / batch / cal_elems, 1
        ),
        "field_mul_elems": round(cal_elems, 1),
        "dynamic_loops": stats["dynamic_loops"],
        "dynamic_update_slice": stats["dus_eqns"],
        "jax_version": jax.__version__,
    }
    with _cache_lock:
        if use_cache:
            _cache[name] = dict(counts)
    return counts


def cached_counts(name: str) -> Optional[Dict]:
    with _cache_lock:
        counts = _cache.get(name)
    return dict(counts) if counts else None


def gauge_value(name: str, metric: str) -> float:
    """Cache-only read for the Kernel.OpBudget.* gauges: −1 until this
    process has traced the kernel (gate run or /opbudget?compute=1) —
    a /metrics scrape must never pay the trace."""
    counts = cached_counts(name)
    if counts is None:
        return -1.0
    return float(counts.get(metric, -1.0))


def _clear_cache(name: Optional[str] = None) -> None:
    with _cache_lock:
        if name is None:
            _cache.clear()
        else:
            _cache.pop(name, None)


# -- manifest + gate ---------------------------------------------------------

def load_manifest(path: Optional[str] = None) -> Dict:
    with open(path or MANIFEST_PATH) as fh:
        return json.load(fh)


def pin_manifest(path: Optional[str] = None,
                 names: Optional[List[str]] = None) -> Dict:
    """Re-measure and pin the named kernels (default: all). Run after a
    DELIBERATE kernel cost change; the diff is the review artifact.
    A partial pin (`--kernel X`) MERGES into the existing manifest —
    re-pinning one kernel must never delete the others' pins."""
    import jax

    existing: Dict = {}
    try:
        existing = load_manifest(path)
    except (OSError, ValueError):
        pass  # no manifest yet (first pin) — start fresh
    manifest = {
        "comment": (
            "Pinned kernel op budgets (docs/perf-roofline.md). Regenerate "
            "with `python -m corda_tpu.ops.opbudget --pin` after a "
            "deliberate kernel change; the tier-1 gate fails when a "
            "kernel's multiply count grows >tolerance over its pin."
        ),
        "tolerance": DEFAULT_TOLERANCE,
        "jax_version": jax.__version__,
        "roofline_reference": {
            "ed25519_pallas_field_muls_per_sig": 3300,
            "doc": "docs/perf-roofline.md",
        },
        "kernels": dict(existing.get("kernels", {})),
    }
    for name in names or KERNEL_NAMES:
        counts = count_kernel(name)
        manifest["kernels"][name] = {
            k: counts[k] for k in PINNED_METRICS
        }
    with open(path or MANIFEST_PATH, "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return manifest


def check_budget(name: str, manifest: Optional[Dict] = None,
                 tolerance: Optional[float] = None) -> List[Dict]:
    """Violations of one kernel's pinned budget (empty list = pass).

    Growth beyond `tolerance` in a gated metric fails; shrink beyond
    tolerance is reported as kind="improved" (non-fatal — re-pin to
    keep the manifest honest). A kernel missing from the manifest is a
    violation: a gate that skips what it was asked to pin is not a gate.
    """
    if manifest is None:
        manifest = load_manifest()
    if tolerance is None:
        tolerance = float(manifest.get("tolerance", DEFAULT_TOLERANCE))
    pinned = manifest.get("kernels", {}).get(name)
    if pinned is None:
        return [{"kernel": name, "metric": None, "kind": "unpinned",
                 "pinned": None, "measured": None, "change": None}]
    counts = count_kernel(name)
    out: List[Dict] = []
    for metric in GATED_METRICS:
        ref = pinned.get(metric)
        cur = counts.get(metric)
        if ref is None or cur is None or ref <= 0:
            continue
        change = (cur - ref) / ref
        if change > tolerance:
            out.append({
                "kernel": name, "metric": metric, "kind": "grew",
                "pinned": ref, "measured": cur,
                "change": round(change, 4),
            })
        elif change < -tolerance:
            out.append({
                "kernel": name, "metric": metric, "kind": "improved",
                "pinned": ref, "measured": cur,
                "change": round(change, 4),
            })
    return out


def check_all(manifest: Optional[Dict] = None,
              tolerance: Optional[float] = None,
              names: Optional[List[str]] = None) -> List[Dict]:
    """Gate every registered kernel; only kind="grew"/"unpinned" entries
    should fail a caller (kind="improved" is advisory)."""
    out: List[Dict] = []
    for name in names or KERNEL_NAMES:
        out.extend(check_budget(name, manifest=manifest, tolerance=tolerance))
    return out


def fatal_violations(violations: List[Dict]) -> List[Dict]:
    return [v for v in violations if v["kind"] in ("grew", "unpinned")]


# -- mesh-wrapped kernel ------------------------------------------------------
#
# The mesh dispatch stage (parallel/mesh.py, docs/perf-pipeline.md) wraps
# the SAME verify kernel in shard_map + a psum — sharding must divide the
# work, never add to it. Deliberately NOT a _SPECS entry: the registry's
# names must stay exactly utils/profiling.OPBUDGET_KERNELS (the jax-free
# gauge source), and the mesh wrapper has no budget of its own — its pin
# IS the single-device ed25519_xla pin.

def count_mesh_kernel(n_devices: int = 2, per_device: int = 16,
                      use_cache: bool = True) -> Dict:
    """Trace the shard_map-wrapped ed25519 verify step and count
    per-signature costs exactly like `count_kernel`.

    The shard body appears ONCE in the traced jaxpr (shard_map traces
    per-shard shapes), so normalizing by the PER-SHARD batch gives the
    cost each device pays per signature — 1:1 comparable with the
    single-device `ed25519_xla` pin (whose spec traces the same kernel
    at batch 16)."""
    cache_key = f"mesh_ed25519_xla:{n_devices}:{per_device}"
    with _cache_lock:
        if use_cache and cache_key in _cache:
            return dict(_cache[cache_key])
    import jax
    import jax.numpy as jnp

    from ..parallel import mesh as mesh_mod
    from . import field25519 as F

    mesh = mesh_mod.data_mesh(n_devices)
    _prepare, fn, _specs, _blk = mesh_mod._sharded_step(mesh, "ed25519")
    B = per_device * n_devices  # global batch: per_device rows per shard
    s = jax.ShapeDtypeStruct
    args = (
        s((B, 16), jnp.uint32), s((B,), jnp.uint32),
        s((B, 16), jnp.uint32), s((B,), jnp.uint32),
        s((B, 8), jnp.uint32), s((B, 8), jnp.uint32),
        s((B,), jnp.bool_),
    )
    stats = _count_fn(fn, args, {})
    cal_stats = _count_fn(
        F.mul, (s((1, 16), jnp.uint32), s((1, 16), jnp.uint32)), {}
    )
    cal_elems = max(cal_stats["mul_elems"] / 1, 1)
    counts = {
        "kernel": f"mesh_ed25519_xla{{n={n_devices}}}",
        "batch": per_device,
        "n_devices": n_devices,
        "mul_eqns": stats["mul_eqns"],
        "u32_mul_elems_per_sig": round(stats["mul_elems"] / per_device, 1),
        "int_elems_per_sig": round(stats["int_elems"] / per_device, 1),
        "field_mul_equiv_per_sig": round(
            stats["mul_elems"] / per_device / cal_elems, 1
        ),
        "field_mul_elems": round(cal_elems, 1),
        "dynamic_loops": stats["dynamic_loops"],
        "dynamic_update_slice": stats["dus_eqns"],
        "jax_version": jax.__version__,
    }
    with _cache_lock:
        if use_cache:
            _cache[cache_key] = dict(counts)
    return counts


def check_mesh_budget(n_devices: int = 2, manifest: Optional[Dict] = None,
                      tolerance: Optional[float] = None) -> List[Dict]:
    """Gate the mesh-wrapped kernel against the SINGLE-DEVICE pin: a
    shard_map wrapping that grows the per-signature multiply count has
    changed the kernel, not just sharded it. Same violation shape and
    `fatal_violations` policy as `check_budget`."""
    if manifest is None:
        manifest = load_manifest()
    if tolerance is None:
        tolerance = float(manifest.get("tolerance", DEFAULT_TOLERANCE))
    pinned = manifest.get("kernels", {}).get("ed25519_xla")
    name = f"mesh_ed25519_xla{{n={n_devices}}}"
    if pinned is None:
        return [{"kernel": name, "metric": None, "kind": "unpinned",
                 "pinned": None, "measured": None, "change": None}]
    counts = count_mesh_kernel(n_devices)
    out: List[Dict] = []
    for metric in GATED_METRICS:
        ref = pinned.get(metric)
        cur = counts.get(metric)
        if ref is None or cur is None or ref <= 0:
            continue
        change = (cur - ref) / ref
        if change > tolerance:
            out.append({
                "kernel": name, "metric": metric, "kind": "grew",
                "pinned": ref, "measured": cur,
                "change": round(change, 4),
            })
        elif change < -tolerance:
            out.append({
                "kernel": name, "metric": metric, "kind": "improved",
                "pinned": ref, "measured": cur,
                "change": round(change, 4),
            })
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="opbudget")
    ap.add_argument("--pin", action="store_true",
                    help="re-measure and rewrite the manifest")
    ap.add_argument("--kernel", action="append",
                    help="restrict to specific kernels (repeatable)")
    ap.add_argument("--tolerance", type=float, default=None)
    args = ap.parse_args(argv)
    if args.pin:
        manifest = pin_manifest(names=args.kernel)
        print(json.dumps(manifest["kernels"], indent=1, sort_keys=True))
        return 0
    violations = check_all(tolerance=args.tolerance, names=args.kernel)
    for name in args.kernel or KERNEL_NAMES:
        print(json.dumps(count_kernel(name), sort_keys=True))
    for v in violations:
        print(json.dumps({"violation": v}, sort_keys=True))
    return 1 if fatal_violations(violations) else 0


if __name__ == "__main__":
    raise SystemExit(main())

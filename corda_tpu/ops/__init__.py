"""corda_tpu.ops: batched JAX/TPU kernels.

The accelerator half of the crypto stack. Host reference implementations and
scalar fallbacks live in corda_tpu.core.crypto; everything here is batch-first
and jit/vmap/shard_map-friendly (static shapes, batch-uniform control flow,
validity carried as bitmasks).
"""
from .ecdsa_batch import prepare_batch as ecdsa_prepare_batch
from .ecdsa_batch import verify_batch as ecdsa_verify_batch
from .ed25519_batch import verify_batch as ed25519_verify_batch
from .ed25519_batch import verify_kernel as ed25519_verify_kernel
from .ed25519_batch import prepare_batch as ed25519_prepare_batch
from .bls12_batch import pairing_batch as bls12_pairing_batch
from .bls12_batch import verify_pairs_batch as bls12_verify_pairs_batch
from .bls12_batch import (
    aggregate_verify_device as bls12_aggregate_verify_device,
)

__all__ = [
    "ecdsa_prepare_batch",
    "ecdsa_verify_batch",
    "ed25519_verify_batch",
    "ed25519_verify_kernel",
    "ed25519_prepare_batch",
    "bls12_pairing_batch",
    "bls12_verify_pairs_batch",
    "bls12_aggregate_verify_device",
]


def _enable_compilation_cache() -> None:
    """Point JAX at a persistent on-disk compilation cache.

    The batch-crypto kernels are expensive to compile (~30 s for the Pallas
    ladder, minutes for the XLA fallback shapes); caching them across
    processes keeps test runs and fresh bench/driver invocations fast.
    Lives here (not the package root) so corda_tpu consumers that never
    touch JAX — broker, RPC clients, node config — don't pay the jax
    import or a global config mutation. Honours an explicit
    JAX_COMPILATION_CACHE_DIR or pre-set jax config.
    """
    import os

    try:
        import jax

        if jax.config.jax_compilation_cache_dir is None:
            cache_dir = os.environ.get(
                "JAX_COMPILATION_CACHE_DIR",
                os.path.join(
                    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
                    ".jax_cache",
                ),
            )
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - jax absent or too old
        pass


_enable_compilation_cache()

"""corda_tpu.ops: batched JAX/TPU kernels.

The accelerator half of the crypto stack. Host reference implementations and
scalar fallbacks live in corda_tpu.core.crypto; everything here is batch-first
and jit/vmap/shard_map-friendly (static shapes, batch-uniform control flow,
validity carried as bitmasks).
"""
from .ecdsa_batch import prepare_batch as ecdsa_prepare_batch
from .ecdsa_batch import verify_batch as ecdsa_verify_batch
from .ed25519_batch import verify_batch as ed25519_verify_batch
from .ed25519_batch import verify_kernel as ed25519_verify_kernel
from .ed25519_batch import prepare_batch as ed25519_prepare_batch

__all__ = [
    "ecdsa_prepare_batch",
    "ecdsa_verify_batch",
    "ed25519_verify_batch",
    "ed25519_verify_kernel",
    "ed25519_prepare_batch",
]

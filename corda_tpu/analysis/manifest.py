"""Pinned baseline for the static lint + kernel-jaxpr lint — the same
pin-and-gate pattern as ops/opbudget_manifest.json.

``analysis_manifest.json`` records, per pass, the KEYS of the findings
that existed (and were reviewed/accepted) when the baseline was pinned.
The gate fails on any NEW key: existing debt is visible but frozen, and
the only way to add a finding is to fix it or suppress it with an
in-source ``# lint: allow(...)`` carrying a reason — both of which show
up in review.  Keys present in the baseline but no longer found are
"stale" (advisory, like op-budget "improved"): re-pin so the baseline
shrinks and stays honest.  ``python -m corda_tpu.analysis --pin``
regenerates; the manifest diff is the review artifact.

The ``kernels`` section pins the kernel-jaxpr lint counts
(dynamic-update-slice eqns and unbounded ``while`` loops per pinned
verify kernel — see :mod:`.kernel_lint`) under the same >5% tolerance
as the op budget (integer counts pinned at 0 fail on ANY growth).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from . import clint
from .astlint import Finding, PASS_IDS, run_passes

MANIFEST_PATH = os.path.join(
    os.path.dirname(__file__), "analysis_manifest.json"
)

DEFAULT_TOLERANCE = 0.05

#: the kernel-jaxpr lint metrics the manifest pins and gates
KERNEL_METRICS = ("dynamic_update_slice", "dynamic_loops")

#: every pinnable pass: the Python-plane ast passes plus the
#: native-plane C-source passes (clint)
ALL_PASS_IDS = tuple(PASS_IDS) + tuple(clint.PASS_IDS)


def run_all_passes(
    paths: Optional[Sequence[str]] = None,
    root: Optional[str] = None,
    passes: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the Python ast passes AND the native C-source passes, split
    by file extension when explicit paths are given.  This is what the
    baseline pin/check and the CLI gate against."""
    wanted = list(passes) if passes is not None else list(ALL_PASS_IDS)
    py_passes = [p for p in wanted if p in PASS_IDS]
    c_passes = [p for p in wanted if p in clint.PASS_IDS]
    findings: List[Finding] = []
    if paths is not None:
        # everything that is not a C/C++ source goes to the ast suite:
        # an unparsable explicit path must surface as a 'file does not
        # parse' finding, never count as linted-clean unexamined
        c_paths = [p for p in paths if p.endswith((".c", ".cc", ".cpp"))]
        py_paths = [p for p in paths if p not in c_paths]
        if py_passes and py_paths:
            findings.extend(run_passes(paths=py_paths, root=root,
                                       passes=py_passes))
        if c_passes and c_paths:
            findings.extend(clint.run_passes(paths=c_paths, root=root,
                                             passes=c_passes))
        return findings
    if py_passes:
        findings.extend(run_passes(root=root, passes=py_passes))
    if c_passes:
        findings.extend(clint.run_passes(root=root, passes=c_passes))
    return findings


def load_manifest(path: Optional[str] = None) -> Dict:
    with open(path or MANIFEST_PATH) as fh:
        return json.load(fh)


def pin_manifest(
    path: Optional[str] = None,
    findings: Optional[Sequence[Finding]] = None,
    kernels: Optional[Dict[str, Dict[str, int]]] = None,
    passes: Optional[Sequence[str]] = None,
) -> Dict:
    """Re-run the passes and rewrite the baseline. A partial pin MERGES:
    `kernels=None` preserves the existing kernel pins (pinning those
    requires jax — tools/lint.py --pin traces them; a static-only pin
    must not drop them), and `passes=<subset>` re-pins only those
    passes, keeping every other pass's accepted baseline (re-pinning
    one pass must never resurrect the others' findings as NEW)."""
    if findings is None:
        findings = run_all_passes(passes=passes)
    existing: Dict = {}
    try:
        existing = load_manifest(path)
    except (OSError, ValueError):
        pass  # first pin
    repinned = set(passes) if passes is not None else set(ALL_PASS_IDS)
    baseline: Dict[str, List[str]] = {
        p: ([] if p in repinned
            else list(existing.get("passes", {}).get(p, [])))
        for p in ALL_PASS_IDS
    }
    for f in findings:
        baseline.setdefault(f.pass_id, []).append(f.key)
    for p in baseline:
        baseline[p] = sorted(set(baseline[p]))
    manifest = {
        "comment": (
            "Accepted-findings baseline for the concurrency lint "
            "(docs/static-analysis.md). Regenerate with `python -m "
            "corda_tpu.analysis --pin` (or tools/lint.py --pin) after "
            "fixing findings; any NEW finding fails tier-1. Never "
            "hand-edit: the pin diff is the review artifact."
        ),
        "tolerance": DEFAULT_TOLERANCE,
        "passes": baseline,
        "kernels": (
            kernels if kernels is not None
            else dict(existing.get("kernels", {}))
        ),
    }
    with open(path or MANIFEST_PATH, "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return manifest


def check_findings(
    findings: Optional[Sequence[Finding]] = None,
    manifest: Optional[Dict] = None,
) -> Dict:
    """Compare current findings to the baseline.

    Returns {"new": [finding dicts], "stale": [keys], "accepted": n}.
    `new` non-empty = gate failure.
    """
    if findings is None:
        findings = run_all_passes()
    if manifest is None:
        manifest = load_manifest()
    baseline: Dict[str, List[str]] = manifest.get("passes", {})
    pinned = {k for keys in baseline.values() for k in keys}
    current = {f.key for f in findings}
    new = [f.as_dict() for f in findings if f.key not in pinned]
    stale = sorted(pinned - current)
    return {
        "new": new,
        "stale": stale,
        "accepted": len(current & pinned),
        "total": len(findings),
    }


def check_kernels(
    counts_by_kernel: Dict[str, Dict],
    manifest: Optional[Dict] = None,
    tolerance: Optional[float] = None,
) -> List[Dict]:
    """Gate the kernel-jaxpr lint counts against the pinned section.
    A kernel missing from the manifest is a violation (a gate that
    skips what it was asked to pin is not a gate); counts pinned at 0
    fail on any growth; nonzero pins tolerate `tolerance` growth and
    report shrink as kind="improved"."""
    if manifest is None:
        manifest = load_manifest()
    if tolerance is None:
        tolerance = float(manifest.get("tolerance", DEFAULT_TOLERANCE))
    pinned_all = manifest.get("kernels", {})
    out: List[Dict] = []
    for name, counts in sorted(counts_by_kernel.items()):
        pinned = pinned_all.get(name)
        if pinned is None:
            out.append({"kernel": name, "metric": None, "kind": "unpinned",
                        "pinned": None, "measured": None})
            continue
        for metric in KERNEL_METRICS:
            ref = pinned.get(metric)
            cur = counts.get(metric)
            if ref is None or cur is None:
                continue
            if cur > ref * (1 + tolerance):
                out.append({"kernel": name, "metric": metric,
                            "kind": "grew", "pinned": ref, "measured": cur})
            elif cur < ref * (1 - tolerance):
                out.append({"kernel": name, "metric": metric,
                            "kind": "improved", "pinned": ref,
                            "measured": cur})
    return out


def fatal_kernel_violations(violations: List[Dict]) -> List[Dict]:
    return [v for v in violations if v["kind"] in ("grew", "unpinned")]

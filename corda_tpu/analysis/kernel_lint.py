"""Kernel-jaxpr lint: structural pathologies in the pinned verify
kernels, gated like the op budget.

PR 7's field tower rewrite exists because the CIOS pattern's
``dynamic-update-slice`` chains compiled pathologically on XLA CPU
(fp12_mul 306s → 5.5s after moving to gathered anti-diagonal products);
``while`` primitives make op counts un-gateable (trip count unknown) and
block the scan-based pipelining every perf item relies on.  Nothing
stopped either from creeping back in.  This pass walks the SAME traces
:mod:`corda_tpu.ops.opbudget` already builds (cached per process — the
tier-1 op-budget tests and this lint share one trace per kernel) and
pins, per kernel:

* ``dynamic_update_slice`` — trip-count-weighted dynamic-update-slice
  equation count (today: 0 everywhere);
* ``dynamic_loops`` — unbounded ``while`` primitives (today: 0).

Counts live in the ``kernels`` section of ``analysis_manifest.json``
under the same >5% tolerance mechanism as the op budget; a count pinned
at 0 fails on ANY growth.  ``tools/lint.py --pin`` re-pins after a
deliberate change; the diff is the review artifact.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from . import manifest as _manifest


def kernel_names() -> Sequence[str]:
    from ..utils.profiling import OPBUDGET_KERNELS

    return OPBUDGET_KERNELS


def kernel_counts(
    names: Optional[Sequence[str]] = None, use_cache: bool = True
) -> Dict[str, Dict[str, int]]:
    """Trace each pinned kernel (through the opbudget cache) and pull
    out the structural-lint counts."""
    from ..ops import opbudget

    out: Dict[str, Dict[str, int]] = {}
    for name in names or kernel_names():
        counts = opbudget.count_kernel(name, use_cache=use_cache)
        out[name] = {
            "dynamic_update_slice": int(
                counts.get("dynamic_update_slice", 0)
            ),
            "dynamic_loops": int(counts.get("dynamic_loops", 0)),
        }
    return out


def check_all(
    manifest: Optional[Dict] = None,
    tolerance: Optional[float] = None,
    names: Optional[Sequence[str]] = None,
    use_cache: bool = True,
) -> List[Dict]:
    return _manifest.check_kernels(
        kernel_counts(names, use_cache=use_cache),
        manifest=manifest, tolerance=tolerance,
    )

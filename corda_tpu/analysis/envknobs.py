"""Central registry of every ``CORDA_TPU_*`` environment knob.

The ``env_registry`` lint pass (corda_tpu/analysis/astlint.py) enforces
three invariants tier-1:

* every knob READ anywhere in the package/tools/bench is registered
  here with its default and a doc reference;
* every registered knob appears in the docs/running-nodes.md knob
  table (``KNOB_TABLE_DOC``);
* every registered knob is actually read somewhere (stale entries are
  findings — the registry cannot drift into fiction).

Adding a knob therefore takes three edits (read site, this registry,
the doc table) and the lint names whichever one you forgot.  Defaults
here are DOCUMENTATION of the read-site defaults, not a second source
of truth the code consults — keep them in sync with the read site (the
doc table is the operator-facing copy).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

#: the operator-facing table every knob must appear in
KNOB_TABLE_DOC = "docs/running-nodes.md"


@dataclass(frozen=True)
class Knob:
    name: str
    default: str  # rendered default ("unset" when absence is meaningful)
    doc: str  # doc file covering this knob's subsystem
    description: str


def _k(name: str, default: str, doc: str, description: str) -> Knob:
    return Knob(name, default, doc, description)


_ENTRIES = [
    # -- admission / overload (PR 5) -----------------------------------------
    _k("CORDA_TPU_ADMISSION_RATE", "unset", "docs/robustness.md",
       "token-bucket rate for new client flow starts (flows/s)"),
    _k("CORDA_TPU_ADMISSION_BURST", "2x rate", "docs/robustness.md",
       "token-bucket size (burst absorbed before shedding)"),
    _k("CORDA_TPU_ADMISSION_MAX_FLOWS", "unset", "docs/robustness.md",
       "live-flow concurrency cap"),
    _k("CORDA_TPU_ADMISSION_RETRY_MS", "250", "docs/robustness.md",
       "retry_after_ms hint floor on shed rejections"),
    _k("CORDA_TPU_OVERLOAD_QDEPTH_HIGH", "5000", "docs/robustness.md",
       "P2P queue depth that flips the overload machine to shedding"),
    _k("CORDA_TPU_OVERLOAD_BACKLOG_HIGH", "256", "docs/robustness.md",
       "blocking-executor backlog shed threshold"),
    _k("CORDA_TPU_OVERLOAD_BATCHER_HIGH", "64", "docs/robustness.md",
       "batcher queued-batches shed threshold"),
    _k("CORDA_TPU_OVERLOAD_HOLD_S", "2", "docs/robustness.md",
       "quiet dwell before overload recovering -> normal"),
    _k("CORDA_TPU_HEALTH_SUSTAIN_S", "5", "docs/robustness.md",
       "how long a breach must hold before readiness degrades"),
    _k("CORDA_TPU_HEALTH_QDEPTH_DEGRADE", "5000", "docs/robustness.md",
       "sustained inbound-depth threshold that degrades /readyz"),
    # -- queues / backpressure ----------------------------------------------
    _k("CORDA_TPU_P2P_QUEUE_MAX", "10000", "docs/robustness.md",
       "p2p.inbound.* depth cap, reject-new policy (0 = unbounded)"),
    _k("CORDA_TPU_RPC_QUEUE_MAX", "10000", "docs/robustness.md",
       "rpc.server.requests depth cap, reject-new (0 = unbounded)"),
    _k("CORDA_TPU_RPC_CLIENT_QUEUE_MAX", "10000", "docs/robustness.md",
       "per-client reply queue cap, drop-oldest to dead.letter"),
    _k("CORDA_TPU_BATCHER_MAX_QUEUED", "16", "docs/robustness.md",
       "verifier batcher flush-queue cap; overflow blocks submitters"),
    _k("CORDA_TPU_NOTARY_QUEUE_MAX", "4096", "docs/robustness.md",
       "notary coalescer pending cap; overflow sheds retryably"),
    # -- verifier / failover (PR 4) -----------------------------------------
    _k("CORDA_TPU_VERIFIER_WORKERS", "max(2, min(4, cpus))",
       "docs/out-of-process-verification.md",
       "out-of-process verifier worker pool size"),
    _k("CORDA_TPU_VERIFY_DEADLINE", "10.0", "docs/robustness.md",
       "per-attempt verification deadline (seconds)"),
    _k("CORDA_TPU_VERIFY_RETRIES", "2", "docs/robustness.md",
       "redispatch attempts before dead-letter"),
    _k("CORDA_TPU_VERIFY_BACKOFF_S", "0.2", "docs/robustness.md",
       "redispatch backoff base (capped exponential + jitter)"),
    _k("CORDA_TPU_VERIFY_FALLBACK", "1", "docs/robustness.md",
       "0 = dead-letter instead of in-process fallback on breaker open"),
    _k("CORDA_TPU_VERIFY_BREAKER_THRESHOLD", "3", "docs/robustness.md",
       "stacked failures that trip the verifier circuit breaker"),
    _k("CORDA_TPU_VERIFY_BREAKER_COOLDOWN", "5.0", "docs/robustness.md",
       "seconds the open breaker waits before a half-open probe"),
    # -- hospital (PR 4) ----------------------------------------------------
    _k("CORDA_TPU_HOSPITAL", "1", "docs/robustness.md",
       "0 disables checkpoint-replay retry of transient flow failures"),
    _k("CORDA_TPU_HOSPITAL_MAX_RETRIES", "3", "docs/robustness.md",
       "transient-failure retries before the dead-letter ward"),
    _k("CORDA_TPU_HOSPITAL_BACKOFF_S", "0.1", "docs/robustness.md",
       "hospital retry backoff base (seconds)"),
    _k("CORDA_TPU_HOSPITAL_BACKOFF_CAP_S", "5.0", "docs/robustness.md",
       "hospital retry backoff cap (seconds)"),
    _k("CORDA_TPU_HOSPITAL_WARD_MAX", "256", "docs/robustness.md",
       "bounded dead-letter ward size"),
    # -- node / flows -------------------------------------------------------
    _k("CORDA_TPU_FLOW_BLOCKING_THREADS", "4", "docs/writing-flows.md",
       "executor threads serving await_blocking flow sections"),
    # -- bank-side flow hot path (this PR) ------------------------------------
    _k("CORDA_TPU_FLOW_LANES", "cpus (0 on a 1-CPU host)",
       "docs/perf-system.md",
       "flow-continuation lane threads on the broker transport "
       "(0 = on-pump dispatch; MockNetwork stays inline unless opted in)"),
    _k("CORDA_TPU_VAULT_CACHE", "65536", "docs/perf-system.md",
       "decoded vault-state cache capacity backing O(selected) coin "
       "selection (0 = full-scan legacy path)"),
    _k("CORDA_TPU_CP_GROUP_COMMIT", "1", "docs/perf-system.md",
       "0 = per-step checkpoint commits instead of group-committed "
       "drain windows on async transports"),
    _k("CORDA_TPU_CP_LINGER_MS", "0", "docs/perf-system.md",
       "bounded linger a checkpoint group-commit leader waits for more "
       "writers (0 = drain-window coalescing only)"),
    _k("CORDA_TPU_GC_THRESHOLD", "50000", "docs/running-nodes.md",
       "gen-0 GC threshold set at node start (allocation-heavy path)"),
    _k("CORDA_TPU_LOG", "WARNING", "docs/running-nodes.md",
       "console log level for `python -m corda_tpu.node`"),
    _k("CORDA_TPU_EXIT_ON_ORPHAN", "unset", "docs/running-nodes.md",
       "1 = node/worker exits when its parent process dies"),
    _k("CORDA_TPU_HOST_BATCH", "1", "docs/perf-host.md",
       "0 disables the native SHA-512 host prehash batch path"),
    _k("CORDA_TPU_ECDSA_HOST", "1", "docs/perf-host.md",
       "0 disables the native ECDSA host-dispatch path"),
    _k("CORDA_TPU_NATIVE_CODEC", "1", "docs/perf-host.md",
       "0 disables the native codec fast path"),
    _k("CORDA_TPU_PUMP_NATIVE", "1", "docs/perf-system.md",
       "0 disables the GIL-releasing native pump core (batch wire "
       "framing/parsing, header-only routing)"),
    # -- notary / sharding (PR 8) -------------------------------------------
    _k("CORDA_TPU_NOTARY_COALESCE", "1", "docs/perf-system.md",
       "0 disables notary commit coalescing"),
    _k("CORDA_TPU_NOTARY_BATCHED", "1", "docs/perf-system.md",
       "0 disables batched notary signature verification"),
    _k("CORDA_TPU_UNIQ_COALESCE_MAX", "512", "docs/perf-system.md",
       "max transactions folded into one coalesced commit round"),
    _k("CORDA_TPU_SHARDS", "unset", "docs/sharding.md",
       "partition the uniqueness provider into N shards"),
    _k("CORDA_TPU_NODE_WORKERS", "unset", "docs/sharding.md",
       "spawn M shard-worker OS processes behind the broker"),
    _k("CORDA_TPU_SHARD_PREPARE_TTL", "30.0", "docs/sharding.md",
       "cross-shard prepare reservation TTL (seconds)"),
    _k("CORDA_TPU_SHARD_WAL_SWEEP", "5", "docs/sharding.md",
       "per-shard sqlite WAL checkpoint sweep interval (seconds)"),
    # -- rpc ----------------------------------------------------------------
    _k("CORDA_TPU_RPC_WORKERS", "max(2, min(8, 2*cpus))",
       "docs/running-nodes.md", "RPC server dispatch pool size"),
    _k("CORDA_TPU_RPC_INLINE", "1", "docs/perf-system.md",
       "0 disables inline dispatch of async-reply flow methods"),
    # -- observability (PRs 2-3, 6) -----------------------------------------
    _k("CORDA_TPU_TRACING", "1", "docs/observability.md",
       "0 disables the tracing spine"),
    _k("CORDA_TPU_TRACE_SLOW_MS", "1000.0", "docs/observability.md",
       "slow-span watchdog threshold (ms)"),
    _k("CORDA_TPU_TRACE_MAX_TRACES", "512", "docs/observability.md",
       "bounded trace store size (LRU)"),
    _k("CORDA_TPU_EVENTLOG", "1", "docs/observability.md",
       "0 disables the structured event log"),
    _k("CORDA_TPU_EVENTLOG_MAX", "4096", "docs/observability.md",
       "event-log ring capacity"),
    _k("CORDA_TPU_EVENTLOG_LEVEL", "info", "docs/observability.md",
       "minimum recorded event severity"),
    _k("CORDA_TPU_PROFILE_DUMP", "unset", "docs/observability.md",
       "directory for legacy cProfile dumps (unset = off)"),
    _k("CORDA_TPU_PROFILE_THREAD", "p2p", "docs/observability.md",
       "which thread the legacy cProfile hook claims"),
    _k("CORDA_TPU_QUIESCE_FILE", "tpu_capture/QUIESCE",
       "docs/observability.md",
       "cross-process quiesce marker path override"),
    # -- fleet observatory (this PR) ----------------------------------------
    _k("CORDA_TPU_METRICS_HISTORY", "1", "docs/observability.md",
       "0 disables the in-process metric time-series ring"),
    _k("CORDA_TPU_METRICS_HISTORY_INTERVAL_S", "1.0",
       "docs/observability.md",
       "metric history sampling interval (seconds)"),
    _k("CORDA_TPU_METRICS_HISTORY_MAX", "512", "docs/observability.md",
       "metric history ring capacity (samples)"),
    _k("CORDA_TPU_TRACE_EXPORT_MAX", "4096", "docs/observability.md",
       "finished-span export ring capacity (/traces/export)"),
    _k("CORDA_TPU_FLEET_POLL_S", "2.0", "docs/observability.md",
       "fleet collector poll interval over the node probes (seconds)"),
    # -- device-plane kernel ledger (this PR) --------------------------------
    _k("CORDA_TPU_KERNEL_LEDGER", "1", "docs/observability.md",
       "0 kills the per-dispatch kernel flight ledger (aggregate "
       "dispatch stats keep recording)"),
    _k("CORDA_TPU_KERNEL_LEDGER_MAX", "1024", "docs/observability.md",
       "kernel flight ledger ring capacity (records)"),
    _k("CORDA_TPU_KERNEL_LEDGER_COST", "1", "docs/observability.md",
       "0 skips XLA cost-analysis capture at kernel lowering time"),
    # -- lockcheck (this PR) -------------------------------------------------
    _k("CORDA_TPU_LOCKCHECK", "0", "docs/static-analysis.md",
       "1 arms the runtime lock-order deadlock detector"),
    _k("CORDA_TPU_LOCKCHECK_HOLD_MS", "1000", "docs/static-analysis.md",
       "hold-time watchdog threshold for instrumented locks (ms)"),
    # -- kernels / jax dispatch ---------------------------------------------
    _k("CORDA_TPU_DISPATCH", "auto", "docs/perf-roofline.md",
       "device dispatch mode: auto | jax | host"),
    _k("CORDA_TPU_BACKEND_PROBE_TIMEOUT", "20", "docs/hardware-runbook.md",
       "seconds ONE subprocess jax backend probe attempt may take"),
    _k("CORDA_TPU_BACKEND_PROBE_RETRIES", "2", "docs/hardware-runbook.md",
       "probe attempts before falling back to cpu (alternate init "
       "scripts rotate per attempt, capped exponential backoff between)"),
    _k("CORDA_TPU_BACKEND_PROBE_BUDGET_S", "45", "docs/hardware-runbook.md",
       "wall-clock budget across ALL probe attempts; exhausted = "
       "classified skip to cpu (backend_probe_status() shows why)"),
    _k("CORDA_TPU_FAST_MUL", "0", "docs/perf-roofline.md",
       "1 enables the experimental fast multiply path (Pallas)"),
    _k("CORDA_TPU_ED25519_RADIX", "13", "docs/perf-roofline.md",
       "ed25519 Pallas limb radix (13 or 16)"),
    _k("CORDA_TPU_ED25519_BLK", "512", "docs/perf-roofline.md",
       "ed25519 Pallas kernel block width"),
    _k("CORDA_TPU_ECDSA_BLK", "256", "docs/perf-roofline.md",
       "ECDSA Pallas kernel block width"),
    _k("CORDA_TPU_BLS12_BLK", "8", "docs/bls-aggregation.md",
       "BLS12-381 pairing kernel batch width"),
    _k("CORDA_TPU_PIPE_CHUNK", "65536", "docs/perf-roofline.md",
       "ed25519 dispatch pipeline chunk size"),
    # -- overlapped verification pipeline (this PR) ---------------------------
    _k("CORDA_TPU_PIPELINE", "1", "docs/perf-pipeline.md",
       "0 restores the synchronous verify path (no staged overlap)"),
    _k("CORDA_TPU_PIPELINE_DEPTH", "4", "docs/perf-pipeline.md",
       "pipeline ring size: batches in flight across all stages"),
    _k("CORDA_TPU_PIPELINE_DONATE", "1", "docs/perf-pipeline.md",
       "0 disables device input-buffer donation on the split dispatch"),
    # -- mesh-sharded dispatch (this PR) --------------------------------------
    _k("CORDA_TPU_MESH_DEVICES", "0", "docs/perf-pipeline.md",
       ">0 swaps the pipeline's dispatch stage for the mesh dispatcher: "
       "each batch is sharded across this many local devices"),
    _k("CORDA_TPU_MESH_WORKER_SLOT", "unset", "docs/perf-pipeline.md",
       "slot k of M co-located verifier workers pins the disjoint device "
       "slice [k*n, (k+1)*n) (unset = first n local devices)"),
    _k("CORDA_TPU_BATCHER_MAX", "4096", "docs/perf-system.md",
       "verifier signature batcher max batch size"),
    _k("CORDA_TPU_BATCHER_LINGER_MS", "2.0", "docs/perf-system.md",
       "batcher linger before a partial flush (ms)"),
    # -- native-plane sanitizers / arena checker (this PR) --------------------
    _k("CORDA_TPU_ARENA_CHECK", "0", "docs/static-analysis.md",
       "1 arms the zero-copy arena lifetime checker (poisoned arenas, "
       "typed use-after-drain errors with creation stacks)"),
    _k("CORDA_TPU_SANITIZE", "unset", "docs/static-analysis.md",
       "asan|ubsan: native loader builds/loads instrumented extension "
       "variants (set by the corda_tpu.analysis.sanitize runner)"),
    # -- remote soak / loadtest (this PR) -------------------------------------
    _k("CORDA_TPU_LOADTEST_DEADLINE_S", "unset", "docs/robustness.md",
       "scales every procdriver wait (driver stop join, counterparty "
       "vault poll) for loaded soak boxes / slow ssh rigs"),
    _k("CORDA_TPU_DOMAIN_DARK_S", "12", "docs/robustness.md",
       "multi-domain soak dark-window seconds for the domain_partition "
       "disruption (floor 10 — the acceptance's minimum dark window)"),
    # -- crash consistency (docs/robustness.md §7) ---------------------------
    _k("CORDA_TPU_CRASH_AT", "unset", "docs/robustness.md",
       "point[:nth] — SIGKILL the process the nth time the named "
       "durability barrier fires (install_env_crash_hook; real-process "
       "crash tests)"),
    _k("CORDA_TPU_JOURNAL_FSYNC", "0", "docs/robustness.md",
       "1 = broker journal fsyncs every enqueue append and compaction "
       "(power-cut-proof enqueues; acks stay batched — loss is "
       "absorbed by redelivery dedup)"),
    _k("CORDA_TPU_ATOMIC_FSYNC", "1", "docs/robustness.md",
       "0 = atomicfile skips fsync-before-rename (fast, crash-unsafe "
       "mode for throwaway rigs; crashmc proves why the default is 1)"),
    # -- bench --------------------------------------------------------------
    _k("CORDA_TPU_BENCH_FORCE_CPU", "unset", "docs/hardware-runbook.md",
       "1 = bench.py skips the TPU probe and runs CPU-only"),
    _k("CORDA_TPU_BENCH_HEADLINE_ONLY", "unset", "docs/hardware-runbook.md",
       "1 = bench.py prints the headline record only"),
]

KNOBS: Dict[str, Knob] = {k.name: k for k in _ENTRIES}
assert len(KNOBS) == len(_ENTRIES), "duplicate knob registration"

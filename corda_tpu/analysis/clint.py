"""Tokenizer-based lint passes for the native C/C++ sources (the
native-plane analogue of :mod:`.astlint` — docs/static-analysis.md).

PR 11 grew ``native/src/`` to ~4.7k LoC of CPython-API C with
GIL-released regions, borrowed buffer-protocol spans and zero-copy
arenas; its review passes caught arena-pinning and buffer-lifetime bugs
by hand.  These passes catch the same bug SHAPES structurally, cheap
enough for every tier-1 run, with no libclang dependency:

``gil_region``
    No CPython C-API identifier may appear lexically between
    ``Py_BEGIN_ALLOW_THREADS`` and ``Py_END_ALLOW_THREADS`` beyond an
    explicit allowlist of GIL-free names (types/constants like
    ``Py_ssize_t``/``PyBUF_SIMPLE``, and the block/unblock macros).
    The scan is lexical: helpers CALLED from a region must themselves
    be GIL-free by construction (the codec's scan/plan helpers use raw
    ``realloc``/``memcpy`` for exactly this reason).

``buffer_release``
    Every buffer acquisition — ``PyObject_GetBuffer(obj, &view, ...)``
    or a ``PyArg_ParseTuple`` format containing ``y*``/``s*``/``w*``
    filling a declared ``Py_buffer`` — must pair with a
    ``PyBuffer_Release`` on every early ``return`` and every
    ``goto``-fail epilogue.  Acquisition-failure guards (the ``return``
    inside ``if (PyObject_GetBuffer(...) < 0)``) are exempt: the view
    was never filled.

``refcount_escape``
    An owning allocation (``PyMem_Malloc``/``malloc``/``fopen``/a
    new-reference constructor like ``PyList_New``) must be released,
    transferred (``PyTuple_SET_ITEM``/``Py_BuildValue``/returned), or
    covered by the ``goto``-fail epilogue before any early-error
    ``return``.  Also flags an unguarded ``new`` expression (no
    ``std::nothrow``) in the C++ sources: a ``bad_alloc`` thrown across
    the ctypes C ABI aborts the process instead of failing the call.

The release/transfer tracking is LEXICAL (any release between the
acquisition and the return disarms it, whatever branch it sits in) —
deliberate: zero false positives on reviewed code, with the dynamic
half of the story (ASan/UBSan, the arena checker) covering what a
tokenizer cannot.  Suppression mirrors astlint:
``/* lint: allow(pass_id) — reason */`` (or ``//``-style) on the
flagged line or the line above.  Findings carry the same stable
``pass:path:symbol`` keys and pin into ``analysis_manifest.json``.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .astlint import Finding, _dedup, _repo_root

PASS_IDS = ("gil_region", "buffer_release", "refcount_escape")

#: identifiers that LOOK like CPython API but are safe without the GIL
#: (types, constants, the region macros themselves)
GIL_FREE_ALLOWLIST = {
    "Py_BEGIN_ALLOW_THREADS", "Py_END_ALLOW_THREADS",
    "Py_BLOCK_THREADS", "Py_UNBLOCK_THREADS",
    "Py_ssize_t", "Py_buffer", "Py_uhash_t", "Py_UCS4", "Py_uintptr_t",
    "PY_SSIZE_T_MAX", "PY_SSIZE_T_MIN", "PY_VERSION_HEX",
    "PyObject",  # the TYPE in declarations; calls are PyObject_* and match
    "PyBUF_SIMPLE", "PyBUF_WRITABLE", "PyBUF_FORMAT", "PyBUF_ND",
}

_PYAPI_RE = re.compile(r"^_?Py[A-Z_0-9]")

#: calls that free/close/decref an owned resource
RELEASE_FNS = {
    "Py_DECREF", "Py_XDECREF", "Py_CLEAR",
    "PyMem_Free", "PyMem_Del", "free", "fclose", "PyBuffer_Release",
}

#: calls returning a resource the caller owns
ALLOC_FNS = {
    "PyMem_Malloc", "PyMem_Calloc", "malloc", "calloc", "fopen",
    "PySequence_Fast", "PyObject_GetIter", "PyBytes_FromStringAndSize",
    "PyBytes_FromObject", "PyList_New", "PyDict_New", "PyTuple_New",
    "PyUnicode_DecodeUTF8", "PyDict_Keys", "PyObject_CallFunctionObjArgs",
    "PyMemoryView_FromObject", "PyList_AsTuple",
}

#: calls that STEAL a reference passed to them (ownership transferred)
TRANSFER_FNS = {
    "PyTuple_SET_ITEM", "PyList_SET_ITEM", "PyTuple_SetItem",
    "PyList_SetItem", "PyModule_AddObject", "Py_BuildValue",
}

_SUPPRESS_RE = re.compile(r"c?lint:\s*allow\(\s*([a-z_,\s]+?)\s*\)")


def native_paths(root: Optional[str] = None) -> List[str]:
    """The C lint target set: every native extension source."""
    root = root or _repo_root()
    src = os.path.join(root, "corda_tpu", "native", "src")
    out: List[str] = []
    if os.path.isdir(src):
        for fn in sorted(os.listdir(src)):
            if fn.endswith((".c", ".cc", ".cpp")):
                out.append(os.path.join(src, fn))
    return out


# -- tokenizer ----------------------------------------------------------------

class Tok:
    __slots__ = ("text", "line")

    def __init__(self, text: str, line: int):
        self.text = text
        self.line = line

    def __repr__(self):  # pragma: no cover - debug aid
        return f"Tok({self.text!r}@{self.line})"


_TOKEN_RE = re.compile(
    r'[A-Za-z_][A-Za-z0-9_]*'            # identifier / keyword
    r'|"(?:[^"\\\n]|\\.)*"'              # string literal (kept: formats)
    r"|'(?:[^'\\\n]|\\.)*'"              # char literal
    r'|0[xX][0-9a-fA-F]+|\d+\.?\d*'      # numbers
    r'|::|->|\S'                         # punctuation (1 char + :: ->)
)


def _strip_comments(src: str) -> str:
    """Replace comments with spaces, preserving line structure.  String
    literals survive (PyArg formats are needed); preprocessor lines are
    blanked (macro bodies would confuse the function scanner)."""
    out: List[str] = []
    i, n = 0, len(src)
    state = "code"  # code | block | line | str | chr
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if state == "code":
            if c == "/" and nxt == "*":
                state = "block"
                out.append("  ")
                i += 2
                continue
            if c == "/" and nxt == "/":
                state = "line"
                out.append("  ")
                i += 2
                continue
            if c == '"':
                state = "str"
            elif c == "'":
                state = "chr"
            out.append(c)
        elif state == "block":
            if c == "*" and nxt == "/":
                state = "code"
                out.append("  ")
                i += 2
                continue
            out.append(c if c == "\n" else " ")
        elif state == "line":
            if c == "\n":
                state = "code"
                out.append(c)
            else:
                out.append(" ")
        elif state == "str":
            if c == "\\":
                out.append(c + nxt)
                i += 2
                continue
            if c == '"' or c == "\n":
                state = "code"
            out.append(c)
        elif state == "chr":
            if c == "\\":
                out.append(c + nxt)
                i += 2
                continue
            if c == "'" or c == "\n":
                state = "code"
            out.append(c)
        i += 1
    # blank preprocessor directives (with backslash continuations)
    lines = "".join(out).split("\n")
    blank_next = False
    for j, ln in enumerate(lines):
        if blank_next or ln.lstrip().startswith("#"):
            blank_next = ln.rstrip().endswith("\\")
            lines[j] = ""
    return "\n".join(lines)


def _tokenize(cleaned: str) -> List[Tok]:
    toks: List[Tok] = []
    for lineno, ln in enumerate(cleaned.split("\n"), start=1):
        for m in _TOKEN_RE.finditer(ln):
            toks.append(Tok(m.group(0), lineno))
    return toks


class _CFile:
    """Tokenized C source + structural indexes (paren/brace matching,
    function spans, suppression table)."""

    def __init__(self, path: str, relpath: str, src: str):
        self.relpath = relpath
        self.raw_lines = src.split("\n")
        self.toks = _tokenize(_strip_comments(src))
        self.suppress: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.raw_lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppress[i] = {
                    p.strip() for p in m.group(1).split(",") if p.strip()
                }
        self.match = self._match_pairs()
        self.functions = self._find_functions()

    def suppressed(self, pass_id: str, line: int) -> bool:
        for ln in (line, line - 1):
            ids = self.suppress.get(ln)
            if ids and (pass_id in ids or "all" in ids):
                return True
        return False

    def _match_pairs(self) -> Dict[int, int]:
        """open-index -> close-index for () and {} (and the reverse)."""
        match: Dict[int, int] = {}
        stack: List[Tuple[str, int]] = []
        for i, t in enumerate(self.toks):
            if t.text in "({":
                stack.append((t.text, i))
            elif t.text in ")}":
                want = "(" if t.text == ")" else "{"
                # tolerate imbalance (macro remnants): pop to the match
                while stack and stack[-1][0] != want:
                    stack.pop()
                if stack:
                    _, j = stack.pop()
                    match[j] = i
                    match[i] = j
        return match

    _NOT_FN = {"if", "for", "while", "switch", "catch", "return", "sizeof",
               "defined"}

    def _find_functions(self) -> List[Tuple[str, int, int, int]]:
        """[(name, body_open_idx, body_close_idx, def_line)] for every
        function definition: ``ident ( ... ) [const...] {``."""
        out = []
        toks = self.toks
        i = 0
        inside_until = -1
        while i < len(toks):
            if toks[i].text == "{" and i > inside_until:
                j = i - 1
                while j >= 0 and toks[j].text in ("const", "noexcept",
                                                  "override", "final"):
                    j -= 1
                if j >= 0 and toks[j].text == ")" and toks[j] is not None \
                        and j in self.match:
                    op = self.match[j]
                    k = op - 1
                    if k >= 0 and re.match(r"^[A-Za-z_]\w*$", toks[k].text) \
                            and toks[k].text not in self._NOT_FN:
                        close = self.match.get(i)
                        if close is not None:
                            out.append((toks[k].text, i, close, toks[k].line))
                            inside_until = close
            i += 1
        return out

    def func_at(self, idx: int) -> str:
        for name, op, close, _ln in self.functions:
            if op <= idx <= close:
                return name
        return "<toplevel>"


# -- pass: gil_region ---------------------------------------------------------

def _pass_gil_region(cf: _CFile) -> List[Finding]:
    findings: List[Finding] = []
    toks = cf.toks
    in_region_since: Optional[int] = None
    seen_in_region: Set[str] = set()
    for i, t in enumerate(toks):
        if t.text == "Py_BEGIN_ALLOW_THREADS":
            in_region_since = i
            seen_in_region = set()
            continue
        if t.text in ("Py_END_ALLOW_THREADS", "Py_BLOCK_THREADS"):
            in_region_since = None
            continue
        if t.text == "Py_UNBLOCK_THREADS":
            in_region_since = i
            seen_in_region = set()
            continue
        if in_region_since is None:
            continue
        name = t.text
        if not _PYAPI_RE.match(name) or name in GIL_FREE_ALLOWLIST:
            continue
        if name in seen_in_region:
            continue  # one finding per API name per region
        seen_in_region.add(name)
        if cf.suppressed("gil_region", t.line):
            continue
        func = cf.func_at(i)
        findings.append(Finding(
            "gil_region", cf.relpath, t.line, f"{func}:{name}",
            f"CPython API {name} used inside a Py_BEGIN_ALLOW_THREADS "
            f"region in {func} (the GIL is NOT held there; allowlist or "
            f"re-acquire with Py_BLOCK_THREADS)",
        ))
    return findings


# -- shared leak engine (buffer_release / refcount_escape) --------------------

class _Tracked:
    __slots__ = ("var", "kind", "line", "exempt_span", "origin")

    def __init__(self, var, kind, line, exempt_span, origin):
        self.var = var
        self.kind = kind            # "buffer" | "alloc"
        self.line = line
        self.exempt_span = exempt_span  # (lo, hi) token idx or None
        self.origin = origin        # allocator name


def _call_args(cf: _CFile, open_idx: int) -> List[List[Tok]]:
    """Top-level comma-split argument token lists of a call."""
    close = cf.match.get(open_idx)
    if close is None:
        return []
    args: List[List[Tok]] = [[]]
    depth = 0
    for i in range(open_idx + 1, close):
        t = cf.toks[i]
        if t.text in "([{":
            depth += 1
        elif t.text in ")]}":
            depth -= 1
        if t.text == "," and depth == 0:
            args.append([])
        else:
            args[-1].append(t)
    return [a for a in args if a]


def _amp_base(arg: List[Tok]) -> Optional[str]:
    """``&ident`` or ``&ident[...]`` -> ident (an lvalue the caller owns)."""
    if len(arg) >= 2 and arg[0].text == "&" \
            and re.match(r"^[A-Za-z_]\w*$", arg[1].text):
        if len(arg) == 2 or arg[2].text == "[":
            return arg[1].text
    return None


def _enclosing_if_guard(cf: _CFile, idx: int,
                        fstart: int) -> Optional[Tuple[int, int]]:
    """If token idx sits inside an ``if (...)`` condition, return the
    span of that if's BODY (guard block: acquisition-failure returns in
    there are exempt)."""
    toks = cf.toks
    # walk back over enclosing open parens
    depth = 0
    j = idx
    while j > fstart:
        t = toks[j].text
        if t == ")":
            depth += 1
        elif t == "(":
            if depth == 0:
                if j - 1 >= 0 and toks[j - 1].text == "if":
                    close = cf.match.get(j)
                    if close is None:
                        return None
                    body_start = close + 1
                    if body_start < len(toks) \
                            and toks[body_start].text == "{":
                        return (body_start, cf.match.get(body_start,
                                                         body_start))
                    # single statement body: to the next ';'
                    k = body_start
                    while k < len(toks) and toks[k].text != ";":
                        k += 1
                    return (body_start, k)
                return None
            depth -= 1
        j -= 1
    return None


def _null_guard_span(cf: _CFile, semi_idx: int,
                     var: str) -> Optional[Tuple[int, int]]:
    """``X = alloc(...); if (!X ...) { ... }`` -> the guard body span.
    Up to three simple statements may sit between the allocation and
    its guard (`t = PyList_AsTuple(k); Py_DECREF(k); if (!t) ...`)."""
    toks = cf.toks
    i = semi_idx + 1
    for _ in range(3):
        if i < len(toks) and toks[i].text == "if":
            break
        # skip one simple statement (no control flow)
        j = i
        while j < len(toks) and toks[j].text not in (";", "{", "}"):
            j += 1
        if j >= len(toks) or toks[j].text != ";":
            return None
        i = j + 1
    if i + 1 >= len(toks) or toks[i].text != "if" or toks[i + 1].text != "(":
        return None
    cond_close = cf.match.get(i + 1)
    if cond_close is None:
        return None
    cond = [t.text for t in toks[i + 2:cond_close]]
    negated = any(
        cond[k] == "!" and k + 1 < len(cond) and cond[k + 1] == var
        for k in range(len(cond))
    ) or any(
        cond[k] == var and k + 1 < len(cond) and cond[k + 1] == "=="
        and k + 2 < len(cond) and cond[k + 2] in ("NULL", "nullptr", "0")
        for k in range(len(cond))
    )
    if not negated:
        return None
    body_start = cond_close + 1
    if body_start < len(toks) and toks[body_start].text == "{":
        return (body_start, cf.match.get(body_start, body_start))
    k = body_start
    while k < len(toks) and toks[k].text != ";":
        k += 1
    return (body_start, k)


def _label_sections(cf: _CFile, fstart: int, fend: int) -> Dict[str, int]:
    """goto-label name -> token index of the label, within a function."""
    out: Dict[str, int] = {}
    toks = cf.toks
    for i in range(fstart, fend):
        if toks[i].text == ":" and i > fstart \
                and re.match(r"^[A-Za-z_]\w*$", toks[i - 1].text):
            # a label is `ident :` at statement start: previous
            # significant token is ; { } or a label's own colon
            prev = toks[i - 2].text if i - 2 >= fstart else "{"
            if prev in (";", "{", "}", ":"):
                # exclude ternary `? x :` and case labels
                if toks[i - 1].text not in ("default", "case", "public",
                                            "private", "protected"):
                    out[toks[i - 1].text] = i
    return out


def _section_releases(cf: _CFile, start: int, fend: int, var: str,
                      release_fns: Set[str]) -> bool:
    toks = cf.toks
    i = start
    while i < fend:
        if toks[i].text in release_fns and i + 1 < fend \
                and toks[i + 1].text == "(":
            close = cf.match.get(i + 1, i + 1)
            if any(toks[k].text == var for k in range(i + 2, close)):
                return True
        if toks[i].text == "delete" and i + 1 < fend \
                and toks[i + 1].text == var:
            return True
        i += 1
    return False


def _leak_engine(
    cf: _CFile, pass_id: str,
    acquire, release_fns: Set[str], what: str,
) -> List[Finding]:
    """Linear lexical scan per function: acquisitions must meet a
    release/transfer before any early return, or ride a goto whose
    label section releases them.  `acquire(cf, i)` returns
    (var, origin) when token i starts an acquisition."""
    findings: List[Finding] = []
    toks = cf.toks
    for fname, fopen, fclose, _defline in cf.functions:
        labels = _label_sections(cf, fopen, fclose)
        tracked: Dict[str, _Tracked] = {}
        i = fopen
        while i < fclose:
            t = toks[i]
            acq = acquire(cf, i, fopen)
            if acq is not None:
                var, origin, exempt = acq
                tracked[var] = _Tracked(var, pass_id, t.line, exempt, origin)
                i += 1
                continue
            # releases
            if t.text in release_fns and i + 1 < fclose \
                    and toks[i + 1].text == "(":
                close = cf.match.get(i + 1, i + 1)
                inner = {toks[k].text for k in range(i + 2, close)}
                for var in list(tracked):
                    if var in inner:
                        del tracked[var]
                i = close
                continue
            if t.text == "delete" and i + 1 < fclose \
                    and toks[i + 1].text in tracked:
                del tracked[toks[i + 1].text]
                i += 2
                continue
            # ownership transfers
            if t.text in TRANSFER_FNS and i + 1 < fclose \
                    and toks[i + 1].text == "(":
                close = cf.match.get(i + 1, i + 1)
                inner = {toks[k].text for k in range(i + 2, close)}
                for var in list(tracked):
                    if var in inner:
                        del tracked[var]
                i = close
                continue
            # plain move: `y = x ;`
            if t.text == "=" and i + 2 < fclose \
                    and toks[i + 1].text in tracked \
                    and toks[i + 2].text == ";":
                del tracked[toks[i + 1].text]
                i += 3
                continue
            if t.text == "goto" and i + 1 < fclose:
                label = toks[i + 1].text
                sec = labels.get(label)
                for var in list(tracked):
                    rec = tracked.pop(var)
                    if rec.exempt_span and \
                            rec.exempt_span[0] <= i <= rec.exempt_span[1]:
                        continue
                    if sec is not None and _section_releases(
                        cf, sec, fclose, var, release_fns
                    ):
                        continue
                    if cf.suppressed(pass_id, t.line):
                        continue
                    findings.append(Finding(
                        pass_id, cf.relpath, t.line, f"{fname}:{var}",
                        f"{what} `{var}` (from {rec.origin}, line "
                        f"{rec.line}) leaks on `goto {label}` in {fname}: "
                        f"the epilogue never releases it",
                    ))
                i += 2
                continue
            if t.text == "return":
                # everything mentioned in the return expression is
                # returned or transferred (`return Py_BuildValue("(NN)",
                # arena, offsets)`), not leaked
                k = i + 1
                ret_idents: Set[str] = set()
                while k < fclose and toks[k].text != ";":
                    ret_idents.add(toks[k].text)
                    k += 1
                for var in list(tracked):
                    rec = tracked[var]
                    if var in ret_idents:
                        del tracked[var]
                        continue
                    if rec.exempt_span and \
                            rec.exempt_span[0] <= i <= rec.exempt_span[1]:
                        continue
                    del tracked[var]
                    if cf.suppressed(pass_id, t.line):
                        continue
                    findings.append(Finding(
                        pass_id, cf.relpath, t.line, f"{fname}:{var}",
                        f"{what} `{var}` (from {rec.origin}, line "
                        f"{rec.line}) leaks on this early return in "
                        f"{fname}: no release on the path",
                    ))
                i += 1
                continue
            i += 1
    return findings


# -- pass: buffer_release -----------------------------------------------------

def _py_buffer_decls(cf: _CFile, fopen: int, fclose: int) -> Set[str]:
    """Names declared ``Py_buffer NAME`` (values, not pointers) in a
    function body; the scan starts a little before the body brace so
    parameter-list declarations count too."""
    out: Set[str] = set()
    for i in range(max(0, fopen - 40), fclose):
        if cf.toks[i].text == "Py_buffer" and i + 1 < fclose \
                and re.match(r"^[A-Za-z_]\w*$", cf.toks[i + 1].text):
            out.add(cf.toks[i + 1].text)
    return out


def _pass_buffer_release(cf: _CFile) -> List[Finding]:
    decls_cache: Dict[int, Set[str]] = {}

    def decls_for(fopen: int, fclose: int) -> Set[str]:
        if fopen not in decls_cache:
            decls_cache[fopen] = _py_buffer_decls(cf, fopen, fclose)
        return decls_cache[fopen]

    def acquire(cfile: _CFile, i: int, fstart: int):
        toks = cfile.toks
        t = toks[i]
        fspan = next(
            ((op, cl) for _n, op, cl, _l in cfile.functions
             if op <= i <= cl), None,
        )
        if fspan is None:
            return None
        if t.text == "PyObject_GetBuffer" and i + 1 < len(toks) \
                and toks[i + 1].text == "(":
            args = _call_args(cfile, i + 1)
            if len(args) >= 2:
                base = _amp_base(args[1])
                if base:
                    exempt = _enclosing_if_guard(cfile, i, fstart)
                    return (base, "PyObject_GetBuffer", exempt)
            return None
        if t.text in ("PyArg_ParseTuple", "PyArg_ParseTupleAndKeywords") \
                and i + 1 < len(toks) and toks[i + 1].text == "(":
            args = _call_args(cfile, i + 1)
            fmt = next(
                (a[0].text for a in args
                 if len(a) == 1 and a[0].text.startswith('"')), "",
            )
            if not any(code in fmt for code in ("y*", "s*", "w*", "z*")):
                return None
            declared = decls_for(*fspan)
            for a in args[1:]:
                base = _amp_base(a)
                if base and base in declared:
                    exempt = _enclosing_if_guard(cfile, i, fstart)
                    return (base, f"{t.text}(\"{fmt.strip(chr(34))}\")",
                            exempt)
        return None

    return _leak_engine(
        cf, "buffer_release", acquire, {"PyBuffer_Release"},
        "buffer-protocol view",
    )


# -- pass: refcount_escape ----------------------------------------------------

def _pass_refcount_escape(cf: _CFile) -> List[Finding]:
    def acquire(cfile: _CFile, i: int, fstart: int):
        toks = cfile.toks
        t = toks[i]
        # `var = ALLOC (` — plain local lvalue only (members excluded:
        # their ownership usually lives in a container with its own
        # cleanup, e.g. the codec Plan)
        if t.text in ALLOC_FNS and i >= 2 and i + 1 < len(toks) \
                and toks[i + 1].text == "(" and toks[i - 1].text == "=" \
                and re.match(r"^[A-Za-z_]\w*$", toks[i - 2].text) \
                and (i - 3 < 0 or toks[i - 3].text not in (".", "->")):
            var = toks[i - 2].text
            exempt = _enclosing_if_guard(cfile, i, fstart)
            if exempt is None:
                semi = i
                close = cfile.match.get(i + 1, i + 1)
                k = close
                while k < len(toks) and toks[k].text != ";":
                    k += 1
                semi = k
                exempt = _null_guard_span(cfile, semi, var)
            return (var, t.text, exempt)
        return None

    findings = _leak_engine(
        cf, "refcount_escape", acquire, RELEASE_FNS, "owned allocation",
    )
    # unguarded `new`: bad_alloc across the ctypes C ABI aborts the
    # process — native code must use std::nothrow and fail the call
    if cf.relpath.endswith((".cc", ".cpp")):
        toks = cf.toks
        for i, t in enumerate(toks):
            if t.text != "new":
                continue
            if i + 1 < len(toks) and toks[i + 1].text == "(":
                close = cf.match.get(i + 1, i + 1)
                inner = [toks[k].text for k in range(i + 2, close)]
                if "nothrow" in inner:
                    continue
            if cf.suppressed("refcount_escape", t.line):
                continue
            func = cf.func_at(i)
            tname = toks[i + 1].text if i + 1 < len(toks) else "?"
            findings.append(Finding(
                "refcount_escape", cf.relpath, t.line, f"{func}:new",
                f"unguarded `new {tname}` in {func}: a thrown bad_alloc "
                f"crosses the ctypes C ABI and aborts the process — use "
                f"`new (std::nothrow)` and fail the call",
            ))
    return findings


# -- driver -------------------------------------------------------------------

_PASS_FNS = {
    "gil_region": _pass_gil_region,
    "buffer_release": _pass_buffer_release,
    "refcount_escape": _pass_refcount_escape,
}


def run_passes(
    paths: Optional[Sequence[str]] = None,
    root: Optional[str] = None,
    passes: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the C-source passes over `paths` (default: every file under
    native/src/) and return findings with stable, de-duplicated keys."""
    root = root or _repo_root()
    paths = list(paths) if paths is not None else native_paths(root)
    passes = list(passes) if passes is not None else list(PASS_IDS)
    findings: List[Finding] = []
    for path in paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                src = fh.read()
        except OSError:
            continue
        cf = _CFile(path, rel, src)
        for pid in passes:
            fn = _PASS_FNS.get(pid)
            if fn is not None:
                findings.extend(fn(cf))
    return _dedup(findings)

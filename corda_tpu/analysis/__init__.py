"""Static concurrency-correctness suite (docs/static-analysis.md).

`python -m corda_tpu.analysis` lints the whole package with the passes
in :mod:`.astlint`, checks the findings against the pinned baseline in
``analysis_manifest.json`` (:mod:`.manifest`), and — unless asked not
to — runs the kernel-jaxpr lint (:mod:`.kernel_lint`).  A NEW finding
(one not in the baseline) fails tier-1 and `tools/lint.py`; the
baseline shrinks by fixing findings and re-pinning (`--pin`), never by
hand-editing.
"""
from .astlint import Finding, PASS_IDS, run_passes, lint_paths  # noqa: F401
from .clint import PASS_IDS as C_PASS_IDS  # noqa: F401
from .manifest import (  # noqa: F401
    ALL_PASS_IDS,
    MANIFEST_PATH,
    check_findings,
    load_manifest,
    pin_manifest,
    run_all_passes,
)

"""ASan/UBSan build-and-run gate for the native extensions
(docs/static-analysis.md).

The C-source lint (:mod:`.clint`) is lexical; this is the dynamic half:
build every native extension with ``-fsanitize=address`` or
``-fsanitize=undefined`` and execute the codec/pump differential parity
and fuzz suites — the same contracts tests/test_pumpcore.py pins —
under the instrumented binaries, with leak checking, so buffer
overflows, use-after-free, UB and native leaks surface as NAMED
findings instead of latent corruption.

Process shape: the instrumented .so cannot load into THIS process (an
ASan library requires the asan runtime to be the first loaded object),
so the runner spawns one CHILD python per sanitizer with
``CORDA_TPU_SANITIZE=<mode>`` (the native loader then builds/loads
``build/<name>.<mode>.so``) and, for asan, ``LD_PRELOAD=libasan``.
The child builds, runs the suites, triggers a recoverable leak check,
and writes a JSON report; the parent parses the sanitizer log files
into findings.

Exit codes (the CI contract):
  0  clean, OR classified skip (no compiler / no sanitizer runtime —
     a NOTICE, since the no-toolchain container is supported)
  1  sanitizer report / suite failure under the sanitizer
  2  usage / infrastructure error

Child exit codes: 0 ok, 2 suite assertion failed, 3 classified skip,
97 sanitizer hard error (ASAN_OPTIONS exitcode).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

from .astlint import _repo_root

SANITIZERS = ("asan", "ubsan")

_CHILD_TIMEOUT = 240
_HARD_ERROR_EXIT = 97

#: sanitizer-report classifiers -> finding kind
_REPORT_RES = (
    (re.compile(r"ERROR: AddressSanitizer:?\s+([-\w]+)"), "{0}"),
    (re.compile(r"ERROR: LeakSanitizer: detected memory leaks"), "leak"),
    (re.compile(r"runtime error:\s+(.+)"), "ub: {0}"),
    (re.compile(r"AddressSanitizer:?\s*DEADLYSIGNAL"), "deadly-signal"),
)


def _runtime_lib(mode: str) -> Optional[str]:
    """Resolve the sanitizer runtime shared object (ELF, not a linker
    script) for LD_PRELOAD, or None when the toolchain lacks it."""
    name = {"asan": "libasan.so", "ubsan": "libubsan.so"}[mode]
    for compiler in ("gcc", "g++"):
        if shutil.which(compiler) is None:
            continue
        try:
            out = subprocess.run(
                [compiler, f"-print-file-name={name}"],
                capture_output=True, text=True, timeout=30,
            ).stdout.strip()
        except (OSError, subprocess.TimeoutExpired):
            continue
        if not out or out == name:
            continue
        candidates = [out]
        d = os.path.dirname(out)
        if os.path.isdir(d):
            candidates += sorted(
                os.path.join(d, fn) for fn in os.listdir(d)
                if fn.startswith(name + ".")
            )
        for cand in candidates:
            try:
                with open(cand, "rb") as fh:
                    if fh.read(4) == b"\x7fELF":
                        return os.path.abspath(cand)
            except OSError:
                continue
    return None


def classify_skip(mode: str) -> Optional[str]:
    """Why this box cannot run `mode`, or None when it can."""
    if shutil.which("gcc") is None or shutil.which("g++") is None:
        return "no_compiler"
    if _runtime_lib(mode) is None:
        return f"no_{mode}_runtime"
    return None


# ---------------------------------------------------------------------------
# Child: build + run the suites under the instrumented extensions
# ---------------------------------------------------------------------------

#: built-in malformed decode corpus (mirrors test_pumpcore.MALFORMED);
#: tests/corpus/decode/*.bin extends it when present
BUILTIN_MALFORMED = [
    b"XX\x01\x00",
    b"CT\x01",
    b"CT\x01\x63",
    b"CT\x01\x04\x05abc",
    b"CT\x01\x05\x03ab",
    b"CT\x01\x09\x04",
    b"CT\x01\x03" + b"\x80" * 95,
    b"CT\x01\x03" + b"\x80" * 95 + b"\x01",
    b"CT\x01\x04" + b"\xff" * 8 + b"\x7f",
    b"CT\x01\x08\x03abc",
    b"CT\x01\x06\x02\x00",
    b"CT\x01" + bytes([6, 1]) * 150 + b"\x00",
]


def corpus_frames(root: Optional[str] = None) -> List[bytes]:
    """The committed malformed-frame regression corpus
    (tests/corpus/decode/*.bin), empty when absent."""
    root = root or _repo_root()
    d = os.path.join(root, "tests", "corpus", "decode")
    out: List[bytes] = []
    if os.path.isdir(d):
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".bin"):
                with open(os.path.join(d, fn), "rb") as fh:
                    out.append(fh.read())
    return out


def _suite_codec(counts: Dict[str, int]) -> None:
    """Differential fuzz: batch + single-shot codec vs the pure-Python
    reference, byte-for-byte, under the sanitizer."""
    import random

    from ..core.serialization import codec

    sys.path.insert(0, os.path.join(_repo_root(), "tests"))
    try:
        from test_pumpcore import _gen_value  # the shared generator
    except ImportError:  # corpus-only environments
        def _gen_value(rng, depth=0):
            return {"k": rng.randbytes(8), "n": rng.randint(-2**70, 2**70),
                    "l": [rng.random() > 0.5, None, "s" * rng.randint(0, 9)]}
    rng = random.Random(20260804)
    values = [_gen_value(rng) for _ in range(150)]
    frames = codec.serialize_many(values)
    for v, frame in zip(values, frames):
        ref = bytearray(codec._MAGIC)
        codec._encode(ref, v)
        assert bytes(frame) == bytes(ref), f"encode parity broke: {v!r}"
        assert codec.deserialize(bytes(frame)) == codec.deserialize_many(
            [bytes(frame)]
        )[0]
    counts["codec_roundtrips"] = len(values)


def _suite_malformed(counts: Dict[str, int]) -> None:
    """Replay the malformed-frame corpus against BOTH codec paths with
    error-taxonomy parity — under the sanitizer, a hostile frame must
    fail typed (with the SAME message the pure-Python decoder gives) or
    decode to the same value, never corrupt."""
    from ..core.serialization import codec
    from ..core.serialization.codec import SerializationError

    def native_outcome(frame):
        try:
            return ("ok", codec.deserialize(frame))
        except SerializationError as exc:
            return ("err", str(exc))

    def python_outcome(frame):
        data = bytes(frame)
        try:
            if data[: len(codec._MAGIC)] != codec._MAGIC:
                raise SerializationError(
                    "bad magic / unsupported format version"
                )
            value, pos = codec._decode(data, len(codec._MAGIC))
            if pos != len(data):
                raise SerializationError(
                    f"{len(data) - pos} trailing bytes"
                )
            return ("ok", value)
        except SerializationError as exc:
            return ("err", str(exc))

    frames = BUILTIN_MALFORMED + corpus_frames()
    for frame in frames:
        native = native_outcome(frame)
        python = python_outcome(frame)
        assert native == python, (
            f"taxonomy divergence on {frame!r}: {native!r} vs {python!r}"
        )
        try:
            many = ("ok", codec.deserialize_many([frame])[0])
        except SerializationError as exc:
            many = ("err", str(exc))
        assert many == native, f"batch divergence on {frame!r}"
    counts["malformed_frames"] = len(frames)


def _suite_pump(counts: Dict[str, int]) -> None:
    """Wire framing fuzz through the native pump primitives."""
    import random

    from ..messaging import pumpcore

    rng = random.Random(97)
    msgs = []
    for i in range(64):
        headers = {
            f"k{j}": "".join(rng.choice("abz0-:漢") for _ in range(
                rng.randint(0, 12)))
            for j in range(rng.randint(0, 5))
        }
        msgs.append((f"mid-{i}", rng.randint(0, 9), headers,
                     rng.randbytes(rng.randint(0, 512))))
    reply = pumpcore.frame_msgs(msgs, 0x81)
    parsed = pumpcore.parse_msgs(reply)
    assert [(m[0], m[1], m[2], bytes(m[3])) for m in parsed] == [
        (m[0], m[1], m[2], m[3]) for m in msgs
    ]
    items = [(f"q{i}", rng.randbytes(rng.randint(0, 256)),
              {"x-dest": f"d{i}"}) for i in range(64)]
    body = pumpcore.frame_send_many(items, 11)
    parsed_items = pumpcore.parse_send_many(body)
    assert [(q, bytes(p), h) for q, p, h in parsed_items] == items
    # header-only extraction over real + empty blobs (the bounds checks)
    from ..messaging.broker import _encode_headers

    blobs = [
        _encode_headers({"x-dest": "d1", "traceparent": "00-ab"}),
        _encode_headers({}),
        _encode_headers({"k": "v" * 64}),
    ]
    rows = pumpcore.parse_headers_many(blobs, ("x-dest", "traceparent"))
    assert rows[0] == ("d1", "00-ab") and rows[1] == (None, None)
    hints = ["h:sess-%d" % i for i in range(32)] + ["t:w3-x", None, "bad"]
    pumpcore.route_hints_many(hints, 4)
    # malformed wire frames must raise, not crash, under the sanitizer
    for bad in (b"", b"\x81", b"\x81\x00\x00\x00\x02\x00\x00",
                reply[:-3], body[:-1], b"\x81" + b"\xff" * 12):
        for fn in (pumpcore.parse_msgs, pumpcore.parse_send_many):
            try:
                fn(bad)
            except Exception:  # lint: allow(swallow) — any typed raise is the PASS verdict; a crash is what the sanitizer reports
                pass
    counts["pump_msgs"] = len(msgs) + len(items)


def _suite_native_misc(counts: Dict[str, int]) -> None:
    """Journal + batch hashing under the sanitizer (the other ctypes
    entry-point families in corda_native.so)."""
    import hashlib

    from .. import native

    msgs = [b"x" * n for n in (0, 1, 63, 64, 65, 127, 128, 1000)]
    assert native.sha256_many(msgs) == [
        hashlib.sha256(m).digest() for m in msgs
    ]
    assert native.sha512_many(msgs) == [
        hashlib.sha512(m).digest() for m in msgs
    ]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "j.log")
        j = native.NativeJournal(path, truncate=True)
        recs = [(1, b"alpha"), (2, b""), (1, b"b" * 300)]
        for t, b in recs:
            j.append(t, b)
        j.close()
        assert native.NativeJournal.scan(path) == recs
    counts["native_misc"] = len(msgs)


def _leak_check(report: Dict) -> None:
    """Trigger LeakSanitizer's recoverable check NOW (leak_check_at_exit
    is off: at interpreter exit every live Python object would count).
    Memory still reachable at this point is not a leak — only native
    allocations the extensions dropped without freeing report."""
    import ctypes

    try:
        fn = ctypes.CDLL(None).__lsan_do_recoverable_leak_check
    except (OSError, AttributeError):
        report["leak_check"] = "unavailable"
        return
    fn.restype = ctypes.c_int
    report["leak_check"] = "leaks" if fn() else "clean"


def run_child(mode: str, report_path: str) -> int:
    from .. import native

    report: Dict = {"mode": mode, "ok": False}
    status = native.build_all(sanitize=mode)
    report["build"] = status
    bad = [e for e, s in status.items() if not s["available"]]
    if bad:
        reason = status[bad[0]].get("reason") or "unknown"
        if reason.startswith(("no_compiler", "no_python_headers")):
            # genuinely-absent toolchain: the classified 0-with-notice
            # skip.  Anything else (compile_error under the sanitize
            # flags, load_error, missing_symbol) is a FAILURE — the
            # parent already proved compiler+runtime exist, so a gate
            # that skipped here would go silently green with no
            # sanitized code ever run
            report["skip"] = reason
            with open(report_path, "w") as fh:
                json.dump(report, fh)
            return 3
        report["error"] = (
            f"instrumented build failed: {bad[0]}: {reason}"
        )
        with open(report_path, "w") as fh:
            json.dump(report, fh)
        return 2
    counts: Dict[str, int] = {}
    try:
        _suite_codec(counts)
        _suite_malformed(counts)
        _suite_pump(counts)
        _suite_native_misc(counts)
    except AssertionError as exc:
        report["error"] = str(exc)
        with open(report_path, "w") as fh:
            json.dump(report, fh)
        return 2
    if mode == "asan":
        _leak_check(report)
    report["ok"] = True
    report["suites"] = counts
    with open(report_path, "w") as fh:
        json.dump(report, fh)
    return 0


# ---------------------------------------------------------------------------
# Detection canary: prove the harness catches a REAL bug end-to-end
# ---------------------------------------------------------------------------

_CANARY_SRC = {
    # one-past-the-end heap write: ASan's bread and butter
    "asan": """
#include <stdlib.h>
void corda_tpu_canary(void) {
    char *p = malloc(8);
    p[8] = 1;
    free(p);
}
""",
    # signed-integer overflow: UBSan's bread and butter
    "ubsan": """
int corda_tpu_canary_v = 2147483647;
void corda_tpu_canary(void) {
    corda_tpu_canary_v += 1;
}
""",
}


def self_test(mode: str, timeout: int = 120) -> Dict:
    """Compile a deliberately buggy snippet under `mode` and run it
    through the same child/report plumbing — the gate's own
    new-finding detection proof (the sanitizer analogue of the lint
    suite's synthetic violations).  status: detected | missed | skip."""
    skip = classify_skip(mode)
    if skip is not None:
        return {"mode": mode, "status": "skip", "skip_reason": skip}
    with tempfile.TemporaryDirectory(prefix="corda-tpu-canary-") as tmp:
        src = os.path.join(tmp, "canary.c")
        so = os.path.join(tmp, "canary.so")
        with open(src, "w") as fh:
            fh.write(_CANARY_SRC[mode])
        flags = {"asan": ["-fsanitize=address"],
                 "ubsan": ["-fsanitize=undefined"]}[mode]
        try:
            subprocess.run(
                ["gcc", "-shared", "-fPIC", "-g", "-O1", *flags,
                 "-o", so, src],
                check=True, capture_output=True, timeout=60,
            )
        except (OSError, subprocess.SubprocessError) as exc:
            return {"mode": mode, "status": "skip",
                    "skip_reason": f"canary_build_failed: {exc}"}
        log_base = os.path.join(tmp, mode)
        env = dict(os.environ)
        if mode == "asan":
            env["LD_PRELOAD"] = _runtime_lib("asan") or ""
            env["ASAN_OPTIONS"] = (
                f"exitcode={_HARD_ERROR_EXIT}:abort_on_error=0:"
                f"log_path={log_base}"
            )
        else:
            env["UBSAN_OPTIONS"] = (
                f"print_stacktrace=1:halt_on_error=0:log_path={log_base}"
            )
        code = (
            "import ctypes; "
            f"ctypes.CDLL({so!r}).corda_tpu_canary()"
        )
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], env=env, timeout=timeout,
                capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            return {"mode": mode, "status": "skip",
                    "skip_reason": "canary_timeout"}
        findings = _parse_logs(tmp, mode)
        for rx, kind_fmt in _REPORT_RES:
            m = rx.search(proc.stderr or "")
            if m and not findings:
                findings.append({"sanitizer": mode, "kind": "stderr",
                                 "log": "stderr",
                                 "line": m.group(0)[:200]})
        detected = bool(findings) or proc.returncode == _HARD_ERROR_EXIT
        return {"mode": mode, "status": "detected" if detected else
                "missed", "findings": findings,
                "child_exit": proc.returncode}


# ---------------------------------------------------------------------------
# Parent: orchestrate children, parse reports into findings
# ---------------------------------------------------------------------------

def _parse_logs(log_dir: str, mode: str) -> List[Dict]:
    findings: List[Dict] = []
    if not os.path.isdir(log_dir):
        return findings
    for fn in sorted(os.listdir(log_dir)):
        if not fn.startswith(mode + "."):
            continue
        try:
            with open(os.path.join(log_dir, fn), errors="replace") as fh:
                text = fh.read()
        except OSError:
            continue
        seen: set = set()
        summary = ""
        for line in text.splitlines():
            m = re.search(r"SUMMARY:\s*(.+)", line)
            if m:
                summary = m.group(1)[:200]
            for rx, kind_fmt in _REPORT_RES:
                m = rx.search(line)
                if m:
                    kind = kind_fmt.format(*m.groups()) if m.groups() \
                        else kind_fmt
                    if kind not in seen:
                        seen.add(kind)
                        findings.append({
                            "sanitizer": mode, "kind": kind,
                            "log": fn, "line": line.strip()[:200],
                        })
        for f in findings:
            f.setdefault("summary", summary)
    return findings


def run_one(mode: str, repo_root: Optional[str] = None,
            timeout: int = _CHILD_TIMEOUT) -> Dict:
    """Build + run one sanitizer mode in a child process.  Returns
    {"mode", "status": clean|findings|skip|error, "findings": [...],
    "skip_reason", "report": child json}."""
    repo_root = repo_root or _repo_root()
    skip = classify_skip(mode)
    if skip is not None:
        return {"mode": mode, "status": "skip", "skip_reason": skip,
                "findings": []}
    with tempfile.TemporaryDirectory(prefix=f"corda-tpu-{mode}-") as tmp:
        report_path = os.path.join(tmp, "report.json")
        log_base = os.path.join(tmp, mode)
        env = dict(os.environ)
        env["CORDA_TPU_SANITIZE"] = mode
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("CORDA_TPU_NATIVE_CODEC", None)
        env.pop("CORDA_TPU_PUMP_NATIVE", None)
        if mode == "asan":
            env["LD_PRELOAD"] = _runtime_lib("asan") or ""
            # pymalloc arenas hide object pointers from LeakSanitizer
            # (every interned string would report as a leak) and mask
            # small overflows from ASan's redzones — route CPython's
            # allocations through raw malloc under the sanitizer
            env["PYTHONMALLOC"] = "malloc"
            env["ASAN_OPTIONS"] = (
                f"detect_leaks=1:leak_check_at_exit=0:"
                f"exitcode={_HARD_ERROR_EXIT}:abort_on_error=0:"
                f"log_path={log_base}"
            )
            supp = os.path.join(tmp, "lsan.supp")
            with open(supp, "w") as fh:
                # interpreter-lifetime allocations (interned strings,
                # import machinery) are deliberately never freed
                fh.write("leak:_PyObject_\nleak:PyObject_Malloc\n"
                         "leak:libpython\nleak:python3\n")
            env["LSAN_OPTIONS"] = (
                f"suppressions={supp}:print_suppressions=0"
            )
        else:
            env["UBSAN_OPTIONS"] = (
                f"print_stacktrace=1:halt_on_error=0:log_path={log_base}"
            )
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "corda_tpu.analysis.sanitize",
                 "--child", mode, "--report", report_path],
                capture_output=True, text=True, timeout=timeout,
                env=env, cwd=repo_root,
            )
        except subprocess.TimeoutExpired:
            return {"mode": mode, "status": "error",
                    "skip_reason": "child_timeout", "findings": []}
        report = {}
        try:
            with open(report_path) as fh:
                report = json.load(fh)
        except (OSError, ValueError):
            pass
        findings = _parse_logs(tmp, mode)
        # stderr also carries reports when log_path misfires
        for rx, kind_fmt in _REPORT_RES:
            m = rx.search(proc.stderr or "")
            if m:
                kind = kind_fmt.format(*m.groups()) if m.groups() \
                    else kind_fmt
                if not any(f["kind"] == kind for f in findings):
                    findings.append({"sanitizer": mode, "kind": kind,
                                     "log": "stderr",
                                     "line": m.group(0)[:200]})
        if report.get("leak_check") == "leaks" and not any(
            f["kind"] == "leak" for f in findings
        ):
            findings.append({"sanitizer": mode, "kind": "leak",
                             "log": "lsan", "line": "recoverable leak "
                             "check reported leaks"})
        if proc.returncode == 3:
            return {"mode": mode, "status": "skip",
                    "skip_reason": report.get("skip", "unknown"),
                    "findings": findings, "report": report}
        if findings:
            return {"mode": mode, "status": "findings",
                    "findings": findings, "report": report,
                    "child_exit": proc.returncode}
        if proc.returncode != 0:
            return {"mode": mode, "status": "error",
                    "skip_reason": f"child_exit_{proc.returncode}",
                    "findings": [],
                    "report": report,
                    "stderr_tail": (proc.stderr or "")[-800:]}
        return {"mode": mode, "status": "clean", "findings": [],
                "report": report}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m corda_tpu.analysis.sanitize",
        description="build + run the native codec/pump parity suites "
                    "under ASan/UBSan (docs/static-analysis.md)",
    )
    ap.add_argument("--sanitizer", choices=(*SANITIZERS, "both"),
                    default="both")
    ap.add_argument("--timeout", type=int, default=_CHILD_TIMEOUT)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-test", action="store_true",
                    help="prove detection: compile a known-buggy snippet "
                         "and require the sanitizer to report it")
    ap.add_argument("--child", choices=SANITIZERS, help=argparse.SUPPRESS)
    ap.add_argument("--report", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.self_test:
        modes = SANITIZERS if args.sanitizer == "both" \
            else (args.sanitizer,)
        rc = 0
        results = []
        for m in modes:
            r = self_test(m, timeout=args.timeout)
            results.append(r)
            if r["status"] == "missed":
                print(f"sanitize[{m}] SELF-TEST FAILED: planted bug not "
                      f"reported", file=sys.stderr)
                rc = 1
            else:
                print(f"sanitize[{m}] self-test: {r['status']}"
                      + (f" ({r.get('skip_reason')})"
                         if r["status"] == "skip" else ""),
                      file=sys.stderr)
        if args.json:
            print(json.dumps({"ok": rc == 0, "results": results},
                             sort_keys=True, default=str))
        return rc

    if args.child:
        if not args.report:
            print("--child requires --report", file=sys.stderr)
            return 2
        return run_child(args.child, args.report)

    modes = SANITIZERS if args.sanitizer == "both" else (args.sanitizer,)
    results = [run_one(m, timeout=args.timeout) for m in modes]
    rc = 0
    for r in results:
        if r["status"] == "skip":
            print(f"sanitize[{r['mode']}]: SKIP ({r['skip_reason']}) — "
                  f"toolchain absent, not a failure", file=sys.stderr)
        elif r["status"] == "clean":
            print(f"sanitize[{r['mode']}]: PASS "
                  f"{json.dumps(r.get('report', {}).get('suites', {}))}",
                  file=sys.stderr)
        elif r["status"] == "findings":
            for f in r["findings"]:
                print(f"SANITIZER FINDING {r['mode']}:{f['kind']} "
                      f"[{f['log']}] {f['line']}", file=sys.stderr)
            rc = 1
        else:
            detail = r.get("report", {}).get("error") \
                or r.get("stderr_tail", "")[-400:]
            print(f"sanitize[{r['mode']}]: ERROR ({r.get('skip_reason')})"
                  f" {detail}", file=sys.stderr)
            rc = 1
    if args.json:
        print(json.dumps({"ok": rc == 0, "results": results},
                         sort_keys=True, default=str))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())

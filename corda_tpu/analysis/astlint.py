"""Stdlib-``ast`` lint passes for the concurrency invariants reviews
hand-checked in PRs 1–8 (docs/static-analysis.md).

Five passes, each cheap enough to run on every tier-1 run:

``guarded_by``
    A field declared ``# guarded-by: _lock`` (trailing comment on the
    statement that initialises it; comma-separated alternatives allowed,
    e.g. ``# guarded-by: _lock, _cv``) may only be WRITTEN lexically
    inside a ``with`` over a matching lock.  Works for ``self.x`` class
    fields and module-level globals; ``__init__``/``__new__`` writes and
    module-level (re)initialisation are exempt — no thread exists yet.

``blocking_under_lock``
    Calls that can block for unbounded time flagged lexically inside a
    held lock: ``.result()`` on futures, ``.get`` on queue-named
    receivers, ``sleep``, broker ``send``/``receive`` families, sqlite
    ``.commit`` on connection-named receivers, ``.join`` on
    thread-named receivers, and ``.wait``/``.wait_for`` on anything
    that is not the condition actually held (a cv wait on its OWN lock
    releases it; a wait on some other primitive holds the lock across
    the park).

``thread_daemon``
    Every ``threading.Thread(...)`` must pass explicit ``daemon=`` and
    ``name=`` — anonymous non-daemon threads are what wedge interpreter
    shutdown and make stack dumps unreadable.

``swallow``
    A bare/broad ``except`` that neither re-raises, nor references the
    bound exception, nor calls anything log-shaped silently destroys
    the only evidence of a concurrency bug.

``env_registry``
    Every ``CORDA_TPU_*`` literal read anywhere must be registered in
    :mod:`corda_tpu.analysis.envknobs` (default + doc reference) and
    documented in the docs/running-nodes.md knob table; stale registry
    entries (never read) are findings too.

Suppression: ``# lint: allow(pass_id)`` trailing the flagged line (or
on the line above), with a reason after the paren —
``# lint: allow(swallow) — probe failure is the signal itself``.
Findings carry a stable key (pass, path, symbol — no line numbers, so
unrelated edits don't churn the baseline) pinned in
``analysis_manifest.json``; see :mod:`corda_tpu.analysis.manifest`.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

PASS_IDS = (
    "guarded_by",
    "blocking_under_lock",
    "thread_daemon",
    "swallow",
    "env_registry",
    "atomic_write",
)

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*allow\(\s*([a-z_,\s]+?)\s*\)")
_GUARDED_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_][\w.]*(?:\s*,\s*[A-Za-z_][\w.]*)*)"
)
_KNOB_RE = re.compile(r"^CORDA_TPU_[A-Z0-9_]+$")

#: mutating container methods treated as writes by `guarded_by`
_MUTATORS = {
    "append", "appendleft", "add", "clear", "extend", "insert", "pop",
    "popleft", "remove", "discard", "update", "setdefault",
}

#: call names that count as "the exception was reported" for `swallow`
_LOG_NAMES = {
    "log", "debug", "info", "warning", "warn", "error", "exception",
    "critical", "emit", "announce", "print_exc", "print",
}


@dataclass(frozen=True)
class Finding:
    pass_id: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str  # stable identity within the file (no line numbers)
    message: str

    @property
    def key(self) -> str:
        return f"{self.pass_id}:{self.path}:{self.symbol}"

    def as_dict(self) -> Dict:
        return {
            "pass": self.pass_id, "path": self.path, "line": self.line,
            "symbol": self.symbol, "message": self.message,
            "key": self.key,
        }


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))


def lint_paths(root: Optional[str] = None) -> List[str]:
    """The lint target set: the whole corda_tpu package plus the
    top-level tools/ CLIs and bench.py (tests lint themselves by
    failing)."""
    root = root or _repo_root()
    out: List[str] = []
    for base in ("corda_tpu", "tools"):
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, base)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return sorted(out)


def _dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as 'a.b.c' (None when the chain
    bottoms out in a call/subscript — those aren't stable names)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _strip_self(dotted: str) -> str:
    return dotted[5:] if dotted.startswith("self.") else dotted


def _suffix_match(expr: str, annotation: str) -> bool:
    """`with self._broker._lock` matches annotations `_lock` and
    `_broker._lock` — segment-aligned suffix match, self-insensitive."""
    e = _strip_self(expr).split(".")
    a = _strip_self(annotation).split(".")
    return len(e) >= len(a) and e[-len(a):] == a


def _lockish(dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    last = dotted.split(".")[-1].lower().lstrip("_")
    return (
        "lock" in last or "mutex" in last
        or last in ("cv", "cond", "condition", "not_empty", "guard")
    )


class _FileCtx:
    """Parsed file + parent links + comment-derived tables."""

    def __init__(self, path: str, relpath: str, src: str):
        self.relpath = relpath
        self.tree = ast.parse(src, filename=path)
        self.lines = src.splitlines()
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.suppress: Dict[int, Set[str]] = {}
        self.guard_ann: Dict[int, List[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            if "#" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppress[i] = {
                    p.strip() for p in m.group(1).split(",") if p.strip()
                }
            m = _GUARDED_RE.search(line)
            if m:
                self.guard_ann[i] = [
                    p.strip() for p in m.group(1).split(",") if p.strip()
                ]

    def suppressed(self, pass_id: str, node: ast.AST) -> bool:
        for ln in (getattr(node, "lineno", 0), getattr(node, "lineno", 0) - 1):
            ids = self.suppress.get(ln)
            if ids and (pass_id in ids or "all" in ids):
                return True
        return False

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(
                anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                parts.append(anc.name)
        return ".".join(reversed(parts)) or "<module>"

    def lock_withs(self, node: ast.AST) -> List[str]:
        """Dotted names of lock-ish `with` items lexically holding
        `node`, stopping at the enclosing function boundary (a nested
        def under a with runs later, not under the lock)."""
        out: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(
                anc,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef),
            ):
                break
            if isinstance(anc, ast.With):
                for item in anc.items:
                    d = _dotted(item.context_expr)
                    if _lockish(d):
                        out.append(d)
        return out

    def annotation_for(self, node: ast.AST) -> Optional[List[str]]:
        """guarded-by annotation attached to any line of `node`."""
        start = getattr(node, "lineno", None)
        if start is None:
            return None
        end = getattr(node, "end_lineno", None) or start
        for ln in range(start, end + 1):
            if ln in self.guard_ann:
                return self.guard_ann[ln]
        return None


# -- pass: guarded_by ---------------------------------------------------------

def _write_targets(node: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(dotted-target, node) pairs for assignment-like statements."""
    out: List[Tuple[str, ast.AST]] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    else:
        return out
    for t in targets:
        base = t
        while isinstance(base, (ast.Subscript, ast.Starred)):
            base = base.value
        d = _dotted(base)
        if d:
            out.append((d, node))
    return out


def _pass_guarded_by(ctx: _FileCtx) -> List[Finding]:
    findings: List[Finding] = []
    if not ctx.guard_ann:
        return findings

    def check_scope(scope: ast.AST, guarded: Dict[str, List[str]],
                    owner: str, is_field: bool) -> None:
        """Flag unguarded writes to `guarded` names inside `scope`."""
        declared_nodes = set()
        for node in ast.walk(scope):
            if ctx.annotation_for(node) and isinstance(
                node, (ast.Assign, ast.AugAssign, ast.AnnAssign)
            ):
                declared_nodes.add(node)
        for node in ast.walk(scope):
            hits: List[str] = []
            for d, stmt in _write_targets(node):
                name = _strip_self(d) if is_field else d
                if (is_field == d.startswith("self.")) and name in guarded:
                    hits.append(name)
            if isinstance(node, ast.Call):
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in _MUTATORS):
                    d = _dotted(fn.value)
                    if d:
                        name = _strip_self(d) if is_field else d
                        if ((is_field == d.startswith("self."))
                                and name in guarded):
                            hits.append(name)
            if not hits or node in declared_nodes:
                continue
            func = ctx.enclosing_function(node)
            if func is None:
                continue  # module-level (re)init: no threads yet
            if is_field and func.name in ("__init__", "__new__"):
                continue
            if ctx.suppressed("guarded_by", node):
                continue
            held = ctx.lock_withs(node)
            for name in hits:
                locks = guarded[name]
                if any(_suffix_match(h, lk) for h in held for lk in locks):
                    continue
                findings.append(Finding(
                    "guarded_by", ctx.relpath, node.lineno,
                    f"{owner}.{name}@{ctx.qualname(node)}",
                    f"write to {owner}.{name} (guarded-by: "
                    f"{', '.join(locks)}) outside `with "
                    f"{locks[0]}` in {ctx.qualname(node)}",
                ))
    # class fields
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded: Dict[str, List[str]] = {}
        for node in ast.walk(cls):
            ann = ctx.annotation_for(node)
            if not ann:
                continue
            for d, _stmt in _write_targets(node):
                if d.startswith("self."):
                    guarded[_strip_self(d)] = ann
        if guarded:
            check_scope(cls, guarded, cls.name, is_field=True)
    # module globals
    guarded_globals: Dict[str, List[str]] = {}
    for node in ctx.tree.body:
        ann = ctx.annotation_for(node)
        if not ann:
            continue
        for d, _stmt in _write_targets(node):
            if "." not in d:
                guarded_globals[d] = ann
    if guarded_globals:
        check_scope(ctx.tree, guarded_globals, ctx.relpath.rsplit("/", 1)[-1],
                    is_field=False)
    return findings


# -- pass: blocking_under_lock ------------------------------------------------

def _contains(dotted: Optional[str], *needles: str) -> bool:
    if not dotted:
        return False
    segs = dotted.lower().split(".")
    return any(n in seg for seg in segs for n in needles)


def _classify_blocking(node: ast.Call, held: List[str]) -> Optional[str]:
    fn = node.func
    d = _dotted(fn)
    if d in ("time.sleep", "sleep") or (d and d.endswith(".sleep")):
        return "sleep() under a held lock"
    if not isinstance(fn, ast.Attribute):
        return None
    recv = _dotted(fn.value)
    attr = fn.attr
    if attr == "result":
        return "future .result() under a held lock"
    last = (recv or "").split(".")[-1].lower().lstrip("_")
    if (
        attr == "get" and (last.endswith("queue") or last == "q")
        and not node.args
        and all(k.arg in ("block", "timeout") for k in node.keywords)
    ):
        # the blocking Queue.get signature only — `d.get(key, default)`
        # on a queue-named dict is a registry lookup
        return "queue .get() under a held lock"
    if attr in ("send", "send_many", "receive", "receive_many") and _contains(
        recv, "broker"
    ):
        return f"broker .{attr}() under a held lock"
    if attr == "commit" and _contains(recv, "conn", "db", "sql"):
        return "db .commit() under a held lock"
    if attr == "join" and _contains(recv, "thread", "worker", "monitor",
                                    "proc"):
        return "thread .join() under a held lock"
    if attr in ("wait", "wait_for") and recv is not None:
        if any(_suffix_match(h, _strip_self(recv))
               or _suffix_match(recv, _strip_self(h)) for h in held):
            return None  # cv wait on the lock actually held: it releases
        if last in ("cv", "cond", "condition", "not_empty"):
            # a condition owned by the same object as a held lock almost
            # certainly WRAPS that lock (`Condition(self._lock)`), and
            # waiting releases it; a cv owned by a DIFFERENT object parks
            # while the held lock stays held
            owner = ".".join(recv.split(".")[:-1])
            if any(owner == ".".join(h.split(".")[:-1]) for h in held):
                return None
        return (f"{recv}.{attr}() parks while holding an unrelated lock "
                f"({', '.join(held)})")
    return None


def _pass_blocking(ctx: _FileCtx) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        held = ctx.lock_withs(node)
        if not held:
            continue
        msg = _classify_blocking(node, held)
        if msg is None or ctx.suppressed("blocking_under_lock", node):
            continue
        d = _dotted(node.func) or getattr(node.func, "attr", "?")
        findings.append(Finding(
            "blocking_under_lock", ctx.relpath, node.lineno,
            f"{ctx.qualname(node)}:{d}",
            f"{msg} (in {ctx.qualname(node)}, holding {', '.join(held)})",
        ))
    return findings


# -- pass: thread_daemon ------------------------------------------------------

def _pass_thread_daemon(ctx: _FileCtx) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None or not (d == "Thread" or d.endswith(".Thread")):
            continue
        kw = {k.arg for k in node.keywords}
        if None in kw:  # **kwargs splat: can't see inside
            continue
        missing = [k for k in ("daemon", "name") if k not in kw]
        if not missing or ctx.suppressed("thread_daemon", node):
            continue
        findings.append(Finding(
            "thread_daemon", ctx.relpath, node.lineno,
            f"{ctx.qualname(node)}",
            f"threading.Thread without explicit {' and '.join(missing)}= "
            f"in {ctx.qualname(node)}",
        ))
    return findings


# -- pass: swallow ------------------------------------------------------------

def _broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [_dotted(e) for e in t.elts]
    else:
        names = [_dotted(t)]
    return any(
        n and n.split(".")[-1] in ("Exception", "BaseException")
        for n in names
    )


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    exc_name = handler.name
    for node in handler.body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Raise):
                return True
            if (exc_name and isinstance(sub, ast.Name)
                    and sub.id == exc_name
                    and isinstance(sub.ctx, ast.Load)):
                return True
            if isinstance(sub, ast.Call):
                fn = sub.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None
                )
                if name in _LOG_NAMES:
                    return True
            if isinstance(sub, ast.Attribute) and sub.attr == "exc_info":
                return True
    return False


def _pass_swallow(ctx: _FileCtx) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_handler(node) or _handler_reports(node):
            continue
        if ctx.suppressed("swallow", node):
            continue
        what = _dotted(node.type) if node.type is not None else "bare"
        findings.append(Finding(
            "swallow", ctx.relpath, node.lineno,
            f"{ctx.qualname(node)}:{what}",
            f"broad `except {what}` swallows the exception silently "
            f"(no re-raise, no log/emit, exception unused) in "
            f"{ctx.qualname(node)}",
        ))
    return findings


# -- pass: env_registry -------------------------------------------------------

def _knob_literals(ctx: _FileCtx) -> List[Tuple[str, int]]:
    """CORDA_TPU_* literals used in read/write positions: call args,
    keyword values AND names, subscripts, comparisons — but not
    docstrings/comments."""
    out: List[Tuple[str, int]] = []

    def lit(node) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _KNOB_RE.match(node.value):
            return node.value
        return None

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            for arg in node.args:
                v = lit(arg)
                if v:
                    out.append((v, arg.lineno))
            for kw in node.keywords:
                v = lit(kw.value)
                if v:
                    out.append((v, kw.value.lineno))
                if kw.arg and _KNOB_RE.match(kw.arg):
                    out.append((kw.arg, node.lineno))
        elif isinstance(node, ast.Subscript):
            v = lit(node.slice)
            if v:
                out.append((v, node.lineno))
        elif isinstance(node, ast.Compare):
            for cmp_node in [node.left, *node.comparators]:
                v = lit(cmp_node)
                if v:
                    out.append((v, cmp_node.lineno))
    return out


def _pass_env_registry(
    ctx: _FileCtx, reads: Dict[str, List[Tuple[str, int]]]
) -> List[Finding]:
    """Per-file half: record reads, flag unregistered knobs. The
    registry-level half (stale/undocumented) runs in run_passes."""
    from . import envknobs

    findings: List[Finding] = []
    if ctx.relpath == "corda_tpu/analysis/envknobs.py":
        # the registry's own registration literals are not READS — if
        # they counted, the stale-entry check could never fire
        return findings
    flagged: Set[str] = set()
    for knob, line in _knob_literals(ctx):
        reads.setdefault(knob, []).append((ctx.relpath, line))
        if knob in envknobs.KNOBS or knob in flagged:
            continue
        node_like = type("L", (), {"lineno": line})()
        if ctx.suppressed("env_registry", node_like):
            continue
        flagged.add(knob)
        findings.append(Finding(
            "env_registry", ctx.relpath, line, knob,
            f"env knob {knob} read here but not registered in "
            f"corda_tpu/analysis/envknobs.py (register with default + "
            f"doc reference)",
        ))
    return findings


def _env_registry_finalize(
    reads: Dict[str, List[Tuple[str, int]]], root: str
) -> List[Finding]:
    from . import envknobs

    findings: List[Finding] = []
    reg_path = "corda_tpu/analysis/envknobs.py"
    doc_cache: Dict[str, str] = {}

    def doc_text(rel: str) -> Optional[str]:
        if rel not in doc_cache:
            try:
                with open(os.path.join(root, rel)) as fh:
                    doc_cache[rel] = fh.read()
            except OSError:
                doc_cache[rel] = ""
        return doc_cache[rel]

    table = doc_text(envknobs.KNOB_TABLE_DOC)
    for name, knob in sorted(envknobs.KNOBS.items()):
        if name not in reads:
            findings.append(Finding(
                "env_registry", reg_path, 1, f"{name}:stale",
                f"registered env knob {name} is never read anywhere — "
                f"remove it or the dead code grew back",
            ))
        if f"`{name}`" not in table:
            # delimited match: a bare substring test would let
            # CORDA_TPU_LOCKCHECK ride on CORDA_TPU_LOCKCHECK_HOLD_MS's
            # row after its own was deleted
            findings.append(Finding(
                "env_registry", reg_path, 1, f"{name}:undocumented",
                f"env knob {name} missing from the "
                f"{envknobs.KNOB_TABLE_DOC} knob table",
            ))
        if not doc_text(knob.doc):
            findings.append(Finding(
                "env_registry", reg_path, 1, f"{name}:badref",
                f"env knob {name} doc reference {knob.doc!r} does not "
                f"exist",
            ))
    return findings


# -- pass: atomic_write -------------------------------------------------------

#: the one module allowed to touch os.replace/os.rename directly: every
#: other durable-write site must route through its fsync-before-rename
#: helpers (docs/robustness.md §7 "The durability contract")
_ATOMIC_HOME = "corda_tpu/utils/atomicfile.py"


def _pass_atomic_write(ctx: _FileCtx) -> List[Finding]:
    """Flag direct `os.replace`/`os.rename` usage outside
    utils/atomicfile.py. A bare rename publishes a file whose DATA may
    still be unwritten after a power cut (rename is metadata; the
    payload needs fsync first) — the torn-state class crashmc exists to
    catch. Deliberate low-level sites (e.g. an injectable io seam that
    carries its own fsync discipline) suppress with
    ``# lint: allow(atomic_write)`` and a reason."""
    if ctx.relpath == _ATOMIC_HOME:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Attribute):
            continue
        d = _dotted(node)
        if d not in ("os.replace", "os.rename"):
            continue
        if ctx.suppressed("atomic_write", node):
            continue
        findings.append(Finding(
            "atomic_write", ctx.relpath, node.lineno,
            f"{ctx.qualname(node)}:{d}",
            f"direct {d} in {ctx.qualname(node)} — route durable "
            f"writes through corda_tpu.utils.atomicfile "
            f"(fsync-before-rename), or suppress with a reason",
        ))
    return findings


# -- driver -------------------------------------------------------------------

_PASS_FNS = {
    "guarded_by": _pass_guarded_by,
    "blocking_under_lock": _pass_blocking,
    "thread_daemon": _pass_thread_daemon,
    "swallow": _pass_swallow,
    "atomic_write": _pass_atomic_write,
}


def run_passes(
    paths: Optional[Sequence[str]] = None,
    root: Optional[str] = None,
    passes: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the static passes over `paths` (default: the whole lint
    target set) and return findings with de-duplicated stable keys."""
    root = root or _repo_root()
    # registry-level env checks (stale/undocumented) only make sense on
    # a full run — an explicit path list would mark every unseen knob
    # stale
    full_run = paths is None
    paths = list(paths) if paths is not None else lint_paths(root)
    passes = list(passes) if passes is not None else list(PASS_IDS)
    findings: List[Finding] = []
    env_reads: Dict[str, List[Tuple[str, int]]] = {}
    for path in paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path) as fh:
                src = fh.read()
        except OSError:
            continue
        try:
            ctx = _FileCtx(path, rel, src)
        except SyntaxError as exc:
            findings.append(Finding(
                "swallow", rel, exc.lineno or 1, "syntax-error",
                f"file does not parse: {exc.msg}",
            ))
            continue
        for pid in passes:
            fn = _PASS_FNS.get(pid)
            if fn is not None:
                findings.extend(fn(ctx))
        if "env_registry" in passes:
            findings.extend(_pass_env_registry(ctx, env_reads))
    if "env_registry" in passes and full_run:
        findings.extend(_env_registry_finalize(env_reads, root))
    return _dedup(findings)


def _dedup(findings: List[Finding]) -> List[Finding]:
    """Identical keys (two findings on the same symbol) get #2, #3 …
    suffixes in (path, line) order so the baseline stays exact."""
    by_key: Dict[str, int] = {}
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.pass_id,
                                             f.symbol)):
        n = by_key.get(f.key, 0)
        by_key[f.key] = n + 1
        if n:
            f = Finding(f.pass_id, f.path, f.line, f"{f.symbol}#{n + 1}",
                        f.message)
        out.append(f)
    return out

"""CLI for the concurrency correctness suite.

    python -m corda_tpu.analysis                # lint + kernel gate
    python -m corda_tpu.analysis --no-kernel    # static passes only
    python -m corda_tpu.analysis --pin          # rewrite the baseline
    python -m corda_tpu.analysis --list         # dump current findings
    python -m corda_tpu.analysis path/to/file.py  # restrict (no gate)

Exit status: 0 = clean vs the pinned analysis_manifest.json, 1 = new
finding / kernel-lint violation, 2 = usage error.  `tools/lint.py` is
the same entry point runnable from any cwd.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import kernel_lint, manifest
from .manifest import ALL_PASS_IDS, run_all_passes


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint", description="concurrency correctness suite "
        "(docs/static-analysis.md)",
    )
    ap.add_argument("paths", nargs="*",
                    help="restrict to these files (skips the baseline "
                    "gate and registry-level checks; prints findings)")
    ap.add_argument("--pin", action="store_true",
                    help="re-run everything and rewrite the baseline "
                    "manifest (the diff is the review artifact)")
    ap.add_argument("--baseline", action="store_true",
                    help="check against the pinned baseline (the "
                    "default; spelled out for CI wiring)")
    ap.add_argument("--list", action="store_true",
                    help="print every current finding, accepted or not")
    ap.add_argument("--pass", dest="only_passes", action="append",
                    choices=ALL_PASS_IDS, metavar="PASS",
                    help="restrict to specific passes (repeatable)")
    ap.add_argument("--no-kernel", action="store_true",
                    help="skip the kernel-jaxpr lint (no jax import; "
                    "static passes only)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable result on stdout")
    ap.add_argument("--root",
                    help="lint an alternate repo root against THIS "
                    "package's pinned baseline (test/dev aid; "
                    "incompatible with --pin)")
    args = ap.parse_args(argv)

    if args.root and args.pin:
        print("lint: --pin cannot target an alternate --root (the "
              "baseline belongs to this package)", file=sys.stderr)
        return 2

    if args.paths:
        findings = run_all_passes(paths=args.paths, passes=args.only_passes)
        for f in findings:
            print(f"{f.path}:{f.line}: [{f.pass_id}] {f.message}")
        print(f"{len(findings)} finding(s) in {len(args.paths)} file(s)",
              file=sys.stderr)
        return 0

    if args.pin:
        findings = run_all_passes(passes=args.only_passes)
        kernels = None
        if not args.no_kernel:
            kernels = kernel_lint.kernel_counts()
        m = manifest.pin_manifest(findings=findings, kernels=kernels,
                                  passes=args.only_passes)
        counts = {p: len(keys) for p, keys in m["passes"].items()}
        print(f"pinned {sum(counts.values())} finding(s): "
              f"{json.dumps(counts, sort_keys=True)}", file=sys.stderr)
        if kernels is not None:
            print(f"pinned kernels: {json.dumps(kernels, sort_keys=True)}",
                  file=sys.stderr)
        return 0

    findings = run_all_passes(passes=args.only_passes, root=args.root)
    if args.list:
        for f in findings:
            print(f"{f.path}:{f.line}: [{f.pass_id}] {f.message}")
    result = manifest.check_findings(findings)
    kviol: List[dict] = []
    if not args.no_kernel:
        kviol = kernel_lint.check_all()
        result["kernel_violations"] = kviol
    for f in result["new"]:
        print(f"NEW FINDING {f['key']}\n  {f['path']}:{f['line']}: "
              f"{f['message']}", file=sys.stderr)
    for k in result["stale"]:
        print(f"stale baseline entry (fixed — re-pin to shrink): {k}",
              file=sys.stderr)
    for v in kviol:
        label = ("KERNEL-LINT improved" if v["kind"] == "improved"
                 else "KERNEL-LINT VIOLATION")
        print(f"{label} {v['kernel']}.{v.get('metric')}: "
              f"pinned={v['pinned']} measured={v['measured']} "
              f"({v['kind']})", file=sys.stderr)
    fatal = bool(result["new"]) or bool(
        manifest.fatal_kernel_violations(kviol)
    )
    ok = not fatal
    if args.json:
        print(json.dumps({"ok": ok, **result}, sort_keys=True))
    else:
        print(
            f"lint: {'PASS' if ok else 'FAIL'} — "
            f"{result['accepted']} accepted, {len(result['new'])} new, "
            f"{len(result['stale'])} stale"
            + ("" if args.no_kernel else f", {len(kviol)} kernel-lint "
               f"violation(s)"),
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

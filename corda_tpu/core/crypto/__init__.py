"""corda_tpu.core.crypto: crypto value types, scheme registry, host sign/verify.

The TPU batch-verification kernels live in corda_tpu.ops; this package is the
scalar host path and the semantic definition the kernels are tested against.
"""
from .composite import CompositeKey, CompositeSignaturesWithKeys, NodeAndWeight
from .crypto import (
    CryptoError,
    SignatureError,
    UnsupportedSchemeError,
    aggregate,
    aggregate_verify,
    bls_key_registered,
    bls_prove_possession,
    bls_register_key,
    derive_keypair,
    derive_keypair_from_entropy,
    do_sign,
    do_verify,
    entropy_to_keypair,
    find_signature_scheme,
    generate_keypair,
    is_operational,
    is_supported,
    is_valid,
    public_key_on_curve,
)
from .keys import KeyPair, PublicKey, SchemePrivateKey, SchemePublicKey
from .merkle import MerkleTree, MerkleTreeError, PartialMerkleTree
from .schemes import (
    BLS_BLS12381,
    COMPOSITE_KEY,
    DEFAULT_SIGNATURE_SCHEME,
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
    RSA_SHA256,
    SPHINCS256_SHA256,
    SUPPORTED_SIGNATURE_SCHEMES,
    SignatureScheme,
)
from .secure_hash import SecureHash, random_63_bit_value, secure_random_bytes
from .signing import (
    DigitalSignature,
    DigitalSignatureWithKey,
    MetaData,
    SignatureType,
    SignedData,
    TransactionSignature,
    sign_bytes,
)

__all__ = [
    "CompositeKey", "CompositeSignaturesWithKeys", "NodeAndWeight",
    "CryptoError", "SignatureError", "UnsupportedSchemeError",
    "aggregate", "aggregate_verify", "bls_key_registered",
    "bls_prove_possession", "bls_register_key",
    "derive_keypair", "derive_keypair_from_entropy", "do_sign", "do_verify",
    "entropy_to_keypair", "find_signature_scheme", "generate_keypair",
    "is_operational", "is_supported", "is_valid", "public_key_on_curve",
    "KeyPair", "PublicKey", "SchemePrivateKey", "SchemePublicKey",
    "MerkleTree", "MerkleTreeError", "PartialMerkleTree",
    "BLS_BLS12381",
    "COMPOSITE_KEY", "DEFAULT_SIGNATURE_SCHEME", "ECDSA_SECP256K1_SHA256",
    "ECDSA_SECP256R1_SHA256", "EDDSA_ED25519_SHA512", "RSA_SHA256",
    "SPHINCS256_SHA256", "SUPPORTED_SIGNATURE_SCHEMES", "SignatureScheme",
    "SecureHash", "random_63_bit_value", "secure_random_bytes",
    "DigitalSignature", "DigitalSignatureWithKey", "MetaData", "SignatureType",
    "SignedData", "TransactionSignature", "sign_bytes",
]

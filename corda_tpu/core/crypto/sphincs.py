"""SPHINCS-256 hash-based post-quantum signatures (scheme id 5).

Parity target: reference binds SPHINCS-256 to BouncyCastle PQC
(`core/.../crypto/Crypto.kt:134-151`, scheme "SPHINCS-256_SHA512").

STATUS: registry entry is live (id/code name preserved for metadata compat)
but the algorithm implementation is scheduled for a later milestone -- a
faithful SPHINCS-256 (WOTS+ hypertree over HORST few-time signatures) is
pure host-side code with no TPU interaction and does not gate any other
component. Until then all entry points raise UnsupportedSchemeError.
"""
from __future__ import annotations

from .crypto import UnsupportedSchemeError
from .keys import KeyPair, PublicKey, SchemePrivateKey

_MSG = "SPHINCS-256 implementation lands in a later milestone (see module docstring)"


def generate_keypair() -> KeyPair:
    raise UnsupportedSchemeError(_MSG)


def sign(private: SchemePrivateKey, data: bytes) -> bytes:
    raise UnsupportedSchemeError(_MSG)


def verify(public: PublicKey, signature: bytes, data: bytes) -> bool:
    raise UnsupportedSchemeError(_MSG)

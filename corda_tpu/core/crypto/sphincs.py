"""SPHINCS-256 hash-based post-quantum signatures (scheme id 5).

Parity target: the reference binds SPHINCS-256 to BouncyCastle PQC
(`core/src/main/kotlin/net/corda/core/crypto/Crypto.kt:134-151`, scheme
"SPHINCS-256_SHA512", ~41KB signatures, 128-bit post-quantum security).
This is a from-scratch implementation of the SPHINCS-256 construction
(Bernstein et al. 2015) with the reference parameter set:

    hypertree height h = 60 in d = 12 layers of height 5
    WOTS+  w = 16  ->  l1 = 64, l2 = 3, l = 67 chains
    HORST  t = 2^16 leaves, k = 32 revealed secrets, tau = 16

The primitive hashes are SHA-256 (chains/trees, accelerated through the
native batch hasher) and SHA-512 (message digest), with HMAC-SHA256 as
the PRF — byte-level interop with BouncyCastle's BLAKE/ChaCha instance is
NOT a goal (the wire format here is this framework's own); the structure,
parameter set and security argument are the parity surface.

Everything is deterministic from the secret seed: signing regenerates the
needed WOTS/HORST keys on demand (stateless, as SPHINCS requires).
Signatures are ~43KB; signing costs ~260k hashes (sub-second with the
native batcher), verification ~3k hashes.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import struct
from typing import List, Tuple

from .keys import KeyPair, SchemePrivateKey, SchemePublicKey

SCHEME = "SPHINCS-256_SHA512"

# Parameter set (SPHINCS-256).
N = 32                 # hash/secret size in bytes
TOTAL_HEIGHT = 60      # hypertree height
LAYERS = 12            # d
SUBTREE_HEIGHT = TOTAL_HEIGHT // LAYERS  # 5
WOTS_W = 16
WOTS_L1 = 64           # 256 bits / log2(16)
WOTS_L2 = 3            # checksum chains
WOTS_L = WOTS_L1 + WOTS_L2
HORST_TAU = 16
HORST_T = 1 << HORST_TAU
HORST_K = 32


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _prf(seed: bytes, label: bytes) -> bytes:
    return _hmac.new(seed, label, hashlib.sha256).digest()


def _native():
    from ... import native

    return native


def _split32(blob: bytes) -> List[bytes]:
    return [blob[i : i + N] for i in range(0, len(blob), N)]


def _mask(domain: bytes) -> bytes:
    return (domain * ((N // len(domain)) + 1))[:N]


def _tree_root_with_paths(leaves: List[bytes], indices: List[int],
                          domain: bytes) -> Tuple[bytes, List[List[bytes]]]:
    """Merkle root + auth path for EACH index, one pass over the levels
    (native pairwise hashing per level)."""
    native = _native()
    mask = _mask(domain)
    paths: List[List[bytes]] = [[] for _ in indices]
    idxs = list(indices)
    level = leaves
    while len(level) > 1:
        for p, idx in enumerate(idxs):
            paths[p].append(level[idx ^ 1])
            idxs[p] = idx >> 1
        masked = bytearray(b"".join(level))
        for off in range(0, len(masked), 2 * N):
            for i in range(N):
                masked[off + i] ^= mask[i]
        level = _split32(native.sha256_pairs(bytes(masked)))
    return level[0], paths


def _tree_root_from_path(leaf: bytes, index: int, path: List[bytes],
                         domain: bytes) -> bytes:
    node = leaf
    idx = index
    mask = _mask(domain)
    for sibling in path:
        left, right = (sibling, node) if idx & 1 else (node, sibling)
        left = bytes(a ^ b for a, b in zip(left, mask))
        node = _sha256(left + right)
        idx >>= 1
    return node


# ---------------------------------------------------------------------------
# WOTS+ (w = 16): addressed hash chains
# ---------------------------------------------------------------------------

def _chain(value: bytes, start: int, steps: int, pub_seed: bytes,
           addr: bytes, chain_index: int) -> bytes:
    for step in range(start, start + steps):
        value = _sha256(
            b"WOTS" + pub_seed + addr + struct.pack(">HH", chain_index, step)
            + value
        )
    return value


def _wots_digits(root: bytes) -> List[int]:
    """64 base-16 message digits + 3 checksum digits."""
    digits = []
    for byte in root:
        digits.append(byte >> 4)
        digits.append(byte & 0xF)
    checksum = sum(WOTS_W - 1 - d for d in digits)
    for _ in range(WOTS_L2):
        digits.append(checksum & 0xF)
        checksum >>= 4
    return digits


def _wots_sk(sk_seed: bytes, addr: bytes) -> List[bytes]:
    return [
        _prf(sk_seed, b"wots" + addr + struct.pack(">H", i))
        for i in range(WOTS_L)
    ]


def _wots_ends(sk_seed: bytes, pub_seed: bytes, addr: bytes) -> List[bytes]:
    return [
        _chain(sk, 0, WOTS_W - 1, pub_seed, addr, i)
        for i, sk in enumerate(_wots_sk(sk_seed, addr))
    ]


def _wots_sign(root: bytes, sk_seed: bytes, pub_seed: bytes,
               addr: bytes) -> List[bytes]:
    digits = _wots_digits(root)
    return [
        _chain(sk, 0, d, pub_seed, addr, i)
        for i, (sk, d) in enumerate(zip(_wots_sk(sk_seed, addr), digits))
    ]


def _wots_pk_from_sig(sig: List[bytes], root: bytes, pub_seed: bytes,
                      addr: bytes) -> bytes:
    digits = _wots_digits(root)
    ends = [
        _chain(part, d, WOTS_W - 1 - d, pub_seed, addr, i)
        for i, (part, d) in enumerate(zip(sig, digits))
    ]
    return _ltree(ends, pub_seed, addr)


def _ltree(nodes: List[bytes], pub_seed: bytes, addr: bytes) -> bytes:
    """Unbalanced binary compression of the 67 chain ends to one value."""
    level = 0
    nodes = list(nodes)
    while len(nodes) > 1:
        nxt = []
        for i in range(0, len(nodes) - 1, 2):
            nxt.append(
                _sha256(
                    b"LTRE" + pub_seed + addr + struct.pack(">HH", level, i)
                    + nodes[i] + nodes[i + 1]
                )
            )
        if len(nodes) & 1:
            nxt.append(nodes[-1])
        nodes = nxt
        level += 1
    return nodes[0]


# ---------------------------------------------------------------------------
# HORST (t = 2^16, k = 32): few-time signature at the hypertree leaf
# ---------------------------------------------------------------------------

def _horst_secrets(sk_seed: bytes, addr: bytes) -> List[bytes]:
    """65536 secrets from one seeded counter stream (native batch)."""
    native = _native()
    base = _prf(sk_seed, b"hrst" + addr)
    return native.sha256_many(
        [base + struct.pack(">I", i) for i in range(HORST_T)]
    )


def _horst_indices(digest: bytes) -> List[int]:
    """k=32 indices of tau=16 bits each from the 512-bit message digest."""
    return [
        struct.unpack(">H", digest[2 * i : 2 * i + 2])[0]
        for i in range(HORST_K)
    ]


def _horst_sign(digest: bytes, sk_seed: bytes, addr: bytes):
    secrets = _horst_secrets(sk_seed, addr)
    leaves = _native().sha256_many(secrets)
    indices = _horst_indices(digest)
    root, paths = _tree_root_with_paths(leaves, indices, b"HORS")
    return root, list(zip((secrets[i] for i in indices), paths))


def _horst_root_from_sig(digest: bytes, sig) -> bytes:
    roots = set()
    for idx, (secret, path) in zip(_horst_indices(digest), sig):
        leaf = _sha256(secret)
        roots.add(_tree_root_from_path(leaf, idx, path, b"HORS"))
    if len(roots) != 1:
        raise ValueError("inconsistent HORST authentication paths")
    return roots.pop()


# ---------------------------------------------------------------------------
# Hypertree
# ---------------------------------------------------------------------------

def _leaf_addr(layer: int, tree_index: int, leaf_index: int) -> bytes:
    return struct.pack(">BQH", layer, tree_index, leaf_index)


def _subtree_root_and_path(sk_seed: bytes, pub_seed: bytes, layer: int,
                           tree_index: int, leaf_index: int):
    leaves = [
        _ltree(
            _wots_ends(sk_seed, pub_seed, _leaf_addr(layer, tree_index, i)),
            pub_seed,
            _leaf_addr(layer, tree_index, i),
        )
        for i in range(1 << SUBTREE_HEIGHT)
    ]
    root, paths = _tree_root_with_paths(leaves, [leaf_index], b"TREE")
    return root, paths[0]


def generate_keypair(seed: bytes | None = None) -> KeyPair:
    import os as _os

    seed = seed if seed is not None else _os.urandom(N)
    if len(seed) != N:
        raise ValueError("seed must be 32 bytes")
    sk_seed = _prf(seed, b"sphincs-sk")
    pub_seed = _prf(seed, b"sphincs-pub")
    root, _ = _subtree_root_and_path(sk_seed, pub_seed, LAYERS - 1, 0, 0)
    public = SchemePublicKey(SCHEME, pub_seed + root)
    private = SchemePrivateKey(SCHEME, sk_seed + pub_seed + root)
    return KeyPair(public=public, private=private)


def _message_digest(randomizer: bytes, message: bytes) -> bytes:
    return hashlib.sha512(randomizer + message).digest()


_HORST_SIG_WORDS = HORST_K * (1 + HORST_TAU)
_LAYER_WORDS = WOTS_L + SUBTREE_HEIGHT
SIGNATURE_SIZE = N + 8 + _HORST_SIG_WORDS * N + LAYERS * _LAYER_WORDS * N


def sign(private: SchemePrivateKey, data: bytes) -> bytes:
    raw = private.encoded
    sk_seed, pub_seed = raw[:N], raw[N : 2 * N]
    # Deterministic randomizer + leaf selection (stateless SPHINCS).
    randomizer = _prf(sk_seed, b"rand" + data)
    digest = _message_digest(randomizer, data)
    leaf = int.from_bytes(
        _prf(sk_seed, b"leaf" + digest)[:8], "big"
    ) % (1 << TOTAL_HEIGHT)

    out = [randomizer, struct.pack(">Q", leaf)]

    indices = [
        (leaf >> (SUBTREE_HEIGHT * i)) & ((1 << SUBTREE_HEIGHT) - 1)
        for i in range(LAYERS)
    ]
    tree_indices = [leaf >> (SUBTREE_HEIGHT * (i + 1)) for i in range(LAYERS)]

    # HORST at the bottom: addressed by the full leaf position.
    horst_addr = struct.pack(">BQ", 255, leaf)
    horst_root, horst_sig = _horst_sign(digest, sk_seed, horst_addr)
    for secret, path in horst_sig:
        out.append(secret)
        out.extend(path)

    # Hypertree: WOTS at each layer signs the root below.
    to_sign = horst_root
    for layer in range(LAYERS):
        addr = _leaf_addr(layer, tree_indices[layer], indices[layer])
        out.extend(_wots_sign(to_sign, sk_seed, pub_seed, addr))
        root, path = _subtree_root_and_path(
            sk_seed, pub_seed, layer, tree_indices[layer], indices[layer]
        )
        out.extend(path)
        to_sign = root
    sig = b"".join(out)
    assert len(sig) == SIGNATURE_SIZE
    return sig


def verify(public: SchemePublicKey, signature: bytes, data: bytes) -> bool:
    try:
        raw = public.encoded
        pub_seed, expected_root = raw[:N], raw[N:]
        if len(signature) != SIGNATURE_SIZE:
            return False
        randomizer = signature[:N]
        (leaf,) = struct.unpack(">Q", signature[N : N + 8])
        if leaf >= 1 << TOTAL_HEIGHT:
            return False
        digest = _message_digest(randomizer, data)
        pos = N + 8
        horst_sig = []
        for _ in range(HORST_K):
            secret = signature[pos : pos + N]
            pos += N
            path = [
                signature[pos + i * N : pos + (i + 1) * N]
                for i in range(HORST_TAU)
            ]
            pos += HORST_TAU * N
            horst_sig.append((secret, path))
        current = _horst_root_from_sig(digest, horst_sig)

        indices = [
            (leaf >> (SUBTREE_HEIGHT * i)) & ((1 << SUBTREE_HEIGHT) - 1)
            for i in range(LAYERS)
        ]
        tree_indices = [
            leaf >> (SUBTREE_HEIGHT * (i + 1)) for i in range(LAYERS)
        ]
        for layer in range(LAYERS):
            addr = _leaf_addr(layer, tree_indices[layer], indices[layer])
            wots_sig = [
                signature[pos + i * N : pos + (i + 1) * N]
                for i in range(WOTS_L)
            ]
            pos += WOTS_L * N
            path = [
                signature[pos + i * N : pos + (i + 1) * N]
                for i in range(SUBTREE_HEIGHT)
            ]
            pos += SUBTREE_HEIGHT * N
            wots_pk = _wots_pk_from_sig(wots_sig, current, pub_seed, addr)
            current = _tree_root_from_path(
                wots_pk, indices[layer], path, b"TREE"
            )
        return current == expected_root
    except Exception:
        return False

"""Key model: scheme-tagged public/private keys with canonical encodings.

Unlike the reference, which leans on JCA `PublicKey`/`PrivateKey` objects and
X.509/PKCS#8 DER (`core/.../crypto/Crypto.kt:253-392`), keys here are small
immutable value objects carrying (scheme code name, canonical raw encoding).
Canonical encodings are chosen for batch-kernel friendliness:

  EDDSA_ED25519_SHA512 : 32-byte RFC 8032 compressed point / 32-byte seed
  ECDSA_SECP256K1/R1   : 33-byte SEC1 compressed point / 32-byte BE scalar
  RSA_SHA256           : DER SubjectPublicKeyInfo / PKCS#8 DER
  SPHINCS-256_SHA512   : scheme-defined (see sphincs.py)
  COMPOSITE            : canonical serialization of the key tree (composite.py)
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import FrozenSet, Iterable, NamedTuple


class PublicKey:
    """Base public-key type. Leaf keys are SchemePublicKey; CompositeKey nests."""

    scheme_code_name: str
    encoded: bytes

    # -- composite-aware helpers (reference CryptoUtils.kt:78-110) ----------
    @property
    def keys(self) -> FrozenSet["PublicKey"]:
        """The set of leaf keys underlying this key (singleton for leaves)."""
        return frozenset([self])

    def is_fulfilled_by(self, keys: Iterable["PublicKey"]) -> bool:
        ks = set(keys)
        return self in ks

    def contains_any(self, other_keys: Iterable["PublicKey"]) -> bool:
        return not self.keys.isdisjoint(set(other_keys))

    def to_base58_string(self) -> str:
        from .encodings import to_base58

        return to_base58(self.encoded)


@dataclass(frozen=True)
class SchemePublicKey(PublicKey):
    scheme_code_name: str
    encoded: bytes

    def __repr__(self) -> str:
        return f"{self.scheme_code_name}:{self.encoded.hex()[:16]}"


@dataclass(frozen=True)
class SchemePrivateKey:
    scheme_code_name: str
    encoded: bytes

    def __repr__(self) -> str:  # never print private material
        return f"<private {self.scheme_code_name}>"


class KeyPair(NamedTuple):
    public: PublicKey
    private: SchemePrivateKey

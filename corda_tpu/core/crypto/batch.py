"""Scheme-dispatching batch signature verification.

The batch-first replacement for the reference's one-at-a-time loop
(`core/.../transactions/TransactionWithSignatures.kt:58-62` ->
`Crypto.kt:535-541`). Signatures are bucketed by scheme: ed25519 and ECDSA
go to the JAX/TPU kernels (corda_tpu.ops) — but only when the resolved JAX
backend is an accelerator. Dispatch is backend-aware: on a CPU-only
deployment ed25519 buckets route to the native batched verifier (ONE
Pippenger multi-scalar multiplication per bucket, core/crypto/host_batch
+ native/src/ed25519_msm.cpp, ~50k sigs/s/core at 4k batch — ~7x the
OpenSSL loop, ~20x the reference's BouncyCastle loop, ~500x the portable
XLA kernel on CPU) and everything else to the host OpenSSL path in a
thread pool. Schemes without a device kernel always stay host-side.
Results come back as a positionally-aligned bool list, so callers keep
exact per-signature accept/reject semantics.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time as _time
from typing import List, Sequence, Tuple

from . import crypto
from ...utils import lockorder
from .keys import PublicKey
from .schemes import (
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
)

# Flip to False to force the host path (e.g. for differential testing).
USE_DEVICE_KERNELS = True

# Below this many signatures of one scheme the host path (OpenSSL via
# cryptography) beats device dispatch+compile amortization.
MIN_DEVICE_BATCH = 32

# Dispatch policy (VERDICT r3 #2 — backend-aware dispatch). The XLA
# fallback kernel on a *CPU* backend does ~90 ed25519 sigs/s while the
# host OpenSSL path in this same package does ~20k/s/core: the device
# kernels only ever win on a real accelerator. "auto" resolves the JAX
# backend once (lazily, on the first large bucket) and routes buckets to
# the host thread pool unless that backend is an accelerator; "device" /
# "host" force one side (tests, differential runs, calibration).
#   auto   -> accelerator backends use device kernels, CPU uses the host
#             pool; an explicitly configured mesh counts as opt-in device
#   device -> always use device kernels above MIN_DEVICE_BATCH
#   host   -> never use device kernels
DISPATCH = os.environ.get("CORDA_TPU_DISPATCH", "auto")
_ACCEL_BACKENDS = frozenset({"tpu", "gpu", "cuda", "rocm"})
_resolved_backend: str | None = None
_BACKEND_LOCK = lockorder.make_lock("batch._BACKEND_LOCK")

#: threads for the host OpenSSL path; OpenSSL verification via the
#: `cryptography` bindings is CPU-bound C code, so a small pool scales on
#: multi-core hosts and degrades to a plain loop on 1-core boxes
_HOST_POOL = None
_HOST_POOL_LOCK = lockorder.make_lock("batch._HOST_POOL_LOCK")
_HOST_POOL_MIN = 256  # below this a pool's overhead beats its speedup


def _backend() -> str:
    """The resolved JAX backend, cached for the process lifetime.

    Resolution can be expensive (accelerator tunnel init) and its answer
    cannot change within a process — JAX latches the backend on first
    use — so one probe is both cheap and sound. If JAX is unavailable
    the host path is the only path.

    TIME-BOUNDED: a half-dead accelerator tunnel can hang backend
    resolution inside the PJRT client init indefinitely (observed live:
    make_c_api_client never returns), which would freeze the first
    verify_batch call forever. The probe runs in a daemon thread with a
    deadline; on timeout the process latches "cpu" — a backend that
    cannot answer a probe cannot verify signatures either, and latching
    keeps the acceptance-rule pin stable for the process lifetime.
    """
    global _resolved_backend
    if _resolved_backend is None:
        # locked: two racing first calls must not each probe and latch
        # different answers (a timeout-latched "cpu" overwritten by a
        # late "tpu" would flip the acceptance-rule pin's basis)
        with _BACKEND_LOCK:
            if _resolved_backend is None:
                _resolved_backend = _resolve_backend_without_hanging()
    return _resolved_backend


#: last backend-probe outcome, for classified reporting (bench harness,
#: node startup): how the backend was resolved, not just what it is.
#: classification: "unresolved" (no probe yet) | "inline" (hang-free
#: in-process read) | "ok" (subprocess probe answered) | "timeout"
#: (attempt(s) hung until the per-attempt deadline) | "error" (probe
#: subprocess failed) | "budget-exhausted" (retry budget ran out).
_probe_status: dict = {
    "classification": "unresolved", "attempts": 0, "backend": None,
    "elapsed_s": 0.0,
}


def backend_probe_status() -> dict:
    """A snapshot of how (and whether) the JAX backend probe resolved —
    lets bench/node startup degrade a wedged accelerator tunnel to a
    CLASSIFIED skip ("timeout after 2 attempts / 40 s") instead of a
    silent cpu fallback or an indefinite hang."""
    return dict(_probe_status)


#: alternate PJRT init paths, tried round-robin across retry attempts: a
#: tunnel that wedges `default_backend()`'s client-cache path sometimes
#:  still answers a direct device enumeration (and vice versa)
_PROBE_SCRIPTS = (
    "import jax; print(jax.default_backend())",
    "import jax; print(jax.devices()[0].platform)",
)


def _resolve_backend_without_hanging() -> str:
    """Resolve the backend without risking THIS process's JAX state.

    A tunnel-backed platform can hang PJRT client creation forever
    (observed live: make_c_api_client never returns). Crucially, even a
    probe THREAD is unsafe: the hung thread holds JAX's backend-init
    lock, so every later array op in the process deadlocks behind it.
    When the process is pinned to CPU (tests, --jax-platform cpu nodes)
    resolution is hang-free and runs inline; otherwise the probe runs in
    a SUBPROCESS whose hang cannot poison us.

    BUDGETED (ROADMAP item 1): one hung attempt used to latch "cpu"
    outright, so a transiently wedged tunnel (libtpu still tearing down
    a previous owner's lock) permanently demoted a healthy accelerator.
    The probe now retries up to CORDA_TPU_BACKEND_PROBE_RETRIES attempts
    with capped backoff, alternating init paths, under a total
    CORDA_TPU_BACKEND_PROBE_BUDGET_S wall budget — and records a
    classification (see backend_probe_status) either way, so startup
    reports a classified skip instead of hanging or guessing."""
    try:
        import jax

        platforms = str(getattr(jax.config, "jax_platforms", "") or "")
    except Exception:
        _probe_status.update(classification="inline", backend="none")
        return "none"
    if platforms and all(
        p.strip() == "cpu" for p in platforms.split(",") if p.strip()
    ):
        backend = jax.default_backend()
        _probe_status.update(classification="inline", backend=backend)
        return backend
    # JAX already initialized IN-PROCESS (simm JIT, ops warm-up, mesh
    # code ran first): the hang hazard only exists before first backend
    # init, and a subprocess probe would CONTEND with this process for
    # the accelerator (libtpu holds an exclusive lock), fail or time
    # out, and silently latch "cpu" despite a healthy accelerator —
    # the round-5 31.4k vs 60.2k cpu-dispatch regression. Read the live
    # answer inline instead.
    try:
        from jax._src import xla_bridge as _xb

        if getattr(_xb, "_backends", None):
            backend = jax.default_backend()
            _probe_status.update(classification="inline", backend=backend)
            return backend
    except Exception:
        pass  # private surface moved: fall through to the subprocess
    env = dict(os.environ)
    if platforms:
        # the parent's IN-PROCESS pin (jax.config.update) is invisible
        # to a child; propagate it so the probe answers for the
        # configuration the parent actually runs
        env["JAX_PLATFORMS"] = platforms
    return _probe_backend_subprocess(env)


def _probe_backend_subprocess(env: dict) -> str:
    """The budgeted subprocess probe loop (split out so the retry/
    backoff/classification contract is directly testable): up to
    CORDA_TPU_BACKEND_PROBE_RETRIES attempts, alternating init scripts,
    each bounded by CORDA_TPU_BACKEND_PROBE_TIMEOUT, all under the
    CORDA_TPU_BACKEND_PROBE_BUDGET_S wall budget; always returns a
    backend name and leaves a classification in _probe_status."""
    attempt_timeout = float(
        os.environ.get("CORDA_TPU_BACKEND_PROBE_TIMEOUT", "20")
    )
    max_attempts = max(
        1, int(os.environ.get("CORDA_TPU_BACKEND_PROBE_RETRIES", "2"))
    )
    budget_s = float(
        os.environ.get("CORDA_TPU_BACKEND_PROBE_BUDGET_S", "45")
    )
    started = _time.monotonic()
    classification = "budget-exhausted"
    for attempt in range(max_attempts):
        remaining = budget_s - (_time.monotonic() - started)
        if remaining <= 0:
            classification = "budget-exhausted"
            break
        _probe_status["attempts"] = attempt + 1
        script = _PROBE_SCRIPTS[attempt % len(_PROBE_SCRIPTS)]
        try:
            out = subprocess.run(
                [sys.executable, "-c", script],
                capture_output=True, text=True, env=env,
                timeout=min(attempt_timeout, remaining),
            )
            lines = (out.stdout or "").strip().splitlines()
            backend = lines[-1].strip() if lines else ""
            # runtimes print banners; accept only a plausible backend name
            if backend in _ACCEL_BACKENDS or backend in ("cpu", "axon"):
                _probe_status.update(
                    classification="ok", backend=backend,
                    elapsed_s=_time.monotonic() - started,
                )
                return backend
            classification = "error"  # probe ran but answered nonsense
        except subprocess.TimeoutExpired:
            classification = "timeout"  # wedged tunnel: try the alt path
        # probe failure is ITSELF the signal: it is classified, surfaced
        # via backend_probe_status(), and answered with the cpu fallback
        except Exception:  # lint: allow(swallow)
            classification = "error"
        # capped backoff before the alternate init path — a tunnel mid-
        # teardown often frees within seconds, and anything longer is the
        # next attempt's timeout's problem
        if attempt + 1 < max_attempts:
            _time.sleep(min(5.0, 1.0 * (2 ** attempt)))
    _probe_status.update(
        classification=classification, backend="cpu",
        elapsed_s=_time.monotonic() - started,
    )
    # hung/failed/over-budget probe: the host paths always work
    return "cpu"


def _use_device_kernels() -> bool:
    if not USE_DEVICE_KERNELS:
        return False
    if DISPATCH == "device":
        return True
    if DISPATCH == "host":
        return False
    # auto: an explicitly configured (and not failed) mesh is a
    # deliberate routing decision — honour it even on the CPU backend
    # (that is exactly what the multichip dryrun exercises)
    if _MESH is not None and not _mesh_failed_once:
        return True
    return _backend() in _ACCEL_BACKENDS


# The ed25519 ACCEPTANCE RULE is pinned at the first dispatch and never
# changes for the process lifetime, even if the engine choice flips later
# (e.g. a mesh failure latching _mesh_failed_once turns _use_device_kernels
# False mid-process on a CPU backend). Device kernels + the OpenSSL loop
# verify cofactorless; the native MSM verifies cofactored (ZIP-215). A
# rule that flipped with the engine would accept/reject adversarial
# torsion-component signatures depending on WHEN a fallback happened —
# the replica-splitting hazard the per-deployment rule exists to prevent.
_pinned_rule: str | None = None  # "cofactorless" | "cofactored"
_RULE_LOCK = lockorder.make_lock("batch._RULE_LOCK")


def _ed25519_rule(use_device: bool | None = None) -> str:
    global _pinned_rule
    if _pinned_rule is None:
        # locked: verify_batch runs concurrently (batcher linger timer +
        # direct callers) and two racing first dispatches must not pin
        # different rules — the split this latch exists to prevent
        with _RULE_LOCK:
            if _pinned_rule is None:
                if use_device if use_device is not None \
                        else _use_device_kernels():
                    _pinned_rule = "cofactorless"
                else:
                    # the cofactored rule needs the native MSM engine: a
                    # replica whose extension failed to build (or with
                    # CORDA_TPU_HOST_BATCH=0) verifies through the
                    # OpenSSL loop, so its REAL rule is cofactorless —
                    # pinning "cofactored" here would misdescribe it and
                    # hide a rule split from its peers
                    from . import host_batch

                    _pinned_rule = (
                        "cofactored" if host_batch.available()
                        else "cofactorless"
                    )
    return _pinned_rule


def _host_verify_rows(items, idx, results) -> None:
    """Verify `idx` rows on the host path, GROUPED by scheme_number_id.

    A scheme the host path cannot serve — an id registered by a newer
    peer but not this build, a half-landed scheme whose verify raises —
    must cost ITS group a False verdict, never poison the whole
    submitted batch with an exception (the failure mode before this
    grouping: one unregistered-scheme row in a 4k-row flush threw out of
    verify_batch and failed every co-batched signature). Groups whose
    scheme resolves still ride the pooled path below."""
    groups: dict = {}
    for i in idx:
        name = getattr(items[i][0], "scheme_code_name", None)
        try:
            key = crypto.find_signature_scheme(name).scheme_number_id
        except crypto.UnsupportedSchemeError:
            key = ("unregistered", name)  # its own degraded group
        groups.setdefault(key, []).append(i)
    for key, rows in groups.items():
        if isinstance(key, tuple):  # unregistered id: no host path exists
            import logging

            logging.getLogger(__name__).warning(
                "unregistered scheme %r: %d rows verdict False "
                "(rest of the batch unaffected)", key[1], len(rows)
            )
            continue
        try:
            _host_verify_group(items, rows, results)
        except Exception:
            # group-scoped degradation: these rows stay False
            import logging

            logging.getLogger(__name__).exception(
                "host verification failed for scheme group %r "
                "(%d rows degraded to False)", key, len(rows)
            )


def _host_verify_group(items, idx, results) -> None:
    """Verify one scheme group's rows, in parallel when the group and
    the machine are big enough to amortise thread handoff."""
    global _HOST_POOL
    if len(idx) < _HOST_POOL_MIN or (os.cpu_count() or 1) < 2:
        for i in idx:
            key, sig, content = items[i]
            results[i] = crypto.is_valid(key, sig, content)
        return
    with _HOST_POOL_LOCK:
        # verify_batch runs concurrently (batcher linger timer + callers):
        # unsynchronized lazy init would leak a second pool's threads
        if _HOST_POOL is None:
            from concurrent.futures import ThreadPoolExecutor

            _HOST_POOL = ThreadPoolExecutor(
                max_workers=min(8, os.cpu_count() or 1),
                thread_name_prefix="corda-tpu-hostverify",
            )
    n_workers = _HOST_POOL._max_workers
    chunks = [idx[k::n_workers] for k in range(n_workers)]

    def run(chunk):
        for i in chunk:
            key, sig, content = items[i]
            results[i] = crypto.is_valid(key, sig, content)

    list(_HOST_POOL.map(run, [c for c in chunks if c]))

# Device-mesh routing (SURVEY §2.10 axis 2: shard the batch across chips).
# When a mesh is configured and a scheme bucket (ed25519 or either ECDSA
# curve) reaches MESH_MIN_BATCH, verification shards across the mesh via
# parallel.mesh's per-scheme kernel table instead of the single-device
# kernel. Opt-in: the verifier worker / node config calls
# configure_mesh() (see corda_tpu.verifier.__main__ --mesh-devices).
_MESH = None
_DEFAULT_MESH_MIN_BATCH = 2048
MESH_MIN_BATCH = _DEFAULT_MESH_MIN_BATCH
#: latched on the first mesh-path failure: a deterministically broken
#: mesh lowering must cost one attempt, not one per bucket
_mesh_failed_once = False


def configure_mesh(mesh, min_batch: int | None = None) -> None:
    """Route large ed25519 buckets through `mesh` (None disables and
    restores the default threshold)."""
    global _MESH, MESH_MIN_BATCH, _mesh_failed_once
    _MESH = mesh
    _mesh_failed_once = False  # a newly configured mesh gets a fresh try
    if min_batch is not None:
        MESH_MIN_BATCH = min_batch
    elif mesh is None:
        MESH_MIN_BATCH = _DEFAULT_MESH_MIN_BATCH


def configured_mesh():
    return _MESH

# scheme code name -> ecdsa_batch curve name
_ECDSA_CURVES = {
    ECDSA_SECP256K1_SHA256.scheme_code_name: "secp256k1",
    ECDSA_SECP256R1_SHA256.scheme_code_name: "secp256r1",
}


def verify_batch(
    items: Sequence[Tuple[PublicKey, bytes, bytes]],
) -> List[bool]:
    """items: (public_key, signature_bytes, content) triples -> bool per item.

    Buckets by scheme (the mixed-scheme dispatch, BASELINE.md): ed25519 and
    both ECDSA curves go to their device kernels when the bucket is large
    enough; everything else (RSA, small buckets) stays host-side.

    CompositeKey items (threshold multi-sig trees) are FLATTENED: each
    constituent (leaf key, leaf sig) pair joins the same scheme buckets as
    plain signatures, and the threshold tree is evaluated over the
    returned bitmask (BASELINE.md multi-sig config; semantics identical to
    `CompositeKey.verify_composite` — every constituent must verify AND
    the tree's weighted thresholds must be met). Nested-composite
    constituents keep the host path.

    Implemented as the back-to-back composition of the four staged
    phases below (plan → prehash → dispatch → collect) — the same
    functions the overlapped verification pipeline
    (corda_tpu.verifier.pipeline, docs/perf-pipeline.md) runs on
    separate stage threads. Run sequentially on one thread they ARE the
    synchronous path: CORDA_TPU_PIPELINE=0 changes nothing but which
    thread calls them.
    """
    return collect_plan(dispatch_plan(prehash_plan(plan_batch(items))))


class BatchPlan:
    """One verify batch flowing through the staged phases.

    Built by :func:`plan_batch` (decode/parse: composite flattening +
    scheme bucketing), advanced by :func:`prehash_plan` (the SHA-512
    host prehash, GIL-releasing native code) and :func:`dispatch_plan`
    (device kernel launches — asynchronous, nothing blocks on device
    results — plus the host verify engines), finished by
    :func:`collect_plan` (materialise device masks, evaluate composite
    threshold trees). The phases communicate ONLY through this object,
    which is what lets the pipeline engine hand it thread-to-thread."""

    __slots__ = (
        "items",          # the submitted (key, sig, content) triples
        "results",        # per-item verdicts (filled by collect)
        "flat",           # composite-flattened rows
        "flat_of_item",   # item idx -> flat row (None for composites)
        "composites",     # (item idx, CompositeKey, [rows], [leaf keys])
        "flat_results",   # per-flat-row verdicts
        "use_device", "rule", "ec_native",
        "buckets",        # scheme name -> [flat rows] (device-sized)
        "host_rows",      # flat rows for the OpenSSL loop
        "ed_host",        # flat rows for the native MSM engine
        "ec_host",        # curve kind -> [flat rows] for native ECDSA
        "split_device",   # opt-in: pipeline splits the device route
        "prepared",       # scheme name -> (kernel kwargs, n) [split route]
        "ed_prehash",     # (rows, (good, hs)) from host_batch.prehash_rows
        "pending",        # (kernel, idx, device mask, t0) to materialise
        "mesh",           # per-plan mesh override (MeshDispatcher stage)
        "mesh_min_batch",  # per-plan mesh threshold override
        "mesh_totals",    # scheme kind -> psum'd mesh-wide valid count
        "mesh_failed",    # an explicit plan mesh failed this dispatch
    )


def plan_batch(
    items: Sequence[Tuple[PublicKey, bytes, bytes]],
    split_device: bool = False,
    mesh=None,
    mesh_min_batch: int | None = None,
) -> BatchPlan:
    """Phase 1 — decode/parse: flatten composites and bucket every flat
    row by scheme and engine. Pure host work, no hashing, no device.

    ``split_device``: opt-in to the SPLIT device route (prepare on the
    prehash phase, asynchronous donated-buffer launch on dispatch,
    deferred materialisation on collect). Only the pipeline engine sets
    it — the sequential composition keeps today's exact call graph
    (ops.ed25519_verify_batch whole in the dispatch phase), so
    CORDA_TPU_PIPELINE=0 is byte-identical to the pre-pipeline path.

    ``mesh``: per-plan device-mesh override for the dispatch phase (the
    pipeline's MeshDispatcher stage sets it; see docs/perf-pipeline.md).
    Unlike the process-global `configure_mesh`, the override routes ONLY
    this plan's buckets, with its own `mesh_min_batch` threshold
    (default MESH_MIN_BATCH). With both left None the plan is bit-for-bit
    the pre-mesh plan — the kill switch reproduces today's call graph."""
    plan = BatchPlan()
    plan.items = items
    plan.split_device = split_device
    plan.mesh = mesh
    plan.mesh_min_batch = mesh_min_batch
    plan.mesh_totals = {}
    plan.mesh_failed = False
    n = len(items)
    plan.results = [False] * n
    plan.flat = []
    plan.flat_of_item = []
    plan.composites = []
    for i, (key, sig, content) in enumerate(items):
        if USE_DEVICE_KERNELS and _is_composite(key):
            from .composite import CompositeSignaturesWithKeys

            try:
                csigs = CompositeSignaturesWithKeys.deserialize(sig)
            except Exception:
                plan.flat_of_item.append(None)  # malformed blob -> False
                continue
            rows, leaf_keys = [], []
            for leaf_pub, leaf_sig in csigs.sigs:
                rows.append(len(plan.flat))
                leaf_keys.append(leaf_pub)
                plan.flat.append((leaf_pub, leaf_sig, content))
            plan.composites.append((i, key, rows, leaf_keys))
            plan.flat_of_item.append(None)
        else:
            plan.flat_of_item.append(len(plan.flat))
            plan.flat.append((key, sig, content))

    flat = plan.flat
    plan.flat_results = [False] * len(flat)
    # an explicit per-plan mesh is the same deliberate opt-in as a
    # configured global mesh: it routes this plan's buckets to device
    # kernels even on a CPU backend (the fake-device bit-identity runs)
    plan.use_device = _use_device_kernels() or mesh is not None
    # pinned for the process on first dispatch; the plan's own engine
    # choice is the hint so a mesh-dispatching pipeline pins the same
    # cofactorless rule configure_mesh would
    plan.rule = _ed25519_rule(plan.use_device)
    # the device kernels are cofactorless: a process pinned to the
    # cofactored rule (it started host-side) must keep ed25519 off them
    # even if the engine choice later flips to device
    ed_device = plan.use_device and plan.rule == "cofactorless"
    from . import ecdsa_host as ecdsa_host_mod

    plan.ec_native = ecdsa_host_mod.available()
    plan.buckets = {}
    plan.host_rows = []
    plan.ed_host = []  # ed25519 rows for the native MSM batch path
    plan.ec_host = {}  # curve kind -> [indices] for the native engine
    for i, (key, sig, content) in enumerate(flat):
        name = key.scheme_code_name
        is_ed = name == EDDSA_ED25519_SHA512.scheme_code_name
        is_ec = name in _ECDSA_CURVES
        if not _is_composite(key) and (
            (is_ed and ed_device) or (is_ec and plan.use_device)
        ):
            plan.buckets.setdefault(name, []).append(i)
        elif is_ed and not _is_composite(key):
            if plan.rule == "cofactored":
                plan.ed_host.append(i)  # native MSM, ZIP-215
            else:
                plan.host_rows.append(i)  # OpenSSL loop, cofactorless
        elif is_ec and not _is_composite(key) and plan.ec_native:
            # native batch engine (combs + batched inversions); the
            # acceptance rule is plain per-signature ECDSA with strict
            # DER — identical to the OpenSSL loop, so routing here at
            # any size cannot split verdicts
            plan.ec_host.setdefault(_ECDSA_CURVES[name], []).append(i)
        else:
            plan.host_rows.append(i)

    for name in list(plan.buckets):
        idx = plan.buckets[name]
        if len(idx) >= MIN_DEVICE_BATCH:
            continue
        if _mesh_would_serve(idx, mesh, mesh_min_batch):
            # the mesh shards this bucket itself at dispatch: its own
            # threshold (mesh_min_batch / MESH_MIN_BATCH) is the floor,
            # not the single-device MIN_DEVICE_BATCH — pruning here
            # would silently unroute a bucket the dispatcher promised
            # to shard
            continue
        del plan.buckets[name]
        # Undersized ECDSA buckets ride the native engine when
        # available (one ECDSA rule everywhere, so this is purely a
        # speed choice)
        if name in _ECDSA_CURVES and plan.ec_native:
            plan.ec_host.setdefault(_ECDSA_CURVES[name], []).extend(idx)
            continue
        # Undersized ed25519 buckets on an accelerator deployment
        # go to the per-signature OpenSSL loop (host_rows), NOT the
        # native MSM:
        # the device kernels verify cofactorless ([s]B == R + [h]A,
        # like OpenSSL) while the MSM verifies cofactored (ZIP-215).
        # The acceptance rule must be a DEPLOYMENT property — one
        # rule per deployment, never a batch-size accident — or an
        # adversarial torsion-component signature would verify or
        # fail depending on how the batcher happened to group it,
        # splitting notary replicas. CPU deployments (use_device
        # False) route every ed25519 row to the MSM, so they are
        # uniformly cofactored; accelerator deployments are
        # uniformly cofactorless. Mixed CPU/accelerator clusters
        # must pin CORDA_TPU_DISPATCH cluster-wide (docs/perf-host.md).
        plan.host_rows.extend(idx)

    plan.prepared = {}
    plan.ed_prehash = None
    plan.pending = []
    return plan


def _mesh_would_serve(idx, mesh=None, min_batch: int | None = None) -> bool:
    """Mirror of the dispatch-phase mesh routing condition, consulted at
    prehash time so the split host prep isn't wasted on a bucket the
    mesh will shard itself (shard_verify runs its own prepare).

    With an explicit per-plan `mesh` (the MeshDispatcher stage) the
    process-global mesh and its failure latch are irrelevant: the
    dispatcher owns its own latch and threshold."""
    if mesh is not None:
        floor = MESH_MIN_BATCH if min_batch is None else min_batch
        return len(idx) >= floor
    return (
        _MESH is not None
        and not _mesh_failed_once
        and len(idx) >= MESH_MIN_BATCH
    )


def _ed25519_split_route() -> bool:
    """Whether the ed25519 device bucket takes the SPLIT prehash/launch
    route (portable XLA kernel): prepare_batch on the prehash stage,
    an asynchronous donated-buffer kernel launch on the dispatch stage,
    materialisation on the collect stage. On the TPU backend the Pallas
    wrapper (ops.ed25519_batch._verify_batch_pallas) stays WHOLE in the
    dispatch phase: it owns its own chunked host/device overlap, the
    known-answer self-check, and the fast-mul/radix degradation ladder —
    splitting it here would bypass all three."""
    try:
        import jax

        return jax.default_backend() != "tpu"
    # lint: allow(swallow) — jax absent means no device route; bucket stays whole
    except Exception:
        return False


def prehash_plan(plan: BatchPlan) -> BatchPlan:
    """Phase 2 — SHA-512 host prehash. Every hash here is a native
    batched pass (corda_tpu.native) that releases the GIL, which is what
    lets the pipeline hash batch N+1 while batch N occupies the device
    (or the MSM engine). Covers the split ed25519 device route
    (prepare_batch: parse + SHA-512(R||A||M) mod L) and the native MSM
    engine's prehash (host_batch.prehash_rows)."""
    flat = plan.flat
    ed_name = EDDSA_ED25519_SHA512.scheme_code_name
    idx = plan.buckets.get(ed_name)
    if (
        idx is not None and plan.split_device
        and not _mesh_would_serve(
            idx, getattr(plan, "mesh", None),
            getattr(plan, "mesh_min_batch", None),
        )
        and _ed25519_split_route()
    ):
        from ... import ops

        kwargs, n_real = ops.ed25519_prepare_batch(
            [flat[i][0].encoded for i in idx],
            [flat[i][1] for i in idx],
            [flat[i][2] for i in idx],
        )
        plan.prepared[ed_name] = (kwargs, n_real)
    if plan.ed_host and plan.split_device:
        from . import host_batch

        if host_batch.available():
            rows = [
                (flat[i][0].encoded, flat[i][1], flat[i][2])
                for i in plan.ed_host
            ]
            plan.ed_prehash = (rows, host_batch.prehash_rows(rows))
    return plan


def dispatch_plan(plan: BatchPlan) -> BatchPlan:
    """Phase 3 — launch device work, run the host engines.

    Device buckets with prepared inputs are LAUNCHED asynchronously
    (JAX dispatch returns before the computation finishes; the donated
    s_ok buffer lets XLA alias the result) and recorded in
    `plan.pending` for the collect phase — nothing here blocks on a
    device result. Unprepared buckets (TPU Pallas ladder, mesh shards,
    ECDSA) and the host engines (native MSM, native ECDSA, the OpenSSL
    pool) run inside this phase; the native engines release the GIL, so
    they still overlap the next batch's prehash under the pipeline."""
    global _mesh_failed_once
    flat = plan.flat
    results = plan.flat_results
    plan_mesh = getattr(plan, "mesh", None)
    plan_min = getattr(plan, "mesh_min_batch", None)
    for name, idx in plan.buckets.items():
        is_ed = name == EDDSA_ED25519_SHA512.scheme_code_name
        kernel = (
            "ed25519.verify_batch" if is_ed
            else f"ecdsa.{_ECDSA_CURVES[name]}.verify_batch"
        )
        mask = None
        if _mesh_would_serve(idx, plan_mesh, plan_min):
            from ...parallel.mesh import shard_layout, shard_verify
            from ...utils import profiling

            pubs = [flat[i][0].encoded for i in idx]
            sigs = [flat[i][1] for i in idx]
            msgs = [flat[i][2] for i in idx]
            scheme_kind = "ed25519" if is_ed else _ECDSA_CURVES[name]
            mesh = plan_mesh if plan_mesh is not None else _MESH
            t0 = _time.perf_counter()
            try:
                mask, total = shard_verify(
                    mesh, scheme_kind, pubs, sigs, msgs, return_total=True
                )
                # the psum'd mesh-wide valid count, preserved for the
                # notary's uniqueness pre-check (docs/perf-pipeline.md)
                plan.mesh_totals[scheme_kind] = (
                    plan.mesh_totals.get(scheme_kind, 0) + total
                )
                try:
                    _, mesh_rows, _ = shard_layout(
                        mesh, scheme_kind, len(idx)
                    )
                # the ledger row still lands without its padding math
                # lint: allow(swallow) — telemetry must not fail dispatch
                except Exception:
                    mesh_rows = None
                profiling.record_dispatch(
                    kernel, _time.perf_counter() - t0,
                    scheme=name, rows=mesh_rows, real_rows=len(idx),
                    mesh_n=int(mesh.devices.size), stage="mesh",
                )
            except Exception:
                # a mesh-path failure (e.g. Pallas-under-shard_map
                # lowering) must not sink verification: fall through to
                # the single-device path, which has its own degradation
                # ladder down to the portable XLA kernel. Latched so a
                # deterministic failure costs one attempt, not one per
                # bucket (configure_mesh resets the latch; an explicit
                # per-plan mesh latches its OWN dispatcher via
                # plan.mesh_failed, never the process-global flag).
                if plan_mesh is not None:
                    plan.mesh_failed = True
                else:
                    _mesh_failed_once = True
                import logging

                logging.getLogger(__name__).exception(
                    "mesh-sharded %s verification failed; the mesh path "
                    "is disabled until reconfigured", scheme_kind
                )
        if mask is not None:
            for j, i in enumerate(idx):
                results[i] = bool(mask[j])
            continue
        prepared = plan.prepared.get(name)
        if prepared is not None:
            # split route: asynchronous launch, deferred materialisation
            from ...ops import ed25519_batch as _ed

            kwargs, _n = prepared
            t0 = _time.perf_counter()
            donate = _pipeline_donate()
            launch = (
                _ed.verify_kernel_donated if donate
                else _ed.verify_kernel
            )
            mask = launch(**kwargs)
            # carry the LAUNCH wall only: collect adds its blocking
            # materialisation wall. Recording launch→materialise wall
            # clock instead would count time the batch merely queued
            # between pipeline stages as device time and make the Jax.*
            # gauges report phantom slowdown under the pipeline.
            rows, bucket = _shape_bucket(True, _n)
            plan.pending.append(
                (kernel, idx, mask, _time.perf_counter() - t0,
                 {"scheme": name, "bucket": bucket, "rows": rows,
                  "real_rows": _n, "donated": donate})
            )
            continue
        from ... import ops
        from ...utils import profiling

        pubs = [flat[i][0].encoded for i in idx]
        sigs = [flat[i][1] for i in idx]
        msgs = [flat[i][2] for i in idx]
        t0 = _time.perf_counter()
        mask = (
            ops.ed25519_verify_batch(pubs, sigs, msgs)
            if is_ed
            else ops.ecdsa_verify_batch(_ECDSA_CURVES[name], pubs, sigs, msgs)
        )
        # backpressure telemetry seam: one record per DISPATCH (not
        # per signature) feeds the ops endpoint's Jax.* gauges and the
        # kernel flight ledger (rows vs real_rows = padding occupancy)
        rows, bucket = _shape_bucket(is_ed, len(idx))
        profiling.record_dispatch(
            kernel, _time.perf_counter() - t0,
            scheme=name, bucket=bucket, rows=rows, real_rows=len(idx),
        )
        for j, i in enumerate(idx):
            results[i] = bool(mask[j])

    from . import ecdsa_host as ecdsa_host_mod

    for kind, idx in plan.ec_host.items():
        out = ecdsa_host_mod.verify_batch_host(
            kind,
            [flat[i][0].encoded for i in idx],
            [flat[i][1] for i in idx],
            [flat[i][2] for i in idx],
        )
        for j, i in enumerate(idx):
            results[i] = out[j]

    if plan.ed_host:
        from . import host_batch

        if plan.ed_prehash is not None:
            # ONE Pippenger multi-scalar multiplication for the whole
            # bucket (~7x the per-signature OpenSSL loop at >= 1k),
            # consuming the prehash phase's hashes. ed_host is populated
            # ONLY on CPU deployments (use_device False routes every
            # non-composite ed25519 row here), so the cofactored
            # ZIP-215 rule applies to EVERY bucket size on such a
            # deployment — the verification rule is a deployment
            # property, never a batch-size accident (a rule that flips
            # at a size threshold would let an adversarial torsion
            # signature split replicas whose batchers grouped it
            # differently). Accelerator deployments use the
            # cofactorless rule at every size instead (device kernels +
            # OpenSSL loop for undersized buckets).
            rows, prehashed = plan.ed_prehash
            verdicts = host_batch.verify_batch_host(rows, prehashed=prehashed)
            for j, ok in enumerate(verdicts):
                results[plan.ed_host[j]] = ok
        elif host_batch.available():
            # synchronous composition (split_device off): both MSM
            # phases run here, exactly the pre-pipeline call graph
            rows = [
                (flat[i][0].encoded, flat[i][1], flat[i][2])
                for i in plan.ed_host
            ]
            for j, ok in enumerate(host_batch.verify_batch_host(rows)):
                results[plan.ed_host[j]] = ok
        else:
            plan.host_rows.extend(plan.ed_host)

    _host_verify_rows(flat, plan.host_rows, results)
    return plan


def collect_plan(plan: BatchPlan) -> List[bool]:
    """Phase 4 — materialise deferred device results (the only blocking
    read of the device), then fold flat verdicts back to items and
    evaluate composite threshold trees."""
    import numpy as _np

    from ...utils import profiling

    results = plan.flat_results
    for kernel, idx, mask, launch_wall, meta in plan.pending:
        t0 = _time.perf_counter()
        arr = _np.asarray(mask)  # the deferred block_until_ready
        # launch wall + the blocking wait for THIS batch's result: the
        # asarray only blocks while the device is still computing, so
        # inter-stage queue time never inflates the dispatch gauges (a
        # batch whose device work finished while queued records ~launch
        # cost alone — a lower bound, never a phantom slowdown)
        profiling.record_dispatch(
            kernel, launch_wall + (_time.perf_counter() - t0),
            scheme=meta["scheme"], bucket=meta["bucket"],
            rows=meta["rows"], real_rows=meta["real_rows"],
            donated=meta["donated"],
        )
        for j, i in enumerate(idx):
            results[i] = bool(arr[j])
    plan.pending = []

    for i in range(len(plan.items)):
        row = plan.flat_of_item[i]
        if row is not None:
            plan.results[i] = results[row]
    for i, ckey, rows, leaf_keys in plan.composites:
        ok = all(results[r] for r in rows)
        plan.results[i] = ok and ckey.is_fulfilled_by(set(leaf_keys))
    return plan.results


def _shape_bucket(is_ed: bool, n: int) -> tuple:
    """(padded rows, bucket label) for an n-row single-device device
    batch — the kernels' padding rules mirrored jax-free so the kernel
    flight ledger can label every record. Ed25519 uses the shared shape
    buckets (off-bucket overflow pads to a 65536 multiple, label
    "other"); ECDSA pads to the next power of two with a floor of 8.
    The TPU Pallas BLK floor can pad higher than this estimate; the
    label still names the bucket family the compile counters use."""
    from ...utils import profiling as _prof

    if is_ed:
        for b in _prof.ED25519_SHAPE_BUCKETS:
            if n <= b:
                return b, str(b)
        last = _prof.ED25519_SHAPE_BUCKETS[-1]
        return ((n + last - 1) // last) * last, "other"
    padded = max(8, 1 << (max(n, 1) - 1).bit_length())
    return padded, str(padded)


def _pipeline_donate() -> bool:
    """CORDA_TPU_PIPELINE_DONATE=0 opts the split dispatch route out of
    buffer donation (debugging aid: donation invalidates the input
    arrays after launch)."""
    return os.environ.get("CORDA_TPU_PIPELINE_DONATE", "1") != "0"


def _is_composite(key: PublicKey) -> bool:
    from .composite import CompositeKey

    return isinstance(key, CompositeKey)

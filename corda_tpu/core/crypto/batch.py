"""Scheme-dispatching batch signature verification.

The batch-first replacement for the reference's one-at-a-time loop
(`core/.../transactions/TransactionWithSignatures.kt:58-62` ->
`Crypto.kt:535-541`). Signatures are bucketed by scheme: ed25519 goes to the
JAX/TPU kernel (corda_tpu.ops.ed25519_batch); schemes without a device kernel
yet fall back to the host path (`crypto.is_valid`). Results come back as a
positionally-aligned bool list, so callers keep exact per-signature
accept/reject semantics.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from . import crypto
from .keys import PublicKey
from .schemes import EDDSA_ED25519_SHA512

# Flip to False to force the host path (e.g. for differential testing).
USE_DEVICE_KERNELS = True

# Below this many ed25519 signatures the host path (OpenSSL via cryptography)
# beats device dispatch+compile amortization on small batches.
MIN_DEVICE_BATCH = 32


def verify_batch(
    items: Sequence[Tuple[PublicKey, bytes, bytes]],
) -> List[bool]:
    """items: (public_key, signature_bytes, content) triples -> bool per item."""
    n = len(items)
    results: List[bool] = [False] * n
    ed_idx: List[int] = []
    for i, (key, sig, content) in enumerate(items):
        if (
            USE_DEVICE_KERNELS
            and key.scheme_code_name == EDDSA_ED25519_SHA512.scheme_code_name
            and not _is_composite(key)
        ):
            ed_idx.append(i)
        else:
            results[i] = crypto.is_valid(key, sig, content)

    if len(ed_idx) >= MIN_DEVICE_BATCH:
        from ... import ops

        mask = ops.ed25519_verify_batch(
            [items[i][0].encoded for i in ed_idx],
            [items[i][1] for i in ed_idx],
            [items[i][2] for i in ed_idx],
        )
        for j, i in enumerate(ed_idx):
            results[i] = bool(mask[j])
    else:
        for i in ed_idx:
            key, sig, content = items[i]
            results[i] = crypto.is_valid(key, sig, content)
    return results


def _is_composite(key: PublicKey) -> bool:
    from .composite import CompositeKey

    return isinstance(key, CompositeKey)

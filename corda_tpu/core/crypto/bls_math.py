"""Pure-Python BLS12-381: field tower, pairing, hash-to-curve, signatures.

The jax-free reference mirror for the batched pairing kernels in
corda_tpu.ops (field_bls12 / bls12_batch) AND the host sign/verify path
for the BLS_BLS12381 SignatureScheme — the same dual role ed25519_math
and secp_math play for their kernels (the container has no
`cryptography` package, and OpenSSL has no BLS anyway).

Scheme: the CFRG BLS signature draft's minimal-pubkey-size,
proof-of-possession ciphersuite
    BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_
(public keys 48-byte compressed G1, signatures 96-byte compressed G2,
messages hashed to G2 per RFC 9380 suite BLS12381G2_XMD:SHA-256_SSWU_RO_).
Aggregation is the committee-consensus lever (PAPERS' EdDSA-vs-BLS
committee study, arXiv 2302.00418): n same-message votes verify as ONE
product-of-2-Miller-loops check instead of n, after PoP registration
rules out rogue-key attacks.

Implementation notes:
  * Field elements are plain ints (Fp) and nested tuples (Fp2 = (c0, c1)
    meaning c0 + c1*u with u^2 = -1; Fp6 = 3 x Fp2 over v^3 = xi = 1+u;
    Fp12 = 2 x Fp6 over w^2 = v). Module-level functions, no classes —
    the per-op overhead dominates pure-Python pairing cost.
  * Every curve/field constant that CAN be derived from the BLS
    parameter x is derived at import (p, r, cofactors, Frobenius
    coefficients) rather than transcribed, and the module asserts the
    derivations against the published values — a transcription error
    dies at import, not in a signature.
  * Final exponentiation hard part uses the Hayashida-Hayasaka-Teruya
    identity  3*(p^4-p^2+1)/r = (x-1)^2*(x+p)*(x^2+p^2-1) + 3
    (asserted at import): the computed pairing is e(P,Q)^3 for the
    textbook reduced ate pairing e. A fixed cube is still bilinear and
    non-degenerate (gcd(3, r) = 1), and GT values are never serialized,
    so every product-equality check below is exact.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
from functools import lru_cache as _lru_cache
from typing import List, Optional, Sequence, Tuple

# --- parameters --------------------------------------------------------------

X = -0xD201000000010000  # the BLS12-381 curve parameter (negative, low weight)

P = (X - 1) ** 2 * (X**4 - X**2 + 1) // 3 + X  # base field prime
R = X**4 - X**2 + 1  # subgroup order (the scalar field)
H1 = (X - 1) ** 2 // 3  # G1 cofactor
H2 = (X**8 - 4 * X**7 + 5 * X**6 - 4 * X**4 + 6 * X**3 - 4 * X**2 - 4 * X + 13) // 9

assert P == 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
assert R == 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
assert H1 == 0x396C8C005555E1568C00AAAB0000AAAB

# RFC 9380 8.8.2 effective G2 cofactor (Budroni-Pintore). Asserted to be
# an exact multiple of the formula-derived h2, so h_eff*P provably lands
# in the r-torsion for every P in E2(Fp2).
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551
assert H_EFF_G2 % H2 == 0 and H_EFF_G2 % R != 0

# hard-part identity the final exponentiation is built on
assert 3 * ((P**4 - P**2 + 1) // R) == (X - 1) ** 2 * (X + P) * (X**2 + P**2 - 1) + 3

# generators (standard, on E1: y^2 = x^3 + 4 and E2: y^2 = x^3 + 4(1+u))
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

DST_SIG = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

Fp2 = Tuple[int, int]
Fp6 = Tuple[Fp2, Fp2, Fp2]
Fp12 = Tuple[Fp6, Fp6]

# --- Fp2 ---------------------------------------------------------------------

FP2_ZERO: Fp2 = (0, 0)
FP2_ONE: Fp2 = (1, 0)
XI: Fp2 = (1, 1)  # the Fp6 non-residue v^3 = 1 + u


def fp2_add(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a: Fp2, b: Fp2) -> Fp2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a: Fp2) -> Fp2:
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a: Fp2, b: Fp2) -> Fp2:
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    # Karatsuba: (a0+a1)(b0+b1) - t0 - t1 = a0b1 + a1b0
    return ((t0 - t1) % P, ((a0 + a1) * (b0 + b1) - t0 - t1) % P)


def fp2_sq(a: Fp2) -> Fp2:
    a0, a1 = a
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fp2_scale(a: Fp2, k: int) -> Fp2:
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a: Fp2) -> Fp2:
    return (a[0], (-a[1]) % P)


def fp2_mul_xi(a: Fp2) -> Fp2:
    # (a0 + a1 u)(1 + u) = (a0 - a1) + (a0 + a1) u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fp2_inv(a: Fp2) -> Fp2:
    a0, a1 = a
    norm_inv = pow(a0 * a0 + a1 * a1, -1, P)
    return (a0 * norm_inv % P, (-a1) * norm_inv % P)


def fp2_is_zero(a: Fp2) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


def fp2_legendre_norm(a: Fp2) -> int:
    """Legendre symbol of norm(a) in Fp: a is a square in Fp2 iff this
    is not -1 (a^((p^2-1)/2) = norm(a)^((p-1)/2))."""
    n = (a[0] * a[0] + a[1] * a[1]) % P
    if n == 0:
        return 0
    return 1 if pow(n, (P - 1) // 2, P) == 1 else -1


def fp_sqrt(a: int) -> Optional[int]:
    """Square root in Fp (p ≡ 3 mod 4); None when a is a non-residue."""
    a %= P
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a else None


def fp2_sqrt(a: Fp2) -> Optional[Fp2]:
    """Square root in Fp2, self-verified (returns None for non-squares)."""
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        c = fp_sqrt(a0)
        if c is not None:
            return (c, 0)
        c = fp_sqrt((-a0) % P)  # a0 = -(c^2) -> sqrt = c*u
        return None if c is None else (0, c)
    lam = fp_sqrt((a0 * a0 + a1 * a1) % P)
    if lam is None:
        return None
    inv2 = (P + 1) // 2  # 1/2 mod p
    delta = (a0 + lam) * inv2 % P
    c0 = fp_sqrt(delta)
    if c0 is None:
        delta = (a0 - lam) * inv2 % P
        c0 = fp_sqrt(delta)
        if c0 is None:
            return None
    c1 = a1 * pow(2 * c0, -1, P) % P
    cand = (c0, c1)
    return cand if fp2_sq(cand) == (a0, a1) else None


# --- Fp6 / Fp12 --------------------------------------------------------------

FP6_ZERO: Fp6 = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE: Fp6 = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def fp6_add(a: Fp6, b: Fp6) -> Fp6:
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a: Fp6, b: Fp6) -> Fp6:
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a: Fp6) -> Fp6:
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a: Fp6, b: Fp6) -> Fp6:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    c0 = fp2_add(t0, fp2_mul_xi(fp2_sub(
        fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), fp2_add(t1, t2))))
    c1 = fp2_add(fp2_sub(
        fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), fp2_add(t0, t1)),
        fp2_mul_xi(t2))
    c2 = fp2_add(fp2_sub(
        fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), fp2_add(t0, t2)), t1)
    return (c0, c1, c2)


def fp6_sq(a: Fp6) -> Fp6:
    return fp6_mul(a, a)


def fp6_mul_by_v(a: Fp6) -> Fp6:
    """a * v (the Fp12 w^2): (a0, a1, a2) -> (xi*a2, a0, a1)."""
    return (fp2_mul_xi(a[2]), a[0], a[1])


def fp6_scale_fp2(a: Fp6, k: Fp2) -> Fp6:
    return (fp2_mul(a[0], k), fp2_mul(a[1], k), fp2_mul(a[2], k))


def fp6_inv(a: Fp6) -> Fp6:
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sq(a0), fp2_mul_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul_xi(fp2_sq(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sq(a1), fp2_mul(a0, a2))
    t = fp2_add(fp2_mul(a0, c0),
                fp2_mul_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))))
    ti = fp2_inv(t)
    return (fp2_mul(c0, ti), fp2_mul(c1, ti), fp2_mul(c2, ti))


FP12_ONE: Fp12 = (FP6_ONE, FP6_ZERO)


def fp12_mul(a: Fp12, b: Fp12) -> Fp12:
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c1 = fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), fp6_add(t0, t1))
    return (fp6_add(t0, fp6_mul_by_v(t1)), c1)


def fp12_sq(a: Fp12) -> Fp12:
    a0, a1 = a
    t = fp6_mul(a0, a1)
    c0 = fp6_sub(
        fp6_mul(fp6_add(a0, a1), fp6_add(a0, fp6_mul_by_v(a1))),
        fp6_add(t, fp6_mul_by_v(t)),
    )
    return (c0, fp6_add(t, t))


def fp12_conj(a: Fp12) -> Fp12:
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a: Fp12) -> Fp12:
    a0, a1 = a
    t = fp6_inv(fp6_sub(fp6_sq(a0), fp6_mul_by_v(fp6_sq(a1))))
    return (fp6_mul(a0, t), fp6_neg(fp6_mul(a1, t)))


# Frobenius coefficients, derived (not transcribed): gamma = xi^((p-1)/6)
# and its square/cube power the v- and w-coefficient twists.
def _fp2_pow(a: Fp2, e: int) -> Fp2:
    out = FP2_ONE
    while e:
        if e & 1:
            out = fp2_mul(out, a)
        a = fp2_sq(a)
        e >>= 1
    return out


_G_W = _fp2_pow(XI, (P - 1) // 6)  # w^(p-1)
_G_V = _fp2_pow(XI, (P - 1) // 3)  # v^(p-1)
_G_V2 = fp2_sq(_G_V)  # v^2(p-1)


def fp6_frob(a: Fp6) -> Fp6:
    return (
        fp2_conj(a[0]),
        fp2_mul(fp2_conj(a[1]), _G_V),
        fp2_mul(fp2_conj(a[2]), _G_V2),
    )


def fp12_frob(a: Fp12) -> Fp12:
    a0, a1 = a
    return (fp6_frob(a0), fp6_scale_fp2(fp6_frob(a1), _G_W))


def fp12_pow_x_abs(a: Fp12) -> Fp12:
    """a^|x| by square-and-multiply (|x| has weight 6)."""
    bits = bin(-X)[2:]
    out = a
    for bit in bits[1:]:
        out = fp12_sq(out)
        if bit == "1":
            out = fp12_mul(out, a)
    return out


# --- curves ------------------------------------------------------------------
# Affine points; None is the point at infinity. G1 coordinates are ints,
# G2 coordinates Fp2 tuples. One generic implementation per coordinate
# field keeps the twist (b' = 4*xi) and the SSWU isogeny domain
# (y^2 = x^3 + A'x + B') on the same code path.

B1 = 4
B2 = fp2_scale(XI, 4)  # 4(1+u) on the twist


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def g1_neg(p1):
    return None if p1 is None else (p1[0], (-p1[1]) % P)


def g1_mul(p1, k: int):
    return _jac_mul(p1, k % R, _FP_OPS)


def g1_on_curve(p1) -> bool:
    if p1 is None:
        return True
    x, y = p1
    return (y * y - (x * x * x + B1)) % P == 0


# -- Jacobian scalar multiplication (shared G1/G2 core) -----------------------
# Affine add/double above are the semantic primitives (and the kernels'
# oracle); scalar multiplication routes through a=0 Jacobian formulas
# (dbl-2009-l / madd-2007-bl) to drop the per-step field inversion —
# ~10x on the 636-bit G2 cofactor clear. tests/test_bls.py cross-checks
# the two paths on random scalars.

_FP_OPS = (
    lambda a, b: a * b % P,          # mul
    lambda a: a * a % P,             # sq
    lambda a, b: (a + b) % P,        # add
    lambda a, b: (a - b) % P,        # sub
    lambda a, k: a * k % P,          # scale
    lambda a: a % P == 0,            # is_zero
    lambda a: pow(a, -1, P),         # inv
    0,                               # zero
)
_FP2_OPS = (
    fp2_mul, fp2_sq, fp2_add, fp2_sub, fp2_scale, fp2_is_zero, fp2_inv,
    (0, 0),
)


def _jac_dbl(X, Y, Z, ops):
    mul, sq, add, sub, scale = ops[:5]
    A = sq(X)
    Bv = sq(Y)
    C = sq(Bv)
    D = scale(sub(sub(sq(add(X, Bv)), A), C), 2)
    E = scale(A, 3)
    X3 = sub(sq(E), scale(D, 2))
    Y3 = sub(mul(E, sub(D, X3)), scale(C, 8))
    return X3, Y3, scale(mul(Y, Z), 2)


def _jac_mul(pt, k: int, ops):
    """k * pt for affine pt on an a=0 short-Weierstrass curve over the
    field described by `ops`; returns affine (or None)."""
    if pt is None or k == 0:
        return None
    mul, sq, add, sub, scale, is_zero, inv, _zero = ops
    one = 1 if ops is _FP_OPS else FP2_ONE
    x2, y2 = pt  # the fixed affine addend
    acc = None  # Jacobian accumulator (X, Y, Z), None = infinity
    for bit in bin(k)[2:]:
        if acc is not None:
            acc = _jac_dbl(*acc, ops)
        if bit == "1":
            if acc is None:
                acc = (x2, y2, one)
                continue
            X, Y, Z = acc
            # madd-2007-bl (mixed add, Z2 = 1)
            ZZ = sq(Z)
            U2 = mul(x2, ZZ)
            S2 = mul(mul(y2, Z), ZZ)
            H = sub(U2, X)
            if is_zero(H):
                if is_zero(sub(S2, Y)):
                    acc = _jac_dbl(X, Y, Z, ops)
                else:
                    acc = None  # P + (-P)
                continue
            HH = sq(H)
            I = scale(HH, 4)
            J = mul(H, I)
            rr = scale(sub(S2, Y), 2)
            V = mul(X, I)
            X3 = sub(sub(sq(rr), J), scale(V, 2))
            Y3 = sub(mul(rr, sub(V, X3)), scale(mul(Y, J), 2))
            Z3 = sub(sub(sq(add(Z, H)), ZZ), HH)
            acc = (X3, Y3, Z3)
    if acc is None:
        return None
    X, Y, Z = acc
    if is_zero(Z):
        return None
    zi = inv(Z)
    zi2 = sq(zi)
    return (mul(X, zi2), mul(Y, mul(zi2, zi)))


def _fp2_curve_add(p1, p2, a_coef: Fp2, scalar_bits=None):
    """Affine add on y^2 = x^3 + a*x + b over Fp2 (b implicit)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if fp2_is_zero(fp2_add(y1, y2)):
            return None
        num = fp2_add(fp2_scale(fp2_sq(x1), 3), a_coef)
        lam = fp2_mul(num, fp2_inv(fp2_scale(y1, 2)))
    else:
        lam = fp2_mul(fp2_sub(y2, y1), fp2_inv(fp2_sub(x2, x1)))
    x3 = fp2_sub(fp2_sub(fp2_sq(lam), x1), x2)
    return (x3, fp2_sub(fp2_mul(lam, fp2_sub(x1, x3)), y1))


def g2_add(p1, p2):
    return _fp2_curve_add(p1, p2, FP2_ZERO)


def g2_neg(p1):
    return None if p1 is None else (p1[0], fp2_neg(p1[1]))


def g2_mul(p1, k: int, modr: bool = True):
    if modr:
        k %= R
    return _jac_mul(p1, k, _FP2_OPS)


def g2_on_curve(p1) -> bool:
    if p1 is None:
        return True
    x, y = p1
    return fp2_is_zero(fp2_sub(
        fp2_sq(y), fp2_add(fp2_mul(fp2_sq(x), x), B2)))


def g1_in_subgroup(p1) -> bool:
    if p1 is None:
        return True
    # NOT g1_mul: that reduces the scalar mod r, which would turn this
    # check into 0*P == infinity — vacuously true for every on-curve
    # point (the small-subgroup hole; g2_in_subgroup avoids it the same
    # way via modr=False)
    return g1_on_curve(p1) and _jac_mul(p1, R, _FP_OPS) is None


def g2_in_subgroup(p1) -> bool:
    if p1 is None:
        return True
    return g2_on_curve(p1) and g2_mul(p1, R, modr=False) is None


# --- pairing -----------------------------------------------------------------

def _line(g0_scalar: Fp2, h1: Fp2, h2: Fp2) -> Fp12:
    """Sparse line element: g0 + h1*w^3 + h2*w^5 in the (1, v, v^2,
    w, vw, v^2 w) basis (w^3 = v*w, w^5 = v^2*w)."""
    return ((g0_scalar, FP2_ZERO, FP2_ZERO), (FP2_ZERO, h1, h2))


def _miller_loop(pairs) -> Fp12:
    """Product of optimal-ate Miller functions f_{|x|,Q_i}(P_i).

    pairs: [(P affine G1, Q affine G2 on the twist)]; pairs with either
    point at infinity contribute 1. Line functions are evaluated via the
    M-twist untwist (x/w^2, y/w^3) and scaled per-line by xi and the
    affine denominators — Fp2 constants, killed by the final
    exponentiation. x < 0 is handled by conjugating the loop output.
    """
    live = [(pp, qq) for pp, qq in pairs if pp is not None and qq is not None]
    f = FP12_ONE
    if not live:
        return f
    ts = [q for _, q in live]
    bits = bin(-X)[3:]  # MSB consumed by the initial T = Q
    for bit in bits:
        f = fp12_sq(f)
        for i, (pt, q) in enumerate(live):
            xp, yp = pt
            tx, ty = ts[i]
            # doubling line at T, evaluated at P (scaled by 2*ty*xi)
            lam = fp2_mul(fp2_scale(fp2_sq(tx), 3),
                          fp2_inv(fp2_scale(ty, 2)))
            h1 = fp2_sub(fp2_mul(lam, tx), ty)
            h2 = fp2_scale(lam, (-xp) % P)
            f = fp12_mul(f, _line(fp2_scale(fp2_mul_xi(FP2_ONE), yp), h1, h2))
            x3 = fp2_sub(fp2_sq(lam), fp2_scale(tx, 2))
            ts[i] = (x3, fp2_sub(fp2_mul(lam, fp2_sub(tx, x3)), ty))
            if bit == "1":
                tx, ty = ts[i]
                qx, qy = q
                # T != +-Q always here: T = k*Q with 0 < k < |x| << r
                lam = fp2_mul(fp2_sub(ty, qy), fp2_inv(fp2_sub(tx, qx)))
                h1 = fp2_sub(fp2_mul(lam, qx), qy)
                h2 = fp2_scale(lam, (-xp) % P)
                f = fp12_mul(
                    f, _line(fp2_scale(fp2_mul_xi(FP2_ONE), yp), h1, h2))
                x3 = fp2_sub(fp2_sub(fp2_sq(lam), tx), qx)
                ts[i] = (x3, fp2_sub(fp2_mul(lam, fp2_sub(tx, x3)), ty))
    return fp12_conj(f)  # x < 0


def final_exponentiation(f: Fp12) -> Fp12:
    """f^(3*(p^12-1)/r): the reduced ate pairing cubed (see module doc).

    Easy part f^((p^6-1)(p^2+1)) puts f in the cyclotomic subgroup
    (inverse = conjugate); hard part via the asserted HHT identity."""
    f = fp12_mul(fp12_conj(f), fp12_inv(f))  # ^(p^6 - 1)
    f = fp12_mul(fp12_frob(fp12_frob(f)), f)  # ^(p^2 + 1)

    def pow_x(a: Fp12) -> Fp12:  # a^x (x < 0: conjugate in cyclotomic)
        return fp12_conj(fp12_pow_x_abs(a))

    a = fp12_mul(pow_x(f), fp12_conj(f))  # f^(x-1)
    a = fp12_mul(pow_x(a), fp12_conj(a))  # f^((x-1)^2)
    b = fp12_mul(pow_x(a), fp12_frob(a))  # ^(x+p)
    c = fp12_mul(
        fp12_mul(pow_x(pow_x(b)), fp12_frob(fp12_frob(b))),  # ^(x^2+p^2)
        fp12_conj(b),  # ^(-1)
    )
    f3 = fp12_mul(fp12_sq(f), f)
    return fp12_mul(c, f3)


def pairing(p1, q2) -> Fp12:
    """e(P, Q)^3 for P in G1, Q in G2 (cubed pairing; see module doc)."""
    return final_exponentiation(_miller_loop([(p1, q2)]))


def pairings_equal_one(pairs) -> bool:
    """Whether the product of pairings over `pairs` is the identity —
    ONE shared Miller loop product and ONE final exponentiation (the
    verification shape: 2 loops + 1 exp per check, aggregate or not)."""
    return final_exponentiation(_miller_loop(pairs)) == FP12_ONE


# --- RFC 9380 hash-to-curve (suite BLS12381G2_XMD:SHA-256_SSWU_RO_) ----------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + 31) // 32
    if ell > 255 or len_in_bytes > 65535:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * 64  # SHA-256 block size
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b_prev = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = b_prev
    for i in range(2, ell + 1):
        b_prev = hashlib.sha256(
            bytes(x ^ y for x, y in zip(b0, b_prev))
            + bytes([i]) + dst_prime
        ).digest()
        out += b_prev
    return out[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, dst: bytes, count: int) -> List[Fp2]:
    """RFC 9380 §5.2 for Fp2 (m = 2, L = 64)."""
    L = 64
    uniform = expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        elems = []
        for j in range(2):
            off = L * (j + i * 2)
            elems.append(int.from_bytes(uniform[off:off + L], "big") % P)
        out.append((elems[0], elems[1]))
    return out


# SSWU isogenous curve E2': y^2 = x^3 + A'x + B' (RFC 9380 §8.8.2)
SSWU_A: Fp2 = (0, 240)
SSWU_B: Fp2 = (1012, 1012)
SSWU_Z: Fp2 = ((-2) % P, (-1) % P)  # -(2 + u)

# 3-isogeny map E2' -> E2 coefficients (RFC 9380 Appendix E.3). These
# are the one transcribed constant block; tests/test_bls.py validates
# them by checking hash-to-curve outputs land ON E2 (a wrong rational-map
# coefficient lands off-curve with overwhelming probability) and in the
# r-torsion.
_K = 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6
ISO_X_NUM = (
    (_K, _K),
    (0, 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1, 0),
)
# x_den = x'^2 + k_(2,1) x' + k_(2,0) (monic quadratic)
ISO_X_DEN = (
    (0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (1, 0),
)
ISO_Y_NUM = (
    (0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
     0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    (0, 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10, 0),
)
ISO_Y_DEN = (
    (0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    (0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    (0x12, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
    (1, 0),
)


def _sgn0_fp2(a: Fp2) -> int:
    """RFC 9380 §4.1 sgn0 for m = 2."""
    sign_0 = a[0] % 2
    zero_0 = a[0] % P == 0
    sign_1 = a[1] % 2
    return sign_0 or (zero_0 and sign_1)


def _sswu_fp2(u: Fp2):
    """RFC 9380 §6.6.2 simplified SWU onto E2' (non-uniform branches are
    fine off-device; the kernels re-derive a batch-uniform version)."""
    u2 = fp2_sq(u)
    zu2 = fp2_mul(SSWU_Z, u2)
    tv1 = fp2_add(fp2_sq(zu2), zu2)  # Z^2 u^4 + Z u^2
    neg_b_over_a = fp2_mul(fp2_neg(SSWU_B), fp2_inv(SSWU_A))
    if fp2_is_zero(tv1):
        x1 = fp2_mul(SSWU_B, fp2_inv(fp2_mul(SSWU_Z, SSWU_A)))
    else:
        x1 = fp2_mul(neg_b_over_a, fp2_add(FP2_ONE, fp2_inv(tv1)))
    gx1 = fp2_add(fp2_mul(fp2_add(fp2_sq(x1), SSWU_A), x1), SSWU_B)
    if fp2_legendre_norm(gx1) != -1:
        x, y = x1, fp2_sqrt(gx1)
    else:
        x2 = fp2_mul(zu2, x1)
        gx2 = fp2_add(fp2_mul(fp2_add(fp2_sq(x2), SSWU_A), x2), SSWU_B)
        x, y = x2, fp2_sqrt(gx2)
    assert y is not None, "SSWU: g(x) must be square on one branch"
    if _sgn0_fp2(u) != _sgn0_fp2(y):
        y = fp2_neg(y)
    return (x, y)


def _eval_poly(ks, x: Fp2) -> Fp2:
    out = FP2_ZERO
    for k in reversed(ks):
        out = fp2_add(fp2_mul(out, x), k)
    return out


def _iso_map_g2(pt):
    """3-isogeny E2' -> E2 (RFC 9380 §4.3 / E.3)."""
    if pt is None:
        return None
    x, y = pt
    x_den = _eval_poly(ISO_X_DEN, x)
    y_den = _eval_poly(ISO_Y_DEN, x)
    if fp2_is_zero(x_den) or fp2_is_zero(y_den):
        return None  # exceptional point maps to infinity
    xo = fp2_mul(_eval_poly(ISO_X_NUM, x), fp2_inv(x_den))
    yo = fp2_mul(fp2_mul(y, _eval_poly(ISO_Y_NUM, x)), fp2_inv(y_den))
    return (xo, yo)


def _sswu_curve_add(p1, p2):
    return _fp2_curve_add(p1, p2, SSWU_A)


def clear_cofactor_g2(pt):
    """h_eff * P (RFC 9380 §8.8.2): lands in the r-torsion (asserted at
    import: h_eff is a multiple of the formula-derived h2)."""
    return _jac_mul(pt, H_EFF_G2, _FP2_OPS)


def hash_to_curve_g2(msg: bytes, dst: bytes = DST_SIG):
    """RFC 9380 hash_to_curve for the G2 suite: two field elements, two
    SSWU maps added on E2', one isogeny evaluation, cofactor cleared.

    LRU-cached: a committee signs (and its verifier re-hashes) the SAME
    vote statement n times — one curve hash serves all of them. The
    function is deterministic, so the cache is semantics-free."""
    return _hash_to_curve_g2_cached(bytes(msg), bytes(dst))


@_lru_cache(maxsize=256)
def _hash_to_curve_g2_cached(msg: bytes, dst: bytes):
    u0, u1 = hash_to_field_fp2(msg, dst, 2)
    q = _sswu_curve_add(_sswu_fp2(u0), _sswu_fp2(u1))
    return clear_cofactor_g2(_iso_map_g2(q))


# --- serialization (ZCash BLS12-381 format) ----------------------------------

_FLAG_COMPRESSED = 0x80
_FLAG_INFINITY = 0x40
_FLAG_SIGN = 0x20


def _y_is_large(y: int) -> bool:
    return 2 * y > P


def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([_FLAG_COMPRESSED | _FLAG_INFINITY]) + b"\x00" * 47
    x, y = pt
    flags = _FLAG_COMPRESSED | (_FLAG_SIGN if _y_is_large(y) else 0)
    b = x.to_bytes(48, "big")
    return bytes([b[0] | flags]) + b[1:]


def g1_decompress(data: bytes):
    """48-byte compressed G1 -> affine point; raises ValueError on any
    malformed/off-curve/non-subgroup encoding. LRU-cached: committee
    keys recur every block, and the r-torsion check is the expensive
    part (the function is deterministic; exceptions are never cached)."""
    return _g1_decompress_cached(bytes(data))


@_lru_cache(maxsize=1024)
def _g1_decompress_cached(data: bytes):
    if len(data) != 48:
        raise ValueError("G1 point must be 48 bytes")
    flags = data[0]
    if not flags & _FLAG_COMPRESSED:
        raise ValueError("uncompressed G1 encoding unsupported")
    if flags & _FLAG_INFINITY:
        if flags & _FLAG_SIGN or any(data[1:]) or data[0] & 0x3F:
            raise ValueError("malformed infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = fp_sqrt((x * x * x + B1) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if _y_is_large(y) != bool(flags & _FLAG_SIGN):
        y = P - y
    pt = (x, y)
    if not g1_in_subgroup(pt):
        raise ValueError("G1 point not in the r-torsion subgroup")
    return pt


def g2_compress(pt) -> bytes:
    if pt is None:
        return (bytes([_FLAG_COMPRESSED | _FLAG_INFINITY])
                + b"\x00" * 95)
    (x0, x1), (y0, y1) = pt
    large = _y_is_large(y1) if y1 != 0 else _y_is_large(y0)
    flags = _FLAG_COMPRESSED | (_FLAG_SIGN if large else 0)
    b = x1.to_bytes(48, "big")
    return bytes([b[0] | flags]) + b[1:] + x0.to_bytes(48, "big")


def g2_decompress(data: bytes, subgroup_check: bool = True):
    """96-byte compressed G2 -> affine point (ValueError on malformed/
    off-curve/out-of-subgroup encodings). `subgroup_check=False` skips
    the r-torsion check — ONLY sound where the caller's verification
    equation covers the result anyway (signature aggregation: the
    aggregate point gets the full check inside aggregate_verify, so
    checking each component would re-pay exactly the per-signature cost
    aggregation exists to remove). Both variants LRU-cached."""
    return _g2_decompress_cached(bytes(data), bool(subgroup_check))


@_lru_cache(maxsize=1024)
def _g2_decompress_cached(data: bytes, subgroup_check: bool):
    if len(data) != 96:
        raise ValueError("G2 point must be 96 bytes")
    flags = data[0]
    if not flags & _FLAG_COMPRESSED:
        raise ValueError("uncompressed G2 encoding unsupported")
    if flags & _FLAG_INFINITY:
        if flags & _FLAG_SIGN or any(data[1:]) or data[0] & 0x3F:
            raise ValueError("malformed infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = fp2_sqrt(fp2_add(fp2_mul(fp2_sq(x), x), B2))
    if y is None:
        raise ValueError("G2 x not on curve")
    y0c, y1c = y
    large = _y_is_large(y1c) if y1c != 0 else _y_is_large(y0c)
    if large != bool(flags & _FLAG_SIGN):
        y = fp2_neg(y)
    pt = (x, y)
    if subgroup_check and not g2_in_subgroup(pt):
        raise ValueError("G2 point not in the r-torsion subgroup")
    return pt


# --- the signature scheme (CFRG BLS draft, min-pubkey-size, PoP) -------------

def keygen(ikm: bytes, key_info: bytes = b"") -> int:
    """CFRG KeyGen: HKDF-SHA256 with the BLS salt, looped until nonzero."""
    if len(ikm) < 32:
        raise ValueError("IKM must be at least 32 bytes")
    salt = b"BLS-SIG-KEYGEN-SALT-"
    L = 48
    info = key_info + L.to_bytes(2, "big")
    sk = 0
    while sk == 0:
        prk = _hmac.new(salt, ikm + b"\x00", hashlib.sha256).digest()
        okm, t = b"", b""
        for i in range(1, (L + 31) // 32 + 1):
            t = _hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
            okm += t
        sk = int.from_bytes(okm[:L], "big") % R
        salt = hashlib.sha256(salt).digest()
    return sk


def sk_to_pk(sk: int) -> bytes:
    return g1_compress(g1_mul(G1_GEN, sk))


def sign(sk: int, message: bytes, dst: bytes = DST_SIG) -> bytes:
    return g2_compress(g2_mul(hash_to_curve_g2(message, dst), sk))


def verify(pk: bytes, signature: bytes, message: bytes,
           dst: bytes = DST_SIG) -> bool:
    """One signature: e(g1, sig) == e(pk, H(m)), checked as a product of
    two Miller loops sharing one final exponentiation."""
    try:
        pk_pt = g1_decompress(pk)
        sig_pt = g2_decompress(signature)
    except ValueError:
        return False
    if pk_pt is None:
        return False  # the identity public key signs everything
    h = hash_to_curve_g2(message, dst)
    return pairings_equal_one([(g1_neg(G1_GEN), sig_pt), (pk_pt, h)])


def aggregate(signatures: Sequence[bytes]) -> bytes:
    """Sum the signature points: n committee votes -> one 96-byte sig.

    Components are decoded WITHOUT per-point subgroup checks (on-curve
    only): the aggregate itself is fully validated inside
    aggregate_verify, and re-checking every component would re-pay the
    exact per-signature cost aggregation removes (CFRG Aggregate does
    the same — subgroup checking happens at verification)."""
    if not signatures:
        raise ValueError("cannot aggregate zero signatures")
    acc = None
    for sig in signatures:
        acc = g2_add(acc, g2_decompress(sig, subgroup_check=False))
    return g2_compress(acc)


def aggregate_pubkeys(pubkeys: Sequence[bytes]):
    acc = None
    for pk in pubkeys:
        acc = g1_add(acc, g1_decompress(pk))
    return acc


def aggregate_verify(pubkeys: Sequence[bytes], message: bytes,
                     agg_signature: bytes, dst: bytes = DST_SIG) -> bool:
    """Same-message aggregate check (CFRG FastAggregateVerify): ONE
    e(g1, agg_sig) == e(sum pk_i, H(m)) — 2 Miller loops + 1 final exp
    regardless of committee size. ONLY sound under proof-of-possession
    registration (rogue-key attacks otherwise; docs/bls-aggregation.md)."""
    if not pubkeys:
        return False
    try:
        agg_pk = aggregate_pubkeys(pubkeys)
        sig_pt = g2_decompress(agg_signature)
    except ValueError:
        return False
    if agg_pk is None:
        return False
    h = hash_to_curve_g2(message, dst)
    return pairings_equal_one([(g1_neg(G1_GEN), sig_pt), (agg_pk, h)])


def aggregate_verify_distinct(pubkeys: Sequence[bytes],
                              messages: Sequence[bytes],
                              agg_signature: bytes,
                              dst: bytes = DST_SIG) -> bool:
    """CFRG AggregateVerify for distinct messages: product of n+1
    pairings, one shared final exponentiation."""
    if not pubkeys or len(pubkeys) != len(messages):
        return False
    try:
        pairs = [(g1_decompress(pk), hash_to_curve_g2(m, dst))
                 for pk, m in zip(pubkeys, messages)]
        sig_pt = g2_decompress(agg_signature)
    except ValueError:
        return False
    if any(pk is None for pk, _ in pairs):
        return False
    pairs.append((g1_neg(G1_GEN), sig_pt))
    return pairings_equal_one(pairs)


def pop_prove(sk: int) -> bytes:
    """Proof of possession: a signature over the pubkey bytes under the
    POP domain separation tag."""
    return sign(sk, sk_to_pk(sk), dst=DST_POP)


def pop_verify(pk: bytes, proof: bytes) -> bool:
    return verify(pk, proof, pk, dst=DST_POP)

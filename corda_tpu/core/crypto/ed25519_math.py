"""Pure-Python ed25519 (RFC 8032) field/point math.

Roles:
  * host-side correctness oracle for the JAX/TPU batch kernel (corda_tpu.ops.ed25519),
  * deterministic key derivation from entropy (reference parity:
    `core/.../crypto/Crypto.kt:718-739` deriveKeyPairFromEntropy),
  * point decompression / limb packing that prepares batches for the TPU kernel
    (decompression is cheap and data-dependent; the double-scalar-mul is the
    FLOP-heavy uniform part that belongs on the accelerator).

Parity: the reference binds ed25519 to net.i2p.crypto.eddsa
(`core/src/main/kotlin/net/corda/core/crypto/Crypto.kt:119-132`).
Implemented here from the public RFC 8032 specification.
"""
from __future__ import annotations

import hashlib
from typing import Tuple

# --- field -----------------------------------------------------------------
P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P  # Edwards curve constant
SQRT_M1 = pow(2, (P - 1) // 4, P)          # sqrt(-1) mod p


def inv(x: int) -> int:
    return pow(x, P - 2, P)


# --- points: extended homogeneous coordinates (X, Y, Z, T), x=X/Z y=Y/Z xy=T/Z
Point = Tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)

# Base point
_By = 4 * inv(5) % P


def _recover_x(y: int, sign: int) -> int | None:
    if y >= P:
        return None
    x2 = (y * y - 1) * inv(D * y * y + 1) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


_Bx = _recover_x(_By, 0)
BASE: Point = (_Bx, _By, 1, _Bx * _By % P)


def point_add(p: Point, q: Point) -> Point:
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = B - A, Dd - C, Dd + C, B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p: Point) -> Point:
    # dedicated doubling (hisil et al. formula); same result as point_add(p, p)
    X1, Y1, Z1, _ = p
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = 2 * Z1 * Z1 % P
    H = (A + B) % P
    E = (H - (X1 + Y1) * (X1 + Y1)) % P
    G = (A - B) % P
    F = (C + G) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def scalar_mult(s: int, p: Point) -> Point:
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_double(p)
        s >>= 1
    return q


def point_equal(p: Point, q: Point) -> bool:
    # x1/z1 == x2/z2  and  y1/z1 == y2/z2
    return (p[0] * q[2] - q[0] * p[2]) % P == 0 and (p[1] * q[2] - q[1] * p[2]) % P == 0


def point_compress(p: Point) -> bytes:
    zinv = inv(p[2])
    x = p[0] * zinv % P
    y = p[1] * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def point_decompress(s: bytes) -> Point | None:
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def is_on_curve(p: Point) -> bool:
    X, Y, Z, T = p
    # -x^2 + y^2 = z^2 + d*t^2  with  x*y = z*t
    return (
        (-X * X + Y * Y - Z * Z - D * T * T) % P == 0
        and (X * Y - Z * T) % P == 0
    )


# --- EdDSA sign/verify (RFC 8032 Ed25519, SHA-512) -------------------------

def _sha512_int(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def secret_expand(seed: bytes) -> Tuple[int, bytes]:
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_from_seed(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return point_compress(scalar_mult(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix = secret_expand(seed)
    A = point_compress(scalar_mult(a, BASE))
    r = _sha512_int(prefix, msg) % L
    Rp = scalar_mult(r, BASE)
    Rs = point_compress(Rp)
    h = _sha512_int(Rs, A, msg) % L
    s = (r + h * a) % L
    return Rs + s.to_bytes(32, "little")


def verify(public: bytes, msg: bytes, signature: bytes) -> bool:
    if len(public) != 32 or len(signature) != 64:
        return False
    A = point_decompress(public)
    if A is None:
        return False
    Rs = signature[:32]
    Rp = point_decompress(Rs)
    if Rp is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False
    h = _sha512_int(Rs, public, msg) % L
    # [s]B == R + [h]A   (unbatched cofactorless check, matching i2p/ref10)
    sB = scalar_mult(s, BASE)
    hA = scalar_mult(h, A)
    return point_equal(sB, point_add(Rp, hA))


def to_affine(p: Point) -> Tuple[int, int]:
    zinv = inv(p[2])
    return p[0] * zinv % P, p[1] * zinv % P

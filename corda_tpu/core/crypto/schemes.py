"""SignatureScheme descriptors and the supported-scheme registry.

Parity: reference `core/src/main/kotlin/net/corda/core/crypto/SignatureScheme.kt`
and the registry in `Crypto.kt:176-183`. Scheme numeric IDs and code names are
kept identical so serialized metadata stays interoperable.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict


@dataclass(frozen=True)
class SignatureScheme:
    scheme_number_id: int
    scheme_code_name: str
    algorithm_name: str
    desc: str
    key_size: int | None


RSA_SHA256 = SignatureScheme(
    1, "RSA_SHA256", "RSA",
    "RSA_SHA256 signature scheme using SHA256 as hash algorithm.", 3072,
)
ECDSA_SECP256K1_SHA256 = SignatureScheme(
    2, "ECDSA_SECP256K1_SHA256", "ECDSA",
    "ECDSA signature scheme using the secp256k1 Koblitz curve.", 256,
)
ECDSA_SECP256R1_SHA256 = SignatureScheme(
    3, "ECDSA_SECP256R1_SHA256", "ECDSA",
    "ECDSA signature scheme using the secp256r1 (NIST P-256) curve.", 256,
)
EDDSA_ED25519_SHA512 = SignatureScheme(
    4, "EDDSA_ED25519_SHA512", "EdDSA",
    "EdDSA signature scheme using the ed25519 twisted Edwards curve.", 256,
)
SPHINCS256_SHA256 = SignatureScheme(
    5, "SPHINCS-256_SHA512", "SPHINCS256",
    "SPHINCS-256 hash-based signature scheme. It provides 128bit security "
    "against post-quantum attackers at the cost of larger key sizes and loss "
    "of compatibility.", 256,
)
COMPOSITE_KEY = SignatureScheme(
    6, "COMPOSITE", "COMPOSITE",
    "Composite keys composed from multiple signature schemes, to enable a "
    "flexible fusion of different signature schemes.", None,
)
BLS_BLS12381 = SignatureScheme(
    7, "BLS_BLS12381", "BLS",
    "BLS aggregate signature scheme over the BLS12-381 pairing curve "
    "(minimal-pubkey-size, proof-of-possession ciphersuite): n committee "
    "signatures over one message verify as a single 2-pairing check.", 256,
)

SUPPORTED_SIGNATURE_SCHEMES: Dict[str, SignatureScheme] = {
    s.scheme_code_name: s
    for s in (
        RSA_SHA256,
        ECDSA_SECP256K1_SHA256,
        ECDSA_SECP256R1_SHA256,
        EDDSA_ED25519_SHA512,
        SPHINCS256_SHA256,
        COMPOSITE_KEY,
        BLS_BLS12381,
    )
}

SCHEMES_BY_ID: Dict[int, SignatureScheme] = {
    s.scheme_number_id: s for s in SUPPORTED_SIGNATURE_SCHEMES.values()
}

DEFAULT_SIGNATURE_SCHEME = EDDSA_ED25519_SHA512

"""Pure-Python short-Weierstrass curve math: secp256k1 and secp256r1 ECDSA.

Roles: host-side oracle for the JAX batch kernels (corda_tpu.ops.secp256),
deterministic key derivation, and point decompression for kernel prep.

Parity: the reference binds ECDSA to BouncyCastle
(`core/src/main/kotlin/net/corda/core/crypto/Crypto.kt:91-117`); signatures are
ASN.1 DER (r, s) as produced by the JCA. Implemented from the public SEC 2 /
FIPS 186-4 specifications (RFC 6979 deterministic nonces for signing).
"""
from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass
from typing import Optional, Tuple

Affine = Optional[Tuple[int, int]]  # None = point at infinity


@dataclass(frozen=True)
class Curve:
    name: str
    p: int   # field prime
    a: int
    b: int
    gx: int
    gy: int
    n: int   # group order
    h: int   # cofactor

    def contains(self, pt: Affine) -> bool:
        if pt is None:
            return True
        x, y = pt
        return (y * y - (x * x * x + self.a * x + self.b)) % self.p == 0

    # -- group law (affine; fine for an oracle) -----------------------------
    def add(self, p1: Affine, p2: Affine) -> Affine:
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        x1, y1 = p1
        x2, y2 = p2
        if x1 == x2 and (y1 + y2) % self.p == 0:
            return None
        if p1 == p2:
            lam = (3 * x1 * x1 + self.a) * pow(2 * y1, self.p - 2, self.p) % self.p
        else:
            lam = (y2 - y1) * pow(x2 - x1, self.p - 2, self.p) % self.p
        x3 = (lam * lam - x1 - x2) % self.p
        y3 = (lam * (x1 - x3) - y1) % self.p
        return (x3, y3)

    def mul(self, k: int, pt: Affine) -> Affine:
        acc: Affine = None
        while k > 0:
            if k & 1:
                acc = self.add(acc, pt)
            pt = self.add(pt, pt)
            k >>= 1
        return acc

    @property
    def g(self) -> Affine:
        return (self.gx, self.gy)

    # -- encoding -----------------------------------------------------------
    def encode_point(self, pt: Affine, compressed: bool = True) -> bytes:
        if pt is None:
            return b"\x00"
        x, y = pt
        if compressed:
            return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")
        return b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")

    def decode_point(self, data: bytes) -> Affine:
        if data == b"\x00":
            return None
        if data[0] == 4:
            x = int.from_bytes(data[1:33], "big")
            y = int.from_bytes(data[33:65], "big")
            pt = (x, y)
            if not self.contains(pt):
                raise ValueError("point not on curve")
            return pt
        if data[0] in (2, 3):
            x = int.from_bytes(data[1:33], "big")
            if x >= self.p:
                raise ValueError("x out of range")
            rhs = (x * x * x + self.a * x + self.b) % self.p
            y = self.sqrt(rhs)
            if y is None:
                raise ValueError("not a quadratic residue")
            if (y & 1) != (data[0] & 1):
                y = self.p - y
            return (x, y)
        raise ValueError("bad point encoding")

    def sqrt(self, v: int) -> Optional[int]:
        # both secp256k1 and secp256r1 have p % 4 == 3
        r = pow(v, (self.p + 1) // 4, self.p)
        if r * r % self.p != v % self.p:
            return None
        return r


SECP256K1 = Curve(
    name="secp256k1",
    p=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F,
    a=0,
    b=7,
    gx=0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    gy=0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
    n=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
    h=1,
)

SECP256R1 = Curve(
    name="secp256r1",
    p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
    a=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFC,
    b=0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,
    gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
    gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
    n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
    h=1,
)


# --- ECDSA ------------------------------------------------------------------

def _bits2int(data: bytes, n: int) -> int:
    v = int.from_bytes(data, "big")
    excess = len(data) * 8 - n.bit_length()
    if excess > 0:
        v >>= excess
    return v


def rfc6979_nonce(curve: Curve, priv: int, digest: bytes) -> int:
    """Deterministic nonce per RFC 6979 (HMAC-SHA256)."""
    qlen_bytes = (curve.n.bit_length() + 7) // 8
    h1 = _bits2int(digest, curve.n) % curve.n
    x_b = priv.to_bytes(qlen_bytes, "big")
    h1_b = h1.to_bytes(qlen_bytes, "big")
    V = b"\x01" * 32
    K = b"\x00" * 32
    K = hmac.new(K, V + b"\x00" + x_b + h1_b, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x_b + h1_b, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        T = b""
        while len(T) < qlen_bytes:
            V = hmac.new(K, V, hashlib.sha256).digest()
            T += V
        k = _bits2int(T[:qlen_bytes], curve.n)
        if 1 <= k < curve.n:
            return k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


def ecdsa_sign(curve: Curve, priv: int, msg: bytes) -> Tuple[int, int]:
    digest = hashlib.sha256(msg).digest()
    z = _bits2int(digest, curve.n)
    while True:
        k = rfc6979_nonce(curve, priv, digest)
        pt = curve.mul(k, curve.g)
        r = pt[0] % curve.n
        if r == 0:
            continue
        s = (z + r * priv) * pow(k, curve.n - 2, curve.n) % curve.n
        if s == 0:
            continue
        # low-s normalisation (matches BouncyCastle/ canonical signatures)
        if s > curve.n // 2:
            s = curve.n - s
        return (r, s)


def ecdsa_verify(curve: Curve, pub: Affine, msg: bytes, r: int, s: int) -> bool:
    if pub is None or not curve.contains(pub):
        return False
    if not (1 <= r < curve.n and 1 <= s < curve.n):
        return False
    digest = hashlib.sha256(msg).digest()
    z = _bits2int(digest, curve.n)
    w = pow(s, curve.n - 2, curve.n)
    u1 = z * w % curve.n
    u2 = r * w % curve.n
    pt = curve.add(curve.mul(u1, curve.g), curve.mul(u2, pub))
    if pt is None:
        return False
    return pt[0] % curve.n == r


# --- DER (r,s) encoding, as emitted by JCA/BouncyCastle ---------------------

def der_encode_sig(r: int, s: int) -> bytes:
    def _int(v: int) -> bytes:
        b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if b[0] & 0x80:
            b = b"\x00" + b
        return b"\x02" + bytes([len(b)]) + b

    body = _int(r) + _int(s)
    return b"\x30" + bytes([len(body)]) + body


def der_decode_sig(data: bytes) -> Tuple[int, int]:
    """STRICT DER (r, s) decode, matching OpenSSL/BouncyCastle: minimal
    integer encodings only, no negative values, short-form lengths.  All
    verification paths (OpenSSL loop, device kernels, native host batch)
    must share one parsing rule or a crafted encoding would verify on
    one path and fail on another."""
    if len(data) < 8 or data[0] != 0x30:
        raise ValueError("bad DER signature")
    if data[1] > 0x7F or data[1] != len(data) - 2:
        raise ValueError("bad DER length")
    i = 2

    def _int() -> int:
        nonlocal i
        if i + 2 > len(data) or data[i] != 0x02:
            raise ValueError("expected DER INTEGER")
        ln = data[i + 1]
        if ln == 0 or ln > 0x7F or i + 2 + ln > len(data):
            raise ValueError("bad DER INTEGER length")
        body = data[i + 2 : i + 2 + ln]
        if body[0] & 0x80:
            raise ValueError("negative DER INTEGER")
        if ln > 1 and body[0] == 0 and not (body[1] & 0x80):
            raise ValueError("non-minimal DER INTEGER")
        i += 2 + ln
        return int.from_bytes(body, "big")

    r = _int()
    s = _int()
    if i != len(data):
        raise ValueError("trailing DER bytes")
    return r, s

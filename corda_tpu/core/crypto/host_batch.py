"""Host-CPU batched ed25519 verification via random linear combination.

The reference verifies one signature at a time through BouncyCastle
(`core/.../crypto/Crypto.kt:535-541`, ~2-3k verifies/s/core); plain
OpenSSL does ~7k/s/core.  This module verifies a whole batch with ONE
Pippenger multi-scalar multiplication (native/src/ed25519_msm.cpp):

    8 * [ sum z_i R_i + sum_k (sum_{i in k} z_i h_i) A_k
          - (sum z_i s_i) B ]  ==  identity

with independent random 128-bit z_i per signature, h_i = SHA-512(R_i ||
A_i || M_i) mod L, and the A-terms aggregated per distinct public key
(notary batches have many signatures from few signers).  Cost per
signature falls from one full double-scalar multiplication to a few
dozen curve additions, ~5x faster than OpenSSL at batch >= 1k.

Semantics:
  * a batch that fails splits recursively, so rejects carry exact
    per-signature positions; LEAVES are decided by the same cofactored
    one-row equation as full batches — ONE verification rule for every
    signature regardless of which batch it landed in (a leaf deciding
    by cofactorless OpenSSL instead would let the same signature
    verify True or False depending on batch composition)
  * that rule is the cofactored equation ZIP-215 standardises for
    consensus use.  For adversarially crafted signatures exploiting the
    small torsion subgroup, cofactored verification can accept where
    cofactorless (OpenSSL/BouncyCastle) single verification rejects —
    accepts form a strict SUPERSET, honestly generated signatures are
    never affected.  The dispatch layer applies this rule to EVERY
    ed25519 bucket size when the native engine is available, so the
    acceptance set is a deployment property rather than a batch-size
    accident.  Deployments that must match cofactorless OpenSSL
    bit-for-bit set CORDA_TPU_HOST_BATCH=0, which routes everything to
    the OpenSSL loop.
  * non-canonical encodings (y >= p, s >= L) and malformed shapes are
    rejected up front, matching RFC 8032 / OpenSSL strictness.
"""
from __future__ import annotations

import hashlib
import os
import secrets
from typing import List, Sequence, Tuple

L = 2**252 + 27742317777372353535851937790883648493
P = 2**255 - 19
#: compressed base point: x sign 0, y = 4/5 mod p
B_COMPRESSED = bytes([0x58]) + b"\x66" * 31

Row = Tuple[bytes, bytes, bytes]  # (public_key_32, signature_64, message)


def available() -> bool:
    if os.environ.get("CORDA_TPU_HOST_BATCH") == "0":
        return False
    from ... import native

    return native.available()


def verify_batch_host(rows: Sequence[Row]) -> List[bool]:
    """Positionally-aligned verdicts for (pub, sig, msg) rows."""
    results = [False] * len(rows)
    good: List[int] = []
    for i, (pub, sig, msg) in enumerate(rows):
        if (
            isinstance(pub, (bytes, bytearray)) and len(pub) == 32
            and isinstance(sig, (bytes, bytearray)) and len(sig) == 64
            and isinstance(msg, (bytes, bytearray))
            and int.from_bytes(sig[32:], "little") < L
            and int.from_bytes(pub, "little") & (2**255 - 1) < P
            and int.from_bytes(sig[:32], "little") & (2**255 - 1) < P
        ):
            good.append(i)
        # else: malformed/non-canonical row stays False
    # h_i is deterministic per row: hash ONCE up front (one batched
    # native SHA-512+reduce pass), not once per recursion level
    hs = _hashes_mod_l(rows, good)
    _verify_range(rows, good, hs, results)
    return results


def _hashes_mod_l(rows: Sequence[Row], idx: List[int]) -> dict:
    """row index -> SHA-512(R || A || M) mod L, hashed in one batched
    native pass (sha512_mod_l_many carries its own pure-Python fallback,
    so no second fallback here)."""
    from ... import native

    msgs = []
    for i in idx:
        pub, sig, msg = rows[i]
        msgs.append(bytes(sig[:32]) + bytes(pub) + bytes(msg))
    words = native.sha512_mod_l_many(msgs)  # (n, 8) uint32 LE
    return {
        i: int.from_bytes(words[j].tobytes(), "little")
        for j, i in enumerate(idx)
    }


def _verify_range(rows: Sequence[Row], idx: List[int], hs: dict,
                  results: List[bool]) -> None:
    if not idx:
        return
    # leaves use the SAME cofactored one-row equation as full batches:
    # one verification rule for every signature, regardless of which
    # batch composition it happened to land in
    if len(idx) == 1:
        results[idx[0]] = _batch_equation_holds(rows, idx, hs)
        return
    if _batch_equation_holds(rows, idx, hs):
        for i in idx:
            results[i] = True
        return
    # some signature is bad: binary-search it out so rejects keep exact
    # positions (and the good half still verifies at batch speed)
    mid = len(idx) // 2
    _verify_range(rows, idx[:mid], hs, results)
    _verify_range(rows, idx[mid:], hs, results)


def _batch_equation_holds(rows: Sequence[Row], idx: List[int],
                          hs: dict) -> bool:
    from ... import native

    pts = bytearray()
    scalars = bytearray()
    key_terms: dict = {}  # pub bytes -> aggregated (z*h) scalar
    b_acc = 0
    # one urandom syscall for the whole batch's blinding scalars (a
    # per-row secrets.randbits was ~10% of host-side prep)
    zbytes = secrets.token_bytes(16 * len(idx))
    for k, i in enumerate(idx):
        pub, sig, msg = rows[i]
        pub, sig = bytes(pub), bytes(sig)
        z = int.from_bytes(zbytes[16 * k:16 * k + 16], "little") | 1
        pts += sig[:32]
        scalars += z.to_bytes(32, "little")
        key_terms[pub] = (key_terms.get(pub, 0) + z * hs[i]) % L
        b_acc = (b_acc + z * int.from_bytes(sig[32:], "little")) % L
    for pub, c in key_terms.items():
        pts += pub
        scalars += c.to_bytes(32, "little")
    pts += B_COMPRESSED
    scalars += ((L - b_acc) % L).to_bytes(32, "little")
    verdict = native.ed25519_msm_is_small(
        bytes(pts), bytes(scalars), len(pts) // 32
    )
    return verdict == 1

"""Host-CPU batched ed25519 verification via random linear combination.

The reference verifies one signature at a time through BouncyCastle
(`core/.../crypto/Crypto.kt:535-541`, ~2-3k verifies/s/core); plain
OpenSSL does ~7k/s/core.  This module verifies a whole batch with ONE
Pippenger multi-scalar multiplication (native/src/ed25519_msm.cpp):

    8 * [ sum z_i R_i + sum_k (sum_{i in k} z_i h_i) A_k
          - (sum z_i s_i) B ]  ==  identity

with independent random 128-bit z_i per signature, h_i = SHA-512(R_i ||
A_i || M_i) mod L, and the A-terms aggregated per distinct public key
(notary batches have many signatures from few signers).  Cost per
signature falls from one full double-scalar multiplication to a few
dozen curve additions, ~5x faster than OpenSSL at batch >= 1k.

Semantics:
  * a batch that fails splits recursively, so rejects carry exact
    per-signature positions; LEAVES are decided by the same cofactored
    one-row equation as full batches — ONE verification rule for every
    signature regardless of which batch it landed in (a leaf deciding
    by cofactorless OpenSSL instead would let the same signature
    verify True or False depending on batch composition)
  * that rule is the cofactored equation ZIP-215 standardises for
    consensus use.  For adversarially crafted signatures exploiting the
    small torsion subgroup, cofactored verification can accept where
    cofactorless (OpenSSL/BouncyCastle) single verification rejects —
    accepts form a strict SUPERSET, honestly generated signatures are
    never affected.  The dispatch layer applies this rule to EVERY
    ed25519 bucket size when the native engine is available, so the
    acceptance set is a deployment property rather than a batch-size
    accident.  Deployments that must match cofactorless OpenSSL
    bit-for-bit set CORDA_TPU_HOST_BATCH=0, which routes everything to
    the OpenSSL loop.
  * non-canonical encodings (y >= p, s >= L) and malformed shapes are
    rejected up front, matching RFC 8032 / OpenSSL strictness.
"""
from __future__ import annotations

import hashlib
import os
import secrets
import threading
from collections import OrderedDict
from typing import List, Sequence, Tuple

L = 2**252 + 27742317777372353535851937790883648493
P = 2**255 - 19
#: compressed base point: x sign 0, y = 4/5 mod p
B_COMPRESSED = bytes([0x58]) + b"\x66" * 31

Row = Tuple[bytes, bytes, bytes]  # (public_key_32, signature_64, message)


def available() -> bool:
    if os.environ.get("CORDA_TPU_HOST_BATCH") == "0":
        return False
    from ... import native

    return native.available()


def prehash_rows(rows: Sequence[Row]):
    """The splittable PREHASH phase: canonicality filter plus ONE batched
    native SHA-512+reduce pass over the well-formed rows.

    Returns ``(good, hs)`` ready to hand to :func:`verify_batch_host` as
    ``prehashed=``.  The verification pipeline (verifier/pipeline.py)
    runs this on its prehash stage thread — the native hashing releases
    the GIL, so batch N+1 hashes while the MSM verifies batch N."""
    good: List[int] = []
    for i, (pub, sig, msg) in enumerate(rows):
        if (
            isinstance(pub, (bytes, bytearray)) and len(pub) == 32
            and isinstance(sig, (bytes, bytearray)) and len(sig) == 64
            and isinstance(msg, (bytes, bytearray))
            and int.from_bytes(sig[32:], "little") < L
            and int.from_bytes(pub, "little") & (2**255 - 1) < P
            and int.from_bytes(sig[:32], "little") & (2**255 - 1) < P
        ):
            good.append(i)
        # else: malformed/non-canonical row stays False
    # h_i is deterministic per row: hash ONCE up front (one batched
    # native SHA-512+reduce pass), not once per recursion level
    hs = _hashes_mod_l(rows, good)
    return good, hs


def verify_batch_host(rows: Sequence[Row], prehashed=None) -> List[bool]:
    """Positionally-aligned verdicts for (pub, sig, msg) rows.

    ``prehashed``: an optional ``(good, hs)`` pair from
    :func:`prehash_rows` over the SAME rows — the staged pipeline hashes
    on its own stage thread and hands the result here; omitted, both
    phases run back-to-back (the synchronous path, byte-identical to the
    pre-pipeline behaviour)."""
    results = [False] * len(rows)
    good, hs = prehashed if prehashed is not None else prehash_rows(rows)
    _verify_range(rows, good, hs, results)
    return results


def _hashes_mod_l(rows: Sequence[Row], idx: List[int]) -> dict:
    """row index -> SHA-512(R || A || M) mod L as 32 little-endian
    bytes, hashed in one batched native pass (sha512_mod_l_many carries
    its own pure-Python fallback, so no second fallback here).  Kept as
    raw bytes: the scalar prep consumes them natively, so converting to
    Python ints here would be pure overhead."""
    from ... import native

    msgs = []
    for i in idx:
        pub, sig, msg = rows[i]
        msgs.append(bytes(sig[:32]) + bytes(pub) + bytes(msg))
    words = native.sha512_mod_l_many(msgs)  # (n, 8) uint32 LE
    return {i: words[j].tobytes() for j, i in enumerate(idx)}


def _verify_range(rows: Sequence[Row], idx: List[int], hs: dict,
                  results: List[bool]) -> None:
    if not idx:
        return
    # leaves use the SAME cofactored one-row equation as full batches:
    # one verification rule for every signature, regardless of which
    # batch composition it happened to land in
    if len(idx) == 1:
        results[idx[0]] = _batch_equation_holds(rows, idx, hs)
        return
    if _batch_equation_holds(rows, idx, hs):
        for i in idx:
            results[i] = True
        return
    # some signature is bad: binary-search it out so rejects keep exact
    # positions (and the good half still verifies at batch speed)
    mid = len(idx) // 2
    _verify_range(rows, idx[:mid], hs, results)
    _verify_range(rows, idx[mid:], hs, results)


# Per-key decompressed-A cache (r4 VERDICT weak #3).  Point
# decompression costs a ~265-field-mul power chain; with few signers the
# A terms aggregate and decompression is negligible, but an all-distinct-
# key batch (many-party networks) pays one chain per signature just for
# the A points.  Caching the affine (x||y) pair per pubkey makes repeat
# keys decompression-free on the A side: the MSM receives cached keys as
# affine slots (one field mul to load) and only R points — necessarily
# fresh per signature — still decompress.  Keyed on the exact 32-byte
# encoding (non-canonical encodings were rejected up front), capped LRU.
_A_CACHE: "OrderedDict[bytes, bytes]" = OrderedDict()
_A_CACHE_MAX = 1 << 16  # 64k keys x 64B values ~ 4MB + dict overhead
_A_CACHE_LOCK = threading.Lock()


def _affine_for_keys(pubs: List[bytes]) -> dict:
    """pub -> 64-byte affine pair for every key that decompresses; keys
    not on the curve are absent (the caller passes those compressed and
    the native MSM rejects them, exactly as before the cache).
    `pubs` must be distinct (they are the key_terms grouping keys)."""
    from ... import native

    out: dict = {}
    missing: List[bytes] = []
    with _A_CACHE_LOCK:
        for pub in pubs:
            aff = _A_CACHE.get(pub)
            if aff is not None:
                _A_CACHE.move_to_end(pub)
                out[pub] = aff
            else:
                missing.append(pub)
    if missing:
        decompressed = native.ed25519_decompress_many(missing)
        with _A_CACHE_LOCK:
            for pub, aff in zip(missing, decompressed):
                if aff is not None:
                    out[pub] = aff
                    _A_CACHE[pub] = aff
            while len(_A_CACHE) > _A_CACHE_MAX:
                _A_CACHE.popitem(last=False)
    return out


def _batch_equation_holds(rows: Sequence[Row], idx: List[int],
                          hs: dict) -> bool:
    from ... import native

    n = len(idx)
    group_of: dict = {}  # pub bytes -> group id
    pubs: List[bytes] = []  # distinct pubs, group-id order
    sig_buf = bytearray()
    r_slots = bytearray()  # R points, compressed (fresh per signature)
    h_buf = bytearray()
    gids = bytearray()  # little-endian u32 per row
    for i in idx:
        pub, sig, msg = rows[i]
        pub, sig = bytes(pub), bytes(sig)
        g = group_of.get(pub)
        if g is None:
            g = group_of[pub] = len(pubs)
            pubs.append(pub)
        gids += g.to_bytes(4, "little")
        sig_buf += sig
        r_slots += sig[:32] + b"\x00" * 32
        h_buf += hs[i]
    # one urandom syscall for the whole batch's blinding scalars, then
    # one native pass for every z*h / z*s mulmod (the per-row Python
    # bigint loop was the last host-side prep cost)
    zbytes = secrets.token_bytes(16 * n)
    z_scalars, key_accums, b_accum = native.ed25519_msm_prep(
        bytes(sig_buf), bytes(h_buf), zbytes, bytes(gids), n, len(pubs)
    )
    affine = _affine_for_keys(pubs)
    pts = r_slots
    mask = bytearray(n)
    for g, pub in enumerate(pubs):
        aff = affine.get(pub)
        if aff is not None:
            pts += aff
            mask.append(1)
        else:  # not on the curve: the MSM rejects it, as pre-cache
            pts += pub + b"\x00" * 32
            mask.append(0)
    pts += B_COMPRESSED + b"\x00" * 32
    mask.append(0)
    b_acc = int.from_bytes(b_accum, "little")
    scalars = (
        z_scalars + key_accums + ((L - b_acc) % L).to_bytes(32, "little")
    )
    verdict = native.ed25519_msm_is_small_mixed(
        bytes(pts), bytes(mask), scalars, len(pts) // 64
    )
    return verdict == 1

"""Host-CPU batched ECDSA verification (secp256k1 / secp256r1).

The reference verifies ECDSA per signature through BouncyCastle
(`core/.../crypto/Crypto.kt:91-151`, ~2-3k/s/core); the `cryptography`
loop in this package does ~7.3k/s and raw OpenSSL tops out ~12k/s on
the 1-core CI box.  This module fronts the native engine
(native/src/ecdsa_host.cpp): Montgomery 4x64 field arithmetic,
fixed-base combs (zero doublings for the shared base G and for cached
hot public keys), and batch-amortized inversions — ~20k P-256
verifies/s warm.

Unlike ed25519 there is no batch equation (R rides only as r = R.x
mod n), so the acceptance rule is plain per-signature ECDSA — identical
to the OpenSSL loop — and routing here at any batch size cannot split
verdicts.  DER parsing is the strict shared rule in
secp_math.der_decode_sig.

The per-key decompressed-point cache mirrors host_batch's ed25519
A-cache: SEC1-compressed keys (the scheme's 33-byte encoding) cost a
~256-squaring sqrt chain to open; repeat keys skip it.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import List, Sequence, Tuple

from . import secp_math

#: curve name -> (native curve id, group order)
CURVE_IDS = {
    "secp256k1": (0, secp_math.SECP256K1.n),
    "secp256r1": (1, secp_math.SECP256R1.n),
}

Row = Tuple[bytes, bytes, bytes]  # (pub SEC1, signature DER, message)

# pub bytes -> 64-byte big-endian affine X||Y, per curve
_PT_CACHE: "OrderedDict[tuple, bytes]" = OrderedDict()
_PT_CACHE_MAX = 1 << 16
_PT_CACHE_LOCK = threading.Lock()


def available() -> bool:
    if os.environ.get("CORDA_TPU_ECDSA_HOST") == "0":
        return False
    from ... import native

    return native.available()


def _affine_pubs(curve_id: int, pubs: Sequence[bytes]) -> List[bytes | None]:
    """Each SEC1 encoding -> 64-byte affine X||Y, or None if invalid.
    Uncompressed (0x04) points pass through (the native verifier
    validates curve membership); compressed points decompress through
    the per-key LRU cache in one batched native call."""
    from ... import native

    out: List[bytes | None] = [None] * len(pubs)
    missing: List[int] = []
    with _PT_CACHE_LOCK:
        for i, pub in enumerate(pubs):
            if len(pub) == 65 and pub[0] == 4:
                out[i] = pub[1:]
                continue
            if len(pub) != 33 or pub[0] not in (2, 3):
                continue
            hit = _PT_CACHE.get((curve_id, pub))
            if hit is not None:
                _PT_CACHE.move_to_end((curve_id, pub))
                out[i] = hit
            else:
                missing.append(i)
    if missing:
        # decompress each DISTINCT missing encoding once
        uniq: dict = {}
        for i in missing:
            uniq.setdefault(pubs[i], None)
        order = list(uniq)
        decompressed = native.ecdsa_decompress_many(curve_id, order)
        for pub, aff in zip(order, decompressed):
            uniq[pub] = aff
        with _PT_CACHE_LOCK:
            for i in missing:
                out[i] = uniq[pubs[i]]
            for pub, aff in uniq.items():
                if aff is not None:
                    _PT_CACHE[(curve_id, pub)] = aff
            while len(_PT_CACHE) > _PT_CACHE_MAX:
                _PT_CACHE.popitem(last=False)
    return out


def verify_batch_host(
    curve_name: str,
    public_keys: Sequence[bytes],
    signatures: Sequence[bytes],
    messages: Sequence[bytes],
) -> List[bool]:
    """Positionally-aligned verdicts; malformed rows are False, never
    exceptions (the dispatch contract shared with the device kernels)."""
    from ... import native

    curve_id, n_order = CURVE_IDS[curve_name]
    n = len(public_keys)
    results = [False] * n
    affine = _affine_pubs(curve_id, public_keys)
    rows: List[int] = []
    rs_buf = bytearray()
    pub_buf = bytearray()
    msgs: List[bytes] = []
    for i in range(n):
        aff = affine[i]
        if aff is None:
            continue
        try:
            r, s = secp_math.der_decode_sig(bytes(signatures[i]))
        except (ValueError, IndexError, TypeError):
            continue
        if not (0 < r < n_order and 0 < s < n_order):
            continue
        rows.append(i)
        pub_buf += aff
        rs_buf += r.to_bytes(32, "big") + s.to_bytes(32, "big")
        msgs.append(bytes(messages[i]))
    if not rows:
        return results
    digests = b"".join(native.sha256_many(msgs))
    verdicts = native.ecdsa_verify_batch_host(
        curve_id, bytes(pub_buf), bytes(rs_buf), digests, len(rows)
    )
    for j, i in enumerate(rows):
        results[i] = verdicts[j]
    return results

"""SecureHash: content-addressing value type.

Parity: reference `core/src/main/kotlin/net/corda/core/crypto/SecureHash.kt:14-49`
(SHA-256 value type with `sha256`, `hashConcat`, `zeroHash`, `randomSHA256`).
"""
from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class SecureHash:
    """An immutable 32-byte SHA-256 digest identifying some content."""

    bytes: bytes

    SIZE = 32

    def __post_init__(self):
        if len(self.bytes) != self.SIZE:
            raise ValueError(f"SecureHash must be {self.SIZE} bytes, got {len(self.bytes)}")

    # -- constructors -------------------------------------------------------
    @staticmethod
    def sha256(data: bytes) -> "SecureHash":
        return SecureHash(hashlib.sha256(data).digest())

    @staticmethod
    def sha256_twice(data: bytes) -> "SecureHash":
        return SecureHash.sha256(hashlib.sha256(data).digest())

    @staticmethod
    def parse(hex_str: str) -> "SecureHash":
        return SecureHash(bytes.fromhex(hex_str))

    @staticmethod
    def random_sha256() -> "SecureHash":
        return SecureHash.sha256(os.urandom(32))

    @staticmethod
    def zero_hash() -> "SecureHash":
        return SecureHash(b"\x00" * SecureHash.SIZE)

    @staticmethod
    def all_ones_hash() -> "SecureHash":
        return SecureHash(b"\xff" * SecureHash.SIZE)

    # -- operations ---------------------------------------------------------
    def hash_concat(self, other: "SecureHash") -> "SecureHash":
        """Digest of the concatenation of two hashes (Merkle node combiner)."""
        return SecureHash.sha256(self.bytes + other.bytes)

    def re_hash(self) -> "SecureHash":
        return SecureHash.sha256(self.bytes)

    def prefix_chars(self, count: int = 6) -> str:
        return self.bytes.hex().upper()[:count]

    def __str__(self) -> str:
        return self.bytes.hex().upper()

    def __repr__(self) -> str:
        return f"SecureHash({self})"


ZERO_HASH = SecureHash.zero_hash()
ALL_ONES_HASH = SecureHash.all_ones_hash()


def secure_random_bytes(n: int) -> bytes:
    return os.urandom(n)


def random_63_bit_value() -> int:
    """A random positive 63-bit integer (reference CryptoUtils.random63BitValue)."""
    while True:
        v = int.from_bytes(os.urandom(8), "big") & 0x7FFF_FFFF_FFFF_FFFF
        if v != 0:
            return v

"""CompositeKey: threshold multi-signature key trees.

Parity: reference `core/src/main/kotlin/net/corda/core/crypto/composite/
CompositeKey.kt` (weighted children, nested trees, `isFulfilledBy` threshold
evaluation, duplicate/weight validation) and `CompositeSignature.kt` /
`CompositeSignaturesWithKeys.kt`. Where the reference plugs into the JCA via a
custom provider (`CordaSecurityProvider.kt`), here CompositeKey is simply a
PublicKey subtype understood by `crypto.is_valid` and (for batch evaluation)
by the verifier's bitmask combiner: the TPU kernel verifies leaf signatures as
a flat batch and the threshold logic folds the resulting pass/fail bitmask up
the tree on the host (pure integer logic, negligible cost).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import FrozenSet, Iterable, List, Sequence, Tuple

from .keys import PublicKey, SchemePublicKey
from .schemes import COMPOSITE_KEY, SCHEMES_BY_ID, SUPPORTED_SIGNATURE_SCHEMES

_LEAF_TAG = 1
_NODE_TAG = 2


@dataclass(frozen=True)
class NodeAndWeight:
    node: PublicKey
    weight: int

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError("weights must be positive")


class CompositeKey(PublicKey):
    """An immutable weighted-threshold tree over leaf public keys."""

    def __init__(self, threshold: int, children: Sequence[NodeAndWeight]):
        if threshold <= 0:
            raise ValueError("threshold must be positive")
        if not children:
            raise ValueError("composite key must have children")
        total = sum(c.weight for c in children)
        if threshold > total:
            raise ValueError(
                f"threshold {threshold} exceeds sum of weights {total}"
            )
        # deterministic ordering for a canonical encoding
        self.threshold = threshold
        self.children: Tuple[NodeAndWeight, ...] = tuple(
            sorted(children, key=lambda c: (_encode_node(c.node), c.weight))
        )
        self.scheme_code_name = COMPOSITE_KEY.scheme_code_name
        self._check_validity()
        self.encoded = _encode_node(self)

    # -- validation (reference CompositeKey.checkValidity) -------------------
    def _check_validity(self):
        seen: set = set()
        self._check_duplicates(seen)

    def _check_duplicates(self, seen: set):
        for c in self.children:
            if isinstance(c.node, CompositeKey):
                c.node._check_duplicates(seen)
            else:
                if c.node in seen:
                    raise ValueError("duplicate leaf keys in composite key tree")
                seen.add(c.node)

    # -- evaluation ----------------------------------------------------------
    @property
    def keys(self) -> FrozenSet[PublicKey]:
        out: set = set()
        for c in self.children:
            out |= c.node.keys
        return frozenset(out)

    def is_fulfilled_by(self, keys: Iterable[PublicKey]) -> bool:
        ks = set(keys)
        return self._fulfilled(ks)

    def _fulfilled(self, ks: set) -> bool:
        total = 0
        for c in self.children:
            if isinstance(c.node, CompositeKey):
                if c.node._fulfilled(ks):
                    total += c.weight
            elif c.node in ks:
                total += c.weight
            if total >= self.threshold:
                return True
        return False

    def verify_composite(self, sigs: "CompositeSignaturesWithKeys", content: bytes) -> bool:
        """Check enough leaf signatures are present AND each one is valid."""
        from . import crypto

        valid_keys = set()
        for pub, sig in sigs.sigs:
            if crypto.is_valid(pub, sig, content):
                valid_keys.add(pub)
            else:
                return False  # any invalid constituent fails the whole composite
        return self.is_fulfilled_by(valid_keys)

    # -- identity ------------------------------------------------------------
    def __eq__(self, other) -> bool:
        return isinstance(other, CompositeKey) and self.encoded == other.encoded

    def __hash__(self) -> int:
        return hash(self.encoded)

    def __repr__(self) -> str:
        return f"CompositeKey(threshold={self.threshold}, children={len(self.children)})"

    # -- builder (reference CompositeKey.Builder) ----------------------------
    class Builder:
        def __init__(self):
            self._children: List[NodeAndWeight] = []

        def add_key(self, key: PublicKey, weight: int = 1) -> "CompositeKey.Builder":
            self._children.append(NodeAndWeight(key, weight))
            return self

        def add_keys(self, *keys: PublicKey) -> "CompositeKey.Builder":
            for k in keys:
                self.add_key(k)
            return self

        def build(self, threshold: int | None = None) -> PublicKey:
            n = len(self._children)
            if n == 0:
                raise ValueError("cannot build composite key with zero children")
            th = threshold if threshold is not None else sum(c.weight for c in self._children)
            # single-child with full threshold collapses to the child itself
            if n == 1 and th == self._children[0].weight:
                return self._children[0].node
            return CompositeKey(th, self._children)


# --- canonical binary encoding of key trees ---------------------------------

def _encode_node(key: PublicKey) -> bytes:
    if isinstance(key, CompositeKey):
        out = [struct.pack(">BII", _NODE_TAG, key.threshold, len(key.children))]
        for c in key.children:
            child = _encode_node(c.node)
            out.append(struct.pack(">I", c.weight))
            out.append(struct.pack(">I", len(child)))
            out.append(child)
        return b"".join(out)
    scheme = SUPPORTED_SIGNATURE_SCHEMES[key.scheme_code_name]
    return struct.pack(">BBI", _LEAF_TAG, scheme.scheme_number_id, len(key.encoded)) + key.encoded


def _decode_node(data: bytes, offset: int = 0) -> Tuple[PublicKey, int]:
    tag = data[offset]
    if tag == _LEAF_TAG:
        _, scheme_id, ln = struct.unpack_from(">BBI", data, offset)
        offset += 6
        if offset + ln > len(data):
            raise ValueError("composite key leaf length exceeds buffer")
        enc = data[offset : offset + ln]
        if scheme_id not in SCHEMES_BY_ID:
            raise ValueError(f"unknown scheme id {scheme_id} in composite key")
        scheme = SCHEMES_BY_ID[scheme_id]
        return SchemePublicKey(scheme.scheme_code_name, enc), offset + ln
    if tag == _NODE_TAG:
        _, threshold, n = struct.unpack_from(">BII", data, offset)
        offset += 9
        children = []
        for _ in range(n):
            (weight,) = struct.unpack_from(">I", data, offset)
            offset += 4
            (ln,) = struct.unpack_from(">I", data, offset)
            offset += 4
            child, consumed = _decode_node(data, offset)
            if consumed != offset + ln:
                raise ValueError("composite key child length mismatch")
            offset = consumed
            children.append(NodeAndWeight(child, weight))
        return CompositeKey(threshold, children), offset
    raise ValueError(f"bad composite key tag {tag}")


def decode_composite_key(data: bytes) -> PublicKey:
    key, consumed = _decode_node(data)
    if consumed != len(data):
        raise ValueError("trailing bytes in composite key encoding")
    return key


@dataclass(frozen=True)
class CompositeSignaturesWithKeys:
    """An aggregate of leaf (key, signature) pairs satisfying a CompositeKey.

    Parity: reference `composite/CompositeSignaturesWithKeys.kt`.
    """

    sigs: Tuple[Tuple[PublicKey, bytes], ...] = field(default_factory=tuple)

    def serialize(self) -> bytes:
        out = [struct.pack(">I", len(self.sigs))]
        for pub, sig in self.sigs:
            enc = _encode_node(pub)
            out.append(struct.pack(">I", len(enc)))
            out.append(enc)
            out.append(struct.pack(">I", len(sig)))
            out.append(sig)
        return b"".join(out)

    @staticmethod
    def deserialize(data: bytes) -> "CompositeSignaturesWithKeys":
        (n,) = struct.unpack_from(">I", data, 0)
        offset = 4
        sigs = []
        for _ in range(n):
            (ln,) = struct.unpack_from(">I", data, offset)
            offset += 4
            pub, consumed = _decode_node(data, offset)
            if consumed != offset + ln:
                raise ValueError("composite signature key length mismatch")
            offset = consumed
            (sl,) = struct.unpack_from(">I", data, offset)
            offset += 4
            if offset + sl > len(data):
                raise ValueError("composite signature length exceeds buffer")
            sigs.append((pub, data[offset : offset + sl]))
            offset += sl
        return CompositeSignaturesWithKeys(tuple(sigs))

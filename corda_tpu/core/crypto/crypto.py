"""Crypto: the central sign/verify/keygen/derive hub (host path).

Parity: reference `core/src/main/kotlin/net/corda/core/crypto/Crypto.kt`
(`doSign` :394-401, `doVerify` :473-483, `isValid` :535-541,
`findSignatureScheme` :250-253, `deriveKeyPairFromEntropy` :718-739,
`publicKeyOnCurve` :859-871). The reference delegates per-scheme math to
BouncyCastle / i2p-EdDSA via the JCA; here the host path delegates to the
`cryptography` package (OpenSSL) plus pure-Python math for derivation, and the
*batch* path lives in corda_tpu.ops (JAX/TPU kernels) behind the
verifier seam -- this module is the scalar fallback and correctness oracle.
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
from typing import Iterable, Tuple

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import (
        ec, ed25519, padding, rsa,
    )

    OPENSSL_AVAILABLE = True
except ImportError:  # pragma: no cover - depends on the host image
    # Degrade to the in-repo pure-Python math (ed25519_math / secp_math —
    # the same modules that serve as the kernels' correctness oracles).
    # ed25519 verification here is cofactorless like OpenSSL's, so the
    # acceptance-rule pinning in core.crypto.batch is unaffected. RSA and
    # X.509 (pki.py) genuinely need OpenSSL and stay gated: their entry
    # points raise UnsupportedSchemeError with a clear message instead of
    # the whole package failing at import.
    OPENSSL_AVAILABLE = False
    ec = ed25519 = padding = rsa = hashes = serialization = None

    class InvalidSignature(Exception):
        pass

from . import ed25519_math, secp_math
from .keys import KeyPair, PublicKey, SchemePrivateKey, SchemePublicKey
from .schemes import (
    COMPOSITE_KEY,
    DEFAULT_SIGNATURE_SCHEME,
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
    RSA_SHA256,
    SCHEMES_BY_ID,
    SPHINCS256_SHA256,
    SUPPORTED_SIGNATURE_SCHEMES,
    SignatureScheme,
)

_EC_CURVES = {
    ECDSA_SECP256K1_SHA256.scheme_code_name: (
        ec.SECP256K1() if OPENSSL_AVAILABLE else None, secp_math.SECP256K1,
    ),
    ECDSA_SECP256R1_SHA256.scheme_code_name: (
        ec.SECP256R1() if OPENSSL_AVAILABLE else None, secp_math.SECP256R1,
    ),
}


class CryptoError(Exception):
    pass


class SignatureError(CryptoError):
    """Raised by do_verify on an invalid signature (reference: SignatureException)."""


class UnsupportedSchemeError(CryptoError):
    pass


def find_signature_scheme(key_or_name) -> SignatureScheme:
    """Resolve a SignatureScheme from a code name, numeric id, or key object."""
    if isinstance(key_or_name, SignatureScheme):
        return key_or_name
    if isinstance(key_or_name, int):
        try:
            return SCHEMES_BY_ID[key_or_name]
        except KeyError:
            raise UnsupportedSchemeError(f"unknown scheme id {key_or_name}")
    if isinstance(key_or_name, str):
        try:
            return SUPPORTED_SIGNATURE_SCHEMES[key_or_name]
        except KeyError:
            raise UnsupportedSchemeError(f"unknown scheme {key_or_name}")
    name = getattr(key_or_name, "scheme_code_name", None)
    if name is not None:
        return find_signature_scheme(name)
    raise UnsupportedSchemeError(f"cannot resolve scheme from {key_or_name!r}")


# Schemes in the registry whose algorithm implementation has not landed yet.
# (Empty since round 2: SPHINCS-256 landed as a full WOTS+/HORST hypertree.)
UNIMPLEMENTED_SCHEMES = frozenset()


def is_supported(scheme: SignatureScheme) -> bool:
    """Registry membership (metadata-recognized). Use is_operational to check
    whether sign/verify/keygen actually work for the scheme."""
    return scheme.scheme_code_name in SUPPORTED_SIGNATURE_SCHEMES


def is_operational(scheme: SignatureScheme) -> bool:
    return is_supported(scheme) and scheme.scheme_code_name not in UNIMPLEMENTED_SCHEMES


# --- key generation ---------------------------------------------------------

def generate_keypair(scheme: SignatureScheme = DEFAULT_SIGNATURE_SCHEME) -> KeyPair:
    name = scheme.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        seed = os.urandom(32)
        return _ed25519_keypair_from_seed(seed)
    if name in _EC_CURVES:
        curve = _EC_CURVES[name][1]
        d = (int.from_bytes(os.urandom(40), "big") % (curve.n - 1)) + 1
        return _ec_keypair_from_scalar(name, d)
    if name == RSA_SHA256.scheme_code_name:
        _require_openssl("RSA key generation")
        priv = rsa.generate_private_key(public_exponent=65537, key_size=3072)
        return _rsa_keypair(priv)
    if name == SPHINCS256_SHA256.scheme_code_name:
        from . import sphincs

        return sphincs.generate_keypair()
    raise UnsupportedSchemeError(f"cannot generate keys for {name}")


def _require_openssl(what: str) -> None:
    if not OPENSSL_AVAILABLE:
        raise UnsupportedSchemeError(
            f"{what} requires the 'cryptography' package (OpenSSL), "
            "which is not installed on this host"
        )


def _ed25519_keypair_from_seed(seed: bytes) -> KeyPair:
    name = EDDSA_ED25519_SHA512.scheme_code_name
    if OPENSSL_AVAILABLE:
        pub = ed25519.Ed25519PrivateKey.from_private_bytes(seed).public_key()
        pub_raw = pub.public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
    else:
        pub_raw = ed25519_math.public_from_seed(seed)
    return KeyPair(SchemePublicKey(name, pub_raw), SchemePrivateKey(name, seed))


def _ec_keypair_from_scalar(name: str, d: int) -> KeyPair:
    jca_curve, curve = _EC_CURVES[name]
    if OPENSSL_AVAILABLE:
        priv = ec.derive_private_key(d, jca_curve)
        pub_raw = priv.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.CompressedPoint,
        )
    else:
        pub_raw = curve.encode_point(curve.mul(d, curve.g), compressed=True)
    return KeyPair(
        SchemePublicKey(name, pub_raw),
        SchemePrivateKey(name, d.to_bytes(32, "big")),
    )


def _rsa_keypair(priv) -> KeyPair:
    name = RSA_SHA256.scheme_code_name
    pub_der = priv.public_key().public_bytes(
        serialization.Encoding.DER, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    priv_der = priv.private_bytes(
        serialization.Encoding.DER,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return KeyPair(SchemePublicKey(name, pub_der), SchemePrivateKey(name, priv_der))


# --- deterministic derivation (reference Crypto.kt:628-753) -----------------

def derive_keypair_from_entropy(
    scheme: SignatureScheme, entropy: int | bytes
) -> KeyPair:
    """Deterministic keypair from entropy (EdDSA + ECDSA only, like the reference).

    KDF: HMAC-SHA512(key=entropy, msg=scheme code name), then clamp/reduce.
    """
    if isinstance(entropy, int):
        entropy = entropy.to_bytes((entropy.bit_length() + 7) // 8 or 1, "big", signed=False)
    material = hmac_mod.new(entropy, scheme.scheme_code_name.encode(), hashlib.sha512).digest()
    name = scheme.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        return _ed25519_keypair_from_seed(material[:32])
    if name in _EC_CURVES:
        curve = _EC_CURVES[name][1]
        d = (int.from_bytes(material, "big") % (curve.n - 1)) + 1
        return _ec_keypair_from_scalar(name, d)
    raise UnsupportedSchemeError(f"deterministic derivation unsupported for {name}")


def derive_keypair(private: SchemePrivateKey, seed: bytes) -> KeyPair:
    """Derive a child keypair from a parent private key + seed (HKDF-style,
    reference Crypto.kt deriveKeyPair)."""
    scheme = find_signature_scheme(private.scheme_code_name)
    return derive_keypair_from_entropy(scheme, private.encoded + seed)


# --- sign / verify ----------------------------------------------------------

def do_sign(private: SchemePrivateKey, clear_data: bytes) -> bytes:
    if len(clear_data) == 0:
        raise CryptoError("signing of an empty array is not permitted")
    name = private.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        if not OPENSSL_AVAILABLE:
            return ed25519_math.sign(private.encoded, clear_data)
        return ed25519.Ed25519PrivateKey.from_private_bytes(private.encoded).sign(clear_data)
    if name in _EC_CURVES:
        jca_curve, curve = _EC_CURVES[name]
        d = int.from_bytes(private.encoded, "big")
        if not OPENSSL_AVAILABLE:
            return secp_math.der_encode_sig(
                *secp_math.ecdsa_sign(curve, d, clear_data)
            )
        return ec.derive_private_key(d, jca_curve).sign(clear_data, ec.ECDSA(hashes.SHA256()))
    if name == RSA_SHA256.scheme_code_name:
        _require_openssl("RSA signing")
        priv = serialization.load_der_private_key(private.encoded, password=None)
        return priv.sign(clear_data, padding.PKCS1v15(), hashes.SHA256())
    if name == SPHINCS256_SHA256.scheme_code_name:
        from . import sphincs

        return sphincs.sign(private, clear_data)
    raise UnsupportedSchemeError(f"cannot sign with {name}")


def do_verify(public: PublicKey, signature: bytes, clear_data: bytes) -> bool:
    """Verify and THROW SignatureError if invalid (reference Crypto.doVerify)."""
    if len(signature) == 0:
        raise CryptoError("verification of an empty signature is not permitted")
    if len(clear_data) == 0:
        raise CryptoError("verification of an empty payload is not permitted")
    if not is_valid(public, signature, clear_data):
        raise SignatureError(
            f"signature verification failed for scheme {public.scheme_code_name}"
        )
    return True


def is_valid(public: PublicKey, signature: bytes, clear_data: bytes) -> bool:
    """Boolean verify, never throws on bad signature (reference Crypto.isValid)."""
    import struct as _struct

    name = public.scheme_code_name
    try:
        if name == EDDSA_ED25519_SHA512.scheme_code_name:
            if not OPENSSL_AVAILABLE:
                # cofactorless, like OpenSSL: the deployment's pinned
                # ed25519 acceptance rule does not shift with this path
                return ed25519_math.verify(
                    public.encoded, clear_data, signature
                )
            ed25519.Ed25519PublicKey.from_public_bytes(public.encoded).verify(
                signature, clear_data
            )
            return True
        if name in _EC_CURVES:
            jca_curve, curve = _EC_CURVES[name]
            if not OPENSSL_AVAILABLE:
                r, s = secp_math.der_decode_sig(signature)
                return secp_math.ecdsa_verify(
                    curve, curve.decode_point(public.encoded),
                    clear_data, r, s,
                )
            pub = ec.EllipticCurvePublicKey.from_encoded_point(jca_curve, public.encoded)
            pub.verify(signature, clear_data, ec.ECDSA(hashes.SHA256()))
            return True
        if name == RSA_SHA256.scheme_code_name:
            _require_openssl("RSA verification")
            pub = serialization.load_der_public_key(public.encoded)
            pub.verify(signature, clear_data, padding.PKCS1v15(), hashes.SHA256())
            return True
        if name == SPHINCS256_SHA256.scheme_code_name:
            from . import sphincs

            return sphincs.verify(public, signature, clear_data)
        if name == COMPOSITE_KEY.scheme_code_name:
            from .composite import CompositeKey, CompositeSignaturesWithKeys

            if not isinstance(public, CompositeKey):
                return False  # scheme tag lies about the key's structure
            sigs = CompositeSignaturesWithKeys.deserialize(signature)
            return public.verify_composite(sigs, clear_data)
    except (InvalidSignature, ValueError, AssertionError, IndexError, _struct.error):
        return False
    raise UnsupportedSchemeError(f"cannot verify with {name}")


# --- validation helpers -----------------------------------------------------

def public_key_on_curve(public: PublicKey) -> bool:
    """Point-validation (reference Crypto.publicKeyOnCurve Crypto.kt:859-871)."""
    name = public.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        pt = ed25519_math.point_decompress(public.encoded)
        return pt is not None and ed25519_math.is_on_curve(pt)
    if name in _EC_CURVES:
        _, curve = _EC_CURVES[name]
        try:
            pt = curve.decode_point(public.encoded)
        except ValueError:
            return False
        return pt is not None and curve.contains(pt)
    return True  # not a curve-based key


def entropy_to_keypair(entropy: int) -> KeyPair:
    """Fixed-entropy test identities (reference TestConstants.entropyToKeyPair)."""
    return derive_keypair_from_entropy(EDDSA_ED25519_SHA512, entropy)

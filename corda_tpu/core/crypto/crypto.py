"""Crypto: the central sign/verify/keygen/derive hub (host path).

Parity: reference `core/src/main/kotlin/net/corda/core/crypto/Crypto.kt`
(`doSign` :394-401, `doVerify` :473-483, `isValid` :535-541,
`findSignatureScheme` :250-253, `deriveKeyPairFromEntropy` :718-739,
`publicKeyOnCurve` :859-871). The reference delegates per-scheme math to
BouncyCastle / i2p-EdDSA via the JCA; here the host path delegates to the
`cryptography` package (OpenSSL) plus pure-Python math for derivation, and the
*batch* path lives in corda_tpu.ops (JAX/TPU kernels) behind the
verifier seam -- this module is the scalar fallback and correctness oracle.
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
import os
import threading
from typing import Iterable, Tuple

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import (
        ec, ed25519, padding, rsa,
    )

    OPENSSL_AVAILABLE = True
except ImportError:  # pragma: no cover - depends on the host image
    # Degrade to the in-repo pure-Python math (ed25519_math / secp_math —
    # the same modules that serve as the kernels' correctness oracles).
    # ed25519 verification here is cofactorless like OpenSSL's, so the
    # acceptance-rule pinning in core.crypto.batch is unaffected. RSA and
    # X.509 (pki.py) genuinely need OpenSSL and stay gated: their entry
    # points raise UnsupportedSchemeError with a clear message instead of
    # the whole package failing at import.
    OPENSSL_AVAILABLE = False
    ec = ed25519 = padding = rsa = hashes = serialization = None

    class InvalidSignature(Exception):
        pass

from . import ed25519_math, secp_math
from .keys import KeyPair, PublicKey, SchemePrivateKey, SchemePublicKey
from .schemes import (
    BLS_BLS12381,
    COMPOSITE_KEY,
    DEFAULT_SIGNATURE_SCHEME,
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512,
    RSA_SHA256,
    SCHEMES_BY_ID,
    SPHINCS256_SHA256,
    SUPPORTED_SIGNATURE_SCHEMES,
    SignatureScheme,
)

_EC_CURVES = {
    ECDSA_SECP256K1_SHA256.scheme_code_name: (
        ec.SECP256K1() if OPENSSL_AVAILABLE else None, secp_math.SECP256K1,
    ),
    ECDSA_SECP256R1_SHA256.scheme_code_name: (
        ec.SECP256R1() if OPENSSL_AVAILABLE else None, secp_math.SECP256R1,
    ),
}


class CryptoError(Exception):
    pass


class SignatureError(CryptoError):
    """Raised by do_verify on an invalid signature (reference: SignatureException)."""


class UnsupportedSchemeError(CryptoError):
    pass


def find_signature_scheme(key_or_name) -> SignatureScheme:
    """Resolve a SignatureScheme from a code name, numeric id, or key object."""
    if isinstance(key_or_name, SignatureScheme):
        return key_or_name
    if isinstance(key_or_name, int):
        try:
            return SCHEMES_BY_ID[key_or_name]
        except KeyError:
            raise UnsupportedSchemeError(f"unknown scheme id {key_or_name}")
    if isinstance(key_or_name, str):
        try:
            return SUPPORTED_SIGNATURE_SCHEMES[key_or_name]
        except KeyError:
            raise UnsupportedSchemeError(f"unknown scheme {key_or_name}")
    name = getattr(key_or_name, "scheme_code_name", None)
    if name is not None:
        return find_signature_scheme(name)
    raise UnsupportedSchemeError(f"cannot resolve scheme from {key_or_name!r}")


# Schemes in the registry whose algorithm implementation has not landed yet.
# (Empty since round 2: SPHINCS-256 landed as a full WOTS+/HORST hypertree.)
UNIMPLEMENTED_SCHEMES = frozenset()


def is_supported(scheme: SignatureScheme) -> bool:
    """Registry membership (metadata-recognized). Use is_operational to check
    whether sign/verify/keygen actually work for the scheme."""
    return scheme.scheme_code_name in SUPPORTED_SIGNATURE_SCHEMES


def is_operational(scheme: SignatureScheme) -> bool:
    return is_supported(scheme) and scheme.scheme_code_name not in UNIMPLEMENTED_SCHEMES


# --- key generation ---------------------------------------------------------

def generate_keypair(scheme: SignatureScheme = DEFAULT_SIGNATURE_SCHEME) -> KeyPair:
    name = scheme.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        seed = os.urandom(32)
        return _ed25519_keypair_from_seed(seed)
    if name in _EC_CURVES:
        curve = _EC_CURVES[name][1]
        d = (int.from_bytes(os.urandom(40), "big") % (curve.n - 1)) + 1
        return _ec_keypair_from_scalar(name, d)
    if name == RSA_SHA256.scheme_code_name:
        _require_openssl("RSA key generation")
        priv = rsa.generate_private_key(public_exponent=65537, key_size=3072)
        return _rsa_keypair(priv)
    if name == SPHINCS256_SHA256.scheme_code_name:
        from . import sphincs

        return sphincs.generate_keypair()
    if name == BLS_BLS12381.scheme_code_name:
        from . import bls_math

        return _bls_keypair(bls_math.keygen(os.urandom(32)))
    raise UnsupportedSchemeError(f"cannot generate keys for {name}")


def _bls_keypair(sk: int) -> KeyPair:
    from . import bls_math

    name = BLS_BLS12381.scheme_code_name
    return KeyPair(
        SchemePublicKey(name, bls_math.sk_to_pk(sk)),
        SchemePrivateKey(name, sk.to_bytes(32, "big")),
    )


def _require_openssl(what: str) -> None:
    if not OPENSSL_AVAILABLE:
        raise UnsupportedSchemeError(
            f"{what} requires the 'cryptography' package (OpenSSL), "
            "which is not installed on this host"
        )


def _ed25519_keypair_from_seed(seed: bytes) -> KeyPair:
    name = EDDSA_ED25519_SHA512.scheme_code_name
    if OPENSSL_AVAILABLE:
        pub = ed25519.Ed25519PrivateKey.from_private_bytes(seed).public_key()
        pub_raw = pub.public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
    else:
        pub_raw = ed25519_math.public_from_seed(seed)
    return KeyPair(SchemePublicKey(name, pub_raw), SchemePrivateKey(name, seed))


def _ec_keypair_from_scalar(name: str, d: int) -> KeyPair:
    jca_curve, curve = _EC_CURVES[name]
    if OPENSSL_AVAILABLE:
        priv = ec.derive_private_key(d, jca_curve)
        pub_raw = priv.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.CompressedPoint,
        )
    else:
        pub_raw = curve.encode_point(curve.mul(d, curve.g), compressed=True)
    return KeyPair(
        SchemePublicKey(name, pub_raw),
        SchemePrivateKey(name, d.to_bytes(32, "big")),
    )


def _rsa_keypair(priv) -> KeyPair:
    name = RSA_SHA256.scheme_code_name
    pub_der = priv.public_key().public_bytes(
        serialization.Encoding.DER, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    priv_der = priv.private_bytes(
        serialization.Encoding.DER,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return KeyPair(SchemePublicKey(name, pub_der), SchemePrivateKey(name, priv_der))


# --- deterministic derivation (reference Crypto.kt:628-753) -----------------

def derive_keypair_from_entropy(
    scheme: SignatureScheme, entropy: int | bytes
) -> KeyPair:
    """Deterministic keypair from entropy (EdDSA + ECDSA only, like the reference).

    KDF: HMAC-SHA512(key=entropy, msg=scheme code name), then clamp/reduce.
    """
    if isinstance(entropy, int):
        entropy = entropy.to_bytes((entropy.bit_length() + 7) // 8 or 1, "big", signed=False)
    material = hmac_mod.new(entropy, scheme.scheme_code_name.encode(), hashlib.sha512).digest()
    name = scheme.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        return _ed25519_keypair_from_seed(material[:32])
    if name in _EC_CURVES:
        curve = _EC_CURVES[name][1]
        d = (int.from_bytes(material, "big") % (curve.n - 1)) + 1
        return _ec_keypair_from_scalar(name, d)
    if name == BLS_BLS12381.scheme_code_name:
        from . import bls_math

        return _bls_keypair(bls_math.keygen(material))
    raise UnsupportedSchemeError(f"deterministic derivation unsupported for {name}")


def derive_keypair(private: SchemePrivateKey, seed: bytes) -> KeyPair:
    """Derive a child keypair from a parent private key + seed (HKDF-style,
    reference Crypto.kt deriveKeyPair)."""
    scheme = find_signature_scheme(private.scheme_code_name)
    return derive_keypair_from_entropy(scheme, private.encoded + seed)


# --- sign / verify ----------------------------------------------------------

def do_sign(private: SchemePrivateKey, clear_data: bytes) -> bytes:
    if len(clear_data) == 0:
        raise CryptoError("signing of an empty array is not permitted")
    name = private.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        if not OPENSSL_AVAILABLE:
            return ed25519_math.sign(private.encoded, clear_data)
        return ed25519.Ed25519PrivateKey.from_private_bytes(private.encoded).sign(clear_data)
    if name in _EC_CURVES:
        jca_curve, curve = _EC_CURVES[name]
        d = int.from_bytes(private.encoded, "big")
        if not OPENSSL_AVAILABLE:
            return secp_math.der_encode_sig(
                *secp_math.ecdsa_sign(curve, d, clear_data)
            )
        return ec.derive_private_key(d, jca_curve).sign(clear_data, ec.ECDSA(hashes.SHA256()))
    if name == RSA_SHA256.scheme_code_name:
        _require_openssl("RSA signing")
        priv = serialization.load_der_private_key(private.encoded, password=None)
        return priv.sign(clear_data, padding.PKCS1v15(), hashes.SHA256())
    if name == SPHINCS256_SHA256.scheme_code_name:
        from . import sphincs

        return sphincs.sign(private, clear_data)
    if name == BLS_BLS12381.scheme_code_name:
        from . import bls_math

        return bls_math.sign(
            int.from_bytes(private.encoded, "big"), clear_data
        )
    raise UnsupportedSchemeError(f"cannot sign with {name}")


def do_verify(public: PublicKey, signature: bytes, clear_data: bytes) -> bool:
    """Verify and THROW SignatureError if invalid (reference Crypto.doVerify)."""
    if len(signature) == 0:
        raise CryptoError("verification of an empty signature is not permitted")
    if len(clear_data) == 0:
        raise CryptoError("verification of an empty payload is not permitted")
    if not is_valid(public, signature, clear_data):
        raise SignatureError(
            f"signature verification failed for scheme {public.scheme_code_name}"
        )
    return True


def is_valid(public: PublicKey, signature: bytes, clear_data: bytes) -> bool:
    """Boolean verify, never throws on bad signature (reference Crypto.isValid)."""
    import struct as _struct

    name = public.scheme_code_name
    try:
        if name == EDDSA_ED25519_SHA512.scheme_code_name:
            if not OPENSSL_AVAILABLE:
                # cofactorless, like OpenSSL: the deployment's pinned
                # ed25519 acceptance rule does not shift with this path
                return ed25519_math.verify(
                    public.encoded, clear_data, signature
                )
            ed25519.Ed25519PublicKey.from_public_bytes(public.encoded).verify(
                signature, clear_data
            )
            return True
        if name in _EC_CURVES:
            jca_curve, curve = _EC_CURVES[name]
            if not OPENSSL_AVAILABLE:
                r, s = secp_math.der_decode_sig(signature)
                return secp_math.ecdsa_verify(
                    curve, curve.decode_point(public.encoded),
                    clear_data, r, s,
                )
            pub = ec.EllipticCurvePublicKey.from_encoded_point(jca_curve, public.encoded)
            pub.verify(signature, clear_data, ec.ECDSA(hashes.SHA256()))
            return True
        if name == RSA_SHA256.scheme_code_name:
            _require_openssl("RSA verification")
            pub = serialization.load_der_public_key(public.encoded)
            pub.verify(signature, clear_data, padding.PKCS1v15(), hashes.SHA256())
            return True
        if name == SPHINCS256_SHA256.scheme_code_name:
            from . import sphincs

            return sphincs.verify(public, signature, clear_data)
        if name == BLS_BLS12381.scheme_code_name:
            from . import bls_math

            return bls_math.verify(public.encoded, signature, clear_data)
        if name == COMPOSITE_KEY.scheme_code_name:
            from .composite import CompositeKey, CompositeSignaturesWithKeys

            if not isinstance(public, CompositeKey):
                return False  # scheme tag lies about the key's structure
            sigs = CompositeSignaturesWithKeys.deserialize(signature)
            return public.verify_composite(sigs, clear_data)
    except (InvalidSignature, ValueError, AssertionError, IndexError, _struct.error):
        return False
    raise UnsupportedSchemeError(f"cannot verify with {name}")


# --- validation helpers -----------------------------------------------------

def public_key_on_curve(public: PublicKey) -> bool:
    """Point-validation (reference Crypto.publicKeyOnCurve Crypto.kt:859-871)."""
    name = public.scheme_code_name
    if name == EDDSA_ED25519_SHA512.scheme_code_name:
        pt = ed25519_math.point_decompress(public.encoded)
        return pt is not None and ed25519_math.is_on_curve(pt)
    if name in _EC_CURVES:
        _, curve = _EC_CURVES[name]
        try:
            pt = curve.decode_point(public.encoded)
        except ValueError:
            return False
        return pt is not None and curve.contains(pt)
    if name == BLS_BLS12381.scheme_code_name:
        from . import bls_math

        try:
            return bls_math.g1_decompress(public.encoded) is not None
        except ValueError:
            return False
    return True  # not a curve-based key


def entropy_to_keypair(entropy: int) -> KeyPair:
    """Fixed-entropy test identities (reference TestConstants.entropyToKeyPair)."""
    return derive_keypair_from_entropy(EDDSA_ED25519_SHA512, entropy)


# --- BLS aggregation + proof-of-possession registry --------------------------
# Same-message aggregation (the committee-consensus shape, PAPERS
# arXiv 2302.00418) is only sound when every participating public key has
# proven knowledge of its secret key: without that, a rogue member
# registers pk' = pk_evil - sum(other pks) and forges the aggregate alone.
# The registry below is the SPI-level gate: committee wiring registers
# each member key WITH its proof of possession, and aggregate_verify
# refuses unregistered keys unless the caller explicitly opts out
# (require_pop=False, for callers enforcing possession out of band).

_POP_REGISTRY: set = set()  # 48-byte compressed G1 pubkeys with valid PoP
_POP_LOCK = threading.Lock()


def _bls_public_bytes(public) -> bytes:
    if isinstance(public, (bytes, bytearray)):
        return bytes(public)
    if getattr(public, "scheme_code_name", None) != BLS_BLS12381.scheme_code_name:
        raise UnsupportedSchemeError(
            f"aggregation requires {BLS_BLS12381.scheme_code_name} keys, "
            f"got {getattr(public, 'scheme_code_name', type(public).__name__)}"
        )
    return public.encoded


def bls_prove_possession(private: SchemePrivateKey) -> bytes:
    """Proof of possession for a BLS private key (sign the pubkey bytes
    under the PoP domain-separation tag)."""
    if private.scheme_code_name != BLS_BLS12381.scheme_code_name:
        raise UnsupportedSchemeError("proof of possession is BLS-only")
    from . import bls_math

    return bls_math.pop_prove(int.from_bytes(private.encoded, "big"))


def bls_register_key(public, proof: bytes) -> bool:
    """Verify `proof` of possession for `public` and admit the key to the
    aggregation registry. Returns False (and does not register) on an
    invalid proof. Idempotent AND cheap on re-registration: a key
    already in the registry passed a full PoP check once, so the
    2-pairing verification is skipped (every replica of an in-process
    committee registers the same n keys — n^2 pairings otherwise)."""
    from . import bls_math

    pk = _bls_public_bytes(public)
    with _POP_LOCK:
        if pk in _POP_REGISTRY:
            return True
    if not bls_math.pop_verify(pk, proof):
        return False
    with _POP_LOCK:
        _POP_REGISTRY.add(pk)
    return True


def bls_key_registered(public) -> bool:
    with _POP_LOCK:
        return _bls_public_bytes(public) in _POP_REGISTRY


def aggregate(signatures) -> bytes:
    """Aggregate BLS signatures (over one message) into one 96-byte
    signature: the n-votes -> one-check committee lever."""
    from . import bls_math

    try:
        return bls_math.aggregate(list(signatures))
    except ValueError as exc:
        raise CryptoError(str(exc))


def aggregate_verify(pubkeys, message: bytes, agg_signature: bytes,
                     require_pop: bool = True) -> bool:
    """Verify an aggregate of same-message signatures: ONE 2-pairing
    check regardless of committee size (vs n checks naively).

    `require_pop=True` (default) refuses public keys that never proved
    possession via bls_register_key — the rogue-key gate. Callers that
    enforce possession elsewhere (e.g. a cluster deploy tool validating
    PoPs at key ceremony) may pass False."""
    from . import bls_math

    pks = [_bls_public_bytes(pk) for pk in pubkeys]
    if not pks:
        return False
    if require_pop:
        with _POP_LOCK:
            if any(pk not in _POP_REGISTRY for pk in pks):
                return False
    return bls_math.aggregate_verify(pks, message, agg_signature)

"""X.509 identity PKI: cert hierarchy, CSRs, TLS contexts.

Reference parity: `core/src/main/kotlin/net/corda/core/crypto/
X509Utilities.kt:28-235` (3-level hierarchy root CA -> intermediate CA ->
client/node CA, plus TLS leaf certs; well-known aliases at :33-36) and
`ContentSignerBuilder.kt` (signing certs with a chosen scheme).  Backed by
the `cryptography` package the way the reference leans on BouncyCastle.

Hierarchy (aliases kept from the reference):
    CORDA_ROOT_CA          self-signed, CA:TRUE pathlen 2
    CORDA_INTERMEDIATE_CA  signed by root, CA:TRUE pathlen 1
    CORDA_CLIENT_CA        per-node, signed by intermediate, CA:TRUE pathlen 0
    identity / TLS leaves  signed by the node CA

Key type: ECDSA P-256 (scheme id 3 in the registry; also the TLS-friendly
choice).  `DEV_ROOT` mirrors the reference's bundled dev-mode certificates
(`AbstractNode.configureWithDevSSLCertificate`): a deterministic dev root
so every dev node chains to the same trust anchor.

TLS: `server_ssl_context` / `client_ssl_context` build mutually-
authenticating contexts for the broker transport
(corda_tpu.messaging.net `server_wrap`/`client_wrap`).
"""
from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    OPENSSL_AVAILABLE = True
except ImportError:  # pragma: no cover - depends on the host image
    # X.509 genuinely needs OpenSSL; there is no pure-Python fallback.
    # Importing this module stays safe (node/services import it lazily);
    # the first actual PKI operation raises with a clear message.
    OPENSSL_AVAILABLE = False

    class _MissingOpenSSL:
        def __init__(self, label: str):
            self._label = label

        def __getattr__(self, name):
            raise ImportError(
                f"{self._label}.{name}: X.509 PKI requires the "
                "'cryptography' package (OpenSSL), which is not "
                "installed on this host"
            )

        def __call__(self, *a, **kw):
            raise ImportError(
                f"{self._label}: X.509 PKI requires the 'cryptography' "
                "package (OpenSSL), which is not installed on this host"
            )

    x509 = _MissingOpenSSL("x509")
    hashes = _MissingOpenSSL("hashes")
    serialization = _MissingOpenSSL("serialization")
    ec = _MissingOpenSSL("ec")
    NameOID = _MissingOpenSSL("NameOID")

CORDA_ROOT_CA = "cordarootca"
CORDA_INTERMEDIATE_CA = "cordaintermediateca"
CORDA_CLIENT_CA = "cordaclientca"
CORDA_TLS = "cordaclienttls"

_ONE_DAY = datetime.timedelta(days=1)
_TEN_YEARS = datetime.timedelta(days=3650)


@dataclass
class CertAndKey:
    cert: x509.Certificate
    key: ec.EllipticCurvePrivateKey

    def cert_pem(self) -> bytes:
        return self.cert.public_bytes(serialization.Encoding.PEM)

    def key_pem(self) -> bytes:
        return self.key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )


def _name(common_name: str, org: str = "corda_tpu",
          unit: Optional[str] = None) -> x509.Name:
    attrs = [
        x509.NameAttribute(NameOID.COMMON_NAME, common_name),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
    ]
    if unit is not None:
        # Distinguishes the node CA's DN from its TLS/identity leaves —
        # an identical subject/issuer DN makes chain builders treat the
        # leaf as self-signed.
        attrs.append(x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, unit))
    return x509.Name(attrs)


def _new_key() -> ec.EllipticCurvePrivateKey:
    return ec.generate_private_key(ec.SECP256R1())


def _build_cert(
    subject: x509.Name,
    subject_key,
    issuer: x509.Name,
    issuer_key,
    is_ca: bool,
    path_len: Optional[int],
    san_dns: Optional[List[str]] = None,
    validity: datetime.timedelta = _TEN_YEARS,
) -> x509.Certificate:
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(issuer)
        .public_key(subject_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + validity)
        .add_extension(
            x509.BasicConstraints(ca=is_ca, path_length=path_len), critical=True
        )
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(subject_key.public_key()),
            critical=False,
        )
    )
    if san_dns:
        builder = builder.add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName(d) for d in san_dns]
                + [x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
    return builder.sign(issuer_key, hashes.SHA256())


def create_self_signed_ca(common_name: str = "Corda Node Root CA") -> CertAndKey:
    """Root of the hierarchy (X509Utilities.createSelfSignedCACert)."""
    key = _new_key()
    name = _name(common_name)
    return CertAndKey(_build_cert(name, key, name, key, True, 2), key)


def create_intermediate_ca(
    root: CertAndKey, common_name: str = "Corda Node Intermediate CA"
) -> CertAndKey:
    key = _new_key()
    cert = _build_cert(
        _name(common_name), key, root.cert.subject, root.key, True, 1
    )
    return CertAndKey(cert, key)


def create_node_ca(intermediate: CertAndKey, legal_name: str) -> CertAndKey:
    """Per-node CA (CORDA_CLIENT_CA; X509Utilities.createIntermediateCert)."""
    key = _new_key()
    cert = _build_cert(
        _name(legal_name, unit="CORDA_CLIENT_CA"), key,
        intermediate.cert.subject, intermediate.key, True, 0,
    )
    return CertAndKey(cert, key)


def create_tls_cert(
    node_ca: CertAndKey, legal_name: str, dns_names: Optional[List[str]] = None
) -> CertAndKey:
    """TLS leaf for the broker transport (X509Utilities.createServerCert)."""
    key = _new_key()
    cert = _build_cert(
        _name(legal_name), key,
        node_ca.cert.subject, node_ca.key, False, None,
        san_dns=dns_names or ["localhost"],
    )
    return CertAndKey(cert, key)


# --- CSR flow (X509Utilities.createCertificateSigningRequest) ---------------

def create_csr(legal_name: str) -> Tuple[x509.CertificateSigningRequest, ec.EllipticCurvePrivateKey]:
    key = _new_key()
    csr = (
        x509.CertificateSigningRequestBuilder()
        .subject_name(_name(legal_name))
        .sign(key, hashes.SHA256())
    )
    return csr, key


def sign_csr(
    ca: CertAndKey, csr: x509.CertificateSigningRequest, is_ca: bool = False
) -> x509.Certificate:
    if not csr.is_signature_valid:
        raise ValueError("CSR signature invalid")
    return _build_cert_from_public(csr.subject, csr.public_key(), ca, is_ca)


def _build_cert_from_public(subject, public_key, ca: CertAndKey, is_ca: bool):
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(ca.cert.subject)
        .public_key(public_key)
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + _TEN_YEARS)
        .add_extension(
            x509.BasicConstraints(ca=is_ca, path_length=0 if is_ca else None),
            critical=True,
        )
    )
    return builder.sign(ca.key, hashes.SHA256())


# --- identity certificates (bind a framework signing key) -------------------

def create_identity_cert(node_ca: CertAndKey, legal_name: str, public_key):
    """Certificate over a framework identity key (reference: the node CA
    certifies the legal identity's SIGNING key, not a fresh EC key).

    `public_key` is a corda_tpu SchemePublicKey; ed25519 and ECDSA keys
    are supported (RSA/SPHINCS identities must use the CSR flow)."""
    from cryptography.hazmat.primitives.asymmetric import ed25519 as _ed

    name = public_key.scheme_code_name
    if name == "EDDSA_ED25519_SHA512":
        subject_key = _ed.Ed25519PublicKey.from_public_bytes(
            public_key.encoded
        )
    elif name.startswith("ECDSA_SECP256"):
        curve = ec.SECP256K1() if "K1" in name else ec.SECP256R1()
        subject_key = ec.EllipticCurvePublicKey.from_encoded_point(
            curve, public_key.encoded
        )
    else:
        raise ValueError(f"cannot certify {name} keys directly")
    return _build_cert_from_public(
        _name(legal_name, unit="Identity"), subject_key, node_ca, is_ca=False
    )


def cert_common_name(cert: x509.Certificate) -> Optional[str]:
    attrs = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
    return attrs[0].value if attrs else None


def cert_matches_key(cert: x509.Certificate, public_key) -> bool:
    """Does the certificate's subject key equal this framework key?"""
    from cryptography.hazmat.primitives import serialization as _ser
    from cryptography.hazmat.primitives.asymmetric import ed25519 as _ed

    subject_key = cert.public_key()
    if isinstance(subject_key, _ed.Ed25519PublicKey):
        raw = subject_key.public_bytes(
            _ser.Encoding.Raw, _ser.PublicFormat.Raw
        )
        return raw == public_key.encoded
    if isinstance(subject_key, ec.EllipticCurvePublicKey):
        # framework ECDSA keys encode as X962 compressed points
        point = subject_key.public_bytes(
            _ser.Encoding.X962, _ser.PublicFormat.CompressedPoint
        )
        return point == public_key.encoded
    return False


# --- validation --------------------------------------------------------------

def _basic_constraints(cert: x509.Certificate):
    try:
        return cert.extensions.get_extension_for_class(
            x509.BasicConstraints
        ).value
    except x509.ExtensionNotFound:
        return None


def verify_chain(leaf: x509.Certificate, chain: List[x509.Certificate],
                 root: x509.Certificate) -> bool:
    """Cert-path validation: signature linkage, issuer/subject matching,
    validity windows, and CA + path-length constraints on every issuer
    (reference InMemoryIdentityService cert-path checks).  Without the CA
    checks, any LEAF key holder could mint certificates that verify."""
    path = [leaf] + list(chain) + [root]
    now = datetime.datetime.now(datetime.timezone.utc)
    for cert in path:
        if not (cert.not_valid_before_utc <= now <= cert.not_valid_after_utc):
            return False
    for depth, (child, parent) in enumerate(zip(path, path[1:])):
        if child.issuer != parent.subject:
            return False
        bc = _basic_constraints(parent)
        if bc is None or not bc.ca:
            return False
        # path_length bounds the number of intermediate CAs BELOW parent:
        # at position i (0-based from the leaf side), parent has `depth`
        # CA certs beneath it excluding the leaf.
        if bc.path_length is not None and depth > bc.path_length:
            return False
        try:
            parent.public_key().verify(
                child.signature,
                child.tbs_certificate_bytes,
                ec.ECDSA(child.signature_hash_algorithm),
            )
        except Exception:
            return False
    try:
        root.public_key().verify(
            root.signature, root.tbs_certificate_bytes,
            ec.ECDSA(root.signature_hash_algorithm),
        )
    except Exception:
        return False
    return True


# --- keystore-on-disk (JKS analogue: PEM files in a directory) --------------

def _atomic_write(path: str, data: bytes) -> None:
    """Write-then-rename so concurrent readers never see a torn file —
    AND fsync-before-rename so a power cut cannot leave an empty
    keystore behind (delegates to the one helper, utils/atomicfile)."""
    from ...utils import atomicfile

    atomicfile.write_atomic(path, data)


def write_cert_store(directory: str, **entries: CertAndKey) -> None:
    os.makedirs(directory, exist_ok=True)
    for alias, ck in entries.items():
        _atomic_write(os.path.join(directory, f"{alias}.cert.pem"), ck.cert_pem())
        if ck.key is not None:  # cert-only entries (e.g. a downloaded chain)
            _atomic_write(os.path.join(directory, f"{alias}.key.pem"), ck.key_pem())


def read_cert(directory: str, alias: str) -> CertAndKey:
    with open(os.path.join(directory, f"{alias}.cert.pem"), "rb") as fh:
        cert = x509.load_pem_x509_certificate(fh.read())
    with open(os.path.join(directory, f"{alias}.key.pem"), "rb") as fh:
        key = serialization.load_pem_private_key(fh.read(), password=None)
    return CertAndKey(cert, key)


def dev_certificates(directory: str, legal_name: str) -> dict:
    """Dev-mode certificates (AbstractNode.configureWithDevSSLCertificate).

    Root + intermediate are SHARED per directory (generated on first use);
    the node CA and TLS leaf are per legal name.  Pointing several nodes at
    one certificates directory therefore gives each its own identity
    chained to a common trust anchor — the shape the reference ships as
    its bundled dev-mode certs."""
    import hashlib

    os.makedirs(directory, exist_ok=True)
    # Concurrent dev nodes may race root creation on a shared directory:
    # claim it with O_EXCL; the loser waits for the winner's atomic writes.
    lock_path = os.path.join(directory, ".root.claim")
    root_cert_path = os.path.join(directory, f"{CORDA_ROOT_CA}.cert.pem")
    claimed = False
    if not os.path.exists(root_cert_path):
        # A claim with no root after 60s is a crashed claimant: break it.
        try:
            if (
                os.path.exists(lock_path)
                and time.time() - os.path.getmtime(lock_path) > 60
            ):
                os.unlink(lock_path)
        except OSError:
            pass
        try:
            fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            claimed = True
        except FileExistsError:
            pass
    if claimed:
        try:
            root = create_self_signed_ca()
            inter = create_intermediate_ca(root)
            write_cert_store(
                directory,
                **{CORDA_ROOT_CA: root, CORDA_INTERMEDIATE_CA: inter},
            )
        finally:
            try:
                os.unlink(lock_path)
            except OSError:
                pass
    else:
        deadline = time.time() + 15
        while not (
            os.path.exists(root_cert_path)
            and os.path.exists(
                os.path.join(directory, f"{CORDA_INTERMEDIATE_CA}.key.pem")
            )
        ):
            if time.time() > deadline:
                raise TimeoutError(
                    f"waiting for shared dev root in {directory}"
                )
            time.sleep(0.05)
        root = read_cert(directory, CORDA_ROOT_CA)
        inter = read_cert(directory, CORDA_INTERMEDIATE_CA)
    tag = hashlib.sha256(legal_name.encode()).hexdigest()[:8]
    ca_alias = f"{tag}-{CORDA_CLIENT_CA}"
    tls_alias = f"{tag}-{CORDA_TLS}"
    if os.path.exists(os.path.join(directory, f"{ca_alias}.cert.pem")):
        node_ca = read_cert(directory, ca_alias)
        tls = read_cert(directory, tls_alias)
    else:
        node_ca = create_node_ca(inter, legal_name)
        tls = create_tls_cert(node_ca, legal_name)
        write_cert_store(directory, **{ca_alias: node_ca, tls_alias: tls})
    return {
        CORDA_ROOT_CA: root,
        CORDA_INTERMEDIATE_CA: inter,
        CORDA_CLIENT_CA: node_ca,
        CORDA_TLS: tls,
        "_tag": tag,
    }


# --- TLS contexts for the broker transport ----------------------------------

def _chain_pem(tls: CertAndKey, *parents: CertAndKey) -> bytes:
    return tls.cert_pem() + b"".join(p.cert_pem() for p in parents)


def _write_tls_material(directory: str, entries: dict) -> Tuple[str, str, str]:
    """(chain_file, key_file, root_file) for ssl.SSLContext consumption."""
    tag = entries.get("_tag", "")
    prefix = f"{tag}-" if tag else ""
    chain_path = os.path.join(directory, f"{prefix}tls.chain.pem")
    key_path = os.path.join(directory, f"{prefix}{CORDA_TLS}.key.pem")
    root_path = os.path.join(directory, "trustroot.pem")
    chain = _chain_pem(
        entries[CORDA_TLS],
        entries[CORDA_CLIENT_CA],
        entries[CORDA_INTERMEDIATE_CA],
    )
    _atomic_write(chain_path, chain)
    _atomic_write(root_path, entries[CORDA_ROOT_CA].cert_pem())
    return chain_path, key_path, root_path


def server_ssl_context(cert_dir: str, entries: dict,
                       require_client_cert: bool = True) -> ssl.SSLContext:
    chain, key, root = _write_tls_material(cert_dir, entries)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(chain, key)
    ctx.load_verify_locations(root)
    if require_client_cert:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(cert_dir: str, entries: dict,
                       trust_root_pem: Optional[bytes] = None) -> ssl.SSLContext:
    chain, key, root = _write_tls_material(cert_dir, entries)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_cert_chain(chain, key)
    ctx.check_hostname = False  # peer auth is by chain-to-root, not hostname
    ctx.verify_mode = ssl.CERT_REQUIRED
    if trust_root_pem is not None:
        ctx.load_verify_locations(cadata=trust_root_pem.decode())
    else:
        ctx.load_verify_locations(root)
    return ctx


def server_wrap(ctx: ssl.SSLContext):
    """Socket-wrap hook for messaging.net.BrokerServer."""
    return lambda sock: ctx.wrap_socket(sock, server_side=True)


def client_wrap(ctx: ssl.SSLContext):
    """Socket-wrap hook for messaging.net.RemoteBroker."""
    return lambda sock: ctx.wrap_socket(sock)

"""Signature value types: DigitalSignature, TransactionSignature, MetaData, SignedData.

Parity: reference `core/.../crypto/DigitalSignature.kt:14-47`,
`MetaData.kt:30-71`, `TransactionSignature.kt:10-21`, `SignedData.kt:16-42`.
"""
from __future__ import annotations

import enum
import struct
from dataclasses import dataclass
from typing import Optional, Set

from . import crypto
from .composite import _encode_node, decode_composite_key
from .keys import PublicKey, SchemePrivateKey
from .secure_hash import SecureHash


@dataclass(frozen=True)
class DigitalSignature:
    """Raw signature bytes."""

    bytes: bytes


@dataclass(frozen=True)
class DigitalSignatureWithKey(DigitalSignature):
    """Signature bytes plus the signer's public key.

    Reference `DigitalSignature.WithKey` -- the element type of
    `SignedTransaction.sigs`, and the unit of work for the TPU batch verifier.
    """

    by: PublicKey

    def verify(self, content: bytes) -> bool:
        """Verify or raise (reference WithKey.verify -> Crypto.doVerify)."""
        return crypto.do_verify(self.by, self.bytes, content)

    def is_valid(self, content: bytes) -> bool:
        return crypto.is_valid(self.by, self.bytes, content)

    def with_without_key(self) -> DigitalSignature:
        return DigitalSignature(self.bytes)


def sign_bytes(private: SchemePrivateKey, public: PublicKey, content: bytes) -> DigitalSignatureWithKey:
    return DigitalSignatureWithKey(crypto.do_sign(private, content), public)


class SignatureType(enum.IntEnum):
    FULL = 0
    PARTIAL = 1
    BLIND = 2


@dataclass(frozen=True)
class MetaData:
    """Attached signature metadata, the actual signed payload for
    metadata-carrying signatures (reference MetaData.kt:30-71)."""

    scheme_code_name: str
    version_id: str
    signature_type: SignatureType
    timestamp: Optional[int]          # unix nanos, None if absent
    visible_inputs: Optional[bytes]   # bitset over inputs visible to signer
    signed_inputs: Optional[bytes]    # bitset over inputs signed (PARTIAL)
    merkle_root: bytes
    public_key: PublicKey

    def bytes(self) -> bytes:
        """Canonical byte form over which the signature is computed."""

        def _opt(b: Optional[bytes]) -> bytes:
            if b is None:
                return struct.pack(">i", -1)
            return struct.pack(">i", len(b)) + b

        name = self.scheme_code_name.encode()
        ver = self.version_id.encode()
        key_enc = _encode_node(self.public_key)
        return b"".join(
            [
                struct.pack(">I", len(name)), name,
                struct.pack(">I", len(ver)), ver,
                struct.pack(">B", int(self.signature_type)),
                struct.pack(">q", -1 if self.timestamp is None else self.timestamp),
                _opt(self.visible_inputs),
                _opt(self.signed_inputs),
                struct.pack(">I", len(self.merkle_root)), self.merkle_root,
                struct.pack(">I", len(key_enc)), key_enc,
            ]
        )


@dataclass(frozen=True)
class TransactionSignature(DigitalSignature):
    """Signature over a MetaData blob (reference TransactionSignature.kt)."""

    meta_data: MetaData

    def verify(self) -> bool:
        return crypto.do_verify(self.meta_data.public_key, self.bytes, self.meta_data.bytes())

    def is_valid(self) -> bool:
        return crypto.is_valid(self.meta_data.public_key, self.bytes, self.meta_data.bytes())


class SignedData:
    """A serialized payload plus a signature over it; `verified()` checks the
    signature then deserializes (reference SignedData.kt:16-42)."""

    def __init__(self, raw: bytes, sig: DigitalSignatureWithKey):
        self.raw = raw
        self.sig = sig

    def verified(self):
        self.sig.verify(self.raw)
        from ..serialization.codec import deserialize

        data = deserialize(self.raw)
        self.verify_data(data)
        return data

    def verify_data(self, data) -> None:
        """Hook for subclasses: extra semantic checks (e.g. signer authority)."""

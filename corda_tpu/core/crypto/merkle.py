"""Merkle trees and partial Merkle proofs.

Parity: reference `core/.../crypto/MerkleTree.kt:27-68` (bottom-up SHA-256 tree,
leaf list zero-padded to a power of two) and `PartialMerkleTree.kt:44-157`
(tear-off proofs for FilteredTransaction).

The host implementation here is the semantic definition; batched SHA-256 tree
construction for large component sets runs on TPU via corda_tpu.ops.sha256.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Union

from .secure_hash import SecureHash, ZERO_HASH


class MerkleTreeError(Exception):
    pass


@dataclass(frozen=True)
class MerkleTree:
    hash: SecureHash
    left: "MerkleTree | None" = None
    right: "MerkleTree | None" = None

    @property
    def is_leaf(self) -> bool:
        return self.left is None and self.right is None

    @staticmethod
    def get_merkle_tree(all_leaves_hashes: Sequence[SecureHash]) -> "MerkleTree":
        if not all_leaves_hashes:
            raise MerkleTreeError("cannot build a Merkle tree with no leaves")
        from ... import native

        leaves = _pad_to_power_of_two(list(all_leaves_hashes))
        level = [MerkleTree(h) for h in leaves]
        while len(level) > 1:
            # One native call hashes the whole level (falls back to hashlib
            # internally when the C++ library is unavailable).
            packed = b"".join(n.hash.bytes for n in level)
            digests = native.sha256_pairs(packed)
            nxt = []
            for i in range(0, len(level), 2):
                l, r = level[i], level[i + 1]
                h = SecureHash(digests[16 * i: 16 * i + 32])
                nxt.append(MerkleTree(h, l, r))
            level = nxt
        return level[0]


def _pad_to_power_of_two(leaves: List[SecureHash]) -> List[SecureHash]:
    n = 1
    while n < len(leaves):
        n *= 2
    return leaves + [ZERO_HASH] * (n - len(leaves))


# --- partial tree -----------------------------------------------------------

@dataclass(frozen=True)
class PartialLeaf:
    """Included leaf whose hash the verifier recomputes from revealed data."""
    hash: SecureHash


@dataclass(frozen=True)
class HiddenLeaf:
    """A pruned subtree, represented only by its hash.

    leaf_span records how many original leaves the collapsed subtree covers so
    that leaf_index can map included leaves back to their true positions.
    """
    hash: SecureHash
    leaf_span: int = 1


@dataclass(frozen=True)
class PartialNode:
    left: "PartialTreeNode"
    right: "PartialTreeNode"


PartialTreeNode = Union[PartialLeaf, HiddenLeaf, PartialNode]


@dataclass(frozen=True)
class PartialMerkleTree:
    root: PartialTreeNode

    @staticmethod
    def build(merkle_root: MerkleTree, included_hashes: Sequence[SecureHash]) -> "PartialMerkleTree":
        included = set(included_hashes)
        used: set = set()
        tree = _build_partial(merkle_root, included, used)
        missing = included - used
        if missing:
            raise MerkleTreeError(f"hashes not found in tree: {missing}")
        return PartialMerkleTree(tree)

    def verify(self, expected_root: SecureHash, hashes_to_check: Sequence[SecureHash]) -> bool:
        found: List[SecureHash] = []
        root_hash = _root_and_collect(self.root, found)
        if root_hash != expected_root:
            return False
        return sorted(h.bytes for h in found) == sorted(h.bytes for h in hashes_to_check)

    def leaf_index(self, leaf_hash: SecureHash) -> int:
        """Position of an included leaf in the original tree (left-to-right)."""
        idx = _leaf_index(self.root, leaf_hash, 0)
        if idx is None:
            raise MerkleTreeError("leaf not included in partial tree")
        return idx


def _build_partial(node: MerkleTree, included: set, used: set) -> PartialTreeNode:
    if node.is_leaf:
        if node.hash in included:
            used.add(node.hash)
            return PartialLeaf(node.hash)
        return HiddenLeaf(node.hash)
    left = _build_partial(node.left, included, used)
    right = _build_partial(node.right, included, used)
    if isinstance(left, HiddenLeaf) and isinstance(right, HiddenLeaf):
        return HiddenLeaf(node.hash, left.leaf_span + right.leaf_span)
    return PartialNode(left, right)


def _root_and_collect(node: PartialTreeNode, found: List[SecureHash]) -> SecureHash:
    if isinstance(node, PartialLeaf):
        found.append(node.hash)
        return node.hash
    if isinstance(node, HiddenLeaf):
        return node.hash
    return _root_and_collect(node.left, found).hash_concat(
        _root_and_collect(node.right, found)
    )


def _leaf_count(node: PartialTreeNode) -> int:
    if isinstance(node, PartialLeaf):
        return 1
    if isinstance(node, HiddenLeaf):
        return node.leaf_span
    return _leaf_count(node.left) + _leaf_count(node.right)


def _leaf_index(node: PartialTreeNode, target: SecureHash, base: int):
    if isinstance(node, PartialLeaf):
        return base if node.hash == target else None
    if isinstance(node, HiddenLeaf):
        return None
    left_idx = _leaf_index(node.left, target, base)
    if left_idx is not None:
        return left_idx
    return _leaf_index(node.right, target, base + _leaf_count(node.left))

"""base58 / base64 / hex encoding helpers.

Parity: reference `core/src/main/kotlin/net/corda/core/crypto/EncodingUtils.kt`.
"""
from __future__ import annotations

import base64

_B58_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_B58_INDEX = {c: i for i, c in enumerate(_B58_ALPHABET)}


def to_base58(data: bytes) -> str:
    n = int.from_bytes(data, "big")
    out = []
    while n > 0:
        n, r = divmod(n, 58)
        out.append(_B58_ALPHABET[r])
    # preserve leading zero bytes
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def from_base58(s: str) -> bytes:
    n = 0
    for c in s:
        if c not in _B58_INDEX:
            raise ValueError(f"invalid base58 character {c!r}")
        n = n * 58 + _B58_INDEX[c]
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b""
    pad = 0
    for c in s:
        if c == "1":
            pad += 1
        else:
            break
    return b"\x00" * pad + raw


def to_base64(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def from_base64(s: str) -> bytes:
    return base64.b64decode(s)


def to_hex(data: bytes) -> str:
    return data.hex().upper()


def from_hex(s: str) -> bytes:
    return bytes.fromhex(s)

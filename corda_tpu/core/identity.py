"""Party identity model.

Parity: reference `core/src/main/kotlin/net/corda/core/identity/`
(`AbstractParty`, `Party`, `AnonymousParty`) — a party is a (X.500-ish name,
owning key) pair; anonymous parties carry only the key. Names here are plain
strings of "O=...,L=...,C=..." form rather than JCA X500Name objects.
"""
from __future__ import annotations

from dataclasses import dataclass

from .crypto.keys import PublicKey
from .serialization.codec import register_adapter


class AbstractParty:
    owning_key: PublicKey

    def ref(self, *ref_bytes: int) -> "PartyAndReference":
        return PartyAndReference(self, bytes(ref_bytes))


@dataclass(frozen=True)
class Party(AbstractParty):
    name: str
    owning_key: PublicKey

    def anonymise(self) -> "AnonymousParty":
        return AnonymousParty(self.owning_key)

    def __repr__(self) -> str:
        return f"Party({self.name})"


@dataclass(frozen=True)
class AnonymousParty(AbstractParty):
    owning_key: PublicKey

    def __repr__(self) -> str:
        return f"AnonymousParty({self.owning_key!r})"


@dataclass(frozen=True)
class PartyAndReference:
    """Reference to something being stored or issued by a party, e.g. an
    issuer reference (reference `Structures.kt` PartyAndReference)."""

    party: AbstractParty
    reference: bytes

    def __repr__(self) -> str:
        return f"{self.party}{self.reference.hex()}"


register_adapter(
    Party, "Party",
    lambda p: {"name": p.name, "key": p.owning_key},
    lambda d: Party(d["name"], d["key"]),
)
register_adapter(
    AnonymousParty, "AnonymousParty",
    lambda p: {"key": p.owning_key},
    lambda d: AnonymousParty(d["key"]),
)
register_adapter(
    PartyAndReference, "PartyAndReference",
    lambda p: {"party": p.party, "ref": p.reference},
    lambda d: PartyAndReference(d["party"], d["ref"]),
)


@dataclass(frozen=True)
class PartyAndCertificate:
    """A well-known identity with its certificate path (reference
    `PartyAndCertificate`): `certificate` binds `party.owning_key` and is
    signed by the node CA; `cert_path` holds the intermediates up to (not
    including) the network trust root. Validated + registered by
    `IdentityService.verify_and_register_identity`."""

    party: Party
    certificate: object          # cryptography x509.Certificate
    cert_path: tuple = ()        # intermediates, leaf-adjacent first

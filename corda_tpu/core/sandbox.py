"""Deterministic execution guard for untrusted contract code (reference
`experimental/sandbox/src/main/java/net/corda/sandbox/` — the JVM
bytecode-rewriting `RuntimeCostAccounter` + `WhitelistClassLoader` become
(a) a static code-object scan and (b) a sys.settrace cost meter; same two
layers, Python-native mechanisms).

Why it matters: attachment-delivered contract code (serialization/
attachments_loader.py) executes inside every verifier; a hostile contract
must not be able to spin forever, exhaust memory, or read
non-deterministic inputs and split consensus.

Layers:
  * `check_code(fn_or_cls)` — static: walks code objects recursively and
    rejects references to forbidden builtins (`open`, `eval`, `exec`,
    `__import__`, …) and forbidden module roots (`os`, `socket`, `random`,
    `time`, `threading`, …) before anything runs (WhitelistClassLoader
    analogue: reject at load time).
  * `run_metered(fn, *args, budget=...)` — dynamic: executes under a trace
    that charges 1 cost unit per line event plus an allocation surcharge
    per call, and enforces a wall-clock ceiling (RuntimeCostAccounter
    analogue: the reference charges per-instruction/allocation/jump).
"""
from __future__ import annotations

import sys
import time
import types
from dataclasses import dataclass
from typing import Any, Callable, FrozenSet, Iterable, Optional

FORBIDDEN_BUILTINS: FrozenSet[str] = frozenset({
    "open", "eval", "exec", "compile", "__import__", "input", "breakpoint",
    "globals", "vars", "memoryview", "exit", "quit",
})

#: module roots contract code must not touch (non-determinism or IO)
FORBIDDEN_MODULES: FrozenSet[str] = frozenset({
    "os", "sys", "io", "socket", "subprocess", "threading", "multiprocessing",
    "random", "secrets", "time", "datetime", "uuid", "pathlib", "shutil",
    "ctypes", "signal", "importlib", "pickle", "marshal", "urllib", "http",
    "posixpath", "ntpath", "genericpath",  # os.path implementation modules
})


class SandboxViolation(Exception):
    """Static rejection: the code references forbidden names/modules."""


class CostLimitExceeded(Exception):
    """Dynamic rejection: the execution budget ran out."""


@dataclass(frozen=True)
class Budget:
    """Execution budget (reference RuntimeCostAccounter's per-category
    thresholds, collapsed to line-cost + call-cost + wall clock)."""

    max_cost: int = 2_000_000       # ~line events + call surcharges
    max_seconds: float = 5.0
    call_surcharge: int = 10


DEFAULT_BUDGET = Budget()


# --- static layer ------------------------------------------------------------

def _iter_code(code: types.CodeType) -> Iterable[types.CodeType]:
    yield code
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            yield from _iter_code(const)


def check_code(obj: Any, extra_forbidden: Iterable[str] = ()) -> None:
    """Statically vet a function or class (e.g. a Contract subclass): every
    reachable code object must not name a forbidden builtin or import a
    forbidden module root. Raises SandboxViolation."""
    forbidden = FORBIDDEN_BUILTINS | frozenset(extra_forbidden)
    codes = []
    if isinstance(obj, type):
        for attr in vars(obj).values():
            fn = getattr(attr, "__func__", attr)
            if isinstance(fn, types.FunctionType):
                codes.append(fn.__code__)
    elif isinstance(obj, types.FunctionType):
        codes.append(obj.__code__)
    elif isinstance(obj, types.MethodType):
        codes.append(obj.__func__.__code__)
    else:
        raise TypeError(f"cannot vet {type(obj).__name__}")

    for top in codes:
        for code in _iter_code(top):
            # co_freevars too: a closure variable bound to a forbidden
            # module reaches the code without appearing in co_names
            names = set(code.co_names) | set(code.co_freevars)
            bad = names & forbidden
            if bad:
                raise SandboxViolation(
                    f"{code.co_qualname or code.co_name} references "
                    f"forbidden name(s) {sorted(bad)}"
                )
            for name in names:
                root = name.split(".", 1)[0]
                if root in FORBIDDEN_MODULES:
                    raise SandboxViolation(
                        f"{code.co_qualname or code.co_name} touches "
                        f"forbidden module {root!r}"
                    )


# --- dynamic layer -----------------------------------------------------------

def run_metered(
    fn: Callable,
    *args: Any,
    budget: Budget = DEFAULT_BUDGET,
    **kwargs: Any,
):
    """Run fn under cost accounting; raises CostLimitExceeded when the
    budget is exhausted and SandboxViolation if execution enters a
    forbidden module. Returns fn's result. Not reentrant per thread."""
    state = {"cost": 0, "deadline": time.monotonic() + budget.max_seconds}

    def tracer(frame, event, arg):
        if event == "call":
            state["cost"] += budget.call_surcharge
            mod = frame.f_globals.get("__name__", "")
            root = mod.split(".", 1)[0]
            if root in FORBIDDEN_MODULES:
                raise SandboxViolation(
                    f"execution entered forbidden module {mod!r}"
                )
            return tracer
        if event == "line":
            state["cost"] += 1
            if state["cost"] > budget.max_cost:
                raise CostLimitExceeded(
                    f"cost budget {budget.max_cost} exhausted"
                )
            if (state["cost"] & 0x3FF) == 0 and (
                time.monotonic() > state["deadline"]
            ):
                raise CostLimitExceeded(
                    f"wall-clock budget {budget.max_seconds}s exhausted"
                )
        return tracer

    prev = sys.gettrace()
    sys.settrace(tracer)
    try:
        return fn(*args, **kwargs)
    finally:
        sys.settrace(prev)


# --- contract-verification integration ---------------------------------------

def metered_contract_verify(
    contract, ltx, budget: Optional[Budget] = None
) -> None:
    """Vet then run one contract's verify under the meter — the hook the
    verifier uses for attachment-delivered (untrusted) contract classes."""
    check_code(type(contract))
    run_metered(contract.verify, ltx, budget=budget or DEFAULT_BUDGET)

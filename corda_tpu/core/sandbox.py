"""Best-effort determinism guard for contract code (reference
`experimental/sandbox/src/main/java/net/corda/sandbox/` — the JVM
bytecode-rewriting `RuntimeCostAccounter` + `WhitelistClassLoader` become
(a) a static code-object scan and (b) a sys.settrace cost meter; same two
layers, Python-native mechanisms).

TRUST MODEL — READ THIS FIRST. These guards are DEFENSE-IN-DEPTH, not a
security boundary: CPython offers no in-process containment, and code
that passes `check_code` still runs with full interpreter privileges.
The PRIMARY control is the same as the reference's: only load
attachments from trusted stores (an operator-vetted attachment
directory, or attachments whose uploader signatures you trust). The
static scan exists to reject *accidental* non-determinism and the
obvious hostile patterns early, and the meter to bound runaway loops —
neither stops a determined attacker.

Known residual bypasses (kept current; add here when found):
  * C-level calls raise no trace events, so the meter cannot see work or
    side effects done inside extension code;
  * memory allocation is unmetered — one line event may allocate
    unbounded memory;
  * attribute names reached via strings that never appear in co_names
    (e.g. computed through data) evade the static scan; `getattr` and
    introspection dunders are forbidden, but exhaustively enumerating
    every reflective path in CPython is not possible.
Operators who must run genuinely untrusted code should do so in a
separate OS process under rlimits/seccomp, not behind this module.

Why it matters anyway: attachment-delivered contract code
(serialization/attachments_loader.py) executes inside every verifier; a
buggy-but-honest contract must not spin forever or read
non-deterministic inputs and split consensus. That accidental class is
what these layers reliably catch.

Layers:
  * `check_code(fn_or_cls)` — static: walks code objects recursively and
    rejects references to forbidden builtins (`open`, `eval`, `exec`,
    `getattr`, `__import__`, …), reflective attributes (`__globals__`,
    `__subclasses__`, …) and forbidden module roots (`os`, `socket`,
    `random`, `time`, `threading`, `gc`, `inspect`, …) before anything
    runs (WhitelistClassLoader analogue: reject at load time).
  * `run_metered(fn, *args, budget=...)` — dynamic: executes under a trace
    that charges 1 cost unit per line event plus an allocation surcharge
    per call, and enforces a wall-clock ceiling (RuntimeCostAccounter
    analogue: the reference charges per-instruction/allocation/jump).
"""
from __future__ import annotations

import sys
import time
import types
from dataclasses import dataclass
from typing import Any, Callable, FrozenSet, Iterable, Optional

FORBIDDEN_BUILTINS: FrozenSet[str] = frozenset({
    "open", "eval", "exec", "compile", "__import__", "input", "breakpoint",
    "globals", "vars", "memoryview", "exit", "quit",
    # reflective escapes: getattr("__globals__"-style walks defeat the
    # name scan, so dynamic attribute access is rejected wholesale
    "getattr", "setattr", "delattr",
})

#: attribute names that walk from any object to interpreter internals
#: (the `().__class__.__base__.__subclasses__()` → `__init__.__globals__`
#: escape and its relatives). co_names carries LOAD_ATTR names, so the
#: static scan sees these even without an explicit getattr call.
FORBIDDEN_ATTRIBUTES: FrozenSet[str] = frozenset({
    "__subclasses__", "__globals__", "__builtins__", "__bases__",
    "__base__", "__mro__", "mro", "__code__", "__closure__", "__func__",
    "__self__", "__dict__", "__getattribute__", "__setattr__",
    "__delattr__", "__reduce__", "__reduce_ex__", "__loader__", "__spec__",
    "__subclasshook__", "__init_subclass__",
})

#: module roots contract code must not touch (non-determinism, IO, or
#: reflection that reaches both — gc.get_objects / inspect walk to
#: arbitrary live objects, operator.attrgetter is a string getattr)
FORBIDDEN_MODULES: FrozenSet[str] = frozenset({
    "os", "sys", "io", "socket", "subprocess", "threading", "multiprocessing",
    "random", "secrets", "time", "datetime", "uuid", "pathlib", "shutil",
    "ctypes", "signal", "importlib", "pickle", "marshal", "urllib", "http",
    "posixpath", "ntpath", "genericpath",  # os.path implementation modules
    "builtins", "gc", "inspect", "traceback", "weakref", "operator",
    "code", "codeop", "pdb", "resource", "select", "asyncio", "socketserver",
})


class SandboxViolation(Exception):
    """Static rejection: the code references forbidden names/modules."""


class CostLimitExceeded(Exception):
    """Dynamic rejection: the execution budget ran out."""


@dataclass(frozen=True)
class Budget:
    """Execution budget (reference RuntimeCostAccounter's per-category
    thresholds, collapsed to line-cost + call-cost + wall clock)."""

    max_cost: int = 2_000_000       # ~line events + call surcharges
    max_seconds: float = 5.0
    call_surcharge: int = 10


DEFAULT_BUDGET = Budget()


# --- static layer ------------------------------------------------------------

def _iter_code(code: types.CodeType) -> Iterable[types.CodeType]:
    yield code
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            yield from _iter_code(const)


#: opcodes whose name operand can resolve to a module: imports and
#: global/name loads (module references enter a function as globals or
#: closure cells). LOAD_ATTR/LOAD_METHOD deliberately excluded — an
#: honest contract reading `tx.code` or calling `rows.select()` must not
#: trip the module blocklist.
_MODULE_POSITION_OPS = frozenset(
    {"IMPORT_NAME", "IMPORT_FROM", "LOAD_GLOBAL", "LOAD_NAME"}
)


def _module_position_names(code: types.CodeType) -> set:
    import dis

    names = set(code.co_freevars)  # closure cell may carry a module
    for ins in dis.get_instructions(code):
        if ins.opname in _MODULE_POSITION_OPS and isinstance(ins.argval, str):
            names.add(ins.argval)
    return names


def check_code(obj: Any, extra_forbidden: Iterable[str] = ()) -> None:
    """Statically vet a function or class (e.g. a Contract subclass): every
    reachable code object must not name a forbidden builtin or import a
    forbidden module root. Raises SandboxViolation."""
    forbidden = (
        FORBIDDEN_BUILTINS | FORBIDDEN_ATTRIBUTES | frozenset(extra_forbidden)
    )
    codes = []
    if isinstance(obj, type):
        for attr in vars(obj).values():
            fn = getattr(attr, "__func__", attr)
            if isinstance(fn, types.FunctionType):
                codes.append(fn.__code__)
    elif isinstance(obj, types.FunctionType):
        codes.append(obj.__code__)
    elif isinstance(obj, types.MethodType):
        codes.append(obj.__func__.__code__)
    else:
        raise TypeError(f"cannot vet {type(obj).__name__}")

    for top in codes:
        for code in _iter_code(top):
            # builtin/attribute blocklist: every referenced name counts
            # (co_names carries LOAD_ATTR names, co_freevars closures)
            names = set(code.co_names) | set(code.co_freevars)
            bad = names & forbidden
            # co_qualname arrived in 3.11; co_name is the 3.10 spelling
            label = getattr(code, "co_qualname", None) or code.co_name
            if bad:
                raise SandboxViolation(
                    f"{label} references "
                    f"forbidden name(s) {sorted(bad)}"
                )
            # module blocklist: only names in module position (imports,
            # global/name loads, closure cells) — plain attribute access
            # like `tx.code` must not match module 'code'
            for name in _module_position_names(code):
                root = name.split(".", 1)[0]
                if root in FORBIDDEN_MODULES:
                    raise SandboxViolation(
                        f"{label} touches "
                        f"forbidden module {root!r}"
                    )


# --- dynamic layer -----------------------------------------------------------

def run_metered(
    fn: Callable,
    *args: Any,
    budget: Budget = DEFAULT_BUDGET,
    **kwargs: Any,
):
    """Run fn under cost accounting; raises CostLimitExceeded when the
    budget is exhausted and SandboxViolation if execution enters a
    forbidden module. Returns fn's result. Not reentrant per thread.

    Best-effort only (see module docstring): C-level calls raise no
    trace events and allocations are unmetered, so this bounds honest
    runaway loops, not hostile code."""
    state = {"cost": 0, "deadline": time.monotonic() + budget.max_seconds}

    def tracer(frame, event, arg):
        if event == "call":
            state["cost"] += budget.call_surcharge
            mod = frame.f_globals.get("__name__", "")
            root = mod.split(".", 1)[0]
            if root in FORBIDDEN_MODULES:
                raise SandboxViolation(
                    f"execution entered forbidden module {mod!r}"
                )
            return tracer
        if event == "line":
            state["cost"] += 1
            if state["cost"] > budget.max_cost:
                raise CostLimitExceeded(
                    f"cost budget {budget.max_cost} exhausted"
                )
            if (state["cost"] & 0x3FF) == 0 and (
                time.monotonic() > state["deadline"]
            ):
                raise CostLimitExceeded(
                    f"wall-clock budget {budget.max_seconds}s exhausted"
                )
        return tracer

    prev = sys.gettrace()
    sys.settrace(tracer)
    try:
        return fn(*args, **kwargs)
    finally:
        sys.settrace(prev)


# --- contract-verification integration ---------------------------------------

def metered_contract_verify(
    contract, ltx, budget: Optional[Budget] = None
) -> None:
    """Vet then run one contract's verify under the meter — the hook the
    verifier uses for attachment-delivered contract classes.

    Defense-in-depth, not containment: the attachment must still come
    from a trusted store (module docstring, TRUST MODEL)."""
    check_code(type(contract))
    run_metered(contract.verify, ltx, budget=budget or DEFAULT_BUDGET)

"""Contract & state model.

Parity: reference `core/src/main/kotlin/net/corda/core/contracts/Structures.kt`
(ContractState :38, TransactionState :99, OwnableState :151, LinearState :194,
SchedulableState :229, StateRef :251, StateAndRef :259, Command :288,
Contract.verify :340, Attachment :387) and `TimeWindow.kt`.

Contracts are pure verification functions over a LedgerTransaction; they are
identified on the wire by a registered contract name so the out-of-process /
TPU verifier can resolve the verify logic without Python pickling.
"""
from __future__ import annotations

import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Dict, List, Optional, Type

from ..crypto.keys import PublicKey
from ..crypto.secure_hash import SecureHash
from ..identity import AbstractParty, Party
from ..serialization.codec import register_adapter

if TYPE_CHECKING:
    from ..transactions.ledger import LedgerTransaction


class TransactionVerificationError(Exception):
    """A transaction failed contract/structural verification (reference
    `TransactionVerificationException`)."""

    def __init__(self, tx_id, message: str):
        super().__init__(f"{message} (tx {tx_id})")
        self.tx_id = tx_id


# --- contracts ---------------------------------------------------------------

_CONTRACT_REGISTRY: Dict[str, Type["Contract"]] = {}


def contract(cls=None, *, name: str | None = None):
    """Register a Contract class under a stable wire name.

    The TPU-native analogue of the reference's attachment-classloader contract
    resolution (`AttachmentsClassLoader.kt`): LedgerTransactions reference
    contracts by name; the verifier process resolves them from this registry.
    """

    def wrap(c):
        wire_name = name or c.__qualname__
        if wire_name in _CONTRACT_REGISTRY and _CONTRACT_REGISTRY[wire_name] is not c:
            raise ValueError(f"contract name {wire_name!r} already registered")
        _CONTRACT_REGISTRY[wire_name] = c
        c.contract_name = wire_name
        return c

    return wrap(cls) if cls is not None else wrap


def resolve_contract(name: str) -> "Contract":
    try:
        return _CONTRACT_REGISTRY[name]()
    except KeyError:
        raise TransactionVerificationError(None, f"unknown contract {name!r}")


class Contract:
    """Verification logic for states (reference Structures.kt:340). Implement
    verify(); raise TransactionVerificationError (or any exception) to reject."""

    contract_name: str = ""

    def verify(self, tx: "LedgerTransaction") -> None:
        raise NotImplementedError

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(self.contract_name.encode())


class ContractState:
    """A fact on the ledger. Subclasses are dataclasses with a `participants`
    property and a `contract_name` class attribute naming their contract."""

    contract_name: str = ""

    @property
    def participants(self) -> List[AbstractParty]:
        raise NotImplementedError

    @property
    def contract(self) -> Contract:
        return resolve_contract(self.contract_name)


class OwnableState(ContractState):
    def move_command(self) -> "CommandData":
        """The command that authorises transferring this state to a new
        owner — used by generic trade flows (TwoPartyTradeFlow) to build
        move transactions without knowing the concrete contract."""
        raise NotImplementedError

    owner: AbstractParty

    def with_new_owner(self, new_owner: AbstractParty) -> "OwnableState":
        raise NotImplementedError


@dataclass(frozen=True)
class UniqueIdentifier:
    """external_id + uuid pair identifying a LinearState chain
    (reference `UniqueIdentifier.kt`)."""

    external_id: Optional[str] = None
    uuid: bytes = field(default_factory=lambda: uuid_mod.uuid4().bytes)

    def __str__(self) -> str:
        u = uuid_mod.UUID(bytes=self.uuid)
        return f"{self.external_id}_{u}" if self.external_id else str(u)


class LinearState(ContractState):
    """A state evolving through a chain of transactions, identified by
    linear_id across versions (reference Structures.kt:194)."""

    linear_id: UniqueIdentifier


@dataclass(frozen=True)
class ScheduledActivity:
    """What to run when a SchedulableState's time arrives: a flow name +
    args (the FlowLogicRef equivalent) and the scheduled unix-nanos time."""

    flow_name: str
    flow_args: tuple
    scheduled_at: int


class SchedulableState(ContractState):
    def next_scheduled_activity(self, this_state_ref: "StateRef") -> Optional[ScheduledActivity]:
        raise NotImplementedError


@dataclass(frozen=True)
class StateRef:
    """(txhash, output index) pointer to a state (reference Structures.kt:251)."""

    txhash: SecureHash
    index: int

    def __repr__(self) -> str:
        return f"{self.txhash}({self.index})"


@dataclass(frozen=True)
class TransactionState:
    """A ContractState plus ledger metadata (reference Structures.kt:99)."""

    data: ContractState
    notary: Party
    encumbrance: Optional[int] = None


@dataclass(frozen=True)
class StateAndRef:
    state: TransactionState
    ref: StateRef


@dataclass(frozen=True)
class Command:
    """Command data + required signing keys (reference Structures.kt:288)."""

    value: "CommandData"
    signers: tuple  # tuple[PublicKey, ...]

    def __post_init__(self):
        if not self.signers:
            raise ValueError("command must have at least one signer")


class CommandData:
    """Marker base for command payloads (dataclasses, registered for wire)."""


@dataclass(frozen=True)
class TypeOnlyCommandData(CommandData):
    """Command whose identity is its type alone (e.g. Move, Exit)."""

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))


@dataclass(frozen=True)
class AuthenticatedObject:
    """A command with its signer metadata resolved to parties
    (reference Structures.kt AuthenticatedObject)."""

    signers: tuple  # keys
    signing_parties: tuple  # resolved parties, possibly empty
    value: CommandData


@dataclass(frozen=True)
class TimeWindow:
    """[from_time, until_time) in unix nanoseconds; either bound optional
    (reference `core/.../contracts/TimeWindow.kt`)."""

    from_time: Optional[int] = None
    until_time: Optional[int] = None

    def __post_init__(self):
        if self.from_time is None and self.until_time is None:
            raise ValueError("a time window needs at least one bound")
        if (
            self.from_time is not None
            and self.until_time is not None
            and self.until_time < self.from_time
        ):
            raise ValueError("until_time < from_time")

    @staticmethod
    def between(from_time: int, until_time: int) -> "TimeWindow":
        return TimeWindow(from_time, until_time)

    @staticmethod
    def from_only(from_time: int) -> "TimeWindow":
        return TimeWindow(from_time, None)

    @staticmethod
    def until_only(until_time: int) -> "TimeWindow":
        return TimeWindow(None, until_time)

    @staticmethod
    def with_tolerance(instant: int, tolerance_nanos: int) -> "TimeWindow":
        return TimeWindow(instant - tolerance_nanos, instant + tolerance_nanos)

    @property
    def midpoint(self) -> Optional[int]:
        if self.from_time is None or self.until_time is None:
            return None
        return (self.from_time + self.until_time) // 2

    def contains(self, instant: int) -> bool:
        if self.from_time is not None and instant < self.from_time:
            return False
        if self.until_time is not None and instant >= self.until_time:
            return False
        return True


class Attachment:
    """Content-addressed binary attachment (reference Structures.kt:387)."""

    def __init__(self, attachment_id: SecureHash, data: bytes):
        self.id = attachment_id
        self.data = data

    @staticmethod
    def of(data: bytes) -> "Attachment":
        return Attachment(SecureHash.sha256(data), data)


# --- wire registration -------------------------------------------------------

register_adapter(
    UniqueIdentifier, "UniqueIdentifier",
    lambda u: {"external_id": u.external_id, "uuid": u.uuid},
    lambda d: UniqueIdentifier(d["external_id"], d["uuid"]),
)
register_adapter(
    StateRef, "StateRef",
    lambda r: {"txhash": r.txhash, "index": r.index},
    lambda d: StateRef(d["txhash"], d["index"]),
)
register_adapter(
    TransactionState, "TransactionState",
    lambda s: {"data": s.data, "notary": s.notary, "encumbrance": s.encumbrance},
    lambda d: TransactionState(d["data"], d["notary"], d["encumbrance"]),
)
register_adapter(
    StateAndRef, "StateAndRef",
    lambda s: {"state": s.state, "ref": s.ref},
    lambda d: StateAndRef(d["state"], d["ref"]),
)
register_adapter(
    Command, "Command",
    lambda c: {"value": c.value, "signers": list(c.signers)},
    lambda d: Command(d["value"], tuple(d["signers"])),
)
register_adapter(
    TimeWindow, "TimeWindow",
    lambda t: {"from_time": t.from_time, "until_time": t.until_time},
    lambda d: TimeWindow(d["from_time"], d["until_time"]),
)

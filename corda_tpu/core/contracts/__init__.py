"""Contract & state model (reference `core/.../contracts/`)."""
from .amount import Amount, Issued, display_token_size
from .structures import (
    Attachment,
    AuthenticatedObject,
    Command,
    CommandData,
    Contract,
    ContractState,
    LinearState,
    OwnableState,
    SchedulableState,
    ScheduledActivity,
    StateAndRef,
    StateRef,
    TimeWindow,
    TransactionState,
    TransactionVerificationError,
    TypeOnlyCommandData,
    UniqueIdentifier,
    contract,
    resolve_contract,
)

__all__ = [
    "Amount", "Issued", "display_token_size",
    "Attachment", "AuthenticatedObject", "Command", "CommandData", "Contract",
    "ContractState", "LinearState", "OwnableState", "SchedulableState",
    "ScheduledActivity", "StateAndRef", "StateRef", "TimeWindow",
    "TransactionState", "TransactionVerificationError", "TypeOnlyCommandData",
    "UniqueIdentifier", "contract", "resolve_contract",
]

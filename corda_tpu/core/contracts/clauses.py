"""Clauses: composable contract-verification units (reference
`core/src/main/kotlin/net/corda/core/contracts/clauses/` — Clause,
AnyOf/AllOf/FirstOf composition, GroupClauseVerifier).

A Clause matches on required commands and verifies one aspect of a
transaction; compositions express contract logic as a tree.  `verify_clause`
is the entry point contracts call from `Contract.verify`.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Set, Type

from .structures import AuthenticatedObject, TransactionVerificationError


class Clause:
    """One verification unit.

    required_commands: command types that must ALL be present among the
    matched commands for this clause to trigger (empty = always triggers).
    """

    required_commands: tuple = ()

    def matches(self, commands: List[AuthenticatedObject]) -> bool:
        present = {type(c.value) for c in commands}
        return all(rc in present for rc in self.required_commands)

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> Set:
        """Verify; returns the set of command VALUES matched/consumed."""
        raise NotImplementedError

    def get_execution_path(self, commands) -> List["Clause"]:
        return [self]


class CompositeClause(Clause):
    def __init__(self, *clauses: Clause):
        self.clauses = list(clauses)


class AllOf(CompositeClause):
    """Every child must match and verify (reference AllOf)."""

    def matches(self, commands) -> bool:
        return all(c.matches(commands) for c in self.clauses)

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> Set:
        matched: Set = set()
        for clause in self.clauses:
            if not clause.matches(commands):
                raise TransactionVerificationError(
                    getattr(tx, "id", None),
                    f"required clause {type(clause).__name__} did not match",
                )
            matched |= clause.verify(tx, inputs, outputs, commands, grouping_key)
        return matched


class AnyOf(CompositeClause):
    """One or more children must match; all that match are verified
    (reference AnyOf/AnyComposition)."""

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> Set:
        matched: Set = set()
        matched_any = False
        for clause in self.clauses:
            if clause.matches(commands):
                matched |= clause.verify(tx, inputs, outputs, commands, grouping_key)
                matched_any = True
        if not matched_any:
            raise TransactionVerificationError(
                getattr(tx, "id", None), "no clause matched the commands"
            )
        return matched


class FirstOf(CompositeClause):
    """The first matching child verifies; error if none match
    (reference FirstOf/FirstComposition)."""

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> Set:
        for clause in self.clauses:
            if clause.matches(commands):
                return clause.verify(tx, inputs, outputs, commands, grouping_key)
        raise TransactionVerificationError(
            getattr(tx, "id", None), "no clause matched the commands"
        )


class GroupClauseVerifier(Clause):
    """Applies a clause tree to each state group independently (reference
    GroupClauseVerifier): subclass provides group_states(tx)."""

    def __init__(self, clause: Clause):
        self.clause = clause

    def group_states(self, tx):
        raise NotImplementedError

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> Set:
        matched: Set = set()
        for group in self.group_states(tx):
            matched |= self.clause.verify(
                tx, list(group.inputs), list(group.outputs), commands,
                group.grouping_key,
            )
        return matched


def verify_clause(tx, clause: Clause, commands: List[AuthenticatedObject]) -> None:
    """Run a clause tree over a LedgerTransaction; every command the
    contract declares must be matched by some clause (reference
    verifyClause: unmatched commands are an error)."""
    matched = clause.verify(
        tx, tx.input_states, tx.output_states, commands, None
    )
    unmatched = [c.value for c in commands if c.value not in matched]
    if unmatched:
        raise TransactionVerificationError(
            getattr(tx, "id", None),
            f"commands not matched by any clause: {unmatched}",
        )

"""Amount and issued-token primitives.

Parity: reference `core/src/main/kotlin/net/corda/core/contracts/Amount.kt`
(`Amount<T>` integer-quantity money math that refuses mixed-token arithmetic
and overflow/negative quantities) and `Structures.kt` `Issued<T>`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Generic, Iterable, TypeVar

from ..identity import PartyAndReference
from ..serialization.codec import register_adapter

T = TypeVar("T")

# display token sizes: minor-unit exponent per ISO currency (default 2)
_EXPONENTS = {"JPY": 0, "KWD": 3, "BHD": 3, "XBT": 8}


def display_token_size(token) -> int:
    """10^-exponent of the token's minor unit (e.g. 100 cents per USD)."""
    code = token if isinstance(token, str) else getattr(token, "product", None)
    return 10 ** _EXPONENTS.get(code, 2) if isinstance(code, str) else 100


@dataclass(frozen=True)
class Issued(Generic[T]):
    """A product with its issuer attached: `Issued(issuer_ref, "USD")`
    (reference Structures.kt Issued)."""

    issuer: PartyAndReference
    product: T

    def __repr__(self) -> str:
        return f"{self.product} issued by {self.issuer}"


@dataclass(frozen=True)
class Amount(Generic[T]):
    """Integer quantity of a token in its minor unit (reference Amount.kt)."""

    quantity: int
    token: T

    def __post_init__(self):
        if self.quantity < 0:
            raise ValueError("amount quantity cannot be negative")

    @staticmethod
    def from_decimal(value, token, rounding: str | None = None) -> "Amount":
        """Convert a decimal value to minor units. Lossy conversions raise
        unless an explicit rounding mode ("floor" or "round") is given —
        money must not silently vanish (reference Amount.fromDecimal)."""
        from decimal import Decimal

        exact = Decimal(str(value)) * display_token_size(token)
        if exact == exact.to_integral_value():
            return Amount(int(exact), token)
        if rounding == "floor":
            return Amount(int(exact.to_integral_value(rounding="ROUND_FLOOR")), token)
        if rounding == "round":
            return Amount(int(exact.to_integral_value(rounding="ROUND_HALF_UP")), token)
        raise ValueError(
            f"{value} is not an exact multiple of {token}'s minor unit; "
            "pass rounding='floor' or 'round' to allow loss"
        )

    def to_decimal(self):
        return self.quantity / display_token_size(self.token)

    def _check(self, other: "Amount[T]"):
        if other.token != self.token:
            raise ValueError(f"token mismatch: {self.token} vs {other.token}")

    def __add__(self, other: "Amount[T]") -> "Amount[T]":
        self._check(other)
        return Amount(self.quantity + other.quantity, self.token)

    def __sub__(self, other: "Amount[T]") -> "Amount[T]":
        self._check(other)
        return Amount(self.quantity - other.quantity, self.token)

    def __mul__(self, k: int) -> "Amount[T]":
        return Amount(self.quantity * k, self.token)

    def __lt__(self, other: "Amount[T]") -> bool:
        self._check(other)
        return self.quantity < other.quantity

    def __le__(self, other: "Amount[T]") -> bool:
        self._check(other)
        return self.quantity <= other.quantity

    @staticmethod
    def sum_or_none(amounts: Iterable["Amount[T]"]):
        amounts = list(amounts)
        if not amounts:
            return None
        total = amounts[0]
        for a in amounts[1:]:
            total = total + a
        return total

    @staticmethod
    def sum_or_zero(amounts: Iterable["Amount[T]"], token: T) -> "Amount[T]":
        return Amount.sum_or_none(amounts) or Amount(0, token)

    @staticmethod
    def sum_or_throw(amounts: Iterable["Amount[T]"]) -> "Amount[T]":
        total = Amount.sum_or_none(amounts)
        if total is None:
            raise ValueError("empty amount list")
        return total

    def __repr__(self) -> str:
        size = display_token_size(self.token)
        digits = len(str(size)) - 1  # 1 -> 0dp, 100 -> 2dp, 1000 -> 3dp
        return f"{self.quantity / size:.{digits}f} {self.token}"


register_adapter(
    Issued, "Issued",
    lambda i: {"issuer": i.issuer, "product": i.product},
    lambda d: Issued(d["issuer"], d["product"]),
)
register_adapter(
    Amount, "Amount",
    lambda a: {"quantity": a.quantity, "token": a.token},
    lambda d: Amount(d["quantity"], d["token"]),
)

"""Core library flows (reference `core/src/main/kotlin/net/corda/core/flows/`).

  * FetchTransactionsFlow / FetchAttachmentsFlow + handlers — FetchDataFlow.kt
  * ResolveTransactionsFlow — dependency-graph download + topological order
    (`ResolveTransactionsFlow.kt`, breadth-limited)
  * BroadcastTransactionFlow + handler — BroadcastTransactionFlow.kt
  * FinalityFlow — notarise + record + broadcast (`FinalityFlow.kt:36-78`)
  * CollectSignaturesFlow / SignTransactionFlow — CollectSignaturesFlow.kt
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Optional, Set, Tuple

from ..contracts.structures import Attachment
from ..crypto.secure_hash import SecureHash
from ..identity import Party
from ..serialization.codec import register_adapter
from ..transactions.signed import SignedTransaction
from .api import FlowException, FlowLogic, initiated_by, initiating_flow


# ---------------------------------------------------------------------------
# Data-fetch protocol
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FetchRequest:
    hashes: Tuple[SecureHash, ...]


@dataclass(frozen=True)
class FetchResponse:
    items: Tuple  # SignedTransaction or bytes (attachment contents), or None


register_adapter(
    FetchRequest, "FetchRequest",
    lambda r: {"hashes": list(r.hashes)},
    lambda d: FetchRequest(tuple(d["hashes"])),
)
register_adapter(
    FetchResponse, "FetchResponse",
    lambda r: {"items": list(r.items)},
    lambda d: FetchResponse(tuple(d["items"])),
)


class DataNotFoundError(FlowException):
    def __init__(self, missing):
        super().__init__(f"counterparty could not provide: {missing}")
        self.missing = missing


@initiating_flow
class FetchTransactionsFlow(FlowLogic):
    """Fetch SignedTransactions by hash from a peer; local storage is
    checked first (reference FetchDataFlow caching behavior)."""

    def __init__(self, hashes: Iterable[SecureHash], other_party: Party):
        self.hashes = tuple(hashes)
        self.other_party = other_party

    def call(self):
        storage = self.service_hub.validated_transactions
        from_disk, to_fetch = [], []
        for h in self.hashes:
            stx = storage.get(h)
            (from_disk if stx is not None else to_fetch).append((h, stx))
        downloaded = []
        if to_fetch:
            req = FetchRequest(tuple(h for h, _ in to_fetch))
            resp = yield self.send_and_receive(
                self.other_party, req, FetchResponse
            )
            if len(resp.items) != len(req.hashes):
                raise FetchDataError("response length mismatch")
            for h, stx in zip(req.hashes, resp.items):
                if stx is None:
                    raise DataNotFoundError(h)
                if stx.id != h:
                    raise FetchDataError(
                        f"downloaded transaction hashes to {stx.id}, wanted {h}"
                    )
                downloaded.append(stx)
        return [stx for _, stx in from_disk if stx is not None] + downloaded


class FetchDataError(FlowException):
    pass


@initiated_by(FetchTransactionsFlow)
class FetchTransactionsHandler(FlowLogic):
    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        req = yield self.receive(self.counterparty, FetchRequest)
        storage = self.service_hub.validated_transactions
        items = tuple(storage.get(h) for h in req.hashes)
        yield self.send(self.counterparty, FetchResponse(items))


@initiating_flow
class FetchAttachmentsFlow(FlowLogic):
    def __init__(self, hashes: Iterable[SecureHash], other_party: Party):
        self.hashes = tuple(hashes)
        self.other_party = other_party

    def call(self):
        att_storage = self.service_hub.attachments
        to_fetch = [h for h in self.hashes if not att_storage.has_attachment(h)]
        if to_fetch:
            resp = yield self.send_and_receive(
                self.other_party, FetchRequest(tuple(to_fetch)), FetchResponse
            )
            if len(resp.items) != len(to_fetch):
                raise FetchDataError("response length mismatch")
            for h, data in zip(to_fetch, resp.items):
                if data is None:
                    raise DataNotFoundError(h)
                got = att_storage.import_attachment(data)
                if got != h:
                    raise FetchDataError(f"attachment hashed to {got}, wanted {h}")
        return [att_storage.open_attachment(h) for h in self.hashes]


@initiated_by(FetchAttachmentsFlow)
class FetchAttachmentsHandler(FlowLogic):
    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        req = yield self.receive(self.counterparty, FetchRequest)
        atts = []
        for h in req.hashes:
            att = self.service_hub.attachments.open_attachment(h)
            atts.append(att.data if att is not None else None)
        yield self.send(self.counterparty, FetchResponse(tuple(atts)))


# ---------------------------------------------------------------------------
# ResolveTransactionsFlow
# ---------------------------------------------------------------------------

class ExcessivelyLargeTransactionGraphError(FlowException):
    pass


def collect_dependencies(stx: SignedTransaction, services, limit: int = 64):
    """The locally-stored dependency chain of `stx`, BFS order, capped.

    Senders attach this to notarise requests and broadcasts so receivers
    resolve without per-dependency fetch dialogues (the pull model's hop
    tax); receivers verify pushed transactions exactly like fetched ones,
    and anything beyond the cap still pulls."""
    storage = services.validated_transactions
    out: List[SignedTransaction] = []
    seen: Set = set()
    frontier = [inp.txhash for inp in stx.tx.inputs]
    while frontier and len(out) < limit:
        h = frontier.pop(0)
        if h in seen:
            continue
        seen.add(h)
        dep = storage.get(h)
        if dep is None:
            continue  # receiver will pull it from us instead
        out.append(dep)
        frontier.extend(inp.txhash for inp in dep.tx.inputs)
    return tuple(out)


@initiating_flow
class ResolveTransactionsFlow(FlowLogic):
    """Download and commit the dependency chain of a transaction
    (reference ResolveTransactionsFlow.kt: BFS with a transaction-count
    bound, then verify/record in topological order).

    `pool`: sender-pushed candidate transactions (UNTRUSTED — they take
    the same verify path as fetched ones); dependencies found there skip
    the fetch dialogue entirely."""

    MAX_TRANSACTIONS = 5000
    #: receiver-side cap on a sender-pushed pool: the 64-entry limit in
    #: collect_dependencies binds only HONEST senders; a hostile peer's
    #: oversized pool must not buy attacker-sized deserialize/hash work
    MAX_POOL = 64

    def __init__(self, stx_or_hashes, other_party: Party, pool=()):
        if isinstance(stx_or_hashes, SignedTransaction):
            self.stx: Optional[SignedTransaction] = stx_or_hashes
            self.hashes: Tuple[SecureHash, ...] = ()
        else:
            self.stx = None
            self.hashes = tuple(stx_or_hashes)
        self.other_party = other_party
        self.pool = tuple(pool)[: self.MAX_POOL]

    def call(self):
        start_hashes = (
            tuple({inp.txhash for inp in self.stx.tx.inputs})
            if self.stx is not None
            else self.hashes
        )
        storage = self.service_hub.validated_transactions
        fetched: dict = {}
        frontier: List[SecureHash] = [
            h for h in start_hashes if storage.get(h) is None
        ]
        # Hash the pool only when something is actually missing locally
        # (ids are recomputed Merkle roots, so a hostile pool cannot
        # alias a different tx under a dependency's hash; a receiver
        # that already has the chain pays nothing for the pool).
        pool_by_id = (
            {t.id: t for t in self.pool if isinstance(t, SignedTransaction)}
            if frontier
            else {}
        )
        while frontier:
            if len(fetched) > self.MAX_TRANSACTIONS:
                raise ExcessivelyLargeTransactionGraphError(
                    f"dependency graph exceeded {self.MAX_TRANSACTIONS}"
                )
            batch = [h for h in frontier if h not in fetched]
            frontier = []
            if not batch:
                break
            stxs = [pool_by_id[h] for h in batch if h in pool_by_id]
            missing = tuple(h for h in batch if h not in pool_by_id)
            if missing:
                stxs += yield from self.sub_flow(
                    FetchTransactionsFlow(missing, self.other_party)
                )
            for stx in stxs:
                if stx.id in fetched:
                    continue
                fetched[stx.id] = stx
                for inp in stx.tx.inputs:
                    if inp.txhash not in fetched and storage.get(inp.txhash) is None:
                        frontier.append(inp.txhash)
        # Topological order: dependencies before dependents.
        ordered = _topological_sort(fetched)
        for stx in ordered:
            # A dependency already in validated storage was verified when
            # it was recorded — re-verifying it (piggybacked pools often
            # carry transactions the receiver already holds) is pure
            # repeat work with the same trust basis as the frontier's
            # storage check above.
            if storage.get(stx.id) is not None:
                continue
            # Fetch attachments referenced by the dependency if missing.
            missing_atts = [
                h for h in stx.tx.attachments
                if not self.service_hub.attachments.has_attachment(h)
            ]
            if missing_atts:
                yield from self.sub_flow(
                    FetchAttachmentsFlow(tuple(missing_atts), self.other_party)
                )
            verify_dependency(stx, self.service_hub)
            self.service_hub.record_transactions([stx])
        return ordered


def verify_dependency(stx: SignedTransaction, services) -> None:
    """Verify a downloaded dependency of either transaction kind.

    Notary-change transactions have no contracts to run and their required
    signers need input resolution (reference
    NotaryChangeLedgerTransaction); everything else takes the regular
    signatures + contracts path."""
    from ..transactions.notary_change import NotaryChangeWireTransaction

    wtx = stx.tx
    if isinstance(wtx, NotaryChangeWireTransaction):
        stx.check_signatures_are_valid()
        try:
            # A committed dependency carries the old notary's signature too.
            wtx.check_inputs_and_signatures(stx.sigs, services.load_state)
        except ValueError as exc:
            raise FlowException(str(exc))
        return
    stx.verify(services)


def _topological_sort(by_id: dict) -> List[SignedTransaction]:
    ordered: List[SignedTransaction] = []
    visited: Set = set()

    def visit(stx):
        if stx.id in visited:
            return
        visited.add(stx.id)
        for inp in stx.tx.inputs:
            dep = by_id.get(inp.txhash)
            if dep is not None:
                visit(dep)
        ordered.append(stx)

    for stx in by_id.values():
        visit(stx)
    return ordered


@initiated_by(ResolveTransactionsFlow)
class ResolveTransactionsHandler(FlowLogic):
    """Counterparty side of resolution: serve fetch requests until the
    initiator's ResolveTransactionsFlow is done.  The initiator's sub-flows
    (FetchTransactionsFlow) open their own sessions, so this responder only
    exists when ResolveTransactionsFlow itself initiates — which it does
    not; kept for registry completeness and session compat."""

    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        return None


# ---------------------------------------------------------------------------
# Broadcast + Finality
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TransactionDelivery:
    """A broadcast transaction with its sender-pushed dependency chain
    (bounded; receiver verifies everything — see collect_dependencies)."""

    stx: SignedTransaction = None
    dependencies: Tuple = ()


register_adapter(
    TransactionDelivery, "TransactionDelivery",
    lambda t: {"stx": t.stx, "deps": list(t.dependencies)},
    lambda d: TransactionDelivery(d["stx"], tuple(d.get("deps") or ())),
)


@initiating_flow
class BroadcastTransactionFlow(FlowLogic):
    """Send a notarised transaction to recipients for recording
    (reference BroadcastTransactionFlow.kt), with its dependency chain
    piggybacked so recipients rarely open fetch dialogues back.

    Always sends the TransactionDelivery wrapper: every node in a
    deployment ships this module (the wrapper registers at import), so
    there is no old-receiver case on the wire; the handler's bare-stx
    branch exists for checkpoints recorded before the wrapper landed."""

    def __init__(self, stx: SignedTransaction, recipients: Iterable[Party]):
        self.stx = stx
        self.recipients = tuple(recipients)

    def call(self):
        deps = collect_dependencies(self.stx, self.service_hub)
        delivery = TransactionDelivery(self.stx, deps)
        for party in self.recipients:
            yield self.send(party, delivery)


@initiated_by(BroadcastTransactionFlow)
class NotifyTransactionHandler(FlowLogic):
    """Receive a broadcast transaction: resolve its chain (sender-pushed
    pool first, fetch dialogues for the rest), verify and record
    (reference NotifyTransactionHandler in AbstractNode.installCoreFlows).
    Accepts a bare SignedTransaction too (pre-piggyback senders)."""

    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        delivery = yield self.receive(self.counterparty, object)
        if isinstance(delivery, TransactionDelivery):
            stx, pool = delivery.stx, delivery.dependencies
        elif isinstance(delivery, SignedTransaction):
            stx, pool = delivery, ()
        else:
            raise FlowException(
                f"expected a transaction delivery, got {type(delivery).__name__}"
            )
        if not isinstance(stx, SignedTransaction):
            # the wrapper's stx field defaults to None; a malformed
            # delivery must reject cleanly, not TypeError mid-resolution
            raise FlowException("transaction delivery carries no transaction")
        yield from self.sub_flow(
            ResolveTransactionsFlow(stx, self.counterparty, pool=pool)
        )
        missing_atts = [
            h for h in stx.tx.attachments
            if not self.service_hub.attachments.has_attachment(h)
        ]
        if missing_atts:
            yield from self.sub_flow(
                FetchAttachmentsFlow(tuple(missing_atts), self.counterparty)
            )
        stx.verify(self.service_hub)
        self.service_hub.record_transactions([stx])


class FinalityFlow(FlowLogic):
    """Notarise (if needed), record locally, broadcast to participants
    (reference FinalityFlow.kt:36-78).  Not @initiating_flow itself: its
    sub-flows open the sessions."""

    def __init__(self, stx: SignedTransaction, extra_recipients: Iterable[Party] = ()):
        self.stx = stx
        self.extra_recipients = tuple(extra_recipients)

    def call(self):
        stx = self.stx
        # Local verification before asking anyone else to trust it.
        if stx.notary is not None:
            stx.verify_signatures_except(stx.notary.owning_key)
        else:
            stx.verify_required_signatures()
        needs_notary = bool(stx.tx.inputs) or stx.tx.time_window is not None
        if needs_notary and stx.notary is not None:
            notary_sigs = yield from self.sub_flow(NotaryClientFlowRef(stx))
            stx = stx.with_additional_signatures(notary_sigs)
        stx.verify_required_signatures()
        self.service_hub.record_transactions([stx])
        recipients = set(self.extra_recipients)
        for ts in stx.tx.outputs:
            for p in ts.data.participants:
                resolved = self.service_hub.identity_service.party_from_anonymous(p)
                if resolved is not None:
                    recipients.add(resolved)
        recipients.discard(self.service_hub.my_info)
        if recipients:
            yield from self.sub_flow(
                BroadcastTransactionFlow(stx, sorted(recipients, key=lambda p: p.name))
            )
        return stx


def NotaryClientFlowRef(stx, notary=None):
    """Late import to avoid core->node cycle at module load. `notary`
    overrides the routing target (the notary-change ASSUME leg sends the
    old-notary-signed tx to the NEW notary); None routes to stx.notary."""
    from ...node.notary import NotaryClientFlow

    return NotaryClientFlow(stx, notary=notary)


# ---------------------------------------------------------------------------
# CollectSignaturesFlow / SignTransactionFlow
# ---------------------------------------------------------------------------

@initiating_flow
class CollectSignaturesFlow(FlowLogic):
    """Gather signatures from every required signer except ourselves and the
    notary (reference CollectSignaturesFlow.kt)."""

    def __init__(self, partially_signed: SignedTransaction):
        self.partially_signed = partially_signed

    def call(self):
        stx = self.partially_signed
        hub = self.service_hub
        my_keys = hub.key_management_service.keys
        notary_key = (
            stx.notary.owning_key.encoded if stx.notary is not None else None
        )
        missing = []
        for key in stx.tx.required_signing_keys:
            if key.encoded == notary_key or key.encoded in my_keys:
                continue
            missing.append(key)
        for key in missing:
            party = hub.identity_service.party_from_key(key)
            if party is None:
                raise FlowException(f"no identity known for required signer {key}")
            sig = yield self.send_and_receive(party, stx)
            stx = stx.with_additional_signature(sig)
        if stx.notary is not None:
            stx.verify_signatures_except(stx.notary.owning_key)
        else:
            stx.verify_required_signatures()
        return stx


class SignTransactionFlow(FlowLogic):
    """Abstract responder: receive a proposed stx, run `check_transaction`,
    sign and return (reference SignTransactionFlow).  Subclass and register
    with @initiated_by(CollectSignaturesFlow)."""

    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def check_transaction(self, stx: SignedTransaction) -> None:
        """Override: raise FlowException to refuse signing."""

    def call(self):
        stx = yield self.receive(self.counterparty, SignedTransaction)
        stx.check_signatures_are_valid()
        self.check_transaction(stx)
        hub = self.service_hub
        my_keys = hub.key_management_service.keys
        to_sign = [
            k for k in stx.tx.required_signing_keys if k.encoded in my_keys
        ]
        if not to_sign:
            raise FlowException("transaction does not require our signature")
        sig = hub.key_management_service.sign(stx.id.bytes, to_sign[0])
        yield self.send(self.counterparty, sig)
        return None

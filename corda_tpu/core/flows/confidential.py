"""Confidential identities: TransactionKeyFlow.

Reference parity: `core/src/main/kotlin/net/corda/core/flows/
TransactionKeyFlow.kt` — both sides of a session generate FRESH keys for
a transaction and swap them, so on-ledger states reference anonymous
keys unlinkable (by outsiders) to legal identities; each node's identity
service records the mapping for its counterparty.
"""
from __future__ import annotations

from ..identity import AnonymousParty, Party
from .api import FlowLogic, initiated_by, initiating_flow


@initiating_flow
class TransactionKeyFlow(FlowLogic):
    """Swap fresh confidential keys with `other_party`; returns a mapping
    {well_known_party: AnonymousParty} covering both sides."""

    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        hub = self.service_hub
        mine = yield self.record(
            lambda: AnonymousParty(hub.key_management_service.fresh_key())
        )
        theirs = yield self.send_and_receive(
            self.other_party, mine, AnonymousParty
        )
        hub.identity_service.register_anonymous_identity(
            theirs.owning_key, self.other_party
        )
        return {self.other_party: theirs, hub.my_info: mine}


@initiated_by(TransactionKeyFlow)
class TransactionKeyHandler(FlowLogic):
    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def call(self):
        hub = self.service_hub
        theirs = yield self.receive(self.counterparty, AnonymousParty)
        hub.identity_service.register_anonymous_identity(
            theirs.owning_key, self.counterparty
        )
        mine = yield self.record(
            lambda: AnonymousParty(hub.key_management_service.fresh_key())
        )
        yield self.send(self.counterparty, mine)
        return {self.counterparty: theirs, hub.my_info: mine}

"""corda_tpu.core.flows: the checkpointable multi-party protocol API.

Reference parity: `core/src/main/kotlin/net/corda/core/flows/` (FlowLogic,
annotations, FlowException).  The TPU-native redesign replaces Quasar
bytecode-instrumented fibers with plain Python generators: a flow's `call()`
is a generator that yields FlowIORequest objects (Send/Receive/...) and is
driven by the node's StateMachineManager, which checkpoints the flow as
(class, args, io-result log) and restores it by deterministic replay —
no stack serialization, no agent (SURVEY.md section 7 item 4).
"""
from .api import (
    FlowException,
    FlowKilledException,
    FlowLogic,
    ProgressTracker,
    Receive,
    Send,
    SendAndReceive,
    WaitForLedgerCommit,
    flow_registry,
    get_initiated_by,
    initiated_by,
    initiating_flow,
    schedulable_flow,
    startable_by_rpc,
)
from .confidential import TransactionKeyFlow, TransactionKeyHandler
from .statereplacement import (
    AbstractStateReplacementAcceptor,
    AbstractStateReplacementInstigator,
    ContractUpgradeFlow,
    NotaryChangeFlow,
    Proposal,
    StateReplacementException,
    UpgradeCommand,
    UpgradedContract,
)
from .library import (
    BroadcastTransactionFlow,
    CollectSignaturesFlow,
    DataNotFoundError,
    FetchAttachmentsFlow,
    FetchDataError,
    FetchTransactionsFlow,
    FinalityFlow,
    NotifyTransactionHandler,
    ResolveTransactionsFlow,
    SignTransactionFlow,
)

__all__ = [
    "FlowException", "FlowKilledException", "FlowLogic", "ProgressTracker",
    "Receive", "Send", "SendAndReceive", "WaitForLedgerCommit",
    "flow_registry", "get_initiated_by", "initiated_by", "initiating_flow",
    "schedulable_flow", "startable_by_rpc",
    "BroadcastTransactionFlow", "CollectSignaturesFlow", "DataNotFoundError",
    "FetchAttachmentsFlow", "FetchDataError", "FetchTransactionsFlow",
    "FinalityFlow", "NotifyTransactionHandler", "ResolveTransactionsFlow",
    "SignTransactionFlow",
    "AbstractStateReplacementAcceptor", "AbstractStateReplacementInstigator",
    "ContractUpgradeFlow", "NotaryChangeFlow", "Proposal",
    "StateReplacementException", "UpgradeCommand", "UpgradedContract",
    "TransactionKeyFlow", "TransactionKeyHandler",
]

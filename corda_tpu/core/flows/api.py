"""FlowLogic API: generator-based checkpointable protocols.

A flow author writes (reference `FlowLogic.kt:38-264` for the surface):

    @initiating_flow
    @startable_by_rpc
    class Ping(FlowLogic):
        def __init__(self, party):
            self.party = party

        def call(self):
            answer = yield self.send_and_receive(self.party, b"ping", bytes)
            return answer

    @initiated_by(Ping)
    class Pong(FlowLogic):
        def __init__(self, counterparty):
            self.counterparty = counterparty

        def call(self):
            msg = yield self.receive(self.counterparty, bytes)
            yield self.send(self.counterparty, b"pong")

Every suspension point is an explicit `yield` of a FlowIORequest; the result
of the suspension is the value the yield evaluates to.  `sub_flow` composes
with `yield from`.  Determinism rule (documented, like the reference's
@Suspendable contract): `call()` must be deterministic given its constructor
args and the sequence of IO results — that is what makes replay-restore
(the checkpoint model) sound.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Type

from ..identity import Party


_exception_registry: Dict[str, type] = {}


class FlowException(Exception):
    """An exception that propagates across the wire to the counterparty
    session (reference `core/.../flows/FlowException.kt`).  Subclasses are
    auto-registered so the receiving side can rethrow the same type."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        _exception_registry[cls.__name__] = cls


class FlowKilledException(FlowException):
    """Raised into a flow (and its caller's future) when it is forcibly
    terminated via killFlow, so callers can tell a kill from an ordinary
    flow failure (reference `KilledFlowException`)."""


def encode_flow_exception(exc: FlowException) -> str:
    return f"{type(exc).__name__}|{exc}"


def rebuild_flow_exception(text: str) -> FlowException:
    """Best-effort reconstruction of a propagated FlowException."""
    name, _, msg = text.partition("|")
    cls = _exception_registry.get(name)
    if cls is not None:
        try:
            exc = cls(msg)
            # Some subclasses decorate the message in __init__; keep the
            # original wire text when they do.
            return exc
        except Exception:
            pass
    return FlowException(text)


# ---------------------------------------------------------------------------
# IO requests — the explicit suspension points
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Send:
    party: Party
    payload: Any
    # flow_name of the (sub)flow that issued the request; sessions are keyed
    # by (party, owner) so @initiating_flow sub-flows get their own session
    owner_name: str = ""


@dataclass(frozen=True)
class Receive:
    party: Party
    expected_type: type = object
    owner_name: str = ""


@dataclass(frozen=True)
class SendAndReceive:
    party: Party
    payload: Any
    expected_type: type = object
    retry_on_failover: bool = False  # sendAndReceiveWithRetry (FlowLogic.kt:107)
    owner_name: str = ""


@dataclass(frozen=True)
class WaitForLedgerCommit:
    tx_id: Any  # SecureHash


@dataclass(frozen=True)
class AwaitBlocking:
    """Run a potentially LONG-BLOCKING `compute()` off the messaging pump:
    the flow parks, the computation runs on the node's blocking executor,
    and the flow resumes with the (recorded, replay-stable) result. A
    computation given here must be idempotent — a flow restored from a
    checkpoint taken before the result was recorded re-executes it (the
    cluster notary's putall commit is the canonical case). On the
    deterministic in-memory network it runs inline."""

    compute: Callable = None


@dataclass(frozen=True)
class RecordValue:
    """Run `compute()` once and record its (codec-serializable) result in
    the checkpoint IO log.  On replay-restore the recorded value is fed back
    WITHOUT re-running compute — this is how flows capture nondeterministic
    work (vault coin selection, random salts, fresh keys, clock reads) so
    the deterministic-replay contract holds.  Usage:
        stx = yield self.record(lambda: build_spend_tx(...))
    """
    compute: Any  # Callable[[], value]


# ---------------------------------------------------------------------------
# Registries + annotations
# ---------------------------------------------------------------------------

flow_registry: Dict[str, Type["FlowLogic"]] = {}
_initiated_by: Dict[str, Type["FlowLogic"]] = {}


def _register(cls: Type["FlowLogic"]) -> None:
    flow_registry[cls.flow_name()] = cls


def initiating_flow(cls=None, *, version: int = 1):
    """Marks a flow that opens new sessions (reference `@InitiatingFlow`)."""
    def wrap(c):
        c._initiating = True
        c._flow_version = version
        _register(c)
        return c
    return wrap(cls) if cls is not None else wrap


def initiated_by(initiator: Type["FlowLogic"]):
    """Registers the responder spawned when `initiator`'s SessionInit arrives
    (reference `@InitiatedBy`)."""
    def wrap(c):
        c._initiated_by = initiator
        _register(c)
        _initiated_by[initiator.flow_name()] = c
        return c
    return wrap


def startable_by_rpc(cls):
    cls._startable_by_rpc = True
    _register(cls)
    return cls


def schedulable_flow(cls):
    cls._schedulable = True
    _register(cls)
    return cls


def get_initiated_by(initiator_name: str) -> Optional[Type["FlowLogic"]]:
    return _initiated_by.get(initiator_name)


# ---------------------------------------------------------------------------
# ProgressTracker
# ---------------------------------------------------------------------------

class ProgressTracker:
    """Hierarchical step tree streamed to observers (reference
    `core/.../utilities/ProgressTracker.kt`)."""

    @dataclass(frozen=True)
    class Step:
        label: str

    def __init__(self, *steps: "ProgressTracker.Step"):
        self.steps = list(steps)
        self.current_step: Optional[ProgressTracker.Step] = None
        self._observers: List = []
        self._children: Dict[ProgressTracker.Step, ProgressTracker] = {}

    def set_child_tracker(self, step: "ProgressTracker.Step", child: "ProgressTracker"):
        self._children[step] = child
        for obs in self._observers:
            child.subscribe(obs)

    def subscribe(self, observer) -> None:
        self._observers.append(observer)
        for child in self._children.values():
            child.subscribe(observer)

    @property
    def current_step_index(self) -> int:
        if self.current_step is None:
            return -1
        return self.steps.index(self.current_step)

    def set_current_step(self, step: "ProgressTracker.Step") -> None:
        if step not in self.steps:
            raise ValueError(f"unknown step {step}")
        self.current_step = step
        for obs in self._observers:
            obs(step.label)


# ---------------------------------------------------------------------------
# FlowLogic
# ---------------------------------------------------------------------------

class FlowLogic:
    """Base class of a checkpointable protocol.

    Subclasses implement `call()` as a generator (it must `yield` at least
    once or simply `return`; plain-return flows are handled too).  The
    driving state machine injects `state_machine` (node-side services
    accessor) before the first step.
    """

    _initiating = False
    _startable_by_rpc = False
    _schedulable = False
    progress_tracker: Optional[ProgressTracker] = None

    def __init_subclass__(cls, **kwargs):
        # EVERY concrete flow class is registered at definition time, so a
        # restart can restore ANY checkpointed fiber — the reference's
        # contract (StateMachineManager.kt:227-241 restores whatever class
        # the checkpoint names). Before r4 only decorator-annotated flows
        # registered, and a node dying inside e.g. FinalityFlow (not
        # @initiating_flow — its sub-flows open the sessions) could not be
        # restored (r3 VERDICT #3).
        super().__init_subclass__(**kwargs)
        _register(cls)

    # injected by the node's state machine before the first step
    state_machine = None
    # per-run ordinal: 0 for the top-level flow, unique per sub_flow call.
    # Sessions are keyed on (party, flow class, ordinal) so each sub-flow
    # INSTANCE gets its own session, like the reference's openSessions keyed
    # on (Party, sessionFlow instance). Deterministic across replay because
    # sub_flow calls re-execute in the same order.
    _ordinal = 0

    @classmethod
    def flow_name(cls) -> str:
        mod = cls.__module__
        if mod == "__main__":
            # `python -m pkg.mod` imports the module as __main__; normalise
            # to the canonical name so registry lookups (scheduler
            # activities, RPC flow starts) resolve either way.
            import sys as _sys

            spec = getattr(_sys.modules.get("__main__"), "__spec__", None)
            if spec is not None and spec.name:
                mod = spec.name
                if mod.endswith(".__main__"):
                    mod = mod[: -len(".__main__")]
        return f"{mod}.{cls.__qualname__}"

    def session_owner_name(self) -> str:
        return f"{self.flow_name()}#{self._ordinal}"

    # -- suspension-point constructors (user code yields these) -------------

    def send(self, party: Party, payload: Any) -> Send:
        return Send(party, payload, owner_name=self.session_owner_name())

    def receive(self, party: Party, expected_type: type = object) -> Receive:
        return Receive(party, expected_type, owner_name=self.session_owner_name())

    def send_and_receive(
        self, party: Party, payload: Any, expected_type: type = object
    ) -> SendAndReceive:
        return SendAndReceive(
            party, payload, expected_type, owner_name=self.session_owner_name()
        )

    def send_and_receive_with_retry(
        self, party: Party, payload: Any, expected_type: type = object
    ) -> SendAndReceive:
        return SendAndReceive(
            party, payload, expected_type, retry_on_failover=True,
            owner_name=self.session_owner_name(),
        )

    def wait_for_ledger_commit(self, tx_id) -> WaitForLedgerCommit:
        return WaitForLedgerCommit(tx_id)

    def await_blocking(self, compute) -> AwaitBlocking:
        """Park the flow while `compute()` runs off the messaging pump;
        resume with its recorded result (see AwaitBlocking's idempotency
        contract). Usage: `result = yield self.await_blocking(fn)`."""
        return AwaitBlocking(compute)

    def record(self, compute) -> RecordValue:
        """Capture a nondeterministic computation into the checkpoint log;
        see RecordValue."""
        return RecordValue(compute)

    @property
    def flow_id(self) -> str:
        """Stable unique id of this flow run — deterministic across
        checkpoint restores (use it for soft-lock ids etc.)."""
        return self.state_machine.flow_id

    def sub_flow(self, flow: "FlowLogic"):
        """Run a child flow inline, sharing this flow's state machine.

        Usage: `result = yield from self.sub_flow(OtherFlow(...))`.
        If the child has its own ProgressTracker it is attached under the
        parent's current step.
        """
        flow.state_machine = self.state_machine
        flow._ordinal = self.state_machine.next_subflow_ordinal()
        if (
            self.progress_tracker is not None
            and flow.progress_tracker is not None
            and self.progress_tracker.current_step is not None
        ):
            self.progress_tracker.set_child_tracker(
                self.progress_tracker.current_step, flow.progress_tracker
            )
        result = yield from _as_generator(flow)
        return result

    @property
    def service_hub(self):
        """The node's services (reference FlowLogic.serviceHub)."""
        return self.state_machine.service_hub

    @property
    def our_identity(self) -> Party:
        return self.state_machine.our_identity

    def call(self):
        raise NotImplementedError

    def __repr__(self) -> str:
        return type(self).__name__


def _as_generator(flow: FlowLogic):
    """Invoke flow.call(), normalising plain-return flows to generators."""
    import inspect

    result = flow.call()
    if inspect.isgenerator(result):
        return result

    def _wrap():
        return result
        yield  # pragma: no cover — makes this a generator

    return _wrap()

"""State-replacement flows: notary change + contract upgrade.

Reference parity: `core/src/main/kotlin/net/corda/core/flows/
AbstractStateReplacementFlow.kt` (Instigator/Acceptor with a signed
Proposal handshake and signature swap), `NotaryChangeFlow.kt` (builds a
NotaryChangeWireTransaction) and `ContractUpgradeFlow.kt` (1-input
1-output 1-UpgradeCommand transaction, output == upgrade(input)).

Shape kept from the reference: the Instigator assembles the replacement
transaction, sends a Proposal to every other participant, collects their
signatures, notarises, sends the full signature set back (so acceptors can
record), records locally and returns the replacement StateAndRef.  The
Acceptor verifies the proposal (subclass hook), signs, and records the
final transaction.  States are replaced one-to-one; no splitting/merging.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Optional, Tuple

from ..contracts.structures import (
    Command,
    CommandData,
    ContractState,
    StateAndRef,
    StateRef,
)
from ..crypto.signing import DigitalSignatureWithKey
from ..identity import Party
from ..serialization.codec import register_adapter
from ..transactions.builder import TransactionBuilder
from ..transactions.notary_change import NotaryChangeWireTransaction
from ..transactions.signed import SignedTransaction
from .api import (
    FlowException,
    FlowLogic,
    initiated_by,
    initiating_flow,
    startable_by_rpc,
)
from .library import NotaryClientFlowRef


class StateReplacementException(FlowException):
    pass


@dataclass(frozen=True)
class Proposal:
    """The proposed modification sent to each participant (reference
    AbstractStateReplacementFlow.Proposal)."""

    state_ref: StateRef
    modification: object   # Party (notary change) | str (upgraded contract)
    stx: SignedTransaction


register_adapter(
    Proposal, "StateReplacementProposal",
    lambda p: {"ref": p.state_ref, "mod": p.modification, "stx": p.stx},
    lambda d: Proposal(d["ref"], d["mod"], d["stx"]),
)


@dataclass(frozen=True)
class SignaturesPayload:
    """Full signature set swapped back to acceptors."""

    signatures: Tuple[DigitalSignatureWithKey, ...]


register_adapter(
    SignaturesPayload, "StateReplacementSignatures",
    lambda p: {"sigs": list(p.signatures)},
    lambda d: SignaturesPayload(tuple(d["sigs"])),
)


def _record_replacement(services, stx: SignedTransaction) -> None:
    """Record a finalised replacement transaction (both tx kinds)."""
    services.record_transactions([stx])


class AbstractStateReplacementInstigator(FlowLogic):
    """Instigator half (reference AbstractStateReplacementFlow.Instigator).

    Subclasses implement `assemble_tx() -> (stx, participant_keys)`."""

    def __init__(self, original_state: StateAndRef, modification):
        self.original_state = original_state
        self.modification = modification

    def assemble_tx(self):
        raise NotImplementedError

    def call(self):
        stx, participant_keys = yield self.record(self.assemble_tx)
        hub = self.service_hub
        my_keys = hub.key_management_service.keys
        others: List[Party] = []
        for key in participant_keys:
            if key.encoded in my_keys:
                continue
            party = hub.identity_service.party_from_key(key)
            if party is None:
                raise StateReplacementException(
                    f"participant {key} not found on the network"
                )
            others.append(party)

        participant_sigs = []
        proposal = Proposal(self.original_state.ref, self.modification, stx)
        for party in others:
            sig = yield self.send_and_receive(
                party, proposal, DigitalSignatureWithKey
            )
            if not party.owning_key.is_fulfilled_by({sig.by}):
                raise StateReplacementException(
                    "not signed by the required participant"
                )
            if not sig.is_valid(stx.id.bytes):
                raise StateReplacementException("invalid participant signature")
            participant_sigs.append(sig)
            stx = stx.with_additional_signature(sig)

        try:
            notary_sigs = yield from self._notarise(stx)
        except Exception as exc:
            raise StateReplacementException(
                f"unable to notarise state change: {exc}"
            )
        final = stx.with_additional_signatures(notary_sigs)
        for party in others:
            yield self.send(
                party, SignaturesPayload(tuple(participant_sigs) + tuple(notary_sigs))
            )
        _record_replacement(hub, final)
        return self._replacement_output(final)

    def _notarise(self, stx: SignedTransaction):
        """Notarisation hook: subclasses with multi-notary protocols (the
        cross-domain notary change) override this; the default is a plain
        single-notary commit."""
        notary_sigs = yield from self.sub_flow(NotaryClientFlowRef(stx))
        return notary_sigs

    def _replacement_output(self, final: SignedTransaction) -> StateAndRef:
        wtx = final.tx
        if isinstance(wtx, NotaryChangeWireTransaction):
            outputs = wtx.resolve_outputs(self.service_hub.load_state)
            return StateAndRef(outputs[0], StateRef(final.id, 0))
        return wtx.out_ref(0)


class AbstractStateReplacementAcceptor(FlowLogic):
    """Acceptor half (reference AbstractStateReplacementFlow.Acceptor).

    Subclasses implement `verify_proposal(proposal)` — raise
    StateReplacementException to refuse."""

    def __init__(self, counterparty: Party):
        self.counterparty = counterparty

    def verify_proposal(self, proposal: Proposal) -> None:
        raise NotImplementedError

    def call(self):
        proposal = yield self.receive(self.counterparty, Proposal)
        self.verify_proposal(proposal)
        stx = proposal.stx
        stx.check_signatures_are_valid()
        hub = self.service_hub
        wtx = stx.tx
        if isinstance(wtx, NotaryChangeWireTransaction):
            for ref in wtx.inputs:
                ts = hub.load_state(ref)
                if ts.notary.owning_key.encoded != wtx.notary.owning_key.encoded:
                    raise StateReplacementException(
                        f"input {ref} is governed by {ts.notary.name}, "
                        f"not {wtx.notary.name}"
                    )
            required = wtx.resolved_required_keys(hub.load_state)
            # (pre-signing view: our signature is what's being requested)
        else:
            required = wtx.required_signing_keys
        my_keys = hub.key_management_service.keys
        to_sign = [k for k in required if k.encoded in my_keys]
        if not to_sign:
            raise StateReplacementException(
                "proposal does not require our signature"
            )
        sig = hub.key_management_service.sign(stx.id.bytes, to_sign[0])
        payload = yield self.send_and_receive(
            self.counterparty, sig, SignaturesPayload
        )
        final = stx.with_additional_signatures(payload.signatures)
        if isinstance(wtx, NotaryChangeWireTransaction):
            final.check_signatures_are_valid()
            try:
                wtx.check_inputs_and_signatures(final.sigs, hub.load_state)
            except ValueError as exc:
                raise StateReplacementException(str(exc))
        else:
            final.verify_required_signatures()
        _record_replacement(hub, final)
        return None


# ---------------------------------------------------------------------------
# Notary change (reference NotaryChangeFlow.kt)
# ---------------------------------------------------------------------------

@startable_by_rpc
@initiating_flow
class NotaryChangeFlow(AbstractStateReplacementInstigator):
    """Migrate a state (and its encumbrance chain) to a new notary.

    Cross-notary moves run a journaled two-phase commit (`_notarise`):
    the OLD notary durably consumes the inputs, then the NEW notary
    durably assumes them, with the in-flight decision journaled in the
    instigator's database so a crash at any point re-drives forward to
    exactly one owning notary (see node/notary_change.py)."""

    def _notarise(self, stx: SignedTransaction):
        from ...node.notary_change import change_journal, fire_crash_point

        wtx = stx.tx
        cross_notary = (
            isinstance(wtx, NotaryChangeWireTransaction)
            and wtx.new_notary.owning_key.encoded
            != wtx.notary.owning_key.encoded
        )
        if not cross_notary:
            # Same-notary re-pin (or non-notary-change subclass use):
            # single commit, no journal — byte-identical to the old path.
            notary_sigs = yield from self.sub_flow(NotaryClientFlowRef(stx))
            return notary_sigs

        journal = change_journal(self.service_hub)
        tx_hex = stx.id.bytes.hex()
        fire_crash_point(
            "notary_change.before_prepare", tx_id=tx_hex,
            old=wtx.notary.name, new=wtx.new_notary.name,
        )
        # Durable intent: recovery can always learn what was in flight.
        journal.put(tx_hex, {
            "phase": "prepare", "stx": stx,
            "old": wtx.notary.name, "new": wtx.new_notary.name,
        })
        fire_crash_point("notary_change.after_prepare", tx_id=tx_hex)

        # CONSUME: the old notary (which governs the inputs) commits.
        old_sigs = yield from self.sub_flow(NotaryClientFlowRef(stx))
        signed = stx.with_additional_signatures(old_sigs)
        # Durable decision flip: the consume is irreversible, so from
        # here recovery must drive the assume — never roll back.
        journal.put(tx_hex, {
            "phase": "assume", "stx": signed,
            "old": wtx.notary.name, "new": wtx.new_notary.name,
        })
        fire_crash_point(
            "notary_change.between_consume_and_assume", tx_id=tx_hex
        )

        # ASSUME: the new notary records the migrated refs in its own
        # log (gated server-side on the old notary's commit signature).
        new_sigs = yield from self.sub_flow(
            NotaryClientFlowRef(signed, notary=wtx.new_notary)
        )
        fire_crash_point("notary_change.after_commit", tx_id=tx_hex)
        journal.remove(tx_hex)
        return tuple(old_sigs) + tuple(new_sigs)

    def assemble_tx(self):
        hub = self.service_hub
        states = [self.original_state]
        # Resolve the encumbrance chain: all-or-nothing migration
        # (reference NotaryChangeFlow.resolveEncumbrances). Cyclic
        # encumbrances pass ledger validation, so terminate on revisit.
        seen = {self.original_state.ref}
        while states[-1].state.encumbrance is not None:
            ref = StateRef(states[-1].ref.txhash, states[-1].state.encumbrance)
            if ref in seen:
                break
            seen.add(ref)
            states.append(StateAndRef(hub.load_state(ref), ref))
        wtx = NotaryChangeWireTransaction(
            tuple(s.ref for s in states),
            self.original_state.state.notary,
            self.modification,
        )
        participant_keys = set()
        for s in states:
            for p in s.state.data.participants:
                key = getattr(p, "owning_key", None)
                if key is not None:
                    participant_keys.add(key)
        my_keys = hub.key_management_service.keys
        mine = [k for k in participant_keys if k.encoded in my_keys]
        if not mine:
            raise StateReplacementException("we are not a participant")
        sig = hub.key_management_service.sign(wtx.id.bytes, mine[0])
        return SignedTransaction.of(wtx, (sig,)), participant_keys


@initiated_by(NotaryChangeFlow)
class NotaryChangeAcceptor(AbstractStateReplacementAcceptor):
    """Default acceptor: checks the proposal is a well-formed notary change
    for a state we hold (reference NotaryChangeHandler via
    installCoreFlows)."""

    def verify_proposal(self, proposal: Proposal) -> None:
        wtx = proposal.stx.tx
        if not isinstance(wtx, NotaryChangeWireTransaction):
            raise StateReplacementException(
                "notary-change proposal with wrong transaction type"
            )
        if not isinstance(proposal.modification, Party):
            raise StateReplacementException("modification must be a Party")
        if wtx.new_notary != proposal.modification:
            raise StateReplacementException(
                "transaction new notary differs from proposed modification"
            )
        if proposal.state_ref not in wtx.inputs:
            raise StateReplacementException(
                "proposed state is not an input of the transaction"
            )
        # The new notary must be an advertised notary we know of.
        cache = self.service_hub.network_map_cache
        notaries = cache.notary_identities
        if proposal.modification not in notaries:
            raise StateReplacementException(
                f"{proposal.modification.name} is not a known notary"
            )


# ---------------------------------------------------------------------------
# Contract upgrade (reference ContractUpgradeFlow.kt + UpgradedContract)
# ---------------------------------------------------------------------------

class UpgradedContract:
    """Interface for a contract that upgrades states of a legacy contract
    (reference Structures.kt:359-374). Register the implementing class
    with @contract(name=...) as usual."""

    legacy_contract_name: str = ""

    def upgrade(self, state: ContractState) -> ContractState:
        raise NotImplementedError


@dataclass(frozen=True)
class UpgradeCommand(CommandData):
    """Authorises a contract upgrade (reference Structures.kt:317)."""

    upgraded_contract_name: str


register_adapter(
    UpgradeCommand, "UpgradeCommand",
    lambda c: {"name": c.upgraded_contract_name},
    lambda d: UpgradeCommand(d["name"]),
)


def verify_upgrade(input_state: ContractState, output_state: ContractState,
                   upgraded_contract: UpgradedContract,
                   command_signers: Iterable) -> None:
    """The upgrade rules every party re-checks (reference
    ContractUpgradeFlow.verify): participants all sign, input is of the
    legacy contract, output equals upgrade(input)."""
    signer_set = set(k.encoded for k in command_signers)
    for p in input_state.participants:
        key = getattr(p, "owning_key", None)
        if key is not None and key.encoded not in signer_set:
            raise StateReplacementException(
                "the signing keys must include all participant keys"
            )
    if input_state.contract_name != upgraded_contract.legacy_contract_name:
        raise StateReplacementException(
            "input state does not reference the legacy contract"
        )
    if output_state != upgraded_contract.upgrade(input_state):
        raise StateReplacementException(
            "output state must be an upgraded version of the input state"
        )


@initiating_flow
class ContractUpgradeFlow(AbstractStateReplacementInstigator):
    """Upgrade a state to a new contract. `modification` is the upgraded
    contract's registered name; the class must be an UpgradedContract."""

    def assemble_tx(self):
        from ..contracts.structures import _CONTRACT_REGISTRY

        hub = self.service_hub
        cls = _CONTRACT_REGISTRY.get(self.modification)
        upgraded = cls() if cls is not None else None
        if upgraded is None or not isinstance(upgraded, UpgradedContract):
            raise StateReplacementException(
                f"{self.modification} is not a registered UpgradedContract"
            )
        old = self.original_state
        participant_keys = {
            p.owning_key
            for p in old.state.data.participants
            if getattr(p, "owning_key", None) is not None
        }
        builder = TransactionBuilder(notary=old.state.notary)
        builder.add_input_state(old)
        builder.add_output_state(upgraded.upgrade(old.state.data))
        builder.add_command(UpgradeCommand(self.modification), *participant_keys)
        stx = hub.sign_initial_transaction(builder)
        return stx, participant_keys


@initiated_by(ContractUpgradeFlow)
class ContractUpgradeAcceptor(AbstractStateReplacementAcceptor):
    def verify_proposal(self, proposal: Proposal) -> None:
        from ..contracts.structures import _CONTRACT_REGISTRY

        if not isinstance(proposal.modification, str):
            raise StateReplacementException("modification must be a contract name")
        # explicit per-state authorisation (reference ContractUpgradeService
        # + CordaRPCOps.authoriseContractUpgrade): being a registered
        # upgrade is NOT consent — this node must have opted the state in
        upgrade_svc = getattr(
            self.service_hub, "contract_upgrade_service", None
        )
        authorised = (
            upgrade_svc.authorised_upgrade(proposal.state_ref)
            if upgrade_svc is not None else None
        )
        if authorised != proposal.modification:
            raise StateReplacementException(
                f"upgrade of {proposal.state_ref} to "
                f"{proposal.modification} is not authorised on this node"
            )
        cls = _CONTRACT_REGISTRY.get(proposal.modification)
        upgraded = cls() if cls is not None else None
        if upgraded is None or not isinstance(upgraded, UpgradedContract):
            raise StateReplacementException(
                f"{proposal.modification} is not a registered UpgradedContract"
            )
        wtx = proposal.stx.tx
        if len(wtx.inputs) != 1 or len(wtx.outputs) != 1:
            raise StateReplacementException(
                "upgrade transaction must have exactly one input and output"
            )
        if wtx.inputs[0] != proposal.state_ref:
            raise StateReplacementException(
                "proposed state is not the transaction input"
            )
        input_state = self.service_hub.load_state(proposal.state_ref)
        upgrade_cmds = [
            c for c in wtx.commands if isinstance(c.value, UpgradeCommand)
        ]
        if len(upgrade_cmds) != 1:
            raise StateReplacementException(
                "upgrade transaction must have exactly one UpgradeCommand"
            )
        verify_upgrade(
            input_state.data,
            wtx.outputs[0].data,
            upgraded,
            upgrade_cmds[0].signers,
        )

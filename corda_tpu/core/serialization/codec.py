"""Canonical tagged binary codec with a whitelisted type registry.

Design requirements (why not msgpack/pickle):
  * DETERMINISTIC: map keys and object fields are emitted in sorted order,
    integers have a single encoding, no implementation-defined float quirks.
    Transaction ids are Merkle roots over these bytes (reference parity:
    `WireTransaction.kt:39,104`), so byte-stability is a consensus property.
  * WHITELISTED: only registered types deserialize (reference parity:
    `CordaClassResolver.kt` whitelist enforcement; `Kryo.kt:45-74` documents
    why open deserialization is an RCE hole).
  * SELF-DESCRIBING: objects carry their type name, so external processes (the
    verifier sidecar, RPC clients) can decode without a schema side-channel.

Wire grammar (all varints are unsigned LEB128; ints are zigzag-LEB128):
  value := NULL | TRUE | FALSE
         | INT <zigzag varint>
         | BYTES <len> <raw>
         | STR <len> <utf8>
         | LIST <count> value*
         | MAP <count> (value value)*     # keys sorted by encoded bytes
         | OBJ <typename: len utf8> <field count> (fieldname value)*  # sorted
         | F64 <8 bytes big-endian IEEE754>  # NaN/-0.0 rejected
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Any, Callable, Dict, Tuple, Type

_NULL, _TRUE, _FALSE, _INT, _BYTES, _STR, _LIST, _MAP, _OBJ, _F64 = range(10)

_MAGIC = b"CT\x01"  # corda_tpu serialization, format version 1

# Maximum container nesting; bounds stack depth against hostile wire data.
_MAX_DEPTH = 100


class SerializationError(Exception):
    pass


# --- type registry ----------------------------------------------------------

# type -> (type_name, to_dict, from_dict)
_BY_TYPE: Dict[Type, Tuple[str, Callable[[Any], dict], Callable[[dict], Any]]] = {}
_BY_NAME: Dict[str, Tuple[Type, Callable[[Any], dict], Callable[[dict], Any]]] = {}

# Encode fast-path caches (profiled ~20% of system time in codec encode):
#   _MRO_CACHE   subclass -> registry entry, so only the FIRST encode of a
#                subclass pays the MRO walk;
#   _ENC_CACHE   cls -> _PreboundEncoder with the OBJ header bytes and the
#                sorted field plan precomputed, so the hot wire shapes
#                (SessionData, SignedTransaction, broker payloads) skip
#                per-object name encoding, field sorting and — for
#                @corda_serializable dataclasses — the to_dict dict build.
_MRO_CACHE: Dict[Type, Any] = {}
_ENC_CACHE: Dict[Type, "_PreboundEncoder"] = {}

# approximate seam counters (GIL-atomic int adds; read by encode_stats)
_STATS = {"obj_fast": 0, "obj_generic": 0}


def encode_stats() -> Dict[str, int]:
    """Encode-path seam telemetry: objects encoded via the pre-bound
    fast path vs the generic adapter path (bench attribution)."""
    return dict(_STATS)


def register_adapter(
    cls: Type,
    type_name: str,
    to_dict: Callable[[Any], dict],
    from_dict: Callable[[dict], Any],
) -> None:
    """Register a custom (non-dataclass) type with explicit converters."""
    if type_name in _BY_NAME and _BY_NAME[type_name][0] is not cls:
        raise SerializationError(f"type name {type_name!r} already registered")
    _BY_TYPE[cls] = (type_name, to_dict, from_dict)
    _BY_NAME[type_name] = (cls, to_dict, from_dict)
    # a new registration can change how an already-cached subclass (or a
    # not-yet-registered type cached as a miss) must serialize
    _MRO_CACHE.clear()
    _ENC_CACHE.clear()


def corda_serializable(cls=None, *, name: str | None = None):
    """Class decorator whitelisting a dataclass for serialization.

    Parity: reference `@CordaSerializable` annotation. Fields are taken from
    the dataclass definition; the wire type name defaults to the qualified
    class name (module-independent simple path keeps refactors cheap).
    """

    def wrap(c):
        if not dataclasses.is_dataclass(c):
            raise SerializationError(f"{c} must be a dataclass to be @corda_serializable")
        type_name = name or c.__qualname__
        field_names = [f.name for f in dataclasses.fields(c)]

        def to_dict(obj):
            return {fn: getattr(obj, fn) for fn in field_names}

        def from_dict(d):
            return c(**d)

        # wire fields == attribute names, so the schema-evolution layer may
        # apply field-level add/drop rules (evolution.py)
        from_dict.__evolvable__ = True
        # fixed field set -> the encode fast-path may read attributes
        # directly in sorted order, skipping the to_dict dict build
        to_dict.__fields__ = tuple(field_names)
        register_adapter(c, type_name, to_dict, from_dict)
        return c

    return wrap(cls) if cls is not None else wrap


# --- varint helpers ---------------------------------------------------------

def _write_uvarint(out: bytearray, v: int) -> None:
    if v < 0:
        raise SerializationError("uvarint cannot encode negatives")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise SerializationError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 640:
            raise SerializationError("varint too long")


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> (v.bit_length() + 1)) if v < 0 else v << 1


def _unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


# --- encode -----------------------------------------------------------------

def _encode(out: bytearray, value: Any, depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        raise SerializationError(f"nesting deeper than {_MAX_DEPTH}")
    if value is None:
        out.append(_NULL)
    elif value is True:
        out.append(_TRUE)
    elif value is False:
        out.append(_FALSE)
    elif isinstance(value, int):
        out.append(_INT)
        _write_uvarint(out, _zigzag(value))
    elif isinstance(value, (bytes, bytearray, memoryview)):
        out.append(_BYTES)
        raw = bytes(value)
        _write_uvarint(out, len(raw))
        out.extend(raw)
    elif isinstance(value, str):
        out.append(_STR)
        raw = value.encode("utf-8")
        _write_uvarint(out, len(raw))
        out.extend(raw)
    elif isinstance(value, float):
        if value != value or (value == 0.0 and str(value)[0] == "-"):
            raise SerializationError("NaN and -0.0 are not canonical")
        out.append(_F64)
        out.extend(struct.pack(">d", value))
    elif isinstance(value, (list, tuple)):
        out.append(_LIST)
        _write_uvarint(out, len(value))
        for item in value:
            _encode(out, item, depth + 1)
    elif isinstance(value, (dict,)):
        out.append(_MAP)
        _write_uvarint(out, len(value))
        encoded_pairs = []
        for k, v in value.items():
            kb = bytearray()
            _encode(kb, k, depth + 1)
            vb = bytearray()
            _encode(vb, v, depth + 1)
            encoded_pairs.append((bytes(kb), bytes(vb)))
        for kb, vb in sorted(encoded_pairs):
            out.extend(kb)
            out.extend(vb)
    elif isinstance(value, (set, frozenset)):
        # canonical set = sorted LIST (decodes as list; registered wrappers
        # that need set semantics convert in from_dict)
        items = []
        for item in value:
            ib = bytearray()
            _encode(ib, item, depth + 1)
            items.append(bytes(ib))
        out.append(_LIST)
        _write_uvarint(out, len(items))
        for ib in sorted(items):
            out.extend(ib)
    else:
        enc = _ENC_CACHE.get(type(value))
        if enc is None:
            enc = _prebind_encoder(type(value))
        enc.encode(out, value, depth)


class _PreboundEncoder:
    """Per-type encode plan: the OBJ header (tag + name + field count) is
    emitted as one precomputed bytes blob, and field names ride as
    precomputed (sorted) prefix bytes. Byte output is identical to the
    generic path — pinned by the differential test in
    tests/test_serialization.py."""

    __slots__ = ("header", "to_dict", "plan", "plan_count", "attr_plan")

    def __init__(self, type_name: str, to_dict):
        name_raw = type_name.encode("utf-8")
        header = bytearray([_OBJ])
        _write_uvarint(header, len(name_raw))
        header.extend(name_raw)
        self.to_dict = to_dict
        fields = getattr(to_dict, "__fields__", None)
        if fields is not None:
            # dataclass: fixed field set known up front — read attributes
            # directly, no dict build
            _write_uvarint(header, len(fields))
            self.attr_plan = tuple(
                (self._fn_prefix(fn), fn) for fn in sorted(fields)
            )
            self.plan = None
        else:
            # adapter: to_dict decides the field set per object; cache the
            # sorted name prefixes for the FIRST seen key set and fast-path
            # objects that match it (adapters in practice emit a fixed set)
            self.attr_plan = None
            self.plan = None
            self.plan_count = b""
        self.header = bytes(header)

    @staticmethod
    def _fn_prefix(fn: str) -> bytes:
        raw = fn.encode("utf-8")
        prefix = bytearray()
        _write_uvarint(prefix, len(raw))
        prefix.extend(raw)
        return bytes(prefix)

    def encode(self, out: bytearray, value: Any, depth: int) -> None:
        if self.attr_plan is not None:
            _STATS["obj_fast"] += 1
            out.extend(self.header)
            for prefix, fn in self.attr_plan:
                out.extend(prefix)
                _encode(out, getattr(value, fn), depth + 1)
            return
        fields = self.to_dict(value)
        plan = self.plan
        if plan is not None and len(fields) == len(plan):
            try:
                tail = [(prefix, fields[fn]) for prefix, fn in plan]
            except KeyError:
                tail = None
            if tail is not None:
                _STATS["obj_fast"] += 1
                out.extend(self.header)
                out.extend(self.plan_count)
                for prefix, fv in tail:
                    out.extend(prefix)
                    _encode(out, fv, depth + 1)
                return
        _STATS["obj_generic"] += 1
        out.extend(self.header)
        count = bytearray()
        _write_uvarint(count, len(fields))
        out.extend(count)
        names = sorted(fields)
        for fn in names:
            out.extend(self._fn_prefix(fn))
            _encode(out, fields[fn], depth + 1)
        if plan is None:
            # plan_count FIRST: plan is the publication flag a concurrent
            # encoder checks, and it must never observe plan set while
            # plan_count still holds the placeholder
            self.plan_count = bytes(count)
            self.plan = tuple((self._fn_prefix(fn), fn) for fn in names)


def _prebind_encoder(cls: Type) -> _PreboundEncoder:
    entry = _lookup_type(cls)
    if entry is None:
        raise SerializationError(
            f"type {cls.__qualname__} is not @corda_serializable/registered"
        )
    enc = _PreboundEncoder(entry[0], entry[1])
    _ENC_CACHE[cls] = enc
    return enc


def _lookup_type(cls: Type):
    entry = _BY_TYPE.get(cls)
    if entry is not None:
        return entry
    if cls in _MRO_CACHE:
        return _MRO_CACHE[cls]
    # walk the MRO so subclasses of registered types serialize as the base;
    # memoised — only the first encode of a subclass pays the walk
    entry = None
    for base in cls.__mro__[1:]:
        entry = _BY_TYPE.get(base)
        if entry is not None:
            break
    _MRO_CACHE[cls] = entry
    return entry


# --- decode -----------------------------------------------------------------

def _decode(
    data: bytes, pos: int, depth: int = 0, obj_hook=None
) -> Tuple[Any, int]:
    """obj_hook(type_name, fields) -> object, when given, replaces the strict
    whitelist construction of OBJ values — the seam the schema-evolution
    layer (evolution.py) plugs into. The default (None) path is the
    consensus-critical strict behavior and must stay byte-for-byte stable."""
    if depth > _MAX_DEPTH:
        raise SerializationError(f"nesting deeper than {_MAX_DEPTH}")
    if pos >= len(data):
        raise SerializationError("truncated value")
    tag = data[pos]
    pos += 1
    if tag == _NULL:
        return None, pos
    if tag == _TRUE:
        return True, pos
    if tag == _FALSE:
        return False, pos
    if tag == _INT:
        v, pos = _read_uvarint(data, pos)
        return _unzigzag(v), pos
    if tag == _BYTES:
        ln, pos = _read_uvarint(data, pos)
        if pos + ln > len(data):
            raise SerializationError("truncated bytes")
        return data[pos : pos + ln], pos + ln
    if tag == _STR:
        ln, pos = _read_uvarint(data, pos)
        if pos + ln > len(data):
            raise SerializationError("truncated string")
        return data[pos : pos + ln].decode("utf-8"), pos + ln
    if tag == _F64:
        if pos + 8 > len(data):
            raise SerializationError("truncated float")
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    if tag == _LIST:
        n, pos = _read_uvarint(data, pos)
        out = []
        for _ in range(n):
            item, pos = _decode(data, pos, depth + 1, obj_hook)
            out.append(item)
        return out, pos
    if tag == _MAP:
        n, pos = _read_uvarint(data, pos)
        d = {}
        for _ in range(n):
            k, pos = _decode(data, pos, depth + 1, obj_hook)
            v, pos = _decode(data, pos, depth + 1, obj_hook)
            if isinstance(k, list):
                k = tuple(k)
            d[k] = v
        return d, pos
    if tag == _OBJ:
        ln, pos = _read_uvarint(data, pos)
        if pos + ln > len(data):
            raise SerializationError("truncated type name")
        type_name = data[pos : pos + ln].decode("utf-8")
        pos += ln
        # structural errors surface BEFORE the whitelist check, matching
        # the native decoder (both its single-shot and batch-scan paths
        # fully parse the frame, then construct): a truncated
        # unknown-type frame must classify identically on every path —
        # pinned by the tests/corpus/decode replay
        n, pos = _read_uvarint(data, pos)
        fields = {}
        for _ in range(n):
            fl, pos = _read_uvarint(data, pos)
            if pos + fl > len(data):
                raise SerializationError("truncated field name")
            fn = data[pos : pos + fl].decode("utf-8")
            pos += fl
            fields[fn], pos = _decode(data, pos, depth + 1, obj_hook)
        if obj_hook is not None:
            return obj_hook(type_name, fields), pos
        entry = _BY_NAME.get(type_name)
        if entry is None:
            raise SerializationError(
                f"type {type_name!r} not in deserialization whitelist"
            )
        try:
            return entry[2](fields), pos
        except TypeError as e:
            raise SerializationError(f"cannot construct {type_name}: {e}") from e
    raise SerializationError(f"unknown tag {tag}")


# --- native acceleration ----------------------------------------------------
#
# The C extension (native/src/codec_ext.c) implements the same grammar
# byte-for-byte; primitives and containers stay in C, registered types
# cross this boundary once each way. Consensus-critical parity is pinned
# by the differential fuzz in tests/test_serialization.py. Set
# CORDA_TPU_NATIVE_CODEC=0 to force the pure-Python paths.

_native_codec = None
if __import__("os").environ.get("CORDA_TPU_NATIVE_CODEC", "1") != "0":
    try:
        from ... import native as _native_pkg

        _native_codec = _native_pkg.codec_extension()
        if _native_codec is not None:
            _native_codec.set_error(SerializationError)
    except Exception:
        _native_codec = None


def _native_lookup(value):
    """encode-side callback: value -> (type_name, fields dict) | None."""
    entry = _lookup_type(type(value))
    if entry is None:
        return None
    return entry[0], entry[1](value)


def _native_construct(type_name: str, fields: dict):
    """decode-side callback: strict whitelist construction (the obj_hook
    seam stays on the Python decoder — evolution passes obj_hook)."""
    entry = _BY_NAME.get(type_name)
    if entry is None:
        raise SerializationError(
            f"type {type_name!r} not in deserialization whitelist"
        )
    try:
        return entry[2](fields)
    except TypeError as e:
        raise SerializationError(f"cannot construct {type_name}: {e}") from e


# --- public api -------------------------------------------------------------

def serialize(value: Any) -> bytes:
    if _native_codec is not None:
        return _native_codec.encode(value, _native_lookup, _MAGIC)
    out = bytearray(_MAGIC)
    _encode(out, value)
    return bytes(out)


def _arena_unwrap(data):
    """CORDA_TPU_ARENA_CHECK seam: an armed-mode ArenaView payload
    (messaging/arenacheck.py) validates its drain-cycle lifetime and
    hands over the real memoryview; everything else passes through
    (one getattr miss on the normal plane)."""
    u = getattr(data, "_arena_unwrap", None)
    return u() if u is not None else data


def deserialize(data: bytes) -> Any:
    if _native_codec is not None:
        # y*-buffer entry point: memoryview payloads (the broker's
        # zero-copy framing plane) decode without an intermediate copy
        return _native_codec.decode(
            _arena_unwrap(data), _native_construct, _MAGIC
        )
    if not isinstance(data, bytes):
        # the pure-Python decoder slices with .decode(): snapshot
        # buffer-protocol inputs once here instead (bytes() also
        # validates an armed-mode ArenaView)
        data = bytes(data)
    if data[: len(_MAGIC)] != _MAGIC:
        raise SerializationError("bad magic / unsupported format version")
    value, pos = _decode(data, len(_MAGIC))
    if pos != len(data):
        raise SerializationError(f"{len(data) - pos} trailing bytes")
    return value


# batch-path seam counters (GIL-atomic int adds, like _STATS): the
# differential/parity tests assert the native batch entry points are
# actually taken — and that one drain makes O(1) native calls
_BATCH_STATS = {"encode_many_native": 0, "decode_many_native": 0,
                "encode_many_fallback": 0, "decode_many_fallback": 0}


def batch_stats() -> Dict[str, int]:
    return dict(_BATCH_STATS)


def serialize_many(values) -> list:
    """Encode a batch of values in ONE native call: a brief GIL-held
    reflection pass flattens the objects into a write plan, then the
    byte-level framing runs with the GIL RELEASED into a single arena
    (native/src/codec_ext.c encode_many). Returns bytes-like frames —
    memoryview slices over the shared arena on the native path (the
    arena stays alive through the views), real bytes on the fallback.
    Byte-identical to [serialize(v) for v in values] on both paths."""
    values = list(values)
    if _native_codec is not None and hasattr(_native_codec, "encode_many"):
        _BATCH_STATS["encode_many_native"] += 1
        arena, offsets = _native_codec.encode_many(
            values, _native_lookup, _MAGIC
        )
        mv = memoryview(arena)
        return [mv[offsets[i]:offsets[i + 1]] for i in range(len(values))]
    _BATCH_STATS["encode_many_fallback"] += 1
    return [serialize(v) for v in values]


def deserialize_many(frames) -> list:
    """Decode a batch of frames in ONE native call: the structural scan
    (varints, bounds, tags) runs with the GIL released over every frame,
    then objects materialize in a single GIL-held pass. Error taxonomy
    is identical to a sequential [deserialize(f) for f in frames] — the
    first malformed frame raises SerializationError either way."""
    frames = [_arena_unwrap(f) for f in frames]
    if _native_codec is not None and hasattr(_native_codec, "decode_many"):
        _BATCH_STATS["decode_many_native"] += 1
        return _native_codec.decode_many(frames, _native_construct, _MAGIC)
    _BATCH_STATS["decode_many_fallback"] += 1
    return [deserialize(f) for f in frames]


# --- built-in adapters for core crypto types --------------------------------

def _register_core_types() -> None:
    from ..crypto.composite import CompositeKey, decode_composite_key
    from ..crypto.keys import SchemePrivateKey, SchemePublicKey
    from ..crypto.secure_hash import SecureHash
    from ..crypto.signing import (
        DigitalSignature,
        DigitalSignatureWithKey,
        MetaData,
        SignatureType,
        TransactionSignature,
    )

    register_adapter(
        SecureHash, "SecureHash",
        lambda h: {"bytes": h.bytes},
        lambda d: SecureHash(d["bytes"]),
    )
    register_adapter(
        SchemePublicKey, "PublicKey",
        lambda k: {"scheme": k.scheme_code_name, "encoded": k.encoded},
        lambda d: SchemePublicKey(d["scheme"], d["encoded"]),
    )
    register_adapter(
        CompositeKey, "CompositeKey",
        lambda k: {"encoded": k.encoded},
        lambda d: decode_composite_key(d["encoded"]),
    )
    register_adapter(
        SchemePrivateKey, "PrivateKey",  # checkpoint-context only in practice
        lambda k: {"scheme": k.scheme_code_name, "encoded": k.encoded},
        lambda d: SchemePrivateKey(d["scheme"], d["encoded"]),
    )
    register_adapter(
        SignatureType, "SignatureType",
        lambda s: {"v": int(s)},
        lambda d: SignatureType(d["v"]),
    )
    register_adapter(
        DigitalSignatureWithKey, "DigitalSignature.WithKey",
        lambda s: {"bytes": s.bytes, "by": s.by},
        lambda d: DigitalSignatureWithKey(d["bytes"], d["by"]),
    )
    register_adapter(
        MetaData, "MetaData",
        lambda m: {
            "scheme": m.scheme_code_name, "version": m.version_id,
            "sig_type": m.signature_type, "ts": m.timestamp,
            "visible": m.visible_inputs, "signed": m.signed_inputs,
            "root": m.merkle_root, "key": m.public_key,
        },
        lambda d: MetaData(
            d["scheme"], d["version"], d["sig_type"], d["ts"],
            d["visible"], d["signed"], d["root"], d["key"],
        ),
    )
    register_adapter(
        TransactionSignature, "TransactionSignature",
        lambda s: {"bytes": s.bytes, "meta": s.meta_data},
        lambda d: TransactionSignature(d["bytes"], d["meta"]),
    )
    register_adapter(
        DigitalSignature, "DigitalSignature",
        lambda s: {"bytes": s.bytes},
        lambda d: DigitalSignature(d["bytes"]),
    )


_register_core_types()

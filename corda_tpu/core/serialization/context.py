"""Serialization use-case contexts.

Parity: reference `node-api/.../serialization/SerializationScheme.kt:21-220`
distinguishes P2P / RPCServer / RPCClient / Storage / Checkpoint contexts.
Here a context only carries the use case and an optional whitelist-relaxation
flag for checkpoints (which may contain framework-internal types).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass


class UseCase(enum.Enum):
    P2P = "p2p"
    RPC_SERVER = "rpc_server"
    RPC_CLIENT = "rpc_client"
    STORAGE = "storage"
    CHECKPOINT = "checkpoint"


@dataclass(frozen=True)
class SerializationContext:
    use_case: UseCase = UseCase.P2P

    @property
    def allow_internal_types(self) -> bool:
        return self.use_case is UseCase.CHECKPOINT


P2P_CONTEXT = SerializationContext(UseCase.P2P)
STORAGE_CONTEXT = SerializationContext(UseCase.STORAGE)
CHECKPOINT_CONTEXT = SerializationContext(UseCase.CHECKPOINT)

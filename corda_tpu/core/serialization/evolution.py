"""Schema-evolution serialization: the reference's AMQP described format +
class carpenter, redesigned for the canonical codec.

Reference parity:
  * AMQP scheme — schema-carrying envelopes so receivers can decode data
    written by senders with older/newer type definitions
    (`core/.../serialization/amqp/SerializerFactory.kt`, `Schema.kt`).
  * Class carpenter — runtime synthesis of types the receiver has never
    seen, so foreign payloads survive a round-trip
    (`core/.../serialization/carpenter/ClassCarpenter.kt:1-326`).

Redesign notes (why this is smaller than 2.9k LoC of Kotlin): the canonical
codec is already self-describing per object (OBJ carries its field names —
codec.py wire grammar), so the envelope schema only needs to add what the
per-object encoding can't: the sender's declared field list per type and
per-field default values for receivers that predate those fields. The
consensus path (`serialize`/`deserialize`, tx ids) is untouched — evolution
applies only at the explicit `deserialize_evolvable` entry point, exactly
like the reference keeps Kryo for checkpoints while AMQP covers P2P/RPC.

Evolution rules (reference `EvolutionSerializer` semantics):
  * wire has extra fields  -> dropped (receiver is older);
  * wire lacks local fields -> filled from the envelope's sender defaults,
    then the local dataclass defaults (receiver is newer); no default -> error;
  * unknown type name       -> a record type is synthesized (carpenter) and
    registered, so the value re-serializes byte-compatibly.
"""
from __future__ import annotations

import dataclasses
import keyword
import re
from typing import Any, Dict, Optional, Tuple

from . import codec
from .codec import SerializationError, _decode, _encode, _read_uvarint

_MAGIC2 = b"CT\x02"  # described (schema-carrying) envelope, version 1

_MISSING = dataclasses.MISSING


# --- schema description ------------------------------------------------------

def schema_for(cls) -> Dict[str, Any]:
    """Describe a registered type: field names and the serializable subset
    of its defaults (the evolution data a newer sender ships to older
    receivers and vice versa)."""
    entry = codec._BY_TYPE.get(cls)
    if entry is None:
        raise SerializationError(f"{cls.__qualname__} is not registered")
    type_name = entry[0]
    fields = []
    defaults: Dict[str, Any] = {}
    if dataclasses.is_dataclass(cls):
        for f in dataclasses.fields(cls):
            fields.append(f.name)
            if f.default is not _MISSING:
                defaults[f.name] = f.default
            elif f.default_factory is not _MISSING:  # type: ignore[misc]
                defaults[f.name] = f.default_factory()  # type: ignore[misc]
    return {"name": type_name, "fields": fields, "defaults": defaults}


def _collect_schemas(value: Any, out: Dict[str, Dict], depth: int = 0) -> None:
    if depth > codec._MAX_DEPTH:
        return
    if isinstance(value, (list, tuple, set, frozenset)):
        for item in value:
            _collect_schemas(item, out, depth + 1)
    elif isinstance(value, dict):
        for k, v in value.items():
            _collect_schemas(k, out, depth + 1)
            _collect_schemas(v, out, depth + 1)
    elif codec._lookup_type(type(value)) is not None:
        type_name, to_dict, _ = codec._lookup_type(type(value))
        if type_name not in out:
            cls = codec._BY_NAME[type_name][0]
            try:
                out[type_name] = schema_for(cls)
            except SerializationError:
                out[type_name] = {"name": type_name, "fields": [], "defaults": {}}
        # always recurse: a later instance may be the first to populate a
        # nested field (e.g. Outer(None) before Outer(Inner(...)))
        for fv in to_dict(value).values():
            _collect_schemas(fv, out, depth + 1)


# --- carpenter ---------------------------------------------------------------

_SYNTH_PREFIX = "Synthesized"

# Synthesized types live in an overlay visible ONLY to the evolvable decode
# path: codec._BY_TYPE gains an entry (so the value re-serializes), but the
# strict-decode whitelist codec._BY_NAME does NOT — a node that has merely
# decoded a tolerant payload must not start strict-accepting the foreign
# type (whitelist pollution; the consensus path stays untouched).
_SYNTH_BY_NAME: Dict[str, Tuple[type, Any, Any]] = {}


def _carpenter(type_name: str, field_names: Tuple[str, ...]):
    """Synthesize a dataclass for a never-seen wire type (reference
    `ClassCarpenter` builds real JVM classes; a dataclass is the Python
    equivalent — attribute access, equality, repr all work)."""
    safe = re.sub(r"\W", "_", type_name)
    cls_fields = []
    for fn in field_names:
        if not fn.isidentifier() or keyword.iskeyword(fn):
            raise SerializationError(
                f"cannot synthesize {type_name!r}: bad field name {fn!r}"
            )
        cls_fields.append((fn, Any, dataclasses.field(default=None)))
    cls = dataclasses.make_dataclass(
        f"{_SYNTH_PREFIX}_{safe}", cls_fields, frozen=True
    )
    cls.__synthesized__ = True

    def to_dict(obj):
        return {fn: getattr(obj, fn) for fn in field_names}

    def from_dict(d):
        return cls(**d)

    from_dict.__evolvable__ = True
    codec._BY_TYPE[cls] = (type_name, to_dict, from_dict)
    _SYNTH_BY_NAME[type_name] = (cls, to_dict, from_dict)
    return cls


def is_synthesized(obj: Any) -> bool:
    return getattr(type(obj), "__synthesized__", False)


# --- evolving decode ---------------------------------------------------------

def _evolve_construct(
    type_name: str,
    wire_fields: Dict[str, Any],
    sender_defaults: Dict[str, Dict[str, Any]],
    strict_unknown: bool,
):
    entry = codec._BY_NAME.get(type_name) or _SYNTH_BY_NAME.get(type_name)
    if entry is None:
        if strict_unknown:
            raise SerializationError(
                f"type {type_name!r} not in deserialization whitelist"
            )
        _carpenter(type_name, tuple(sorted(wire_fields)))
        entry = _SYNTH_BY_NAME[type_name]
    cls, _, from_dict = entry
    # Field-level evolution only applies when the wire field names ARE the
    # dataclass attribute names — i.e. the default @corda_serializable
    # converter (or a carpenter product). Custom adapters may rename wire
    # fields (e.g. StateMachineInfo's {id,name,done}), so they evolve via
    # their own from_dict below.
    if dataclasses.is_dataclass(cls) and getattr(from_dict, "__evolvable__", False):
        local = {f.name: f for f in dataclasses.fields(cls)}
        kept = {k: v for k, v in wire_fields.items() if k in local}
        for fn, f in local.items():
            if fn in kept:
                continue
            # receiver is newer: sender's declared default, then local default
            sd = sender_defaults.get(type_name, {})
            if fn in sd:
                kept[fn] = sd[fn]
            elif f.default is not _MISSING:
                kept[fn] = f.default
            elif f.default_factory is not _MISSING:  # type: ignore[misc]
                kept[fn] = f.default_factory()  # type: ignore[misc]
            else:
                raise SerializationError(
                    f"cannot evolve {type_name}: field {fn!r} missing on the "
                    "wire and has no default"
                )
        try:
            return cls(**kept)
        except TypeError as e:
            raise SerializationError(
                f"cannot construct {type_name}: {e}"
            ) from e
    # custom-adapter type: fall back to the strict converter
    try:
        return from_dict(wire_fields)
    except (TypeError, KeyError) as e:
        raise SerializationError(
            f"cannot evolve custom-adapter type {type_name}: {e}"
        ) from e


# --- public api --------------------------------------------------------------

def serialize_described(value: Any) -> bytes:
    """Schema-carrying envelope: MAGIC2 + {type: {fields, defaults}} + the
    standard canonical payload. The payload bytes are identical to
    `serialize(value)` minus magic, so ids computed over payloads agree."""
    schemas: Dict[str, Dict] = {}
    _collect_schemas(value, schemas)
    # defaults must themselves be serializable; drop any that aren't
    clean = {}
    for name, sch in schemas.items():
        defaults = {}
        for k, v in sch["defaults"].items():
            try:
                _encode(bytearray(), v)
                defaults[k] = v
            except SerializationError:
                pass
        clean[name] = {"fields": list(sch["fields"]), "defaults": defaults}
    out = bytearray(_MAGIC2)
    _encode(out, clean)
    _encode(out, value)
    return bytes(out)


def deserialize_evolvable(
    data: bytes, synthesize_unknown: bool = True
) -> Any:
    """Tolerant decode of either wire format (CT1 standard, CT2 described):
    added/removed fields evolve per the module rules; unknown types are
    carpenter-synthesized unless synthesize_unknown=False."""
    sender_defaults: Dict[str, Dict[str, Any]] = {}
    if data[: len(_MAGIC2)] == _MAGIC2:
        schemas, pos = _decode(data, len(_MAGIC2))
        if isinstance(schemas, dict):
            for name, sch in schemas.items():
                if isinstance(sch, dict):
                    sender_defaults[name] = dict(sch.get("defaults") or {})
    elif data[: len(codec._MAGIC)] == codec._MAGIC:
        pos = len(codec._MAGIC)
    else:
        raise SerializationError("bad magic / unsupported format version")

    def hook(type_name: str, fields: Dict[str, Any]):
        return _evolve_construct(
            type_name, fields, sender_defaults,
            strict_unknown=not synthesize_unknown,
        )

    value, end = _decode(data, pos, obj_hook=hook)
    if end != len(data):
        raise SerializationError(f"{len(data) - end} trailing bytes")
    return value

"""Attachment-delivered contract code: the AttachmentsClassLoader analogue.

Reference parity: `core/src/main/kotlin/net/corda/core/serialization/
AttachmentsClassLoader.kt:23-40` — contract classes are shipped inside
attachment JARs; a dedicated classloader serves classes from the
transaction's attachments and REJECTS overlapping file paths between
attachments (so one attachment cannot shadow another's contract code).

TPU-build shape: an attachment is a ZIP whose `*.py` entries are contract
modules; `load_contracts_from_attachments` executes them in synthetic
modules so their `@contract`-decorated classes land in the global contract
registry (corda_tpu.core.contracts.structures), which LedgerTransaction
verification resolves by name.  Protections kept from the reference:

  * overlap rejection: the same entry path provided by two attachments
    with different content is an error (`OverlappingAttachments`);
  * idempotence: re-loading an identical attachment is a no-op;
  * contract-name collisions with ALREADY-registered code are rejected by
    the registry itself (same name, different class).

Trust model: ONLY LOAD ATTACHMENTS FROM TRUSTED STORES. That is the
primary control, exactly as in the reference (which gates trust on
attachment signing): CPython offers no in-process containment, so an
attachment from an untrusted source runs with full process privileges
regardless of vetting. The sandbox integration layered on top is
defense-in-depth against *accidental* non-determinism: newly registered
contract classes are statically vetted (`core.sandbox.check_code`) at
load time — the WhitelistClassLoader analogue — and tagged
`__untrusted__`, which makes `LedgerTransaction.verify` run them under
the dynamic cost meter (`core.sandbox.run_metered`). See
`core/sandbox.py`'s TRUST MODEL note for the residual bypasses. Pass
vet=False to skip the best-effort layer entirely.
"""
from __future__ import annotations

import hashlib
import io
import sys
import threading
import types
import zipfile
from typing import Dict, List, Tuple

from ..contracts.structures import _CONTRACT_REGISTRY


class AttachmentLoadError(Exception):
    pass


class OverlappingAttachments(AttachmentLoadError):
    """Two attachments provide the same path with different content
    (reference AttachmentsClassLoader overlap check)."""


# content digests already executed (idempotence across calls). Overlap
# rejection is scoped PER CALL (i.e. per transaction, matching the
# reference's per-transaction classloader) — two unrelated transactions
# may legitimately both ship a `contracts/contract.py`.
_loaded_digests: set = set()
# One loader at a time: the atomic-rollback bookkeeping snapshots the
# global contract registry, so concurrent loads from multiple verifier
# worker threads would roll back each other's registrations.
_load_lock = threading.Lock()


def load_contracts_from_attachments(attachments, vet: bool = True) -> List[str]:
    """Execute the contract modules in `attachments` (iterable of objects
    with `.id` and `.data` — corda_tpu Attachment, or raw zip bytes) and
    return the names of newly registered contracts.  Atomic: on any
    failure the contract registry, module table and digest cache are
    rolled back to their pre-call state."""
    before = set(_CONTRACT_REGISTRY)
    entries: Dict[str, Tuple[bytes, bytes]] = {}
    for att in attachments:
        data = att.data if hasattr(att, "data") else bytes(att)
        try:
            zf = zipfile.ZipFile(io.BytesIO(data))
        except zipfile.BadZipFile as exc:
            raise AttachmentLoadError(f"attachment is not a zip: {exc}")
        for info in zf.infolist():
            if not info.filename.endswith(".py"):
                continue
            content = zf.read(info)
            digest = hashlib.sha256(content).digest()
            if info.filename in entries and entries[info.filename][0] != digest:
                raise OverlappingAttachments(
                    f"{info.filename} provided by two attachments "
                    "with different content"
                )
            entries[info.filename] = (digest, content)

    new_modules: List[str] = []
    new_digests: List[bytes] = []
    try:
        for path, (digest, content) in entries.items():
            if digest in _loaded_digests:
                continue  # identical content already executed: no-op
            mod_name = (
                "corda_tpu.attachments."
                + path[:-3].replace("/", ".")
                + "_"
                + digest[:6].hex()
            )
            module = types.ModuleType(mod_name)
            module.__file__ = f"<attachment:{path}>"
            sys.modules[mod_name] = module
            new_modules.append(mod_name)
            try:
                exec(compile(content, module.__file__, "exec"), module.__dict__)
            except Exception as exc:
                raise AttachmentLoadError(f"error loading {path}: {exc}")
            _loaded_digests.add(digest)
            new_digests.append(digest)
        if vet:
            from ..sandbox import check_code

            for contract_name in set(_CONTRACT_REGISTRY) - before:
                cls = _CONTRACT_REGISTRY[contract_name]
                check_code(cls)  # raises SandboxViolation -> rollback below
                cls.__untrusted__ = True  # run metered at verify time
    except Exception:
        # Roll back everything this call touched: a partial load must not
        # leave resolvable contracts whose companion code never loaded.
        for name in reversed(new_modules):
            sys.modules.pop(name, None)
        for digest in new_digests:
            _loaded_digests.discard(digest)
        for contract_name in set(_CONTRACT_REGISTRY) - before:
            del _CONTRACT_REGISTRY[contract_name]
        raise

    return sorted(set(_CONTRACT_REGISTRY) - before)

"""corda_tpu.core.serialization: one deterministic, schema'd wire format.

The reference carries two serialization stacks -- prototype-grade Kryo
(`core/.../serialization/Kryo.kt`, explicitly insecure/slow) and an incubating
AMQP scheme (`core/.../serialization/amqp/`). This framework has exactly one:
a canonical tagged binary format with a whitelist-based type registry
(reference parity: `@CordaSerializable` / `CordaClassResolver.kt` whitelist
enforcement). Canonical means byte-identical across processes and platforms,
because transaction ids are Merkle roots over serialized components.
"""
from .codec import (
    SerializationError,
    corda_serializable,
    deserialize,
    register_adapter,
    serialize,
)
from .context import SerializationContext, UseCase

__all__ = [
    "SerializationError",
    "corda_serializable",
    "deserialize",
    "register_adapter",
    "serialize",
    "SerializationContext",
    "UseCase",
]

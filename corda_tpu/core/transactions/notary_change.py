"""NotaryChangeWireTransaction: the special notary-migration transaction.

Reference parity: `core/src/main/kotlin/net/corda/core/transactions/
NotaryChangeTransactions.kt:16-60` — a transaction carrying only input
StateRefs, the old notary and the new notary.  It has NO stored outputs:
the outputs are derived by resolving the inputs and swapping their notary
(so the state data provably cannot change in flight).  Filtering/tear-offs
do not apply; required signers are the input states' participants, which
means signature verification needs resolution (reference
NotaryChangeLedgerTransaction:52-90).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Tuple

from ..contracts.structures import StateRef, TransactionState
from ..crypto.secure_hash import SecureHash
from ..identity import Party
from ..serialization.codec import register_adapter, serialize


@dataclass(frozen=True)
class NotaryChangeWireTransaction:
    inputs: Tuple[StateRef, ...]
    notary: Party       # the current notary (commits the inputs)
    new_notary: Party

    def __post_init__(self):
        if not self.inputs:
            raise ValueError("a notary change transaction must have inputs")
        if self.notary == self.new_notary:
            raise ValueError("the old and new notaries must be different")

    @property
    def id(self) -> SecureHash:
        # Inputs are globally unique (their originating transactions used
        # salted nonces), so a plain hash over the canonical serialization
        # is collision-safe here — no privacy salt needed (reference
        # NotaryChangeTransactions.kt:33-37).
        return SecureHash.sha256(
            serialize(
                {"in": list(self.inputs), "old": self.notary,
                 "new": self.new_notary}
            )
        )

    # Duck-typed WireTransaction surface used by SignedTransaction / the
    # notary path. Outputs and signers need resolution — the resolver is a
    # `load_state(StateRef) -> TransactionState` callable.

    @property
    def outputs(self):
        raise NotImplementedError(
            "notary-change outputs require resolution: use resolve_outputs()"
        )

    @property
    def time_window(self):
        return None

    @property
    def attachments(self):
        return ()

    def _remap_encumbrance(self, ref: StateRef, encumbrance) -> "int | None":
        """An input's encumbrance index points into its ORIGINAL transaction;
        the migrated output's encumbrance must point at the corresponding
        position in THIS transaction's derived outputs (reference
        NotaryChangeLedgerTransaction remaps via inputs.indexOf)."""
        if encumbrance is None:
            return None
        target = StateRef(ref.txhash, encumbrance)
        try:
            return self.inputs.index(target)
        except ValueError:
            return None  # encumbrance not migrated alongside: link severed

    def resolve_outputs(
        self, load_state: Callable[[StateRef], TransactionState]
    ) -> List[TransactionState]:
        """Output i = input i with the notary swapped and the encumbrance
        index remapped to this transaction's output positions."""
        outs = []
        for ref in self.inputs:
            ts = load_state(ref)
            outs.append(
                TransactionState(
                    data=ts.data, notary=self.new_notary,
                    encumbrance=self._remap_encumbrance(ref, ts.encumbrance),
                )
            )
        return outs

    def resolve_output(
        self, index: int, load_state: Callable[[StateRef], TransactionState]
    ) -> TransactionState:
        """Single derived output (back-chain resolution touches one index;
        resolving all would be quadratic over a chain)."""
        ref = self.inputs[index]
        ts = load_state(ref)
        return TransactionState(
            data=ts.data, notary=self.new_notary,
            encumbrance=self._remap_encumbrance(ref, ts.encumbrance),
        )

    def check_inputs_and_signatures(
        self,
        sigs,
        load_state: Callable[[StateRef], TransactionState],
        exclude_notary: bool = False,
    ) -> None:
        """The one notary-change validity check used by every verifier
        (instigator, acceptor, notary, dependency resolver):
          * every input must currently be governed by this tx's OLD notary
            (otherwise inputs committed under notary A could be consumed
            through notary B, forking the ledger);
          * the signature set must cover every input participant (and the
            old notary, unless exclude_notary — the pre-notarisation view).
        Raises ValueError; callers wrap in their domain exception."""
        for ref in self.inputs:
            ts = load_state(ref)
            if ts.notary.owning_key.encoded != self.notary.owning_key.encoded:
                raise ValueError(
                    f"input {ref} is governed by {ts.notary.name}, "
                    f"not the transaction's old notary {self.notary.name}"
                )
        signed = {sig.by for sig in sigs}
        notary_encoded = self.notary.owning_key.encoded
        missing = {
            k
            for k in self.resolved_required_keys(load_state)
            if not k.is_fulfilled_by(signed)
            and not (exclude_notary and k.encoded == notary_encoded)
        }
        if missing:
            raise ValueError(
                f"notary change is missing signatures for: {missing}"
            )

    def resolved_required_keys(
        self, load_state: Callable[[StateRef], TransactionState]
    ) -> frozenset:
        """Participants of every input state, plus the old notary
        (reference NotaryChangeLedgerTransaction.requiredSigningKeys)."""
        keys = {self.notary.owning_key}
        for ref in self.inputs:
            ts = load_state(ref)
            for p in ts.data.participants:
                key = getattr(p, "owning_key", None)
                if key is not None:
                    keys.add(key)
        return frozenset(keys)

    @property
    def required_signing_keys(self) -> frozenset:
        raise NotImplementedError(
            "notary-change signers require resolution: use "
            "resolved_required_keys()"
        )


register_adapter(
    NotaryChangeWireTransaction, "NotaryChangeWireTransaction",
    lambda t: {"in": list(t.inputs), "old": t.notary, "new": t.new_notary},
    lambda d: NotaryChangeWireTransaction(tuple(d["in"]), d["old"], d["new"]),
)

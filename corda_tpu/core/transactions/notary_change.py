"""NotaryChangeWireTransaction: the special notary-migration transaction.

Reference parity: `core/src/main/kotlin/net/corda/core/transactions/
NotaryChangeTransactions.kt:16-60` — a transaction carrying only input
StateRefs, the old notary and the new notary.  It has NO stored outputs:
the outputs are derived by resolving the inputs and swapping their notary
(so the state data provably cannot change in flight).  Filtering/tear-offs
do not apply; required signers are the input states' participants, which
means signature verification needs resolution (reference
NotaryChangeLedgerTransaction:52-90).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Tuple

from ..contracts.structures import StateRef, TransactionState
from ..crypto.secure_hash import SecureHash
from ..identity import Party
from ..serialization.codec import register_adapter, serialize


@dataclass(frozen=True)
class NotaryChangeWireTransaction:
    inputs: Tuple[StateRef, ...]
    notary: Party       # the current notary (commits the inputs)
    new_notary: Party

    def __post_init__(self):
        if not self.inputs:
            raise ValueError("a notary change transaction must have inputs")
        if self.notary == self.new_notary:
            raise ValueError("the old and new notaries must be different")

    @property
    def id(self) -> SecureHash:
        # Inputs are globally unique (their originating transactions used
        # salted nonces), so a plain hash over the canonical serialization
        # is collision-safe here — no privacy salt needed (reference
        # NotaryChangeTransactions.kt:33-37).
        return SecureHash.sha256(
            serialize(
                {"in": list(self.inputs), "old": self.notary,
                 "new": self.new_notary}
            )
        )

    # Duck-typed WireTransaction surface used by SignedTransaction / the
    # notary path. Outputs and signers need resolution — the resolver is a
    # `load_state(StateRef) -> TransactionState` callable.

    @property
    def outputs(self):
        raise NotImplementedError(
            "notary-change outputs require resolution: use resolve_outputs()"
        )

    @property
    def time_window(self):
        return None

    @property
    def attachments(self):
        return ()

    def resolve_outputs(
        self, load_state: Callable[[StateRef], TransactionState]
    ) -> List[TransactionState]:
        """Output i = input i with the notary swapped (reference
        NotaryChangeLedgerTransaction.outputs computation)."""
        outs = []
        for ref in self.inputs:
            ts = load_state(ref)
            outs.append(
                TransactionState(
                    data=ts.data, notary=self.new_notary,
                    encumbrance=ts.encumbrance,
                )
            )
        return outs

    def resolved_required_keys(
        self, load_state: Callable[[StateRef], TransactionState]
    ) -> frozenset:
        """Participants of every input state, plus the old notary
        (reference NotaryChangeLedgerTransaction.requiredSigningKeys)."""
        keys = {self.notary.owning_key}
        for ref in self.inputs:
            ts = load_state(ref)
            for p in ts.data.participants:
                key = getattr(p, "owning_key", None)
                if key is not None:
                    keys.add(key)
        return frozenset(keys)

    @property
    def required_signing_keys(self) -> frozenset:
        raise NotImplementedError(
            "notary-change signers require resolution: use "
            "resolved_required_keys()"
        )


register_adapter(
    NotaryChangeWireTransaction, "NotaryChangeWireTransaction",
    lambda t: {"in": list(t.inputs), "old": t.notary, "new": t.new_notary},
    lambda d: NotaryChangeWireTransaction(tuple(d["in"]), d["old"], d["new"]),
)

"""TransactionBuilder: mutable transaction assembly + signing.

Parity: reference `core/src/main/kotlin/net/corda/core/transactions/
TransactionBuilder.kt` (signWith, toWireTransaction, toSignedTransaction).
"""
from __future__ import annotations

import os
from typing import List, Optional, Union

from ..contracts.structures import (
    Command,
    CommandData,
    ContractState,
    StateAndRef,
    StateRef,
    TimeWindow,
    TransactionState,
)
from ..crypto import crypto
from ..crypto.keys import KeyPair, PublicKey
from ..crypto.secure_hash import SecureHash
from ..crypto.signing import DigitalSignatureWithKey
from ..identity import Party
from .signed import SignedTransaction
from .wire import WireTransaction


class TransactionBuilder:
    def __init__(self, notary: Optional[Party] = None):
        self.notary = notary
        self._inputs: List[StateRef] = []
        self._outputs: List[TransactionState] = []
        self._commands: List[Command] = []
        self._attachments: List[SecureHash] = []
        self._time_window: Optional[TimeWindow] = None
        self._privacy_salt: bytes = os.urandom(32)
        self._signers: List[KeyPair] = []

    # -- assembly -----------------------------------------------------------

    def add_input_state(self, state_and_ref: StateAndRef) -> "TransactionBuilder":
        notary = state_and_ref.state.notary
        if self.notary is None:
            self.notary = notary
        elif notary != self.notary:
            raise ValueError(
                f"input state requires notary {notary}, builder has {self.notary}"
            )
        self._inputs.append(state_and_ref.ref)
        return self

    def add_output_state(
        self,
        state: Union[TransactionState, ContractState],
        notary: Optional[Party] = None,
        encumbrance: Optional[int] = None,
    ) -> "TransactionBuilder":
        if isinstance(state, TransactionState):
            if notary is not None or encumbrance is not None:
                raise ValueError(
                    "notary/encumbrance args conflict with an explicit "
                    "TransactionState; set them on the TransactionState itself"
                )
            self._outputs.append(state)
        else:
            n = notary or self.notary
            if n is None:
                raise ValueError("no notary for output state")
            self._outputs.append(TransactionState(state, n, encumbrance))
        return self

    def add_command(
        self, data: CommandData, *signers: PublicKey
    ) -> "TransactionBuilder":
        self._commands.append(Command(data, tuple(signers)))
        return self

    def add_attachment(self, attachment_id: SecureHash) -> "TransactionBuilder":
        self._attachments.append(attachment_id)
        return self

    def set_time_window(self, time_window: TimeWindow) -> "TransactionBuilder":
        self._time_window = time_window
        return self

    def with_items(self, *items) -> "TransactionBuilder":
        for item in items:
            if isinstance(item, StateAndRef):
                self.add_input_state(item)
            elif isinstance(item, (TransactionState, ContractState)):
                self.add_output_state(item)
            elif isinstance(item, Command):
                self._commands.append(item)
            elif isinstance(item, SecureHash):
                self.add_attachment(item)
            elif isinstance(item, TimeWindow):
                self.set_time_window(item)
            else:
                raise ValueError(f"cannot add {item!r} to a transaction")
        return self

    # -- output -------------------------------------------------------------

    def to_wire_transaction(self) -> WireTransaction:
        return WireTransaction(
            inputs=tuple(self._inputs),
            outputs=tuple(self._outputs),
            commands=tuple(self._commands),
            attachments=tuple(self._attachments),
            notary=self.notary,
            time_window=self._time_window,
            privacy_salt=self._privacy_salt,
        )

    def sign_with(self, key_pair: KeyPair) -> "TransactionBuilder":
        self._signers.append(key_pair)
        return self

    def to_signed_transaction(
        self, check_sufficient_signatures: bool = True
    ) -> SignedTransaction:
        wtx = self.to_wire_transaction()
        content = wtx.id.bytes
        sigs = [
            DigitalSignatureWithKey(crypto.do_sign(kp.private, content), kp.public)
            for kp in self._signers
        ]
        stx = SignedTransaction.of(wtx, sigs)
        if check_sufficient_signatures:
            stx.verify_required_signatures()
        return stx

"""WireTransaction: the serialized, Merkle-tree-identified transaction.

Parity: reference `core/src/main/kotlin/net/corda/core/transactions/
WireTransaction.kt` — id = Merkle root over component leaf hashes (:39,104),
per-leaf nonces derived from a privacy salt (:97-166), requiredSigningKeys
(:42-50), toLedgerTransaction resolution (:60-92).
"""
from __future__ import annotations

import enum
import os
import struct
from dataclasses import dataclass, field
from functools import cached_property
from typing import Callable, List, Optional, Tuple

from ..contracts.structures import (
    Attachment,
    AuthenticatedObject,
    Command,
    StateAndRef,
    StateRef,
    TimeWindow,
    TransactionState,
)
from ..crypto.merkle import MerkleTree
from ..crypto.secure_hash import SecureHash
from ..identity import Party
from ..serialization.codec import register_adapter, serialize


class ComponentGroup(enum.IntEnum):
    """Merkle leaf ordering (reference ComponentGroupEnum)."""

    INPUTS = 0
    OUTPUTS = 1
    COMMANDS = 2
    ATTACHMENTS = 3
    NOTARY = 4
    TIMEWINDOW = 5
    # Always-revealed per-group component counts. A FilteredTransaction
    # proves leaf INCLUSION only; without the counts a tear-off could hide
    # inputs from a non-validating notary (signed double-spend). The counts
    # leaf makes group completeness checkable from the tear-off alone.
    GROUP_SIZES = 6


def component_nonce(privacy_salt: bytes, group: int, index: int) -> SecureHash:
    """Deterministic per-leaf nonce (reference WireTransaction.kt:97-166):
    prevents brute-forcing hidden components of a FilteredTransaction."""
    return SecureHash.sha256(privacy_salt + struct.pack(">II", group, index))


def component_leaf_hash(
    nonce: SecureHash, group: int, index: int, component_bytes: bytes
) -> SecureHash:
    """Leaf preimage binds the component's (group, index) position so a
    FilteredTransaction prover cannot relabel a genuine leaf as a different
    group or index (the verifier has no privacy salt to recheck the nonce)."""
    return SecureHash.sha256(
        nonce.bytes + struct.pack(">II", group, index) + component_bytes
    )


@dataclass(frozen=True)
class WireTransaction:
    inputs: Tuple[StateRef, ...] = ()
    outputs: Tuple[TransactionState, ...] = ()
    commands: Tuple[Command, ...] = ()
    attachments: Tuple[SecureHash, ...] = ()
    notary: Optional[Party] = None
    time_window: Optional[TimeWindow] = None
    privacy_salt: bytes = field(default_factory=lambda: os.urandom(32))

    def __post_init__(self):
        if len(self.privacy_salt) != 32:
            raise ValueError("privacy salt must be 32 bytes")
        if not (self.inputs or self.outputs or self.commands):
            raise ValueError("transaction must have inputs, outputs or commands")
        if self.time_window is not None and self.notary is None:
            raise ValueError("transactions with a time window must have a notary")
        if len(set(self.inputs)) != len(self.inputs):
            # double-counting one state would let fungible contracts see 2x
            # input value (reference BaseTransaction.kt:35-37)
            raise ValueError("duplicate input states detected")

    # -- components & id ----------------------------------------------------

    def available_components(self) -> List[Tuple[int, int, object]]:
        """(group, index, component) triples in canonical Merkle-leaf order."""
        out: List[Tuple[int, int, object]] = []
        for idx, c in enumerate(self.inputs):
            out.append((ComponentGroup.INPUTS, idx, c))
        for idx, c in enumerate(self.outputs):
            out.append((ComponentGroup.OUTPUTS, idx, c))
        for idx, c in enumerate(self.commands):
            out.append((ComponentGroup.COMMANDS, idx, c))
        for idx, c in enumerate(self.attachments):
            out.append((ComponentGroup.ATTACHMENTS, idx, c))
        if self.notary is not None:
            out.append((ComponentGroup.NOTARY, 0, self.notary))
        if self.time_window is not None:
            out.append((ComponentGroup.TIMEWINDOW, 0, self.time_window))
        out.append((ComponentGroup.GROUP_SIZES, 0, self.group_sizes))
        return out

    @property
    def group_sizes(self) -> List[int]:
        return [
            len(self.inputs), len(self.outputs), len(self.commands),
            len(self.attachments),
            1 if self.notary is not None else 0,
            1 if self.time_window is not None else 0,
        ]

    def component_hashes(self) -> List[SecureHash]:
        return [
            component_leaf_hash(
                component_nonce(self.privacy_salt, group, idx), group, idx, serialize(c)
            )
            for group, idx, c in self.available_components()
        ]

    @cached_property
    def merkle_tree(self) -> MerkleTree:
        # cached: the dataclass is frozen/content-addressed and id is hot
        return MerkleTree.get_merkle_tree(self.component_hashes())

    @property
    def id(self) -> SecureHash:
        return self.merkle_tree.hash

    # -- signing keys -------------------------------------------------------

    @property
    def required_signing_keys(self) -> frozenset:
        """Command signers, plus the notary when its signature is semantically
        required (consuming inputs or attesting a time window) — reference
        WireTransaction.kt:42-50."""
        keys = {k for cmd in self.commands for k in cmd.signers}
        if self.notary is not None and (self.inputs or self.time_window):
            keys.add(self.notary.owning_key)
        return frozenset(keys)

    # -- resolution ---------------------------------------------------------

    def to_ledger_transaction(
        self,
        resolve_state: Callable[[StateRef], TransactionState],
        resolve_attachment: Callable[[SecureHash], Attachment],
        resolve_party: Callable[[object], Optional[Party]] = lambda key: None,
    ) -> "LedgerTransaction":
        """Resolve refs into a verifiable LedgerTransaction (reference
        WireTransaction.toLedgerTransaction :60-92)."""
        from .ledger import LedgerTransaction

        resolved_inputs = tuple(
            StateAndRef(resolve_state(ref), ref) for ref in self.inputs
        )
        resolved_attachments = tuple(
            resolve_attachment(h) for h in self.attachments
        )
        auth_commands = tuple(
            AuthenticatedObject(
                signers=cmd.signers,
                signing_parties=tuple(
                    p for p in (resolve_party(k) for k in cmd.signers) if p is not None
                ),
                value=cmd.value,
            )
            for cmd in self.commands
        )
        return LedgerTransaction(
            inputs=resolved_inputs,
            outputs=self.outputs,
            commands=auth_commands,
            attachments=resolved_attachments,
            id=self.id,
            notary=self.notary,
            time_window=self.time_window,
        )

    # -- tear-offs ----------------------------------------------------------

    def build_filtered_transaction(self, filter_fn: Callable[[object], bool]):
        """Merkle tear-off revealing only components matching filter_fn
        (reference buildFilteredTransaction / filterWithFun :97-166)."""
        from .filtered import FilteredTransaction

        return FilteredTransaction.build(self, filter_fn)

    def out_ref(self, index: int) -> StateAndRef:
        return StateAndRef(self.outputs[index], StateRef(self.id, index))

    def __repr__(self) -> str:
        return f"WireTransaction({self.id})"


register_adapter(
    WireTransaction, "WireTransaction",
    lambda t: {
        "inputs": list(t.inputs),
        "outputs": list(t.outputs),
        "commands": list(t.commands),
        "attachments": list(t.attachments),
        "notary": t.notary,
        "time_window": t.time_window,
        "privacy_salt": t.privacy_salt,
    },
    lambda d: WireTransaction(
        tuple(d["inputs"]), tuple(d["outputs"]), tuple(d["commands"]),
        tuple(d["attachments"]), d["notary"], d["time_window"], d["privacy_salt"],
    ),
)

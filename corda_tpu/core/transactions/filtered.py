"""FilteredTransaction: Merkle tear-offs for non-validating notaries/oracles.

Parity: reference `core/src/main/kotlin/net/corda/core/transactions/
MerkleTransaction.kt:1-179` — `FilteredLeaves` + `PartialMerkleTree`;
`verify()` recomputes leaf hashes from the revealed components + nonces and
checks them against the partial tree and the expected root (= tx id).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ..contracts.structures import Command, StateRef, TimeWindow, TransactionState
from ..crypto.merkle import MerkleTree, PartialMerkleTree
from ..crypto.secure_hash import SecureHash
from ..identity import Party
from ..serialization.codec import register_adapter, serialize
from .wire import ComponentGroup, WireTransaction, component_leaf_hash


class FilteredTransactionVerificationError(Exception):
    pass


@dataclass(frozen=True)
class FilteredComponent:
    """A revealed component with its group/index position and leaf nonce."""

    group: int
    index: int
    component: object
    nonce: SecureHash


@dataclass(frozen=True)
class FilteredTransaction:
    id: SecureHash
    filtered_components: Tuple[FilteredComponent, ...]
    partial_tree: PartialMerkleTree

    @staticmethod
    def build(
        wtx: WireTransaction, filter_fn: Callable[[object], bool]
    ) -> "FilteredTransaction":
        """Reveal components matching filter_fn; prune the rest to hashes.
        The GROUP_SIZES leaf is always revealed so verifiers can check
        group completeness (see ComponentGroup.GROUP_SIZES)."""
        from .wire import ComponentGroup, component_nonce

        included: List[FilteredComponent] = []
        included_hashes: List[SecureHash] = []
        for group, idx, comp in wtx.available_components():
            if group == ComponentGroup.GROUP_SIZES or filter_fn(comp):
                nonce = component_nonce(wtx.privacy_salt, group, idx)
                included.append(FilteredComponent(group, idx, comp, nonce))
                included_hashes.append(
                    component_leaf_hash(nonce, group, idx, serialize(comp))
                )
        tree = wtx.merkle_tree
        partial = PartialMerkleTree.build(tree, included_hashes)
        return FilteredTransaction(tree.hash, tuple(included), partial)

    def verify(self) -> None:
        """Recompute each revealed leaf hash and prove inclusion under id.

        The leaf preimage binds (group, index), so a component relabelled to a
        different position/group hashes to a value absent from the tree."""
        hashes = [
            component_leaf_hash(fc.nonce, fc.group, fc.index, serialize(fc.component))
            for fc in self.filtered_components
        ]
        if len(set(hashes)) != len(hashes):
            raise FilteredTransactionVerificationError("duplicate components")
        if not self.partial_tree.verify(self.id, hashes):
            raise FilteredTransactionVerificationError(
                f"partial Merkle tree verification failed for {self.id}"
            )

    def check_with_fun(self, checking_fun: Callable[[object], bool]) -> bool:
        """True if there is at least one component and every revealed component
        satisfies checking_fun (reference FilteredTransaction.checkWithFun).
        The always-revealed GROUP_SIZES meta leaf is not a user component."""
        from .wire import ComponentGroup

        components = [
            fc.component for fc in self.filtered_components
            if fc.group != ComponentGroup.GROUP_SIZES
        ]
        return bool(components) and all(checking_fun(c) for c in components)

    # -- typed accessors ----------------------------------------------------

    def _of_group(self, group: int) -> List:
        """Revealed components of one group, ordered by leaf index (a
        deserialized tear-off may carry components out of order)."""
        return [
            fc.component
            for fc in sorted(
                (fc for fc in self.filtered_components if fc.group == group),
                key=lambda fc: fc.index,
            )
        ]

    @property
    def inputs(self) -> List[StateRef]:
        return self._of_group(ComponentGroup.INPUTS)

    @property
    def outputs(self) -> List[TransactionState]:
        return self._of_group(ComponentGroup.OUTPUTS)

    @property
    def commands(self) -> List[Command]:
        return self._of_group(ComponentGroup.COMMANDS)

    @property
    def attachments(self) -> List[SecureHash]:
        return self._of_group(ComponentGroup.ATTACHMENTS)

    @property
    def notary(self) -> Optional[Party]:
        n = self._of_group(ComponentGroup.NOTARY)
        return n[0] if n else None

    @property
    def time_window(self) -> Optional[TimeWindow]:
        t = self._of_group(ComponentGroup.TIMEWINDOW)
        return t[0] if t else None

    @property
    def group_sizes(self) -> List[int]:
        """The always-revealed per-group counts; raises if the builder
        omitted them (a tear-off without them proves nothing about
        completeness and must be rejected)."""
        g = self._of_group(ComponentGroup.GROUP_SIZES)
        if not g:
            raise FilteredTransactionVerificationError(
                "tear-off is missing the group-sizes leaf"
            )
        return list(g[0])

    def check_all_inputs_revealed(self) -> None:
        """Every input, the notary, and any time window must be revealed —
        what a non-validating notary needs before committing (prevents a
        hidden-input tear-off obtaining a signed double spend)."""
        sizes = self.group_sizes
        if len(self.inputs) != sizes[ComponentGroup.INPUTS]:
            raise FilteredTransactionVerificationError(
                f"tear-off reveals {len(self.inputs)} of "
                f"{sizes[ComponentGroup.INPUTS]} inputs"
            )
        if sizes[ComponentGroup.NOTARY] and self.notary is None:
            raise FilteredTransactionVerificationError(
                "tear-off hides the notary"
            )
        if sizes[ComponentGroup.TIMEWINDOW] and self.time_window is None:
            raise FilteredTransactionVerificationError(
                "tear-off hides the time window"
            )


def _encode_partial(node) -> dict:
    from ..crypto.merkle import HiddenLeaf, PartialLeaf, PartialNode

    if isinstance(node, PartialLeaf):
        return {"kind": 0, "hash": node.hash}
    if isinstance(node, HiddenLeaf):
        return {"kind": 1, "hash": node.hash, "span": node.leaf_span}
    return {"kind": 2, "left": _encode_partial(node.left), "right": _encode_partial(node.right)}


def _decode_partial(d):
    from ..crypto.merkle import HiddenLeaf, PartialLeaf, PartialNode

    if d["kind"] == 0:
        return PartialLeaf(d["hash"])
    if d["kind"] == 1:
        return HiddenLeaf(d["hash"], d["span"])
    return PartialNode(_decode_partial(d["left"]), _decode_partial(d["right"]))


register_adapter(
    FilteredComponent, "FilteredComponent",
    lambda f: {"group": f.group, "index": f.index, "component": f.component, "nonce": f.nonce},
    lambda d: FilteredComponent(d["group"], d["index"], d["component"], d["nonce"]),
)
register_adapter(
    FilteredTransaction, "FilteredTransaction",
    lambda f: {
        "id": f.id,
        "components": list(f.filtered_components),
        "tree": _encode_partial(f.partial_tree.root),
    },
    lambda d: FilteredTransaction(
        d["id"], tuple(d["components"]), PartialMerkleTree(_decode_partial(d["tree"]))
    ),
)

"""SignedTransaction and signature-set verification.

Parity: reference `core/src/main/kotlin/net/corda/core/transactions/
SignedTransaction.kt` (:78-98 withAdditionalSignature, :143-149 verify) and
`TransactionWithSignatures.kt` (:26,41-47 verifyRequiredSignatures /
verifySignaturesExcept, :58-62 checkSignaturesAreValid, :72-78 missing-key
detection via isFulfilledBy).

TPU-first: checkSignaturesAreValid is *batch-first* — the reference's hot
per-signature loop is replaced with one call into the scheme-bucketed batch
verifier (core.crypto.batch -> ops.ed25519_batch on device).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import FrozenSet, Iterable, List, Set, Tuple

from ..crypto import batch as crypto_batch
from ..crypto.crypto import SignatureError
from ..crypto.keys import PublicKey
from ..crypto.secure_hash import SecureHash
from ..crypto.signing import DigitalSignatureWithKey
from ..serialization.codec import deserialize, register_adapter, serialize
from .wire import WireTransaction

from collections import OrderedDict

#: (content, scheme, key bytes, signature bytes) -> True for signatures
#: that verified; bounded LRU, per process. The SCHEME is part of the
#: key: two keys with identical encoded bytes under different schemes
#: verify through different engines, and a cache hit across them would
#: make acceptance process-history-dependent (warm-cache replicas accept
#: what cold-cache replicas reject — the replica split the rule-pinning
#: work exists to prevent). See check_signatures_are_valid.
_VERIFIED_SIGS: "OrderedDict[tuple, bool]" = OrderedDict()
_VERIFIED_SIGS_MAX = 1 << 16

import threading as _threading

_VERIFIED_SIGS_LOCK = _threading.Lock()


class SignaturesMissingError(SignatureError):
    def __init__(self, missing: FrozenSet[PublicKey], descriptions: List[str], tx_id):
        self.missing = missing
        self.descriptions = descriptions
        self.tx_id = tx_id
        super().__init__(
            f"missing signatures on {tx_id} for: "
            + ", ".join(descriptions or [repr(k) for k in missing])
        )


class TransactionWithSignatures:
    """Mixin: signature-set verification over a Merkle-identified payload."""

    id: SecureHash
    sigs: Tuple[DigitalSignatureWithKey, ...]

    @property
    def required_signing_keys(self) -> frozenset:
        raise NotImplementedError

    def get_key_descriptions(self, keys: Set[PublicKey]) -> List[str]:
        return [repr(k) for k in keys]

    def verify_required_signatures(self) -> None:
        self.verify_signatures_except()

    def verify_signatures_except(self, *allowed_to_be_missing: PublicKey) -> None:
        """Check every attached signature cryptographically, then check the
        required-keys set is fulfilled modulo allowed_to_be_missing."""
        self.check_signatures_are_valid()
        self.check_required_keys_except(*allowed_to_be_missing)

    def check_required_keys_except(self, *allowed_to_be_missing: PublicKey) -> None:
        """The fulfilment half of verify_signatures_except alone — for
        callers that already ran the cryptographic check elsewhere (e.g.
        the notary offloads it to the cross-transaction batcher)."""
        needed = self._missing_signatures()
        missing = needed - set(allowed_to_be_missing)
        if missing:
            raise SignaturesMissingError(
                frozenset(missing), self.get_key_descriptions(missing), self.id
            )

    def signature_check_items(self) -> List[Tuple[PublicKey, bytes, bytes]]:
        """(key, signature, content) rows for a batch verifier — the same
        triples check_signatures_are_valid feeds to verify_batch, exposed
        so services can merge them into CROSS-transaction batches."""
        content = self.id.bytes
        return [(sig.by, sig.bytes, content) for sig in self.sigs]

    def check_signatures_are_valid(self) -> None:
        """Batch cryptographic check of all attached signatures over id.bytes
        (replaces the reference's per-sig loop TransactionWithSignatures.kt:58-62).

        Successful verifications enter a per-process LRU keyed on the
        exact (content, key, signature) bytes: verification is a pure
        function of those bytes, and the SAME signatures re-check
        several times per transaction lifecycle (pre-notarise, post-
        notarise, dependency resolution), so cache hits skip the crypto
        without changing any verdict. Failures are never cached."""
        if not self.sigs:
            return
        content = self.id.bytes
        rows = [(sig.by, sig.bytes, content) for sig in self.sigs]
        todo = []
        with _VERIFIED_SIGS_LOCK:
            for i, (key, sig, _) in enumerate(rows):
                k = (content, key.scheme_code_name, key.encoded, sig)
                if k in _VERIFIED_SIGS:
                    _VERIFIED_SIGS.move_to_end(k)  # true LRU recency
                else:
                    todo.append(i)
        if todo:
            results = crypto_batch.verify_batch([rows[i] for i in todo])
            bad = [todo[j] for j, ok in enumerate(results) if not ok]
            if bad:
                raise SignatureError(
                    f"invalid signature(s) at positions {bad} on {self.id}"
                )
            with _VERIFIED_SIGS_LOCK:
                for i in todo:
                    key, sig, _ = rows[i]
                    _VERIFIED_SIGS[
                        (content, key.scheme_code_name, key.encoded, sig)
                    ] = True
                while len(_VERIFIED_SIGS) > _VERIFIED_SIGS_MAX:
                    _VERIFIED_SIGS.popitem(last=False)

    def _missing_signatures(self) -> Set[PublicKey]:
        # The signed set is exactly the keys that produced valid signatures —
        # never expanded to composite leaves, or an attacker could wrap a
        # victim's key in a 1-of-2 CompositeKey and "sign for" it. A required
        # CompositeKey is fulfilled when its threshold is met by keys in this
        # set (reference TransactionWithSignatures.kt:72-78).
        signed = {sig.by for sig in self.sigs}
        return {
            k
            for k in self.required_signing_keys
            if not k.is_fulfilled_by(signed)
        }


@dataclass(frozen=True)
class SignedTransaction(TransactionWithSignatures):
    """Serialized WireTransaction bytes + signatures over its id."""

    tx_bits: bytes
    sigs: Tuple[DigitalSignatureWithKey, ...]

    def __post_init__(self):
        if not self.sigs:
            raise ValueError("tried to make a SignedTransaction without signatures")

    @staticmethod
    def of(tx: WireTransaction, sigs: Iterable[DigitalSignatureWithKey]) -> "SignedTransaction":
        return SignedTransaction(serialize(tx), tuple(sigs))

    @cached_property
    def tx(self) -> WireTransaction:
        # cached: tx_bits is immutable, and verification touches .tx / .id
        # several times (each access would otherwise re-deserialize and
        # rebuild the Merkle tree)
        return deserialize(self.tx_bits)

    @property
    def id(self) -> SecureHash:
        return self.tx.id

    @property
    def required_signing_keys(self) -> frozenset:
        return self.tx.required_signing_keys

    @property
    def notary(self):
        return self.tx.notary

    @property
    def inputs(self):
        return self.tx.inputs

    def with_additional_signature(self, sig: DigitalSignatureWithKey) -> "SignedTransaction":
        return SignedTransaction(self.tx_bits, self.sigs + (sig,))

    def with_additional_signatures(
        self, sigs: Iterable[DigitalSignatureWithKey]
    ) -> "SignedTransaction":
        return SignedTransaction(self.tx_bits, self.sigs + tuple(sigs))

    def __add__(self, sig: DigitalSignatureWithKey) -> "SignedTransaction":
        return self.with_additional_signature(sig)

    def verify(self, services, check_sufficient_signatures: bool = True) -> None:
        """Full verification: signatures, then resolution + contract verify
        through the (possibly async/batched) TransactionVerifierService
        (reference SignedTransaction.kt:143-149)."""
        if check_sufficient_signatures:
            self.verify_required_signatures()
        else:
            self.check_signatures_are_valid()
        ltx = self.tx.to_ledger_transaction(
            resolve_state=services.load_state,
            resolve_attachment=services.open_attachment,
            resolve_party=getattr(services, "party_from_key", lambda key: None),
        )
        services.transaction_verifier_service.verify_sync(ltx)

    def __repr__(self) -> str:
        return f"SignedTransaction({self.id}, {len(self.sigs)} sigs)"


register_adapter(
    SignedTransaction, "SignedTransaction",
    lambda t: {"tx_bits": t.tx_bits, "sigs": list(t.sigs)},
    lambda d: SignedTransaction(d["tx_bits"], tuple(d["sigs"])),
)

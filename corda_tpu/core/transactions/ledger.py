"""LedgerTransaction: a fully-resolved transaction ready for verification.

Parity: reference `core/src/main/kotlin/net/corda/core/transactions/
LedgerTransaction.kt` — verify() runs every in/out contract (:63-79), plus
notary-consistency and encumbrance checks (:88-125). Serializable so it can be
shipped to the out-of-process / TPU verifier (:22-25).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple, TypeVar

from ..contracts.structures import (
    Attachment,
    AuthenticatedObject,
    StateAndRef,
    StateRef,
    TimeWindow,
    TransactionState,
    TransactionVerificationError,
    resolve_contract,
)
from ..crypto.secure_hash import SecureHash
from ..identity import Party
from ..serialization.codec import register_adapter

S = TypeVar("S")
K = TypeVar("K")


@dataclass(frozen=True)
class InOutGroup:
    """States grouped by a key, for per-group contract verification
    (reference LedgerTransaction.InOutGroup / groupStates)."""

    inputs: tuple
    outputs: tuple
    grouping_key: object


@dataclass(frozen=True)
class LedgerTransaction:
    inputs: Tuple[StateAndRef, ...]
    outputs: Tuple[TransactionState, ...]
    commands: Tuple[AuthenticatedObject, ...]
    attachments: Tuple[Attachment, ...]
    id: SecureHash
    notary: Optional[Party]
    time_window: Optional[TimeWindow]

    # -- verification (contract half; signatures live on SignedTransaction) --

    def verify(self) -> None:
        """Structural checks then every distinct contract's verify()."""
        self._check_no_duplicate_inputs()
        self._check_no_notary_change()
        self._check_encumbrances_protected()
        contracts = {}
        for ts in [s.state for s in self.inputs] + list(self.outputs):
            contracts[ts.data.contract_name] = True
        for name in contracts:
            contract = resolve_contract(name)
            try:
                if getattr(type(contract), "__untrusted__", False):
                    # attachment-delivered code runs under the cost meter
                    # (core/sandbox.py; reference experimental/sandbox)
                    from ..sandbox import run_metered

                    run_metered(contract.verify, self)
                else:
                    contract.verify(self)
            except TransactionVerificationError:
                raise
            except Exception as e:
                raise TransactionVerificationError(
                    self.id, f"contract {name} rejected: {e}"
                ) from e

    def _check_no_duplicate_inputs(self) -> None:
        refs = [s.ref for s in self.inputs]
        if len(set(refs)) != len(refs):
            raise TransactionVerificationError(
                self.id, "duplicate input states detected"
            )

    def _check_no_notary_change(self) -> None:
        if self.notary is None:
            if self.inputs:
                raise TransactionVerificationError(
                    self.id, "transaction with input states must have a notary"
                )
            return
        for s in self.inputs:
            if s.state.notary != self.notary:
                raise TransactionVerificationError(
                    self.id,
                    "input state notary differs from transaction notary; "
                    "use a notary-change transaction",
                )

    def _check_encumbrances_protected(self) -> None:
        # every encumbrance pointer must reference an output of this tx, and
        # an encumbered input must have its encumbrance consumed alongside it
        n_out = len(self.outputs)
        for i, out in enumerate(self.outputs):
            if out.encumbrance is not None:
                if out.encumbrance == i or not (0 <= out.encumbrance < n_out):
                    raise TransactionVerificationError(
                        self.id, f"output {i} has invalid encumbrance {out.encumbrance}"
                    )
        consumed = {s.ref for s in self.inputs}
        for s in self.inputs:
            if s.state.encumbrance is not None:
                enc_ref = StateRef(s.ref.txhash, s.state.encumbrance)
                if enc_ref not in consumed:
                    raise TransactionVerificationError(
                        self.id,
                        f"encumbered input {s.ref} consumed without its "
                        f"encumbrance {enc_ref}",
                    )

    # -- convenience accessors (reference LedgerTransaction helpers) --------

    @property
    def input_states(self) -> List:
        return [s.state.data for s in self.inputs]

    @property
    def output_states(self) -> List:
        return [s.data for s in self.outputs]

    def inputs_of_type(self, cls) -> List:
        return [s for s in self.input_states if isinstance(s, cls)]

    def outputs_of_type(self, cls) -> List:
        return [s for s in self.output_states if isinstance(s, cls)]

    def commands_of_type(self, cls) -> List[AuthenticatedObject]:
        return [c for c in self.commands if isinstance(c.value, cls)]

    def group_states(
        self, cls, key_fn: Callable[[object], K]
    ) -> List[InOutGroup]:
        """Group in/out states of a type by a key (reference groupStates) —
        the backbone of fungible-asset contract verification."""
        groups: Dict[object, Tuple[list, list]] = {}
        for s in self.inputs_of_type(cls):
            groups.setdefault(key_fn(s), ([], []))[0].append(s)
        for s in self.outputs_of_type(cls):
            groups.setdefault(key_fn(s), ([], []))[1].append(s)
        return [
            InOutGroup(tuple(ins), tuple(outs), k)
            for k, (ins, outs) in groups.items()
        ]


register_adapter(
    InOutGroup, "InOutGroup",
    lambda g: {"inputs": list(g.inputs), "outputs": list(g.outputs), "key": g.grouping_key},
    lambda d: InOutGroup(tuple(d["inputs"]), tuple(d["outputs"]), d["key"]),
)
register_adapter(
    Attachment, "Attachment",
    lambda a: {"id": a.id, "data": a.data},
    lambda d: Attachment(d["id"], d["data"]),
)
register_adapter(
    AuthenticatedObject, "AuthenticatedObject",
    lambda a: {"signers": list(a.signers), "parties": list(a.signing_parties), "value": a.value},
    lambda d: AuthenticatedObject(tuple(d["signers"]), tuple(d["parties"]), d["value"]),
)
register_adapter(
    LedgerTransaction, "LedgerTransaction",
    lambda t: {
        "inputs": list(t.inputs), "outputs": list(t.outputs),
        "commands": list(t.commands), "attachments": list(t.attachments),
        "id": t.id, "notary": t.notary, "time_window": t.time_window,
    },
    lambda d: LedgerTransaction(
        tuple(d["inputs"]), tuple(d["outputs"]), tuple(d["commands"]),
        tuple(d["attachments"]), d["id"], d["notary"], d["time_window"],
    ),
)

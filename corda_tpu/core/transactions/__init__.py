"""Transaction model (reference `core/.../transactions/`)."""
from .builder import TransactionBuilder
from .filtered import (
    FilteredComponent,
    FilteredTransaction,
    FilteredTransactionVerificationError,
)
from .ledger import InOutGroup, LedgerTransaction
from .signed import (
    SignatureError,
    SignaturesMissingError,
    SignedTransaction,
    TransactionWithSignatures,
)
from .wire import ComponentGroup, WireTransaction

__all__ = [
    "ComponentGroup", "FilteredComponent", "FilteredTransaction",
    "FilteredTransactionVerificationError", "InOutGroup", "LedgerTransaction",
    "SignatureError", "SignaturesMissingError", "SignedTransaction",
    "TransactionBuilder", "TransactionWithSignatures", "WireTransaction",
]

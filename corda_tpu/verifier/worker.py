"""Verifier worker — the external verification process body.

Reference parity: `verifier/src/main/kotlin/net/corda/verifier/Verifier.kt:50-90`
(consume `verifier.requests`, verify, reply error-or-null).  Extensions:
  * handles `SignatureBatchRequest` by pushing items through a local
    SignatureBatcher (TPU batch kernels) and replying with the bitmask —
    the reference never offloads signatures; this build does (SURVEY §2.7).
  * runs as a thread against an in-process broker (tests, in-node pools) or
    as a standalone process via `main()` with a TCP broker bridge once the
    node runtime exposes one.

Elasticity comes from broker competing-consumer semantics: start N workers
for scale-out, kill one mid-run and its unacked requests are redelivered
(mirrors `VerifierTests.kt:73-101`).

The worker's batcher drains into the overlapped verification pipeline
(verifier/pipeline.py, CORDA_TPU_PIPELINE): each SignatureBatchRequest's
flush hands the batch to the staged engine, so with several workers (or
several requests flushed by one) the host prehash of one batch overlaps
the device/native dispatch of another; replies still follow the
ack-after-result discipline, so redelivery semantics are unchanged.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

from ..core.serialization.codec import deserialize, serialize
from ..messaging import Broker
from ..utils import faultpoints
from .api import (
    VERIFICATION_REQUESTS_QUEUE_NAME,
    SignatureBatchRequest,
    SignatureBatchResponse,
    VerificationRequest,
    VerificationResponse,
)
from .batcher import SignatureBatcher


def worker_slot() -> Optional[int]:
    """This process's device-placement slot, or None.

    CORDA_TPU_MESH_WORKER_SLOT is set by whatever spawns M co-located
    verifier processes (one value per process): slot k of M pins the
    disjoint device slice [k*n, (k+1)*n) of the local device set, so
    workers scale across chips without contending for one
    (docs/perf-pipeline.md, worker placement). Unset/invalid = no slot:
    the whole local device set, today's behaviour."""
    raw = os.environ.get("CORDA_TPU_MESH_WORKER_SLOT", "")
    if not raw:
        return None
    try:
        slot = int(raw)
    except ValueError:
        return None
    return slot if slot >= 0 else None


def placement_mesh(n_devices: int):
    """The n-device mesh this worker process should verify on: its
    slot's disjoint slice when CORDA_TPU_MESH_WORKER_SLOT is set, the
    first n local devices otherwise. Raises when the local device set
    cannot satisfy the slice — a misplaced worker must fail loudly at
    startup, not silently share devices with its neighbour."""
    from ..parallel.mesh import data_mesh, worker_slot_mesh

    slot = worker_slot()
    if slot is None:
        return data_mesh(n_devices)
    return worker_slot_mesh(n_devices, slot)


def mesh_placement() -> dict:
    """The healthcheck/ops view of this process's device placement: the
    configured mesh width, the device ids it pinned, and the slot."""
    from ..core.crypto import batch as crypto_batch

    mesh = crypto_batch.configured_mesh()
    return {
        "devices": 0 if mesh is None else int(mesh.devices.size),
        "device_ids": (
            [] if mesh is None
            else [int(d.id) for d in mesh.devices.flat]
        ),
        "worker_slot": worker_slot(),
    }


class VerifierWorker:
    def __init__(self, broker: Broker, name: str = "verifier-0",
                 batcher: Optional[SignatureBatcher] = None):
        self.name = name
        self._broker = broker
        broker.create_queue(VERIFICATION_REQUESTS_QUEUE_NAME)
        self._batcher = batcher or SignatureBatcher()
        self._stop = threading.Event()
        # prefetch=1: workers COMPETE on this queue — client-side
        # buffering would pin requests to an alive-but-slow worker that
        # an idle peer could otherwise steal (reference VerifierTests
        # rebalancing contract)
        self._consumer = broker.create_consumer(
            VERIFICATION_REQUESTS_QUEUE_NAME, prefetch=1
        )
        self._thread: Optional[threading.Thread] = None
        self.verified_count = 0
        self.crashed = False  # set when a fault injection killed the loop

    def start(self) -> "VerifierWorker":
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            msg = self._consumer.receive(timeout=0.2)
            if msg is None:
                continue
            try:
                request = deserialize(msg.payload)
            except Exception:
                # Poison message (undecodable, so no reply address is
                # recoverable): ack it away rather than redeliver forever.
                self._consumer.ack(msg)
                continue
            if faultpoints.hook is not None:
                action = faultpoints.fire(
                    "verifier.worker", request=type(request).__name__,
                    worker=self.name,
                )
                if action == "crash_before_ack":
                    # hard death mid-verify: the unacked request returns
                    # to the queue for a surviving worker
                    self._die()
                    return
                if action == "crash_after_ack":
                    # the NASTY mode: the broker thinks the request was
                    # handled, but the response is lost forever — only a
                    # requester-side deadline can recover this
                    self._consumer.ack(msg)
                    self._die()
                    return
                if action == "corrupt_response":
                    reply_to = getattr(request, "response_address", None)
                    if reply_to is not None:
                        try:
                            self._broker.send(reply_to, b"\xde\xad\xbe\xef")
                        except Exception:
                            pass
                    self._consumer.ack(msg)
                    continue
            response = self._handle(request)
            if response is not None:
                reply_to, payload = response
                try:
                    self._broker.send(reply_to, payload)
                except Exception:
                    pass  # requester is gone; nothing to do
            self._consumer.ack(msg)
            self.verified_count += 1

    def _handle(self, request):
        if isinstance(request, VerificationRequest):
            try:
                request.transaction.verify()
                error = None
            except Exception as exc:
                error = str(exc)
            resp = VerificationResponse(request.verification_id, error)
            return request.response_address, serialize(resp)
        if isinstance(request, SignatureBatchRequest):
            try:
                futures = self._batcher.submit_many(list(request.items))
                self._batcher.flush()
                valid = tuple(f.result() for f in futures)
                resp = SignatureBatchResponse(request.verification_id, valid)
            except Exception as exc:
                # Worker-side failure is an error reply, not a hang: the
                # requester's futures must resolve either way.
                resp = SignatureBatchResponse(
                    request.verification_id, (), str(exc)
                )
            return request.response_address, serialize(resp)
        return None

    def _die(self) -> None:
        """Simulated crash from inside the consume loop: stop consuming
        and release the consumer session exactly as a dead process would
        (the broker requeues whatever was left unacked)."""
        self.crashed = True
        self._stop.set()
        self._consumer.close()

    def stop(self, graceful: bool = True) -> None:
        """graceful=False mimics a crash: in-flight work is NOT acked, so the
        broker redelivers it to surviving workers."""
        self._stop.set()
        if graceful and self._thread is not None:
            self._thread.join(timeout=2)
        self._consumer.close()
        self._batcher.close()

"""Cross-transaction signature batching buffer.

The reference verifies signatures one at a time inside each transaction
(`TransactionWithSignatures.kt:58-62`).  The TPU design inverts this:
callers submit signature-check items from ANY number of transactions and
get futures back; the batcher accumulates items and flushes them through
`core.crypto.batch.verify_batch` (which buckets by scheme and runs the
device kernels) when either
  * the buffer reaches `max_batch` items, or
  * `linger_ms` elapses after the first pending item (latency bound), or
  * a caller forces `flush()`.

Padding to the next power of two happens inside the device kernel wrapper
(`ops.ed25519_batch.prepare_batch(pad_to=...)`), so XLA sees a small fixed
set of shapes and recompiles rarely.
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import Future
from typing import List, Optional, Sequence, Tuple

from ..core.crypto import batch as crypto_batch
from ..core.crypto.keys import PublicKey

Item = Tuple[PublicKey, bytes, bytes]  # (key, signature, content)


class SignatureBatcher:
    """Thread-safe accumulate-and-flush buffer over the batch verify path.

    Defaults are env-tunable (CORDA_TPU_BATCHER_MAX /
    CORDA_TPU_BATCHER_LINGER_MS) so deployments can trade notarise
    latency against batch size without code changes — node OS processes
    inherit the environment from their launcher."""

    def __init__(self, max_batch: Optional[int] = None,
                 linger_ms: Optional[float] = None):
        if max_batch is None:
            max_batch = int(os.environ.get("CORDA_TPU_BATCHER_MAX", 4096))
        if linger_ms is None:
            linger_ms = float(
                os.environ.get("CORDA_TPU_BATCHER_LINGER_MS", 2.0)
            )
        self.max_batch = max_batch
        self.linger_ms = linger_ms
        self._lock = threading.Lock()
        self._pending: List[Tuple[Item, Future]] = []
        self._timer = None  # TimerHandle from the shared wheel
        self._closed = False
        # telemetry
        self.flushes = 0
        self.items_verified = 0
        self.largest_batch = 0

    def submit(self, item: Item) -> Future:
        """Queue one signature check; resolves to bool."""
        return self.submit_many([item])[0]

    def submit_many(self, items: Sequence[Item]) -> List[Future]:
        futures = [Future() for _ in items]
        run_now = False
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._pending.extend(zip(items, futures))
            if len(self._pending) >= self.max_batch:
                run_now = True
            elif self._timer is None:
                # shared timer wheel (one process-wide thread), not a
                # threading.Timer thread per linger window
                from ..utils.timerwheel import call_later

                self._timer = call_later(self.linger_ms / 1000.0, self.flush)
        if run_now:
            self.flush()
        return futures

    def flush(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._pending = self._pending, []
        if not batch:
            return
        items = [it for it, _ in batch]
        try:
            results = crypto_batch.verify_batch(items)
        except Exception as exc:  # propagate to every waiter
            for _, fut in batch:
                fut.set_exception(exc)
            return
        self.flushes += 1
        self.items_verified += len(batch)
        self.largest_batch = max(self.largest_batch, len(batch))
        for (_, fut), ok in zip(batch, results):
            fut.set_result(bool(ok))

    def close(self) -> None:
        # Refuse new work first, then drain: a submit racing with close
        # either lands before the final flush or fails with "closed" —
        # never a silently-stranded future.
        with self._lock:
            self._closed = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        self.flush()

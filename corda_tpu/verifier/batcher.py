"""Cross-transaction signature batching buffer.

The reference verifies signatures one at a time inside each transaction
(`TransactionWithSignatures.kt:58-62`).  The TPU design inverts this:
callers submit signature-check items from ANY number of transactions and
get futures back; the batcher accumulates items and flushes them through
`core.crypto.batch.verify_batch` (which buckets by scheme and runs the
device kernels) when either
  * the buffer reaches `max_batch` items, or
  * `linger_ms` elapses after the first pending item (latency bound), or
  * a caller forces `flush()`.

DOUBLE-BUFFERED: full/lingered buffers hand off to a dedicated flush
thread that drains them while `submit` keeps filling the next buffer —
a submitter never pays a flush it didn't force, and the verify body
never runs on the shared timer wheel's 2-thread callback pool (where a
minutes-long first XLA compile would stall every other timeout in the
process — the round-5 advisor finding).  The linger callback only moves
the buffer onto the flush queue, which is exactly the "strictly
lightweight wheel callback" contract.

Padding to the next power of two happens inside the device kernel wrapper
(`ops.ed25519_batch.prepare_batch(pad_to=...)`), so XLA sees a small fixed
set of shapes and recompiles rarely.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, List, Optional, Sequence, Tuple

from ..core.crypto import batch as crypto_batch
from ..core.crypto.keys import PublicKey
from ..utils import lockorder, tracing
from . import pipeline as pipeline_mod

Item = Tuple[PublicKey, bytes, bytes]  # (key, signature, content)

#: a pending entry: (item, its future, the submitter's trace context) —
#: the context is what lets one flushed batch emit a fan-in span linking
#: every trace it served
_Entry = Tuple[Item, Future, Optional[tracing.SpanContext]]


class SignatureBatcher:
    """Thread-safe accumulate-and-flush buffer over the batch verify path.

    Defaults are env-tunable (CORDA_TPU_BATCHER_MAX /
    CORDA_TPU_BATCHER_LINGER_MS) so deployments can trade notarise
    latency against batch size without code changes — node OS processes
    inherit the environment from their launcher."""

    def __init__(self, max_batch: Optional[int] = None,
                 linger_ms: Optional[float] = None,
                 max_queued_batches: Optional[int] = None,
                 pipeline: Optional[bool] = None):
        """``pipeline``: route flushed batches through the overlapped
        verification pipeline (verifier/pipeline.py) instead of a
        synchronous ``verify_batch`` call — the host prehashes batch N+1
        while the device/native engine verifies batch N. ``None``
        follows the CORDA_TPU_PIPELINE env gate (on by default;
        ``0`` keeps today's synchronous path byte-identical)."""
        if max_batch is None:
            max_batch = int(os.environ.get("CORDA_TPU_BATCHER_MAX", 4096))
        if linger_ms is None:
            linger_ms = float(
                os.environ.get("CORDA_TPU_BATCHER_LINGER_MS", 2.0)
            )
        if max_queued_batches is None:
            max_queued_batches = int(
                os.environ.get("CORDA_TPU_BATCHER_MAX_QUEUED", 16)
            )
        self.max_batch = max_batch
        self.linger_ms = linger_ms
        # overload protection: with the flush queue at this many waiting
        # buffers, submit_many BLOCKS the submitter until the flush
        # thread catches up — overflow becomes synchronous backpressure
        # on producers instead of unbounded queued batches. 0 = unbounded.
        self.max_queued_batches = max_queued_batches
        # one lock: guards the fill buffer AND (as the condition's lock)
        # the flush queue / in-flight count
        self._lock = lockorder.make_lock("SignatureBatcher._lock")
        self._cv = lockorder.make_condition(
            self._lock, name="SignatureBatcher._cv"
        )
        self._pending: List[_Entry] = []
        self._flush_queue: Deque[List[_Entry]] = deque()
        self._in_flight = 0  # batches being verified right now
        self._flush_thread: Optional[threading.Thread] = None
        self._timer = None  # TimerHandle from the shared wheel
        self._closed = False
        # telemetry (seam timers for bench.py stage attribution).
        # flush() runs batches on CALLER threads concurrently with the
        # flush thread, so these are multi-writer counters.
        self.flushes = 0  # guarded-by: _lock
        self.items_verified = 0  # guarded-by: _lock
        self.largest_batch = 0  # guarded-by: _lock
        self.handoffs = 0  # buffers drained by the flush thread
        self.flush_wall_s = 0.0  # guarded-by: _lock
        # backpressure telemetry: cumulative time handed-off buffers
        # waited before the flush thread picked them up (flush-thread
        # lag — the queueing signal the committee-consensus measurements
        # say precedes a throughput collapse), plus an optional registry
        # binding for the gauges/histograms
        self.flush_lag_s = 0.0  # guarded-by: _cv
        self.backpressure_waits = 0  # guarded-by: _lock
        self._registry = None
        # overlapped-pipeline routing (docs/perf-pipeline.md): decided
        # once at construction so the env gate cannot flip a live
        # batcher's semantics mid-stream; the engine itself is built
        # lazily on the first flush (no threads for batchers that never
        # verify anything)
        self._use_pipeline = (
            pipeline_mod.pipeline_enabled() if pipeline is None
            else bool(pipeline)
        )
        self._pipeline: Optional[pipeline_mod.VerificationPipeline] = None

    def bind_metrics(self, registry) -> None:
        """Register this batcher's occupancy/lag instruments on a node's
        MetricRegistry (gauge re-registration replaces stale closures, so
        a recreated batcher can bind to the same names)."""
        self._registry = registry
        with self._lock:
            pipe = self._pipeline
        if pipe is not None:
            pipe.bind_metrics(registry)
        registry.gauge("Verifier.BatcherOccupancy",
                       lambda: self.pending_count)
        registry.gauge("Verifier.BatcherQueuedBatches",
                       lambda: self.queued_batches)
        registry.gauge("Verifier.BatcherInFlight", lambda: self.in_flight)
        registry.gauge("Verifier.BatcherFlushLagSeconds",
                       lambda: round(self.oldest_queued_age_s, 6))
        registry.gauge("Verifier.BatcherBackpressureWaits",
                       lambda: self.backpressure_waits)
        registry.histogram("Verifier.BatchSize")

    # -- backpressure read surface -----------------------------------------

    @property
    def pending_count(self) -> int:
        """Items in the fill buffer (not yet handed to the flush thread)."""
        with self._lock:
            return len(self._pending)

    @property
    def queued_batches(self) -> int:
        """Buffers handed off but not yet picked up by the flush thread."""
        with self._lock:
            return len(self._flush_queue)

    @property
    def in_flight(self) -> int:
        """Batches being verified right now."""
        with self._lock:
            return self._in_flight

    @property
    def oldest_queued_age_s(self) -> float:
        """Age of the oldest handed-off buffer still waiting for the
        flush thread — the live flush-thread-lag reading (0 when the
        queue is empty)."""
        with self._lock:
            if not self._flush_queue:
                return 0.0
            return time.monotonic() - self._flush_queue[0][0]

    def submit(self, item: Item) -> Future:
        """Queue one signature check; resolves to bool."""
        return self.submit_many([item])[0]

    def submit_many(self, items: Sequence[Item]) -> List[Future]:
        futures = [Future() for _ in items]
        ctx = tracing.current_context()  # the submitting flow's trace
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if (
                self.max_queued_batches
                and len(self._flush_queue) >= self.max_queued_batches
            ):
                # flush queue at capacity: block the SUBMITTER until the
                # flush thread drains (synchronous backpressure — the
                # overload stops here instead of growing the queue).
                # Bounded wait: a dead flush thread must degrade to the
                # old unbounded behavior, never deadlock a submitter.
                self.backpressure_waits += 1
                deadline = time.monotonic() + 30.0
                while (
                    len(self._flush_queue) >= self.max_queued_batches
                    and not self._closed
                    and time.monotonic() < deadline
                ):
                    self._cv.wait(timeout=0.05)
                if self._closed:
                    raise RuntimeError("batcher is closed")
            self._pending.extend(
                (item, fut, ctx) for item, fut in zip(items, futures)
            )
            if len(self._pending) >= self.max_batch:
                # full buffer -> flush thread; submit keeps filling the
                # next buffer without waiting for the verify
                self._hand_off_locked()
            elif self._timer is None:
                # shared timer wheel (one process-wide thread), not a
                # threading.Timer thread per linger window
                from ..utils.timerwheel import call_later

                self._timer = call_later(
                    self.linger_ms / 1000.0, self._linger_fired
                )
        return futures

    # -- double-buffer plumbing -------------------------------------------

    def _linger_fired(self) -> None:
        # runs on the wheel's callback pool: MUST stay lightweight — it
        # only moves the buffer across and wakes the flush thread
        with self._lock:
            self._timer = None
            if self._pending:
                self._hand_off_locked()

    def _hand_off_locked(self) -> None:
        # NOTE: hands off even when the flush queue is at its cap — the
        # linger callback runs on the timer wheel's shared pool and must
        # never block; only submit_many (caller threads) absorbs the
        # backpressure, so the queue can exceed the cap by one buffer.
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        batch, self._pending = self._pending, []
        if not batch:
            return
        # enqueue timestamp rides along: the flush thread's pickup delay
        # is the flush-lag backpressure signal
        self._flush_queue.append((time.monotonic(), batch))
        self.handoffs += 1
        if self._flush_thread is None or not self._flush_thread.is_alive():
            self._flush_thread = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="sig-batcher-flush",
            )
            self._flush_thread.start()
        self._cv.notify_all()

    def _flush_loop(self) -> None:
        while True:
            with self._cv:
                while not self._flush_queue and not self._closed:
                    self._cv.wait()
                if not self._flush_queue:
                    return  # closed and drained
                t_queued, batch = self._flush_queue.popleft()
                self.flush_lag_s += time.monotonic() - t_queued
                self._in_flight += 1
                # wake submitters blocked on the flush-queue cap: the
                # queue just shrank
                self._cv.notify_all()
            try:
                self._run_batch(batch)
            finally:
                with self._cv:
                    self._in_flight -= 1
                    self._cv.notify_all()

    def _run_batch(self, batch: List[_Entry]) -> None:
        if self._use_pipeline:
            pipe = self._ensure_pipeline()
            if pipe is not None and self._run_batch_pipelined(pipe, batch):
                return
        self._run_batch_sync(batch)

    def _run_batch_sync(self, batch: List[_Entry]) -> None:
        items = [it for it, _, _ in batch]
        # fan-in span: ONE batch served N parent traces — link them all
        # so each trace's tree shows the shared flush (untraced batches
        # emit no span at all)
        sp = tracing.get_tracer().fan_in_span(
            "verifier.batch", (ctx for _, _, ctx in batch)
        )
        t0 = time.perf_counter()
        try:
            results = crypto_batch.verify_batch(items)
        except Exception as exc:  # propagate to every waiter
            sp.finish(error=exc)
            self._fail_batch(batch, exc)
            return
        sp.finish()
        self._complete_batch(batch, results, time.perf_counter() - t0)

    # -- pipelined route (docs/perf-pipeline.md) ---------------------------

    def _ensure_pipeline(self):
        with self._lock:
            if self._pipeline is None and not self._closed:
                self._pipeline = pipeline_mod.VerificationPipeline(
                    name="batcher"
                )
                if self._registry is not None:
                    self._pipeline.bind_metrics(self._registry)
            return self._pipeline

    def _run_batch_pipelined(self, pipe, batch: List[_Entry]) -> bool:
        """Hand the batch to the staged engine; False = the engine
        refused (stopping mid-close race) and the caller must run the
        synchronous path instead. submit() BLOCKING on a full ring is
        the designed backpressure: it parks the flush thread, the flush
        queue fills to its cap, and submit_many converts that to
        producer backpressure (PR-5 composition)."""
        items = [it for it, _, _ in batch]
        ctxs = [ctx for _, _, ctx in batch]
        t0 = time.perf_counter()
        try:
            fut = pipe.submit(items, ctxs=ctxs)
        except pipeline_mod.PipelineStoppedError:
            return False
        except Exception as exc:
            # ANY submit failure (e.g. thread exhaustion starting the
            # stage threads) must degrade to the synchronous path, not
            # kill the flush thread with this popped batch's futures
            # stranded unresolved
            from ..utils import eventlog

            eventlog.emit(
                "warning", "verifier",
                "pipeline submit failed; batch served synchronously",
                error=f"{type(exc).__name__}: {exc}", items=len(batch),
            )
            return False

        def done(f) -> None:
            exc = f.exception()
            # the batch's own busy time (sum of its stage walls), NOT
            # submit→completion elapsed: under a loaded ring the latter
            # counts queueing behind other batches as verify work and
            # inflates flush_wall_s up to depth-fold vs the sync path
            # (queueing pressure is flush_lag_s' job)
            walls = getattr(f, "pipeline_stage_walls", None)
            wall = (
                sum(walls.values()) if walls
                else time.perf_counter() - t0
            )
            if exc is not None:
                self._fail_batch(batch, exc)
                return
            # the fan-in span the sync path emits inline: recorded at
            # completion with the measured wall so /traces shows the
            # shared flush identically in both modes (per-stage
            # pipeline.* spans ride alongside, emitted by the engine)
            tracing.get_tracer().record_span(
                "verifier.batch", wall,
                links=[c for c in ctxs if c is not None],
                items=len(batch), pipelined=True,
            )
            self._complete_batch(batch, f.result(), wall)

        fut.add_done_callback(done)
        return True

    # -- shared completion (one source of truth for both modes) ------------

    def _fail_batch(self, batch: List[_Entry], exc: BaseException) -> None:
        from ..utils import eventlog

        eventlog.emit(
            "error", "verifier", "signature batch failed",
            trace_ids={c.trace_id for _, _, c in batch if c is not None},
            items=len(batch), error=f"{type(exc).__name__}: {exc}",
        )
        for _, fut, _ in batch:
            if not fut.done():
                fut.set_exception(exc)

    def _complete_batch(self, batch: List[_Entry], results,
                        wall: float) -> None:
        with self._lock:
            self.flush_wall_s += wall
            self.flushes += 1
            self.items_verified += len(batch)
            self.largest_batch = max(self.largest_batch, len(batch))
        if self._registry is not None:
            self._registry.histogram("Verifier.BatchSize").update(len(batch))
        # flight recorder: one event per flush, fanned under every trace
        # the batch served so /logs?trace=<id> shows the shared flush
        from ..utils import eventlog

        eventlog.emit(
            "info", "verifier", "signature batch verified",
            trace_ids={c.trace_id for _, _, c in batch if c is not None},
            items=len(batch), wall_ms=round(wall * 1000, 3),
        )
        for (_, fut, _), ok in zip(batch, results):
            if not fut.done():
                fut.set_result(bool(ok))

    # -- synchronous edges -------------------------------------------------

    def flush(self) -> None:
        """Run the fill buffer NOW on the caller's thread, then wait for
        any batches already handed to the flush thread — after flush()
        returns, every previously submitted future is resolved (the
        contract deterministic single-pump callers rely on)."""
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._pending = self._pending, []
        if batch:
            self._run_batch(batch)
        while True:
            with self._cv:
                if not self._flush_queue and not self._in_flight:
                    break
                # defensive: a dead flush thread must not strand queued
                # batches (and hang this wait) — drain them inline
                thread_dead = (
                    self._flush_thread is None
                    or not self._flush_thread.is_alive()
                )
                stranded = (
                    self._flush_queue.popleft()
                    if self._flush_queue and thread_dead else None
                )
                if stranded is None:
                    self._cv.wait(timeout=0.05)
                    continue
                t_queued, stranded_batch = stranded
                self.flush_lag_s += time.monotonic() - t_queued
            self._run_batch(stranded_batch)
        # pipelined mode hands batches to the staged engine and returns
        # before they verify: the flush() contract ("every previously
        # submitted future is resolved on return") extends to the ring.
        # Unbounded, like the sync loop above — a slow batch must delay
        # flush(), never let it return with unresolved futures.
        with self._lock:
            pipe = self._pipeline
        if pipe is not None:
            pipe.drain(timeout=None)

    def close(self) -> None:
        # Refuse new work first, then drain: a submit racing with close
        # either lands before the final flush or fails with "closed" —
        # never a silently-stranded future.
        with self._lock:
            self._closed = True
            self._cv.notify_all()  # wake the flush thread to exit
        self.flush()
        with self._lock:
            pipe, self._pipeline = self._pipeline, None
        if pipe is not None:
            pipe.stop()

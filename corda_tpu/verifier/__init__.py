"""corda_tpu.verifier: the out-of-process / batched verification subsystem.

This is the north-star seam (SURVEY.md section 2.7): the reference provides
a pluggable `TransactionVerifierService` and an Artemis queue protocol
(`VerifierApi.kt`) feeding external verifier workers.  Here the same
topology feeds a batching buffer that accumulates signature checks across
transactions and dispatches them to the TPU kernels in corda_tpu.ops —
widening the reference's per-signature loop into device-wide batches.
"""
from .api import (
    VERIFICATION_REQUESTS_QUEUE_NAME,
    VERIFICATION_RESPONSES_QUEUE_NAME_PREFIX,
    SignatureBatchRequest,
    SignatureBatchResponse,
    VerificationRequest,
    VerificationResponse,
)
from .batcher import SignatureBatcher
from .failover import CircuitBreaker, backoff_delay
from .pipeline import PipelineStoppedError, VerificationPipeline
from .service import (
    InMemoryTransactionVerifierService,
    OutOfProcessTransactionVerifierService,
    TransactionVerifierService,
    VerificationError,
    VerificationTimeoutError,
)
from .worker import VerifierWorker

__all__ = [
    "VERIFICATION_REQUESTS_QUEUE_NAME",
    "VERIFICATION_RESPONSES_QUEUE_NAME_PREFIX",
    "SignatureBatchRequest", "SignatureBatchResponse",
    "VerificationRequest", "VerificationResponse",
    "SignatureBatcher",
    "PipelineStoppedError", "VerificationPipeline",
    "CircuitBreaker", "backoff_delay",
    "InMemoryTransactionVerifierService",
    "OutOfProcessTransactionVerifierService",
    "TransactionVerifierService", "VerificationError",
    "VerificationTimeoutError",
    "VerifierWorker",
]

"""TransactionVerifierService SPI and its two implementations.

Reference parity:
  * SPI `verify(ltx) -> Future` — `core/.../TransactionVerifierService.kt:9-15`
  * `InMemoryTransactionVerifierService` — fixed worker pool
    (`InMemoryTransactionVerifierService.kt:10-18`)
  * `OutOfProcessTransactionVerifierService` — nonce-keyed futures over the
    broker queues, with Duration/Success/Failure/InFlight metrics
    (`OutOfProcessTransactionVerifierService.kt:33-71`)
"""
from __future__ import annotations

import os
import random
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from ..core.crypto.secure_hash import random_63_bit_value
from ..core.serialization.codec import deserialize, deserialize_many, serialize
from ..core.transactions.ledger import LedgerTransaction
from ..messaging import Broker
from ..utils import eventlog, lockorder, timerwheel, tracing
from ..utils.metrics import MetricRegistry
from .api import (
    VERIFICATION_REQUESTS_QUEUE_NAME,
    VERIFICATION_RESPONSES_QUEUE_NAME_PREFIX,
    SignatureBatchRequest,
    SignatureBatchResponse,
    VerificationRequest,
    VerificationResponse,
)
from .batcher import Item, SignatureBatcher
from .failover import CircuitBreaker, backoff_delay


class VerificationError(Exception):
    """A transaction failed verification on the verifier side."""


class VerificationTimeoutError(VerificationError):
    """An out-of-process verification request exceeded its deadline
    budget and was dead-lettered (no worker answered after every
    redispatch attempt, and no fallback backend was available)."""


class TransactionVerifierService:
    """SPI: async contract verification plus (TPU extension) batched
    signature verification."""

    def verify(self, ltx: LedgerTransaction) -> Future:
        raise NotImplementedError

    def verify_sync(self, ltx: LedgerTransaction) -> None:
        exc = self.verify(ltx).result()
        if exc is not None:
            raise exc

    def verify_signatures(self, items: Sequence[Item]) -> List[Future]:
        """Offload signature checks; each future resolves to bool."""
        raise NotImplementedError

    def flush_signatures(self) -> None:
        """Force any buffered signature checks to run now. Callers that
        are about to BLOCK on their futures in a context where no other
        producer can feed the batch (deterministic single-pump networks)
        use this to skip the batcher's linger wait; a no-op by default."""

    def healthcheck(self) -> dict:
        """Cheap readiness detail for the node's /healthz//readyz
        aggregation: `ok` False means the verifier backend cannot accept
        work right now."""
        return {"ok": True, "backend": type(self).__name__}


class InMemoryTransactionVerifierService(TransactionVerifierService):
    """Worker pool in the node process; signature checks go through a local
    SignatureBatcher so device batching still happens."""

    def __init__(self, worker_count: Optional[int] = None,
                 batcher: Optional[SignatureBatcher] = None):
        if worker_count is None:
            import os

            # CPU-aware: 4 runnable verify workers on a 1-core box only
            # context-thrash; multi-core hosts keep the full pool
            worker_count = int(
                os.environ.get(
                    "CORDA_TPU_VERIFIER_WORKERS",
                    max(2, min(4, os.cpu_count() or 1)),
                )
            )
        self._pool = ThreadPoolExecutor(
            max_workers=worker_count, thread_name_prefix="verifier"
        )
        self._batcher = batcher or SignatureBatcher()

    def verify(self, ltx: LedgerTransaction) -> Future:
        def run():
            try:
                ltx.verify()
                return None
            except Exception as exc:
                return VerificationError(str(exc))

        return self._pool.submit(run)

    def verify_signatures(self, items: Sequence[Item]) -> List[Future]:
        return self._batcher.submit_many(items)

    def flush_signatures(self) -> None:
        self._batcher.flush()

    def healthcheck(self) -> dict:
        from .worker import mesh_placement

        return {
            "ok": not self._batcher._closed,
            "backend": "in-memory",
            "batcher_occupancy": self._batcher.pending_count,
            "batcher_queued_batches": self._batcher.queued_batches,
            "mesh": mesh_placement(),
        }

    def stop(self) -> None:
        self._batcher.close()
        self._pool.shutdown(wait=False)


class _Metrics:
    """Verifier stats on the shared MetricRegistry (reference metric names
    `OutOfProcessTransactionVerifierService.kt:33-45`): Verification.Success
    / .Failure counters, a Verification.InFlight gauge and a
    Verification.Duration timer whose reservoir is bounded like every
    other registry timer — so verifier stats land in the same /metrics
    snapshot as everything else instead of a hand-rolled side channel.
    The legacy read surface (success/failure/in_flight/durations) is kept
    as properties for existing callers."""

    def __init__(self, registry: MetricRegistry, in_flight_fn):
        self.registry = registry
        self._success = registry.counter("Verification.Success")
        self._failure = registry.counter("Verification.Failure")
        self._duration = registry.timer("Verification.Duration")
        registry.gauge("Verification.InFlight", in_flight_fn)
        # failover telemetry (this PR's failure-handling layer)
        self.redispatched = registry.counter("Verification.Redispatched")
        self.dead_lettered = registry.counter("Verification.DeadLettered")
        self.fallback_served = registry.counter("Verification.FallbackServed")
        self.malformed = registry.counter("Verification.MalformedResponses")

    def record(self, ok: bool, seconds: Optional[float]) -> None:
        (self._success if ok else self._failure).inc()
        if seconds is not None:
            self._duration.update(seconds)

    @property
    def success(self) -> int:
        return self._success.value

    @property
    def failure(self) -> int:
        return self._failure.value

    @property
    def in_flight(self) -> int:
        return int(self.registry.gauge("Verification.InFlight").value)

    @property
    def durations(self):
        """Snapshot of the recent-duration window (the timer's bounded
        reservoir), copied under the timer's lock — the consumer thread
        appends concurrently, so handing out the live deque would let
        callers iterate into a RuntimeError."""
        timer = self._duration
        with timer._lock:
            return list(timer._durations)


class _Inflight:
    """One supervised out-of-process request: everything the deadline
    supervisor needs to redispatch it (the serialized request bytes),
    fail it over (the original payload objects), or dead-letter it."""

    __slots__ = (
        "nonce", "kind", "blob", "futures", "payload", "t0", "attempts",
        "timer", "ctx",
    )

    def __init__(self, nonce: int, kind: str, blob: bytes, futures: List[Future],
                 payload, ctx):
        self.nonce = nonce
        self.kind = kind  # "tx" | "sigs"
        self.blob = blob
        self.futures = futures
        self.payload = payload  # LedgerTransaction | list of Items
        self.t0 = time.monotonic()
        self.attempts = 1  # dispatch attempts so far (first send included)
        self.timer = None  # TimerHandle of the armed deadline/redispatch
        self.ctx = ctx


class OutOfProcessTransactionVerifierService(TransactionVerifierService):
    """Fans verification out over the broker to external verifier workers.

    A nonce keys each request to its future; a consumer thread on this
    node's private response queue completes them.  Competing consumers on
    the shared request queue give worker elasticity for free.

    Failure handling (the robustness layer): every request carries a
    deadline served off the shared timer wheel. A request that times out
    is REDISPATCHED (same nonce — a late reply from the first attempt
    completes it and the second reply is ignored) with exponential
    backoff + jitter, up to `max_retries` extra attempts, after which it
    is dead-lettered into a `VerificationTimeoutError`. A circuit
    breaker trips when the worker pool is observed empty at a deadline
    or when failures stack up; while open (and until a half-open probe
    succeeds), requests are served by a lazily-constructed IN-PROCESS
    fallback backend so flows keep completing through a total worker
    outage. Knobs: CORDA_TPU_VERIFY_DEADLINE (s, <=0 disables
    supervision), CORDA_TPU_VERIFY_RETRIES, CORDA_TPU_VERIFY_BACKOFF_S,
    CORDA_TPU_VERIFY_BREAKER_THRESHOLD / _COOLDOWN,
    CORDA_TPU_VERIFY_FALLBACK=0 (dead-letter instead of falling back).
    """

    def __init__(self, broker: Broker, node_name: str,
                 metrics: Optional[MetricRegistry] = None,
                 deadline_s: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 fallback: Optional[bool] = None,
                 breaker: Optional[CircuitBreaker] = None):
        """`metrics`: the node's shared MetricRegistry (a private one is
        created when standalone, so the read surface always works)."""
        self._broker = broker
        self._response_queue = (
            VERIFICATION_RESPONSES_QUEUE_NAME_PREFIX + node_name
        )
        broker.create_queue(VERIFICATION_REQUESTS_QUEUE_NAME)
        broker.create_queue(self._response_queue)
        self._inflight: Dict[int, _Inflight] = {}
        self._lock = lockorder.make_lock(
            "OutOfProcessTransactionVerifierService._lock"
        )
        self.metrics = _Metrics(
            metrics or MetricRegistry(), lambda: len(self._inflight)
        )
        env = os.environ
        self._deadline = (
            deadline_s if deadline_s is not None
            else float(env.get("CORDA_TPU_VERIFY_DEADLINE", 10.0))
        )
        self._max_retries = (
            max_retries if max_retries is not None
            else int(env.get("CORDA_TPU_VERIFY_RETRIES", 2))
        )
        self._backoff_base = float(env.get("CORDA_TPU_VERIFY_BACKOFF_S", 0.2))
        self._fallback_enabled = (
            fallback if fallback is not None
            else env.get("CORDA_TPU_VERIFY_FALLBACK", "1") != "0"
        )
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=int(
                env.get("CORDA_TPU_VERIFY_BREAKER_THRESHOLD", 3)
            ),
            cooldown_s=float(
                env.get("CORDA_TPU_VERIFY_BREAKER_COOLDOWN", 5.0)
            ),
        )
        self.metrics.registry.gauge(
            "Verification.BreakerState", lambda: self.breaker.state_code
        )
        self._rng = random.Random()  # jitter only; no determinism contract
        self._fallback: Optional[InMemoryTransactionVerifierService] = None
        self._stop = threading.Event()
        self._consumer = broker.create_consumer(self._response_queue)
        self._thread = threading.Thread(
            target=self._consume_responses, name=f"verifier-responses-{node_name}",
            daemon=True,
        )
        self._thread.start()

    # -- request side ------------------------------------------------------

    def _submit(self, kind: str, payload, futures: List[Future],
                make_request) -> None:
        """Register + dispatch one supervised request. When the breaker
        is open (and fallback is on), skip the broker entirely — the
        worker pool is known-dead and the deadline would only add
        latency to the inevitable failover."""
        if self._fallback_enabled and not self.breaker.allow_request():
            entry = _Inflight(0, kind, b"", futures, payload,
                              tracing.current_context())
            self._serve_via_fallback(entry, cause="breaker open")
            return
        nonce = random_63_bit_value()
        blob = serialize(make_request(nonce))
        entry = _Inflight(nonce, kind, blob, futures, payload,
                          tracing.current_context())
        with self._lock:
            self._inflight[nonce] = entry
            if self._deadline > 0:
                entry.timer = timerwheel.call_later(
                    self._deadline, lambda: self._on_deadline(nonce)
                )
        try:
            self._broker.send(VERIFICATION_REQUESTS_QUEUE_NAME, blob)
        except Exception as exc:
            # broker gone at submit time: resolve NOW, never strand
            self._finish_undeliverable(nonce, f"broker send failed: {exc}")

    def verify(self, ltx: LedgerTransaction) -> Future:
        fut: Future = Future()
        self._submit(
            "tx", ltx, [fut],
            lambda nonce: VerificationRequest(nonce, ltx, self._response_queue),
        )
        return fut

    def verify_signatures(self, items: Sequence[Item]) -> List[Future]:
        items = list(items)
        futures = [Future() for _ in items]
        self._submit(
            "sigs", items, futures,
            lambda nonce: SignatureBatchRequest(
                nonce, tuple(items), self._response_queue
            ),
        )
        return futures

    def worker_count(self) -> int:
        return self._broker.consumer_count(VERIFICATION_REQUESTS_QUEUE_NAME)

    # -- deadline supervision ----------------------------------------------

    def _pop(self, nonce: int) -> Optional[_Inflight]:
        with self._lock:
            entry = self._inflight.pop(nonce, None)
        if entry is not None and entry.timer is not None:
            entry.timer.cancel()
        return entry

    def _on_deadline(self, nonce: int) -> None:
        """Timer-wheel callback: the request's current attempt exceeded
        its deadline. Decide redispatch vs failover vs dead-letter."""
        with self._lock:
            entry = self._inflight.get(nonce)
            if entry is None:
                return  # completed while the timer fired
            attempts = entry.attempts
        workers = self.worker_count()
        exhausted = attempts > self._max_retries
        if workers == 0:
            # direct evidence the pool is gone: trip so NEW requests skip
            # the broker while the outage lasts
            self.breaker.trip("worker pool empty at deadline")
        elif exhausted:
            self.breaker.record_failure("deadline exhausted")
        # With the fallback ON, an empty pool fails over immediately —
        # waiting out the retry budget only adds latency to the
        # inevitable. With it OFF, an empty pool still gets the full
        # redispatch budget: a respawning worker (the chaos worker_kill
        # heal pattern) can pick the retry up, and dead-letter is final.
        fail_over_now = exhausted or (workers == 0 and self._fallback_enabled)
        breaker_gating = (
            self._fallback_enabled and not self.breaker.allow_request()
        )
        if breaker_gating and not fail_over_now:
            # this request timed out while the breaker gates the pool —
            # including the half-open PROBE itself: count the failure so
            # a timed-out probe re-opens the breaker (and frees the probe
            # slot) instead of wedging half-open forever
            self.breaker.record_failure("timeout while breaker gating")
        if fail_over_now or breaker_gating:
            entry = self._pop(nonce)
            if entry is None:
                return
            cause = (
                "worker pool empty" if workers == 0
                else f"no response after {attempts} attempts"
            )
            if self._fallback_enabled:
                self._serve_via_fallback(entry, cause=cause)
            else:
                self._dead_letter(entry, cause=cause)
            return
        # redispatch: same nonce (a late first-attempt reply still
        # completes; the duplicate reply is dropped by the nonce pop)
        with self._lock:
            entry = self._inflight.get(nonce)
            if entry is None:
                return
            entry.attempts += 1
            delay = backoff_delay(
                entry.attempts - 1, base_s=self._backoff_base, rng=self._rng
            )
            entry.timer = timerwheel.call_later(
                delay, lambda: self._redispatch(nonce)
            )
        self.metrics.redispatched.inc()
        eventlog.emit(
            "warning", "verifier", "verification request redispatched",
            nonce=nonce, attempt=entry.attempts, backoff_s=round(delay, 3),
            workers=workers, kind=entry.kind,
        )

    def _redispatch(self, nonce: int) -> None:
        with self._lock:
            entry = self._inflight.get(nonce)
            if entry is None:
                return
            blob = entry.blob
            if self._deadline > 0:
                entry.timer = timerwheel.call_later(
                    self._deadline, lambda: self._on_deadline(nonce)
                )
        try:
            self._broker.send(VERIFICATION_REQUESTS_QUEUE_NAME, blob)
        except Exception as exc:
            self._finish_undeliverable(nonce, f"broker send failed: {exc}")

    def _finish_undeliverable(self, nonce: int, cause: str) -> None:
        entry = self._pop(nonce)
        if entry is None:
            return
        if self._fallback_enabled:
            self._serve_via_fallback(entry, cause=cause)
        else:
            self._dead_letter(entry, cause=cause)

    # -- failover endpoints --------------------------------------------------

    def _fallback_backend(self) -> InMemoryTransactionVerifierService:
        with self._lock:
            if self._stop.is_set():
                # a deadline callback racing stop() must not lazily
                # re-create a backend nobody will ever stop
                raise RuntimeError("verifier service stopped")
            if self._fallback is None:
                self._fallback = InMemoryTransactionVerifierService(
                    batcher=SignatureBatcher()
                )
            return self._fallback

    def _serve_via_fallback(self, entry: _Inflight, cause: str) -> None:
        """Complete the request on the in-process backend, chaining its
        futures onto the ones callers already hold."""
        self.metrics.fallback_served.inc()
        eventlog.emit(
            "warning", "verifier", "request served by in-process fallback",
            cause=cause, kind=entry.kind, items=len(entry.futures),
            breaker=self.breaker.state,
        )

        def chain(src: Future, dst: Future) -> None:
            def done(s: Future) -> None:
                if dst.done():
                    return
                exc = s.exception()
                if exc is not None:
                    dst.set_exception(exc)
                else:
                    dst.set_result(s.result())
            src.add_done_callback(done)

        try:
            fb = self._fallback_backend()
            if entry.kind == "tx":
                chain(fb.verify(entry.payload), entry.futures[0])
            else:
                for src, dst in zip(
                    fb.verify_signatures(entry.payload), entry.futures
                ):
                    chain(src, dst)
        except Exception as exc:  # fallback refused (e.g. closed mid-stop)
            self._dead_letter(entry, cause=f"{cause}; fallback failed: {exc}")

    @staticmethod
    def _resolve_with_error(entry: _Inflight, exc: VerificationError) -> None:
        """THE error contract, encoded once: a tx verify() future
        RESOLVES to the error (verify_sync raises it), signature futures
        raise it; already-done futures are left alone."""
        if entry.kind == "tx":
            if not entry.futures[0].done():
                entry.futures[0].set_result(exc)
        else:
            for fut in entry.futures:
                if not fut.done():
                    fut.set_exception(exc)

    def _dead_letter(self, entry: _Inflight, cause: str) -> None:
        self.metrics.dead_lettered.inc()
        eventlog.emit(
            "error", "verifier", "verification request dead-lettered",
            cause=cause, kind=entry.kind, items=len(entry.futures),
        )
        self._resolve_with_error(entry, VerificationTimeoutError(
            f"verification gave up after {entry.attempts} attempts: {cause}"
        ))

    # -- response side -----------------------------------------------------

    def _consume_responses(self) -> None:
        # local consumers drain a batch under one lock acquisition and
        # decode it in ONE GIL-releasing native call (deserialize_many —
        # the verifier-feeding leg of the round-16 message plane);
        # remote consumers already pipeline on the wire and keep the
        # one-at-a-time surface. The response queue is EXCLUSIVE to
        # this service, so batching cannot starve a competing consumer.
        batched = hasattr(self._consumer, "receive_many")
        while not self._stop.is_set():
            if batched:
                batch = self._consumer.receive_many(32, timeout=0.2)
            else:
                one = self._consumer.receive(timeout=0.2)
                batch = [one] if one is not None else []
            if not batch:
                continue
            try:
                decoded = deserialize_many([m.payload for m in batch])
            # lint: allow(swallow) — per-message fallback re-reports each
            except Exception:
                # a malformed frame ANYWHERE in the drain: fall back to
                # per-message decode so the malformed accounting (count
                # + eventlog per offender) stays message-granular
                decoded = None
            for idx, msg in enumerate(batch):
                self._handle_response(msg, decoded[idx] if decoded else None,
                                      decoded is not None)

    def _handle_response(self, msg, resp, predecoded: bool) -> None:
        """One response message's handling — semantics identical to the
        historical inline loop body; `predecoded` means the batch
        decode already produced `resp`."""
        if predecoded:
            known = isinstance(
                resp, (VerificationResponse, SignatureBatchResponse)
            )
            decode_error = None
        else:
            try:
                resp = deserialize(msg.payload)
                known = isinstance(
                    resp, (VerificationResponse, SignatureBatchResponse)
                )
            except Exception as exc:
                resp, known, decode_error = None, False, exc
            else:
                decode_error = None
        if not known:
            # malformed (undecodable or unexpected type): count it
            # and say WHICH queue carried it — silence here cost a
            # debugging session per occurrence
            self.metrics.malformed.inc()
            eventlog.emit(
                "warning", "verifier", "malformed verification response",
                queue=self._response_queue,
                error=(
                    f"{type(decode_error).__name__}: {decode_error}"
                    if decode_error is not None
                    else f"unexpected type {type(resp).__name__}"
                ),
            )
            try:
                self._consumer.ack(msg)
            except Exception:
                pass
            return
        try:
            if isinstance(resp, VerificationResponse):
                self._complete_tx(resp)
            else:
                self._complete_sigs(resp)
            self._consumer.ack(msg)
        except Exception:
            # An ack racing stop()'s consumer close must not kill
            # the completer thread.
            pass

    def _complete_tx(self, resp: VerificationResponse) -> None:
        entry = self._pop(resp.verification_id)
        if entry is None:
            return  # duplicate reply after redispatch/failover
        elapsed = time.monotonic() - entry.t0
        self.metrics.record(resp.error is None, elapsed)
        self.breaker.record_success()
        if entry.ctx is not None:
            tracing.get_tracer().record_span(
                "verifier.verify", elapsed, parent=entry.ctx, remote=True,
            )
        entry.futures[0].set_result(
            None if resp.error is None else VerificationError(resp.error)
        )

    def _complete_sigs(self, resp: SignatureBatchResponse) -> None:
        entry = self._pop(resp.verification_id)
        if entry is None:
            return
        futures = entry.futures
        self.breaker.record_success()
        if entry.ctx is not None:
            # the worker process batches OUR items with other nodes' —
            # its own tracer has the true fan-in; this span records the
            # round trip as seen from the requesting trace
            tracing.get_tracer().record_span(
                "verifier.batch", time.monotonic() - entry.t0,
                links=(entry.ctx,), items=len(futures), remote=True,
            )
        if resp.error is not None or len(resp.valid) != len(futures):
            exc = VerificationError(resp.error or "verdict count mismatch")
            for fut in futures:
                fut.set_exception(exc)
            return
        for fut, ok in zip(futures, resp.valid):
            fut.set_result(bool(ok))

    def healthcheck(self) -> dict:
        from .worker import mesh_placement

        detail = {
            "ok": not self._stop.is_set() and self._thread.is_alive(),
            "backend": "out-of-process",
            "workers": self.worker_count(),
            "in_flight": len(self._inflight),
            "breaker": self.breaker.state,
            "breaker_trips": self.breaker.trips,
            "fallback_active": self._fallback is not None,
            # THIS process's device placement (the in-process fallback
            # path); each remote worker reports its own slot/slice via
            # its own healthcheck surface
            "mesh": mesh_placement(),
        }
        return detail

    def stop(self) -> None:
        self._stop.set()
        self._consumer.close()
        self._thread.join(timeout=2)
        # Drain every still-pending future: a caller blocked on a reply
        # that can now never arrive must fail fast, not hang past
        # shutdown.
        with self._lock:
            entries = list(self._inflight.values())
            self._inflight.clear()
        for entry in entries:
            if entry.timer is not None:
                entry.timer.cancel()
            self._resolve_with_error(
                entry, VerificationError("verifier service stopped")
            )
        with self._lock:
            fallback, self._fallback = self._fallback, None
        if fallback is not None:
            fallback.stop()

"""TransactionVerifierService SPI and its two implementations.

Reference parity:
  * SPI `verify(ltx) -> Future` — `core/.../TransactionVerifierService.kt:9-15`
  * `InMemoryTransactionVerifierService` — fixed worker pool
    (`InMemoryTransactionVerifierService.kt:10-18`)
  * `OutOfProcessTransactionVerifierService` — nonce-keyed futures over the
    broker queues, with Duration/Success/Failure/InFlight metrics
    (`OutOfProcessTransactionVerifierService.kt:33-71`)
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from ..core.crypto.secure_hash import random_63_bit_value
from ..core.serialization.codec import deserialize, serialize
from ..core.transactions.ledger import LedgerTransaction
from ..messaging import Broker
from ..utils import tracing
from ..utils.metrics import MetricRegistry
from .api import (
    VERIFICATION_REQUESTS_QUEUE_NAME,
    VERIFICATION_RESPONSES_QUEUE_NAME_PREFIX,
    SignatureBatchRequest,
    SignatureBatchResponse,
    VerificationRequest,
    VerificationResponse,
)
from .batcher import Item, SignatureBatcher


class VerificationError(Exception):
    """A transaction failed verification on the verifier side."""


class TransactionVerifierService:
    """SPI: async contract verification plus (TPU extension) batched
    signature verification."""

    def verify(self, ltx: LedgerTransaction) -> Future:
        raise NotImplementedError

    def verify_sync(self, ltx: LedgerTransaction) -> None:
        exc = self.verify(ltx).result()
        if exc is not None:
            raise exc

    def verify_signatures(self, items: Sequence[Item]) -> List[Future]:
        """Offload signature checks; each future resolves to bool."""
        raise NotImplementedError

    def flush_signatures(self) -> None:
        """Force any buffered signature checks to run now. Callers that
        are about to BLOCK on their futures in a context where no other
        producer can feed the batch (deterministic single-pump networks)
        use this to skip the batcher's linger wait; a no-op by default."""

    def healthcheck(self) -> dict:
        """Cheap readiness detail for the node's /healthz//readyz
        aggregation: `ok` False means the verifier backend cannot accept
        work right now."""
        return {"ok": True, "backend": type(self).__name__}


class InMemoryTransactionVerifierService(TransactionVerifierService):
    """Worker pool in the node process; signature checks go through a local
    SignatureBatcher so device batching still happens."""

    def __init__(self, worker_count: Optional[int] = None,
                 batcher: Optional[SignatureBatcher] = None):
        if worker_count is None:
            import os

            # CPU-aware: 4 runnable verify workers on a 1-core box only
            # context-thrash; multi-core hosts keep the full pool
            worker_count = int(
                os.environ.get(
                    "CORDA_TPU_VERIFIER_WORKERS",
                    max(2, min(4, os.cpu_count() or 1)),
                )
            )
        self._pool = ThreadPoolExecutor(
            max_workers=worker_count, thread_name_prefix="verifier"
        )
        self._batcher = batcher or SignatureBatcher()

    def verify(self, ltx: LedgerTransaction) -> Future:
        def run():
            try:
                ltx.verify()
                return None
            except Exception as exc:
                return VerificationError(str(exc))

        return self._pool.submit(run)

    def verify_signatures(self, items: Sequence[Item]) -> List[Future]:
        return self._batcher.submit_many(items)

    def flush_signatures(self) -> None:
        self._batcher.flush()

    def healthcheck(self) -> dict:
        return {
            "ok": not self._batcher._closed,
            "backend": "in-memory",
            "batcher_occupancy": self._batcher.pending_count,
            "batcher_queued_batches": self._batcher.queued_batches,
        }

    def stop(self) -> None:
        self._batcher.close()
        self._pool.shutdown(wait=False)


class _Metrics:
    """Verifier stats on the shared MetricRegistry (reference metric names
    `OutOfProcessTransactionVerifierService.kt:33-45`): Verification.Success
    / .Failure counters, a Verification.InFlight gauge and a
    Verification.Duration timer whose reservoir is bounded like every
    other registry timer — so verifier stats land in the same /metrics
    snapshot as everything else instead of a hand-rolled side channel.
    The legacy read surface (success/failure/in_flight/durations) is kept
    as properties for existing callers."""

    def __init__(self, registry: MetricRegistry, in_flight_fn):
        self.registry = registry
        self._success = registry.counter("Verification.Success")
        self._failure = registry.counter("Verification.Failure")
        self._duration = registry.timer("Verification.Duration")
        registry.gauge("Verification.InFlight", in_flight_fn)

    def record(self, ok: bool, seconds: Optional[float]) -> None:
        (self._success if ok else self._failure).inc()
        if seconds is not None:
            self._duration.update(seconds)

    @property
    def success(self) -> int:
        return self._success.value

    @property
    def failure(self) -> int:
        return self._failure.value

    @property
    def in_flight(self) -> int:
        return int(self.registry.gauge("Verification.InFlight").value)

    @property
    def durations(self):
        """Snapshot of the recent-duration window (the timer's bounded
        reservoir), copied under the timer's lock — the consumer thread
        appends concurrently, so handing out the live deque would let
        callers iterate into a RuntimeError."""
        timer = self._duration
        with timer._lock:
            return list(timer._durations)


class OutOfProcessTransactionVerifierService(TransactionVerifierService):
    """Fans verification out over the broker to external verifier workers.

    A nonce keys each request to its future; a consumer thread on this
    node's private response queue completes them.  Competing consumers on
    the shared request queue give worker elasticity for free.
    """

    def __init__(self, broker: Broker, node_name: str,
                 metrics: Optional[MetricRegistry] = None):
        """`metrics`: the node's shared MetricRegistry (a private one is
        created when standalone, so the read surface always works)."""
        self._broker = broker
        self._response_queue = (
            VERIFICATION_RESPONSES_QUEUE_NAME_PREFIX + node_name
        )
        broker.create_queue(VERIFICATION_REQUESTS_QUEUE_NAME)
        broker.create_queue(self._response_queue)
        self._pending: Dict[int, Future] = {}
        self._started: Dict[int, float] = {}
        self._sig_pending: Dict[int, List[Future]] = {}
        # nonce -> requester trace context (requester-side spans for the
        # out-of-process hop: the worker lives in another process, so the
        # round trip is recorded here, at reply time)
        self._trace_ctxs: Dict[int, Optional[tracing.SpanContext]] = {}
        self._lock = threading.Lock()
        self.metrics = _Metrics(
            metrics or MetricRegistry(), lambda: len(self._pending)
        )
        self._stop = threading.Event()
        self._consumer = broker.create_consumer(self._response_queue)
        self._thread = threading.Thread(
            target=self._consume_responses, name=f"verifier-responses-{node_name}",
            daemon=True,
        )
        self._thread.start()

    # -- request side ------------------------------------------------------

    def verify(self, ltx: LedgerTransaction) -> Future:
        nonce = random_63_bit_value()
        fut: Future = Future()
        with self._lock:
            self._pending[nonce] = fut
            self._started[nonce] = time.monotonic()
            self._trace_ctxs[nonce] = tracing.current_context()
        req = VerificationRequest(nonce, ltx, self._response_queue)
        self._broker.send(VERIFICATION_REQUESTS_QUEUE_NAME, serialize(req))
        return fut

    def verify_signatures(self, items: Sequence[Item]) -> List[Future]:
        nonce = random_63_bit_value()
        futures = [Future() for _ in items]
        with self._lock:
            self._sig_pending[nonce] = futures
            self._started[nonce] = time.monotonic()
            self._trace_ctxs[nonce] = tracing.current_context()
        req = SignatureBatchRequest(nonce, tuple(items), self._response_queue)
        self._broker.send(VERIFICATION_REQUESTS_QUEUE_NAME, serialize(req))
        return futures

    def worker_count(self) -> int:
        return self._broker.consumer_count(VERIFICATION_REQUESTS_QUEUE_NAME)

    # -- response side -----------------------------------------------------

    def _consume_responses(self) -> None:
        while not self._stop.is_set():
            msg = self._consumer.receive(timeout=0.2)
            if msg is None:
                continue
            try:
                resp = deserialize(msg.payload)
                if isinstance(resp, VerificationResponse):
                    self._complete_tx(resp)
                elif isinstance(resp, SignatureBatchResponse):
                    self._complete_sigs(resp)
                self._consumer.ack(msg)
            except Exception:
                # A malformed response — or an ack racing stop()'s consumer
                # close — must not kill the completer thread.
                pass

    def _complete_tx(self, resp: VerificationResponse) -> None:
        with self._lock:
            fut = self._pending.pop(resp.verification_id, None)
            t0 = self._started.pop(resp.verification_id, None)
            ctx = self._trace_ctxs.pop(resp.verification_id, None)
            if fut is None:
                return
        elapsed = time.monotonic() - t0 if t0 is not None else None
        self.metrics.record(resp.error is None, elapsed)
        if ctx is not None and elapsed is not None:
            tracing.get_tracer().record_span(
                "verifier.verify", elapsed, parent=ctx, remote=True,
            )
        fut.set_result(
            None if resp.error is None else VerificationError(resp.error)
        )

    def _complete_sigs(self, resp: SignatureBatchResponse) -> None:
        with self._lock:
            futures = self._sig_pending.pop(resp.verification_id, None)
            t0 = self._started.pop(resp.verification_id, None)
            ctx = self._trace_ctxs.pop(resp.verification_id, None)
        if futures is None:
            return
        if ctx is not None and t0 is not None:
            # the worker process batches OUR items with other nodes' —
            # its own tracer has the true fan-in; this span records the
            # round trip as seen from the requesting trace
            tracing.get_tracer().record_span(
                "verifier.batch", time.monotonic() - t0, links=(ctx,),
                items=len(futures), remote=True,
            )
        if resp.error is not None or len(resp.valid) != len(futures):
            exc = VerificationError(resp.error or "verdict count mismatch")
            for fut in futures:
                fut.set_exception(exc)
            return
        for fut, ok in zip(futures, resp.valid):
            fut.set_result(bool(ok))

    def healthcheck(self) -> dict:
        return {
            "ok": not self._stop.is_set() and self._thread.is_alive(),
            "backend": "out-of-process",
            "workers": self.worker_count(),
            "in_flight": len(self._pending),
        }

    def stop(self) -> None:
        self._stop.set()
        self._consumer.close()
        self._thread.join(timeout=2)

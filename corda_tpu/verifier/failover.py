"""Failover primitives for the out-of-process verification path.

The committee-consensus measurements (PAPERS.md, arXiv:2302.00418) treat
verifier failure and recomputation as a first-class cost; the
permissioned-ledger engines (arXiv:2112.02229) assume the host can
redispatch work around a failed accelerator. This module supplies the
two mechanisms the service layer builds that on:

  * `backoff_delay` — capped exponential backoff with full jitter for
    redispatch pacing (jitter keeps N requesters that timed out together
    from re-stampeding the queue in lockstep);
  * `CircuitBreaker` — the classic closed → open → half-open machine.
    Closed counts consecutive failures and trips at a threshold (or
    immediately via `trip()` when the caller KNOWS the backend is gone,
    e.g. a zero-consumer queue). Open fails fast for a cooldown window,
    then half-open admits exactly one probe: its success closes the
    breaker, its failure re-opens it for another cooldown.

Both are deliberately dependency-free (stdlib only) so the worker
process and the node import the same code.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional
from ..utils import lockorder

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

#: numeric encoding for the Prometheus gauge (strings cannot ride a
#: gauge sample): closed=0, half-open=1, open=2
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def backoff_delay(attempt: int, base_s: float = 0.2, cap_s: float = 5.0,
                  rng: Optional[random.Random] = None) -> float:
    """Delay before redispatch `attempt` (1-based): exponential growth
    capped at `cap_s`, scaled by full jitter in [0.5, 1.0)."""
    raw = min(cap_s, base_s * (2 ** max(0, attempt - 1)))
    r = rng.random() if rng is not None else random.random()
    return raw * (0.5 + r / 2)


class CircuitBreaker:
    """Thread-safe three-state breaker guarding one backend."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = lockorder.make_lock("CircuitBreaker._lock")
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_outstanding = False
        self.trips = 0  # lifetime open transitions (telemetry)
        self.last_trip_reason: Optional[str] = None

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    @property
    def state_code(self) -> int:
        return STATE_CODES[self.state]

    def _maybe_half_open_locked(self) -> None:
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._state = HALF_OPEN
            self._probe_outstanding = False

    def allow_request(self) -> bool:
        """May the next request go to the guarded backend? Closed: yes.
        Open: no (fail over) until the cooldown elapses. Half-open: yes
        for exactly ONE in-flight probe; concurrent requests keep failing
        over until the probe settles."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_outstanding:
                self._probe_outstanding = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_outstanding = False
            self._state = CLOSED

    def record_failure(self, reason: str = "failure") -> None:
        """One backend failure; trips to open at the threshold (a
        half-open probe failure re-opens immediately)."""
        with self._lock:
            self._maybe_half_open_locked()
            self._consecutive_failures += 1
            if self._state == HALF_OPEN or (
                self._consecutive_failures >= self.failure_threshold
            ):
                self._trip_locked(reason)

    def trip(self, reason: str) -> None:
        """Open NOW, bypassing the threshold — for callers with direct
        evidence the backend is gone (empty worker pool)."""
        with self._lock:
            self._trip_locked(reason)

    def _trip_locked(self, reason: str) -> None:
        if self._state != OPEN:
            # stamp the cooldown clock only on the TRANSITION into open:
            # trailing timeouts of requests already in flight when the
            # pool died would otherwise keep sliding the half-open probe
            # past the configured cooldown
            self.trips += 1
            self._opened_at = self._clock()
        self._state = OPEN
        self._probe_outstanding = False
        self.last_trip_reason = reason

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open_locked()
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "trips": self.trips,
                "last_trip_reason": self.last_trip_reason,
            }

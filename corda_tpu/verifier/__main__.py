"""Standalone verifier process: `python -m corda_tpu.verifier`.

Reference parity: `verifier/src/main/kotlin/net/corda/verifier/Verifier.kt:50-90`
(a separate JVM that connects to the node's broker over TCP, consumes
`verifier.requests` as a competing consumer, verifies, replies) and its
config loading (`verifier.conf` overlaying `verifier-reference.conf`,
Verifier.kt:42-47; docs `docs/source/out-of-process-verification.rst`).

Usage:
    python -m corda_tpu.verifier --connect HOST:PORT [--name N] [--workers K]
    python -m corda_tpu.verifier CONFIG_DIR       # reads CONFIG_DIR/verifier.conf

verifier.conf is JSON overlaying these defaults (the reference-conf
pattern):  {"connect": "127.0.0.1:10010", "name": "verifier", "workers": 1,
"jax_platform": null}

Scale-out is plain competing consumers: run N of these processes against
one broker; kill one mid-burst and its unacked requests redeliver to the
survivors (reference `VerifierTests.kt:73-101`).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

_DEFAULTS = {
    "connect": "127.0.0.1:10010",
    "name": "verifier",
    "workers": 1,
    "jax_platform": None,  # e.g. "cpu" to force the CPU backend
    "mesh_devices": 0,      # >0: shard big batches across this many devices
}


def _load_config(config_dir: str) -> dict:
    cfg = dict(_DEFAULTS)
    path = os.path.join(config_dir, "verifier.conf")
    if os.path.exists(path):
        with open(path) as fh:
            cfg.update(json.load(fh))
    return cfg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="corda_tpu.verifier")
    ap.add_argument("config_dir", nargs="?", help="directory with verifier.conf")
    ap.add_argument("--connect", help="broker address HOST:PORT")
    ap.add_argument("--name")
    ap.add_argument("--workers", type=int)
    ap.add_argument("--jax-platform", dest="jax_platform")
    ap.add_argument("--mesh-devices", dest="mesh_devices", type=int)
    args = ap.parse_args(argv)

    cfg = _load_config(args.config_dir) if args.config_dir else dict(_DEFAULTS)
    for key in ("connect", "name", "workers", "jax_platform", "mesh_devices"):
        val = getattr(args, key)
        if val is not None:
            cfg[key] = val

    if cfg["jax_platform"]:
        # Must run before any JAX backend use (see tests/conftest.py for the
        # same recipe; the axon sitecustomize latches JAX_PLATFORMS).
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
        )
        import jax

        jax.config.update("jax_platforms", cfg["jax_platform"])

    if int(cfg.get("mesh_devices") or 0) > 0:
        # Shard large signature batches across a device mesh
        # (SURVEY §2.10: pmap/shard_map across the chips of a pod slice).
        # With CORDA_TPU_MESH_WORKER_SLOT set, slot k of M co-located
        # verifier processes pins devices [k*n, (k+1)*n) — disjoint
        # slices, so workers never contend for a chip.
        from ..core.crypto import batch as crypto_batch
        from .worker import placement_mesh

        crypto_batch.configure_mesh(placement_mesh(int(cfg["mesh_devices"])))

    from ..messaging.net import RemoteBroker
    from .worker import VerifierWorker

    host, port_s = cfg["connect"].rsplit(":", 1)
    broker = RemoteBroker(host, int(port_s))

    workers = []
    for i in range(int(cfg["workers"])):
        w = VerifierWorker(broker, name=f"{cfg['name']}-{i}")
        w.start()
        workers.append(w)
    print(f"verifier ready: {len(workers)} worker(s) on {cfg['connect']}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    finally:
        for w in workers:
            w.stop()
        broker.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Overlapped host/device verification pipeline (ROADMAP item 3).

The round-11 profile showed the flow thread at ~96% CPU share while the
device path waited: the device ladder idles while the host parses and
SHA-512-prehashes the next batch.  This module is the structural fix —
a staged engine in the shape of the FPGA ECDSA verification engine of
arXiv:2112.02229 (PAPERS.md), where parse, hash and verify each run
continuously on *different* data and no stage ever blocks another:

    submit ──> [decode] ──> [prehash] ──> [dispatch] ──> [collect] ──> futures
                 parse        SHA-512       async           deferred
                 bucket       (native,      launch /        block_until_ready
                 schemes      GIL-free)     host engines    + composites

Each stage runs on its own daemon thread; batches flow through per-stage
handoff queues; a bounded ring of K batches in flight
(CORDA_TPU_PIPELINE_DEPTH) double-buffers the stages — the host hashes
batch N+1 while the device (or the GIL-releasing native MSM engine)
verifies batch N.  A full ring converts to SYNCHRONOUS ``submit()``
backpressure, which composes with the PR-5 batcher caps: the blocked
flush thread fills the batcher's flush queue, whose cap in turn blocks
producers in ``submit_many`` — overload propagates to the submitters,
never into unbounded queueing.

The stage functions default to the staged phase API of
``core.crypto.batch`` (plan → prehash → dispatch → collect, with the
split device route opted in), but are injectable: a mesh-backed dispatch
stage drops in for 8-chip scale-out (``parallel/mesh.shard_verify`` has
the same batch-in/mask-out shape), and tests substitute gated stubs.

Failure containment: a stage function that raises fails ONLY its own
batch (the batch's future carries the exception; the stage thread and
every other in-flight batch continue).  The ``pipeline.stage`` fault
point (utils/faultpoints) injects exactly that, plus per-stage delays,
under the seeded testing/faults machinery.

Telemetry: ``Pipeline.InFlightBatches`` / ``Pipeline.OverlapRatio``
gauges, per-stage ``Pipeline.StageOccupancy{stage=…}`` /
``Pipeline.StageWallSeconds{stage=…}``, one tracing span per stage
(``pipeline.<stage>``) linked to every trace the batch serves, and
eventlog records for stage failures.  See docs/perf-pipeline.md for the
ring-sizing and overlap math.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Callable, Deque, List, Optional, Sequence, Tuple

from ..utils import faultpoints, lockorder, profiling, tracing

#: default bound on batches in flight across ALL stages (the ring):
#: one per stage double-buffers every handoff; deeper only adds memory
DEFAULT_DEPTH = 4

Stage = Tuple[str, Callable]


class PipelineStoppedError(RuntimeError):
    """The pipeline refused or abandoned a batch because it is stopping."""


def pipeline_enabled() -> bool:
    """The CORDA_TPU_PIPELINE gate: on by default; ``0`` restores the
    synchronous verify path byte-identically (the batcher never
    constructs an engine)."""
    return os.environ.get("CORDA_TPU_PIPELINE", "1") not in ("0", "")


def default_depth() -> int:
    try:
        depth = int(os.environ.get("CORDA_TPU_PIPELINE_DEPTH", DEFAULT_DEPTH))
    except ValueError:
        return DEFAULT_DEPTH
    return max(1, depth)


def mesh_devices() -> int:
    """The CORDA_TPU_MESH_DEVICES knob: shard the pipeline's dispatch
    stage across an N-device mesh (0/unset = single-device dispatch,
    byte-identical to the pre-mesh call graph)."""
    try:
        return max(0, int(
            os.environ.get("CORDA_TPU_MESH_DEVICES", "0") or "0"
        ))
    except ValueError:
        return 0


def default_stages() -> Sequence[Stage]:
    """The production stage functions: the staged phase API of
    core.crypto.batch with the split device route opted in (async
    donated-buffer kernel launches, deferred materialisation).

    With CORDA_TPU_MESH_DEVICES=N (N > 0) the decode and dispatch stage
    functions come from a :class:`MeshDispatcher` instead: each plan's
    dispatch phase shards device buckets across an N-device 1-D data
    mesh (parallel/mesh.shard_verify), decode/prehash/collect unchanged.
    The knob at 0 keeps today's exact call graph — the kill switch."""
    from ..core.crypto import batch as crypto_batch

    n = mesh_devices()
    if n > 0:
        return MeshDispatcher(n_devices=n).stages()
    return (
        ("decode", lambda items: crypto_batch.plan_batch(
            items, split_device=True
        )),
        ("prehash", lambda plan: crypto_batch.prehash_plan(plan)),
        ("dispatch", lambda plan: crypto_batch.dispatch_plan(plan)),
        ("collect", lambda plan: crypto_batch.collect_plan(plan)),
    )


class MeshDispatcher:
    """The mesh-sharded dispatch stage the pipeline was designed for
    (docs/perf-pipeline.md "Scale-out: the same ring feeds the mesh").

    Owns a 1-D N-device data mesh (built lazily so constructing the
    stage table never initialises a backend) and injects it per-plan
    through ``plan_batch(mesh=...)``: the dispatch phase shards each
    device bucket across the mesh via ``parallel/mesh.shard_verify`` —
    per-shard donated buffers, ragged tails masked so a padding row can
    never flip a verdict, and the psum'd mesh-wide valid count
    preserved on the plan (``plan.mesh_totals``) for the notary.
    Decode/prehash stay host work feeding all shards; collect gathers
    exactly as in the single-device pipeline.

    Failure containment is two-level: a shard raising fails only its
    own batch (the pipeline's stage-isolation contract), and the
    dispatcher latches ``_failed`` off ``plan.mesh_failed`` so a
    deterministically broken mesh lowering costs one batch's retry —
    every later plan routes single-device, like the process-global
    latch in core.crypto.batch but scoped to this engine.

    Telemetry: ``Mesh.Devices`` (configured width; 0 once latched
    failed) and ``Mesh.ShardOccupancy{n=k}`` (REAL rows shard k carried
    in the most recent mesh-routed dispatch — the ragged-tail imbalance
    view), plus ``valid_total``, the cumulative psum'd valid count.
    """

    def __init__(self, n_devices: Optional[int] = None,
                 min_batch: Optional[int] = None, axis: str = "data"):
        n = n_devices if n_devices is not None else mesh_devices()
        if n < 1:
            raise ValueError(f"MeshDispatcher needs >= 1 device, got {n}")
        self.n_devices = n
        self.axis = axis
        if min_batch is None:
            from ..core.crypto import batch as crypto_batch

            # an explicitly mesh-enabled pipeline shards every
            # device-sized bucket; the global-mesh default (2048) exists
            # for opportunistic routing, not for a dedicated stage
            min_batch = crypto_batch.MIN_DEVICE_BATCH
        self.min_batch = min_batch
        self._mesh = None
        self._failed = False
        self._lock = lockorder.make_lock("MeshDispatcher._lock")
        self._shard_occupancy = {}  # shard idx -> real rows, last dispatch
        self.valid_total = 0  # cumulative psum'd mesh-wide valid count
        self.dispatches = 0  # mesh-routed dispatch-phase executions

    def _mesh_or_none(self):
        """The mesh, built on first use; None once latched failed (so
        plans fall back to the single-device route) or when the local
        device set cannot satisfy the requested width."""
        with self._lock:
            if self._failed:
                return None
            if self._mesh is None:
                from ..parallel import mesh as mesh_mod

                try:
                    self._mesh = mesh_mod.data_mesh(
                        self.n_devices, axis=self.axis
                    )
                except Exception:
                    self._failed = True
                    import logging

                    logging.getLogger(__name__).exception(
                        "MeshDispatcher: cannot build a %d-device mesh; "
                        "dispatch stays single-device", self.n_devices,
                    )
                    return None
            return self._mesh

    # -- stage functions ---------------------------------------------------

    def plan(self, items):
        from ..core.crypto import batch as crypto_batch

        return crypto_batch.plan_batch(
            items, split_device=True, mesh=self._mesh_or_none(),
            mesh_min_batch=self.min_batch,
        )

    def dispatch(self, plan):
        from ..core.crypto import batch as crypto_batch

        plan = crypto_batch.dispatch_plan(plan)
        if getattr(plan, "mesh_failed", False):
            with self._lock:
                if not self._failed:
                    self._failed = True
                    import logging

                    logging.getLogger(__name__).warning(
                        "MeshDispatcher: mesh dispatch failed (batch "
                        "fell back single-device); the mesh stage is "
                        "latched off for this engine"
                    )
        totals = getattr(plan, "mesh_totals", None)
        if totals:
            self._record_occupancy(plan)
        return plan

    def stages(self) -> Sequence[Stage]:
        """The injectable stage table: decode and dispatch bound to this
        dispatcher, prehash/collect the stock phase functions."""
        from ..core.crypto import batch as crypto_batch

        return (
            ("decode", self.plan),
            ("prehash", lambda plan: crypto_batch.prehash_plan(plan)),
            ("dispatch", self.dispatch),
            ("collect", lambda plan: crypto_batch.collect_plan(plan)),
        )

    # -- telemetry ---------------------------------------------------------

    def _record_occupancy(self, plan) -> None:
        from ..core.crypto import batch as crypto_batch
        from ..core.crypto.schemes import EDDSA_ED25519_SHA512
        from ..parallel import mesh as mesh_mod

        mesh = self._mesh
        if mesh is None:
            return
        occ: dict = {}
        for name, idx in plan.buckets.items():
            kind = (
                "ed25519"
                if name == EDDSA_ED25519_SHA512.scheme_code_name
                else crypto_batch._ECDSA_CURVES.get(name)
            )
            if kind not in plan.mesh_totals:
                continue  # this bucket rode the single-device path
            try:
                _, _, per_shard = mesh_mod.shard_layout(
                    mesh, kind, len(idx)
                )
            except Exception:
                import logging

                # telemetry must never fail a dispatch
                logging.getLogger(__name__).debug(
                    "mesh occupancy layout failed for bucket %r",
                    name, exc_info=True,
                )
                continue
            for k, rows in enumerate(per_shard):
                occ[k] = occ.get(k, 0) + rows
        with self._lock:
            self._shard_occupancy = occ
            self.valid_total += sum(plan.mesh_totals.values())
            self.dispatches += 1

    def shard_occupancy(self, shard: int) -> int:
        with self._lock:
            return self._shard_occupancy.get(shard, 0)

    @property
    def devices(self) -> int:
        """Mesh width for the Mesh.Devices gauge: the configured N, or 0
        once the dispatcher latched failed (the operator's signal that
        the mesh stage degraded to single-device dispatch)."""
        with self._lock:
            return 0 if self._failed else self.n_devices

    def bind_metrics(self, registry) -> None:
        """Register the Mesh.* instruments (labelled-name convention,
        docs/observability.md)."""
        registry.gauge("Mesh.Devices", lambda: self.devices)
        registry.gauge("Mesh.ValidTotal", lambda: self.valid_total)
        for k in range(self.n_devices):
            registry.gauge(
                f"Mesh.ShardOccupancy{{n={k}}}",
                lambda s=k: self.shard_occupancy(s),
            )


class _Job:
    """One batch in flight: the evolving stage value, the caller's
    future, and the trace contexts of every submitter it serves."""

    __slots__ = ("value", "future", "ctxs", "error", "walls")

    def __init__(self, value, future: Future, ctxs):
        self.value = value
        self.future = future
        self.ctxs = tuple(ctxs)
        self.error: Optional[BaseException] = None
        self.walls = {}


class VerificationPipeline:
    """A staged, double-buffered batch engine with a bounded in-flight
    ring.  ``submit()`` returns a Future resolving to the last stage's
    return value; stage threads are created lazily on first submit and
    torn down by ``stop()``."""

    def __init__(self, stages: Optional[Sequence[Stage]] = None,
                 depth: Optional[int] = None, name: str = "verifier",
                 registry=None):
        self.name = name
        self.stages: List[Stage] = list(
            stages if stages is not None else default_stages()
        )
        if not self.stages:
            raise ValueError("a pipeline needs at least one stage")
        self.depth = depth if depth is not None else default_depth()
        self._lock = lockorder.make_lock("VerificationPipeline._lock")
        self._cv = lockorder.make_condition(
            self._lock, name="VerificationPipeline._cv"
        )
        #: one handoff queue per stage (jobs waiting for that stage)
        self._queues: List[Deque[_Job]] = [deque() for _ in self.stages]
        #: jobs popped by a stage thread and not yet finished/forwarded —
        #: what stop() must fail when a wedged stage outlives its timeout
        self._running: List[_Job] = []  # guarded-by: _cv
        self._in_flight = 0  # guarded-by: _cv
        self._threads: List[threading.Thread] = []
        self._stopping = False  # guarded-by: _cv
        self._stopped = False  # guarded-by: _cv
        self._poisoned = False  # thread creation failed; engine unusable
        # telemetry (all guarded by _cv): cumulative per-stage busy
        # seconds, live per-stage occupancy (queued + running), and the
        # engine-active wall needed for the overlap ratio
        self._stage_wall = {s: 0.0 for s, _ in self.stages}
        self._stage_occupancy = {s: 0 for s, _ in self.stages}
        self._busy_total = 0.0  # sum of all stage walls
        self._active_wall = 0.0  # wall time with >= 1 batch in flight
        self._busy_since: Optional[float] = None
        self.batches = 0  # completed (ok or failed)
        self.failures = 0  # batches whose stage raised
        if registry is not None:
            self.bind_metrics(registry)

    # -- read surface ------------------------------------------------------

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    def stage_wall_s(self, stage: str) -> float:
        with self._lock:
            return self._stage_wall.get(stage, 0.0)

    def stage_occupancy(self, stage: str) -> int:
        with self._lock:
            return self._stage_occupancy.get(stage, 0)

    @property
    def overlap_ratio(self) -> float:
        """Fraction of cumulative stage work hidden under other stages'
        work: (sum of stage walls − engine-active wall) / sum of stage
        walls. 0 = fully serial (or idle); → (S−1)/S for S perfectly
        overlapped stages. The live counterpart of the bench A/B's
        ``pipeline_overlap_ratio`` (docs/perf-pipeline.md)."""
        with self._lock:
            busy = self._busy_total
            active = self._active_wall
            if self._busy_since is not None:
                active += time.monotonic() - self._busy_since
        if busy <= 0.0:
            return 0.0
        return max(0.0, (busy - active) / busy)

    def bind_metrics(self, registry) -> None:
        """Register the Pipeline.* instruments (labelled-name convention,
        docs/observability.md); gauge re-registration replaces stale
        closures so a recreated engine can rebind the same names."""
        registry.gauge("Pipeline.InFlightBatches", lambda: self.in_flight)
        registry.gauge(
            "Pipeline.OverlapRatio", lambda: round(self.overlap_ratio, 4)
        )
        for stage, _fn in self.stages:
            registry.gauge(
                f"Pipeline.StageOccupancy{{stage={stage}}}",
                lambda s=stage: self.stage_occupancy(s),
            )
            registry.gauge(
                f"Pipeline.StageWallSeconds{{stage={stage}}}",
                lambda s=stage: round(self.stage_wall_s(s), 6),
            )
        dispatcher = self.mesh_dispatcher
        if dispatcher is not None:
            dispatcher.bind_metrics(registry)

    @property
    def mesh_dispatcher(self) -> Optional["MeshDispatcher"]:
        """The MeshDispatcher owning this engine's dispatch stage, when
        one was injected (CORDA_TPU_MESH_DEVICES > 0); None otherwise."""
        for _stage, fn in self.stages:
            owner = getattr(fn, "__self__", None)
            if isinstance(owner, MeshDispatcher):
                return owner
        return None

    # -- submission --------------------------------------------------------

    def submit(self, value, ctxs=()) -> Future:
        """Enqueue one batch; returns a Future of the final stage's
        return value.  BLOCKS while the ring is full — the synchronous
        backpressure that composes with the batcher's flush-queue cap —
        and raises :class:`PipelineStoppedError` once stop() began."""
        fut: Future = Future()
        job = _Job(value, fut, ctxs)
        with self._cv:
            while (
                self._in_flight >= self.depth
                and not self._stopping
            ):
                self._cv.wait(timeout=0.1)
            if self._stopping:
                raise PipelineStoppedError(f"pipeline {self.name} stopped")
            self._in_flight += 1
            if self._in_flight == 1 and self._busy_since is None:
                self._busy_since = time.monotonic()
            try:
                self._ensure_threads_locked()
            except BaseException:
                # thread exhaustion (the overload regime this engine
                # targets): release the ring slot this submit took —
                # a leaked slot would eventually wedge every later
                # submit against the depth cap — and let the caller
                # fall back to the synchronous path
                self._in_flight -= 1
                if self._in_flight == 0 and self._busy_since is not None:
                    self._busy_since = None
                raise
            self._queues[0].append(job)
            self._stage_occupancy[self.stages[0][0]] += 1
            self._cv.notify_all()
        return fut

    def _ensure_threads_locked(self) -> None:
        if self._poisoned:
            # a previous thread-creation failure: refuse rather than
            # queue onto missing stages
            raise PipelineStoppedError(
                f"pipeline {self.name} unusable: stage threads "
                "failed to start"
            )
        if self._threads:
            return
        started = []
        try:
            for i, (stage, _fn) in enumerate(self.stages):
                t = threading.Thread(
                    target=self._stage_loop, args=(i,),
                    name=f"pipeline-{self.name}-{stage}", daemon=True,
                )
                t.start()
                started.append(t)
        except BaseException:
            # thread exhaustion mid-creation: partial stage coverage
            # would wedge every batch at the missing stage, so poison
            # the engine — the started threads see _stopped and exit;
            # later submits raise and callers fall back to the
            # synchronous path
            self._poisoned = True
            # lint: allow(guarded_by) — _ensure_threads_locked runs under _cv (submit holds it)
            self._stopping = True
            # lint: allow(guarded_by) — same: the caller holds _cv
            self._stopped = True
            self._threads = started
            self._cv.notify_all()
            raise
        self._threads = started

    # -- stage machinery ---------------------------------------------------

    def _stage_loop(self, i: int) -> None:
        stage, fn = self.stages[i]
        q = self._queues[i]
        while True:
            with self._cv:
                while not q and not self._stopped:
                    self._cv.wait()
                if not q:
                    return  # stopped; leftovers were failed by stop()
                job = q.popleft()
                self._running.append(job)
            self._run_stage(i, stage, fn, job)

    def _run_stage(self, i: int, stage: str, fn, job: _Job) -> None:
        # fan-in span per stage: ONE stage execution serves every trace
        # the batch carries (NOOP when the batch is untraced)
        sp = tracing.get_tracer().fan_in_span(
            f"pipeline.{stage}", job.ctxs, pipeline=self.name
        )
        t0 = time.monotonic()
        err: Optional[BaseException] = None
        try:
            if faultpoints.hook is not None:
                action = faultpoints.fire(
                    "pipeline.stage", stage=stage, pipeline=self.name
                )
                if action == "crash":
                    raise RuntimeError(
                        f"injected pipeline fault at stage {stage}"
                    )
                if isinstance(action, tuple) and action and \
                        action[0] == "delay":
                    time.sleep(action[1])
            # thread-local stage context: dispatch records the stage
            # functions produce land in the kernel flight ledger
            # labelled with the stage that ran them (utils/profiling)
            profiling.set_stage(stage)
            try:
                job.value = fn(job.value)
            finally:
                profiling.set_stage(None)
        except BaseException as exc:
            err = exc
        wall = time.monotonic() - t0
        sp.finish(error=err)
        last = i + 1 >= len(self.stages)
        if err is not None:
            job.error = err
            from ..utils import eventlog

            eventlog.emit(
                "error", "pipeline", "pipeline stage failed",
                trace_ids={c.trace_id for c in job.ctxs if c is not None},
                stage=stage, name=self.name,
                error=f"{type(err).__name__}: {err}",
            )
        with self._cv:
            self._stage_occupancy[stage] -= 1
            self._stage_wall[stage] += wall
            self._busy_total += wall
            job.walls[stage] = wall
            if job in self._running:
                self._running.remove(job)
            if err is None and not last and not self._stopped:
                self._queues[i + 1].append(job)
                self._stage_occupancy[self.stages[i + 1][0]] += 1
                self._cv.notify_all()
                return
            if err is None and not last:
                # stopped while this stage ran: the next stage's thread
                # is gone, so terminate the batch here instead of
                # parking it on a dead queue (stop() already failed the
                # future; _resolve below is done()-guarded)
                job.error = PipelineStoppedError(
                    f"pipeline {self.name} stopped mid-batch"
                )
        # terminal (finished or failed): resolve the future FIRST, so a
        # caller woken by drain()/flush() can never observe an
        # unresolved future for a batch the ring no longer counts
        self._resolve(job)
        with self._cv:
            self.batches += 1
            if job.error is not None:
                self.failures += 1
            self._in_flight -= 1
            if self._in_flight == 0 and self._busy_since is not None:
                self._active_wall += time.monotonic() - self._busy_since
                self._busy_since = None
            self._cv.notify_all()

    @staticmethod
    def _resolve(job: _Job) -> None:
        if job.future.done():
            return
        # the batch's own per-stage busy walls ride the future (read by
        # done callbacks, e.g. the batcher's flush_wall_s accounting):
        # elapsed submit→resolve time would count ring blocking and
        # inter-stage queueing as verify work
        job.future.pipeline_stage_walls = dict(job.walls)
        try:
            if job.error is not None:
                job.future.set_exception(job.error)
            else:
                job.future.set_result(job.value)
        except InvalidStateError:
            # lost the race against stop()'s wedged-batch failover
            # (done() checks are not atomic with the set): the loser
            # must never kill a stage thread — the terminal accounting
            # after this call still has to run
            pass

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: Optional[float] = 60.0) -> bool:
        """Block until no batch is in flight (True) or `timeout` elapsed
        (False). Completion order guarantees every drained batch's
        future is already resolved when this returns."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._in_flight > 0:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(timeout=0.5 if remaining is None else
                              min(0.5, remaining))
            return True

    def stop(self, timeout: float = 10.0) -> None:
        """Refuse new submissions, drain in-flight batches, then tear the
        stage threads down.  Batches still unfinished after `timeout`
        (e.g. a stage wedged by fault injection) are failed with
        :class:`PipelineStoppedError` — zero hung futures, ever."""
        with self._cv:
            if self._stopped:
                return
            self._stopping = True
            self._cv.notify_all()  # wake blocked submitters to raise
        self.drain(timeout=timeout)
        leftovers: List[_Job] = []
        with self._cv:
            self._stopped = True
            for i, q in enumerate(self._queues):
                while q:
                    job = q.popleft()
                    self._stage_occupancy[self.stages[i][0]] -= 1
                    job.error = PipelineStoppedError(
                        f"pipeline {self.name} stopped with the batch "
                        "still queued"
                    )
                    leftovers.append(job)
                    self._in_flight -= 1
            # a batch RUNNING inside a wedged stage still holds its
            # caller's future: fail it now rather than strand the
            # caller; the stage thread's eventual completion finds the
            # future already done (_resolve is done()-guarded) and only
            # updates telemetry
            wedged = list(self._running)
            if self._in_flight <= 0 and self._busy_since is not None:
                self._active_wall += time.monotonic() - self._busy_since
                self._busy_since = None
            self._cv.notify_all()
        for job in leftovers:
            self._resolve(job)
        for job in wedged:
            if not job.future.done():
                try:
                    job.future.set_exception(PipelineStoppedError(
                        f"pipeline {self.name} stopped with the batch "
                        "wedged in a stage"
                    ))
                except InvalidStateError:
                    pass  # the stage completed between check and set
        if wedged:
            from ..utils import eventlog

            eventlog.emit(
                "warning", "pipeline", "pipeline stopped with wedged batches",
                name=self.name, batches=len(wedged),
            )
        for t in self._threads:
            t.join(timeout=5)

"""Verifier wire protocol (reference `node-api/.../VerifierApi.kt:11-58`).

Queue-name contract kept identical to the reference so the topology reads
the same: one shared request queue with competing consumers, one response
queue per requesting node.

Two request kinds (the reference has only the first; the second is the
north-star extension that moves the signature hot loop onto this seam):
  * `VerificationRequest`  — a resolved LedgerTransaction; worker runs
    contract verification and replies error-or-None.
  * `SignatureBatchRequest` — (key, signature, content) triples from any
    number of transactions; worker batches them onto the TPU kernels and
    replies with a validity bitmask.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..core.crypto.keys import PublicKey
from ..core.serialization.codec import register_adapter
from ..core.transactions.ledger import LedgerTransaction

VERIFICATION_REQUESTS_QUEUE_NAME = "verifier.requests"
VERIFICATION_RESPONSES_QUEUE_NAME_PREFIX = "verifier.responses."


@dataclass(frozen=True)
class VerificationRequest:
    verification_id: int
    transaction: LedgerTransaction
    response_address: str


@dataclass(frozen=True)
class VerificationResponse:
    verification_id: int
    error: Optional[str]  # None = verified OK


@dataclass(frozen=True)
class SignatureBatchRequest:
    verification_id: int
    items: Tuple[Tuple[PublicKey, bytes, bytes], ...]  # (key, sig, content)
    response_address: str


@dataclass(frozen=True)
class SignatureBatchResponse:
    verification_id: int
    valid: Tuple[bool, ...]  # positionally aligned with request items
    error: Optional[str] = None  # worker-side failure (not a bad signature)


register_adapter(
    VerificationRequest, "VerificationRequest",
    lambda r: {
        "id": r.verification_id, "tx": r.transaction,
        "reply": r.response_address,
    },
    lambda d: VerificationRequest(d["id"], d["tx"], d["reply"]),
)
register_adapter(
    VerificationResponse, "VerificationResponse",
    lambda r: {"id": r.verification_id, "error": r.error},
    lambda d: VerificationResponse(d["id"], d["error"]),
)
register_adapter(
    SignatureBatchRequest, "SignatureBatchRequest",
    lambda r: {
        "id": r.verification_id,
        "items": [list(t) for t in r.items],
        "reply": r.response_address,
    },
    lambda d: SignatureBatchRequest(
        d["id"], tuple(tuple(t) for t in d["items"]), d["reply"]
    ),
)
register_adapter(
    SignatureBatchResponse, "SignatureBatchResponse",
    lambda r: {
        "id": r.verification_id, "valid": [bool(v) for v in r.valid],
        "error": r.error,
    },
    lambda d: SignatureBatchResponse(d["id"], tuple(d["valid"]), d["error"]),
)

"""Demobench: interactive local-network launcher (reference
`tools/demobench/` — the JavaFX desktop app that spawns node + webserver
processes is rebuilt as a terminal tool on the driver DSL).

Usage:
  python -m corda_tpu.tools.demobench [--base-dir DIR]
Commands:
  add NAME [--notary] [--web]   spawn a node (first node becomes the
                                network-map directory; later nodes join it)
  list                          show running processes + endpoints
  explorer NAME                 open the explorer REPL against a node
  kill NAME                     terminate one node
  quit                          shut everything down
A scripted profile can be piped on stdin.
"""
from __future__ import annotations

import shlex
import sys
import tempfile
import threading
from typing import Dict, Optional

from ..testing.driver import Driver, NodeHandle, free_port
from ..utils.miniweb import MiniWebServer


class DemoBench:
    def __init__(self, base_dir: Optional[str] = None, out=None):
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="demobench-")
        self.driver = Driver(self.base_dir, jax_platform="cpu")
        self.nodes: Dict[str, NodeHandle] = {}
        self.webs: Dict[str, object] = {}
        self.meta: Dict[str, dict] = {}  # name -> {notary, network_map, web_port}
        self._map_address: Optional[str] = None
        self.out = out or sys.stdout
        #: fleet mutations come from the REPL thread OR web handler
        #: threads (the --web panel). Two locks: _spawn_lock serializes
        #: the seconds-long mutations (add/kill) against each other;
        #: _state_lock guards only the dict snapshots, so status reads
        #: never block behind a node boot.
        self._spawn_lock = threading.RLock()
        self._state_lock = threading.Lock()

    def _p(self, text: str) -> None:
        self.out.write(text + "\n")

    # -- commands ------------------------------------------------------------

    def add(self, name: str, notary: bool = False, web: bool = False) -> NodeHandle:
        with self._spawn_lock:
            with self._state_lock:
                if name in self.nodes:
                    raise ValueError(f"node {name!r} already exists")
                is_map = self._map_address is None
            legal = name if name.startswith("O=") else f"O={name},L=Demo,C=GB"
            conf = {
                "my_legal_name": legal,
                "broker_port": free_port(),
                "rpc_users": [
                    {"username": "admin", "password": "admin",
                     "permissions": ["ALL"]}
                ],
            }
            if notary:
                conf["notary_type"] = "validating"
            if is_map:
                conf["network_map_service"] = True
            else:
                conf["network_map"] = self._map_address
            # the boot itself runs WITHOUT the state lock: status reads
            # (the panel polls every 2.5s) must not block behind it
            handle = self.driver.start_node(conf, name=name.replace(" ", "-"))
            with self._state_lock:
                if is_map:
                    self._map_address = f"127.0.0.1:{handle.broker_port}"
                self.nodes[name] = handle
                self.meta[name] = {
                    "notary": notary, "network_map": is_map, "web_port": None
                }
            self._p(f"node {name} up: broker 127.0.0.1:{handle.broker_port}"
                    + (" [notary]" if notary else "")
                    + (" [network-map]" if is_map else ""))
            if web:
                self.start_web(name)
            return handle

    def start_web(self, name: str):
        with self._spawn_lock:
            with self._state_lock:
                handle = self.nodes[name]
            web_port = free_port()
            web = self.driver._spawn(
                [
                    "-m", "corda_tpu.webserver",
                    "--connect", f"127.0.0.1:{handle.broker_port}",
                    "--port", str(web_port),
                ],
                name=f"web-{name}",
            )
            from ..testing.driver import _wait_for

            _wait_for(
                lambda: "webserver ready" in web.log() or not web.alive(),
                timeout=60, what=f"webserver for {name}",
            )
            for line in web.log().splitlines():
                if "webserver ready" in line:
                    self._p(f"  {line.strip()}")
            with self._state_lock:
                self.webs[name] = web
                if name in self.meta:
                    self.meta[name]["web_port"] = web_port
            return web

    def fleet_status(self) -> dict:
        """JSON-shaped fleet snapshot for the web panel."""
        with self._state_lock:
            return {
                "base_dir": self.base_dir,
                "nodes": [
                    {
                        "name": name,
                        "alive": h.alive(),
                        "broker_port": h.broker_port,
                        **self.meta.get(
                            name,
                            {"notary": False, "network_map": False,
                             "web_port": None},
                        ),
                    }
                    for name, h in self.nodes.items()
                ],
            }

    def node_log(self, name: str, tail: int = 200) -> str:
        with self._state_lock:
            handle = self.nodes[name]
        lines = handle.log().splitlines()
        return "\n".join(lines[-tail:])

    def list(self) -> None:
        for name, h in self.nodes.items():
            status = "up" if h.alive() else "DEAD"
            self._p(f"  {name:<20} {status} broker=127.0.0.1:{h.broker_port}")
        for name, w in self.webs.items():
            self._p(f"  web:{name:<16} {'up' if w.alive() else 'DEAD'}")

    def explorer(self, name: str) -> None:
        from .explorer import Explorer

        handle = self.nodes[name]
        client = handle.rpc()
        conn = client.start("admin", "admin")
        try:
            Explorer(conn.proxy, out=self.out).repl()
        finally:
            conn.close()
            client.close()

    def kill(self, name: str) -> None:
        with self._spawn_lock:
            with self._state_lock:
                handle = self.nodes.pop(name, None)
                self.meta.pop(name, None)
                web = self.webs.pop(name, None)
            if handle is not None:
                handle.terminate()
                self._p(f"{name} stopped")
            if web is not None:
                web.terminate()

    def shutdown(self) -> None:
        self.driver.shutdown()

    # -- repl ----------------------------------------------------------------

    def repl(self, stream=None) -> None:
        stream = stream or sys.stdin
        interactive = stream is sys.stdin and stream.isatty()
        if interactive:
            self._p("demobench — add NAME [--notary] [--web] | list | "
                    "explorer NAME | kill NAME | quit")
        for line in stream:
            argv = shlex.split(line)
            if not argv:
                continue
            cmd, *rest = argv
            try:
                if cmd == "add":
                    name = rest[0]
                    self.add(
                        name,
                        notary="--notary" in rest,
                        web="--web" in rest,
                    )
                elif cmd == "list":
                    self.list()
                elif cmd == "explorer":
                    self.explorer(rest[0])
                elif cmd == "kill":
                    self.kill(rest[0])
                elif cmd in ("quit", "exit"):
                    break
                else:
                    self._p(f"unknown command {cmd!r}")
            except Exception as exc:
                self._p(f"error: {exc}")


class FleetWebServer(MiniWebServer):
    """The demobench fleet panel (reference `tools/demobench/`'s JavaFX
    shell as a browser page): spawn/stop nodes and tail their logs over
    a small JSON API; the page itself is webserver/static/fleet.html.
    Built on the shared MiniWebServer scaffold (utils/miniweb.py)."""

    pages = {"/": "fleet.html", "/index.html": "fleet.html"}

    def __init__(self, bench: DemoBench, host: str = "127.0.0.1",
                 port: int = 0):
        self.bench = bench
        super().__init__(host=host, port=port)

    def handle(self, method, path, query, body):
        bench = self.bench
        if method == "GET" and path == "/fleet":
            return 200, bench.fleet_status()
        if method == "GET" and path == "/fleet/logs":
            name = query.get("name", "")
            try:
                tail = int(query.get("tail", "200"))
            except ValueError:
                return 400, {"error": "tail must be an integer"}
            try:
                return 200, {"log": bench.node_log(name, tail)}
            except KeyError:
                return 404, {"error": f"no node {name!r}"}
        if method == "POST" and path == "/fleet/add":
            name = str(body.get("name", "")).strip()
            if not name:
                return 400, {"error": "name required"}
            handle = bench.add(
                name, notary=bool(body.get("notary")),
                web=bool(body.get("web")),
            )
            return 200, {"name": name, "broker_port": handle.broker_port}
        if method == "POST" and path == "/fleet/kill":
            name = str(body.get("name", ""))
            with bench._state_lock:
                known = name in bench.nodes
            if not known:
                return 404, {"error": f"no node {name!r}"}
            bench.kill(name)
            return 200, {"stopped": name}
        return 404, {"error": f"no route {path}"}


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="corda_tpu.tools.demobench")
    ap.add_argument("--base-dir")
    ap.add_argument(
        "--web", type=int, metavar="PORT", default=None,
        help="serve the fleet panel GUI on this port (0 = ephemeral) "
             "instead of the terminal REPL",
    )
    args = ap.parse_args(argv)
    bench = DemoBench(base_dir=args.base_dir)
    try:
        if args.web is not None:
            server = FleetWebServer(bench, port=args.web)
            print(
                f"demobench fleet panel ready at "
                f"http://127.0.0.1:{server.port}/",
                flush=True,
            )
            try:
                import time

                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                server.stop()
        else:
            bench.repl()
    finally:
        bench.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())

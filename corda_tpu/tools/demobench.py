"""Demobench: interactive local-network launcher (reference
`tools/demobench/` — the JavaFX desktop app that spawns node + webserver
processes is rebuilt as a terminal tool on the driver DSL).

Usage:
  python -m corda_tpu.tools.demobench [--base-dir DIR]
Commands:
  add NAME [--notary] [--web]   spawn a node (first node becomes the
                                network-map directory; later nodes join it)
  list                          show running processes + endpoints
  explorer NAME                 open the explorer REPL against a node
  kill NAME                     terminate one node
  quit                          shut everything down
A scripted profile can be piped on stdin.
"""
from __future__ import annotations

import shlex
import sys
import tempfile
from typing import Dict, Optional

from ..testing.driver import Driver, NodeHandle, free_port


class DemoBench:
    def __init__(self, base_dir: Optional[str] = None, out=None):
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="demobench-")
        self.driver = Driver(self.base_dir, jax_platform="cpu")
        self.nodes: Dict[str, NodeHandle] = {}
        self.webs: Dict[str, object] = {}
        self._map_address: Optional[str] = None
        self.out = out or sys.stdout

    def _p(self, text: str) -> None:
        self.out.write(text + "\n")

    # -- commands ------------------------------------------------------------

    def add(self, name: str, notary: bool = False, web: bool = False) -> NodeHandle:
        legal = name if name.startswith("O=") else f"O={name},L=Demo,C=GB"
        conf = {
            "my_legal_name": legal,
            "broker_port": free_port(),
            "rpc_users": [
                {"username": "admin", "password": "admin", "permissions": ["ALL"]}
            ],
        }
        if notary:
            conf["notary_type"] = "validating"
        if self._map_address is None:
            conf["network_map_service"] = True
        else:
            conf["network_map"] = self._map_address
        handle = self.driver.start_node(conf, name=name.replace(" ", "-"))
        if self._map_address is None:
            self._map_address = f"127.0.0.1:{handle.broker_port}"
        self.nodes[name] = handle
        self._p(f"node {name} up: broker 127.0.0.1:{handle.broker_port}"
                + (" [notary]" if notary else "")
                + (" [network-map]" if conf.get("network_map_service") else ""))
        if web:
            self.start_web(name)
        return handle

    def start_web(self, name: str):
        handle = self.nodes[name]
        web = self.driver._spawn(
            [
                "-m", "corda_tpu.webserver",
                "--connect", f"127.0.0.1:{handle.broker_port}",
                "--port", str(free_port()),
            ],
            name=f"web-{name}",
        )
        from ..testing.driver import _wait_for

        _wait_for(
            lambda: "webserver ready" in web.log() or not web.alive(),
            timeout=60, what=f"webserver for {name}",
        )
        for line in web.log().splitlines():
            if "webserver ready" in line:
                self._p(f"  {line.strip()}")
        self.webs[name] = web
        return web

    def list(self) -> None:
        for name, h in self.nodes.items():
            status = "up" if h.alive() else "DEAD"
            self._p(f"  {name:<20} {status} broker=127.0.0.1:{h.broker_port}")
        for name, w in self.webs.items():
            self._p(f"  web:{name:<16} {'up' if w.alive() else 'DEAD'}")

    def explorer(self, name: str) -> None:
        from .explorer import Explorer

        handle = self.nodes[name]
        client = handle.rpc()
        conn = client.start("admin", "admin")
        try:
            Explorer(conn.proxy, out=self.out).repl()
        finally:
            conn.close()
            client.close()

    def kill(self, name: str) -> None:
        handle = self.nodes.pop(name, None)
        if handle is not None:
            handle.terminate()
            self._p(f"{name} stopped")
        web = self.webs.pop(name, None)
        if web is not None:
            web.terminate()

    def shutdown(self) -> None:
        self.driver.shutdown()

    # -- repl ----------------------------------------------------------------

    def repl(self, stream=None) -> None:
        stream = stream or sys.stdin
        interactive = stream is sys.stdin and stream.isatty()
        if interactive:
            self._p("demobench — add NAME [--notary] [--web] | list | "
                    "explorer NAME | kill NAME | quit")
        for line in stream:
            argv = shlex.split(line)
            if not argv:
                continue
            cmd, *rest = argv
            try:
                if cmd == "add":
                    name = rest[0]
                    self.add(
                        name,
                        notary="--notary" in rest,
                        web="--web" in rest,
                    )
                elif cmd == "list":
                    self.list()
                elif cmd == "explorer":
                    self.explorer(rest[0])
                elif cmd == "kill":
                    self.kill(rest[0])
                elif cmd in ("quit", "exit"):
                    break
                else:
                    self._p(f"unknown command {cmd!r}")
            except Exception as exc:
                self._p(f"error: {exc}")


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="corda_tpu.tools.demobench")
    ap.add_argument("--base-dir")
    args = ap.parse_args(argv)
    bench = DemoBench(base_dir=args.base_dir)
    try:
        bench.repl()
    finally:
        bench.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())

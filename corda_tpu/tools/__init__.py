"""Operator tools (reference `tools/`): explorer, demobench, cordform,
loadtest (loadtest lives in corda_tpu.loadtest)."""

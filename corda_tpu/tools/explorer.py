"""Ledger explorer (reference `tools/explorer/` — the JavaFX GUI's
capabilities as a terminal tool over RPC: node info, network map, vault
browsing with criteria paging, cash positions, transaction feed, flow
start/watch, attachments, metrics).

Usage:
  python -m corda_tpu.tools.explorer --connect HOST:PORT [--user U --password P] CMD ...
  CMD: info | network | vault [CONTRACT] | balances | txs | flows |
       start FLOW [JSON_ARGS] | metrics | attachments PUT file | attachments GET hash
  With no CMD an interactive shell opens (same commands, plus watch/quit).
"""
from __future__ import annotations

import json
import shlex
import sys
from typing import List, Optional

from ..client.jackson import to_json
from ..client.models import ContractStateModel, NetworkIdentityModel


def _short(name: str) -> str:
    for part in str(name).split(","):
        if part.startswith("O="):
            return part[2:]
    return str(name)


class Explorer:
    def __init__(self, proxy, out=None):
        self.proxy = proxy
        self.out = out or sys.stdout

    def _p(self, text: str = "") -> None:
        self.out.write(text + "\n")

    # -- commands ------------------------------------------------------------

    def info(self) -> None:
        me = self.proxy.node_info()
        self._p(f"identity : {me.name}")
        self._p(f"key      : {me.owning_key.encoded.hex()[:32]}…")
        self._p(f"time     : {self.proxy.current_node_time():.3f}")

    def network(self) -> None:
        model = NetworkIdentityModel(self.proxy)
        self._p(f"{len(model.parties)} peers:")
        notary_names = {n.name for n in model.notaries.items}
        for p in model.parties.items:
            tag = "  [notary]" if p.name in notary_names else ""
            self._p(f"  {_short(p.name):<28} {p.name}{tag}")

    def vault(self, contract: Optional[str] = None, page: int = 1) -> None:
        from ..node.vault_query import PageSpecification, VaultQueryCriteria

        criteria = VaultQueryCriteria(
            contract_names=(contract,) if contract else ()
        )
        result = self.proxy.vault_query_by(
            criteria, PageSpecification(page_number=page, page_size=25), None
        )
        self._p(
            f"page {result.page_number} of {result.total_states_available} states"
        )
        for sr in result.states:
            data = sr.state.data
            self._p(f"  {sr.ref.txhash.bytes.hex()[:16]}…[{sr.ref.index}] "
                    f"{type(data).__name__}: {data}")

    def balances(self) -> None:
        model = ContractStateModel(self.proxy)
        if not model.balances.value:
            self._p("no cash positions")
        for ccy, qty in sorted(model.balances.value.items()):
            self._p(f"  {ccy}: {qty / 100:,.2f}")
        model.close()

    def txs(self) -> None:
        feed = self.proxy.verified_transactions_feed()
        self._p(f"{len(feed.snapshot)} verified transactions (snapshot)")
        for stx in feed.snapshot[-20:]:
            self._p(f"  {stx.id.bytes.hex()[:24]}… sigs={len(stx.sigs)}")

    def flows(self) -> None:
        feed = self.proxy.state_machines_feed()
        self._p(f"{len(feed.snapshot)} flows in flight")
        for info in feed.snapshot:
            self._p(f"  {info.flow_id} {info.flow_name}")

    def start(self, flow_name: str, json_args: str = "[]") -> None:
        args = json.loads(json_args)
        if isinstance(args, dict):
            flow_id = self.proxy.start_flow_dynamic(flow_name, **args)
        else:
            flow_id = self.proxy.start_flow_dynamic(flow_name, *args)
        self._p(f"started {flow_id}")
        try:
            result = self.proxy.flow_result(flow_id, timeout=30)
            self._p(f"result: {to_json(result)}")
        except Exception as exc:
            self._p(f"flow error: {exc}")

    def metrics(self) -> None:
        self._p(json.dumps(self.proxy.node_metrics(), indent=2, default=str))

    def attachments(self, op: str, arg: str) -> None:
        from ..core.crypto.secure_hash import SecureHash

        if op.upper() == "PUT":
            with open(arg, "rb") as fh:
                att_id = self.proxy.upload_attachment(fh.read())
            self._p(f"uploaded {att_id.bytes.hex()}")
        else:
            data = self.proxy.open_attachment(
                SecureHash(bytes.fromhex(arg))
            )
            if data is None:
                self._p("not found")
            else:
                sys.stdout.buffer.write(data)

    # -- dispatch ------------------------------------------------------------

    COMMANDS = {
        "info", "network", "vault", "balances", "txs", "flows", "start",
        "metrics", "attachments",
    }

    def run_command(self, argv: List[str]) -> bool:
        if not argv:
            return True
        cmd, *rest = argv
        if cmd in ("quit", "exit"):
            return False
        if cmd not in self.COMMANDS:
            self._p(f"unknown command {cmd!r}; one of {sorted(self.COMMANDS)}")
            return True
        try:
            getattr(self, cmd)(*rest)
        except Exception as exc:
            self._p(f"error: {exc}")
        return True

    def repl(self) -> None:
        self._p("corda_tpu explorer — commands: "
                + " ".join(sorted(self.COMMANDS)) + " quit")
        while True:
            try:
                line = input("explorer> ")
            except EOFError:
                break
            if not self.run_command(shlex.split(line)):
                break


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="corda_tpu.tools.explorer")
    ap.add_argument("--connect", required=True, help="node broker HOST:PORT")
    ap.add_argument("--user", default="admin")
    ap.add_argument("--password", default="admin")
    ap.add_argument("--cordapps", default="corda_tpu.finance.flows",
                    help="comma-separated modules to import for codecs")
    ap.add_argument("command", nargs="*", help="one-shot command")
    args = ap.parse_args(argv)

    import importlib

    for mod in args.cordapps.split(","):
        if mod:
            importlib.import_module(mod)

    from ..messaging.net import RemoteBroker
    from ..rpc.client import CordaRPCClient

    host, port_s = args.connect.rsplit(":", 1)
    client = CordaRPCClient(RemoteBroker(host, int(port_s)))
    conn = client.start(args.user, args.password)
    try:
        ex = Explorer(conn.proxy)
        if args.command:
            ex.run_command(args.command)
        else:
            ex.repl()
    finally:
        conn.close()
        client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())

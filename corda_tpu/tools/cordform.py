"""Cordform / deployNodes equivalent: declarative multi-node deployment
descriptor -> on-disk node directories with configs and run scripts
(reference `gradle-plugins/cordformation/.../Cordform.groovy`, `Node.groovy`
— the Gradle DSL becomes a plain data structure; the generated artifact is
a directory tree any orchestrator (shell, systemd, k8s initContainer) can
launch, plus a runnodes script like the reference's).

Descriptor example (see samples' deploy specs):
    {
      "nodes": [
        {"name": "O=Notary,L=Zurich,C=CH", "notary": "validating",
         "network_map_service": true},
        {"name": "O=Bank A,L=London,C=GB", "web": true},
        {"name": "O=Bank B,L=New York,C=US",
         "cordapps": ["corda_tpu.finance.flows"]}
      ],
      "tls": false
    }
"""
from __future__ import annotations

import hashlib
import json
import os
import stat
from typing import Dict, List, Optional

from ..testing.driver import free_port

RUNNODES = """#!/bin/sh
# Launch every deployed node (reference cordformation's runnodes script).
# Each node logs to <node-dir>/node.log; PIDs land in <node-dir>/node.pid.
cd "$(dirname "$0")"
for d in */; do
  [ -f "$d/node.conf" ] || continue
  ( cd "$d" && exec python -m corda_tpu.node . > node.log 2>&1 & echo $! > node.pid )
  echo "started $d (pid $(cat $d/node.pid))"
done
"""


def _dir_name(legal_name: str) -> str:
    for part in legal_name.split(","):
        if part.startswith("O="):
            return part[2:].strip().replace(" ", "")
    return legal_name.replace(" ", "")


def _expand_raft_clusters(nodes: List[Dict]) -> List[Dict]:
    """A node entry with notary "raft-validating"/"raft-simple"/"bft" and
    "cluster_size": N expands into N member nodes sharing a
    raft_cluster / bft_cluster block (reference: cordformation's
    NotaryCluster DSL + ServiceIdentityGenerator run at deploy time).
    Member identities use deterministic entropies so every member
    derives the same composite cluster identity locally."""
    out: List[Dict] = []
    for n in nodes:
        notary = n.get("notary", "")
        is_bft = notary == "bft"
        if not (isinstance(notary, str)
                and (notary.startswith("raft") or is_bft)):
            out.append(n)
            continue
        # a cluster notary ALWAYS expands (a missing/1 cluster_size
        # becomes a single-member raft cluster) — passing the entry
        # through unexpanded would materialise a node that dies at boot
        # for want of a cluster block. BFT needs n >= 3f+1 with f >= 1.
        size = max(1, int(n.get("cluster_size", 1) or 1))
        if is_bft and size < 4:
            raise ValueError(
                f"bft notary {n['name']!r} needs cluster_size >= 4 "
                f"(got {size})"
            )
        cluster_name = n["name"]
        # default entropy base derives from the CLUSTER NAME: two clusters
        # in one spec must not share member keypairs (identical composite
        # identities under different names would break signature
        # attribution)
        default_base = 880_000 + (
            int.from_bytes(
                hashlib.sha256(cluster_name.encode()).digest()[:4], "big"
            )
            << 8
        )
        base_entropy = int(n.get("cluster_entropy_base", default_base))
        members = []
        seeds = []
        for i in range(size):
            parts = [p.strip() for p in cluster_name.split(",")]
            parts = [
                f"O={p[2:]} {i}" if p.startswith("O=") else p for p in parts
            ]
            member = {"name": ",".join(parts), "entropy": base_entropy + i}
            if is_bft:
                # per-member RANDOM replica signing key, generated at
                # deploy time: the private seed goes ONLY into that
                # member's own config; the cluster block shares publics
                from ..core.crypto import ed25519_math as _edm

                seed = os.urandom(32)
                seeds.append(seed)
                member["signing_pub"] = _edm.public_from_seed(seed).hex()
            members.append(member)
        for i, member in enumerate(members):
            entry = {
                k: v for k, v in n.items()
                # per-node resources must NOT be cloned across members: a
                # pinned broker_port would collide on every member but
                # one, and a shared advertised_address would route every
                # member's traffic through one interposed hop
                if k not in (
                    "name", "cluster_size", "cluster_entropy_base",
                    "broker_port", "web", "advertised_address",
                )
            }
            entry["name"] = member["name"]
            entry["identity_entropy"] = member["entropy"]
            cluster_block = {
                "name": cluster_name,
                "index": i,
                "members": members,
            }
            if is_bft:
                cluster_block["signing_seed"] = seeds[i].hex()
                if n.get("view_timeout") is not None:
                    vt = float(n["view_timeout"])
                    if vt <= 0:
                        raise ValueError(
                            f"bft notary {n['name']!r}: view_timeout must "
                            f"be > 0 (got {vt})"
                        )
                    cluster_block["view_timeout"] = vt
            entry["bft_cluster" if is_bft else "raft_cluster"] = cluster_block
            out.append(entry)
    return out


def deploy_nodes(spec: Dict, out_dir: str) -> List[Dict]:
    """Materialise the descriptor under out_dir; returns the resolved
    per-node configs (with allocated ports and network-map wiring)."""
    nodes = _expand_raft_clusters(spec.get("nodes", []))
    if not nodes:
        raise ValueError("descriptor has no nodes")
    os.makedirs(out_dir, exist_ok=True)

    # The first node with network_map_service (or simply the first node)
    # becomes the directory node everyone else points at.
    map_idx = next(
        (i for i, n in enumerate(nodes) if n.get("network_map_service")), 0
    )
    resolved: List[Dict] = []
    map_address: Optional[str] = None
    shared_certs = os.path.abspath(os.path.join(out_dir, "certificates"))

    for i, n in enumerate(nodes):
        port = n.get("broker_port") or free_port()
        conf = {
            "my_legal_name": n["name"],
            "broker_host": n.get("host", "127.0.0.1"),
            "broker_port": port,
            "rpc_users": n.get(
                "rpc_users",
                [{"username": "admin", "password": "admin",
                  "permissions": ["ALL"]}],
            ),
            "cordapps": n.get("cordapps", ["corda_tpu.finance.flows"]),
        }
        if n.get("notary"):
            conf["notary_type"] = n["notary"]
        if n.get("verifier_type"):
            conf["verifier_type"] = n["verifier_type"]
        if n.get("advertised_address"):
            # peers reach this node through an interposed hop (port
            # forward / the soak's partition proxy) instead of the bind
            # address
            conf["advertised_address"] = str(n["advertised_address"])
        for adm_key in ("admission_rate", "admission_burst",
                        "admission_max_flows"):
            if n.get(adm_key) is not None:
                conf[adm_key] = n[adm_key]
        if n.get("domain") is not None:
            # multi-domain federation (docs/robustness.md §6): pins the
            # node's trust segment; its map fetches become domain-scoped
            conf["domain"] = str(n["domain"])
        if n.get("gateway"):
            conf["gateway"] = True
        if n.get("shards") is not None:
            conf["shards"] = int(n["shards"])
        if n.get("node_workers") is not None:
            conf["node_workers"] = int(n["node_workers"])
        if n.get("ops_port") is not None:
            conf["ops_port"] = int(n["ops_port"])
        if n.get("identity_entropy") is not None:
            conf["identity_entropy"] = n["identity_entropy"]
        if n.get("raft_cluster"):
            conf["raft_cluster"] = n["raft_cluster"]
        if n.get("bft_cluster"):
            conf["bft_cluster"] = n["bft_cluster"]
        if n.get("cluster_route_refresh") is not None:
            conf["cluster_route_refresh"] = float(n["cluster_route_refresh"])
        if spec.get("tls"):
            conf["tls"] = True
            conf["certificates_dir"] = shared_certs
        if i == map_idx:
            conf["network_map_service"] = True
            map_address = f"{conf['broker_host']}:{port}"
        else:
            conf["network_map"] = map_address
        if n.get("jax_platform") or spec.get("jax_platform"):
            conf["jax_platform"] = n.get("jax_platform") or spec["jax_platform"]
        node_dir = os.path.join(out_dir, _dir_name(n["name"]))
        os.makedirs(node_dir, exist_ok=True)
        with open(os.path.join(node_dir, "node.conf"), "w") as fh:
            json.dump(conf, fh, indent=2)
        resolved.append({**conf, "dir": node_dir, "web": bool(n.get("web"))})

    # Nodes registered later must still find the directory node: rewrite
    # configs written before the map node allocated its port.
    for conf in resolved:
        if not conf.get("network_map_service") and conf.get("network_map") is None:
            conf["network_map"] = map_address
            with open(os.path.join(conf["dir"], "node.conf"), "w") as fh:
                json.dump(
                    {k: v for k, v in conf.items() if k not in ("dir", "web")},
                    fh, indent=2,
                )

    script = os.path.join(out_dir, "runnodes")
    with open(script, "w") as fh:
        fh.write(RUNNODES)
    os.chmod(script, os.stat(script).st_mode | stat.S_IEXEC)
    return resolved


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="corda_tpu.tools.cordform")
    ap.add_argument("descriptor", help="JSON deployment descriptor")
    ap.add_argument("out_dir")
    args = ap.parse_args(argv)
    with open(args.descriptor) as fh:
        spec = json.load(fh)
    resolved = deploy_nodes(spec, args.out_dir)
    for conf in resolved:
        print(f"{conf['dir']}: {conf['my_legal_name']} "
              f"broker={conf['broker_host']}:{conf['broker_port']}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

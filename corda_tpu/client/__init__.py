"""corda_tpu.client: client-side libraries (reference `client/*`).

  * rpc    — corda_tpu.rpc.CordaRPCClient (lives in the rpc package)
  * jackson — JSON mapping for core types + string flow-start parsing
"""

"""JSON support for core types (reference
`client/jackson/src/main/kotlin/net/corda/jackson/JacksonSupport.kt` +
`StringToMethodCallParser` used by the shell and webserver).

`to_json` / `from_json` round-trip the common API types;
`parse_flow_start` parses shell-style invocations like
    "CashIssueFlow amount: 100 USD, recipient: O=Alice,L=London,C=GB"
"""
from __future__ import annotations

import json
import re
from typing import Any, Callable, Dict, Optional

from ..core.contracts.amount import Amount, Issued
from ..core.contracts.structures import StateAndRef, StateRef, TransactionState
from ..core.crypto.keys import SchemePublicKey
from ..core.crypto.secure_hash import SecureHash
from ..core.identity import AnonymousParty, Party, PartyAndReference


def _encode(value: Any) -> Any:
    if isinstance(value, SecureHash):
        return {"_type": "SecureHash", "value": value.bytes.hex().upper()}
    if isinstance(value, Party):
        return {
            "_type": "Party", "name": value.name,
            "key": value.owning_key.encoded.hex(),
            "scheme": value.owning_key.scheme_code_name,
        }
    if isinstance(value, AnonymousParty):
        return {
            "_type": "AnonymousParty",
            "key": value.owning_key.encoded.hex(),
            "scheme": value.owning_key.scheme_code_name,
        }
    if isinstance(value, SchemePublicKey):
        return {
            "_type": "PublicKey", "key": value.encoded.hex(),
            "scheme": value.scheme_code_name,
        }
    if isinstance(value, PartyAndReference):
        return {
            "_type": "PartyAndReference",
            "party": _encode(value.party),
            "reference": value.reference.hex(),
        }
    if isinstance(value, Issued):
        return {
            "_type": "Issued", "issuer": _encode(value.issuer),
            "product": _encode(value.product),
        }
    if isinstance(value, Amount):
        return {
            "_type": "Amount", "quantity": value.quantity,
            "token": _encode(value.token),
        }
    if isinstance(value, StateRef):
        return {
            "_type": "StateRef", "txhash": value.txhash.bytes.hex().upper(),
            "index": value.index,
        }
    if isinstance(value, StateAndRef):
        return {
            "_type": "StateAndRef", "ref": _encode(value.ref),
            "state": _encode(value.state),
        }
    if isinstance(value, TransactionState):
        return {
            "_type": "TransactionState",
            "data": _encode_state_data(value.data),
            "notary": _encode(value.notary),
        }
    if isinstance(value, bytes):
        return {"_type": "bytes", "value": value.hex()}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _encode(v) for k, v in value.items()}
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return _encode_state_data(value)


def _encode_state_data(state) -> Any:
    import dataclasses

    if dataclasses.is_dataclass(state):
        return {
            "_type": type(state).__name__,
            **{
                f.name: _encode(getattr(state, f.name))
                for f in dataclasses.fields(state)
            },
        }
    return repr(state)


_DECODERS: Dict[str, Callable[[dict], Any]] = {
    "SecureHash": lambda d: SecureHash(bytes.fromhex(d["value"])),
    "Party": lambda d: Party(
        d["name"], SchemePublicKey(d["scheme"], bytes.fromhex(d["key"]))
    ),
    "AnonymousParty": lambda d: AnonymousParty(
        SchemePublicKey(d["scheme"], bytes.fromhex(d["key"]))
    ),
    "PublicKey": lambda d: SchemePublicKey(
        d["scheme"], bytes.fromhex(d["key"])
    ),
    "PartyAndReference": lambda d: PartyAndReference(
        from_json_value(d["party"]), bytes.fromhex(d["reference"])
    ),
    "Issued": lambda d: Issued(
        from_json_value(d["issuer"]), from_json_value(d["product"])
    ),
    "Amount": lambda d: Amount(d["quantity"], from_json_value(d["token"])),
    "StateRef": lambda d: StateRef(
        SecureHash(bytes.fromhex(d["txhash"])), d["index"]
    ),
    "bytes": lambda d: bytes.fromhex(d["value"]),
}


def from_json_value(value: Any) -> Any:
    if isinstance(value, dict):
        t = value.get("_type")
        if t in _DECODERS:
            return _DECODERS[t](value)
        return {k: from_json_value(v) for k, v in value.items() if k != "_type"}
    if isinstance(value, list):
        return [from_json_value(v) for v in value]
    return value


def to_json(value: Any, indent: Optional[int] = None) -> str:
    return json.dumps(_encode(value), indent=indent)


def from_json(text: str) -> Any:
    return from_json_value(json.loads(text))


# ---------------------------------------------------------------------------
# Shell-style flow start parsing (StringToMethodCallParser equivalent)
# ---------------------------------------------------------------------------

_AMOUNT_RE = re.compile(r"^(\d+(?:\.\d+)?)\s+([A-Z]{3})$")


def parse_argument(text: str, identity_lookup: Optional[Callable] = None) -> Any:
    """Parse one shell argument: '100 USD' -> Amount, 'O=..' -> Party (via
    identity_lookup), int/float/str otherwise."""
    text = text.strip()
    m = _AMOUNT_RE.match(text)
    if m:
        number, currency = m.groups()
        return Amount.from_decimal(float(number), currency)
    if text.startswith("O=") and identity_lookup is not None:
        party = identity_lookup(text)
        if party is None:
            raise ValueError(f"unknown party {text!r}")
        return party
    if re.fullmatch(r"0x(?:[0-9A-Fa-f]{2})+", text):
        # hex literal -> bytes (OpaqueBytes-style args, e.g. issuer refs)
        return bytes.fromhex(text[2:])
    if re.fullmatch(r"-?\d+", text):
        return int(text)
    if re.fullmatch(r"-?\d+\.\d+", text):
        return float(text)
    if text.lower() in ("true", "false"):
        return text.lower() == "true"
    return text


def parse_flow_start(
    text: str, identity_lookup: Optional[Callable] = None
):
    """'FlowName key: value, key: value' -> (flow_name, kwargs);
    'FlowName v1, v2' -> (flow_name, [args])."""
    text = text.strip()
    if " " not in text:
        return text, []
    flow_name, rest = text.split(" ", 1)
    if ":" in rest:
        kwargs = {}
        for part in _split_top_level(rest):
            key, _, value = part.partition(":")
            kwargs[key.strip()] = parse_argument(value, identity_lookup)
        return flow_name, kwargs
    return flow_name, [
        parse_argument(p, identity_lookup) for p in _split_top_level(rest)
    ]


def _split_top_level(text: str):
    """Split on commas that are not inside an X.500 name (O=..,L=..,C=..):
    a chunk like 'L=London' (key=value, no colon) continues the previous
    argument rather than starting a new one."""
    merged: list = []
    for chunk in text.split(","):
        if merged and re.match(r"^\s*[A-Z]{1,2}=[^:]*$", chunk) and "=" in merged[-1]:
            merged[-1] += "," + chunk
        else:
            merged.append(chunk)
    return [p for p in merged if p.strip()]

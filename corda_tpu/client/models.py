"""Observable view models over the RPC surface (reference `client/jfx/` —
`NodeMonitorModel`, `ContractStateModel`, `NetworkIdentityModel` and the
observable-collection utilities in `client/jfx/src/main/kotlin/net/corda/
client/jfx/utils/`). The JavaFX bindings are GUI plumbing; the *models* —
live, self-maintaining collections derived from RPC feeds — are the
reusable capability, so they are rebuilt here headless: any UI (TUI,
notebook, web) can subscribe.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

from ..utils.observable import DataFeed, Observable, Subscription


# --- observable combinators (reference client/jfx/utils/ObservableUtilities)

def map_observable(source: Observable, fn: Callable[[Any], Any]) -> Observable:
    out = Observable()
    source.subscribe(
        lambda v: out.on_next(fn(v)),
        on_error=out.on_error,
        on_completed=out.on_completed,
    )
    return out


def filter_observable(source: Observable, pred: Callable[[Any], bool]) -> Observable:
    out = Observable()
    source.subscribe(
        lambda v: out.on_next(v) if pred(v) else None,
        on_error=out.on_error,
        on_completed=out.on_completed,
    )
    return out


class ObservableValue:
    """Current value + change stream (reference ObservableValue bindings)."""

    def __init__(self, initial: Any = None):
        self._value = initial
        self.updates: Observable = Observable()
        self._lock = threading.Lock()

    @property
    def value(self) -> Any:
        return self._value

    def set(self, value: Any) -> None:
        with self._lock:
            self._value = value
        self.updates.on_next(value)


class ObservableList:
    """Self-maintaining list fed by an update stream (reference
    ObservableList folds in `client/jfx/utils/`). Mutations notify
    subscribers with the whole list (small, UI-oriented)."""

    def __init__(self):
        self._items: List[Any] = []
        self._lock = threading.Lock()
        self.updates: Observable = Observable()

    def _mutate(self, fn: Callable[[List[Any]], None]) -> None:
        with self._lock:
            fn(self._items)
            snapshot = list(self._items)
        self.updates.on_next(snapshot)

    def append(self, item: Any) -> None:
        self._mutate(lambda xs: xs.append(item))

    def remove_where(self, pred: Callable[[Any], bool]) -> None:
        def do(xs: List[Any]) -> None:
            xs[:] = [x for x in xs if not pred(x)]

        self._mutate(do)

    def replace_where(self, pred: Callable[[Any], bool], item: Any) -> None:
        def do(xs: List[Any]) -> None:
            for i, x in enumerate(xs):
                if pred(x):
                    xs[i] = item
                    return
            xs.append(item)

        self._mutate(do)

    def set_all(self, items: List[Any]) -> None:
        def do(xs: List[Any]) -> None:
            xs[:] = list(items)

        self._mutate(do)

    @property
    def items(self) -> List[Any]:
        with self._lock:
            return list(self._items)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


# --- the models --------------------------------------------------------------

class NodeMonitorModel:
    """Aggregates every RPC feed into live collections (reference
    `client/jfx/.../model/NodeMonitorModel.kt`): in-flight state machines,
    verified transactions, vault updates, progress steps, network map."""

    def __init__(self, ops):
        """ops: CordaRPCOps or an RPC client proxy exposing the same feeds."""
        self.ops = ops
        self.state_machines = ObservableList()      # in-flight only
        self.transactions = ObservableList()        # every verified tx
        self.vault_updates = ObservableList()       # raw update dicts
        self.progress_events = ObservableList()
        self.network_identities = ObservableList()
        self._subs: List[Subscription] = []

        smm_feed: DataFeed = ops.state_machines_feed()
        for info in smm_feed.snapshot:
            self.state_machines.append(info)
        self._subs.append(smm_feed.updates.subscribe(self._on_smm))

        tx_feed: DataFeed = ops.verified_transactions_feed()
        for tx in tx_feed.snapshot:
            self.transactions.append(tx)
        self._subs.append(tx_feed.updates.subscribe(self.transactions.append))

        vault_feed: DataFeed = ops.vault_track()
        self._subs.append(vault_feed.updates.subscribe(self.vault_updates.append))

        for node in ops.network_map_snapshot():
            self.network_identities.append(node)

    def _on_smm(self, info) -> None:
        if getattr(info, "done", False):
            self.state_machines.remove_where(
                lambda x: x.flow_id == info.flow_id
            )
        else:
            self.state_machines.replace_where(
                lambda x: x.flow_id == info.flow_id, info
            )

    def close(self) -> None:
        for sub in self._subs:
            sub.unsubscribe()


class ContractStateModel:
    """Cash-position model (reference `ContractStateModel.kt`): folds vault
    updates into live balances keyed by currency."""

    def __init__(self, ops):
        from ..finance.cash import CashState

        self.ops = ops
        self._cash_cls = CashState
        self.cash_states = ObservableList()
        self.balances = ObservableValue({})  # currency -> minor units
        self._refs: Dict[Any, Any] = {}  # StateRef -> StateAndRef
        feed = ops.vault_track()
        for sr in feed.snapshot:
            if isinstance(sr.state.data, CashState):
                self._refs[sr.ref] = sr
        self._sub = feed.updates.subscribe(self._on_update)
        self._recompute()

    def _on_update(self, update: Dict) -> None:
        changed = False
        for sr in update.get("produced", []):
            if isinstance(sr.state.data, self._cash_cls):
                self._refs[sr.ref] = sr
                changed = True
        for ref in update.get("consumed", []):
            if self._refs.pop(ref, None) is not None:
                changed = True
        if changed:
            self._recompute()

    @staticmethod
    def _currency_of(state) -> str:
        token = state.amount.token
        while not isinstance(token, str):  # unwrap Issued[...[currency]]
            token = getattr(token, "product", str(token))
        return token

    def _recompute(self) -> None:
        totals: Dict[str, int] = defaultdict(int)
        for sr in self._refs.values():
            state = sr.state.data
            totals[self._currency_of(state)] += state.amount.quantity
        self.balances.set(dict(totals))
        self.cash_states.set_all(list(self._refs.values()))

    def close(self) -> None:
        self._sub.unsubscribe()


class NetworkIdentityModel:
    """Peer directory model (reference `NetworkIdentityModel.kt`)."""

    def __init__(self, ops):
        self.ops = ops
        self.parties = ObservableList()
        self.notaries = ObservableList()
        for node in ops.network_map_snapshot():
            self.parties.append(node)
        for notary in ops.notary_identities():
            self.notaries.append(notary)

    def lookup(self, name: str) -> Optional[Any]:
        return next((p for p in self.parties.items if p.name == name), None)

    def refresh(self) -> None:
        self.parties.set_all(self.ops.network_map_snapshot())
        self.notaries.set_all(self.ops.notary_identities())


class ExchangeRateModel:
    """Observable FX conversion (reference `ExchangeRateModel.kt`): a
    pluggable rate source, identity by default, with amount conversion
    for display models."""

    def __init__(self):
        self.exchange_rate = ObservableValue(lambda currency: 1.0)

    def set_rates(self, usd_per_unit: Dict[str, float]) -> None:
        """Install a rate table (currency -> USD per minor unit scale)."""
        table = dict(usd_per_unit)
        self.exchange_rate.set(lambda currency: table.get(currency, 1.0))

    def exchange_amount(self, quantity: int, from_currency: str,
                        to_currency: str) -> int:
        """Convert minor units via the current rate source."""
        rate = self.exchange_rate.value
        usd = quantity * rate(from_currency)
        to_rate = rate(to_currency)
        return int(round(usd / to_rate)) if to_rate else 0


class InputResolution:
    """reference TransactionDataModel.kt:23-31 — one transaction input,
    either still unresolved (its source tx not yet seen) or resolved to
    the producing StateAndRef."""

    __slots__ = ("state_ref", "state_and_ref")

    def __init__(self, state_ref, state_and_ref=None):
        self.state_ref = state_ref
        self.state_and_ref = state_and_ref

    @property
    def resolved(self) -> bool:
        return self.state_and_ref is not None


class PartiallyResolvedTransaction:
    """A verified transaction whose inputs resolve INCREMENTALLY as their
    producing transactions arrive over the feed (reference
    `PartiallyResolvedTransaction`): the explorer can render a tx the
    moment it lands and fill input details later."""

    def __init__(self, stx, inputs: List[InputResolution]):
        self.transaction = stx
        self.id = stx.id
        self.inputs = inputs

    @property
    def fully_resolved(self) -> bool:
        return all(r.resolved for r in self.inputs)


class TransactionDataModel:
    """Folds the verified-transactions feed into PartiallyResolved
    transactions (reference `TransactionDataModel.kt`): keeps a tx map
    by id and re-resolves open inputs whenever a new tx supplies them."""

    def __init__(self, ops):
        self.ops = ops
        self.partially_resolved = ObservableList()
        self._by_id: Dict[Any, Any] = {}
        #: (resolution, owning entry) pairs still awaiting a source tx
        self._open: List[tuple] = []
        feed = ops.verified_transactions_feed()
        # subscribe BEFORE folding the snapshot: a tx committed in the
        # gap would otherwise be missed forever (no replay on the
        # Observable); _on_tx dedups by id, so overlap is harmless
        self._sub = feed.updates.subscribe(self._on_tx)
        for stx in feed.snapshot:
            self._on_tx(stx)

    def _resolve(self, res: InputResolution) -> bool:
        src = self._by_id.get(res.state_ref.txhash)
        if src is None:
            return False
        try:
            res.state_and_ref = src.tx.out_ref(res.state_ref.index)
        except (IndexError, AttributeError):
            return False
        return True

    def _on_tx(self, stx) -> None:
        if stx.id in self._by_id:
            return
        self._by_id[stx.id] = stx
        # late resolutions FIRST, and with a visible list event per
        # affected entry: subscribers must learn that an EARLIER
        # transaction's inputs just resolved, not only that a new one
        # appended (an out-of-order arrival would otherwise leave its
        # dependents rendered unresolved forever)
        still_open = []
        touched = []
        for res, owner in self._open:
            if self._resolve(res):
                touched.append(owner)
            else:
                still_open.append((res, owner))
        self._open = still_open
        # one event per AFFECTED ENTRY, not per resolved input (one tx
        # can supply several inputs of the same spender)
        for owner in dict.fromkeys(touched):
            self.partially_resolved.replace_where(
                lambda x, o=owner: x.id == o.id, owner
            )
        inputs = [InputResolution(ref) for ref in stx.tx.inputs]
        entry = PartiallyResolvedTransaction(stx, inputs)
        for res in inputs:
            if not self._resolve(res):
                self._open.append((res, entry))
        self.partially_resolved.append(entry)

    def lookup(self, tx_id):
        return self._by_id.get(tx_id)

    def close(self) -> None:
        self._sub.unsubscribe()

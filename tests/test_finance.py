"""Finance layer tests (reference `finance/src/test/.../CashTests.kt`,
`CommercialPaperTests.kt`, `TwoPartyTradeFlowTests.kt`).
"""
import pytest

from corda_tpu.core.contracts import Amount, Issued, StateAndRef, StateRef, TimeWindow
from corda_tpu.core.crypto import crypto
from corda_tpu.core.identity import Party
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.finance import (
    Cash,
    CashCommand,
    CashExitFlow,
    CashIssueFlow,
    CashPaymentFlow,
    CashState,
    InsufficientBalanceError,
    SellerFlow,
    issued_by,
)
from corda_tpu.finance.commercial_paper import CommercialPaperState, CPCommand
from corda_tpu.testing import MockNetwork

USD = "USD"


def _amount(n, token=USD):
    return Amount(n, token)


class TestCashContract:
    def setup_method(self):
        self.bank_kp = crypto.entropy_to_keypair(500)
        self.alice_kp = crypto.entropy_to_keypair(501)
        self.notary_kp = crypto.entropy_to_keypair(502)
        self.bank = Party("O=Bank,L=London,C=GB", self.bank_kp.public)
        self.alice = Party("O=Alice,L=London,C=GB", self.alice_kp.public)
        self.notary = Party("O=Notary,L=Zurich,C=CH", self.notary_kp.public)
        self.token = Issued(self.bank.ref(1), USD)

    def _ltx(self, builder, input_states=None):
        wtx = builder.to_wire_transaction()
        resolved = dict(input_states or {})
        return wtx.to_ledger_transaction(
            resolve_state=lambda ref: resolved[ref],
            resolve_attachment=lambda h: None,
        )

    def test_issue_ok(self):
        b = TransactionBuilder(notary=self.notary)
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.alice)
        )
        b.add_command(CashCommand.Issue(), self.bank.owning_key)
        self._ltx(b).verify()

    def test_issue_not_signed_by_issuer_rejected(self):
        b = TransactionBuilder(notary=self.notary)
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.alice)
        )
        b.add_command(CashCommand.Issue(), self.alice.owning_key)
        with pytest.raises(Exception, match="signed by the issuer"):
            self._ltx(b).verify()

    def _issued_input(self, quantity, owner):
        issue_b = TransactionBuilder(notary=self.notary)
        issue_b.add_output_state(
            CashState(amount=Amount(quantity, self.token), owner=owner)
        )
        issue_b.add_command(CashCommand.Issue(), self.bank.owning_key)
        issue_wtx = issue_b.to_wire_transaction()
        ref = StateRef(issue_wtx.id, 0)
        return ref, issue_wtx.outputs[0]

    def test_move_conserved_ok(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.bank)
        )
        b.add_command(CashCommand.Move(), self.alice.owning_key)
        self._ltx(b, {ref: ts}).verify()

    def test_move_not_conserved_rejected(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(90, self.token), owner=self.bank)
        )
        b.add_command(CashCommand.Move(), self.alice.owning_key)
        with pytest.raises(Exception, match="not conserved"):
            self._ltx(b, {ref: ts}).verify()

    def test_move_missing_owner_signature_rejected(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.bank)
        )
        b.add_command(CashCommand.Move(), self.bank.owning_key)
        with pytest.raises(Exception, match="signed by all input owners"):
            self._ltx(b, {ref: ts}).verify()

    def test_exit_ok(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(60, self.token), owner=self.alice)
        )
        b.add_command(
            CashCommand.Exit(Amount(40, self.token)),
            self.bank.owning_key, self.alice.owning_key,
        )
        self._ltx(b, {ref: ts}).verify()


class TestCashFlows:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.bank = self.net.create_node("O=Bank,L=London,C=GB")
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.bob = self.net.create_node("O=Bob,L=New York,C=US")

    def teardown_method(self):
        self.net.stop_nodes()

    def _balance(self, node):
        return sum(
            sr.state.data.amount.quantity
            for sr in node.services.vault_service.unconsumed_states(
                CashState.contract_name
            )
        )

    def _issue_to(self, node, quantity):
        flow = CashIssueFlow(
            _amount(quantity), b"\x01", node.info, self.notary.info
        )
        h = self.bank.start_flow(flow)
        self.net.run_network()
        return h.result.result(timeout=1)

    def test_issue_and_pay(self):
        self._issue_to(self.alice, 1000)
        assert self._balance(self.alice) == 1000
        assert self._balance(self.bank) == 0

        token = Issued(self.bank.info.ref(1), USD)
        h = self.alice.start_flow(
            CashPaymentFlow(Amount(300, token), self.bob.info, self.notary.info)
        )
        self.net.run_network()
        h.result.result(timeout=1)
        assert self._balance(self.alice) == 700  # change came back
        assert self._balance(self.bob) == 300

    def test_payment_insufficient_balance(self):
        self._issue_to(self.alice, 100)
        token = Issued(self.bank.info.ref(1), USD)
        h = self.alice.start_flow(
            CashPaymentFlow(Amount(500, token), self.bob.info, self.notary.info)
        )
        self.net.run_network()
        with pytest.raises(InsufficientBalanceError):
            h.result.result(timeout=1)
        # soft locks were released on failure
        assert self._balance(self.alice) == 100
        h2 = self.alice.start_flow(
            CashPaymentFlow(Amount(50, token), self.bob.info, self.notary.info)
        )
        self.net.run_network()
        h2.result.result(timeout=1)
        assert self._balance(self.bob) == 50

    def test_exit(self):
        self._issue_to(self.bank, 500)
        token = Issued(self.bank.info.ref(1), USD)
        h = self.bank.start_flow(CashExitFlow(Amount(200, token), self.notary.info))
        self.net.run_network()
        h.result.result(timeout=1)
        assert self._balance(self.bank) == 300


class TestTwoPartyTrade:
    def test_dvp_paper_for_cash(self):
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        bank = net.create_node("O=Bank,L=London,C=GB")
        seller = net.create_node("O=Seller,L=London,C=GB")
        buyer = net.create_node("O=Buyer,L=New York,C=US")

        # Buyer gets 1000 issued USD.
        h = bank.start_flow(
            CashIssueFlow(_amount(1000), b"\x01", buyer.info, notary.info)
        )
        net.run_network()
        h.result.result(timeout=1)

        # Seller self-issues commercial paper (time-windowed issue).
        now = int(seller.services.clock() * 1_000_000_000)
        token = Issued(bank.info.ref(1), USD)
        paper = CommercialPaperState(
            issuance=seller.info.ref(2),
            owner=seller.info,
            face_value=Amount(900, token),
            maturity_date=now + int(30 * 86400 * 1e9),
        )
        b = TransactionBuilder(notary=notary.info)
        b.add_output_state(paper)
        b.add_command(CPCommand.Issue(), seller.info.owning_key)
        b.set_time_window(TimeWindow.with_tolerance(now, int(120 * 1e9)))
        issue_stx = seller.services.sign_initial_transaction(b)
        from corda_tpu.core.flows import FinalityFlow

        h2 = seller.start_flow(FinalityFlow(issue_stx), issue_stx)
        net.run_network()
        h2.result.result(timeout=1)

        # Trade: paper for 800 USD.
        asset = issue_stx.tx.out_ref(0)
        h3 = seller.start_flow(
            SellerFlow(buyer.info, asset, Amount(800, token), notary.info),
            buyer.info,
        )
        net.run_network()
        h3.result.result(timeout=5)

        seller_cash = sum(
            sr.state.data.amount.quantity
            for sr in seller.services.vault_service.unconsumed_states(
                CashState.contract_name
            )
        )
        buyer_cash = sum(
            sr.state.data.amount.quantity
            for sr in buyer.services.vault_service.unconsumed_states(
                CashState.contract_name
            )
        )
        buyer_paper = buyer.services.vault_service.unconsumed_states(
            CommercialPaperState.contract_name
        )
        assert seller_cash == 800
        assert buyer_cash == 200
        assert len(buyer_paper) == 1
        assert buyer_paper[0].state.data.owner == buyer.info
        net.stop_nodes()


class TestObligation:
    def setup_method(self):
        self.o_kp = crypto.entropy_to_keypair(520)
        self.b_kp = crypto.entropy_to_keypair(521)
        self.n_kp = crypto.entropy_to_keypair(522)
        self.obligor = Party("O=Obligor,L=London,C=GB", self.o_kp.public)
        self.beneficiary = Party("O=Beneficiary,L=Paris,C=FR", self.b_kp.public)
        self.notary = Party("O=Notary,L=Zurich,C=CH", self.n_kp.public)
        self.token = Issued(self.obligor.ref(1), "USD")

    def _settle_ltx(self, n_obligations, cash_paid):
        from corda_tpu.core.contracts import StateRef, StateAndRef, TransactionState
        from corda_tpu.finance.obligation import ObligationCommand, ObligationState
        from corda_tpu.core.crypto import SecureHash

        b = TransactionBuilder(notary=self.notary)
        resolved = {}
        for i in range(n_obligations):
            ob = ObligationState(
                obligor=self.obligor, beneficiary=self.beneficiary,
                amount=Amount(100, self.token),
            )
            ts = TransactionState(ob, self.notary)
            ref = StateRef(SecureHash.sha256(b"ob%d" % i), 0)
            resolved[ref] = ts
            b.add_input_state(StateAndRef(ts, ref))
        if cash_paid:
            cash_ts = TransactionState(
                CashState(amount=Amount(cash_paid, self.token),
                          owner=self.obligor),
                self.notary,
            )
            cash_ref = StateRef(SecureHash.sha256(b"cash"), 0)
            resolved[cash_ref] = cash_ts
            b.add_input_state(StateAndRef(cash_ts, cash_ref))
            b.add_output_state(
                CashState(amount=Amount(cash_paid, self.token),
                          owner=self.beneficiary)
            )
            b.add_command(CashCommand.Move(), self.obligor.owning_key)
        b.add_command(ObligationCommand.Settle(), self.obligor.owning_key)
        wtx = b.to_wire_transaction()
        return wtx.to_ledger_transaction(
            resolve_state=lambda r: resolved[r],
            resolve_attachment=lambda h: None,
        )

    def test_settle_full_payment_ok(self):
        self._settle_ltx(2, cash_paid=200).verify()

    def test_settle_underpayment_rejected(self):
        # Regression: one 100-cash output must not settle two 100-obligations.
        with pytest.raises(Exception, match="settlement must pay"):
            self._settle_ltx(2, cash_paid=100).verify()

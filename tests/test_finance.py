"""Finance layer tests (reference `finance/src/test/.../CashTests.kt`,
`CommercialPaperTests.kt`, `TwoPartyTradeFlowTests.kt`).
"""
import pytest

from corda_tpu.core.contracts import Amount, Issued, StateAndRef, StateRef, TimeWindow
from corda_tpu.core.crypto import crypto
from corda_tpu.core.identity import Party, PartyAndReference
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.finance import (
    Cash,
    CashCommand,
    CashExitFlow,
    CashIssueFlow,
    CashPaymentFlow,
    CashState,
    InsufficientBalanceError,
    SellerFlow,
    issued_by,
)
from corda_tpu.finance.commercial_paper import CommercialPaperState, CPCommand
from corda_tpu.testing import MockNetwork

USD = "USD"


def _amount(n, token=USD):
    return Amount(n, token)


class TestCashContract:
    def setup_method(self):
        self.bank_kp = crypto.entropy_to_keypair(500)
        self.alice_kp = crypto.entropy_to_keypair(501)
        self.notary_kp = crypto.entropy_to_keypair(502)
        self.bank = Party("O=Bank,L=London,C=GB", self.bank_kp.public)
        self.alice = Party("O=Alice,L=London,C=GB", self.alice_kp.public)
        self.notary = Party("O=Notary,L=Zurich,C=CH", self.notary_kp.public)
        self.token = Issued(self.bank.ref(1), USD)

    def _ltx(self, builder, input_states=None):
        wtx = builder.to_wire_transaction()
        resolved = dict(input_states or {})
        return wtx.to_ledger_transaction(
            resolve_state=lambda ref: resolved[ref],
            resolve_attachment=lambda h: None,
        )

    def test_issue_ok(self):
        b = TransactionBuilder(notary=self.notary)
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.alice)
        )
        b.add_command(CashCommand.Issue(), self.bank.owning_key)
        self._ltx(b).verify()

    def test_issue_not_signed_by_issuer_rejected(self):
        b = TransactionBuilder(notary=self.notary)
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.alice)
        )
        b.add_command(CashCommand.Issue(), self.alice.owning_key)
        with pytest.raises(Exception, match="signed by the issuer"):
            self._ltx(b).verify()

    def _issued_input(self, quantity, owner):
        issue_b = TransactionBuilder(notary=self.notary)
        issue_b.add_output_state(
            CashState(amount=Amount(quantity, self.token), owner=owner)
        )
        issue_b.add_command(CashCommand.Issue(), self.bank.owning_key)
        issue_wtx = issue_b.to_wire_transaction()
        ref = StateRef(issue_wtx.id, 0)
        return ref, issue_wtx.outputs[0]

    def test_move_conserved_ok(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.bank)
        )
        b.add_command(CashCommand.Move(), self.alice.owning_key)
        self._ltx(b, {ref: ts}).verify()

    def test_move_not_conserved_rejected(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(90, self.token), owner=self.bank)
        )
        b.add_command(CashCommand.Move(), self.alice.owning_key)
        with pytest.raises(Exception, match="not conserved"):
            self._ltx(b, {ref: ts}).verify()

    def test_move_missing_owner_signature_rejected(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(100, self.token), owner=self.bank)
        )
        b.add_command(CashCommand.Move(), self.bank.owning_key)
        with pytest.raises(Exception, match="signed by all input owners"):
            self._ltx(b, {ref: ts}).verify()

    def test_exit_ok(self):
        ref, ts = self._issued_input(100, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(StateAndRef(ts, ref))
        b.add_output_state(
            CashState(amount=Amount(60, self.token), owner=self.alice)
        )
        b.add_command(
            CashCommand.Exit(Amount(40, self.token)),
            self.bank.owning_key, self.alice.owning_key,
        )
        self._ltx(b, {ref: ts}).verify()


class TestCashFlows:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.bank = self.net.create_node("O=Bank,L=London,C=GB")
        self.alice = self.net.create_node("O=Alice,L=London,C=GB")
        self.bob = self.net.create_node("O=Bob,L=New York,C=US")

    def teardown_method(self):
        self.net.stop_nodes()

    def _balance(self, node):
        return sum(
            sr.state.data.amount.quantity
            for sr in node.services.vault_service.unconsumed_states(
                CashState.contract_name
            )
        )

    def _issue_to(self, node, quantity):
        flow = CashIssueFlow(
            _amount(quantity), b"\x01", node.info, self.notary.info
        )
        h = self.bank.start_flow(flow)
        self.net.run_network()
        return h.result.result(timeout=1)

    def test_issue_and_pay(self):
        self._issue_to(self.alice, 1000)
        assert self._balance(self.alice) == 1000
        assert self._balance(self.bank) == 0

        token = Issued(self.bank.info.ref(1), USD)
        h = self.alice.start_flow(
            CashPaymentFlow(Amount(300, token), self.bob.info, self.notary.info)
        )
        self.net.run_network()
        h.result.result(timeout=1)
        assert self._balance(self.alice) == 700  # change came back
        assert self._balance(self.bob) == 300

    def test_payment_insufficient_balance(self):
        self._issue_to(self.alice, 100)
        token = Issued(self.bank.info.ref(1), USD)
        h = self.alice.start_flow(
            CashPaymentFlow(Amount(500, token), self.bob.info, self.notary.info)
        )
        self.net.run_network()
        with pytest.raises(InsufficientBalanceError):
            h.result.result(timeout=1)
        # soft locks were released on failure
        assert self._balance(self.alice) == 100
        h2 = self.alice.start_flow(
            CashPaymentFlow(Amount(50, token), self.bob.info, self.notary.info)
        )
        self.net.run_network()
        h2.result.result(timeout=1)
        assert self._balance(self.bob) == 50

    def test_exit(self):
        self._issue_to(self.bank, 500)
        token = Issued(self.bank.info.ref(1), USD)
        h = self.bank.start_flow(CashExitFlow(Amount(200, token), self.notary.info))
        self.net.run_network()
        h.result.result(timeout=1)
        assert self._balance(self.bank) == 300


class TestTwoPartyTrade:
    def test_dvp_paper_for_cash(self):
        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        bank = net.create_node("O=Bank,L=London,C=GB")
        seller = net.create_node("O=Seller,L=London,C=GB")
        buyer = net.create_node("O=Buyer,L=New York,C=US")

        # Buyer gets 1000 issued USD.
        h = bank.start_flow(
            CashIssueFlow(_amount(1000), b"\x01", buyer.info, notary.info)
        )
        net.run_network()
        h.result.result(timeout=1)

        # Seller self-issues commercial paper (time-windowed issue).
        now = int(seller.services.clock() * 1_000_000_000)
        token = Issued(bank.info.ref(1), USD)
        paper = CommercialPaperState(
            issuance=seller.info.ref(2),
            owner=seller.info,
            face_value=Amount(900, token),
            maturity_date=now + int(30 * 86400 * 1e9),
        )
        b = TransactionBuilder(notary=notary.info)
        b.add_output_state(paper)
        b.add_command(CPCommand.Issue(), seller.info.owning_key)
        b.set_time_window(TimeWindow.with_tolerance(now, int(120 * 1e9)))
        issue_stx = seller.services.sign_initial_transaction(b)
        from corda_tpu.core.flows import FinalityFlow

        h2 = seller.start_flow(FinalityFlow(issue_stx), issue_stx)
        net.run_network()
        h2.result.result(timeout=1)

        # Trade: paper for 800 USD.
        asset = issue_stx.tx.out_ref(0)
        h3 = seller.start_flow(
            SellerFlow(buyer.info, asset, Amount(800, token), notary.info),
            buyer.info,
        )
        net.run_network()
        h3.result.result(timeout=5)

        seller_cash = sum(
            sr.state.data.amount.quantity
            for sr in seller.services.vault_service.unconsumed_states(
                CashState.contract_name
            )
        )
        buyer_cash = sum(
            sr.state.data.amount.quantity
            for sr in buyer.services.vault_service.unconsumed_states(
                CashState.contract_name
            )
        )
        buyer_paper = buyer.services.vault_service.unconsumed_states(
            CommercialPaperState.contract_name
        )
        assert seller_cash == 800
        assert buyer_cash == 200
        assert len(buyer_paper) == 1
        assert buyer_paper[0].state.data.owner == buyer.info
        net.stop_nodes()


class TestObligation:
    def setup_method(self):
        self.o_kp = crypto.entropy_to_keypair(520)
        self.b_kp = crypto.entropy_to_keypair(521)
        self.n_kp = crypto.entropy_to_keypair(522)
        self.obligor = Party("O=Obligor,L=London,C=GB", self.o_kp.public)
        self.beneficiary = Party("O=Beneficiary,L=Paris,C=FR", self.b_kp.public)
        self.notary = Party("O=Notary,L=Zurich,C=CH", self.n_kp.public)
        self.token = Issued(self.obligor.ref(1), "USD")

    def _settle_ltx(self, n_obligations, cash_paid):
        from corda_tpu.core.contracts import StateRef, StateAndRef, TransactionState
        from corda_tpu.finance.obligation import ObligationCommand, ObligationState
        from corda_tpu.core.crypto import SecureHash

        b = TransactionBuilder(notary=self.notary)
        resolved = {}
        for i in range(n_obligations):
            ob = ObligationState(
                obligor=self.obligor, beneficiary=self.beneficiary,
                amount=Amount(100, self.token),
            )
            ts = TransactionState(ob, self.notary)
            ref = StateRef(SecureHash.sha256(b"ob%d" % i), 0)
            resolved[ref] = ts
            b.add_input_state(StateAndRef(ts, ref))
        if cash_paid:
            cash_ts = TransactionState(
                CashState(amount=Amount(cash_paid, self.token),
                          owner=self.obligor),
                self.notary,
            )
            cash_ref = StateRef(SecureHash.sha256(b"cash"), 0)
            resolved[cash_ref] = cash_ts
            b.add_input_state(StateAndRef(cash_ts, cash_ref))
            b.add_output_state(
                CashState(amount=Amount(cash_paid, self.token),
                          owner=self.beneficiary)
            )
            b.add_command(CashCommand.Move(), self.obligor.owning_key)
        b.add_command(ObligationCommand.Settle(), self.obligor.owning_key)
        wtx = b.to_wire_transaction()
        return wtx.to_ledger_transaction(
            resolve_state=lambda r: resolved[r],
            resolve_attachment=lambda h: None,
        )

    def test_settle_full_payment_ok(self):
        self._settle_ltx(2, cash_paid=200).verify()

    def test_settle_underpayment_rejected(self):
        # Regression: one 100-cash output must not settle two 100-obligations.
        with pytest.raises(Exception, match="settlement must pay"):
            self._settle_ltx(2, cash_paid=100).verify()


class TestCommodity:
    def setup_method(self):
        from corda_tpu.finance.commodity import Commodity

        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.trader = self.net.create_node("O=Trader,L=London,C=GB")
        self.gold = Commodity("XAU", "Gold", 3)

    def teardown_method(self):
        self.net.stop_nodes()

    def test_issue_move_exit_conservation(self):
        from corda_tpu.core.contracts import Issued
        from corda_tpu.finance.commodity import (
            CommodityCommand,
            CommodityContract,
            CommodityState,
        )

        me = self.trader.info
        token = Issued(PartyAndReference(me, b"c1"), self.gold)
        # issue 100 XAU
        b = TransactionBuilder(notary=self.notary.info)
        CommodityContract.generate_issue(
            b, CommodityState(amount=Amount(100, token), owner=me)
        )
        stx = self.trader.services.sign_initial_transaction(b)
        self.trader.services.record_transactions([stx])
        ref = stx.tx.out_ref(0)
        # exit 40, change 60 back
        b2 = TransactionBuilder(notary=self.notary.info)
        CommodityContract.generate_exit(b2, Amount(40, token), [ref])
        stx2 = self.trader.services.sign_initial_transaction(b2)
        ltx = stx2.tx.to_ledger_transaction(
            resolve_state=self.trader.services.load_state,
            resolve_attachment=self.trader.services.open_attachment,
            resolve_party=self.trader.services.party_from_key,
        )
        ltx.verify()  # conservation holds
        self.trader.services.record_transactions([stx2])
        remaining = self.trader.services.vault_service.unconsumed_states(
            CommodityState.contract_name
        )
        assert len(remaining) == 1
        assert remaining[0].state.data.amount.quantity == 60

    def test_unbalanced_move_rejected(self):
        from corda_tpu.core.contracts import Issued, StateRef
        from corda_tpu.core.crypto.secure_hash import SecureHash
        from corda_tpu.finance.commodity import (
            CommodityCommand,
            CommodityState,
        )

        me = self.trader.info
        token = Issued(PartyAndReference(me, b"c1"), self.gold)
        fake_ref = StateRef(SecureHash.sha256(b"x"), 0)
        from corda_tpu.core.contracts import TransactionState

        ts = TransactionState(
            CommodityState(amount=Amount(100, token), owner=me),
            self.notary.info,
        )
        b = TransactionBuilder(notary=self.notary.info)
        b.add_input_state(StateAndRef(ts, fake_ref))
        b.add_output_state(
            CommodityState(amount=Amount(90, token), owner=me)
        )
        b.add_command(CommodityCommand.Move(), me.owning_key)
        wtx = b.to_wire_transaction()
        ltx = wtx.to_ledger_transaction(
            resolve_state=lambda r: ts,
            resolve_attachment=None,
            resolve_party=lambda k: None,
        )
        with pytest.raises(Exception, match="not conserved"):
            ltx.verify()


class TestTwoPartyDealFlow:
    def test_deal_agreed_and_committed_both_sides(self):
        from corda_tpu.core.flows import (
            FlowException,
            initiated_by,
            initiating_flow,
        )
        from corda_tpu.finance.flows import Handshake, TwoPartyDealFlow

        net = MockNetwork()
        notary = net.create_notary_node(validating=True)
        a = net.create_node("O=Dealer A,L=London,C=GB")
        b = net.create_node("O=Dealer B,L=Paris,C=FR")

        # Deal-specific subclasses (the reference pattern: Instigator/
        # Acceptor specialise Primary/Secondary per deal type).
        @initiating_flow
        class ProposeDeal(TwoPartyDealFlow.Primary):
            def check_proposal(self, stx):
                if not stx.tx.outputs:
                    raise FlowException("empty deal")

        notary_info = notary.info

        @initiated_by(ProposeDeal)
        class AcceptDeal(TwoPartyDealFlow.Secondary):
            def validate_handshake(self, handshake):
                if handshake.payload != "interest rate swap":
                    raise FlowException("unknown deal type")
                return handshake

            def assemble_shared_tx(self, handshake):
                builder = TransactionBuilder(notary=notary_info)
                builder.add_output_state(
                    _deal_state(
                        (self.counterparty, self.service_hub.my_info)
                    )
                )
                builder.add_command(
                    _DealCmd(), handshake.public_key,
                    self.service_hub.my_info.owning_key,
                )
                return builder

        h = a.start_flow(ProposeDeal(b.info, "interest rate swap"))
        net.run_network()
        stx = h.result.result(timeout=5)
        # both parties recorded the deal
        for node in (a, b):
            assert node.services.validated_transactions.get(stx.id) is not None
        net.stop_nodes()


def _deal_state(parties):
    from dataclasses import dataclass as _dc

    return _TestDealState(parties=tuple(parties))


from dataclasses import dataclass as _dataclass2  # noqa: E402
from corda_tpu.core.contracts import Contract as _Contract  # noqa: E402
from corda_tpu.core.contracts import ContractState as _ContractState  # noqa: E402
from corda_tpu.core.contracts import TypeOnlyCommandData as _TOC  # noqa: E402
from corda_tpu.core.contracts import contract as _contract  # noqa: E402
from corda_tpu.core.serialization.codec import (  # noqa: E402
    corda_serializable as _cs,
)


@_cs
@_dataclass2(frozen=True)
class _TestDealState(_ContractState):
    parties: tuple = ()
    contract_name = "TestDeal"

    @property
    def participants(self):
        return list(self.parties)


@_cs
@_dataclass2(frozen=True)
class _DealCmd(_TOC):
    pass


@_contract(name="TestDeal")
class _TestDealContract(_Contract):
    def verify(self, tx):
        pass


class TestConfidentialIdentities:
    def test_transaction_key_flow_swaps_fresh_keys(self):
        from corda_tpu.core.flows import TransactionKeyFlow
        from corda_tpu.core.identity import AnonymousParty

        net = MockNetwork()
        a = net.create_node("O=A,L=London,C=GB")
        b = net.create_node("O=B,L=Paris,C=FR")
        h = a.start_flow(TransactionKeyFlow(b.info))
        net.run_network()
        mapping = h.result.result(timeout=5)
        anon_b = mapping[b.info]
        anon_a = mapping[a.info]
        assert isinstance(anon_b, AnonymousParty)
        # fresh keys differ from the legal identities
        assert anon_b.owning_key.encoded != b.info.owning_key.encoded
        assert anon_a.owning_key.encoded != a.info.owning_key.encoded
        # each side can resolve the counterparty's anonymous key
        assert (
            a.services.identity_service.party_from_anonymous(anon_b) == b.info
        )
        assert (
            b.services.identity_service.party_from_anonymous(anon_a) == a.info
        )
        # an outsider cannot (no mapping registered elsewhere)
        c = net.create_node("O=C,L=NYC,C=US")
        assert c.services.identity_service.party_from_anonymous(anon_b) is None
        net.stop_nodes()

    def test_identity_poisoning_refused(self):
        """A peer claiming another party's well-known key as its 'fresh'
        confidential key must be refused (round-2 review finding)."""
        net = MockNetwork()
        a = net.create_node("O=A,L=London,C=GB")
        b = net.create_node("O=B,L=Paris,C=FR")
        m = net.create_node("O=Mallory,L=X,C=US")
        with pytest.raises(ValueError, match="refusing to rebind"):
            a.services.identity_service.register_anonymous_identity(
                b.info.owning_key, m.info
            )
        # resolution unchanged
        assert a.services.identity_service.party_from_key(
            b.info.owning_key
        ) == b.info
        net.stop_nodes()

"""Contract sandbox tests (reference experimental/sandbox —
WhitelistClassLoader static rejection + RuntimeCostAccounter metering)."""
import io
import zipfile

import pytest

from corda_tpu.core.sandbox import (
    Budget,
    CostLimitExceeded,
    SandboxViolation,
    check_code,
    metered_contract_verify,
    run_metered,
)


class TestStaticLayer:
    def test_clean_function_passes(self):
        def ok(tx):
            total = sum(i for i in range(10))
            return total and len(str(tx))

        check_code(ok)

    def test_open_rejected(self):
        def evil(tx):
            return open("/etc/passwd").read()

        with pytest.raises(SandboxViolation, match="open"):
            check_code(evil)

    def test_forbidden_module_rejected(self):
        import os

        def evil(tx):
            return os.environ

        with pytest.raises(SandboxViolation, match="os"):
            check_code(evil)

    def test_eval_in_nested_code_rejected(self):
        def outer(tx):
            def inner():
                return eval("1+1")
            return inner()

        with pytest.raises(SandboxViolation, match="eval"):
            check_code(outer)

    def test_class_vetting(self):
        class CleanContract:
            def verify(self, tx):
                if not tx:
                    raise ValueError("empty")

        class DirtyContract:
            def verify(self, tx):
                exec("print(1)")

        check_code(CleanContract)
        with pytest.raises(SandboxViolation):
            check_code(DirtyContract)

    def test_real_cash_contract_passes(self):
        from corda_tpu.finance.cash import Cash

        check_code(Cash)

    def test_subclasses_globals_walk_rejected(self):
        """The classic object-graph escape (ADVICE round 2):
        ().__class__.__base__.__subclasses__() reaches _wrap_close, whose
        __init__.__globals__ is the os module's namespace. Every hop is a
        LOAD_ATTR, so the static scan must reject it."""

        def evil(tx):
            for cls in ().__class__.__base__.__subclasses__():
                if cls.__name__ == "_wrap_close":
                    return cls.__init__.__globals__["system"]("id")

        with pytest.raises(SandboxViolation):
            check_code(evil)

    def test_module_names_in_attribute_position_allowed(self):
        """`tx.code` / `rows.select()` are plain attribute accesses — the
        module blocklist must only match names in import/global position
        (code-review round 3 false-positive fix)."""

        def honest(tx):
            if tx.code == "USD":
                return tx.rows.select(1)
            return None

        check_code(honest)

    def test_getattr_rejected(self):
        def evil(tx):
            return getattr(tx, "__glo" + "bals__")

        with pytest.raises(SandboxViolation, match="getattr"):
            check_code(evil)

    def test_operator_attrgetter_rejected(self):
        import operator

        def evil(tx):
            return operator.attrgetter("__globals__")(tx.verify)

        with pytest.raises(SandboxViolation):
            check_code(evil)

    def test_gc_and_inspect_rejected(self):
        import gc
        import inspect

        def evil_gc(tx):
            return gc.get_objects()

        def evil_inspect(tx):
            return inspect.stack()

        with pytest.raises(SandboxViolation):
            check_code(evil_gc)
        with pytest.raises(SandboxViolation):
            check_code(evil_inspect)


class TestDynamicLayer:
    def test_normal_execution_returns(self):
        assert run_metered(lambda a, b: a + b, 2, 3) == 5

    def test_runaway_loop_killed_by_cost(self):
        def spin():
            n = 0
            while True:
                n += 1

        with pytest.raises(CostLimitExceeded, match="cost budget"):
            run_metered(spin, budget=Budget(max_cost=50_000, max_seconds=60))

    def test_wall_clock_ceiling(self):
        def slowish():
            n = 0
            while True:
                n += 1

        with pytest.raises(CostLimitExceeded):
            run_metered(
                slowish,
                budget=Budget(max_cost=10**12, max_seconds=0.2),
            )

    def test_forbidden_module_entry_caught(self):
        import os.path

        def sneaky():
            # os.path.join is a Python-level function in a forbidden module
            return os.path.join("a", "b")

        with pytest.raises(SandboxViolation, match="forbidden module"):
            run_metered(sneaky)

    def test_trace_restored(self):
        import sys

        before = sys.gettrace()
        run_metered(lambda: 1)
        assert sys.gettrace() is before

    def test_exceptions_propagate(self):
        def boom():
            raise ValueError("inner")

        with pytest.raises(ValueError, match="inner"):
            run_metered(boom)


class TestMeteredContractVerify:
    def test_legit_contract_verifies(self):
        class Okay:
            def verify(self, ltx):
                return None

        metered_contract_verify(Okay(), object())

    def test_hostile_contract_rejected_statically(self):
        class Evil:
            def verify(self, ltx):
                return open("x")

        with pytest.raises(SandboxViolation):
            metered_contract_verify(Evil(), object())


def _zip_of(source: str, path: str = "contracts/evil.py") -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr(path, source)
    return buf.getvalue()


class TestAttachmentIntegration:
    def test_hostile_attachment_rejected_at_load(self):
        from corda_tpu.core.contracts.structures import _CONTRACT_REGISTRY
        from corda_tpu.core.serialization.attachments_loader import (
            load_contracts_from_attachments,
        )

        before = set(_CONTRACT_REGISTRY)
        evil = _zip_of(
            "from corda_tpu.core.contracts.structures import contract, Contract\n"
            "@contract(name='sandbox.EvilLoad')\n"
            "class EvilContract(Contract):\n"
            "    def verify(self, tx):\n"
            "        return open('/etc/passwd')\n"
        )
        with pytest.raises(SandboxViolation):
            load_contracts_from_attachments([evil])
        assert set(_CONTRACT_REGISTRY) == before  # rolled back

    def test_runaway_attachment_contract_metered_at_verify(self):
        from corda_tpu.core.contracts.structures import (
            _CONTRACT_REGISTRY,
            resolve_contract,
        )
        from corda_tpu.core.serialization.attachments_loader import (
            load_contracts_from_attachments,
        )

        spin = _zip_of(
            "from corda_tpu.core.contracts.structures import contract, Contract\n"
            "@contract(name='sandbox.Spin')\n"
            "class SpinContract(Contract):\n"
            "    def verify(self, tx):\n"
            "        n = 0\n"
            "        while True:\n"
            "            n += 1\n",
            path="contracts/spin.py",
        )
        loaded = load_contracts_from_attachments([spin])
        try:
            assert "sandbox.Spin" in loaded
            cls = type(resolve_contract("sandbox.Spin"))
            assert getattr(cls, "__untrusted__", False)
            from corda_tpu.core.sandbox import run_metered

            with pytest.raises(CostLimitExceeded):
                run_metered(
                    resolve_contract("sandbox.Spin").verify, object(),
                    budget=Budget(max_cost=10_000, max_seconds=30),
                )
        finally:
            _CONTRACT_REGISTRY.pop("sandbox.Spin", None)

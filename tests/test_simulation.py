"""Simulation framework tests (reference `IRSSimulationTest.kt` +
`Simulation.kt` TestClock/latency machinery)."""
import io

import pytest

from corda_tpu.samples.visualiser import ConsoleVisualiser
from corda_tpu.testing.simulation import IRSSimulation, Simulation
from corda_tpu.utils.ansi_progress import ANSIProgressRenderer
from corda_tpu.utils.clocks import TestClock


class TestTestClock:
    def test_advance_and_set(self):
        c = TestClock(100.0)
        assert c() == 100.0
        c.advance_by(5)
        assert c.now() == 105.0
        c.set_to(200.0)
        assert c() == 200.0

    def test_forward_only(self):
        c = TestClock(100.0)
        with pytest.raises(ValueError):
            c.advance_by(-1)
        with pytest.raises(ValueError):
            c.set_to(99.0)

    def test_listeners_fire(self):
        c = TestClock(0.0)
        seen = []
        c.on_advance(seen.append)
        c.advance_by(3)
        c.set_to(10)
        assert seen == [3.0, 10.0]


class TestIRSSimulation:
    def test_full_scenario(self):
        sim = IRSSimulation()
        events = []
        sim.events.subscribe(events.append)
        try:
            outcome = sim.run()
        finally:
            sim.stop()
        assert outcome["floating_rate"] == IRSSimulation.ORACLE_RATE
        # clock hopped at least to the fixing date (start + 24h)
        assert outcome["clock"] >= 1_400_000_000.0 + 24 * 3600
        kinds = {e.kind for e in events}
        assert {"message", "flow", "clock"} <= kinds
        flows = [e.detail["flow"] for e in events if e.kind == "flow"]
        assert any("FixingFlow" in f for f in flows)
        # the oracle's tear-off handlers ran
        assert any("FixSignHandler" in f for f in flows)

    def test_latency_delays_messages(self):
        # 60s wire latency: nothing can settle without advancing the clock,
        # proving delivery rides the TestClock (reference LatencyCalculator).
        sim = IRSSimulation(latency_seconds=lambda s, r: 60.0)
        try:
            mn = sim.net.messaging_network
            bank_a, bank_b = sim.banks
            bank_a.network.send(bank_b.info, "app.ping", b"x")
            assert mn.pump() is False  # delayed into the future
            assert mn.next_due() == sim.clock.now() + 60.0
            sim.clock.advance_by(61)
            assert mn.pump() is True
        finally:
            sim.stop()

    def test_full_scenario_with_latency(self):
        sim = IRSSimulation(latency_seconds=lambda s, r: 5.0)
        try:
            outcome = sim.run()
        finally:
            sim.stop()
        assert outcome["floating_rate"] == IRSSimulation.ORACLE_RATE


class TestVisualiser:
    def test_text_and_json_rendering(self):
        out = io.StringIO()
        sim = Simulation(n_banks=2)
        vis = ConsoleVisualiser(stream=out)
        vis.attach(sim)
        try:
            sim.advance(1.0)
            bank_a, bank_b = sim.banks
            bank_a.network.send(bank_b.info, "app.demo", b"hello")
            sim.settle()
        finally:
            sim.stop()
        text = out.getvalue()
        assert "clock" in text
        assert "app.demo" in text
        assert vis.counts["message"] >= 1


class TestANSIRenderer:
    def test_non_tty_fallback_logs_steps(self):
        from corda_tpu.core.flows.api import ProgressTracker

        out = io.StringIO()
        r = ANSIProgressRenderer(stream=out)
        t = ProgressTracker(
            ProgressTracker.Step("ONE"), ProgressTracker.Step("TWO")
        )
        r.progress_tracker = t
        t.set_current_step(t.steps[0])
        t.set_current_step(t.steps[1])
        assert "ONE" in out.getvalue() and "TWO" in out.getvalue()

    def test_tty_repaints_tree(self):
        class FakeTTY(io.StringIO):
            def isatty(self):
                return True

        from corda_tpu.core.flows.api import ProgressTracker

        out = FakeTTY()
        r = ANSIProgressRenderer(stream=out)
        t = ProgressTracker(
            ProgressTracker.Step("ONE"), ProgressTracker.Step("TWO")
        )
        child = ProgressTracker(ProgressTracker.Step("SUB"))
        t.set_child_tracker(t.steps[0], child)
        r.progress_tracker = t
        t.set_current_step(t.steps[0])
        child.set_current_step(child.steps[0])
        t.set_current_step(t.steps[1])
        r.done()
        painted = out.getvalue()
        assert "\x1b[" in painted  # ANSI repaint codes
        assert "SUB" in painted and "TWO" in painted

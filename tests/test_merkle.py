"""Merkle tree + partial Merkle proof tests (reference PartialMerkleTreeTest.kt)."""
import pytest

from corda_tpu.core.crypto.merkle import (
    MerkleTree,
    MerkleTreeError,
    PartialMerkleTree,
)
from corda_tpu.core.crypto.secure_hash import SecureHash, ZERO_HASH


def _leaves(n):
    return [SecureHash.sha256(bytes([i]) * 4) for i in range(n)]


def test_single_leaf():
    ls = _leaves(1)
    t = MerkleTree.get_merkle_tree(ls)
    assert t.hash == ls[0]


def test_power_of_two_padding():
    ls = _leaves(3)
    t = MerkleTree.get_merkle_tree(ls)
    # 3 leaves pad to 4 with zero hash
    expected = ls[0].hash_concat(ls[1]).hash_concat(ls[2].hash_concat(ZERO_HASH))
    assert t.hash == expected


def test_empty_rejected():
    with pytest.raises(MerkleTreeError):
        MerkleTree.get_merkle_tree([])


def test_deterministic():
    ls = _leaves(7)
    assert MerkleTree.get_merkle_tree(ls).hash == MerkleTree.get_merkle_tree(ls).hash
    swapped = ls[:5] + [ls[6], ls[5]]
    assert MerkleTree.get_merkle_tree(swapped).hash != MerkleTree.get_merkle_tree(ls).hash


@pytest.mark.parametrize("n,included", [(8, [0, 3, 7]), (5, [1]), (1, [0]), (16, list(range(16)))])
def test_partial_tree_verifies(n, included):
    ls = _leaves(n)
    tree = MerkleTree.get_merkle_tree(ls)
    inc = [ls[i] for i in included]
    pmt = PartialMerkleTree.build(tree, inc)
    assert pmt.verify(tree.hash, inc)


def test_partial_tree_wrong_root_fails():
    ls = _leaves(8)
    tree = MerkleTree.get_merkle_tree(ls)
    pmt = PartialMerkleTree.build(tree, [ls[2]])
    assert not pmt.verify(SecureHash.random_sha256(), [ls[2]])


def test_partial_tree_wrong_leaves_fail():
    ls = _leaves(8)
    tree = MerkleTree.get_merkle_tree(ls)
    pmt = PartialMerkleTree.build(tree, [ls[2]])
    assert not pmt.verify(tree.hash, [ls[3]])
    assert not pmt.verify(tree.hash, [ls[2], ls[3]])


def test_partial_tree_unknown_leaf_rejected():
    ls = _leaves(8)
    tree = MerkleTree.get_merkle_tree(ls)
    with pytest.raises(MerkleTreeError):
        PartialMerkleTree.build(tree, [SecureHash.random_sha256()])


def test_secure_hash_basics():
    h = SecureHash.sha256(b"abc")
    assert h == SecureHash.parse(str(h))
    assert len(h.bytes) == 32
    assert h.hash_concat(h) == SecureHash.sha256(h.bytes + h.bytes)
    with pytest.raises(ValueError):
        SecureHash(b"short")

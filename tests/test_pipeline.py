"""Overlapped verification pipeline tests (docs/perf-pipeline.md).

Covers the staged engine (ring backpressure, clean drain on stop with
zero hung futures, per-batch fault containment via the testing/faults
seams), the SignatureBatcher wiring (flush contract, PR-5 backpressure
composition, close teardown), parity of the staged phase API with the
synchronous verify path, the sync-vs-pipelined A/B harness, the bench
gate's direction classification of the new stage keys, and the
`loadtest/real._hot_timers` snapshot-tolerance fix.
"""
import threading
import time

import pytest

from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto import batch as crypto_batch
from corda_tpu.testing import faults
from corda_tpu.verifier.batcher import SignatureBatcher
from corda_tpu.verifier.pipeline import (
    PipelineStoppedError,
    VerificationPipeline,
    pipeline_enabled,
)


def _items(n, entropy0=6000, tamper_idx=()):
    items = []
    for i in range(n):
        kp = crypto.entropy_to_keypair(entropy0 + i)
        content = b"pipe-msg-%d" % i
        sig = crypto.do_sign(kp.private, content)
        if i in tamper_idx:
            content = b"tampered-%d" % i
        items.append((kp.public, sig, content))
    return items


def _ident(v):
    return v


# ---------------------------------------------------------------------------
# The staged engine
# ---------------------------------------------------------------------------

class TestEngine:
    def test_jobs_flow_through_stages_in_order(self):
        seen = []
        p = VerificationPipeline(
            stages=[
                ("a", lambda v: (seen.append(("a", v)), v + 1)[-1]),
                ("b", lambda v: (seen.append(("b", v)), v * 10)[-1]),
            ],
            depth=2, name="order",
        )
        try:
            futs = [p.submit(i) for i in range(4)]
            assert [f.result(timeout=5) for f in futs] == [10, 20, 30, 40]
            # per-stage FIFO: stage a saw 0..3 in order, so did b (+1)
            assert [v for s, v in seen if s == "a"] == [0, 1, 2, 3]
            assert [v for s, v in seen if s == "b"] == [1, 2, 3, 4]
            assert p.batches == 4 and p.failures == 0
            assert p.in_flight == 0
        finally:
            p.stop()

    def test_full_ring_converts_to_submit_backpressure(self):
        gate = threading.Event()
        entered = threading.Event()

        def gated(v):
            entered.set()
            assert gate.wait(timeout=10)
            return v

        p = VerificationPipeline(
            stages=[("decode", _ident), ("dispatch", gated)],
            depth=2, name="bp",
        )
        try:
            f1 = p.submit(1)
            assert entered.wait(5)
            f2 = p.submit(2)  # fills the ring (1 running, 1 queued)
            unblocked = threading.Event()
            extra = {}

            def third():
                extra["f3"] = p.submit(3)
                unblocked.set()

            t = threading.Thread(target=third, daemon=True, name="bp-sub")
            t.start()
            # the ring is full: the third submit must BLOCK, not queue
            assert not unblocked.wait(timeout=0.3)
            assert p.in_flight == 2
            gate.set()
            assert unblocked.wait(timeout=10)
            assert f1.result(5) == 1 and f2.result(5) == 2
            assert extra["f3"].result(5) == 3
        finally:
            gate.set()
            p.stop()

    def test_stop_with_wedged_stage_zero_hung_futures(self):
        gate = threading.Event()

        def wedged(v):
            assert gate.wait(timeout=30)
            return v

        p = VerificationPipeline(
            stages=[("decode", _ident), ("dispatch", wedged)],
            depth=3, name="wedge",
        )
        futs = [p.submit(i) for i in range(3)]  # 1 wedged, 2 queued
        t0 = time.monotonic()
        p.stop(timeout=0.3)  # must NOT wait for the wedge to clear
        assert time.monotonic() - t0 < 10
        # zero hung futures: every one resolved, queued ones with the
        # typed stop error
        done = [f.done() for f in futs]
        assert all(done), done
        errors = sum(
            1 for f in futs if f.exception() is not None
        )
        assert errors == 3
        assert all(
            isinstance(f.exception(), PipelineStoppedError) for f in futs
        )
        with pytest.raises(PipelineStoppedError):
            p.submit(99)
        gate.set()  # let the wedged thread exit

    def test_clean_stop_drains_in_flight_batches(self):
        p = VerificationPipeline(
            stages=[("a", lambda v: v + 1)], depth=2, name="drain",
        )
        futs = [p.submit(i) for i in range(5)]
        p.stop()  # default timeout: drains, then tears down
        assert [f.result(0) for f in futs] == [1, 2, 3, 4, 5]

    def test_stage_crash_fails_only_its_batch(self):
        def picky(v):
            if v == "boom":
                raise ValueError("stage exploded")
            return v

        p = VerificationPipeline(
            stages=[("decode", _ident), ("dispatch", picky)],
            depth=2, name="crash",
        )
        try:
            f1 = p.submit("ok-1")
            f2 = p.submit("boom")
            f3 = p.submit("ok-2")
            assert f1.result(5) == "ok-1"
            with pytest.raises(ValueError):
                f2.result(5)
            assert f3.result(5) == "ok-2"  # the stage thread survived
            assert p.failures == 1 and p.batches == 3
        finally:
            p.stop()

    def test_fault_injection_crashes_one_batch(self):
        p = VerificationPipeline(
            stages=[("decode", _ident), ("dispatch", _ident)],
            depth=2, name="faulted",
        )
        try:
            with faults.inject(seed=3) as fi:
                rule = fi.rule(
                    "pipeline.stage", "crash", match="dispatch", times=1
                )
                f1 = p.submit("a")
                with pytest.raises(RuntimeError, match="injected"):
                    f1.result(5)
                f2 = p.submit("b")
                assert f2.result(5) == "b"
            assert rule.fired == 1
        finally:
            p.stop()

    def test_fault_injection_delay(self):
        p = VerificationPipeline(
            stages=[("dispatch", _ident)], depth=2, name="delayed",
        )
        try:
            with faults.inject(seed=3) as fi:
                fi.rule("pipeline.stage", ("delay", 0.15), times=1)
                t0 = time.monotonic()
                assert p.submit("x").result(5) == "x"
                assert time.monotonic() - t0 >= 0.15
        finally:
            p.stop()

    def test_overlap_ratio_accounts_concurrent_stages(self):
        def slow(v):
            time.sleep(0.05)
            return v

        p = VerificationPipeline(
            stages=[("a", slow), ("b", slow)], depth=4, name="ratio",
        )
        try:
            futs = [p.submit(i) for i in range(4)]
            for f in futs:
                f.result(10)
            # 8 stage executions x 50ms = 400ms busy; with stage a of
            # job N+1 overlapping stage b of job N the active wall is
            # well under the busy sum
            assert p.overlap_ratio > 0.1, p.overlap_ratio
            assert p.stage_wall_s("a") >= 0.15
            assert p.stage_wall_s("b") >= 0.15
        finally:
            p.stop()

    def test_thread_start_failure_poisons_engine(self):
        p = VerificationPipeline(
            stages=[("a", _ident)], depth=2, name="exhausted",
        )
        with pytest.MonkeyPatch.context() as mp:
            def failing_start(self_t):
                raise RuntimeError("can't start new thread")

            mp.setattr(threading.Thread, "start", failing_start)
            with pytest.raises(RuntimeError, match="can't start"):
                p.submit(1)
        # the ring slot was rolled back, and the engine is poisoned:
        # later submits refuse (callers fall back to the sync path)
        # instead of queueing onto missing stage threads
        assert p.in_flight == 0
        with pytest.raises(PipelineStoppedError):
            p.submit(2)

    def test_metrics_bound(self):
        from corda_tpu.utils.metrics import MetricRegistry

        reg = MetricRegistry()
        p = VerificationPipeline(
            stages=[("decode", _ident), ("dispatch", _ident)],
            depth=2, name="metered", registry=reg,
        )
        try:
            assert reg.gauge("Pipeline.InFlightBatches").value == 0
            p.submit("x").result(5)
            assert reg.gauge("Pipeline.InFlightBatches").value == 0
            assert reg.gauge(
                "Pipeline.StageOccupancy{stage=decode}"
            ).value == 0
            assert reg.gauge(
                "Pipeline.StageWallSeconds{stage=dispatch}"
            ).value >= 0.0
            assert 0.0 <= reg.gauge("Pipeline.OverlapRatio").value <= 1.0
        finally:
            p.stop()

    def test_stage_spans_link_served_traces(self):
        from corda_tpu.utils import tracing

        tracer = tracing.get_tracer()
        tracer.reset()
        ctx = tracing.SpanContext(trace_id="ab" * 16, span_id="cd" * 8)
        p = VerificationPipeline(
            stages=[("decode", _ident), ("dispatch", _ident)],
            depth=2, name="traced",
        )
        try:
            p.submit("x", ctxs=[ctx]).result(5)
        finally:
            p.stop()
        spans = tracer.get_trace(ctx.trace_id) or []
        names = {s["name"] for s in spans}
        assert "pipeline.decode" in names, names
        assert "pipeline.dispatch" in names, names


# ---------------------------------------------------------------------------
# SignatureBatcher wiring
# ---------------------------------------------------------------------------

class TestBatcherPipelined:
    def test_pipelined_flush_resolves_and_counts(self):
        from corda_tpu.utils.metrics import MetricRegistry

        reg = MetricRegistry()
        b = SignatureBatcher(max_batch=8, linger_ms=10_000, pipeline=True)
        b.bind_metrics(reg)
        try:
            futures = b.submit_many(_items(8))
            assert all(f.result(timeout=10) for f in futures)
            assert b.flushes == 1
            assert b.items_verified == 8
            assert b.largest_batch == 8
            assert b.flush_wall_s > 0.0
            assert reg.histogram("Verifier.BatchSize").count == 1
            # the engine exists and its instruments are registered
            assert b._pipeline is not None
            assert reg.gauge("Pipeline.InFlightBatches").value == 0
        finally:
            b.close()

    def test_pipeline_false_never_builds_engine(self):
        b = SignatureBatcher(max_batch=4, linger_ms=10_000, pipeline=False)
        try:
            futures = b.submit_many(_items(4, entropy0=6200))
            assert all(f.result(timeout=10) for f in futures)
            assert b._pipeline is None
        finally:
            b.close()

    def test_env_gate(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_PIPELINE", "0")
        assert not pipeline_enabled()
        b = SignatureBatcher(max_batch=2, linger_ms=10_000)
        assert b._use_pipeline is False
        b.close()
        monkeypatch.setenv("CORDA_TPU_PIPELINE", "1")
        assert pipeline_enabled()

    def test_flush_waits_for_ring(self):
        """flush() contract in pipelined mode: every previously
        submitted future is resolved when it returns, even while the
        engine holds the batch behind a gated dispatch stage."""
        gate = threading.Event()

        def gated_verify(items):
            assert gate.wait(timeout=10)
            return crypto_batch.verify_batch(items)

        b = SignatureBatcher(max_batch=2, linger_ms=10_000, pipeline=True)
        b._pipeline = VerificationPipeline(
            stages=[("decode", _ident), ("dispatch", gated_verify)],
            depth=2, name="flushwait",
        )
        try:
            futures = b.submit_many(_items(2, entropy0=6300))
            timer = threading.Timer(0.2, gate.set)
            timer.start()
            b.flush()  # must block until the engine drained
            assert all(f.done() for f in futures)
            assert all(f.result(0) for f in futures)
            timer.cancel()
        finally:
            gate.set()
            b.close()

    def test_ring_backpressure_composes_with_flush_queue_cap(self):
        """ISSUE acceptance: a full ring under a paused dispatch stage
        converts to synchronous submit backpressure — ring full parks
        the flush thread, the flush queue hits its cap, and
        submit_many blocks the producer (the PR-5 composition)."""
        gate = threading.Event()

        def gated_verify(items):
            assert gate.wait(timeout=15)
            return crypto_batch.verify_batch(items)

        b = SignatureBatcher(max_batch=1, linger_ms=10_000,
                             max_queued_batches=1, pipeline=True)
        b._pipeline = VerificationPipeline(
            stages=[("dispatch", gated_verify)], depth=1, name="compose",
        )
        items = _items(4, entropy0=6400)
        try:
            futures = [b.submit(items[0])]  # ring slot: paused dispatch
            deadline = time.monotonic() + 5
            while b._pipeline.in_flight == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert b._pipeline.in_flight == 1
            futures.append(b.submit(items[1]))  # flush thread blocks in
            # pipe.submit; this buffer sits in the flush queue (cap 1)
            deadline = time.monotonic() + 5
            while not b.queued_batches and time.monotonic() < deadline:
                time.sleep(0.01)
            futures.append(b.submit(items[2]))  # queue at cap after pop?
            blocked = threading.Event()
            extra = {}

            def producer():
                extra["f"] = b.submit(items[3])
                blocked.set()

            t = threading.Thread(
                target=producer, daemon=True, name="compose-producer"
            )
            t.start()
            assert not blocked.wait(timeout=0.4), (
                "producer must BLOCK while the ring+flush queue are full"
            )
            assert b.backpressure_waits >= 1
            gate.set()
            assert blocked.wait(timeout=15)
            futures.append(extra["f"])
            assert all(f.result(timeout=15) for f in futures)
        finally:
            gate.set()
            b.close()

    def test_stage_crash_fails_only_that_flush(self):
        """A fault-injected stage crash (testing/faults seam) fails its
        own batch's futures; the next flush through the same engine
        verifies clean."""
        b = SignatureBatcher(max_batch=3, linger_ms=10_000, pipeline=True)
        try:
            with faults.inject(seed=11) as fi:
                rule = fi.rule(
                    "pipeline.stage", "crash", match="dispatch", times=1
                )
                first = b.submit_many(_items(3, entropy0=6500))
                for f in first:
                    with pytest.raises(RuntimeError, match="injected"):
                        f.result(timeout=10)
                second = b.submit_many(_items(3, entropy0=6600))
                assert all(f.result(timeout=10) for f in second)
            assert rule.fired == 1
            assert b._pipeline.failures == 1
        finally:
            b.close()

    def test_submit_failure_falls_back_to_sync(self, monkeypatch):
        """A non-stopped submit failure (e.g. thread exhaustion) must
        serve the batch synchronously, never kill the flush thread with
        the popped batch's futures stranded."""
        b = SignatureBatcher(max_batch=4, linger_ms=10_000, pipeline=True)
        try:
            pipe = b._ensure_pipeline()

            def boom():
                raise RuntimeError("can't start new thread")

            monkeypatch.setattr(pipe, "_ensure_threads_locked", boom)
            futures = b.submit_many(_items(4, entropy0=7300))
            assert all(f.result(timeout=10) for f in futures)
            assert b.flushes == 1  # the sync path served it
            assert pipe.in_flight == 0  # no leaked ring slot
        finally:
            b.close()

    def test_close_stops_engine_threads(self):
        b = SignatureBatcher(max_batch=2, linger_ms=10_000, pipeline=True)
        futures = b.submit_many(_items(2, entropy0=6700))
        assert all(f.result(timeout=10) for f in futures)
        engine = b._pipeline
        assert engine is not None
        b.close()
        assert b._pipeline is None
        for t in engine._threads:
            t.join(timeout=5)
            assert not t.is_alive()

    def test_worker_drains_through_pipeline(self):
        """The out-of-process verifier worker's batcher rides the same
        engine: a SignatureBatchRequest flush goes through the staged
        pipeline and replies correctly."""
        from corda_tpu.messaging import Broker
        from corda_tpu.verifier.service import (
            OutOfProcessTransactionVerifierService,
        )
        from corda_tpu.verifier.worker import VerifierWorker

        broker = Broker()
        batcher = SignatureBatcher(
            max_batch=64, linger_ms=10_000, pipeline=True
        )
        svc = OutOfProcessTransactionVerifierService(broker, "pipe-node")
        worker = VerifierWorker(
            broker, name="pipe-worker", batcher=batcher
        ).start()
        try:
            items = _items(6, entropy0=6800, tamper_idx={2})
            futures = svc.verify_signatures(items)
            results = [f.result(timeout=30) for f in futures]
            assert results == [True, True, False, True, True, True]
            assert batcher._pipeline is not None  # the engine really ran
            assert batcher.flushes >= 1
        finally:
            worker.stop()
            svc.stop()
            broker.close()


# ---------------------------------------------------------------------------
# Staged phase API parity
# ---------------------------------------------------------------------------

class TestStagedParity:
    def test_staged_composition_matches_verify_batch(self):
        items = _items(10, entropy0=6900, tamper_idx={1, 7})
        # malformed rows: wrong-length key and signature stay False
        kp = crypto.entropy_to_keypair(6999)
        items.append((kp.public, b"\x00" * 10, b"short sig"))
        expected = crypto_batch.verify_batch(items)
        plan = crypto_batch.plan_batch(items, split_device=True)
        crypto_batch.prehash_plan(plan)
        crypto_batch.dispatch_plan(plan)
        staged = crypto_batch.collect_plan(plan)
        assert staged == expected
        assert expected[1] is False and expected[7] is False
        assert expected[0] is True and expected[-1] is False

    def test_default_stages_verify_correctly(self):
        p = VerificationPipeline(name="prod-stages")
        try:
            items = _items(6, entropy0=7000, tamper_idx={4})
            out = p.submit(items).result(timeout=30)
            assert out == [True, True, True, True, False, True]
        finally:
            p.stop()

    def test_host_batch_prehash_split_parity(self):
        from corda_tpu.core.crypto import host_batch

        if not host_batch.available():
            pytest.skip("native host batch engine unavailable")
        rows = []
        for i in range(6):
            kp = crypto.entropy_to_keypair(7100 + i)
            content = b"split-%d" % i
            rows.append((kp.public.encoded,
                         crypto.do_sign(kp.private, content), content))
        # one tampered row + one malformed row
        pub, sig, _ = rows[2]
        rows[2] = (pub, sig, b"tampered")
        rows.append((b"\x01" * 31, b"\x02" * 64, b"bad key length"))
        whole = host_batch.verify_batch_host(rows)
        split = host_batch.verify_batch_host(
            rows, prehashed=host_batch.prehash_rows(rows)
        )
        assert whole == split
        assert whole[2] is False and whole[-1] is False
        assert whole[0] is True


# ---------------------------------------------------------------------------
# The A/B harness + gate wiring
# ---------------------------------------------------------------------------

class TestOverlapHarness:
    def test_measure_pipeline_overlap_smoke(self):
        from corda_tpu.loadtest.latency import measure_pipeline_overlap

        out = measure_pipeline_overlap(n_batches=2, batch=48, msg_len=512)
        for key in (
            "pipeline_sync_wall_ms", "pipeline_pipelined_wall_ms",
            "pipeline_prehash_wall_ms", "pipeline_dispatch_wall_ms",
            "pipeline_overlap_ratio", "pipeline_prehash_hidden_pct",
            "pipeline_engine_interleave", "pipeline_route",
            "pipeline_cpus",
        ):
            assert key in out, key
        assert out["pipeline_sync_wall_ms"] > 0
        assert out["pipeline_pipelined_wall_ms"] > 0
        assert 0.0 <= out["pipeline_overlap_ratio"] <= 1.0
        assert 0.0 <= out["pipeline_prehash_hidden_pct"] <= 100.0
        # the noise floor: scheduler-jitter ratios report exactly 0.0
        # (compare_records skips 0-base ratios, so noise cannot arm the
        # regression gate on low-core hosts)
        assert (
            out["pipeline_overlap_ratio"] == 0.0
            or out["pipeline_overlap_ratio"] >= 0.05
        )

    def test_gate_directions_for_pipeline_keys(self):
        from corda_tpu.loadtest.gate import direction

        assert direction("pipeline_overlap_ratio") == "higher"
        assert direction("pipeline_prehash_hidden_pct") == "higher"
        assert direction("pipeline_sync_wall_ms") == "lower"
        assert direction("pipeline_pipelined_wall_ms") == "lower"
        assert direction("pipeline_prehash_wall_ms") == "lower"
        assert direction(
            "stage_timings.pipeline_overlap_ratio"
        ) == "higher"
        # shape keys stay ungated: a workload change is not a regression
        assert direction("pipeline_batch_rows") is None
        assert direction("pipeline_cpus") is None

    def test_gate_flags_overlap_ratio_shrink(self):
        from corda_tpu.loadtest.gate import compare_records

        prev = {"stage_timings": {"pipeline_overlap_ratio": 0.40}}
        cur = {"stage_timings": {"pipeline_overlap_ratio": 0.10}}
        regs = compare_records(prev, cur)
        assert any(
            r["key"].endswith("pipeline_overlap_ratio") for r in regs
        ), regs
        # and the good direction passes
        assert compare_records(cur, prev) == []


# ---------------------------------------------------------------------------
# loadtest/real._hot_timers snapshot tolerance (satellite fix)
# ---------------------------------------------------------------------------

class TestHotTimers:
    def test_ranks_by_total_and_rounds_consistently(self):
        from corda_tpu.loadtest.real import _hot_timers

        metrics = {
            "RPC.big": {"type": "timer", "count": 100, "total": 5.0,
                        "mean": 0.05, "p95": 0.2},
            "RPC.small": {"type": "timer", "count": 10, "total": 0.1,
                          "mean": 0.01, "p95": 0.02},
            "Flows.InFlight": {"type": "gauge", "value": 3},
        }
        out = _hot_timers(metrics, top=5)
        assert list(out) == ["RPC.big", "RPC.small"]
        assert out["RPC.big"]["total_s"] == 5.0
        assert out["RPC.big"]["p95_ms"] == 200.0
        assert out["RPC.small"]["mean_ms"] == 10.0

    def test_missing_total_falls_back_to_count_x_mean(self):
        from corda_tpu.loadtest.real import _hot_timers

        metrics = {
            "P2P.Handle.old-build": {"type": "timer", "count": 1000,
                                     "mean": 0.004, "p95": 0.01},
            "P2P.Handle.trivial": {"type": "timer", "count": 2,
                                   "total": 0.001, "mean": 0.0005,
                                   "p95": 0.001},
        }
        out = _hot_timers(metrics, top=5)
        # 1000 x 4ms = 4s ranks FIRST despite the missing total key
        assert list(out)[0] == "P2P.Handle.old-build"
        assert out["P2P.Handle.old-build"]["total_s"] == 4.0

    def test_missing_total_and_mean_does_not_misrank(self):
        from corda_tpu.loadtest.real import _hot_timers

        metrics = {
            # a busy timer from a snapshot with neither total nor mean:
            # the p50 fallback must keep it ranked above the trivial one
            "RPC.keyPoor": {"type": "timer", "count": 500, "p50": 0.01,
                            "p95": 0.05},
            "RPC.tiny": {"type": "timer", "count": 3, "total": 0.003,
                         "mean": 0.001, "p95": 0.002},
        }
        out = _hot_timers(metrics, top=5)
        assert list(out)[0] == "RPC.keyPoor"
        row = out["RPC.keyPoor"]
        assert row["total_s"] == 5.0  # 500 x p50
        assert row["mean_ms"] == 10.0  # derived total/count
        assert row["p95_ms"] == 50.0

    def test_empty_reservoir_snapshot_survives(self):
        from corda_tpu.loadtest.real import _hot_timers

        metrics = {
            # Timer.snapshot() with an empty reservoir: count/total only
            "RPC.neverFired": {"type": "timer", "count": 0, "total": 0.0},
            "RPC.active": {"type": "timer", "count": 5, "total": 0.5,
                           "mean": 0.1, "p95": 0.3},
            "weird": "not-a-dict",
        }
        out = _hot_timers(metrics, top=5)
        assert list(out)[0] == "RPC.active"
        assert out["RPC.neverFired"] == {
            "count": 0, "mean_ms": 0.0, "p95_ms": 0.0, "total_s": 0.0,
        }

    def test_p95_falls_back_to_max_then_mean(self):
        from corda_tpu.loadtest.real import _hot_timers

        metrics = {
            "RPC.noP95": {"type": "timer", "count": 4, "total": 0.4,
                          "mean": 0.1, "max": 0.25},
            "RPC.meanOnly": {"type": "timer", "count": 4, "total": 0.2,
                             "mean": 0.05},
            # present-but-null max (foreign build's empty-reservoir
            # serialisation) must fall through to mean, not crash
            "RPC.nullMax": {"type": "timer", "count": 2, "total": 0.1,
                            "mean": 0.05, "max": None},
        }
        out = _hot_timers(metrics, top=5)
        assert out["RPC.noP95"]["p95_ms"] == 250.0
        assert out["RPC.meanOnly"]["p95_ms"] == 50.0
        assert out["RPC.nullMax"]["p95_ms"] == 50.0

"""Universal contract DSL tests (reference experimental universal-contract
suites: Cap.kt/Swaption-style arrangements, action exercise, fixings)."""
import pytest

from corda_tpu.core.contracts import Amount, StateAndRef, StateRef, TransactionState
from corda_tpu.core.contracts.structures import TransactionVerificationError
from corda_tpu.core.crypto import crypto
from corda_tpu.core.crypto.secure_hash import SecureHash
from corda_tpu.core.identity import Party
from corda_tpu.core.serialization.codec import deserialize, serialize
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.experimental.universal import (
    Action,
    Actions,
    All,
    Do,
    FloatingObligation,
    Issue,
    Obligation,
    Settle,
    UniversalState,
    Zero,
    all_of,
    normalize,
    obliged_parties,
)
from corda_tpu.samples.irs_demo import Fix, FixOf


class Base:
    def setup_method(self):
        self.a_kp = crypto.entropy_to_keypair(700)
        self.b_kp = crypto.entropy_to_keypair(701)
        self.n_kp = crypto.entropy_to_keypair(702)
        self.alice = Party("O=UAlice,L=London,C=GB", self.a_kp.public)
        self.bob = Party("O=UBob,L=Paris,C=FR", self.b_kp.public)
        self.notary = Party("O=UNotary,L=Zurich,C=CH", self.n_kp.public)

    def _ltx(self, builder, input_states=None):
        wtx = builder.to_wire_transaction()
        resolved = dict(input_states or {})
        return wtx.to_ledger_transaction(
            resolve_state=lambda ref: resolved[ref],
            resolve_attachment=lambda h: None,
        )

    def _fx_forward(self):
        """EUR/USD forward: on 'execute' both legs become payable."""
        legs = all_of(
            Obligation(Amount(1_000_000_00, "EUR"), self.alice, self.bob),
            Obligation(Amount(1_080_000_00, "USD"), self.bob, self.alice),
        )
        return UniversalState(
            arrangement=Actions((
                Action("execute", (self.alice, self.bob), legs),
            )),
            parties=(self.alice, self.bob),
        )

    def _input(self, state):
        ref = StateRef(SecureHash.sha256(b"universal-in"), 0)
        ts = TransactionState(data=state, notary=self.notary)
        return ref, {ref: ts}, StateAndRef(ts, ref)


class TestAlgebra(Base):
    def test_all_of_normalizes(self):
        ob = Obligation(Amount(1, "USD"), self.alice, self.bob)
        assert all_of() == Zero()
        assert all_of(Zero(), ob) == ob
        nested = All((ob, All((ob, Zero()))))
        flat = normalize(nested)
        assert isinstance(flat, All) and len(flat.parts) == 2

    def test_obliged_parties_sees_through_actions(self):
        state = self._fx_forward()
        assert obliged_parties(state.arrangement) == {
            self.alice.name, self.bob.name,
        }

    def test_arrangement_round_trips_codec(self):
        state = self._fx_forward()
        assert deserialize(serialize(state)) == state


class TestIssue(Base):
    def test_issue_signed_by_both(self):
        b = TransactionBuilder(notary=self.notary)
        b.add_output_state(self._fx_forward())
        b.add_command(Issue(), self.alice.owning_key, self.bob.owning_key)
        self._ltx(b).verify()

    def test_issue_missing_obliged_signer_rejected(self):
        b = TransactionBuilder(notary=self.notary)
        b.add_output_state(self._fx_forward())
        b.add_command(Issue(), self.alice.owning_key)
        with pytest.raises(TransactionVerificationError, match="obliged"):
            self._ltx(b).verify()


class TestDo(Base):
    def test_execute_produces_legs(self):
        state = self._fx_forward()
        ref, resolved, sar = self._input(state)
        legs = state.arrangement.actions[0].result
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(
            UniversalState(arrangement=legs, parties=state.parties)
        )
        b.add_command(Do("execute"), self.alice.owning_key, self.bob.owning_key)
        self._ltx(b, resolved).verify()

    def test_wrong_result_rejected(self):
        state = self._fx_forward()
        ref, resolved, sar = self._input(state)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(
            UniversalState(arrangement=Zero(), parties=state.parties)
        )
        b.add_command(Do("execute"), self.alice.owning_key, self.bob.owning_key)
        with pytest.raises(TransactionVerificationError, match="not the action's result"):
            self._ltx(b, resolved).verify()

    def test_unoffered_action_rejected(self):
        state = self._fx_forward()
        ref, resolved, sar = self._input(state)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(state)
        b.add_command(Do("cancel"), self.alice.owning_key, self.bob.owning_key)
        with pytest.raises(TransactionVerificationError, match="not offered"):
            self._ltx(b, resolved).verify()

    def test_actor_signature_required(self):
        state = self._fx_forward()
        ref, resolved, sar = self._input(state)
        legs = state.arrangement.actions[0].result
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(
            UniversalState(arrangement=legs, parties=state.parties)
        )
        b.add_command(Do("execute"), self.alice.owning_key)
        with pytest.raises(TransactionVerificationError, match="actor signatures"):
            self._ltx(b, resolved).verify()


class TestFixings(Base):
    """A cap-style floating leg resolves through an oracle Fix command
    (the same Fix type the irs-demo oracle tear-off-signs)."""

    def _floating_state(self):
        fix_of = FixOf("LIBOR", "2026-12-01", "6M")
        floating = FloatingObligation(
            fix_of=fix_of, scale=10_000_00, frm=self.bob, to=self.alice,
            currency="USD",
        )
        return fix_of, UniversalState(
            arrangement=Actions((
                Action("fix", (self.alice, self.bob), floating),
            )),
            parties=(self.alice, self.bob),
        )

    def test_fix_resolves_floating_obligation(self):
        fix_of, state = self._floating_state()
        ref, resolved, sar = self._input(state)
        expected = Obligation(
            Amount(int(round(3.25 * 10_000_00)), "USD"), self.bob, self.alice
        )
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(
            UniversalState(arrangement=expected, parties=state.parties)
        )
        b.add_command(Do("fix"), self.alice.owning_key, self.bob.owning_key)
        b.add_command(Fix(fix_of, 3.25), self.notary.owning_key)  # oracle key
        self._ltx(b, resolved).verify()

    def test_missing_fix_rejected(self):
        fix_of, state = self._floating_state()
        ref, resolved, sar = self._input(state)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(
            UniversalState(arrangement=Zero(), parties=state.parties)
        )
        b.add_command(Do("fix"), self.alice.owning_key, self.bob.owning_key)
        with pytest.raises(TransactionVerificationError, match="needs a Fix"):
            self._ltx(b, resolved).verify()


class TestSettle(Base):
    def test_settle_reduces_arrangement(self):
        legs = all_of(
            Obligation(Amount(100, "EUR"), self.alice, self.bob),
            Obligation(Amount(200, "USD"), self.bob, self.alice),
        )
        state = UniversalState(
            arrangement=legs, parties=(self.alice, self.bob)
        )
        ref, resolved, sar = self._input(state)
        remaining = Obligation(Amount(200, "USD"), self.bob, self.alice)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(
            UniversalState(arrangement=remaining, parties=state.parties)
        )
        b.add_command(Settle(), self.alice.owning_key)
        self._ltx(b, resolved).verify()

    def test_settle_requires_payer_signature(self):
        legs = Obligation(Amount(100, "EUR"), self.alice, self.bob)
        state = UniversalState(
            arrangement=legs, parties=(self.alice, self.bob)
        )
        ref, resolved, sar = self._input(state)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_command(Settle(), self.bob.owning_key)
        with pytest.raises(TransactionVerificationError, match="did not sign"):
            self._ltx(b, resolved).verify()

    def test_settle_cannot_invent_obligations(self):
        legs = Obligation(Amount(100, "EUR"), self.alice, self.bob)
        state = UniversalState(
            arrangement=legs, parties=(self.alice, self.bob)
        )
        ref, resolved, sar = self._input(state)
        b = TransactionBuilder(notary=self.notary)
        b.add_input_state(sar)
        b.add_output_state(UniversalState(
            arrangement=Obligation(Amount(999, "GBP"), self.bob, self.alice),
            parties=state.parties,
        ))
        b.add_command(Settle(), self.alice.owning_key)
        with pytest.raises(TransactionVerificationError):
            self._ltx(b, resolved).verify()

"""Overload protection (ISSUE 5): admission control at the flow-start
seam, bounded queues with shed policies, and graceful degradation +
recovery under sustained overload (docs/robustness.md).

Acceptance: under a sustained 5x flow-start burst on a MockNetwork node,
queue depths and live-flow count stay under their configured caps,
rejections surface as NodeOverloadedError with a retry_after_ms hint
(never a hang or unbounded growth), priority/system traffic is never
shed before new client work, and /readyz flips 503 while shedding and
returns 200 after recovery.
"""
import json
import random
import threading
import time
import types
import urllib.request

import pytest

from corda_tpu.core.flows.api import FlowLogic, startable_by_rpc
from corda_tpu.loadtest.latency import _HoldFlow
from corda_tpu.messaging import (
    DEAD_LETTER_QUEUE,
    Broker,
    QueueFullError,
)
from corda_tpu.node.admission import (
    AdmissionController,
    NodeOverloadedError,
    OverloadStateMachine,
    TokenBucket,
)
from corda_tpu.testing import MockNetwork
from corda_tpu.utils.metrics import MetricRegistry


class SystemFlow(FlowLogic):
    """Marked system/priority: admission must never shed it."""

    _system_flow = True

    def call(self):
        return "system"
        yield  # pragma: no cover


@startable_by_rpc
class QuickFlow(FlowLogic):
    def __init__(self, n):
        self.n = n

    def call(self):
        return self.n * 2
        yield  # pragma: no cover


# ---------------------------------------------------------------------------
# token bucket + admission controller
# ---------------------------------------------------------------------------

class TestTokenBucket:
    def test_burst_then_refill(self):
        t = [0.0]
        b = TokenBucket(10, 5, clock=lambda: t[0])
        assert sum(1 for _ in range(10) if b.try_acquire()[0]) == 5
        ok, wait = b.try_acquire()
        assert not ok and wait == pytest.approx(0.1)
        t[0] += 0.1
        assert b.try_acquire()[0]

    def test_tokens_capped_at_burst(self):
        t = [0.0]
        b = TokenBucket(100, 4, clock=lambda: t[0])
        t[0] += 100
        assert b.tokens == 4


class TestAdmissionController:
    def _controller(self, **kw):
        kw.setdefault("metrics", MetricRegistry())
        return AdmissionController(**kw)

    def test_rate_limit_rejects_with_retry_hint(self):
        t = [0.0]
        c = self._controller(rate=2.0, burst=1, clock=lambda: t[0])
        c.admit()
        with pytest.raises(NodeOverloadedError) as err:
            c.admit()
        assert err.value.retry_after_ms >= 1
        assert c.admitted.value == 1
        assert c.rejected.value == 1
        assert c.rejected_rate.value == 1
        t[0] += 1.0  # bucket refills
        c.admit()

    def test_concurrency_cap(self):
        live = [0]
        c = self._controller(max_flows=2, live_flows=lambda: live[0])
        c.admit()
        live[0] = 2
        with pytest.raises(NodeOverloadedError):
            c.admit()
        assert c.rejected_cap.value == 1
        live[0] = 1
        c.admit()

    def test_priority_never_shed(self):
        c = self._controller(max_flows=1, live_flows=lambda: 99)
        with pytest.raises(NodeOverloadedError):
            c.admit()
        # responder flows and _system_flow classes pass unconditionally
        c.admit(flow=_HoldFlow(None), is_responder=True)
        c.admit(flow=SystemFlow())
        assert c.priority.value == 2
        assert c.rejected.value == 1

    def test_shedding_state_rejects_new_client_work(self):
        o = OverloadStateMachine(hold_s=1.0)
        sig = [100.0]
        o.add_signal("x", lambda: sig[0], high=10)
        c = self._controller(max_flows=100, live_flows=lambda: 0, overload=o)
        with pytest.raises(NodeOverloadedError) as err:
            c.admit()
        assert c.rejected_shedding.value == 1
        assert err.value.retry_after_ms == c.shed_retry_ms
        c.admit(is_responder=True)  # priority still flows while shedding


class TestOverloadStateMachine:
    def test_hysteresis_cycle(self):
        t = [0.0]
        sig = [0.0]
        o = OverloadStateMachine(hold_s=1.0, clock=lambda: t[0])
        o.add_signal("q", lambda: sig[0], high=10, low=2)
        assert o.evaluate() == "normal"
        sig[0] = 10
        assert o.evaluate() == "shedding"
        sig[0] = 5  # under high but over low: hysteresis holds shedding
        assert o.evaluate() == "shedding"
        sig[0] = 1
        assert o.evaluate() == "recovering"
        t[0] += 0.5
        assert o.evaluate() == "recovering"  # dwell not over
        sig[0] = 5  # noise above low restarts the dwell
        assert o.evaluate() == "recovering"
        sig[0] = 1
        t[0] += 0.9
        assert o.evaluate() == "recovering"
        t[0] += 1.1
        assert o.evaluate() == "normal"
        assert o.transitions == 3

    def test_high_breach_during_recovery_resheds(self):
        t = [0.0]
        sig = [20.0]
        o = OverloadStateMachine(hold_s=1.0, clock=lambda: t[0])
        o.add_signal("q", lambda: sig[0], high=10, low=2)
        assert o.evaluate() == "shedding"
        sig[0] = 0
        assert o.evaluate() == "recovering"
        sig[0] = 50
        assert o.evaluate() == "shedding"

    def test_snapshot_and_dead_signal_tolerated(self):
        o = OverloadStateMachine(hold_s=1.0)
        o.add_signal("boom", lambda: 1 / 0, high=10)
        assert o.evaluate() == "normal"  # a dead signal never wedges
        snap = o.snapshot()
        assert snap["state"] == "normal"
        assert "error" in snap["signals"]["boom"]


# ---------------------------------------------------------------------------
# bounded broker queues + shed policies
# ---------------------------------------------------------------------------

class TestBoundedBrokerQueues:
    def test_reject_new_raises_and_counts(self):
        b = Broker()
        sheds = []
        b.on_shed = lambda q, policy, msg: sheds.append((q, policy))
        b.create_queue("in", max_depth=2, shed_policy="reject")
        b.send("in", b"1")
        b.send("in", b"2")
        with pytest.raises(QueueFullError):
            b.send("in", b"3")
        assert b.message_count("in") == 2
        assert b.shed_counts == {"in": 1}
        assert sheds == [("in", "reject")]

    def test_drop_oldest_dead_letters_with_origin(self):
        b = Broker()
        b.create_queue("out", max_depth=2, shed_policy="drop_oldest")
        b.send("out", b"old")
        b.send("out", b"mid")
        b.send("out", b"new")
        assert b.message_count("out") == 2
        c = b.create_consumer("out")
        assert c.receive(timeout=1).payload == b"mid"  # oldest shed
        dlq = b.create_consumer(DEAD_LETTER_QUEUE)
        dead = dlq.receive(timeout=1)
        assert dead.payload == b"old"
        assert dead.headers["x-dead-from"] == "out"

    def test_dead_letter_queue_is_itself_bounded(self):
        from corda_tpu.messaging.broker import DEAD_LETTER_MAX

        b = Broker()
        b.create_queue("q", max_depth=1, shed_policy="drop_oldest")
        for i in range(DEAD_LETTER_MAX + 10):
            b.send("q", b"%d" % i)
        assert b.message_count(DEAD_LETTER_QUEUE) <= DEAD_LETTER_MAX

    def test_send_many_reject_is_all_or_nothing(self):
        b = Broker()
        b.create_queue("a", max_depth=2, shed_policy="reject")
        b.create_queue("b")
        with pytest.raises(QueueFullError):
            b.send_many([
                ("b", b"x", {}), ("a", b"1", {}), ("a", b"2", {}),
                ("a", b"3", {}),
            ])
        # nothing from the failed batch landed anywhere
        assert b.message_count("a") == 0
        assert b.message_count("b") == 0

    def test_durable_drop_oldest_never_redelivers_shed(self, tmp_path):
        jd = str(tmp_path / "journal")
        b = Broker(journal_dir=jd)
        b.create_queue("dur", durable=True)
        b.set_queue_bound("dur", 2, "drop_oldest")
        b.send("dur", b"one")
        b.send("dur", b"two")
        b.send("dur", b"three")  # sheds "one", journal-acked
        b.close()
        b2 = Broker(journal_dir=jd)
        c = b2.create_consumer("dur")
        got = {c.receive(timeout=1).payload for _ in range(2)}
        assert got == {b"two", b"three"}
        assert c.receive(timeout=0.05) is None  # "one" must NOT resurrect
        b2.close()

    def test_queue_full_crosses_the_wire(self):
        from corda_tpu.messaging.net import BrokerServer, RemoteBroker

        b = Broker()
        b.create_queue("remote", max_depth=1, shed_policy="reject")
        server = BrokerServer(b).start()
        try:
            rb = RemoteBroker(server.host, server.port)
            rb.send("remote", b"1")
            with pytest.raises(QueueFullError):
                rb.send("remote", b"2")
            rb.close()
        finally:
            server.stop()


class TestInMemoryNetworkCaps:
    def test_reject_policy_backpressures_sender(self):
        from corda_tpu.node.network import InMemoryMessagingNetwork
        from corda_tpu.core.identity import Party

        net = InMemoryMessagingNetwork()
        a = net.create_endpoint(Party("A", None))
        net.create_endpoint(Party("B", None))
        net.set_recipient_cap("B", 2, "reject")
        a.send(Party("B", None), "t", b"1")
        a.send(Party("B", None), "t", b"2")
        with pytest.raises(QueueFullError):
            a.send(Party("B", None), "t", b"3")
        assert net.queue_depth("B") == 2
        assert net.shed_counts["B"] == 1

    def test_drop_oldest_policy_dead_letters(self):
        from corda_tpu.node.network import InMemoryMessagingNetwork
        from corda_tpu.core.identity import Party

        net = InMemoryMessagingNetwork()
        a = net.create_endpoint(Party("A", None))
        net.create_endpoint(Party("B", None))
        net.set_recipient_cap("B", 1, "drop_oldest")
        a.send(Party("B", None), "t", b"old")
        a.send(Party("B", None), "t", b"new")
        assert net.queue_depth("B") == 1
        assert len(net.dead_letters) == 1
        assert net.dead_letters[0].payload == b"old"


# ---------------------------------------------------------------------------
# batcher flush-queue backpressure + bounded notary queue
# ---------------------------------------------------------------------------

class TestBatcherBackpressure:
    def test_submit_blocks_at_flush_queue_cap(self, monkeypatch):
        from corda_tpu.verifier import batcher as batcher_mod
        from corda_tpu.verifier.batcher import SignatureBatcher

        gate = threading.Event()

        def slow_verify(items):
            gate.wait(timeout=10)
            return [True] * len(items)

        monkeypatch.setattr(
            batcher_mod.crypto_batch, "verify_batch", slow_verify
        )
        # pipeline=False: this pins the SYNC path's flush-queue-cap
        # backpressure by stubbing verify_batch (the staged pipeline's
        # ring backpressure is pinned in tests/test_pipeline.py)
        b = SignatureBatcher(max_batch=1, linger_ms=10_000,
                             max_queued_batches=1, pipeline=False)
        item = (None, b"sig", b"content")
        f1 = b.submit(item)  # hands off; flush thread blocks in verify
        # wait until the first batch is actually in flight so the next
        # handoff occupies the single queue slot
        deadline = time.monotonic() + 5
        while b.in_flight == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        f2 = b.submit(item)  # queued: flush queue now at its cap
        done = threading.Event()
        result = {}

        def third():
            result["f3"] = b.submit(item)
            done.set()

        t = threading.Thread(target=third, daemon=True)
        t.start()
        assert not done.wait(timeout=0.3), (
            "submit must BLOCK while the flush queue is at its cap"
        )
        gate.set()  # unblock the verifier; queue drains; submit resumes
        assert done.wait(timeout=10)
        assert b.backpressure_waits >= 1
        for f in (f1, f2, result["f3"]):
            assert f.result(timeout=10) is True
        b.close()


class TestNotaryQueueBound:
    def test_overflow_sheds_with_retryable_unavailable(self):
        from corda_tpu.node.notary import (
            CoalescingUniquenessProvider,
            NotaryException,
        )

        gate = threading.Event()
        started = threading.Event()

        class SlowDelegate:
            def commit_many(self, requests):
                started.set()
                gate.wait(timeout=10)
                return [None] * len(requests)

        p = CoalescingUniquenessProvider(SlowDelegate(), max_queue=1)
        party = types.SimpleNamespace(name="N")
        tx = types.SimpleNamespace(bytes=b"\x01" * 32)
        errs, oks = [], []

        def commit():
            try:
                p.commit([], tx, party)
                oks.append(1)
            except NotaryException as exc:
                errs.append(exc)

        t1 = threading.Thread(target=commit, daemon=True)
        t1.start()
        assert started.wait(timeout=5)  # t1 is the drainer, mid-round
        t2 = threading.Thread(target=commit, daemon=True)
        t2.start()
        deadline = time.monotonic() + 5
        while len(p._pending) < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        commit()  # queue full: must shed synchronously on THIS thread
        gate.set()
        t1.join(timeout=10)
        t2.join(timeout=10)
        assert p.sheds == 1
        assert len(errs) == 1 and "unavailable" in str(errs[0])
        assert len(oks) == 2

        # the shed is hospital-transient: admitted flows retry from
        # their checkpoint instead of dying
        from corda_tpu.node.hospital import FlowHospital

        hospital = FlowHospital(
            types.SimpleNamespace(metrics=MetricRegistry())
        )
        assert hospital.classify(errs[0]) == "transient"


# ---------------------------------------------------------------------------
# health: liveness/readiness split + sustained degradation
# ---------------------------------------------------------------------------

class TestHealthDegradation:
    def test_sustained_breach_debounces(self):
        from corda_tpu.node.health import SustainedBreach

        t = [0.0]
        s = SustainedBreach(5.0, clock=lambda: t[0])
        assert not s.observe(True)  # first sighting: not sustained yet
        t[0] = 4.9
        assert not s.observe(True)
        t[0] = 5.1
        assert s.observe(True)
        assert not s.observe(False)  # recovery clears immediately
        t[0] = 20.0
        assert not s.observe(True)  # fresh breach restarts the window

    def test_liveness_false_check_degrades_readyz_only(self):
        from corda_tpu.node.health import HealthTracker

        h = HealthTracker()
        h.mark_serving()
        h.register("overload", lambda: {"ok": False, "state": "shedding"},
                   readiness=True, liveness=False)
        code, body = h.healthz()
        assert code == 200, body  # shedding is not sickness
        assert body["checks"]["overload"]["state"] == "shedding"
        code, body = h.readyz()
        assert code == 503
        assert "overload" in body["cause"]

    def test_sustained_queue_depth_degrades_node_readyz(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_HEALTH_SUSTAIN_S", "0")
        monkeypatch.setenv("CORDA_TPU_HEALTH_QDEPTH_DEGRADE", "3")
        net = MockNetwork()
        try:
            a = net.create_node("O=DepthA,L=London,C=GB")
            b = net.create_node("O=DepthB,L=Paris,C=FR")
            code, _ = a.health.readyz()
            assert code == 200
            for _ in range(6):  # flood A's inbound backlog, never pump
                b.network.send(a.info, "noise", b"x")
            code, body = a.health.readyz()
            assert code == 503
            assert "degraded" in body["checks"]["backpressure"]
            # overload-class degradation must NOT fail liveness: an
            # orchestrator restart would destroy the in-flight work
            code, _ = a.health.healthz()
            assert code == 200
            net.run_network()  # drain -> readiness returns immediately
            code, _ = a.health.readyz()
            assert code == 200
        finally:
            net.stop_nodes()


# ---------------------------------------------------------------------------
# the ISSUE acceptance test: sustained 5x burst on a MockNetwork node
# ---------------------------------------------------------------------------

class TestSustainedOverloadAcceptance:
    def test_burst_sheds_degrades_and_recovers(self, monkeypatch):
        monkeypatch.setenv("CORDA_TPU_OVERLOAD_HOLD_S", "0.05")
        net = MockNetwork()
        try:
            a = net.create_node(
                "O=LoadedNode,L=London,C=GB", admission_max_flows=8,
            )
            b = net.create_node("O=Peer,L=Paris,C=FR")
            net.messaging_network.set_recipient_cap("O=Peer,L=Paris,C=FR",
                                                    64, "reject")
            handles, rejections = [], []
            for _ in range(40):  # 5x the live-flow cap, without pumping
                try:
                    handles.append(
                        a.start_flow(_HoldFlow(b.info), b.info)
                    )
                except NodeOverloadedError as exc:
                    rejections.append(exc)
            # bounded, typed, hinted — never hung or unbounded
            assert len(handles) == 8
            assert len(rejections) == 32
            assert all(r.retry_after_ms > 0 for r in rejections)
            assert a.smm.in_flight_count <= 8
            assert net.messaging_network.queue_depth("O=Peer,L=Paris,C=FR") <= 64

            # degradation: /readyz 503 while shedding, /healthz 200 with
            # the overload component detail
            code, body = a.health.readyz()
            assert code == 503
            assert body["checks"]["overload"]["state"] == "shedding"
            code, body = a.health.healthz()
            assert code == 200
            assert body["checks"]["overload"]["state"] == "shedding"

            # priority traffic is never shed before new client work:
            # a system flow starts fine mid-shed...
            h_sys = a.start_flow(SystemFlow())
            assert a.admission.priority.value >= 1
            # ...and a responder (a peer's already-admitted flow) spawns
            # on the shedding node without rejection once delivered
            h_peer = b.start_flow(_HoldFlow(a.info), a.info)

            # recovery: drain the load, the machine walks back to
            # normal, /readyz returns 200
            net.run_network()
            assert a.admission.priority.value >= 2  # the responder too
            assert h_sys.result.result(timeout=10) == "system"
            assert h_peer.result.result(timeout=10) == b"ok"
            assert all(
                h.result.result(timeout=10) == b"ok" for h in handles
            )
            deadline = time.monotonic() + 10
            while True:
                code, _ = a.health.readyz()
                if code == 200:
                    break
                assert time.monotonic() < deadline, "readyz never recovered"
                time.sleep(0.01)
            snap = a.admission.snapshot()
            assert snap["rejected"] == 32
            assert snap["admitted"] == 8
        finally:
            net.stop_nodes()


# ---------------------------------------------------------------------------
# RPC propagation: CordaRPCClient sees the typed error + retry hint
# ---------------------------------------------------------------------------

class TestRPCOverloadPropagation:
    def test_client_gets_typed_error_with_retry_hint(self):
        from corda_tpu.rpc import CordaRPCClient, CordaRPCOps, RPCServer

        net = MockNetwork()
        broker = Broker()
        server = client = None
        try:
            node = net.create_node(
                "O=RpcLoaded,L=London,C=GB",
                admission_rate=0.5, admission_burst=1,
            )
            ops = CordaRPCOps(node.services, node.smm)
            server = RPCServer(broker, ops)
            client = CordaRPCClient(broker)
            conn = client.start("admin", "admin")
            fid = conn.proxy.start_flow_dynamic("QuickFlow", 21)
            assert conn.proxy.flow_result(fid, 10) == 42
            with pytest.raises(NodeOverloadedError) as err:
                conn.proxy.start_flow_dynamic("QuickFlow", 2)
            # the hint crossed the RPC boundary intact (bucket refill
            # time at 0.5 flows/s ~ 2 s)
            assert err.value.retry_after_ms >= 1000
            conn.close()
        finally:
            if client is not None:
                client.close()
            if server is not None:
                server.stop()
            net.stop_nodes()
            broker.close()


# ---------------------------------------------------------------------------
# satellites: upload slot accounting, hospital jitter
# ---------------------------------------------------------------------------

class TestUploadSlotAccounting:
    def _ops(self, net):
        from corda_tpu.rpc import CordaRPCOps

        node = net.create_node("O=Upload,L=London,C=GB")
        return CordaRPCOps(node.services, node.smm)

    def test_max_concurrent_uploads_rejection_and_abort_release(self):
        net = MockNetwork()
        try:
            ops = self._ops(net)
            ids = [ops.upload_attachment_begin()
                   for _ in range(ops.MAX_CONCURRENT_UPLOADS)]
            with pytest.raises(ValueError, match="too many concurrent"):
                ops.upload_attachment_begin()
            # abort releases the slot immediately (idempotent)
            assert ops.upload_attachment_abort(ids[0]) is True
            assert ops.upload_attachment_abort(ids[0]) is False
            ops.upload_attachment_begin()
        finally:
            net.stop_nodes()

    def test_error_mid_stream_releases_slot(self, monkeypatch):
        from corda_tpu.rpc.ops import CordaRPCOps as OpsCls

        net = MockNetwork()
        try:
            ops = self._ops(net)
            monkeypatch.setattr(OpsCls, "MAX_ATTACHMENT_SIZE", 8)
            monkeypatch.setattr(OpsCls, "MAX_CONCURRENT_UPLOADS", 1)
            uid = ops.upload_attachment_begin()
            with pytest.raises(ValueError, match="exceeds"):
                ops.upload_attachment_chunk(uid, b"0123456789")
            # the failed upload's slot is free again — no leak
            uid2 = ops.upload_attachment_begin()
            ops.upload_attachment_chunk(uid2, b"ok")
            att_id = ops.upload_attachment_end(uid2)
            assert ops.attachment_exists(att_id)
            # ...and completing released the slot too
            ops.upload_attachment_begin()
        finally:
            net.stop_nodes()


class TestHospitalRetryJitter:
    def test_scheduled_retries_are_spread(self):
        from concurrent.futures import Future

        from corda_tpu.node.hospital import FlowHospital, TransientFlowError

        smm = types.SimpleNamespace(metrics=MetricRegistry())
        hospital = FlowHospital(
            smm, enabled=True, max_retries=3,
            backoff_s=1.0, backoff_cap_s=1.0,  # raw delay fixed at 1.0 s
            rng=random.Random(42),
        )
        try:
            delays = []
            for i in range(8):
                fsm = types.SimpleNamespace(
                    flow_id=f"flow-{i}",
                    flow=types.SimpleNamespace(
                        flow_name=lambda: "SharedOutageFlow"
                    ),
                    result=Future(),
                    is_responder=False,
                )
                delays.append(
                    hospital.consider(fsm, TransientFlowError("outage"))
                )
            # a shared outage admits the herd in the same instant; jitter
            # must spread the replays instead of re-releasing them at once
            assert all(0.5 <= d < 1.0 for d in delays), delays
            assert len({round(d, 3) for d in delays}) >= 6, delays
            assert max(delays) - min(delays) > 0.1
            snap = hospital.snapshot()
            retry_times = [r["next_retry_at"] for r in snap["recovering"]]
            assert len({round(t, 3) for t in retry_times}) >= 6
        finally:
            hospital.close()


# ---------------------------------------------------------------------------
# tooling/CI: gate coverage + /metrics exposition
# ---------------------------------------------------------------------------

class TestGateCoversOverloadStage:
    def test_overload_keys_are_direction_classified(self):
        from corda_tpu.loadtest.gate import direction

        assert direction("overload_shed_recovery_ms") == "lower"
        assert direction("overload_goodput_per_sec") == "higher"

    def test_recovery_regression_fails_the_gate(self):
        from corda_tpu.loadtest.gate import run_gate

        prev = {"stage_timings": {"overload_shed_recovery_ms": 100.0,
                                  "overload_goodput_per_sec": 50.0}}
        cur = {"stage_timings": {"overload_shed_recovery_ms": 300.0,
                                 "overload_goodput_per_sec": 50.0}}
        verdict = run_gate(cur, prev)
        assert not verdict["ok"]
        assert verdict["regressions"][0]["key"] == (
            "stage_timings.overload_shed_recovery_ms"
        )
        assert run_gate(prev, prev)["ok"]  # clean run passes

    def test_shed_rate_slo_breach_fails_and_clean_passes(self):
        from corda_tpu.loadtest.gate import check_slos

        slos = {"shed_rate": {"max": 0.5}}
        breach = check_slos({"shed_rate": 0.93}, slos)
        assert breach and breach[0]["kind"] == "max"
        assert check_slos({"shed_rate": 0.2}, slos) == []

    def test_bench_gate_cli_enforces_shed_rate_slo(self, tmp_path):
        import os
        import subprocess
        import sys

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cli = os.path.join(here, "tools", "bench_gate.py")
        # empty --repo: no BENCH_r*.json baseline, so only the SLO gates
        repo = str(tmp_path)

        def run_cli(record):
            return subprocess.run(
                [sys.executable, cli, "--current", "-", "--repo", repo,
                 "--slo", "shed_rate<=0.5"],
                input=json.dumps(record), text=True, capture_output=True,
            )

        breach = run_cli({"shed_rate": 0.9})
        assert breach.returncode == 1, breach.stderr
        clean = run_cli({"shed_rate": 0.1})
        assert clean.returncode == 0, clean.stderr


class TestMetricsExposition:
    def test_admission_and_shed_families_render_valid_prometheus(self):
        import re

        net = MockNetwork()
        try:
            node = net.create_node(
                "O=OverloadProm,L=London,C=GB", ops_port=0,
                admission_max_flows=2,
            )
            peer = net.create_node("O=PromPeer,L=Paris,C=FR")
            for _ in range(4):  # drive both admit and reject counters
                try:
                    node.start_flow(_HoldFlow(peer.info), peer.info)
                except NodeOverloadedError:
                    pass
            with urllib.request.urlopen(
                f"http://127.0.0.1:{node.ops_server.port}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
        finally:
            net.stop_nodes()
        for family in (
            "corda_tpu_admission_admitted_total",
            "corda_tpu_admission_rejected_total",
            "corda_tpu_admission_rejected_by_cap_total",
            "corda_tpu_shed_dead_lettered_total",
            "corda_tpu_shed_rejected_sends_total",
            "corda_tpu_overload_state",
        ):
            assert f"\n{family}" in body or body.startswith(family), family
        # strict exposition validity over the whole scrape
        sample_re = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
            r'(\{[a-zA-Z0-9_]+="[^"]*"(,[a-zA-Z0-9_]+="[^"]*")*\})?'
            r" -?[0-9.eE+-]+$"
        )
        families = []
        for line in body.splitlines():
            if line.startswith("# TYPE "):
                families.append(line.split()[2])
                continue
            if line.startswith("#"):
                continue
            assert sample_re.match(line), f"bad sample line: {line}"
        assert len(families) == len(set(families)), "duplicate TYPE family"


# ---------------------------------------------------------------------------
# loadtest scenario + disruption
# ---------------------------------------------------------------------------

class TestSustainedOverloadScenario:
    def test_scenario_bounded_goodput_and_recovery(self, monkeypatch):
        from corda_tpu.loadtest.harness import Nodes
        from corda_tpu.loadtest.tests import SustainedOverloadLoadTest

        monkeypatch.setenv("CORDA_TPU_OVERLOAD_HOLD_S", "0.05")
        net = MockNetwork()
        try:
            a = net.create_node(
                "O=SoakA,L=London,C=GB", admission_max_flows=4,
            )
            b = net.create_node("O=SoakB,L=Paris,C=FR")
            nodes = Nodes(network=net, notary=a, nodes=[a, b])
            result = SustainedOverloadLoadTest(burst_factor=5).run(
                nodes, iterations=3, parallelism=4,
                slos={
                    "shed_rate": {"max": 0.99},
                    "recovered": {"min": 1.0},
                    "max_live_flows": {"max": 4.0},
                    "bad_rejections": {"max": 0.0},
                },
            )
            assert result.ok, (result.errors, result.slo_violations)
            assert result.metrics["shed_rate"] > 0.5  # 5x burst DID shed
            assert result.metrics["completed"] == result.metrics["admitted"]
            # the same run fails a strict shed-rate SLO — the gate seam
            # the CI satellite relies on
            from corda_tpu.loadtest.gate import check_slos

            assert check_slos(result.metrics, {"shed_rate": {"max": 0.01}})
        finally:
            net.stop_nodes()

    def test_overload_burst_disruption(self, monkeypatch):
        from corda_tpu.loadtest.disruption import overload_burst
        from corda_tpu.loadtest.harness import Nodes

        monkeypatch.setenv("CORDA_TPU_OVERLOAD_HOLD_S", "0.05")
        net = MockNetwork()
        try:
            a = net.create_node(
                "O=BurstA,L=London,C=GB", admission_max_flows=4,
            )
            b = net.create_node("O=BurstB,L=Paris,C=FR")
            nodes = Nodes(network=net, notary=a, nodes=[a, b])
            d = overload_burst(burst=20, probability=1.0)
            rng = random.Random(0)
            d.maybe_fire(rng, nodes, 0)
            assert d.state["admitted"] == 4
            assert d.state["shed"] == 16
            assert a.smm.in_flight_count <= 4
            d.maybe_heal(rng, nodes, 2)  # heal_after=2 -> pump + drain
            assert a.smm.in_flight_count == 0
        finally:
            net.stop_nodes()

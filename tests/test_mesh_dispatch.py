"""Mesh-sharded dispatch stage tests (docs/perf-pipeline.md, scale-out).

Differential guarantees this file pins:

* mesh-vs-single-device BIT-IDENTITY over a fuzz corpus at every mesh
  width the 8-virtual-CPU-device conftest can build (n = 1, 2, 4, 8) —
  the kill-switch contract: CORDA_TPU_MESH_DEVICES must never change a
  verdict, only where it is computed;
* ragged-tail masking: batches below / equal to / above the mesh width
  pad per shard, and a padding row can never flip a verdict or leak
  into the psum'd valid count;
* MeshDispatcher stage semantics: telemetry, failure latch + fallback,
  and the pipeline's stage-isolation contract (one poisoned batch fails
  alone);
* worker device placement (CORDA_TPU_MESH_WORKER_SLOT) and the
  regression-gate / provenance plumbing for mesh_sigs_s.
"""
import numpy as np
import pytest

from corda_tpu.core.crypto import batch as crypto_batch
from corda_tpu.core.crypto import crypto, ed25519_math
from corda_tpu.ops import ed25519_batch
from corda_tpu.parallel import data_mesh, shard_layout, worker_slot_mesh
from corda_tpu.parallel import mesh as mesh_mod
from corda_tpu.verifier.pipeline import MeshDispatcher, VerificationPipeline


def _fuzz_corpus(n=24, seed=42):
    """The ops-level fuzz corpus (same mutation ladder as
    test_ops_ed25519.test_agrees_with_host_oracle_fuzz): one in four
    rows valid, the rest tampered sig / extended msg / garbage key."""
    rng = np.random.default_rng(seed)
    pubs, sigs, msgs, expect = [], [], [], []
    for i in range(n):
        sk = rng.bytes(32)
        pub = ed25519_math.public_from_seed(sk)
        msg = rng.bytes(int(rng.integers(1, 200)))
        sig = ed25519_math.sign(sk, msg)
        kind = i % 4
        if kind == 1:
            sig = bytes([sig[0] ^ 0xFF]) + sig[1:]
        elif kind == 2:
            msg = msg + b"!"
        elif kind == 3:
            pub = rng.bytes(32)
        pubs.append(pub)
        sigs.append(sig)
        msgs.append(msg)
        expect.append(ed25519_math.verify(pub, msg, sig))
    return pubs, sigs, msgs, expect


def _items(n, entropy0=7000, tamper_idx=()):
    """Production-shape (public_key, signature, content) rows."""
    out = []
    for i in range(n):
        kp = crypto.entropy_to_keypair(entropy0 + i)
        content = b"mesh dispatch row %d" % i
        sig = crypto.do_sign(kp.private, content)
        if i in tamper_idx:
            content = b"forged"
        out.append((kp.public, sig, content))
    return out


# ---------------------------------------------------------------------------
# bit-identity: the mesh must agree with the single-device kernel exactly
# ---------------------------------------------------------------------------

class TestMeshBitIdentity:
    @pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
    def test_fuzz_corpus_identical_at_every_width(self, n_dev):
        """Verdict vector at mesh width n == the unsharded kernel's ==
        the host oracle's, bit for bit. n=1 is the degenerate mesh: one
        shard must reproduce the CORDA_TPU_MESH_DEVICES=0 path."""
        pubs, sigs, msgs, expect = _fuzz_corpus()
        single = [bool(b) for b in ed25519_batch.verify_batch(pubs, sigs, msgs)]
        mask, total = mesh_mod.shard_verify(
            data_mesh(n_dev), "ed25519", pubs, sigs, msgs, return_total=True
        )
        assert [bool(b) for b in mask] == single == expect
        assert total == sum(expect)

    @pytest.mark.parametrize("n", [3, 8, 11, 29])
    def test_ragged_tails_below_equal_above_mesh_width(self, n):
        """Batch sizes below (3), equal to (8) and above (11, 29) the
        8-device mesh width: the trailing shards carry padding rows,
        which must neither appear in the mask nor inflate the psum."""
        pubs, sigs, msgs, expect = _fuzz_corpus(n, seed=100 + n)
        mask, total = mesh_mod.shard_verify(
            data_mesh(8), "ed25519", pubs, sigs, msgs, return_total=True
        )
        assert mask.shape == (n,)
        assert [bool(b) for b in mask] == expect
        assert total == sum(expect)

    def test_shard_layout_padding_math(self):
        """The documented padding math: per-shard bucket is the next
        power of two (min 8), the batch pads to per_device * n_dev, and
        occupancy counts REAL rows only."""
        mesh = data_mesh(4)
        per_device, padded, occ = shard_layout(mesh, "ed25519", 10)
        assert per_device == 8
        assert padded == 32
        assert occ == [8, 2, 0, 0]
        assert sum(occ) == 10
        # a full batch leaves no padding anywhere
        _, _, occ_full = shard_layout(mesh, "ed25519", 32)
        assert occ_full == [8, 8, 8, 8]


# ---------------------------------------------------------------------------
# MeshDispatcher: the injectable pipeline stage
# ---------------------------------------------------------------------------

class TestMeshDispatcher:
    def test_stages_verify_and_record_telemetry(self):
        md = MeshDispatcher(n_devices=4, min_batch=8)
        items = _items(12, tamper_idx={2, 7})
        plan = md.plan(items)
        plan = crypto_batch.prehash_plan(plan)
        plan = md.dispatch(plan)
        out = crypto_batch.collect_plan(plan)
        host = [crypto.is_valid(k, s, c) for k, s, c in items]
        assert out == host == [i not in {2, 7} for i in range(12)]
        # the psum'd mesh-wide valid count reached the dispatcher
        assert plan.mesh_totals == {"ed25519": 10}
        assert md.valid_total == 10
        assert md.dispatches == 1
        assert md.devices == 4
        # occupancy counts REAL rows per shard (12 rows, bucket 8)
        occ = [md.shard_occupancy(k) for k in range(4)]
        assert occ == [8, 4, 0, 0]

    def test_below_min_batch_stays_single_device(self):
        md = MeshDispatcher(n_devices=2, min_batch=64)
        items = _items(6, entropy0=7100, tamper_idx={1})
        plan = md.plan(items)
        plan = crypto_batch.prehash_plan(plan)
        plan = md.dispatch(plan)
        out = crypto_batch.collect_plan(plan)
        assert out == [True, False, True, True, True, True]
        assert plan.mesh_totals == {}
        assert md.dispatches == 0

    def test_shard_failure_latches_and_falls_back(self, monkeypatch):
        """A broken mesh lowering costs one batch's mesh attempt: the
        verdicts still come back (single-device fallback), the
        dispatcher latches off, and shard_verify is never tried again."""
        calls = []

        def boom(*a, **k):
            calls.append(1)
            raise RuntimeError("mesh lowering failed (simulated)")

        monkeypatch.setattr(mesh_mod, "shard_verify", boom)
        md = MeshDispatcher(n_devices=2, min_batch=4)
        items = _items(8, entropy0=7200, tamper_idx={5})
        plan = md.plan(items)
        plan = crypto_batch.prehash_plan(plan)
        plan = md.dispatch(plan)
        out = crypto_batch.collect_plan(plan)
        assert out == [i != 5 for i in range(8)]
        assert plan.mesh_failed
        assert md.devices == 0  # the Mesh.Devices gauge signal
        assert calls == [1]
        # the process-global latch must NOT have been poisoned by this
        # engine-scoped failure
        assert not crypto_batch._mesh_failed_once
        # second batch: latched dispatcher plans without a mesh
        plan2 = md.plan(items)
        plan2 = crypto_batch.prehash_plan(plan2)
        plan2 = md.dispatch(plan2)
        assert crypto_batch.collect_plan(plan2) == out
        assert calls == [1]

    def test_pipeline_stage_isolation_one_poisoned_batch(self, monkeypatch):
        """The pipeline's stage-isolation contract with the mesh stage
        injected: a batch whose dispatch raises fails ONLY its own
        future; batches before and after verify normally."""
        md = MeshDispatcher(n_devices=2, min_batch=4)
        real_dispatch = crypto_batch.dispatch_plan

        def flaky(plan):
            if any(c == b"poison" for _, _, c in plan.flat):
                raise RuntimeError("injected shard failure")
            return real_dispatch(plan)

        monkeypatch.setattr(crypto_batch, "dispatch_plan", flaky)
        good = _items(8, entropy0=7300, tamper_idx={3})
        kp = crypto.entropy_to_keypair(7399)
        poison = [(kp.public, crypto.do_sign(kp.private, b"poison"),
                   b"poison")] * 8
        p = VerificationPipeline(stages=md.stages(), depth=2, name="mesh-iso")
        try:
            f1 = p.submit(good)
            f2 = p.submit(poison)
            f3 = p.submit(good)
            assert f1.result(60) == [i != 3 for i in range(8)]
            with pytest.raises(RuntimeError, match="injected shard failure"):
                f2.result(60)
            assert f3.result(60) == [i != 3 for i in range(8)]
            assert p.failures == 1
            assert p.batches == 3
        finally:
            p.stop()

    def test_mesh_gauges_bound_through_pipeline(self):
        from corda_tpu.utils.metrics import MetricRegistry

        reg = MetricRegistry()
        md = MeshDispatcher(n_devices=2, min_batch=4)
        p = VerificationPipeline(
            stages=md.stages(), depth=2, name="mesh-metered", registry=reg,
        )
        try:
            assert p.mesh_dispatcher is md
            assert reg.gauge("Mesh.Devices").value == 2
            assert reg.gauge("Mesh.ValidTotal").value == 0
            out = p.submit(_items(8, entropy0=7400)).result(60)
            assert out == [True] * 8
            assert reg.gauge("Mesh.ValidTotal").value == 8
            assert (
                reg.gauge("Mesh.ShardOccupancy{n=0}").value
                + reg.gauge("Mesh.ShardOccupancy{n=1}").value
            ) == 8
        finally:
            p.stop()

    def test_rejects_zero_devices(self):
        with pytest.raises(ValueError):
            MeshDispatcher(n_devices=0)


class TestMeshKnob:
    def test_mesh_devices_parsing(self, monkeypatch):
        from corda_tpu.verifier import pipeline as pl

        monkeypatch.delenv("CORDA_TPU_MESH_DEVICES", raising=False)
        assert pl.mesh_devices() == 0
        monkeypatch.setenv("CORDA_TPU_MESH_DEVICES", "4")
        assert pl.mesh_devices() == 4
        monkeypatch.setenv("CORDA_TPU_MESH_DEVICES", "junk")
        assert pl.mesh_devices() == 0
        monkeypatch.setenv("CORDA_TPU_MESH_DEVICES", "-2")
        assert pl.mesh_devices() == 0
        monkeypatch.setenv("CORDA_TPU_MESH_DEVICES", "")
        assert pl.mesh_devices() == 0

    def test_default_stages_swap_behind_knob(self, monkeypatch):
        """CORDA_TPU_MESH_DEVICES>0 swaps decode/dispatch for the
        dispatcher's bound methods; 0 keeps the stock stage functions —
        the stage GRAPH (names, order) is identical either way."""
        from corda_tpu.verifier import pipeline as pl

        monkeypatch.delenv("CORDA_TPU_MESH_DEVICES", raising=False)
        stock = pl.default_stages()
        names = [n for n, _ in stock]
        assert names == ["decode", "prehash", "dispatch", "collect"]
        assert all(
            not isinstance(getattr(fn, "__self__", None), pl.MeshDispatcher)
            for _, fn in stock
        )
        monkeypatch.setenv("CORDA_TPU_MESH_DEVICES", "4")
        meshed = pl.default_stages()
        assert [n for n, _ in meshed] == names
        owner = dict(meshed)["dispatch"].__self__
        assert isinstance(owner, pl.MeshDispatcher)
        assert owner.n_devices == 4


# ---------------------------------------------------------------------------
# worker device placement
# ---------------------------------------------------------------------------

class TestWorkerPlacement:
    def test_worker_slot_mesh_disjoint_slices(self):
        ids0 = [int(d.id) for d in worker_slot_mesh(2, 0).devices.flat]
        ids1 = [int(d.id) for d in worker_slot_mesh(2, 1).devices.flat]
        ids3 = [int(d.id) for d in worker_slot_mesh(2, 3).devices.flat]
        assert ids0 == [0, 1]
        assert ids1 == [2, 3]
        assert ids3 == [6, 7]
        assert not (set(ids0) & set(ids1))

    def test_worker_slot_mesh_bounds(self):
        # slot 2 of width 4 needs devices [8, 12); conftest pins 8
        with pytest.raises(ValueError):
            worker_slot_mesh(4, 2)
        with pytest.raises(ValueError):
            worker_slot_mesh(0, 0)
        with pytest.raises(ValueError):
            worker_slot_mesh(2, -1)

    def test_worker_slot_env_parsing(self, monkeypatch):
        from corda_tpu.verifier import worker

        monkeypatch.delenv("CORDA_TPU_MESH_WORKER_SLOT", raising=False)
        assert worker.worker_slot() is None
        monkeypatch.setenv("CORDA_TPU_MESH_WORKER_SLOT", "3")
        assert worker.worker_slot() == 3
        monkeypatch.setenv("CORDA_TPU_MESH_WORKER_SLOT", "junk")
        assert worker.worker_slot() is None
        monkeypatch.setenv("CORDA_TPU_MESH_WORKER_SLOT", "-1")
        assert worker.worker_slot() is None

    def test_placement_mesh_follows_slot(self, monkeypatch):
        from corda_tpu.verifier import worker

        monkeypatch.delenv("CORDA_TPU_MESH_WORKER_SLOT", raising=False)
        assert [
            int(d.id) for d in worker.placement_mesh(2).devices.flat
        ] == [0, 1]
        monkeypatch.setenv("CORDA_TPU_MESH_WORKER_SLOT", "3")
        assert [
            int(d.id) for d in worker.placement_mesh(2).devices.flat
        ] == [6, 7]
        # a misplaced worker fails loudly at startup
        monkeypatch.setenv("CORDA_TPU_MESH_WORKER_SLOT", "4")
        with pytest.raises(ValueError):
            worker.placement_mesh(2)

    def test_mesh_placement_healthcheck_view(self, monkeypatch):
        from corda_tpu.verifier import worker

        monkeypatch.delenv("CORDA_TPU_MESH_WORKER_SLOT", raising=False)
        assert worker.mesh_placement() == {
            "devices": 0, "device_ids": [], "worker_slot": None,
        }
        crypto_batch.configure_mesh(data_mesh(2))
        try:
            view = worker.mesh_placement()
        finally:
            crypto_batch.configure_mesh(None)
        assert view["devices"] == 2
        assert view["device_ids"] == [0, 1]


# ---------------------------------------------------------------------------
# regression gate + provenance plumbing
# ---------------------------------------------------------------------------

class TestMeshGate:
    def test_direction_classifies_labelled_mesh_keys(self):
        from corda_tpu.loadtest import gate

        assert gate.direction("mesh_sigs_s") == "higher"
        assert gate.direction("mesh_sigs_s{n=4}") == "higher"
        assert gate.direction("stage_timings.mesh_sigs_s{n=8}") == "higher"
        assert gate.direction("mesh_stage_error{n=4}") is None

    def test_gate_trips_on_mesh_scaling_regression(self):
        """A synthetic 50% collapse of one mesh scaling point must trip
        the gate (same-env records, so no cross-env demotion); the
        mirror-image improvement must pass."""
        from corda_tpu.loadtest import gate

        fp = {"backend": "cpu", "shards": 0, "node_workers": 0}
        fast = {
            "stage_timings": {
                "mesh_sigs_s{n=1}": 300.0, "mesh_sigs_s{n=4}": 1000.0,
            },
            "env_fingerprint": fp,
        }
        slow = {
            "stage_timings": {
                "mesh_sigs_s{n=1}": 300.0, "mesh_sigs_s{n=4}": 500.0,
            },
            "env_fingerprint": fp,
        }
        tripped = gate.run_gate(slow, fast)
        assert not tripped["ok"]
        assert [r["key"] for r in tripped["regressions"]] == [
            "stage_timings.mesh_sigs_s{n=4}"
        ]
        assert tripped["regressions"][0]["direction"] == "higher"
        improved = gate.run_gate(fast, slow)
        assert improved["ok"]
        assert improved["regressions"] == []

    def test_load_multichip_record_shapes(self, tmp_path):
        """All three MULTICHIP artifact generations load into a
        gate-comparable record: parsed block, MULTICHIP_JSON tail line,
        legacy prose-only tail."""
        import json

        from corda_tpu.loadtest import gate

        parsed = tmp_path / "MULTICHIP_r90.json"
        parsed.write_text(json.dumps({
            "n_devices": 8, "ok": True,
            "parsed": {"n_devices": 8, "mesh_sigs_s": 123.4,
                       "env_fingerprint": {"backend": "cpu"}},
        }))
        rec = gate.load_multichip_record(str(parsed))
        assert rec["mesh_sigs_s"] == 123.4

        structured = tmp_path / "MULTICHIP_r91.json"
        structured.write_text(json.dumps({
            "n_devices": 8, "ok": True,
            "tail": 'MULTICHIP_JSON: {"backend": "cpu", "mesh_sigs_s": '
                    '78.7, "n_devices": 8}\ndryrun_multichip OK: ...',
        }))
        rec = gate.load_multichip_record(str(structured))
        assert rec["mesh_sigs_s"] == 78.7
        assert rec["backend"] == "cpu"

        legacy = tmp_path / "MULTICHIP_r92.json"
        legacy.write_text(json.dumps({
            "n_devices": 8, "ok": True,
            "tail": "dryrun_multichip OK: psum total 2048 "
                    "(2048 sigs = 256/device in 26.0s on the virtual CPU "
                    "mesh; real chips retire this in microseconds)",
        }))
        rec = gate.load_multichip_record(str(legacy))
        assert rec["mesh_sigs_s"] == round(2048 / 26.0, 3)
        assert rec["env_fingerprint"] == {"backend": "cpu"}
        # throughput-free legacy tails still classify the backend
        no_rate = tmp_path / "MULTICHIP_r93.json"
        no_rate.write_text(json.dumps({
            "n_devices": 8, "ok": False,
            "tail": "... vs host machine features ...",
        }))
        rec = gate.load_multichip_record(str(no_rate))
        assert "mesh_sigs_s" not in rec
        assert rec["backend"] == "cpu"

    def test_in_repo_multichip_artifacts_load(self):
        """Every committed MULTICHIP_r*.json must parse into a record
        the gate can consume (the provenance satellite)."""
        import glob
        import os

        from corda_tpu.loadtest import gate

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json")))
        assert paths, "no MULTICHIP round artifacts in the repo"
        for p in paths:
            rec = gate.load_multichip_record(p)
            assert rec.get("n_devices") == 8, p
            assert "ok" in rec, p


# ---------------------------------------------------------------------------
# op-budget: sharding must not add per-signature field work
# ---------------------------------------------------------------------------

class TestMeshOpBudget:
    def test_mesh_kernel_matches_single_device_pin(self):
        """Tracing the mesh-wrapped ed25519 kernel per shard must count
        exactly the single-device pin's field multiplies per signature —
        shard_map distributes the work, it must never duplicate it."""
        from corda_tpu.ops import opbudget

        pinned = opbudget.load_manifest()["kernels"]["ed25519_xla"]
        counted = opbudget.count_mesh_kernel(n_devices=2)
        assert counted["u32_mul_elems_per_sig"] == (
            pinned["u32_mul_elems_per_sig"]
        )
        assert opbudget.fatal_violations(opbudget.check_mesh_budget(2)) == []
        # width must not change the per-sig count either
        counted4 = opbudget.count_mesh_kernel(n_devices=4)
        assert counted4["u32_mul_elems_per_sig"] == (
            counted["u32_mul_elems_per_sig"]
        )

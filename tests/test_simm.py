"""SIMM valuation demo tests (reference simm-valuation-demo integration
test: both nodes must agree the portfolio valuation)."""
import numpy as np
import pytest

from corda_tpu.core.contracts.structures import TransactionVerificationError
from corda_tpu.core.flows.library import FinalityFlow
from corda_tpu.core.serialization.codec import deserialize, serialize
from corda_tpu.core.transactions import TransactionBuilder
from corda_tpu.samples.simm_demo import (
    DEMO_CURVE,
    DEMO_TRADES,
    IRSTrade,
    PortfolioCommand,
    PortfolioState,
    RequestValuationFlow,
    Valuation,
    ValuationMismatch,
    compute_valuation,
    delta_ladder,
    portfolio_pv,
    simm_initial_margin,
)
from corda_tpu.testing import MockNetwork


class TestAnalytics:
    def test_par_swap_has_zero_pv(self):
        # a swap struck at the par rate must be worth ~0
        flat = tuple(0.03 for _ in DEMO_CURVE)
        probe = IRSTrade("P", 1_000_000_00, 0.03, 5.0, True)
        # par rate for a flat curve is near the flat rate; PV small relative
        # to notional * duration
        pv = portfolio_pv([probe], flat)
        assert abs(pv) < probe.notional * 0.01

    def test_payer_receiver_antisymmetry(self):
        t = IRSTrade("X", 1_000_000_00, 0.02, 10.0, True)
        r = IRSTrade("X", 1_000_000_00, 0.02, 10.0, False)
        pv_pay = portfolio_pv([t], DEMO_CURVE)
        pv_rec = portfolio_pv([r], DEMO_CURVE)
        assert pv_pay == pytest.approx(-pv_rec, rel=1e-9)

    def test_autodiff_matches_finite_difference(self):
        """The grad delta ladder is the reference's bump-and-revalue,
        without the bump. JAX computes in float32 by default, so the
        central difference uses a wide bump and loose tolerance (the f32
        noise floor on a ~1e10-minor-unit PV is ~1e3)."""
        deltas = delta_ladder(DEMO_TRADES, DEMO_CURVE)
        eps = 5e-4
        for k in (1, 4, 6):
            up = list(DEMO_CURVE)
            up[k] += eps
            dn = list(DEMO_CURVE)
            dn[k] -= eps
            fd = (
                portfolio_pv(DEMO_TRADES, up) - portfolio_pv(DEMO_TRADES, dn)
            ) / (2 * eps)
            assert deltas[k] == pytest.approx(fd, rel=5e-2, abs=5e4)

    def test_margin_positive_and_scales(self):
        im1 = simm_initial_margin(DEMO_TRADES, DEMO_CURVE)
        double = [
            IRSTrade(t.trade_id, t.notional * 2, t.fixed_rate,
                     t.maturity_years, t.pay_fixed)
            for t in DEMO_TRADES
        ]
        im2 = simm_initial_margin(double, DEMO_CURVE)
        assert im1 > 0
        assert im2 == pytest.approx(2 * im1, rel=1e-6)

    def test_valuation_round_trips_codec(self):
        v = compute_valuation("P1", DEMO_TRADES[:2], DEMO_CURVE)
        assert deserialize(serialize(v)) == v


class TestPortfolioContract:
    def setup_method(self):
        from corda_tpu.core.crypto import crypto
        from corda_tpu.core.identity import Party

        self.a = Party("O=SA,L=London,C=GB", crypto.entropy_to_keypair(800).public)
        self.b = Party("O=SB,L=Paris,C=FR", crypto.entropy_to_keypair(801).public)
        self.n = Party("O=SN,L=Zurich,C=CH", crypto.entropy_to_keypair(802).public)

    def _verify(self, builder):
        wtx = builder.to_wire_transaction()
        wtx.to_ledger_transaction(
            resolve_state=lambda ref: None,
            resolve_attachment=lambda h: None,
        ).verify()

    def test_agree_both_signed_ok(self):
        b = TransactionBuilder(notary=self.n)
        b.add_output_state(PortfolioState(self.a, self.b, DEMO_TRADES))
        b.add_command(
            PortfolioCommand("Agree"), self.a.owning_key, self.b.owning_key
        )
        self._verify(b)

    def test_agree_one_signature_rejected(self):
        b = TransactionBuilder(notary=self.n)
        b.add_output_state(PortfolioState(self.a, self.b, DEMO_TRADES))
        b.add_command(PortfolioCommand("Agree"), self.a.owning_key)
        with pytest.raises(TransactionVerificationError, match="must sign"):
            self._verify(b)


class TestValuationFlows:
    def setup_method(self):
        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.a = self.net.create_node("O=SimmA,L=London,C=GB")
        self.b = self.net.create_node("O=SimmB,L=New York,C=US")

    def teardown_method(self):
        self.net.stop_nodes()

    def _agree(self, trades=DEMO_TRADES):
        portfolio = PortfolioState(self.a.info, self.b.info, trades, "P1")
        builder = TransactionBuilder(notary=self.notary.info)
        builder.add_output_state(portfolio)
        builder.add_command(
            PortfolioCommand("Agree"),
            self.a.info.owning_key, self.b.info.owning_key,
        )
        stx = self.a.services.sign_initial_transaction(builder)
        sig_b = self.b.services.key_management_service.sign(
            stx.id.bytes, self.b.info.owning_key
        )
        stx = stx.with_additional_signature(sig_b)
        h = self.a.start_flow(FinalityFlow(stx), stx)
        self.net.run_network()
        h.result.result(timeout=30)

    def test_both_sides_agree(self):
        self._agree()
        h = self.a.start_flow(
            RequestValuationFlow(self.b.info, "P1", DEMO_CURVE),
            self.b.info, "P1", DEMO_CURVE,
        )
        self.net.run_network()
        valuation = h.result.result(timeout=60)
        assert isinstance(valuation, Valuation)
        expected = compute_valuation("P1", DEMO_TRADES, DEMO_CURVE)
        assert valuation == expected

    def test_selects_portfolio_by_id_among_many(self):
        self._agree()  # P1 = DEMO_TRADES
        other = (IRSTrade("O", 1_000_000_00, 0.02, 2.0, True),)
        portfolio = PortfolioState(self.a.info, self.b.info, other, "P2")
        builder = TransactionBuilder(notary=self.notary.info)
        builder.add_output_state(portfolio)
        builder.add_command(
            PortfolioCommand("Agree"),
            self.a.info.owning_key, self.b.info.owning_key,
        )
        stx = self.a.services.sign_initial_transaction(builder)
        sig_b = self.b.services.key_management_service.sign(
            stx.id.bytes, self.b.info.owning_key
        )
        h = self.a.start_flow(
            FinalityFlow(stx.with_additional_signature(sig_b)),
            stx.with_additional_signature(sig_b),
        )
        self.net.run_network()
        h.result.result(timeout=30)
        # valuing P2 prices `other`, not whichever state is first
        h = self.a.start_flow(
            RequestValuationFlow(self.b.info, "P2", DEMO_CURVE),
            self.b.info, "P2", DEMO_CURVE,
        )
        self.net.run_network()
        valuation = h.result.result(timeout=60)
        assert valuation == compute_valuation("P2", other, DEMO_CURVE)

    def test_divergent_books_detected(self):
        """The two sides hold different books -> the agreement round must
        fail with ValuationMismatch, not silently accept."""

        def record_local(node, trades):
            state = PortfolioState(self.a.info, self.b.info, trades, "P1")
            builder = TransactionBuilder(notary=self.notary.info)
            builder.add_output_state(state)
            builder.add_command(
                PortfolioCommand("Agree"),
                self.a.info.owning_key, self.b.info.owning_key,
            )
            stx = node.services.sign_initial_transaction(builder)
            node.services.record_transactions([stx])

        record_local(self.a, DEMO_TRADES)
        record_local(
            self.b, (IRSTrade("R", 999_000_000_00, 0.05, 30.0, True),)
        )
        h = self.a.start_flow(
            RequestValuationFlow(self.b.info, "P1", DEMO_CURVE),
            self.b.info, "P1", DEMO_CURVE,
        )
        self.net.run_network()
        with pytest.raises(ValuationMismatch):
            h.result.result(timeout=60)


class TestRound5Analytics:
    """Round-5 widening toward the reference's analytic surface
    (AnalyticsEngine.kt): per-trade PVs, leave-one-out margin, curve
    calibration, and the PortfolioApi-equivalent web routes."""

    def test_per_trade_pvs_sum_to_portfolio(self):
        from corda_tpu.samples import simm_demo as sd

        pvs = sd.per_trade_pvs(sd.DEMO_TRADES, sd.DEMO_CURVE)
        assert len(pvs) == len(sd.DEMO_TRADES)
        total = sd.portfolio_pv(sd.DEMO_TRADES, sd.DEMO_CURVE)
        assert abs(pvs.sum() - total) < max(16.0, abs(total) * 1e-5)

    def test_marginal_im_matches_leave_one_out(self):
        """The vmapped formula must agree with literally re-running the
        margin without each trade (the reference's omit-loop)."""
        from corda_tpu.samples import simm_demo as sd

        trades, curve = sd.DEMO_TRADES, sd.DEMO_CURVE
        fast = sd.marginal_im(trades, curve)
        im_all = sd.simm_initial_margin(trades, curve)
        for i in range(len(trades)):
            without = [t for j, t in enumerate(trades) if j != i]
            slow = im_all - sd.simm_initial_margin(without, curve)
            assert abs(fast[i] - slow) < max(1.0, abs(slow) * 1e-4), i

    def test_calibration_reprices_par_quotes(self):
        """Bootstrapped zero curve must reprice the input par quotes
        through the SAME pricing model (consistency by construction)."""
        import numpy as np

        from corda_tpu.samples import simm_demo as sd

        quotes = (0.030, 0.031, 0.033, 0.0345, 0.036, 0.039, 0.041, 0.042)
        zero = sd.calibrate_curve(quotes)
        assert zero.shape == (len(sd.TENORS),)
        # a par-rate swap struck at its quote has ~zero PV on this curve
        for tenor, q in zip(sd.TENORS, quotes):
            if tenor < 1.0:
                continue  # the yearly-payment model has no sub-1y flows
            t = sd.IRSTrade("X", 1_000_000_00, q, tenor, True)
            pv = sd.portfolio_pv([t], zero)
            assert abs(pv) < 200, (tenor, pv)  # < 2.00 per 1m notional

    def test_web_api_routes(self):
        """The PortfolioApi-equivalent surface through the webserver
        plugin registry, against a real node's ops."""
        from corda_tpu.samples import simm_demo as sd
        from corda_tpu.webserver.plugins import registered_plugins

        # another test may have wiped the registry (clear_web_plugins
        # test hook); registration is idempotent, so restore it
        sd.register_simm_web_api()
        plugin = next(
            p for p in registered_plugins()
            if isinstance(p, sd.SimmApiPlugin)
        )

        class FakeOps:  # vault surface only
            @staticmethod
            def vault_query(contract_name=None):
                from types import SimpleNamespace

                state = sd.PortfolioState(
                    SimpleNamespace(name="O=A"), SimpleNamespace(name="O=B"),
                    sd.DEMO_TRADES, "P-1",
                )
                return [SimpleNamespace(
                    state=SimpleNamespace(data=state)
                )]

        code, out = plugin.handle(FakeOps, "GET", "business-date", {}, None)
        assert code == 200 and "businessDate" in out
        code, out = plugin.handle(FakeOps, "GET", "portfolios", {}, None)
        assert code == 200 and out["portfolios"][0]["id"] == "P-1"
        code, out = plugin.handle(FakeOps, "GET", "P-1/trades", {}, None)
        assert code == 200 and len(out["trades"]) == len(sd.DEMO_TRADES)
        tid = out["trades"][0]["id"]
        code, out = plugin.handle(
            FakeOps, "GET", f"P-1/trades/{tid}", {}, None
        )
        assert code == 200 and out["id"] == tid
        code, out = plugin.handle(FakeOps, "GET", "P-1/valuation", {}, None)
        assert code == 200
        assert set(out) >= {
            "presentValue", "perTradePV", "deltaLadder",
            "initialMargin", "marginalIM",
        }
        # float32 summation tolerance at 1.7e8 scale
        assert abs(
            sum(out["perTradePV"].values()) - out["presentValue"]
        ) < abs(out["presentValue"]) * 1e-6 + 1.0
        code, out = plugin.handle(
            FakeOps, "GET", "P-1/valuation", {"curve": "bad"}, None
        )
        assert code == 400
        code, _ = plugin.handle(FakeOps, "GET", "NOPE/trades", {}, None)
        assert code == 404

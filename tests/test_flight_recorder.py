"""Node flight recorder: structured event log, /logs trace correlation,
health/readiness probes, backpressure telemetry, and the bench
regression gate (docs/observability.md).

Covers: the bounded event-log ring + filters + stdlib-logging bridge;
trace-id correlation between /traces/<id> and /logs?trace=<id> on a
MockNetwork notarised transaction (events from >= 3 components);
/healthz per-component detail and the 503 drain flip; /readyz before
and after the verifier backend is up; the broker queue-depth gauge
under a paused consumer; batcher occupancy/lag instruments; and
tools/bench_gate.py failing on a synthetic stage-timing regression.
"""
import json
import logging
import os
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from corda_tpu.utils import eventlog, tracing
from corda_tpu.utils.eventlog import EventLog
from corda_tpu.utils.tracing import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def fresh_log():
    prev = eventlog.set_event_log(EventLog())
    yield eventlog.get_event_log()
    eventlog.set_event_log(prev)


@pytest.fixture()
def tracer():
    prev = tracing.set_tracer(Tracer())
    yield tracing.get_tracer()
    tracing.set_tracer(prev)


# ---------------------------------------------------------------------------
# EventLog mechanics
# ---------------------------------------------------------------------------

class TestEventLog:
    def test_ring_is_bounded_and_counts_drops(self):
        log = EventLog(capacity=8)
        for i in range(20):
            log.emit("info", "test", f"m{i}")
        assert len(log.records()) == 8
        stats = log.stats()
        assert stats["emitted"] == 20
        assert stats["dropped"] == 12
        # oldest dropped: the ring keeps the newest
        assert log.records()[0]["message"] == "m12"

    def test_level_floor_and_filters(self):
        log = EventLog(capacity=64, min_level="info")
        log.emit("debug", "a", "below the floor")
        log.emit("info", "a", "hello")
        log.emit("warning", "b", "uh oh")
        assert len(log.records()) == 2  # debug never recorded
        assert [e["message"] for e in log.records(level="warning")] == ["uh oh"]
        assert [e["component"] for e in log.records(component="a")] == ["a"]
        assert log.records(limit=1)[0]["message"] == "uh oh"

    def test_trace_context_captured_and_fan_in_matchable(self, tracer):
        log = EventLog(capacity=64)
        with tracer.span("op") as sp:
            log.emit("info", "test", "inside the span")
        tid = sp.context.trace_id
        [event] = log.records(trace=tid)
        assert event["trace_id"] == tid
        assert event["span_id"] == sp.context.span_id
        # fan-in events match through trace_ids too
        log.emit("info", "batch", "served many", trace_ids=[tid, "f" * 32])
        assert len(log.records(trace=tid)) == 2
        assert len(log.records(trace="f" * 32)) == 1

    def test_jsonl_rendering(self):
        log = EventLog(capacity=8)
        log.emit("info", "test", "one", n=1)
        lines = [
            json.loads(line) for line in log.to_jsonl().strip().splitlines()
        ]
        assert lines[0]["message"] == "one" and lines[0]["n"] == 1

    def test_stdlib_bridge_components(self, fresh_log):
        eventlog.install_stdlib_bridge()
        logging.getLogger("corda_tpu.raft").warning("lost leader")
        logging.getLogger("corda_tpu.node.scheduler").warning("dropped")
        logging.getLogger("corda_tpu.flow.abc123").warning("flow warn")
        logging.getLogger("corda_tpu.raft").critical("meltdown")
        comps = {e["component"]: e for e in fresh_log.records()}
        assert "raft" in comps
        assert "scheduler" in comps
        assert comps["flow"]["flow_id"] == "abc123"
        # CRITICAL outranks error in the minimum-severity filter
        [worst] = fresh_log.records(level="critical")
        assert worst["message"] == "meltdown"
        assert fresh_log.records(level="error") == [worst]

    def test_stdlib_bridge_does_not_change_library_log_levels(self, fresh_log):
        # embedding a node must not start leaking INFO to a
        # WARNING-configured console: the bridge leaves logger levels
        # alone unless capture_info (the node binary) asks
        root = logging.getLogger("corda_tpu")
        prev = root.level
        try:
            root.setLevel(logging.WARNING)
            eventlog.install_stdlib_bridge()
            assert root.level == logging.WARNING
            eventlog.install_stdlib_bridge(capture_info=True)
            assert root.getEffectiveLevel() == logging.INFO
        finally:
            root.setLevel(prev)

    def test_disabled_log_records_nothing(self):
        log = EventLog(capacity=8, enabled=False)
        log.emit("error", "test", "nope")
        assert log.records() == []


# ---------------------------------------------------------------------------
# MetricRegistry histogram family + deterministic snapshots
# ---------------------------------------------------------------------------

class TestHistogram:
    def test_histogram_bounded_percentiles(self):
        from corda_tpu.utils.metrics import Histogram, MetricRegistry

        reg = MetricRegistry()
        h = reg.histogram("batch.sizes")
        assert reg.histogram("batch.sizes") is h
        for i in range(Histogram.RESERVOIR + 100):
            h.update(i)
        snap = h.snapshot()
        assert snap["type"] == "histogram"
        assert snap["count"] == Histogram.RESERVOIR + 100
        assert len(h._values) == Histogram.RESERVOIR
        assert snap["min"] <= snap["p50"] <= snap["p95"] <= snap["max"]
        with pytest.raises(TypeError):
            reg.timer("batch.sizes")

    def test_snapshot_order_is_deterministic(self):
        from corda_tpu.utils.metrics import MetricRegistry

        a, b = MetricRegistry(), MetricRegistry()
        a.counter("zz").inc()
        a.histogram("aa").update(1)
        b.histogram("aa").update(1)
        b.counter("zz").inc()  # reverse registration order
        assert list(a.snapshot()) == list(b.snapshot()) == ["aa", "zz"]

    def test_histogram_renders_as_prometheus_summary(self):
        from corda_tpu.node.opsserver import render_prometheus
        from corda_tpu.utils.metrics import MetricRegistry

        reg = MetricRegistry()
        reg.histogram("Verifier.BatchSize").update(17)
        text = render_prometheus(reg.snapshot())
        assert "# TYPE corda_tpu_verifier_batch_size summary" in text
        assert 'corda_tpu_verifier_batch_size{quantile="0.5"} 17' in text
        assert "corda_tpu_verifier_batch_size_count 1" in text


# ---------------------------------------------------------------------------
# End-to-end: trace <-> log correlation + health on a MockNetwork node
# ---------------------------------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, json.loads(resp.read())


class TestFlightRecorderEndToEnd:
    def setup_method(self):
        self._prev_tracer = tracing.set_tracer(Tracer())
        self._prev_log = eventlog.set_event_log(EventLog())
        from corda_tpu.testing.mocknetwork import MockNetwork

        self.net = MockNetwork()
        self.notary = self.net.create_notary_node(validating=True)
        self.alice = self.net.create_node(
            "O=RecAlice,L=London,C=GB", ops_port=0
        )
        self.bob = self.net.create_node("O=RecBob,L=Paris,C=FR")

    def teardown_method(self):
        self.net.stop_nodes()
        tracing.set_tracer(self._prev_tracer)
        eventlog.set_event_log(self._prev_log)

    def _run_payment(self) -> str:
        from corda_tpu.core.contracts import Amount
        from corda_tpu.core.contracts.amount import Issued
        from corda_tpu.rpc import CordaRPCOps

        ops = CordaRPCOps(self.alice.services, self.alice.smm)
        fid = ops.start_flow_dynamic(
            "corda_tpu.finance.flows.CashIssueFlow",
            Amount(1000, "USD"), (1,), self.alice.info, self.notary.info,
        )
        self.net.run_network()
        assert ops.flow_result(fid, timeout=10) is not None
        token = Issued(self.alice.info.ref(1), "USD")
        fid = ops.start_flow_dynamic(
            "corda_tpu.finance.flows.CashPaymentFlow",
            Amount(400, token), self.bob.info, self.notary.info,
        )
        self.net.run_network()
        assert ops.flow_result(fid, timeout=10) is not None
        tracer = self.net.tracer
        for tid in tracer.trace_ids():
            if any(
                "CashPaymentFlow" in str(s["tags"].get("flow", ""))
                for s in tracer.get_trace(tid)
            ):
                return tid
        raise AssertionError("no trace contains the payment flow")

    def test_logs_correlate_with_trace_across_components(self):
        tid = self._run_payment()
        port = self.alice.ops_server.port
        # the trace exists...
        status, tree = _get(port, f"/traces/{tid}")
        assert tree["span_count"] >= 4
        # ...and /logs?trace= joins >= 3 components against it
        status, logs = _get(port, f"/logs?trace={tid}")
        components = {e["component"] for e in logs["events"]}
        assert len(components) >= 3, components
        assert {"statemachine", "verifier", "notary"} <= components
        # every returned event really references the trace
        for e in logs["events"]:
            assert e.get("trace_id") == tid or tid in e.get("trace_ids", ())
        # component + level filters narrow the same view
        status, only_notary = _get(
            port, f"/logs?trace={tid}&component=notary"
        )
        assert only_notary["events"]
        assert all(
            e["component"] == "notary" for e in only_notary["events"]
        )
        # a malformed limit is the CLIENT's error: 400, not 500
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(port, "/logs?limit=abc")
        assert err.value.code == 400
        # jsonl rendering serves raw lines
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/logs?format=jsonl&limit=5", timeout=5
        ) as resp:
            assert resp.headers["Content-Type"].startswith("application/jsonl")
            lines = resp.read().decode().strip().splitlines()
        assert 0 < len(lines) <= 5
        json.loads(lines[0])

    def test_healthz_detail_and_drain_flip(self):
        self._run_payment()
        port = self.alice.ops_server.port
        status, body = _get(port, "/healthz")
        assert status == 200 and body["status"] == "ok"
        # per-component detail is present
        assert {"messaging", "verifier", "statemachine"} <= set(body["checks"])
        assert body["checks"]["verifier"]["ok"] is True
        assert "flows_in_flight" in body["checks"]["statemachine"]
        # draining (without teardown) flips both probes to 503
        self.alice.drain()
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(port, "/healthz")
        assert err.value.code == 503
        payload = json.loads(err.value.read())
        assert payload["cause"] == "node is draining"
        assert err.value.headers["Content-Type"] == "application/json"
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(port, "/readyz")
        assert err.value.code == 503

    def test_readyz_before_and_after_verifier_backend(self):
        from corda_tpu.node.opsserver import OpsServer

        port = self.alice.ops_server.port
        status, body = _get(port, "/readyz")
        assert status == 200 and body["status"] == "ready"
        assert body["checks"]["verifier"]["ok"] is True
        # kill the verifier backend: readiness must drop with the cause
        self.alice.services.transaction_verifier_service.stop()
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(port, "/readyz")
        assert err.value.code == 503
        payload = json.loads(err.value.read())
        assert payload["checks"]["verifier"]["ok"] is False
        assert "verifier" in payload["cause"]
        # a node still STARTING (never marked serving) is not ready even
        # with healthy components: probe a fresh tracker via OpsServer
        from corda_tpu.node.health import HealthTracker
        from corda_tpu.utils.metrics import MetricRegistry

        starting = OpsServer(MetricRegistry(), health=HealthTracker())
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(starting.port, "/readyz")
            assert err.value.code == 503
            assert json.loads(err.value.read())["cause"] == "node is starting"
        finally:
            starting.stop()

    def test_backpressure_gauges_in_metrics_snapshot(self):
        self._run_payment()
        snap = self.alice.smm.metrics.snapshot()
        assert snap["P2P.QueueDepth"]["value"] == 0  # quiescent network
        assert snap["Verifier.BatcherOccupancy"]["value"] == 0
        assert snap["Flows.InFlight"]["value"] == 0
        assert "Jax.Backend" in snap and "Jax.CompileCount" in snap
        # at least one node's batcher flushed a real batch (whichever
        # party performed the signature checks)
        flushed = sum(
            n.metrics.snapshot().get("Verifier.BatchSize", {}).get("count", 0)
            for n in self.net.nodes
        )
        assert flushed >= 1


# ---------------------------------------------------------------------------
# Broker queue depth under a paused consumer
# ---------------------------------------------------------------------------

class TestBrokerQueueDepth:
    def test_gauge_climbs_while_consumer_paused_and_drains_on_start(self):
        from corda_tpu.messaging import Broker
        from corda_tpu.node.network import BrokerMessagingService
        from corda_tpu.node.node import AbstractNode, NodeConfiguration

        broker = Broker()
        node = AbstractNode(
            NodeConfiguration(
                my_legal_name="O=Depth,L=London,C=GB", identity_entropy=77,
            ),
            messaging_factory=lambda me: BrokerMessagingService(broker, me),
            broker=broker,
        )
        try:
            # the node is constructed but NOT started: its p2p pump (the
            # queue's only consumer) is paused, so sends pile up
            for _ in range(5):
                broker.send(
                    f"p2p.inbound.{node.info.name}", b"x",
                    {"topic": "noop"},
                )
            snap = node.metrics.snapshot()
            assert snap["P2P.QueueDepth"]["value"] == 5
            # health check surfaces the same backlog
            _, body = node.health.healthz()
            assert body["checks"]["messaging"]["queue_depth"] == 5
            # starting the pump drains it
            node.start()
            import time

            for _ in range(100):
                if node.network.queue_depth() == 0:
                    break
                time.sleep(0.05)
            assert node.metrics.snapshot()["P2P.QueueDepth"]["value"] == 0
        finally:
            node.stop()
            broker.close()


# ---------------------------------------------------------------------------
# Batcher occupancy / flush-lag instruments
# ---------------------------------------------------------------------------

class TestBatcherBackpressure:
    def test_occupancy_and_lag_telemetry(self):
        from corda_tpu.core.crypto import crypto
        from corda_tpu.utils.metrics import MetricRegistry
        from corda_tpu.verifier.batcher import SignatureBatcher

        reg = MetricRegistry()
        batcher = SignatureBatcher(max_batch=1000, linger_ms=10_000)
        batcher.bind_metrics(reg)
        kp = crypto.generate_keypair()
        sig = crypto.do_sign(kp.private, b"m")
        batcher.submit((kp.public, sig, b"m"))
        assert reg.gauge("Verifier.BatcherOccupancy").value == 1
        assert batcher.oldest_queued_age_s == 0.0  # nothing handed off
        batcher.flush()
        assert reg.gauge("Verifier.BatcherOccupancy").value == 0
        assert reg.histogram("Verifier.BatchSize").count == 1
        assert batcher.flush_lag_s >= 0.0
        batcher.close()


# ---------------------------------------------------------------------------
# MiniWebServer error bodies are JSON with the JSON content type
# ---------------------------------------------------------------------------

class TestMiniWebErrorBodies:
    def test_404_500_and_unsupported_method_are_json(self):
        from corda_tpu.utils.miniweb import MiniWebServer

        class Server(MiniWebServer):
            def handle(self, method, path, query, body):
                if path == "/boom":
                    raise RuntimeError("kapow")
                raise KeyError(path)

        srv = Server(port=0)
        try:
            for path, code in (("/nope", 404), ("/boom", 500)):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}{path}", timeout=5
                    )
                assert err.value.code == code
                assert err.value.headers["Content-Type"] == "application/json"
                json.loads(err.value.read())
            # stdlib-dispatched failure (unsupported method) included
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/x", method="DELETE"
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=5)
            assert err.value.headers["Content-Type"] == "application/json"
            assert "error" in json.loads(err.value.read())
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Bench regression gate
# ---------------------------------------------------------------------------

def _bench_record():
    return {
        "metric": "ed25519-sig-verifies/sec/chip",
        "value": 26899.0,
        "backend": "cpu",
        "p50_notarise_ms": 2.7,
        "p95_notarise_ms": 3.3,
        "p99_notarise_ms": 4.0,
        "settlement_burst_sigs_s": 8062.1,
        "batcher_largest_batch": 1025,
        "stage_timings": {
            "codec_encode_us_per_tx": 6.1,
            "batcher_flush_wall_s": 0.5,
            "uniq_commit_batch_mean": 12.0,
            "critical_path": {
                "notary.commit": {"count": 64, "p50_ms": 1.0, "p99_ms": 2.0},
            },
        },
    }


class TestBenchGateLibrary:
    def test_identical_records_pass(self):
        from corda_tpu.loadtest import gate

        rec = _bench_record()
        assert gate.compare_records(rec, rec) == []
        assert gate.run_gate(rec, rec)["ok"]

    def test_synthetic_2x_stage_regression_fails(self):
        from corda_tpu.loadtest import gate

        prev, cur = _bench_record(), _bench_record()
        cur["stage_timings"]["codec_encode_us_per_tx"] *= 2  # 2x slower
        regs = gate.compare_records(prev, cur)
        assert [r["key"] for r in regs] == [
            "stage_timings.codec_encode_us_per_tx"
        ]
        assert regs[0]["change"] == pytest.approx(1.0)
        assert not gate.run_gate(cur, prev)["ok"]

    def test_throughput_drop_and_latency_rise_both_flag(self):
        from corda_tpu.loadtest import gate

        prev, cur = _bench_record(), _bench_record()
        cur["settlement_burst_sigs_s"] = prev["settlement_burst_sigs_s"] / 2
        cur["stage_timings"]["critical_path"]["notary.commit"]["p99_ms"] = 10.0
        keys = {r["key"] for r in gate.compare_records(prev, cur)}
        assert "settlement_burst_sigs_s" in keys
        assert "stage_timings.critical_path.notary.commit.p99_ms" in keys

    def test_improvements_and_unclassified_keys_do_not_flag(self):
        from corda_tpu.loadtest import gate

        prev, cur = _bench_record(), _bench_record()
        cur["p99_notarise_ms"] = 1.0  # faster: fine
        cur["settlement_burst_sigs_s"] *= 3  # faster: fine
        cur["batcher_largest_batch"] = 1  # workload shape: not gated
        cur["stage_timings"]["uniq_commit_batch_mean"] = 1.0  # not gated
        assert gate.compare_records(prev, cur) == []

    def test_old_baseline_without_stage_timings_gates_nothing(self):
        from corda_tpu.loadtest import gate

        prev = {"metric": "x", "value": 1.0}  # r01-era artifact shape
        assert gate.compare_records(prev, _bench_record()) == []

    def test_slo_assertions(self):
        from corda_tpu.loadtest import gate

        rec = _bench_record()
        ok = gate.check_slos(rec, {"p99_notarise_ms": {"max": 500.0}})
        assert ok == []
        bad = gate.check_slos(rec, {
            "p99_notarise_ms": {"max": 1.0},
            "settlement_burst_sigs_s": {"min": 1e9},
            "not_measured": {"max": 1.0},
        })
        kinds = {v["key"]: v["kind"] for v in bad}
        assert kinds == {
            "p99_notarise_ms": "max",
            "settlement_burst_sigs_s": "min",
            "not_measured": "missing",
        }

    def test_harness_slo_integration(self):
        from corda_tpu.loadtest.harness import LoadTest, Nodes

        class _Null(LoadTest):
            name = "null-test"

            def setup(self, nodes):
                return 0

            def generate(self, state, parallelism):
                from corda_tpu.testing.generator import Generator

                return Generator.pure([None] * parallelism)

            def interpret(self, state, command):
                return state + 1

            def execute(self, nodes, command):
                pass

            def gather(self, nodes):
                return self._state_now

            def compare(self, predicted, observed):
                return True

            def collect_metrics(self, nodes):
                return {"widgets_per_run": 7.0}

            _state_now = 0

        class _StillNodes(Nodes):
            def pump(self):
                pass

        nodes = _StillNodes(network=None, notary=None, nodes=[])
        # collect_metrics lands on the result AND feeds the SLO check
        result = _Null().run(
            nodes, iterations=2, parallelism=3,
            slos={"widgets_per_run": {"min": 10.0},
                  "commands_per_sec": {"min": 0.0}},
        )
        assert result.metrics == {"widgets_per_run": 7.0}
        assert [v["key"] for v in result.slo_violations] == [
            "widgets_per_run"
        ]
        assert not result.ok
        # bounds that hold leave the result ok
        ok = _Null().run(
            nodes, iterations=1, parallelism=1,
            slos={"widgets_per_run": {"min": 1.0}},
        )
        assert ok.ok and ok.slo_violations == []


class TestBenchGateCLI:
    """The tier-1 CI satellite: inject a synthetic regression into a
    copied bench JSON and assert the gate process fails."""

    def _run(self, tmp_path, cur, prev):
        cur_p, prev_p = tmp_path / "cur.json", tmp_path / "prev.json"
        cur_p.write_text(json.dumps(cur))
        # baseline rides the driver artifact shape ({"parsed": ...})
        prev_p.write_text(json.dumps({"parsed": prev, "rc": 0}))
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--current", str(cur_p), "--baseline", str(prev_p)],
            capture_output=True, text=True, timeout=60,
        )

    def test_gate_exits_nonzero_on_synthetic_regression(self, tmp_path):
        prev, cur = _bench_record(), _bench_record()
        cur["stage_timings"]["batcher_flush_wall_s"] *= 2
        proc = self._run(tmp_path, cur, prev)
        assert proc.returncode == 1, proc.stderr
        assert "REGRESSION" in proc.stderr
        summary = json.loads(proc.stdout)
        assert not summary["ok"]
        assert summary["regressions"][0]["key"] == (
            "stage_timings.batcher_flush_wall_s"
        )

    def test_gate_exits_zero_on_clean_run(self, tmp_path):
        proc = self._run(tmp_path, _bench_record(), _bench_record())
        assert proc.returncode == 0, proc.stderr
        assert json.loads(proc.stdout)["ok"]

    def test_gate_slo_defaults_flag(self, tmp_path):
        # without --slo-defaults the built-in bounds are NOT applied...
        cur = _bench_record()
        cur["p99_notarise_ms"] = 10_000.0  # way past DEFAULT_SLOS' 500ms
        proc = self._run(tmp_path, cur, cur)
        assert proc.returncode == 0, proc.stderr
        # ...with the flag, the same record fails on the default bound
        cur_p = tmp_path / "cur.json"
        cur_p.write_text(json.dumps(cur))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--current", str(cur_p), "--baseline", str(cur_p),
             "--slo-defaults"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1
        assert "p99_notarise_ms" in proc.stderr

    def test_gate_slo_flag(self, tmp_path):
        cur_p = tmp_path / "cur.json"
        cur_p.write_text(json.dumps(_bench_record()))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
             "--current", str(cur_p), "--baseline", str(cur_p),
             "--slo", "p99_notarise_ms<=0.5"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 1
        assert "SLO VIOLATION" in proc.stderr

"""Flow hospital: transient failures auto-retry from their checkpoint,
fatal ones dead-letter into the ward with the node_hospital()/
retry_flow()/kill_flow() operator surface (docs/robustness.md).

ISSUE 4 acceptance: a flow failing transiently (injected verifier
timeout) is auto-retried from its checkpoint to success; a flow failing
fatally (contract violation) lands in the dead-letter ward, is visible
via node_hospital(), and retry_flow()/kill_flow() behave as documented.
"""
import json
import time
import urllib.request

import pytest

from corda_tpu.core.contracts import TransactionVerificationError
from corda_tpu.core.flows.api import FlowKilledException, FlowLogic
from corda_tpu.messaging import Broker
from corda_tpu.node.hospital import TransientFlowError
from corda_tpu.rpc.ops import CordaRPCOps
from corda_tpu.testing import MockNetwork, faults
from corda_tpu.utils import faultpoints
from corda_tpu.verifier import (
    OutOfProcessTransactionVerifierService,
    VerifierWorker,
)

#: module-level side-effect counters: replay must NOT re-execute
#: recorded steps, so these count real executions
COUNTS = {"record": 0, "flaky": 0}

#: a standalone out-of-process verifier the flaky flow calls into (the
#: "injected verifier timeout" is a REAL deadline exhaustion, not a stub)
VERIFIER = {"svc": None, "items": None}


def _recorded_step():
    COUNTS["record"] += 1
    return COUNTS["record"]


def _verify_step():
    COUNTS["flaky"] += 1
    futures = VERIFIER["svc"].verify_signatures(VERIFIER["items"])
    return all(f.result(timeout=10) for f in futures)


class VerifyingFlow(FlowLogic):
    """record (checkpointed) -> out-of-process signature verify."""

    def call(self):
        before = yield self.record(_recorded_step)
        ok = yield self.await_blocking(_verify_step)
        return (before, ok)


def _transient_step():
    COUNTS["flaky"] += 1
    faults_hook = faultpoints.hook
    if faults_hook is not None:
        action = faultpoints.fire("test.transient")
        if action == "fail":
            raise TransientFlowError("injected transient failure")
    return "ok"


class FlakyFlow(FlowLogic):
    def call(self):
        before = yield self.record(_recorded_step)
        value = yield self.await_blocking(_transient_step)
        return (before, value)


class FatalFlow(FlowLogic):
    def call(self):
        yield self.record(_recorded_step)
        raise TransactionVerificationError("deadbeef", "contract violation")


@pytest.fixture(autouse=True)
def _reset_counters():
    COUNTS["record"] = 0
    COUNTS["flaky"] = 0
    VERIFIER["svc"] = None
    VERIFIER["items"] = None
    yield


def _make_node(net=None, **hospital_knobs):
    net = net or MockNetwork()
    node = net.create_node("O=Hospital,L=London,C=GB")
    h = node.smm.hospital
    h.backoff_s = hospital_knobs.get("backoff_s", 0.05)
    h.backoff_cap_s = hospital_knobs.get("backoff_cap_s", 0.1)
    h.max_retries = hospital_knobs.get("max_retries", 3)
    return net, node


class TestTransientRetry:
    def test_injected_verifier_timeout_autoretries_from_checkpoint(self):
        """Acceptance: the flow fails on a REAL verifier deadline
        exhaustion (no workers, fallback off), the hospital replays it
        from its checkpoint, and — a worker having arrived — it
        completes into the ORIGINAL caller future. The recorded step
        must not re-execute."""
        from corda_tpu.core.crypto import crypto

        net, node = _make_node(backoff_s=0.3, backoff_cap_s=0.4)
        broker = Broker()
        svc = OutOfProcessTransactionVerifierService(
            broker, "hospitalVerify", deadline_s=0.15, max_retries=0,
            fallback=False,
        )
        kp = crypto.entropy_to_keypair(8600)
        content = b"hospital-verify"
        VERIFIER["svc"] = svc
        VERIFIER["items"] = [
            (kp.public, crypto.do_sign(kp.private, content), content)
        ]
        try:
            handle = node.start_flow(VerifyingFlow())
            # first attempt dead-letters (VerificationTimeoutError) and
            # the hospital admits the flow; now bring a worker up so the
            # replay succeeds
            worker = VerifierWorker(broker, name="hospital-w").start()
            result = handle.result.result(timeout=15)
            assert result == (1, True)
            assert COUNTS["record"] == 1  # replay fed the recorded value
            assert COUNTS["flaky"] == 2   # the failed + the retried verify
            snap = node.smm.hospital.snapshot()
            assert snap["retries"] == 1
            assert snap["recovered"] == 1
            assert snap["recovering"] == [] and snap["ward"] == []
            worker.stop()
        finally:
            svc.stop()
            net.stop_nodes()

    def test_marker_error_retries_and_exhaustion_wards(self):
        net, node = _make_node(max_retries=2)
        with faults.inject(seed=3) as fi:
            fi.rule("test.transient", "fail", times=1)
            handle = node.start_flow(FlakyFlow())
            assert handle.result.result(timeout=10) == (1, "ok")
        assert COUNTS["flaky"] == 2
        assert node.smm.hospital.snapshot()["recovered"] == 1

        # now a PERSISTENT transient error: retries exhaust, flow wards
        COUNTS["record"] = 0
        COUNTS["flaky"] = 0
        with faults.inject(seed=4) as fi:
            fi.rule("test.transient", "fail", times=None)
            handle = node.start_flow(FlakyFlow())
            with pytest.raises(TransientFlowError):
                handle.result.result(timeout=20)
        assert COUNTS["flaky"] == 3  # first + 2 retries
        snap = node.smm.hospital.snapshot()
        assert [w["flow_id"] for w in snap["ward"]] == [handle.flow_id]
        net.stop_nodes()


class TestWardAndOperatorSurface:
    def test_fatal_flow_lands_in_ward_and_rpc_surface_works(self):
        net, node = _make_node()
        ops = CordaRPCOps(node.services, node.smm)
        handle = node.start_flow(FatalFlow())
        with pytest.raises(TransactionVerificationError):
            handle.result.result(timeout=10)
        # visible via node_hospital()
        hosp = ops.node_hospital()
        assert len(hosp["ward"]) == 1
        rec = hosp["ward"][0]
        assert rec["flow_id"] == handle.flow_id
        assert rec["error_type"] == "TransactionVerificationError"
        assert "contract violation" in rec["error"]
        assert hosp["recovering"] == []

        # retry_flow: replays from the captured checkpoint, fails the
        # same way (deterministic error), re-wards
        records_before = COUNTS["record"]
        assert ops.retry_flow(handle.flow_id) is True
        time.sleep(0.1)
        hosp = ops.node_hospital()
        assert len(hosp["ward"]) == 1
        # replay fed the recorded step back — no re-execution
        assert COUNTS["record"] == records_before

        # a relaunch that cannot happen reports False and stays warded
        with node.smm.hospital._lock:
            node.smm.hospital._ward[handle.flow_id]["checkpoint"] = b"\x00junk"
        assert ops.retry_flow(handle.flow_id) is False
        assert len(ops.node_hospital()["ward"]) == 1

        # kill_flow discharges the ward record
        assert ops.kill_flow(handle.flow_id) is True
        assert ops.node_hospital()["ward"] == []
        # unknown id: False
        assert ops.retry_flow("nope") is False
        assert ops.kill_flow("nope") is False
        net.stop_nodes()

    def test_kill_flow_cancels_scheduled_retry(self):
        net, node = _make_node(backoff_s=5.0, backoff_cap_s=10.0)
        with faults.inject(seed=5) as fi:
            fi.rule("test.transient", "fail", times=None)
            handle = node.start_flow(FlakyFlow())
            # the flow is now waiting out a long backoff
            deadline = time.monotonic() + 5
            while not node.smm.hospital.snapshot()["recovering"]:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            assert node.smm.kill_flow(handle.flow_id) is True
            with pytest.raises(FlowKilledException):
                handle.result.result(timeout=5)
        snap = node.smm.hospital.snapshot()
        assert snap["recovering"] == [] and snap["ward"] == []
        # the checkpoint is gone: nothing can resurrect the flow
        assert node.smm.checkpoint_storage.get(handle.flow_id) is None
        net.stop_nodes()

    def test_node_stop_fails_recovering_futures_fast(self):
        """Shutdown must not strand a caller blocked on a recovering
        flow's result: hospital.close() resolves the preserved future
        (the checkpoint survives for a restarted node)."""
        from corda_tpu.core.flows.api import FlowException

        net, node = _make_node(backoff_s=5.0, backoff_cap_s=10.0)
        with faults.inject(seed=8) as fi:
            fi.rule("test.transient", "fail", times=None)
            handle = node.start_flow(FlakyFlow())
            deadline = time.monotonic() + 5
            while not node.smm.hospital.snapshot()["recovering"]:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            node.stop()
            with pytest.raises(FlowException, match="node stopped"):
                handle.result.result(timeout=5)
        net.nodes.remove(node)
        net.stop_nodes()

    def test_kills_are_never_warded(self):
        net, node = _make_node()

        class ParkedFlow(FlowLogic):
            def call(self):
                yield self.await_blocking(lambda: time.sleep(0))
                yield self.record(lambda: None)
                # park forever on a ledger commit that never happens
                from corda_tpu.core.crypto.secure_hash import SecureHash

                yield self.wait_for_ledger_commit(
                    SecureHash.sha256(b"never")
                )

        handle = node.start_flow(ParkedFlow())
        assert node.smm.kill_flow(handle.flow_id) is True
        with pytest.raises(FlowKilledException):
            handle.result.result(timeout=5)
        assert node.smm.hospital.snapshot()["ward"] == []
        net.stop_nodes()

    def test_ward_is_bounded(self):
        net, node = _make_node()
        node.smm.hospital.ward_max = 3
        handles = [node.start_flow(FatalFlow()) for _ in range(5)]
        for h in handles:
            with pytest.raises(TransactionVerificationError):
                h.result.result(timeout=10)
        snap = node.smm.hospital.snapshot()
        assert len(snap["ward"]) == 3
        # oldest evicted, newest kept
        kept = {w["flow_id"] for w in snap["ward"]}
        assert kept == {h.flow_id for h in handles[2:]}
        net.stop_nodes()

    def test_disabled_hospital_wards_but_never_retries(self):
        net, node = _make_node()
        node.smm.hospital.enabled = False
        with faults.inject(seed=6) as fi:
            fi.rule("test.transient", "fail", times=None)
            handle = node.start_flow(FlakyFlow())
            with pytest.raises(TransientFlowError):
                handle.result.result(timeout=5)
        assert COUNTS["flaky"] == 1  # no retry
        snap = node.smm.hospital.snapshot()
        assert len(snap["ward"]) == 1  # the ward still records
        net.stop_nodes()


class TestHospitalOpsEndpoint:
    def test_hospital_endpoint_and_health_detail(self):
        net = MockNetwork()
        node = net.create_node("O=HospitalOps,L=London,C=GB", ops_port=0)
        handle = node.start_flow(FatalFlow())
        with pytest.raises(TransactionVerificationError):
            handle.result.result(timeout=10)
        port = node.ops_server.port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/hospital", timeout=5
        ) as resp:
            body = json.loads(resp.read())
        assert body["enabled"] is True
        assert [w["flow_id"] for w in body["ward"]] == [handle.flow_id]
        assert body["warded"] == 1
        # the health view carries the informational hospital component
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as resp:
            health = json.loads(resp.read())
        assert health["checks"]["hospital"]["ward"] == 1
        assert health["checks"]["hospital"]["ok"] is True
        # hospital metrics ride /metrics with everything else
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            text = resp.read().decode()
        assert "corda_tpu_hospital_ward_size" in text
        net.stop_nodes()
